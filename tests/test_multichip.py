"""Multichip SPMD execution suite (PR 12): planner-native sharding over
the virtual 8-device CPU mesh (conftest).

Covers the four multichip guarantees end to end:
- shard_map lowering equivalence: q5-shaped pipelines (filter /
  group-by / equi-join) oracle-identical, plain AND encoded columns,
  including mismatched per-shard dictionaries forcing reconciliation;
- ICI-resident exchange: the planner stamps [strategy=ici], the
  transfer ledger shows ici-direction bytes and ZERO host-direction
  shuffle bytes, telemetry reports iciBytes / hostBytesAvoided;
- transient fabric faults (ici.collective) retry transparently;
- chip.fatal fences ONE chip and recovers the lost shards from
  lineage over the surviving mesh — oracle-identical, zero leaked
  permits/buffers, other chips stay serving.
"""

import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime import device_monitor as dm
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime import semaphore as sem_mod
from spark_rapids_tpu.runtime.memory import get_catalog
from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    with_cpu_session,
    with_tpu_session,
)

MESH = {"spark.rapids.tpu.mesh": 8,
        "spark.sql.shuffle.partitions": 4,
        "spark.sql.autoBroadcastJoinThreshold": -1}


@pytest.fixture(autouse=True)
def _isolated_chip_state():
    """Chip fences are process-global by design (a dead chip stays
    dead); tests must not bleed a fenced virtual device into the rest
    of the suite."""
    faults.install(faults.FaultRegistry())
    dm.clear_chip_fences()
    yield
    faults.install(faults.FaultRegistry())
    dm.clear_chip_fences()


def _mesh_vs_oracle(df_fn, conf=None, ignore_order=True):
    mesh_conf = {**MESH, **(conf or {})}
    got = with_tpu_session(lambda s: df_fn(s).collect_arrow(),
                           mesh_conf)
    want = with_cpu_session(lambda s: df_fn(s).collect_arrow(),
                            conf or {})
    assert_tables_equal(got, want, ignore_order=ignore_order)
    return got


def _write_sharded_parquet(tmp_path, n_files=8, per=600,
                           shared=("both_a", "both_b")):
    """n_files parquet parts whose string column draws from DISJOINT
    per-file vocabularies plus a small shared core: every file's
    dictionary page differs, so mesh ingestion (one file per shard)
    MUST reconcile per-shard dictionaries before codes can meet in an
    exchange."""
    path = str(tmp_path / "facts")
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(5)
    for i in range(n_files):
        vocab = [f"f{i}_v{j}" for j in range(5)] + list(shared)
        t = pa.table({
            "cat": pa.array(rng.choice(vocab, per),
                            type=pa.large_string()),
            "store": pa.array(rng.integers(0, 50, per),
                              type=pa.int64()),
            "amount": pa.array(rng.random(per) * 100,
                               type=pa.float64()),
        })
        pq.write_table(t, os.path.join(path, f"part-{i}.parquet"),
                       use_dictionary=["cat"], row_group_size=per)
    return path


def _q5(s, fact_rows=4000, seed=3):
    rng = np.random.default_rng(seed)
    fact = s.createDataFrame(pa.table({
        "store": pa.array(rng.integers(0, 40, fact_rows),
                          type=pa.int64()),
        "amount": pa.array(rng.random(fact_rows) * 100,
                           type=pa.float64()),
    }))
    dim = s.createDataFrame(pa.table({
        "store": pa.array(np.arange(0, 60), type=pa.int64()),
        "region": pa.array([f"region_{i % 7}" for i in range(60)],
                           type=pa.large_string()),
    }))
    return (fact.filter(F.col("amount") > 10.0)
            .join(dim, on="store", how="inner")
            .groupBy("region")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n")))


def _wait_until(pred, timeout_s=10.0, tick=0.005):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return False


def _assert_clean():
    assert _wait_until(lambda: sem_mod.get().holders() == 0
                       and get_catalog().buffer_count() == 0), \
        sem_mod.get()._holder_diagnostics()
    get_catalog().check_leaks(raise_on_leak=True)


# -------------------------------------- lowering equivalence (plain)

def test_q5_pipeline_oracle_identical():
    _mesh_vs_oracle(_q5)


def test_encoded_scan_groupby_reconciles_dictionaries(tmp_path):
    """Per-shard dictionaries differ file to file; the union dictionary
    + remapped codes must produce the exact oracle group set (a missed
    reconciliation either collides codes across shards or drops
    groups)."""
    data = _write_sharded_parquet(tmp_path)

    def q(s):
        return (s.read.parquet(data).groupBy("cat")
                .agg(F.sum("amount").alias("rev"),
                     F.count("*").alias("n")))

    got = _mesh_vs_oracle(q)
    # 8 files x 5 private values + 2 shared = 42 distinct groups
    assert got.num_rows == 42


def test_encoded_scan_join_on_plain_key(tmp_path):
    """Equi-join where the encoded column rides THROUGH the hash
    exchange as codes (join key is plain int): exercises the
    hold-the-dictionary-back collective path."""
    data = _write_sharded_parquet(tmp_path)

    def q(s):
        dim = s.createDataFrame(pa.table({
            "store": pa.array(np.arange(0, 50), type=pa.int64()),
            "w": pa.array((np.arange(0, 50) % 9).astype("float64")),
        }))
        return (s.read.parquet(data)
                .join(dim, on="store", how="inner")
                .groupBy("cat")
                .agg(F.sum((F.col("amount") * F.col("w"))
                           .alias("x")).alias("wrev")))

    _mesh_vs_oracle(q)


def test_reconcile_disabled_still_correct(tmp_path):
    """reconcileDictionaries=false decodes before sharding — slower,
    still oracle-identical."""
    data = _write_sharded_parquet(tmp_path)

    def q(s):
        return (s.read.parquet(data).groupBy("cat")
                .agg(F.count("*").alias("n")))

    _mesh_vs_oracle(
        q,
        conf={"spark.rapids.tpu.multichip.reconcileDictionaries":
              False})


# ------------------------------------------- ICI-resident strategy

def test_exchange_stamped_ici_and_zero_host_shuffle_bytes():
    s = TpuSparkSession(dict(MESH))
    try:
        df = _q5(s)
        out = df.collect_arrow()
        rec = s.last_execution
        assert rec["engine"] == "mesh"
        tel = rec.get("telemetry") or {}
        moved = tel.get("bytesMoved") or {}
        # the exchange never left the fabric: ici bytes moved, zero
        # host-direction shuffle bytes
        assert moved.get("ici", 0) > 0
        assert moved.get("shuffle", 0) == 0
        assert tel.get("iciBytes", 0) > 0
        assert tel.get("hostBytesAvoided", 0) > 0
        assert out.num_rows > 0
    finally:
        s.stop()


def test_explain_shows_ici_strategy(capsys):
    """Explicit repartition keeps a TpuShuffleExchangeExec node in the
    plan (join/agg exchanges are internal to their mesh lowerings);
    explain() must show the transport the planner chose for it."""
    s = TpuSparkSession(dict(MESH))
    try:
        rng = np.random.default_rng(2)
        df = (s.createDataFrame(pa.table({
            "k": pa.array(rng.integers(0, 30, 3000), type=pa.int64()),
            "v": pa.array(rng.random(3000)),
        })).repartition(4, "k").groupBy("k")
            .agg(F.sum("v").alias("sv")))
        df.collect_arrow()
        assert s.last_execution["engine"] == "mesh"
        df.explain()
        text = capsys.readouterr().out
        assert "[strategy=ici]" in text
    finally:
        s.stop()


def test_ici_shuffle_disabled_pins_exchange_to_host():
    """iciShuffle.enabled=false: exchanges pin to the host strategy,
    the mesh compiler refuses them, and the plan falls back to the
    single-chip engine — still oracle-identical."""
    conf = {**MESH,
            "spark.rapids.tpu.multichip.iciShuffle.enabled": False}
    s = TpuSparkSession(conf)
    try:
        df = _q5(s)
        got = df.collect_arrow()
        assert s.last_execution["engine"] != "mesh"
    finally:
        s.stop()
    want = with_cpu_session(lambda s2: _q5(s2).collect_arrow())
    assert_tables_equal(got, want, ignore_order=True)


# ------------------------------------------------- fault injection

def test_ici_collective_fault_retries_transparently():
    conf = {**MESH,
            "spark.rapids.tpu.chaos.enabled": True,
            "spark.rapids.tpu.chaos.sites": "ici.collective:once"}
    s = TpuSparkSession(conf)
    try:
        got = _q5(s).collect_arrow()
        assert s.last_execution["engine"] == "mesh"
        c = faults.counters().get("ici.collective", {})
        assert c.get("injected", 0) == 1
    finally:
        s.stop()
    want = with_cpu_session(lambda s2: _q5(s2).collect_arrow())
    assert_tables_equal(got, want, ignore_order=True)
    _assert_clean()


def test_chip_fatal_fences_one_chip_and_recovers():
    """One chip dies mid-collective: ONLY that chip fences (the
    process-wide fence never raises), the chip epoch bumps, and the
    query re-executes its lineage over the 7 survivors —
    oracle-identical, leak-free."""
    conf = {**MESH,
            "spark.rapids.tpu.chaos.enabled": True,
            "spark.rapids.tpu.chaos.sites": "chip.fatal:once"}
    before = dm.counters()
    s = TpuSparkSession(conf)
    try:
        got = _q5(s).collect_arrow()
        rec = s.last_execution
        assert rec["engine"] == "mesh"
    finally:
        s.stop()
    after = dm.counters()
    assert after["chipFences"] == before["chipFences"] + 1
    assert after["chipRecoveries"] == before["chipRecoveries"] + 1
    assert after["fencedChips"] == 1
    # the PROCESS-wide fence did not move: other queries kept serving
    assert after["fences"] == before["fences"]
    want = with_cpu_session(lambda s2: _q5(s2).collect_arrow())
    assert_tables_equal(got, want, ignore_order=True)
    _assert_clean()


def test_chip_recovery_disabled_escalates_to_resubmission():
    """chipRecovery off: the executor still fences the lost chip but
    raises DeviceLostError instead of recovering in place — the PR 9
    query-resubmission path handles it (one clean resubmit over the
    surviving mesh), so the collect succeeds WITHOUT an in-executor
    chip recovery."""
    conf = {**MESH,
            "spark.rapids.tpu.multichip.chipRecovery.enabled": False,
            "spark.rapids.tpu.chaos.enabled": True,
            "spark.rapids.tpu.chaos.sites": "chip.fatal:once"}
    before = dm.counters()
    s = TpuSparkSession(conf)
    try:
        got = _q5(s).collect_arrow()
    finally:
        s.stop()
    after = dm.counters()
    assert after["chipFences"] == before["chipFences"] + 1
    assert after["chipRecoveries"] == before["chipRecoveries"]
    want = with_cpu_session(lambda s2: _q5(s2).collect_arrow())
    assert_tables_equal(got, want, ignore_order=True)
    _assert_clean()


# -------------------------------------------------- per-chip fencing

def test_fence_chip_api_and_mesh_shrinks():
    from spark_rapids_tpu.parallel.plan_compiler import (
        MeshQueryExecutor,
    )

    ep0 = dm.chip_epoch()
    import jax

    victim = jax.devices()[-1].id
    ep1 = dm.fence_chip(victim, cause="test")
    assert ep1 == ep0 + 1 and victim in dm.fenced_chips()
    # idempotent: re-fencing the same chip does not bump the epoch
    assert dm.fence_chip(victim) == ep1
    ex = MeshQueryExecutor.for_devices(8)
    assert ex.n == 7  # mesh laid out over healthy chips only
    dm.unfence_chip(victim)
    assert victim not in dm.fenced_chips()
    ex2 = MeshQueryExecutor.for_devices(8)
    assert ex2.n == 8


def test_queries_keep_serving_while_chip_fenced():
    import jax

    dm.fence_chip(jax.devices()[-1].id, cause="test")
    _mesh_vs_oracle(_q5)  # mesh engine runs over the 7 healthy chips
