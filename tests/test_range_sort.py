"""Distributed global sort via range partitioning + TakeOrderedAndProject
fusion (round-2 verdict item 7): global orderBy no longer funnels through
a single partition."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.exec import operators as ops
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)

_CONF = {"spark.sql.shuffle.partitions": 4,
         "spark.rapids.sql.reader.batchSizeRows": 700,
         # one scan task per file so the child is multi-partition and
         # global sort must actually distribute
         "spark.rapids.sql.format.parquet.reader.type": "PERFILE"}


@pytest.fixture(scope="module")
def data_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("rs")
    rng = np.random.default_rng(21)
    n = 6000
    t = pa.table({
        "k": pa.array(rng.integers(-1000, 1000, n), type=pa.int64()),
        "v": pa.array(rng.random(n) * 100, type=pa.float64()),
        "s": pa.array([f"s{i % 97:02d}" for i in range(n)],
                      type=pa.string()),
    })
    for i in range(4):
        pq.write_table(t.slice(i * 1500, 1500),
                       os.path.join(d, f"p{i}.parquet"))
    return str(d)


def _find(phys, cls):
    out = []

    def walk(p):
        if isinstance(p, cls):
            out.append(p)
        for c in p.children:
            walk(c)

    walk(phys)
    return out


def test_global_sort_uses_range_exchange(data_path):
    def run(spark):
        df = spark.read.parquet(data_path).orderBy("k", "v")
        phys, _ = df._physical()
        return phys

    phys = with_tpu_session(run, _CONF)
    rex = _find(phys, ops.TpuRangeShuffleExchangeExec)
    assert rex, "global sort did not plan a range exchange"
    assert rex[0].num_partitions > 1, "range exchange degenerated to 1"


@pytest.mark.parametrize("asc", [True, False])
def test_global_sort_order_exact(data_path, asc):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(data_path)
        .select("k", "v")
        .orderBy(F.col("k") if asc else F.col("k").desc(),
                 F.col("v")),
        conf=_CONF, ignore_order=False)


def test_global_sort_strings(data_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(data_path)
        .select("s", "k").orderBy("s", "k"),
        conf=_CONF, ignore_order=False)


def test_take_ordered_fusion(data_path):
    """orderBy().limit() plans the fused TopN (per-partition sort+limit,
    single-gather, final sort+limit) — no range exchange, no full-data
    single-partition sort."""

    def run(spark):
        df = spark.read.parquet(data_path).orderBy("k").limit(10)
        phys, _ = df._physical()
        return phys

    phys = with_tpu_session(run, _CONF)
    assert not _find(phys, ops.TpuRangeShuffleExchangeExec)
    limits = _find(phys, ops.TpuLocalLimitExec)
    sorts = _find(phys, ops.TpuSortExec)
    assert len(limits) >= 2 and len(sorts) >= 2, (limits, sorts)


def test_take_ordered_results(data_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(data_path)
        .select("k", "v").orderBy(F.col("v").desc()).limit(17),
        conf=_CONF, ignore_order=False)


def test_range_sort_skewed_keys():
    """Heavy key skew: bounds collapse onto the hot key; all duplicate
    keys land in one partition and order is still total."""

    def q(s):
        n = 5000
        vals = np.where(np.arange(n) % 20 == 0,
                        np.arange(n) % 7, 42).astype(np.int64)
        df = s.createDataFrame(pa.table({
            "k": pa.array(vals),
            "i": pa.array(np.arange(n, dtype=np.int64))}))
        return df.repartition(5).orderBy("k", "i")

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF,
                                         ignore_order=False)
