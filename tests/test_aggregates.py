"""Aggregate-function breadth: moments/bivariate/collect/distinct/
percentile families (reference:
sql-plugin/src/main/scala/org/apache/spark/sql/rapids/aggregate/
aggregateFunctions.scala, GpuApproximatePercentile.scala) — differential
tests against the CPU oracle plus numpy spot checks of the Spark
formulas."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
)


@pytest.fixture(scope="module")
def stats_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("aggdata")
    rng = np.random.default_rng(7)
    n = 4000
    x = rng.random(n) * 10
    t = pa.table({
        "k": pa.array(rng.integers(0, 6, n)),
        "x": pa.array(x, mask=rng.random(n) < 0.15),
        "y": pa.array(rng.random(n) * 3,
                      mask=rng.random(n) < 0.1),
        "b": pa.array(rng.random(n) < 0.5,
                      mask=rng.random(n) < 0.2),
        "i": pa.array(rng.integers(0, 9, n),
                      mask=rng.random(n) < 0.1),
    })
    p = str(d / "stats.parquet")
    pq.write_table(t, p)
    return p


def _agg_diff(path, *cols, conf=None):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(path).groupBy("k").agg(*cols),
        conf=conf)


def test_variance_family(stats_path):
    _agg_diff(stats_path,
              F.var_pop("x").alias("vp"),
              F.var_samp("x").alias("vs"),
              F.stddev_pop("x").alias("sp"),
              F.stddev("x").alias("ss"))


def test_skew_kurtosis(stats_path):
    _agg_diff(stats_path,
              F.skewness("x").alias("sk"),
              F.kurtosis("x").alias("ku"))


def test_corr_covar(stats_path):
    _agg_diff(stats_path,
              F.corr("x", "y").alias("c"),
              F.covar_pop("x", "y").alias("cp"),
              F.covar_samp("x", "y").alias("cs"))


def test_bool_and_or(stats_path):
    _agg_diff(stats_path,
              F.bool_and("b").alias("ba"),
              F.bool_or("b").alias("bo"))


def test_collect_list_set(stats_path):
    # list order is engine-defined: compare as sorted lists
    from spark_rapids_tpu.testing.asserts import (
        with_cpu_session,
        with_tpu_session,
    )

    def q(spark):
        out = (spark.read.parquet(stats_path).groupBy("k")
               .agg(F.collect_list("i").alias("cl"),
                    F.collect_set("i").alias("cs"))
               .collect_arrow())
        df = out.to_pandas().sort_values("k").reset_index(drop=True)
        df["cl"] = df["cl"].apply(lambda v: sorted(v))
        df["cs"] = df["cs"].apply(lambda v: sorted(v))
        return df

    tpu = with_tpu_session(q)
    cpu = with_cpu_session(q)
    assert tpu["k"].tolist() == cpu["k"].tolist()
    for c in ("cl", "cs"):
        for a, b in zip(tpu[c], cpu[c]):
            assert list(a) == list(b), c


def test_count_sum_distinct(stats_path):
    _agg_diff(stats_path,
              F.countDistinct("i").alias("cd"),
              F.sum_distinct("i").alias("sd"))


def test_percentile(stats_path):
    _agg_diff(stats_path,
              F.percentile("x", 0.5).alias("p50"),
              F.percentile("x", 0.25).alias("p25"))


def test_approx_percentile_sketch(stats_path):
    """approx_percentile is a bounded K-point quantile sketch (round-4
    verdict item #9): per-group answers stay within the sketch's rank
    tolerance of exact, with O(K) buffers regardless of group size."""
    import pyarrow.parquet as pq

    from spark_rapids_tpu.testing.asserts import with_tpu_session

    def q(spark):
        return (spark.read.parquet(stats_path).groupBy("k")
                .agg(F.percentile_approx("x", 0.9).alias("p90"))
                .collect_arrow())

    got = {r["k"]: r["p90"] for r in with_tpu_session(q).to_pylist()}
    t = pq.read_table(stats_path).to_pandas()
    for k, sub in t.groupby("k"):
        vals = np.sort(sub["x"].dropna().to_numpy())
        if not len(vals):
            continue
        # rank tolerance: |rank(got) - 0.9*n| <= n/64 + interpolation
        n = len(vals)
        r = np.searchsorted(vals, got[k])
        assert abs(r - 0.9 * n) <= max(2.0, n / 32), (k, got[k], r, n)


def test_approx_percentile_bounded_buffers_and_mesh():
    """The sketch buffer is K+1 columns independent of group size, and
    (being jittable) lowers into the mesh SPMD program."""
    from spark_rapids_tpu.expr.aggregates import ApproxPercentile
    from spark_rapids_tpu.expr.core import BoundReference
    from spark_rapids_tpu.sqltypes.datatypes import double

    fn = ApproxPercentile(BoundReference(0, double, True), 0.5)
    assert fn.jittable
    assert len(fn.buffer_types()) == fn.K + 1  # O(K), not O(rows)

    from spark_rapids_tpu.testing.asserts import with_tpu_session

    rng = np.random.default_rng(3)
    ks = np.arange(4000) % 3
    # each group draws from a DISJOINT value range (group g in
    # [1000g, 1000g+100)) so cross-group contamination in the
    # partial->merge path is caught, not averaged away
    vals = rng.random(4000) * 100 + ks * 1000.0

    def q(spark):
        t = pa.table({"k": pa.array(ks, type=pa.int64()),
                      "x": pa.array(vals)})
        return (spark.createDataFrame(t).groupBy("k")
                .agg(F.percentile_approx("x", 0.5).alias("p"))
                .collect_arrow())

    got = with_tpu_session(q, {"spark.rapids.tpu.mesh": 8,
                               "spark.sql.shuffle.partitions": 8})
    assert len(got) == 3
    for r in got.to_pylist():
        sub = np.sort(vals[ks == r["k"]])
        assert sub[0] <= r["p"] <= sub[-1], (r, sub[0], sub[-1])
        rk = np.searchsorted(sub, r["p"])
        assert abs(rk - 0.5 * len(sub)) <= max(2.0, len(sub) / 32)


def test_any_value(stats_path):
    # any value from the group is legal; assert it is a member
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    def q(spark):
        return (spark.read.parquet(stats_path).groupBy("k")
                .agg(F.any_value("i").alias("av"),
                     F.collect_set("i").alias("members"))
                .collect_arrow().to_pandas())

    df = with_tpu_session(q)
    for _, row in df.iterrows():
        if row["av"] is not None and not (
                isinstance(row["av"], float) and np.isnan(row["av"])):
            assert row["av"] in set(row["members"])


def test_global_stats_agg(stats_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(stats_path).agg(
            F.stddev("x").alias("sd"),
            F.corr("x", "y").alias("c"),
            F.countDistinct("i").alias("cd"),
            F.percentile("x", 0.75).alias("p75")))


def test_variance_edge_singleton():
    """n=1 groups: var_samp/stddev_samp NULL (Spark 3.x default);
    var_pop 0."""
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    def q(spark):
        df = spark.createDataFrame(
            pa.table({"k": pa.array([1, 2, 2]),
                      "x": pa.array([5.0, 1.0, 3.0])}))
        return (df.groupBy("k")
                .agg(F.var_samp("x").alias("vs"),
                     F.var_pop("x").alias("vp"),
                     F.corr("x", "x").alias("c"))
                .collect_arrow().to_pandas().sort_values("k")
                .reset_index(drop=True))

    out = with_tpu_session(q)
    assert out["vs"][0] is None or np.isnan(out["vs"][0])
    assert out["vp"][0] == 0.0
    assert abs(out["vs"][1] - 2.0) < 1e-12
    # corr(x, x) of a singleton has zero variance -> NULL
    assert out["c"][0] is None or np.isnan(out["c"][0])


def test_collect_through_multiple_partitions(stats_path):
    """Partial/merge across a multi-partition shuffle must union the
    per-batch lists correctly."""
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    def q(nparts):
        def run(spark):
            out = (spark.read.parquet(stats_path).groupBy("k")
                   .agg(F.collect_set("i").alias("cs"),
                        F.countDistinct("i").alias("cd"))
                   .collect_arrow())
            df = out.to_pandas().sort_values("k").reset_index(drop=True)
            df["cs"] = df["cs"].apply(sorted)
            return df
        return with_tpu_session(
            run, conf={"spark.sql.shuffle.partitions": nparts})

    one = q(1)
    many = q(5)
    assert one["cs"].tolist() == many["cs"].tolist()
    assert one["cd"].tolist() == many["cd"].tolist()
    for _, row in one.iterrows():
        assert row["cd"] == len(row["cs"])


def test_collect_set_nan_dedup():
    """NaN == NaN for set semantics (Spark collect_set/count distinct
    keep a single NaN)."""
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    def q(spark):
        df = spark.createDataFrame(
            pa.table({"k": pa.array([1, 1, 1, 1]),
                      "x": pa.array([float("nan"), float("nan"),
                                     2.0, 2.0])}))
        return (df.groupBy("k")
                .agg(F.collect_set("x").alias("cs"),
                     F.countDistinct("x").alias("cd"))
                .collect_arrow().to_pandas())

    out = with_tpu_session(q)
    assert out["cd"][0] == 2
    vals = list(out["cs"][0])
    assert len(vals) == 2
    assert sum(1 for v in vals if np.isnan(v)) == 1


def test_mesh_falls_back_for_collect(stats_path):
    """The SPMD mesh path has no static lowering for collect_*; the
    session must fall back to the thread-pool path, not crash."""
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    def run(spark):
        out = (spark.read.parquet(stats_path).groupBy("k")
               .agg(F.collect_set("i").alias("cs"))
               .collect_arrow())
        return out.num_rows

    n = with_tpu_session(run, conf={"spark.rapids.tpu.mesh": 4})
    assert n == 6


def test_approx_percentile_tail_error_on_skewed_data():
    """Quantified rank error of the K-point quantile sketch at TAIL
    quantiles of a heavily skewed (lognormal) distribution, across a
    multi-chunk merge (round-4 verdict weak #6): the estimate's RANK in
    the exact sorted data must stay within a bounded distance of the
    requested quantile. The sketch's uniform grid concentrates less
    than a t-digest at the tails, so the bound here IS the documented
    accuracy contract, checked at q=0.99 and q=0.999."""
    import numpy as np

    from spark_rapids_tpu.api.session import TpuSparkSession

    rng = np.random.default_rng(42)
    n = 200_000
    vals = rng.lognormal(mean=0.0, sigma=2.5, size=n)  # heavy tail
    t = pa.table({"g": pa.array(np.zeros(n, np.int64)),
                  "v": pa.array(vals)})
    s = TpuSparkSession({
        "spark.sql.shuffle.partitions": 4,
        # multiple chunks force partial-sketch merges
        "spark.rapids.sql.batchSizeRows": 32768,
        "spark.rapids.sql.reader.batchSizeRows": 32768})
    try:
        sorted_vals = np.sort(vals)
        for q, rank_tol in ((0.99, 0.005), (0.999, 0.005)):
            out = (s.createDataFrame(t).groupBy("g")
                   .agg(F.percentile_approx("v", q, 10000).alias("p"))
                   .collect_arrow())
            est = out["p"].to_pylist()[0]
            # rank of the estimate in the exact data
            rank = np.searchsorted(sorted_vals, est) / n
            assert abs(rank - q) <= rank_tol, (q, est, rank)
    finally:
        s.stop()


def test_approx_percentile_q99_error_bound_scales_with_K():
    """The documented accuracy contract (expr/aggregates.py:954-972):
    rank error is O(1/K) per merge level, K = min(max(accuracy, 16),
    128). Quantified against EXACT q=0.99 on skewed data for a small
    and the default K: the asserted bound is levels/K + interpolation
    slack, so a sketch regression (or a silent K cap change) fails
    here instead of drifting — flagged in rounds 4 and 5."""
    import numpy as np

    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.expr.aggregates import ApproxPercentile
    from spark_rapids_tpu.expr.core import BoundReference
    from spark_rapids_tpu.sqltypes.datatypes import double

    rng = np.random.default_rng(7)
    n = 120_000
    # skewed: 95% tight body, 5% heavy pareto tail
    vals = np.where(rng.random(n) < 0.95,
                    rng.random(n),
                    1.0 + rng.pareto(1.5, n) * 50.0)
    sorted_vals = np.sort(vals)
    exact = float(np.quantile(vals, 0.99))
    t = pa.table({"g": pa.array(np.zeros(n, np.int64)),
                  "v": pa.array(vals)})
    chunk = 16384
    s = TpuSparkSession({
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.batchSizeRows": chunk,
        "spark.rapids.sql.reader.batchSizeRows": chunk})
    try:
        for accuracy in (16, 10000):
            K = ApproxPercentile(
                BoundReference(0, double, True), 0.99,
                accuracy=accuracy).K
            out = (s.createDataFrame(t).groupBy("g")
                   .agg(F.percentile_approx("v", 0.99, accuracy)
                        .alias("p"))
                   .collect_arrow())
            est = out["p"].to_pylist()[0]
            rank = np.searchsorted(sorted_vals, est) / n
            # grid spacing is 1/(K-1); per-merge drift is O(1/K) — a
            # 4/K envelope covers both with margin while still scaling
            # with the contract (vacuous bounds catch nothing)
            bound = 4.0 / K
            assert abs(rank - 0.99) <= bound, \
                (accuracy, K, est, exact, rank, bound)
            # value-space sanity at the default K: the estimate must
            # land between the exact neighbors the rank bound allows
            if K >= 128:
                lo = sorted_vals[int(n * (0.99 - bound))]
                hi = sorted_vals[min(int(n * (0.99 + bound)), n - 1)]
                assert lo <= est <= hi, (est, lo, hi, exact)
    finally:
        s.stop()
