"""DECIMAL128 (precision > 18) — [cap, 2] int64 limb columns with
device limb arithmetic (ops/decimal128.py; reference: cuDF DECIMAL128 +
spark-rapids-jni DecimalUtils/Aggregation128Utils, SURVEY.md §2.12)."""

import decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)


@pytest.fixture(scope="module")
def dec_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("dec128")
    rng = np.random.default_rng(41)
    n = 3000

    def gen(lo, hi, scale, null_p=0.1):
        return [decimal.Decimal(int(rng.integers(lo, hi))).scaleb(-scale)
                if rng.random() > null_p else None for _ in range(n)]

    t = pa.table({
        "k": pa.array(rng.integers(0, 6, n)),
        "price": pa.array(gen(-10 ** 11, 10 ** 11, 2),
                          type=pa.decimal128(12, 2)),
        "wide": pa.array(
            [decimal.Decimal(int(rng.integers(-10 ** 17, 10 ** 17))
                             * 10 ** 9).scaleb(-4)
             if rng.random() > 0.1 else None for _ in range(n)],
            type=pa.decimal128(30, 4)),
    })
    p = str(d / "dec.parquet")
    pq.write_table(t, p)
    return p


def test_limb_arithmetic_vs_python():
    """Limb kernels against Python big-int arithmetic."""
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import decimal128 as D

    rng = np.random.default_rng(0)
    a = [int(rng.integers(-10 ** 18, 10 ** 18))
         * int(rng.integers(1, 9 * 10 ** 18)) for _ in range(300)]
    b = [int(rng.integers(-10 ** 18, 10 ** 18))
         * int(rng.integers(1, 9 * 10 ** 18)) for _ in range(300)]

    def to_limbs(vals):
        hi, lo = [], []
        for x in vals:
            v = x & ((1 << 128) - 1)
            h = v >> 64
            hi.append(h - (1 << 64) if h >= (1 << 63) else h)
            lo.append(D._i64_bits(v))
        return (jnp.asarray(np.array(hi, np.int64)),
                jnp.asarray(np.array(lo, np.int64)))

    def from_limbs(hi, lo):
        out = []
        for h, lo_ in zip(np.asarray(hi), np.asarray(lo)):
            v = (((int(h) << 64) | (int(lo_) & ((1 << 64) - 1)))
                 & ((1 << 128) - 1))
            out.append(v - (1 << 128) if v >= (1 << 127) else v)
        return out

    ah, al = to_limbs(a)
    bh, bl = to_limbs(b)
    rh, rl = D.add128(ah, al, bh, bl)
    wrap = lambda x: (x + (1 << 127)) % (1 << 128) - (1 << 127)  # noqa
    assert from_limbs(rh, rl) == [wrap(x + y) for x, y in zip(a, b)]

    x = rng.integers(-2 ** 62, 2 ** 62, 300)
    y = rng.integers(-2 ** 62, 2 ** 62, 300)
    mh, ml = D.mul_i64_i64(jnp.asarray(x), jnp.asarray(y))
    assert from_limbs(mh, ml) == [int(p) * int(q) for p, q in zip(x, y)]

    d = rng.integers(1, 10 ** 18, 300)
    qh, ql = D.div128_round_half_up(ah, al, jnp.asarray(d))
    with decimal.localcontext() as ctx:
        ctx.prec = 60
        want = [int((decimal.Decimal(v) / int(dd)).to_integral_value(
            decimal.ROUND_HALF_UP)) for v, dd in zip(a, d)]
    assert from_limbs(qh, ql) == want

    # segmented reductions require sorted/contiguous gids (the
    # engine's group_by sorts first)
    gid = jnp.asarray(np.sort(rng.integers(0, 5, 300)).astype(np.int32))
    valid = jnp.asarray(rng.random(300) < 0.9)
    sh, sl = D.seg_sum128(ah, al, valid, gid, 8)
    got = from_limbs(sh, sl)[:5]
    want = [sum(v for v, g, ok in zip(a, np.asarray(gid),
                                      np.asarray(valid))
                if g == i and ok) for i in range(5)]
    assert got == want


def test_sum_avg_needs_128(dec_path):
    """sum(decimal(12,2)) -> decimal(22,2): the buffer is DECIMAL128
    through the shuffle."""
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(dec_path).groupBy("k")
        .agg(F.sum("price").alias("s"), F.avg("price").alias("a")),
        conf={"spark.sql.shuffle.partitions": 4})


def test_wide_input_aggregates(dec_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(dec_path).groupBy("k")
        .agg(F.sum("wide").alias("s"), F.min("wide").alias("mn"),
             F.max("wide").alias("mx"), F.avg("wide").alias("a"),
             F.count("wide").alias("c")))


def test_wide_arithmetic_and_casts(dec_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(dec_path)
        .select("k",
                (F.col("wide") + F.col("wide")).alias("add"),
                (F.col("wide") - F.lit(1)).alias("sub"),
                (F.col("price") * F.col("price")).alias("mul128"),
                F.col("wide").cast("string").alias("s"),
                F.col("wide").cast("decimal(12,1)").alias("narrow"),
                F.col("wide").cast("long").alias("l"),
                F.abs(F.col("wide")).alias("ab"),
                (-F.col("wide")).alias("neg")))


def test_wide_sort(dec_path):
    def q(spark):
        return (spark.read.parquet(dec_path)
                .orderBy(F.col("wide").desc()).limit(20)
                .collect_arrow())

    tpu = with_tpu_session(q)
    vals = [v for v in tpu.column("wide").to_pylist() if v is not None]
    assert vals == sorted(vals, reverse=True)


def test_global_wide_sum(dec_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(dec_path)
        .agg(F.sum("wide").alias("s"), F.avg("price").alias("a")))


def test_wide_key_falls_back(dec_path):
    """decimal(>18) grouping keys have no device hash: CPU placement,
    same result."""
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_fallback_collect,
    )

    assert_tpu_fallback_collect(
        lambda spark: spark.read.parquet(dec_path).groupBy("wide")
        .agg(F.count("*").alias("c")),
        fallback_class="CpuHashAggregateExec")


def test_filter_on_wide_comparison(dec_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(dec_path)
        .filter(F.col("wide") > 0).groupBy("k")
        .agg(F.count("*").alias("c")))
