"""Test configuration: force the CPU backend with a virtual 8-device mesh.

Mirrors the reference's testing stance (SURVEY.md section 4): correctness
suites run without special hardware; distributed semantics are tested on a
virtual device mesh. On this machine the axon TPU plugin's sitecustomize
calls `jax.config.update("jax_platforms", "axon,cpu")` at interpreter
start, overriding JAX_PLATFORMS env — so the override must be undone via
jax.config after import, before any backend initializes.
"""

import os
import tempfile

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Isolate the persistent compile cache per test run: the layer stays
# ENABLED (cross-module recompiles load from disk after the per-module
# jit_cache clear below), but state never leaks between runs — tests
# asserting compile counts must not see a previous run's artifacts.
# Explicit per-test dirs (test_compile_cache.py) still win: env-derived
# conf values are defaults, not overrides.
os.environ.setdefault(
    "SPARK_RAPIDS_TPU_CONF_spark__rapids__tpu__compileCache__dir",
    tempfile.mkdtemp(prefix="srtpu_test_compile_cache_"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert devs[0].platform == "cpu", devs
    return devs


@pytest.fixture(autouse=True, scope="module")
def _clear_jit_cache_between_modules():
    """Release compiled programs after each test module.

    The full suite compiles 700+ XLA CPU executables in one process;
    keeping them all loaded segfaulted XLA's JIT late in the run
    (deterministic SIGSEGV inside backend_compile_and_load at ~97%).
    Bounding the live-executable set per module avoids the crash and
    caps memory; programs shared across modules simply recompile."""
    yield
    from spark_rapids_tpu.runtime import jit_cache

    jit_cache.clear()
    jax.clear_caches()
