"""Device-loss resilience suite — fatal-TPU detection, device fencing,
the device epoch, and warm engine recovery (runtime/device_monitor.py).

The acceptance contract under test: a query interrupted by an injected
`device.fatal` mid-execution completes with oracle-identical results
after warm recovery (no process restart) on BOTH engines, with the
epoch bumped exactly once per fence and zero leaked permits/buffers;
stale pre-epoch device handles deterministically raise DeviceLostError
instead of touching recycled device memory; a cancel racing the fence
unwind still yields a single clean error and a leak-free engine; and
the satellite disciplines hold (crash-consistent spill files with an
orphan sweep, the per-query cumulative retry budget, fence state in
the semaphore diagnostics table).
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime import (
    backoff,
    cancellation,
    device_monitor,
    faults,
)
from spark_rapids_tpu.runtime import semaphore as sem_mod
from spark_rapids_tpu.runtime.errors import (
    DeviceLostError,
    QueryCancelledError,
    QueryRejectedError,
    RetryExhausted,
)
from spark_rapids_tpu.runtime.memory import get_catalog


def _mk_parquet(tmp_path, rows=20_000, mod=7):
    rng = np.random.default_rng(11)
    path = str(tmp_path / "dl")
    os.makedirs(path, exist_ok=True)
    pq.write_table(pa.table({
        "k": pa.array(np.arange(rows) % mod, pa.int64()),
        "v": pa.array(rng.random(rows)),
    }), os.path.join(path, "part-0.parquet"))
    return path


def _agg(s, data):
    return (s.read.parquet(data).repartition(4, "k").groupBy("k")
            .agg(F.sum("v").alias("sv")).orderBy("k"))


def _wait_until(pred, timeout_s=10.0, tick=0.005):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return False


def _assert_clean():
    assert _wait_until(lambda: sem_mod.get().holders() == 0
                       and get_catalog().buffer_count() == 0), \
        sem_mod.get()._holder_diagnostics()
    get_catalog().check_leaks(raise_on_leak=True)


# ------------------------------------------------------ classification

def test_classify_taxonomy():
    assert device_monitor.classify(
        faults.InjectedFault("device.fatal")) == "fatal"
    assert device_monitor.classify(
        faults.InjectedFault("io.read")) == "other"
    assert device_monitor.classify(
        DeviceLostError("x", epoch=1)) == "fatal"
    from spark_rapids_tpu.runtime.errors import TpuRetryOOM

    assert device_monitor.classify(TpuRetryOOM("oom")) == "oom"
    assert device_monitor.classify(ValueError("nope")) == "other"


def test_plugin_fatal_policy_excludes_recovered_form():
    from spark_rapids_tpu.plugin import _is_fatal_device_error

    assert not _is_fatal_device_error(DeviceLostError("handled",
                                                      epoch=1))
    assert _is_fatal_device_error(faults.InjectedFault("device.fatal"))


# -------------------------------------------- warm recovery, end to end

@pytest.mark.parametrize("fused", [True, False],
                         ids=["fused", "per-operator"])
def test_device_fatal_recovers_oracle_identical(tmp_path, fused):
    """A mid-query device.fatal costs one recovery window: the engine
    fences, bumps the epoch exactly once, rebuilds the backend, and
    the resubmitted query returns oracle-identical results — no
    process restart, zero leaked permits/buffers."""
    data = _mk_parquet(tmp_path)
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 4})
    try:
        want = _agg(s, data).collect_arrow().to_pydict()
    finally:
        s.stop()
    conf = {"spark.sql.shuffle.partitions": 4,
            "spark.rapids.tpu.chaos.enabled": True,
            "spark.rapids.tpu.chaos.sites": "device.fatal:once"}
    if not fused:
        conf["spark.rapids.sql.fusedExec.enabled"] = False
    s = TpuSparkSession(conf)
    try:
        mon = device_monitor.get()
        e0 = mon.epoch
        c0 = mon.counters()
        got = _agg(s, data).collect_arrow().to_pydict()
        assert got == want
        c1 = mon.counters()
        assert c1["fences"] - c0["fences"] == 1
        assert c1["epoch"] == e0 + 1, "epoch bumps exactly once"
        assert c1["recoveries"] - c0["recoveries"] == 1
        assert c1["resubmits"] - c0["resubmits"] == 1
        assert not mon.fenced
        _assert_clean()
        # recovery is visible as epoch-tagged obs events
        evs = s.obs.history.events()
        kinds = [e["event"] for e in evs]
        assert "device.fatal" in kinds
        assert "device.fence" in kinds
        rec = [e for e in evs if e["event"] == "device.recovery"]
        assert rec and rec[-1]["epoch"] == e0 + 1
    finally:
        s.stop()


def test_lost_buffer_stale_handle_raises_then_recovers(tmp_path):
    """Chaos site device.lost_buffer: one poisoned device buffer's
    next use raises DeviceLostError (stale pre-epoch handle — never a
    read of recycled memory), the query unwinds cleanly and the
    resubmission is oracle-identical."""
    data = _mk_parquet(tmp_path, rows=30_000)
    base = {"spark.rapids.sql.fusedExec.enabled": False,
            "spark.sql.shuffle.partitions": 4,
            "spark.rapids.sql.reader.batchSizeRows": 4096}
    s = TpuSparkSession(base)
    try:
        want = _agg(s, data).collect_arrow().to_pydict()
    finally:
        s.stop()
    s = TpuSparkSession({
        **base,
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.sites": "device.lost_buffer:once"})
    try:
        mon = device_monitor.get()
        stale0 = mon.counters()["staleHandles"]
        got = _agg(s, data).collect_arrow().to_pydict()
        assert got == want
        assert mon.counters()["staleHandles"] == stale0 + 1
        _assert_clean()
    finally:
        s.stop()


def test_stale_spillable_raises_deterministically():
    """Direct stale-handle check: a device-resident spillable stamped
    with a dead epoch raises DeviceLostError from get_batch."""
    from spark_rapids_tpu.columnar import arrow_to_device

    s = TpuSparkSession({})
    try:
        catalog = get_catalog()
        b = arrow_to_device(pa.table({"a": list(range(256))}))
        sb = catalog.add_batch(b)
        sb.device_epoch -= 1  # as if the device died under it
        with pytest.raises(DeviceLostError) as ei:
            sb.get_batch()
        assert "stale device handle" in str(ei.value)
        sb.close()
        _assert_clean()
    finally:
        s.stop()


def test_host_tier_survives_recovery():
    """A spilled (host-tier) buffer is restorable: after on_device_lost
    it unspills into the new epoch with identical contents, while a
    device-tier buffer is dropped and raises."""
    from spark_rapids_tpu.columnar import arrow_to_device
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow

    s = TpuSparkSession({})
    try:
        catalog = get_catalog()
        vals = list(range(512))
        spilled = catalog.add_batch(
            arrow_to_device(pa.table({"a": vals})))
        lost = catalog.add_batch(
            arrow_to_device(pa.table({"a": vals})))
        with catalog._lock:
            catalog._spill_one(spilled)
        assert spilled.tier.name == "HOST"
        restorable, dropped = catalog.on_device_lost()
        assert restorable >= 1 and dropped == 1
        with pytest.raises(DeviceLostError):
            lost.get_batch()
        back = device_to_arrow(spilled.get_batch())
        assert back.column("a").to_pylist() == vals
        assert spilled.device_epoch == device_monitor.current_epoch()
        spilled.close()
        lost.close()
        _assert_clean()
    finally:
        s.stop()


# -------------------------------------------- cancel racing the fence

def test_cancel_racing_fence_single_clean_error(tmp_path):
    """Satellite acceptance: a user cancel landing WHILE device-loss
    fencing unwinds the same query yields one clean
    QueryCancelledError-family error (DeviceLostError is one), zero
    held permits, zero leaked buffers/reservations — extends the
    cancel-storm pattern to the fence unwind."""
    data = _mk_parquet(tmp_path, rows=40_000)
    s = TpuSparkSession({
        "spark.rapids.sql.fusedExec.enabled": False,
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.reader.batchSizeRows": 4096,
        "spark.rapids.tpu.chaos.enabled": True,
        # every dispatch fatal: the fence always lands mid-unwind, so
        # the user cancel below always races it; resubmission would
        # hit the site again, so the error must surface exactly once
        "spark.rapids.tpu.chaos.sites": "device.fatal:every=3",
        "spark.rapids.tpu.device.recovery.resubmit": False})
    try:
        df = _agg(s, data)
        outcomes = []
        for i in range(4):
            err = []

            def run():
                try:
                    df.collect_arrow()
                    err.append(None)
                except QueryCancelledError as e:
                    err.append(e)  # DeviceLostError included

            t = threading.Thread(target=run)
            t.start()
            time.sleep(0.005 * i)  # cancel lands at varied depths
            s.cancel_all("storm racing the fence")
            t.join(60)
            assert not t.is_alive()
            outcomes.append(err[0] if err else "hung")
            device_monitor.get().await_ready()
        assert all(o is None or isinstance(o, QueryCancelledError)
                   for o in outcomes), outcomes
        assert _wait_until(
            lambda: s.admission_status()["running"] == [])
        _assert_clean()
        # and the engine still serves queries afterwards
        faults.configure(None)
        out = df.collect_arrow()
        assert out.num_rows == 7
    finally:
        faults.configure(None)
        s.stop()


# -------------------------------------------------- fenced admission

def test_fenced_admission_degrade_serves_cpu(tmp_path):
    """While fenced, the degrade ladder serves on the CPU rung: the
    query completes (engine=cpu) with a recorded demotion naming the
    fence, and never touches a device rung."""
    data = _mk_parquet(tmp_path, rows=4_000)
    s = TpuSparkSession({})
    try:
        mon = device_monitor.get()
        with mon._cv:
            mon._fenced = True
        try:
            out = _agg(s, data).collect_arrow()
            assert out.num_rows == 7
            rec = s.last_execution
            assert rec["engine"] == "cpu"
            assert any("device fenced" in d["reason"]
                       for d in rec["degradations"])
        finally:
            with mon._cv:
                mon._fenced = False
                mon._cv.notify_all()
    finally:
        s.stop()


def test_fenced_admission_shed_and_queue(tmp_path):
    data = _mk_parquet(tmp_path, rows=4_000)
    s = TpuSparkSession({
        "spark.rapids.tpu.device.recovery.fencedAdmission": "shed"})
    try:
        mon = device_monitor.get()
        with mon._cv:
            mon._fenced = True
        try:
            with pytest.raises(QueryRejectedError) as ei:
                _agg(s, data).collect_arrow()
            assert "FENCED" in str(ei.value)
        finally:
            with mon._cv:
                mon._fenced = False
                mon._cv.notify_all()
    finally:
        s.stop()
    # queue mode: submission parks until the fence lifts, then runs
    s = TpuSparkSession({
        "spark.rapids.tpu.device.recovery.fencedAdmission": "queue"})
    try:
        mon = device_monitor.get()
        with mon._cv:
            mon._fenced = True
        got = []

        def run():
            got.append(_agg(s, data).collect_arrow().num_rows)

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.15)
        assert not got, "queued submission must wait out the fence"
        with mon._cv:
            mon._fenced = False
            mon._cv.notify_all()
        mon._notify_admission()
        t.join(30)
        assert got == [7]
    finally:
        s.stop()


# ------------------------------------------------- epoch invalidation

def test_epoch_invalidates_jit_and_dict_caches():
    """An epoch bump makes every in-memory compiled program a miss
    (old executables reference the torn-down client) and drops the
    encoded-dictionary device cache, while host dictionaries survive
    for lazy re-upload."""
    from spark_rapids_tpu.columnar import encoding
    from spark_rapids_tpu.runtime import jit_cache

    s = TpuSparkSession({})
    try:
        key = ("test-epoch-inval",)
        fn = jit_cache.cached_jit(key, lambda: (lambda x: x + 1))
        import jax.numpy as jnp

        assert int(fn(jnp.int32(1))) == 2
        assert jit_cache.probe(key)
        arr = pa.array(["a", "b", "a", None]).dictionary_encode()
        did, _ = encoding.intern_dictionary(arr.dictionary)
        assert encoding.device_dictionary(did) is not None
        device_monitor._EPOCH += 1
        try:
            assert not jit_cache.probe(key), \
                "epoch bump must invalidate resident programs"
            dropped = encoding.invalidate_device_cache()
            assert dropped >= 1
            # host dictionary survives; device copy re-uploads lazily
            assert encoding.dictionary_values(did) is not None
            assert encoding.device_dictionary(did) is not None
        finally:
            encoding.invalidate_device_cache()
            device_monitor._EPOCH -= 1
    finally:
        s.stop()


# ------------------------------------------------ satellite: sweeping

def test_crash_consistent_spill_sweep(tmp_path):
    """A crash mid-spill leaves .inprogress (and orphaned complete)
    files; catalog startup sweeps anything no live catalog owns and
    counts it, while the live catalog's own files are untouched."""
    from spark_rapids_tpu.columnar import arrow_to_device
    from spark_rapids_tpu.runtime.memory import SpillCatalog

    spill_dir = str(tmp_path / "spill")
    os.makedirs(spill_dir)
    # a dead process's leftovers: truncated in-progress + orphan
    for name in ("spill-deadbeef-aaaaaaaaaaaa.npz.inprogress",
                 "spill-deadbeef-bbbbbbbbbbbb.npz",
                 "spill-cccccccccccc.npz"):  # legacy unprefixed
        with open(os.path.join(spill_dir, name), "wb") as f:
            f.write(b"truncated")
    cat = SpillCatalog(device_limit=1 << 24, host_limit=1 << 24,
                       spill_dir=spill_dir)
    assert cat.metrics["orphaned_files_swept"] == 3
    assert os.listdir(spill_dir) == []
    # a real spill round-trips through .inprogress + atomic rename
    sb = cat.add_batch(arrow_to_device(
        pa.table({"a": list(range(128))})))
    with cat._lock:
        sb._to_host()
        sb._to_disk()
    files = os.listdir(spill_dir)
    assert len(files) == 1 and files[0].startswith(f"spill-{cat.uid}-")
    assert not files[0].endswith(".inprogress")
    # a SECOND catalog in the same process must not sweep the live one
    cat2 = SpillCatalog(device_limit=1 << 24, host_limit=1 << 24,
                        spill_dir=spill_dir)
    assert cat2.metrics["orphaned_files_swept"] == 0
    assert os.listdir(spill_dir) == files
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow

    assert device_to_arrow(
        sb.get_batch()).column("a").to_pylist() == list(range(128))
    sb.close()


# --------------------------------------------- satellite: retry budget

def test_cumulative_retry_budget_fails_fast():
    """Chained retry storms during an outage: the per-query cumulative
    budget (io.retry.maxTotalMs) fails fast with the budget named,
    instead of multiplying per-site backoffs."""
    s = TpuSparkSession({
        "spark.rapids.tpu.io.retry.attempts": 50,
        "spark.rapids.tpu.io.retry.backoffMs": 20,
        "spark.rapids.tpu.io.retry.maxBackoffMs": 20,
        "spark.rapids.tpu.io.retry.maxTotalMs": 60})
    try:
        token = cancellation.CancelToken(991)
        calls = {"n": 0}

        def always_fails():
            calls["n"] += 1
            raise OSError("outage")

        t0 = time.monotonic()
        with cancellation.scope(token):
            with pytest.raises(RetryExhausted) as ei:
                backoff.retry_io(always_fails, what="site A",
                                 site=None, retry_on=(OSError,))
        msg = str(ei.value)
        assert "maxTotalMs=60" in msg and "cumulative" in msg
        assert calls["n"] < 50, "budget must cut the attempt loop short"
        assert time.monotonic() - t0 < 5.0
        # the budget is per QUERY: a second site under the same token
        # inherits the spent budget and fails immediately
        calls["n"] = 0
        with cancellation.scope(token):
            with pytest.raises(RetryExhausted):
                backoff.retry_io(always_fails, what="site B",
                                 site=None, retry_on=(OSError,))
        assert calls["n"] <= 2
    finally:
        s.stop()


def test_retry_budget_disabled_keeps_attempt_loop():
    s = TpuSparkSession({
        "spark.rapids.tpu.io.retry.attempts": 4,
        "spark.rapids.tpu.io.retry.backoffMs": 1,
        "spark.rapids.tpu.io.retry.maxBackoffMs": 1,
        "spark.rapids.tpu.io.retry.maxTotalMs": 0})
    try:
        token = cancellation.CancelToken(992)
        calls = {"n": 0}

        def always_fails():
            calls["n"] += 1
            raise OSError("outage")

        with cancellation.scope(token):
            with pytest.raises(RetryExhausted):
                backoff.retry_io(always_fails, what="site",
                                 site=None, retry_on=(OSError,))
        assert calls["n"] == 4
    finally:
        s.stop()


# -------------------------------------- satellite: semaphore diagnosis

def test_semaphore_diagnostics_name_fence_and_epoch():
    sem = sem_mod.TpuSemaphore(concurrent_tasks=2)
    sem.acquire_if_necessary(12345)
    try:
        diag = sem._holder_diagnostics()
        assert "deviceEpoch=" in diag
        assert "engine=RUNNING" in diag
        mon = device_monitor.get()
        with mon._cv:
            mon._fenced = True
        try:
            assert "engine=FENCED" in sem._holder_diagnostics()
        finally:
            with mon._cv:
                mon._fenced = False
                mon._cv.notify_all()
    finally:
        sem.release_if_necessary(12345)
