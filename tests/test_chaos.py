"""Chaos harness + failure-domain hardening suite (PR 2).

The reference proves its reliability story with forced-fault tests
(the *RetrySuite strategy); this suite does the same for every failure
domain the deterministic injection registry (runtime/faults.py)
covers: shuffle checksums + fetch backoff, file-read backoff,
compile-cache quarantine, semaphore timeouts, disk-spill errors, and
the fused -> eager -> CPU degradation ladder with its circuit breaker.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.runtime import backoff, degrade, faults
from spark_rapids_tpu.runtime.errors import (
    RetryExhausted,
    SemaphoreTimeout,
    ShuffleChecksumError,
    ShuffleFetchError,
    SpillFileError,
)

FAST = backoff.BackoffPolicy(attempts=4, base_ms=1, max_ms=4)


@pytest.fixture(autouse=True)
def _isolated_faults():
    """Every test starts disarmed and leaves no registry behind."""
    faults.install(faults.FaultRegistry())
    yield
    faults.install(faults.FaultRegistry())


def _arm(spec, seed=42):
    return faults.install(faults.FaultRegistry(
        seed, faults.parse_sites(spec, 0.05)))


# ------------------------------------------------------ registry core

def test_policy_parsing_and_validation():
    pols = faults.parse_sites(
        "io.read:p=0.25; shuffle.fetch:every=3 ;spill.disk:once;x", 0.1)
    assert pols["io.read"].kind == "p" and pols["io.read"].value == 0.25
    assert pols["shuffle.fetch"].kind == "every"
    assert pols["spill.disk"].kind == "once"
    assert pols["x"].kind == "p" and pols["x"].value == 0.1
    with pytest.raises(ValueError):
        faults.parse_sites("io.read:p=1.5", 0.1)
    with pytest.raises(ValueError):
        faults.parse_sites("io.read:sometimes", 0.1)


def test_registry_determinism_per_site():
    """Same seed -> same per-site injection sequence, independent of
    how calls interleave across sites."""
    spec = "a:p=0.3;b:p=0.3"
    r1 = faults.FaultRegistry(7, faults.parse_sites(spec, 0.05))
    r2 = faults.FaultRegistry(7, faults.parse_sites(spec, 0.05))
    seq_a1 = [r1.should_inject("a") for _ in range(40)]
    # r2 interleaves b calls between a calls; a's stream must not move
    seq_a2 = []
    for _ in range(40):
        r2.should_inject("b")
        seq_a2.append(r2.should_inject("a"))
    assert seq_a1 == seq_a2 and any(seq_a1)


def test_every_and_once_policies():
    r = faults.FaultRegistry(0, faults.parse_sites("e:every=4;o:once", 0))
    assert [r.should_inject("e") for _ in range(8)] == \
        [False] * 3 + [True] + [False] * 3 + [True]
    assert [r.should_inject("o") for _ in range(4)] == \
        [True, False, False, False]
    assert r.counters()["e"] == {"checked": 8, "injected": 2}


def test_disarmed_registry_is_noop():
    faults.maybe_inject("io.read")  # must not raise
    assert not faults.get().armed
    assert faults.counters() == {}


# --------------------------------------------------------- backoff

def test_retry_io_recovers_and_counts():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    before = backoff.counters().get("t", 0)
    out = backoff.retry_io(flaky, "t", policy=FAST, counter="t",
                           sleep=lambda _s: None)
    assert out == "ok" and calls["n"] == 3
    assert backoff.counters()["t"] - before == 2


def test_retry_io_exhaustion_chains_last_error():
    with pytest.raises(RetryExhausted) as ei:
        backoff.retry_io(lambda: (_ for _ in ()).throw(OSError("disk")),
                         "doomed", policy=FAST, sleep=lambda _s: None)
    assert isinstance(ei.value.__cause__, OSError)
    assert "doomed" in str(ei.value)


def test_retry_io_no_retry_classes_fail_fast():
    calls = {"n": 0}

    def gone():
        calls["n"] += 1
        raise FileNotFoundError("deleted")

    with pytest.raises(FileNotFoundError):
        backoff.retry_io(gone, "g", policy=FAST,
                         no_retry=(FileNotFoundError,),
                         sleep=lambda _s: None)
    assert calls["n"] == 1


def test_retry_io_foreign_site_fault_propagates():
    """An InjectedFault from a site this loop does not own must escape
    untouched — its recovery point is elsewhere."""
    _arm("other.site:every=1")

    def fn():
        faults.maybe_inject("other.site")
        return 1

    with pytest.raises(faults.InjectedFault):
        backoff.retry_io(fn, "f", site="io.read", policy=FAST,
                         sleep=lambda _s: None)


# ------------------------------------------------- shuffle hardening

def _table(n=500, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 50, n), pa.int64()),
        "v": pa.array(rng.random(n)),
        "s": pa.array([f"s{i % 17}" for i in range(n)]),
    })


def test_serde_checksum_roundtrip_and_detection():
    from spark_rapids_tpu.shuffle import serde

    t = _table()
    for codec in ("none", "zlib"):
        buf = serde.serialize_table(t, codec=codec)
        assert serde.deserialize_table(buf).equals(t)
        for flip in (14, buf.size // 2, buf.size - 1):  # header+body
            bad = buf.copy()
            bad[flip] ^= 0x5A
            with pytest.raises(ShuffleChecksumError):
                serde.deserialize_table(bad)
    # checksum-less frames (older writers) still decode
    legacy = serde.serialize_table(t, codec="zlib", checksum=False)
    assert serde.deserialize_table(legacy).equals(t)


def test_shuffle_fetch_retries_injected_faults(tmp_path, monkeypatch):
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    monkeypatch.setattr(backoff, "policy_from_conf", lambda conf=None:
                        backoff.BackoffPolicy(4, 1, 4))
    mgr = ShuffleManager("MULTITHREADED", shuffle_dir=str(tmp_path),
                         num_threads=2, codec="zlib")
    t = _table()
    sid = mgr.new_shuffle_id()
    mgr.put(sid, 0, t)
    _arm("shuffle.fetch:once")  # first attempt dies, retry recovers
    out = mgr.fetch(sid, 0)
    assert len(out) == 1 and out[0].equals(t)
    assert mgr.fetch_retries >= 1
    mgr.remove_shuffle(sid)
    mgr.shutdown()


def test_shuffle_fetch_budget_exhaustion_names_block(tmp_path,
                                                     monkeypatch):
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    monkeypatch.setattr(backoff, "policy_from_conf", lambda conf=None:
                        backoff.BackoffPolicy(3, 1, 4))
    mgr = ShuffleManager("MULTITHREADED", shuffle_dir=str(tmp_path),
                         num_threads=2)
    sid = mgr.new_shuffle_id()
    mgr.put(sid, 3, _table())
    _arm("shuffle.fetch:p=1.0")  # unrecoverable
    with pytest.raises(ShuffleFetchError) as ei:
        mgr.fetch(sid, 3)
    msg = str(ei.value)
    assert f"shuffle_id={sid}" in msg and "reduce_pid=3" in msg
    faults.install(faults.FaultRegistry())
    mgr.remove_shuffle(sid)
    mgr.shutdown()


def test_shuffle_persistent_corruption_surfaces_cleanly(tmp_path,
                                                        monkeypatch):
    """A truly corrupt on-disk block (re-read returns the same bad
    bytes every attempt) exhausts the budget into ShuffleFetchError —
    never a wrong-data result, never a raw struct/json error."""
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    monkeypatch.setattr(backoff, "policy_from_conf", lambda conf=None:
                        backoff.BackoffPolicy(3, 1, 4))
    mgr = ShuffleManager("MULTITHREADED", shuffle_dir=str(tmp_path),
                         num_threads=2)
    sid = mgr.new_shuffle_id()
    mgr.put(sid, 0, _table())
    [fb.future.result() for fs in mgr._files.values() for fb in fs]
    blk = next(p for p in os.listdir(tmp_path) if p.endswith(".stpu"))
    path = os.path.join(tmp_path, blk)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    with pytest.raises(ShuffleFetchError):
        mgr.fetch(sid, 0)
    assert mgr.checksum_failures >= 3  # every attempt verified
    mgr.remove_shuffle(sid)
    mgr.shutdown()


# ---------------------------------------------------- io.read domain

def test_reader_survives_injected_read_faults(tmp_path, monkeypatch):
    from spark_rapids_tpu.io import readers

    monkeypatch.setattr(backoff, "policy_from_conf", lambda conf=None:
                        backoff.BackoffPolicy(4, 1, 4))
    t = _table(300)
    path = str(tmp_path / "a.parquet")
    pq.write_table(t, path)
    _arm("io.read:once")
    got = pa.concat_tables(
        readers.read_parquet_task([path], None, 128))
    assert got.equals(t)
    assert backoff.counters().get("io.read", 0) >= 1


def test_reader_missing_file_fails_fast(tmp_path):
    from spark_rapids_tpu.io import readers

    with pytest.raises(FileNotFoundError):
        list(readers.read_parquet_task(
            [str(tmp_path / "nope.parquet")], None, 128))


# ------------------------------------- compile-cache artifact domain

def test_corrupt_artifact_quarantined_as_cache_miss(tmp_path,
                                                    monkeypatch):
    from spark_rapids_tpu.runtime import compile_cache as cc

    monkeypatch.setattr(cc, "_configured_dir", str(tmp_path))
    os.makedirs(tmp_path / "artifacts")
    digest = "d" * 32
    (tmp_path / "artifacts" / f"{digest}.key").write_text("('k',)")
    (tmp_path / "artifacts" / f"{digest}.bin").write_bytes(
        b"\x00truncated-garbage")
    before = cc.stats.snapshot()["artifactsQuarantined"]
    assert cc._load_artifact(digest, "('k',)") is None  # miss, no raise
    assert cc.stats.snapshot()["artifactsQuarantined"] == before + 1
    names = os.listdir(tmp_path / "artifacts")
    assert f"{digest}.bin.quarantine" in names
    assert f"{digest}.bin" not in names
    # quarantined entry does not resurrect: a second load is a plain
    # miss (FileNotFoundError path), not another quarantine
    assert cc._load_artifact(digest, "('k',)") is None
    assert cc.stats.snapshot()["artifactsQuarantined"] == before + 1


def test_injected_cache_load_fault_is_cache_miss(tmp_path, monkeypatch):
    from spark_rapids_tpu.runtime import compile_cache as cc

    monkeypatch.setattr(cc, "_configured_dir", str(tmp_path))
    os.makedirs(tmp_path / "artifacts")
    _arm("compile.cache_load:once")
    assert cc._load_artifact("e" * 32, "('x',)") is None


# -------------------------------------------------- semaphore domain

def test_semaphore_timeout_dumps_holder_diagnostics():
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore

    sem = TpuSemaphore(concurrent_tasks=1, acquire_timeout_ms=80)
    sem.acquire_if_necessary(11)
    with pytest.raises(SemaphoreTimeout) as ei:
        sem.acquire_if_necessary(22)
    msg = str(ei.value)
    assert "task 22" in msg and "task=11" in msg
    assert "permits=1000" in msg and "held_s=" in msg
    assert sem.timeouts == 1
    sem.release_if_necessary(11)
    sem.acquire_if_necessary(22)  # permits free: acquire works again
    sem.release_if_necessary(22)


def test_semaphore_zero_timeout_waits_forever_config():
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore

    sem = TpuSemaphore(concurrent_tasks=2, acquire_timeout_ms=0)
    sem.acquire_if_necessary(1)
    sem.acquire_if_necessary(1)  # re-entrant stays free
    assert sem.holders() == 1
    sem.release_if_necessary(1)


# ------------------------------------------------- spill.disk domain

def _mk_catalog(tmp_path, **kw):
    from spark_rapids_tpu.runtime.memory import SpillCatalog

    return SpillCatalog(1 << 30, 1 << 30, spill_dir=str(tmp_path), **kw)


def _device_batch(n=400):
    from spark_rapids_tpu.columnar import arrow_to_device

    return arrow_to_device(pa.table(
        {"a": pa.array(range(n), pa.int64())}))


def test_missing_spill_file_raises_clean_engine_error(tmp_path):
    cat = _mk_catalog(tmp_path)
    sb = cat.add_batch(_device_batch())
    cat.spill_device_bytes(sb.size_bytes)   # -> HOST
    cat.spill_host_bytes(sb.size_bytes)     # -> DISK
    assert sb._disk_path is not None
    os.unlink(sb._disk_path)
    with pytest.raises(SpillFileError) as ei:
        sb.get_batch()
    msg = str(ei.value)
    assert sb.id in msg and "DISK" in msg and "spill-" in msg
    assert not isinstance(ei.value, OSError) or True  # engine class
    sb.close()


def test_spill_write_retries_injected_disk_faults(tmp_path, monkeypatch):
    monkeypatch.setattr(backoff, "policy_from_conf", lambda conf=None:
                        backoff.BackoffPolicy(4, 1, 4))
    cat = _mk_catalog(tmp_path)
    sb = cat.add_batch(_device_batch())
    _arm("spill.disk:once")
    cat.spill_device_bytes(sb.size_bytes)
    cat.spill_host_bytes(sb.size_bytes)
    from spark_rapids_tpu.runtime.memory import SpillTier

    assert sb.tier == SpillTier.DISK  # survived the injected fault
    assert backoff.counters().get("spill.disk", 0) >= 1
    got = sb.get_batch()
    from spark_rapids_tpu.columnar import device_to_arrow

    assert device_to_arrow(got).column("a").to_pylist()[:3] == [0, 1, 2]
    sb.close()


# ------------------------------------------- degradation ladder

def _q(s):
    import spark_rapids_tpu.api.functions as F

    return (s.createDataFrame({"a": [1, 2, 3, 4, 2],
                               "b": [1.0, 2.0, 3.0, 4.0, 5.0]})
            .filter(F.col("a") > 1)
            .groupBy("a").agg(F.sum("b").alias("s")))


def _sorted_dict(t):
    return t.sort_by([(c, "ascending") for c in t.column_names]) \
        .to_pydict()


@pytest.fixture
def _fresh_breaker():
    degrade.reset_for_tests()
    yield
    degrade.reset_for_tests()


def test_ladder_fused_to_eager_on_dispatch_fault(_fresh_breaker):
    from spark_rapids_tpu.api.session import TpuSparkSession

    s0 = TpuSparkSession({})
    want = _sorted_dict(_q(s0).collect_arrow())
    s0.stop()
    s = TpuSparkSession({
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.sites": "device.dispatch:once"})
    try:
        got = _sorted_dict(_q(s).collect_arrow())
        assert got == want
        rec = s.last_execution
        assert rec["engine"] == "eager"
        assert rec["degradations"] and \
            rec["degradations"][0]["from"] == "fused"
        assert s.query_metrics.metric("degrade.fusedToEager").value >= 1
    finally:
        s.stop()


def test_ladder_eager_to_cpu_terminal(_fresh_breaker):
    from spark_rapids_tpu.api.session import TpuSparkSession

    s0 = TpuSparkSession({})
    want = _sorted_dict(_q(s0).collect_arrow())
    s0.stop()
    s = TpuSparkSession({
        "spark.rapids.sql.fusedExec.enabled": False,
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.sites": "device.dispatch:once"})
    try:
        got = _sorted_dict(_q(s).collect_arrow())
        assert got == want
        rec = s.last_execution
        assert rec["engine"] == "cpu"
        assert [(d["from"], d["to"]) for d in rec["degradations"]] == \
            [("eager", "cpu")]
    finally:
        s.stop()


def test_circuit_breaker_opens_after_threshold(_fresh_breaker):
    from spark_rapids_tpu.api.session import TpuSparkSession

    s = TpuSparkSession({
        "spark.rapids.tpu.chaos.enabled": True,
        # every fused dispatch dies; eager survives (site fires once
        # per query at the eager rung too, so give eager headroom)
        "spark.rapids.tpu.chaos.sites": "device.dispatch:every=1",
        "spark.rapids.tpu.degrade.circuitBreaker.threshold": 2})
    try:
        # chaos at every=1 also kills the eager rung's dispatch check,
        # landing on cpu — results must still be right every time
        outs = [_sorted_dict(_q(s).collect_arrow()) for _ in range(3)]
        assert outs[0] == outs[1] == outs[2]
        recs = s.query_metrics
        # first two queries burn the breaker; the third short-circuits
        assert recs.metric("degrade.breakerShortCircuit").value >= 1
        last = s.last_execution["degradations"]
        assert any("circuit breaker open" in d["reason"] for d in last)
        assert degrade.breaker().open_keys() >= 1
    finally:
        s.stop()


def test_breaker_success_closes(_fresh_breaker):
    b = degrade.CircuitBreaker(threshold=2)
    k = ("degrade", "x")
    assert b.allow(k)
    b.record_failure(k)
    b.record_failure(k)
    assert not b.allow(k) and b.opens == 1
    b.record_success(k)
    assert b.allow(k)


def test_oom_injection_routes_fused_through_eager(_fresh_breaker):
    """Satellite: exec/fused.py OOM-injection guard is a metric-counted
    automatic fallback, not a FusedCompileError crash — and the
    injection then reaches real eager allocation points."""
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.runtime.memory import get_catalog

    s0 = TpuSparkSession({})
    want = _sorted_dict(_q(s0).collect_arrow())
    s0.stop()
    s = TpuSparkSession({
        "spark.rapids.memory.gpu.oomInjection.mode": "once"})
    try:
        got = _sorted_dict(_q(s).collect_arrow())
        assert got == want
        rec = s.last_execution
        assert rec["engine"] in ("eager", "aqe")
        assert any("OOM injection" in d["reason"]
                   for d in rec["degradations"])
        assert s.query_metrics.metric(
            "degrade.fusedOomInjectionFallback").value >= 1
        assert get_catalog().metrics["retry_oom_injected"] >= 1
    finally:
        s.stop()


def test_fused_executor_direct_call_survives_oom_injection(
        _fresh_breaker):
    """Direct FusedSingleChipExecutor.execute with injection armed
    returns results via the eager route instead of raising."""
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.exec.fused import FusedSingleChipExecutor

    s = TpuSparkSession({
        "spark.rapids.memory.gpu.oomInjection.mode": "once"})
    try:
        phys, _ = _q(s)._physical()
        out = FusedSingleChipExecutor(s.rapids_conf).execute(phys)
        assert out.num_rows == 3  # groups {2, 3, 4}
        assert degrade.counters().get("fusedOomInjectionFallback", 0) \
            >= 1
    finally:
        s.stop()


def test_ladder_disabled_propagates(_fresh_breaker):
    from spark_rapids_tpu.api.session import TpuSparkSession

    s = TpuSparkSession({
        "spark.rapids.tpu.degrade.enabled": False,
        "spark.rapids.sql.fusedExec.enabled": False,
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.sites": "device.dispatch:once"})
    try:
        with pytest.raises(faults.InjectedFault):
            _q(s).collect_arrow()
    finally:
        s.stop()


def test_session_chaos_configuration_and_counters(_fresh_breaker):
    from spark_rapids_tpu.api.session import TpuSparkSession

    s = TpuSparkSession({
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.seed": 9,
        "spark.rapids.tpu.chaos.sites": "device.dispatch:once"})
    try:
        _q(s).collect_arrow()
        rm = s.robustness_metrics
        assert rm["chaos"]["device.dispatch"]["injected"] == 1
        assert "retries" in rm and "degrade" in rm
    finally:
        s.stop()
    # a plain session disarms the registry again
    s2 = TpuSparkSession({})
    try:
        assert not faults.get().armed
    finally:
        s2.stop()
