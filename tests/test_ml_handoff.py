"""ColumnarRdd zero-copy export, device UDFs, and profiler integration
(reference ColumnarRdd.scala, RapidsUDF.java, NvtxWithMetrics.scala)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.columnar_rdd import ColumnarRdd
from spark_rapids_tpu.api.session import TpuSparkSession

_CONF = {"spark.sql.shuffle.partitions": 2}


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


def _df(spark, n=1000):
    rng = np.random.default_rng(8)
    return spark.createDataFrame(pa.table({
        "x": pa.array(rng.random(n), type=pa.float64()),
        "y": pa.array(rng.integers(0, 10, n), type=pa.int64()),
    }))


def test_columnar_rdd_yields_device_batches(spark):
    import jax

    df = _df(spark).filter(F.col("x") > 0.5)
    batches = list(ColumnarRdd.convert(df))
    assert batches
    for b in batches:
        for c in b.columns:
            assert isinstance(c.data, jax.Array), type(c.data)


def test_to_jax_matches_collect(spark):
    df = _df(spark).select("x", (F.col("y") * 2).alias("y2"))
    arrays = ColumnarRdd.to_jax(df)
    want = df.collect_arrow()
    x, xv = arrays["x"]
    got = np.asarray(x)[np.asarray(xv)]
    assert np.allclose(sorted(got),
                       sorted(want.column("x").to_pylist()))


def test_device_udf_fused_on_device(spark):
    @F.device_udf(returnType="double")
    def scaled(v, v_valid):
        return v * 2.0 + 1.0, v_valid

    df = _df(spark)
    out = df.select(scaled(df["x"]).alias("s"))
    phys, _ = out._physical()

    def walk(p):
        yield p
        for c in p.children:
            yield from walk(c)

    names = [type(p).__name__ for p in walk(phys)]
    assert "TpuProjectExec" in names and "CpuProjectExec" not in names
    got = out.collect_arrow().column("s").to_pylist()
    want = [2.0 * v + 1.0
            for v in _df(spark).collect_arrow().column("x").to_pylist()]
    assert np.allclose(sorted(got), sorted(want))


def test_profiler_trace_produces_output(spark, tmp_path):
    d = str(tmp_path / "trace")
    spark.startProfiler(d)
    _df(spark).groupBy("y").agg(F.sum("x").alias("s")).collect_arrow()
    spark.stopProfiler()
    import os

    found = []
    for root, _dirs, files in os.walk(d):
        found.extend(files)
    assert found, "profiler session produced no trace files"
