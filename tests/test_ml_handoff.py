"""ColumnarRdd zero-copy export, device UDFs, and profiler integration
(reference ColumnarRdd.scala, RapidsUDF.java, NvtxWithMetrics.scala)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.columnar_rdd import ColumnarRdd
from spark_rapids_tpu.api.session import TpuSparkSession

_CONF = {"spark.sql.shuffle.partitions": 2}


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


def _df(spark, n=1000):
    rng = np.random.default_rng(8)
    return spark.createDataFrame(pa.table({
        "x": pa.array(rng.random(n), type=pa.float64()),
        "y": pa.array(rng.integers(0, 10, n), type=pa.int64()),
    }))


def test_columnar_rdd_yields_device_batches(spark):
    import jax

    df = _df(spark).filter(F.col("x") > 0.5)
    batches = list(ColumnarRdd.convert(df))
    assert batches
    for b in batches:
        for c in b.columns:
            assert isinstance(c.data, jax.Array), type(c.data)


def test_to_jax_matches_collect(spark):
    df = _df(spark).select("x", (F.col("y") * 2).alias("y2"))
    arrays = ColumnarRdd.to_jax(df)
    want = df.collect_arrow()
    x, xv = arrays["x"]
    got = np.asarray(x)[np.asarray(xv)]
    assert np.allclose(sorted(got),
                       sorted(want.column("x").to_pylist()))


def test_device_udf_fused_on_device(spark):
    @F.device_udf(returnType="double")
    def scaled(v, v_valid):
        return v * 2.0 + 1.0, v_valid

    df = _df(spark)
    out = df.select(scaled(df["x"]).alias("s"))
    phys, _ = out._physical()

    def walk(p):
        yield p
        for c in p.children:
            yield from walk(c)

    names = [type(p).__name__ for p in walk(phys)]
    assert "TpuProjectExec" in names and "CpuProjectExec" not in names
    got = out.collect_arrow().column("s").to_pylist()
    want = [2.0 * v + 1.0
            for v in _df(spark).collect_arrow().column("x").to_pylist()]
    assert np.allclose(sorted(got), sorted(want))


def test_profiler_trace_produces_output(spark, tmp_path):
    d = str(tmp_path / "trace")
    spark.startProfiler(d)
    _df(spark).groupBy("y").agg(F.sum("x").alias("s")).collect_arrow()
    spark.stopProfiler()
    import os

    found = []
    for root, _dirs, files in os.walk(d):
        found.extend(files)
    assert found, "profiler session produced no trace files"


# ----------------- observability: OOM dumps + debug batch dumps (5.5)

def test_oom_dump_writes_state_at_terminal_failure(tmp_path):
    """A TERMINAL OOM (retry budget exhausted) writes a JSON
    spill-catalog snapshot to the configured dump dir — the reference
    gpuOomDumpDir post-mortem policy. Recoverable retry-class OOMs do
    NOT dump (they are normal execution events)."""
    import json

    from spark_rapids_tpu.runtime.errors import TpuRetryOOM
    from spark_rapids_tpu.runtime.retry import retry_on_oom

    s = TpuSparkSession({
        "spark.rapids.memory.gpu.oomDumpDir": str(tmp_path)})
    try:
        calls = {"n": 0}

        def always_oom():
            calls["n"] += 1
            raise TpuRetryOOM("forced")

        with pytest.raises(TpuRetryOOM):
            retry_on_oom(always_oom, max_attempts=3)
        assert calls["n"] == 3
        dumps = [p for p in tmp_path.iterdir() if p.suffix == ".json"]
        assert len(dumps) == 1, "exactly one dump at the TERMINAL OOM"
        state = json.loads(dumps[0].read_text())
        assert "retry budget exhausted" in state["reason"]
        assert "buffers" in state and "device_limit" in state
    finally:
        s.stop()


def test_debug_batch_dump(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    s = TpuSparkSession({
        "spark.rapids.sql.debug.dumpBatchesPath": str(tmp_path / "dumps"),
        "spark.rapids.sql.fusedExec.enabled": False,
        "spark.sql.shuffle.partitions": 2})
    try:
        t = pa.table({"x": pa.array(np.arange(100), type=pa.int64())})
        out = (s.createDataFrame(t)
               .filter(F.col("x") >= 50).collect_arrow())
        assert out.num_rows == 50
        files = list((tmp_path / "dumps").glob("*.parquet"))
        assert files, "no batch dumps written"
        # the dumped operator outputs are real, readable batches
        assert any(pq.read_table(f).num_rows for f in files)
    finally:
        s.stop()
