"""Shim loader (ShimLoader/SparkShimServiceProvider analog), plugin
lifecycle (Plugin.scala:412-684), api validation
(ApiValidation.scala), and dist packaging (parallel-worlds layout)."""

import json
import os

import pytest


def test_shim_loader_picks_current_jax():
    import jax

    from spark_rapids_tpu.shims import detect_shim_provider, get_shim

    mod = detect_shim_provider()
    assert mod.matches(jax.__version__)
    assert get_shim() is detect_shim_provider()


def test_shim_provider_selection_by_version():
    from spark_rapids_tpu.shims import ShimError, detect_shim_provider

    legacy = detect_shim_provider("0.4.30")
    assert "legacy" in legacy.__name__
    current = detect_shim_provider("0.9.0")
    assert "current" in current.__name__
    with pytest.raises(ShimError):
        detect_shim_provider("0.3.25")


def test_shim_worlds_export_identical_api():
    from spark_rapids_tpu.tools.api_validation import validate_shims

    assert validate_shims() == []


def test_operator_pair_signatures():
    from spark_rapids_tpu.tools.api_validation import (
        validate_operator_pairs,
    )

    assert validate_operator_pairs() == []


def test_shimmed_shard_map_runs():
    """The active world's shard_map executes a collective program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from spark_rapids_tpu.shims import get_shim

    devs = jax.devices()[:4]
    mesh = get_shim().make_mesh(devs, "x")

    def f(a):
        return jax.lax.psum(a, "x")

    out = get_shim().shard_map(f, mesh, (P("x"),), P())(
        jnp.arange(8.0))
    assert float(out.sum()) == float(jnp.arange(8.0).sum()) * 1

    # matches per-shard psum: every element equals total of its column
    # pairs across shards; just sanity-check shape/finite
    assert out.shape == (2,)


def test_plugin_lifecycle():
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.plugin import (
        ColumnarOverrideRules,
        TpuDriverPlugin,
        _is_fatal_device_error,
    )

    spark = TpuSparkSession({})
    try:
        assert spark._executor_plugin.initialized
        assert isinstance(spark._conf_map, dict)
        conf_map = TpuDriverPlugin().init(spark.rapids_conf)
        assert isinstance(conf_map, dict)
        rules = ColumnarOverrideRules()
        assert rules.pre_columnar_transitions(
            spark.rapids_conf) is not None
        # fatal classification: OOM-ish errors are NOT fatal
        assert not _is_fatal_device_error(MemoryError("oom"))
        assert not spark._executor_plugin.on_task_failed(
            ValueError("x"))
    finally:
        spark.stop()


def test_driver_plugin_warns_unknown_rapids_keys():
    import warnings

    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.plugin import TpuDriverPlugin

    conf = rc.RapidsConf({"spark.rapids.sql.noSuchKnob": 1})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        TpuDriverPlugin().init(conf)
    assert any("noSuchKnob" in str(x.message) for x in w)


def test_package_dist(tmp_path):
    from spark_rapids_tpu.tools.package_dist import build_dist

    target = build_dist(str(tmp_path))
    manifest = json.load(open(os.path.join(target, "MANIFEST.json")))
    assert manifest["version"]
    assert "jax_current" in manifest["shim_worlds"]
    assert os.path.isdir(os.path.join(target, "spark_rapids_tpu",
                                      "shims"))
    # the packaged tree is importable standalone
    import subprocess
    import sys

    code = ("import spark_rapids_tpu, spark_rapids_tpu.shims as s; "
            "print(s.get_shim().description())")
    env = dict(os.environ, PYTHONPATH=target, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr
    assert "world" in out.stdout


def test_fatal_policy_invoked_on_task_failure(monkeypatch):
    """exec/base.py routes task exceptions through
    TpuExecutorPlugin.on_task_failed (Plugin.scala onTaskFailed)."""
    import pyarrow as pa

    from spark_rapids_tpu import plugin as plugin_mod
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.api import functions as F

    seen = []
    orig = plugin_mod.TpuExecutorPlugin.on_task_failed

    def spy(self, exc):
        seen.append(type(exc).__name__)
        return orig(self, exc)

    monkeypatch.setattr(plugin_mod.TpuExecutorPlugin, "on_task_failed",
                        spy)
    spark = TpuSparkSession({})
    try:
        df = spark.createDataFrame(pa.table({"x": pa.array([1, 2])}))
        bad = df.select(
            F.udf(lambda v: 1 // 0, "bigint")(F.col("x")).alias("y"))
        with pytest.raises(Exception):
            bad.collect_arrow()
        assert seen, "on_task_failed was not invoked"
    finally:
        spark.stop()
