"""Resilient stage scheduler suite (PR 3): task re-attempts, worker
eviction, speculative execution with commit-once shuffle staging, and
lost-map-output lineage recomputation — the DAGScheduler semantics the
reference plugin inherits from Spark, proven here with deterministic
fault injection (worker.crash / task.straggler / shuffle.lost_output
sites) and direct TaskSet drives."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import rapids_conf as rc
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime import scheduler as sched
from spark_rapids_tpu.runtime.errors import ShuffleFetchError, WorkerLost
from spark_rapids_tpu.runtime.scheduler import StageScheduler, Task


@pytest.fixture(autouse=True)
def _isolated_faults():
    faults.install(faults.FaultRegistry())
    yield
    faults.install(faults.FaultRegistry())


def _arm(spec):
    faults.install(faults.FaultRegistry(
        42, faults.parse_sites(spec, 0.05)))


def _conf(**over):
    return rc.RapidsConf({k: v for k, v in over.items()})


def _delta(fn):
    before = sched.stats.snapshot()
    out = fn()
    return out, sched.stats.delta(before, sched.stats.snapshot())


# ----------------------------------------------------------- TaskSets

def test_results_in_task_order():
    tasks = [Task(i, run=lambda _a, i=i: i * i) for i in range(10)]
    out, d = _delta(lambda: StageScheduler(None, name="t").run(tasks))
    assert out == [i * i for i in range(10)]
    assert d["tasksLaunched"] == 10 and d["stagesRun"] == 1


def test_single_task_runs_inline():
    out, d = _delta(lambda: StageScheduler(None).run(
        [Task(0, run=lambda _a: "x")]))
    assert out == ["x"] and d["tasksLaunched"] == 1


def test_commit_called_exactly_once_per_task():
    commits = []
    tasks = [Task(i, run=lambda _a, i=i: i,
                  commit=lambda res, att, i=i: commits.append((i, res)))
             for i in range(6)]
    StageScheduler(None).run(tasks)
    assert sorted(commits) == [(i, i) for i in range(6)]


def test_nonretryable_error_propagates():
    def boom(_a):
        raise ValueError("semantic failure")

    tasks = [Task(0, run=lambda _a: 1), Task(1, run=boom)]
    with pytest.raises(ValueError, match="semantic failure"):
        StageScheduler(None, name="err").run(tasks)


# -------------------------------------------- worker.crash + eviction

def test_worker_crash_evicts_and_retries():
    _arm("worker.crash:once")
    tasks = [Task(i, run=lambda _a, i=i: i) for i in range(5)]
    out, d = _delta(lambda: StageScheduler(None, name="c").run(tasks))
    assert out == list(range(5))
    assert d["tasksRetried"] >= 1
    assert d["recomputedPartitions"] >= 1
    assert d["evictedWorkers"] >= 1
    assert d["tasksLaunched"] == 6  # 5 + the one re-attempt


def test_worker_crash_budget_exhaustion_raises():
    _arm("worker.crash:p=1.0")
    conf = _conf(**{"spark.rapids.tpu.stage.maxAttempts": 2})
    with pytest.raises(faults.InjectedFault):
        StageScheduler(conf, name="doom").run(
            [Task(i, run=lambda _a, i=i: i) for i in range(3)])


def test_worker_lost_exception_is_retryable():
    seen = []

    def flaky(attempt, i):
        seen.append((i, attempt))
        if i == 2 and attempt == 0:
            raise WorkerLost("w-x", "simulated executor death")
        return i

    tasks = [Task(i, run=lambda a, i=i: flaky(a, i)) for i in range(4)]
    out, d = _delta(lambda: StageScheduler(None, name="wl").run(tasks))
    assert out == list(range(4))
    assert (2, 1) in seen and d["evictedWorkers"] >= 1


def test_non_rerunnable_stage_disables_crash_injection():
    """Consuming lineage (device-mode blocks) must not be re-run: the
    scheduler runs single-attempt and skips the crash site."""
    _arm("worker.crash:p=1.0")
    tasks = [Task(i, run=lambda _a, i=i: i) for i in range(3)]
    out = StageScheduler(None, name="nr", rerunnable=False).run(tasks)
    assert out == [0, 1, 2]
    assert faults.counters()["worker.crash"]["injected"] == 0


# ------------------------------------------------------- speculation

def _spec_conf(**over):
    base = {"spark.rapids.tpu.speculation.enabled": True,
            "spark.rapids.tpu.speculation.multiplier": 1.2,
            "spark.rapids.tpu.speculation.quantile": 0.5,
            "spark.rapids.tpu.speculation.minTaskRuntimeMs": 30}
    base.update(over)
    return rc.RapidsConf(base)


def test_speculation_duplicates_straggler_and_commits_once():
    commits = []
    lock = threading.Lock()

    def run(attempt, i):
        # task 0's FIRST attempt stalls; its duplicate returns fast
        if i == 0 and attempt == 0:
            time.sleep(2.0)
        else:
            time.sleep(0.05)
        return (i, attempt)

    tasks = [Task(i, run=lambda a, i=i: run(a, i),
                  commit=lambda res, att, i=i:
                      commits.append((i, att)) or None)
             for i in range(4)]
    t0 = time.monotonic()
    out, d = _delta(lambda: StageScheduler(
        _spec_conf(), name="spec").run(tasks))
    wall = time.monotonic() - t0
    assert [o[0] for o in out] == list(range(4))
    assert d["tasksSpeculated"] >= 1
    assert d["speculativeWins"] >= 1
    assert wall < 1.9, "stage must finish before the straggler wakes"
    with lock:
        assert sorted(c[0] for c in commits) == [0, 1, 2, 3]


def test_injected_straggler_speculates():
    _arm("task.straggler:once")
    def run(_a):
        time.sleep(0.05)
        return 1

    tasks = [Task(i, run=run) for i in range(4)]
    out, d = _delta(lambda: StageScheduler(
        _spec_conf(), name="straggle").run(tasks))
    assert out == [1, 1, 1, 1]
    assert faults.counters()["task.straggler"]["injected"] == 1
    assert d["tasksSpeculated"] >= 1


# ------------------------- speculation commit-once over real shuffle

def _mk_mgr(tmp_path, mode="CACHE_ONLY"):
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    return ShuffleManager(mode, shuffle_dir=str(tmp_path),
                          num_threads=2)


def _rows(mgr, sid, nparts):
    return sum(t.num_rows for rp in range(nparts)
               for t in mgr.fetch(sid, rp))


@pytest.mark.parametrize("mode", ["CACHE_ONLY", "MULTITHREADED"])
def test_speculative_duplicate_never_double_counts(tmp_path, mode):
    """Satellite: two attempts of one map task both stage identical
    blocks; the first commit wins, the loser's blocks are discarded —
    row counts stay exact and remove_shuffle leaves NOTHING (no files,
    no staged entries, no committed markers)."""
    import os

    mgr = _mk_mgr(tmp_path, mode)
    sid = mgr.new_shuffle_id()
    t = pa.table({"a": pa.array(np.arange(100), pa.int64())})
    for rp in range(2):
        mgr.put(sid, rp, t, map_id=0, attempt=0)
        mgr.put(sid, rp, t, map_id=0, attempt=1)  # duplicate attempt
    assert _rows(mgr, sid, 2) == 0  # staged: invisible pre-commit
    assert mgr.commit_map_output(sid, 0, attempt=0) is True
    assert mgr.commit_map_output(sid, 0, attempt=1) is False  # loser
    assert mgr.speculative_discards >= 2
    assert _rows(mgr, sid, 2) == 200  # not 400: no double count
    mgr.remove_shuffle(sid)
    assert _rows(mgr, sid, 2) == 0
    assert not mgr._staged and not mgr._committed
    leftovers = [p for p in os.listdir(tmp_path) if p.endswith(".stpu")]
    assert leftovers == [], leftovers
    assert mgr.orphaned_files == 0
    mgr.shutdown()


def test_abandoned_attempt_discard_is_idempotent(tmp_path):
    mgr = _mk_mgr(tmp_path)
    sid = mgr.new_shuffle_id()
    t = pa.table({"a": [1, 2, 3]})
    mgr.put(sid, 0, t, map_id=3, attempt=0)
    mgr.discard_attempt(sid, 3, 0)
    mgr.discard_attempt(sid, 3, 0)  # second call: no-op
    assert _rows(mgr, sid, 1) == 0 and not mgr._staged
    mgr.remove_shuffle(sid)
    mgr.shutdown()


# ------------------------------------------- lost-output recomputation

def test_replace_commit_swaps_lost_map_output(tmp_path):
    mgr = _mk_mgr(tmp_path)
    sid = mgr.new_shuffle_id()
    t1 = pa.table({"a": pa.array(np.arange(10), pa.int64())})
    mgr.put(sid, 0, t1, map_id=0, attempt=0)
    mgr.commit_map_output(sid, 0, 0)
    assert _rows(mgr, sid, 1) == 10
    # recompute: identical data under a recovery attempt REPLACES
    att = mgr.recompute_attempt(sid, 0)
    mgr.put(sid, 0, t1, map_id=0, attempt=att)
    mgr.commit_map_output(sid, 0, att, replace=True)
    assert _rows(mgr, sid, 1) == 10  # swapped, not appended
    mgr.remove_shuffle(sid)
    mgr.shutdown()


def _eager_conf(**over):
    base = {"spark.rapids.sql.fusedExec.enabled": False,
            "spark.rapids.shuffle.mode": "MULTITHREADED",
            "spark.sql.shuffle.partitions": 4,
            "spark.rapids.tpu.io.retry.backoffMs": 1,
            "spark.rapids.tpu.io.retry.maxBackoffMs": 5}
    base.update(over)
    return base


def _shuffle_query(s):
    import spark_rapids_tpu.api.functions as F

    rng = np.random.default_rng(7)
    df = s.createDataFrame(pa.table({
        "k": pa.array(rng.integers(0, 50, 4000), pa.int64()),
        "v": pa.array(rng.random(4000))}))
    return (df.repartition(4, "k").groupBy("k")
            .agg(F.sum("v").alias("sv"), F.count("*").alias("c")))


def _sorted_dict(t):
    return t.sort_by([("k", "ascending")]).to_pydict()


def test_lost_output_recovery_end_to_end():
    """A shuffle block lost AFTER the block retry budget re-runs only
    the owning map task; results equal the clean run and
    last_execution['scheduler'] reports the recomputation."""
    from spark_rapids_tpu.api.session import TpuSparkSession

    s0 = TpuSparkSession(_eager_conf())
    want = _sorted_dict(_shuffle_query(s0).collect_arrow())
    s0.stop()
    s = TpuSparkSession(_eager_conf(**{
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.sites": "shuffle.lost_output:once"}))
    try:
        got = _sorted_dict(_shuffle_query(s).collect_arrow())
        assert got["k"] == want["k"] and got["c"] == want["c"]
        np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)
        rec = s.last_execution
        assert rec["scheduler"]["recomputedPartitions"] >= 1
        assert s.query_metrics.metric(
            "scheduler.recomputedPartitions").value >= 1
    finally:
        s.stop()


def test_lost_output_without_lineage_raises_cleanly():
    """A ShuffleFetchError with no owning map id (legacy writer) is
    NOT recoverable — it must surface, not spin."""
    from spark_rapids_tpu.exec.operators import TpuShuffleExchangeExec

    class _Mgr:
        def fetch(self, _sid, _pid):
            raise ShuffleFetchError("gone", map_id=None)

    ex = TpuShuffleExchangeExec.__new__(TpuShuffleExchangeExec)
    ex._shuffle_id = 1
    ex.conf = None
    import spark_rapids_tpu.exec.operators as ops
    real = ops.get_shuffle_manager
    ops.get_shuffle_manager = lambda: _Mgr()
    try:
        with pytest.raises(ShuffleFetchError):
            ex.fetch_blocks(0)
    finally:
        ops.get_shuffle_manager = real


def test_worker_crash_query_end_to_end():
    from spark_rapids_tpu.api.session import TpuSparkSession

    s0 = TpuSparkSession(_eager_conf())
    want = _sorted_dict(_shuffle_query(s0).collect_arrow())
    s0.stop()
    s = TpuSparkSession(_eager_conf(**{
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.sites": "worker.crash:once"}))
    try:
        got = _sorted_dict(_shuffle_query(s).collect_arrow())
        assert got["k"] == want["k"] and got["c"] == want["c"]
        rec = s.last_execution
        assert rec["scheduler"]["tasksRetried"] >= 1
        assert rec["scheduler"]["evictedWorkers"] >= 1
        assert s.robustness_metrics["scheduler"]["evictedWorkers"] >= 1
    finally:
        s.stop()


def test_speculation_query_end_to_end():
    """Injected straggler + speculation on a real multi-partition
    result stage (AQE off so partitions stay wide): identical results,
    speculated counter ticks, no double counts."""
    from spark_rapids_tpu.api.session import TpuSparkSession

    base = _eager_conf(**{"spark.sql.adaptive.enabled": False})
    s0 = TpuSparkSession(base)
    want = _sorted_dict(_shuffle_query(s0).collect_arrow())
    s0.stop()
    s = TpuSparkSession({**base,
                         "spark.rapids.tpu.speculation.enabled": True,
                         "spark.rapids.tpu.speculation.quantile": 0.5,
                         "spark.rapids.tpu.speculation.multiplier": 1.2,
                         "spark.rapids.tpu.speculation.minTaskRuntimeMs":
                             30,
                         "spark.rapids.tpu.chaos.enabled": True,
                         "spark.rapids.tpu.chaos.sites":
                             "task.straggler:once"})
    try:
        got = _sorted_dict(_shuffle_query(s).collect_arrow())
        assert got["k"] == want["k"] and got["c"] == want["c"]
        np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)
    finally:
        s.stop()
