"""Wiring tests: these fail if the optimizer, reader strategies, ORC/Avro
scan routing, or the native shuffle hash are disconnected from the engine
(round-2 verdict items: dead code must be called, with tests that break
when the wiring is removed)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.exec import operators as ops
from spark_rapids_tpu.io import readers
from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    assert_tpu_and_cpu_are_equal_collect,
    with_cpu_session,
    with_tpu_session,
)

_CONF = {"spark.sql.shuffle.partitions": 4}


@pytest.fixture(scope="module")
def pq_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("wiring")
    rng = np.random.default_rng(3)
    n = 4000
    t = pa.table({
        "a": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        "b": pa.array(rng.random(n) * 100, type=pa.float64()),
        "c": pa.array(rng.integers(-50, 50, n), type=pa.int64()),
    })
    # two files, each with several row groups so pushdown can prune
    pq.write_table(t.slice(0, 2000), os.path.join(d, "p0.parquet"),
                   row_group_size=500)
    pq.write_table(t.slice(2000, 2000), os.path.join(d, "p1.parquet"),
                   row_group_size=500)
    return str(d)


def _find(phys, cls):
    out = []

    def walk(p):
        if isinstance(p, cls):
            out.append(p)
        for c in p.children:
            walk(c)

    walk(phys)
    return out


# ------------------------------------------------- optimizer is invoked

def test_optimizer_prunes_scan_columns(pq_dir):
    def run(spark):
        df = (spark.read.parquet(pq_dir)
              .filter(F.col("a") > 10)
              .select((F.col("b") * 2).alias("x")))
        phys, _ = df._physical()
        return phys

    phys = with_tpu_session(run, _CONF)
    scans = _find(phys, ops.TpuFileScanExec)
    assert scans, "no scan in physical plan"
    # pruning: only a (filter) and b (project) should be read, not c
    assert sorted(scans[0].pushed_columns) == ["a", "b"]


def test_optimizer_pushes_filters_to_scan(pq_dir):
    def run(spark):
        df = (spark.read.parquet(pq_dir)
              .filter(F.col("a") > 50)
              .select("a", "b"))
        phys, _ = df._physical()
        return phys

    phys = with_tpu_session(run, _CONF)
    scans = _find(phys, ops.TpuFileScanExec)
    assert scans[0].pushed_filters == [("a", ">", 50)]


def test_pushdown_results_match_oracle(pq_dir):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(pq_dir)
        .filter((F.col("a") > 50) & (F.col("c") <= 25))
        .select("a", "b", "c"),
        conf=_CONF)


# -------------------------------------- reader strategies are dispatched

def test_perfile_strategy_splits_per_file(pq_dir):
    def run(spark):
        phys, _ = spark.read.parquet(pq_dir).select("a")._physical()
        return phys

    conf = dict(_CONF)
    conf["spark.rapids.sql.format.parquet.reader.type"] = "PERFILE"
    phys = with_tpu_session(run, conf)
    scan = _find(phys, ops.TpuFileScanExec)[0]
    assert scan.num_partitions == 2  # one task per file
    assert all(len(task) == 1 for task in scan._tasks)


def test_multithreaded_reader_is_called(pq_dir, monkeypatch):
    calls = []
    orig = readers.read_parquet_multithreaded

    def spy(*a, **k):
        calls.append(a)
        return orig(*a, **k)

    monkeypatch.setattr(readers, "read_parquet_multithreaded", spy)
    conf = dict(_CONF)
    conf["spark.rapids.sql.format.parquet.reader.type"] = "MULTITHREADED"
    got = with_tpu_session(
        lambda s: s.read.parquet(pq_dir).select("a", "b")
        .collect_arrow(), conf)
    assert calls, "MULTITHREADED conf did not reach the prefetch reader"
    want = with_cpu_session(
        lambda s: s.read.parquet(pq_dir).select("a", "b")
        .collect_arrow(), _CONF)
    assert_tables_equal(got, want)


def test_multithreaded_matches_oracle_with_pushdown(pq_dir):
    conf = dict(_CONF)
    conf["spark.rapids.sql.format.parquet.reader.type"] = "MULTITHREADED"
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(pq_dir)
        .filter(F.col("a") >= 90)
        .groupBy("a").agg(F.sum("b").alias("s")),
        conf=conf)


# ------------------------------------------------- orc / avro scan paths

@pytest.fixture(scope="module")
def orc_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("orcdata")
    rng = np.random.default_rng(4)
    n = 1000
    t = pa.table({
        "k": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        "v": pa.array(rng.random(n), type=pa.float64()),
    })
    from pyarrow import orc as pa_orc

    p = os.path.join(d, "data.orc")
    pa_orc.write_table(t, p)
    return p


@pytest.fixture(scope="module")
def avro_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("avrodata")
    rng = np.random.default_rng(5)
    n = 800
    t = pa.table({
        "k": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        "v": pa.array(rng.random(n), type=pa.float64()),
    })
    from spark_rapids_tpu.io.avro import write_avro

    p = os.path.join(d, "data.avro")
    write_avro(t, p)
    return p


def test_orc_scan_device_path(orc_path):
    def run(spark):
        df = spark.read.orc(orc_path).groupBy("k").agg(
            F.sum("v").alias("s"))
        phys, _ = df._physical()
        assert _find(phys, ops.TpuFileScanExec), \
            "orc scan did not route through the device scan exec"
        return df.collect_arrow()

    got = with_tpu_session(run, _CONF)
    want = with_cpu_session(
        lambda s: s.read.orc(orc_path).groupBy("k")
        .agg(F.sum("v").alias("s")).collect_arrow(), _CONF)
    assert_tables_equal(got, want)


def test_avro_scan_device_path(avro_path):
    def run(spark):
        df = spark.read.avro(avro_path).filter(F.col("v") > 0.5)
        phys, _ = df._physical()
        assert _find(phys, ops.TpuFileScanExec), \
            "avro scan did not route through the device scan exec"
        return df.collect_arrow()

    got = with_tpu_session(run, _CONF)
    want = with_cpu_session(
        lambda s: s.read.avro(avro_path).filter(F.col("v") > 0.5)
        .collect_arrow(), _CONF)
    assert_tables_equal(got, want)


# --------------------------------------- native murmur3 in CPU exchange

def test_cpu_exchange_uses_native_murmur3(monkeypatch):
    from spark_rapids_tpu import native

    if native.get_lib() is None:
        pytest.skip("native library unavailable")
    calls = []
    orig = native.murmur3_host

    def spy(cols, seed=42):
        calls.append(seed)
        return orig(cols, seed=seed)

    monkeypatch.setattr(native, "murmur3_host", spy)

    from spark_rapids_tpu.columnar.arrow_bridge import schema_from_arrow
    from spark_rapids_tpu.expr import BoundReference
    from spark_rapids_tpu.sqltypes.datatypes import long

    rng = np.random.default_rng(7)
    t = pa.table({"k": pa.array(rng.integers(0, 50, 2000),
                                type=pa.int64()),
                  "v": pa.array(rng.random(2000), type=pa.float64())})
    spark = TpuSparkSession({"spark.rapids.tpu.test.cpuOracle": True})
    try:
        child = ops.LocalRelationExec(t, schema_from_arrow(t.schema),
                                      spark.rapids_conf)
        ex = ops.CpuShuffleExchangeExec(
            child, [BoundReference(0, long, True)], 4, spark.rapids_conf)
        out = ex.collect()
    finally:
        spark.stop()
    assert calls, "CPU shuffle partitioning bypassed the native murmur3"
    assert out.num_rows == t.num_rows
    # every row with the same key lands in the same partition: verify by
    # comparing against the device partitioning path elsewhere (hash
    # parity suite); here row conservation + native call is the contract


# ------------------- batch coalescing goal lattice (GpuCoalesceBatches)

def test_coalesce_batches_after_chunked_scan(tmp_path):
    """Chunked scans yield many small batches; TpuCoalesceBatchesExec
    concatenates them toward batchSizeRows before per-batch consumers
    (goal-lattice role, GpuCoalesceBatches.scala:170-226)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.exec.operators import TpuCoalesceBatchesExec

    rng = np.random.default_rng(4)
    n = 20000
    xs = rng.random(n)
    pq.write_table(pa.table({"x": pa.array(xs)}),
                   str(tmp_path / "p.parquet"))
    s = TpuSparkSession({
        "spark.rapids.sql.reader.batchSizeRows": 1024,  # 20 chunks
        "spark.rapids.sql.batchSizeRows": 8192,
        "spark.rapids.sql.fusedExec.enabled": False})
    try:
        df = (s.read.parquet(str(tmp_path))
              .filter(F.col("x") > 0.5)
              .select((F.col("x") * 2).alias("y")))
        phys, _ = df._physical()

        found = []

        def walk(nd):
            if isinstance(nd, TpuCoalesceBatchesExec):
                found.append(nd)
            for c in nd.children:
                walk(c)

        walk(phys)
        assert found, "no coalesce node inserted after the scan"

        # batches reaching the filter are coalesced: count them
        from spark_rapids_tpu.exec.base import new_task_context

        batches = list(found[0].execute_partition(
            0, new_task_context(s.rapids_conf)))
        assert len(batches) <= 4, (
            f"{len(batches)} batches; expected ~20/8 coalesced groups")

        got = np.sort(np.asarray(df.collect_arrow().column("y")))
        want = np.sort(xs[xs > 0.5] * 2)
        np.testing.assert_allclose(got, want, rtol=1e-12)
    finally:
        s.stop()


def test_coalesce_identity_under_fused_and_mesh(tmp_path):
    """The coalesce node is identity for the fused and mesh engines —
    plans containing it still take those paths and stay correct."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSparkSession

    rng = np.random.default_rng(5)
    xs = rng.random(5000)
    ks = rng.integers(0, 20, 5000)
    pq.write_table(pa.table({"k": pa.array(ks, type=pa.int64()),
                             "x": pa.array(xs)}),
                   str(tmp_path / "p.parquet"))

    from spark_rapids_tpu.exec import fused as fused_mod
    from spark_rapids_tpu.parallel import plan_compiler as mesh_mod

    for conf, mod, cls_name in (
            ({"spark.rapids.sql.fusedExec.enabled": True},
             fused_mod, "FusedSingleChipExecutor"),
            ({"spark.rapids.tpu.mesh": 8},
             mesh_mod, "MeshQueryExecutor")):
        s = TpuSparkSession({**conf, "spark.sql.shuffle.partitions": 4})
        # assert the engine actually EXECUTED (a silent fallback to the
        # per-operator engine must fail this test, not pass it)
        cls = getattr(mod, cls_name)
        ran = {"n": 0}
        orig = cls.execute

        def spy(self, phys, _orig=orig, _ran=ran):
            _ran["n"] += 1
            return _orig(self, phys)

        cls.execute = spy
        try:
            df = (s.read.parquet(str(tmp_path)).groupBy("k")
                  .agg(F.sum("x").alias("sx")))
            got = {r["k"]: r["sx"] for r in
                   df.collect_arrow().to_pylist()}
            assert ran["n"] >= 1, f"{cls_name} never executed the plan"
            for k in np.unique(ks):
                np.testing.assert_allclose(got[int(k)],
                                           xs[ks == k].sum(), rtol=1e-9)
        finally:
            cls.execute = orig
            s.stop()
