"""UDF bytecode compiler tests (the udf-compiler OpcodeSuite analog):
compile Python lambdas to expression IR, execute on the device backend,
and diff against the Python function itself applied rowwise.
"""

import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.expr import BoundReference
from spark_rapids_tpu.sqltypes.datatypes import (
    boolean, double, long, string,
)
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_fallback_collect,
    with_tpu_session,
)
from spark_rapids_tpu.udf import UdfCompileError, compile_udf

GLOBAL_RATE = 1.25

NUMERIC_UDFS = [
    (lambda x: x * 2 + 1, long),
    (lambda x: (x - 3) * (x + 3), long),
    (lambda x: x % 7, long),
    (lambda x: x // 3, long),
    (lambda x: -x, long),
    (lambda x: abs(x - 500), long),
    (lambda x: x / 4, double),
    (lambda x: float(x) ** 2, double),
    (lambda x: math.sqrt(abs(x)) + math.log(x + 2000), double),
    (lambda x: x * GLOBAL_RATE, double),
    (lambda x: min(max(x, 10), 100), long),
    (lambda x: x if x > 0 else -x, long),
    (lambda x: 1 if x % 2 == 0 else 0, long),
    (lambda x: x > 0 and x % 5 == 0, boolean),
    (lambda x: x < -900 or x > 900, boolean),
    (lambda x: not (x > 0), boolean),
    (lambda x: (x & 255) ^ (x >> 3 & 15), long),
    (lambda x: x in (1, 5, 9, 42), boolean),
    (lambda x: round(x / 7, 2), double),
    (lambda x: x // -3, long),
    (lambda x: x % -3, long),
    (lambda x: x % -2.5, double),
    (lambda x: (x / 2) % -3.0, double),
    (lambda x: (x % 2 == 0) and (x // -7) % 5 > 1, boolean),
]


@pytest.mark.parametrize("case", range(len(NUMERIC_UDFS)))
def test_numeric_udf_compiles_and_matches_python(case):
    fn, rtype = NUMERIC_UDFS[case]
    # compiles (no fallback)
    compiled = compile_udf(fn, [BoundReference(0, long, True)])
    assert compiled is not None

    rng = np.random.default_rng(case)
    vals = rng.integers(-1000, 1000, 200).tolist() + [0, 1, -1, 999]

    def q(s):
        df = s.createDataFrame({"v": vals})
        u = F.udf(fn, returnType=rtype)
        return df.select(u(df["v"]).alias("out"))

    got = with_tpu_session(lambda s: q(s).collect_arrow())
    want = [fn(v) for v in vals]
    for g, w, v in zip(got.column("out").to_pylist(), want, vals):
        if isinstance(w, float):
            assert g == pytest.approx(w, rel=1e-9), (case, v, g, w)
        elif isinstance(w, bool):
            assert bool(g) == w, (case, v, g, w)
        else:
            assert g == w, (case, v, g, w)


STRING_UDFS = [
    lambda s: s.upper(),
    lambda s: s.strip().lower(),
    lambda s: s.startswith("ab"),
    lambda s: s.endswith("z"),
    lambda s: s.replace("a", "@"),
    lambda s: len(s),
    lambda s: "yes" if s.startswith("a") else "no",
]


@pytest.mark.parametrize("case", range(len(STRING_UDFS)))
def test_string_udf_matches_python(case):
    fn = STRING_UDFS[case]
    vals = ["abc", "  Padded  ", "xyz", "aZ", "", "abcz", "zebra"]
    sample = fn(vals[0])
    rtype = (boolean if isinstance(sample, bool)
             else long if isinstance(sample, int) else string)

    def q(s):
        df = s.createDataFrame({"v": vals})
        u = F.udf(fn, returnType=rtype)
        return df.select(u(df["v"]).alias("out"))

    got = with_tpu_session(lambda s: q(s).collect_arrow())
    want = [fn(v) for v in vals]
    for g, w in zip(got.column("out").to_pylist(), want):
        if isinstance(w, bool):
            assert bool(g) == w, (case, g, w)
        else:
            assert g == w, (case, g, w)


def test_none_guard_compiles():
    fn = lambda x: 0 if x is None else x + 1  # noqa: E731
    compiled = compile_udf(fn, [BoundReference(0, long, True)])

    def q(s):
        df = s.createDataFrame(pa.table({
            "v": pa.array([1, None, 3, None], type=pa.int64())}))
        u = F.udf(fn, returnType=long)
        return df.select(u(df["v"]).alias("out"))

    got = with_tpu_session(lambda s: q(s).collect_arrow())
    assert got.column("out").to_pylist() == [2, 0, 4, 0]


def test_two_arg_udf():
    fn = lambda a, b: a * b + a % (b + 10)  # noqa: E731

    def q(s):
        df = s.createDataFrame({"a": [1, 2, 3, -4, 5],
                                "b": [9, 8, 7, 6, 5]})
        u = F.udf(fn, returnType=long)
        return df.select(u(df["a"], df["b"]).alias("out"))

    got = with_tpu_session(lambda s: q(s).collect_arrow())
    want = [fn(a, b) for a, b in zip([1, 2, 3, -4, 5], [9, 8, 7, 6, 5])]
    assert got.column("out").to_pylist() == want


def test_closure_constant():
    factor = 3

    def fn(x):
        return x * factor

    compiled = compile_udf(fn, [BoundReference(0, long, True)])
    assert compiled is not None

    def q(s):
        df = s.createDataFrame({"v": [1, 2, 3]})
        u = F.udf(fn, returnType=long)
        return df.select(u(df["v"]).alias("out"))

    got = with_tpu_session(lambda s: q(s).collect_arrow())
    assert got.column("out").to_pylist() == [3, 6, 9]


UNCOMPILABLE = [
    lambda x: sum(range(x)),              # loop/builtin-iter
    lambda x: [x, x + 1],                 # list construction
    lambda x: {"k": x},                   # dict construction
    lambda x: str(x)[::-1] if x else "",  # slicing
]


@pytest.mark.parametrize("case", range(len(UNCOMPILABLE)))
def test_uncompilable_raises(case):
    with pytest.raises(UdfCompileError):
        compile_udf(UNCOMPILABLE[case], [BoundReference(0, long, True)])


def test_uncompilable_falls_back_to_host():
    """Uncompilable UDF runs rowwise on CPU; operator placement shows
    the fallback and results are still correct."""

    def weird(x):
        return sum(range(x % 5))

    def q(s):
        df = s.createDataFrame({"v": [3, 7, 11, 4]})
        u = F.udf(weird, returnType=long)
        return df.select(u(df["v"]).alias("out"))

    assert_tpu_fallback_collect(q, "CpuProjectExec")
    got = with_tpu_session(lambda s: q(s).collect_arrow())
    assert got.column("out").to_pylist() == [sum(range(v % 5))
                                             for v in [3, 7, 11, 4]]


def test_truthiness_and_typed_none_branches():
    """Python truthiness (`not x`, `if s:`) and None-returning branches
    compile with correct semantics (review regressions)."""

    def run(s, fn, rtype, data):
        df = s.createDataFrame(data)
        u = F.udf(fn, returnType=rtype)
        col = df[df.columns[0]]
        return df.select(u(col).alias("o")).collect_arrow() \
            .column("o").to_pylist()

    def q(s):
        assert run(s, lambda x: not x, boolean,
                   {"x": [0, 5, -3]}) == [True, False, False]
        tbl = pa.table({"s": pa.array(["abc", None, ""],
                                      type=pa.string())})
        assert run(s, lambda v: None if v is None else v.upper(),
                   string, tbl) == ["ABC", None, ""]
        assert run(s, lambda v: v.upper() if v else "EMPTY", string,
                   tbl) == ["ABC", "EMPTY", "EMPTY"]
        return s.createDataFrame({"k": [1]})

    with_tpu_session(lambda s: q(s))


def test_python_floor_div_and_mod_negative_semantics():
    """Python // and % (floor/sign-of-divisor) — NOT Java truncation."""
    fn = lambda x: (x // 3) * 100 + x % 3  # noqa: E731

    def q(s):
        df = s.createDataFrame({"v": [-7, -3, -1, 0, 1, 7]})
        u = F.udf(fn, returnType=long)
        return df.select(u(df["v"]).alias("out"))

    got = with_tpu_session(lambda s: q(s).collect_arrow())
    assert got.column("out").to_pylist() == [fn(v)
                                            for v in [-7, -3, -1, 0, 1, 7]]
