"""Out-of-core streaming executor (spark_rapids_tpu/stream/): window
bounding, encoded-codes row capacity, priority-scaled window quotas,
and mid-stream cancellation hygiene."""

import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession

WINDOW = 2 << 20  # forced-small device window for every test here


def _write_dataset(tmp_path, files=4, rows=120_000, seed=0):
    rng = np.random.default_rng(seed)
    d = tmp_path / "ds"
    d.mkdir(exist_ok=True)
    for i in range(files):
        t = pa.table({
            "store": pa.array(rng.integers(0, 50, rows), pa.int64()),
            "amount": pa.array(rng.integers(0, 100, rows), pa.int64()),
            "region": pa.array(
                rng.choice(["east", "west", "north", "south"], rows)),
        })
        pq.write_table(t, str(d / f"part{i}.parquet"),
                       row_group_size=20_000)
    return str(d)


def _stream_conf(**extra):
    conf = {
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.tpu.stream.enabled": "true",
        "spark.rapids.tpu.stream.window.maxBytes": str(WINDOW),
        # make the selection gate trip for any test-sized table
        "spark.rapids.tpu.stream.window.quotaFraction": "0.0001",
    }
    conf.update(extra)
    return conf


def _canon(t):
    cols = [t.column(i).to_pylist() for i in range(t.num_columns)]
    return sorted(map(tuple, zip(*cols))) if cols else []


def _query(spark, path):
    return (spark.read.parquet(path)
            .filter(F.col("amount") > 15)
            .groupBy("region")
            .agg(F.sum("amount").alias("s"), F.count("*").alias("c")))


# ------------------------------------------------- window high-water

def test_window_bounded_high_water(tmp_path):
    """A table many times the window streams oracle-identically with
    the catalog's device high-water inside the window budget plus
    slack — the out-of-core contract."""
    from spark_rapids_tpu.runtime.memory import get_catalog

    path = _write_dataset(tmp_path)
    s = TpuSparkSession(_stream_conf())
    try:
        out = _query(s, path).collect_arrow()
        rec = s.last_execution
        tel = rec.get("telemetry") or {}
        assert rec["engine"] == "stream"
        # many window-sized admissions, not one table-sized one
        assert tel.get("partitionsStreamed", 0) >= 8
        # window accounting bounded by the budget (estimate-based, so
        # a capacity-padding slack rides on top)
        assert tel.get("windowPeakBytes", 0) <= 2 * WINDOW
        # the REAL device high-water must also stay window-shaped:
        # well under the decoded table size (~4x window here), with
        # slack for padding, spill scratch and the final merge
        assert get_catalog().pool.peak <= 4 * WINDOW
        assert tel.get("overlapFraction") is not None
    finally:
        s.stop()
    s2 = TpuSparkSession({"spark.sql.shuffle.partitions": 4,
                          "spark.rapids.tpu.stream.enabled": "false"})
    try:
        want = _query(s2, path).collect_arrow()
    finally:
        s2.stop()
    assert _canon(out) == _canon(want)


# ------------------------------------------- encoded codes in window

def test_encoded_codes_shrink_window(tmp_path):
    """Low-cardinality strings stream as dictionary CODES: the same
    row count admits strictly fewer window bytes encoded than with
    decoded strings, so each window slot holds more rows."""
    path = _write_dataset(tmp_path, files=2)

    def peak(encoded):
        s = TpuSparkSession(_stream_conf(**{
            "spark.rapids.tpu.encoded.enabled": str(encoded).lower(),
        }))
        try:
            out = _query(s, path).collect_arrow()
            tel = (s.last_execution or {}).get("telemetry") or {}
            assert s.last_execution["engine"] == "stream"
            return _canon(out), tel.get("windowPeakBytes", 0)
        finally:
            s.stop()

    rows_enc, peak_enc = peak(True)
    rows_plain, peak_plain = peak(False)
    assert rows_enc == rows_plain
    assert peak_enc > 0 and peak_plain > 0
    assert peak_enc < peak_plain


# --------------------------------------------- priority-scaled quota

def test_priority_scales_window_budget():
    """A batch-class (negative priority) tenant derives HALF the
    window of an interactive one under identical memory conditions —
    the starvation guard for 10x-HBM batch streams (regression for
    the quota-scaling rule, not a timing test)."""
    from spark_rapids_tpu.stream import window_budget

    # quotaFraction=1.0 so the conf'd maxBytes is the binding term and
    # the expected budgets are deterministic regardless of free HBM
    s = TpuSparkSession(_stream_conf(**{
        "spark.rapids.tpu.stream.window.quotaFraction": "1.0",
    }))
    try:
        conf = s.rapids_conf
        interactive = window_budget(conf, priority=100)
        standard = window_budget(conf, priority=0)
        batch = window_budget(conf, priority=-100)
        assert interactive == standard == WINDOW
        assert batch == WINDOW // 2
        assert batch < interactive
    finally:
        s.stop()


def test_window_budget_floor_and_quota_cap():
    from spark_rapids_tpu.stream import window_budget
    from spark_rapids_tpu.stream.window import MIN_WINDOW_BYTES

    s = TpuSparkSession(_stream_conf(**{
        "spark.rapids.tpu.stream.window.maxBytes": "1",
    }))
    try:
        assert window_budget(s.rapids_conf) == MIN_WINDOW_BYTES
        # the floor is priority-independent: even a batch tenant's
        # halved budget cannot drop below one usable slot
        assert window_budget(s.rapids_conf,
                             priority=-100) == MIN_WINDOW_BYTES
    finally:
        s.stop()


# ------------------------------------------------- mid-stream cancel

def test_midstream_cancel_leak_free(tmp_path):
    """A query deadline landing mid-stream unwinds leak-free: no
    spillable buffers, no device reservation, no admission slot left
    behind — and the session still serves the next query."""
    from spark_rapids_tpu.runtime import admission
    from spark_rapids_tpu.runtime.errors import (
        QueryCancelledError,
        QueryDeadlineExceeded,
    )
    from spark_rapids_tpu.runtime.memory import get_catalog

    path = _write_dataset(tmp_path)
    s = TpuSparkSession(_stream_conf(**{
        "spark.rapids.tpu.query.timeoutMs": "1",
    }))
    try:
        with pytest.raises((QueryDeadlineExceeded, QueryCancelledError)):
            _query(s, path).collect_arrow()
        # prefetch/upload threads unwind asynchronously; give the
        # pipeline a bounded quiesce before asserting hygiene
        cat = get_catalog()
        deadline = time.time() + 10
        while time.time() < deadline and (
                cat.buffer_count() or cat.pool.reserved):
            time.sleep(0.1)
        assert cat.check_leaks() == 0
        assert cat.buffer_count() == 0
        assert cat.pool.reserved == 0
        assert admission.current_handle() is None
        # the lane is clear: the next (undeadlined) query runs
        s.conf.set("spark.rapids.tpu.query.timeoutMs", "0")
        out = _query(s, path).collect_arrow()
        assert out.num_rows == 4
    finally:
        s.stop()


# ------------------------------------------------ planner selection

def test_small_scan_not_selected(tmp_path):
    """A scan that fits residently must NOT stream — the resident
    engines are faster in core."""
    path = _write_dataset(tmp_path, files=1, rows=1_000)
    s = TpuSparkSession({
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.tpu.stream.enabled": "true",
        # default quota fraction: a 1k-row table is far under it
    })
    try:
        _query(s, path).collect_arrow()
        assert s.last_execution["engine"] != "stream"
    finally:
        s.stop()


def test_explain_stamps_stream_strategy(tmp_path, capsys):
    path = _write_dataset(tmp_path, files=2)
    s = TpuSparkSession(_stream_conf())
    try:
        df = _query(s, path)
        df.collect_arrow()
        assert s.last_execution["engine"] == "stream"
        df.explain()
        text = capsys.readouterr().out
        assert "TpuFileScanExec [strategy=stream]" in text
    finally:
        s.stop()
