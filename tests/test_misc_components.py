"""Columnar cache (df.cache), z-order OPTIMIZE, Hive text serde, and
generated docs — the remaining small inventory components."""

import glob
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession

_CONF = {"spark.sql.shuffle.partitions": 2}


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


def _df(spark, n=800, seed=3):
    rng = np.random.default_rng(seed)
    return spark.createDataFrame(pa.table({
        "a": pa.array(rng.integers(0, 50, n), type=pa.int64()),
        "b": pa.array(rng.random(n), type=pa.float64()),
        "s": pa.array([f"r{i % 9}" for i in range(n)],
                      type=pa.string()),
    }))


# --------------------------------------------------------------- cache

def test_cache_serves_second_action(spark, monkeypatch):
    df = _df(spark).groupBy("a").agg(F.sum("b").alias("t")).cache()
    first = df.collect_arrow()
    assert df._cache_blob is not None
    # second action must not re-plan: poison the planner
    import spark_rapids_tpu.plan.overrides as ov

    def boom(*a, **k):
        raise AssertionError("replanned a cached DataFrame")

    monkeypatch.setattr(ov, "plan_query", boom)
    second = df.collect_arrow()
    assert second.equals(first)
    df.unpersist()
    assert df._cache_blob is None


def test_cache_blob_is_compressed_parquet(spark):
    df = _df(spark, n=5000).cache()
    raw = df.collect_arrow()
    assert len(df._cache_blob) < raw.nbytes  # parquet-compressed


# -------------------------------------------------------------- z-order

def test_zorder_kernel_locality():
    """Morton-sorted data clusters both dimensions: the first half of
    rows covers about half the range of EACH key, unlike a plain sort
    (which only clusters the primary key)."""
    import jax

    from spark_rapids_tpu.columnar.arrow_bridge import (
        arrow_to_device,
        device_to_arrow,
    )
    from spark_rapids_tpu.ops.zorder import zorder_sort

    rng = np.random.default_rng(5)
    n = 4096
    t = pa.table({
        "x": pa.array(rng.integers(0, 1 << 20, n), type=pa.int64()),
        "y": pa.array(rng.integers(0, 1 << 20, n), type=pa.int64()),
    })
    out = device_to_arrow(zorder_sort(arrow_to_device(t), [0, 1]))
    # an aligned quarter of the Morton curve is a quadrant of key
    # space: BOTH dimensions roughly halve (a plain sort would only
    # constrain the primary key)
    quarter = out.slice(0, n // 4)
    for col in ("x", "y"):
        spread = (max(quarter.column(col).to_pylist()) -
                  min(quarter.column(col).to_pylist()))
        full = (max(out.column(col).to_pylist()) -
                min(out.column(col).to_pylist()))
        assert spread < 0.7 * full, (col, spread, full)
    # row set preserved
    assert sorted(out.column("x").to_pylist()) == \
        sorted(t.column("x").to_pylist())


def test_delta_optimize_zorder(spark, tmp_path):
    from spark_rapids_tpu.lakehouse.delta import DeltaTable, load_snapshot

    p = str(tmp_path / "zt")
    _df(spark, n=500).write.format("delta").save(p)
    DeltaTable.forPath(spark, p).optimize().executeZOrderBy("a", "b")
    snap = load_snapshot(p)
    assert snap.version == 1
    out = spark.read.delta(p).collect_arrow()
    assert out.num_rows == 500


# ------------------------------------------------------------ hive text

def test_hive_text_roundtrip(spark, tmp_path):
    df = _df(spark, n=300)
    p = str(tmp_path / "ht")
    df.write.format("hivetext").save(p)
    # part files carry the committer's job-unique tag
    [part] = glob.glob(os.path.join(p, "part-00000-*.txt"))
    raw = open(part).readline()
    assert "\x01" in raw  # LazySimpleSerDe delimiter
    import pyarrow as _pa

    schema = _pa.schema([("a", _pa.int64()), ("b", _pa.float64()),
                         ("s", _pa.string())])
    back = (spark.read.schema(schema).hivetext(p)
            .groupBy("s").agg(F.count("*").alias("n")).collect_arrow())
    want = df.groupBy("s").agg(F.count("*").alias("n")).collect_arrow()
    assert sorted(back.column("n").to_pylist()) == \
        sorted(want.column("n").to_pylist())


def test_hive_text_nulls(spark, tmp_path):
    t = pa.table({"a": pa.array([1, None, 3], type=pa.int64()),
                  "s": pa.array(["x", None, "z"], type=pa.string())})
    df = spark.createDataFrame(t)
    p = str(tmp_path / "htn")
    df.write.format("hivetext").save(p)
    [part] = glob.glob(os.path.join(p, "part-00000-*.txt"))
    content = open(part).read()
    assert "\\N" in content
    import pyarrow as _pa

    schema = _pa.schema([("a", _pa.int64()), ("s", _pa.string())])
    back = spark.read.schema(schema).hivetext(p).collect_arrow()
    assert back.column("a").to_pylist() == [1, None, 3]
    assert back.column("s").to_pylist() == ["x", None, "z"]


# ----------------------------------------------------------------- docs

def test_generated_docs_current(tmp_path):
    """docs/ artifacts match the generators (the reference keeps
    supported_ops.md generated and checked in)."""
    from spark_rapids_tpu.tools.gendocs import configs_md, supported_ops_md

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert open(os.path.join(repo, "docs", "configs.md")).read() == \
        configs_md()
    assert open(os.path.join(repo, "docs",
                             "supported_ops.md")).read() == \
        supported_ops_md()


def test_docs_mention_core_surface():
    from spark_rapids_tpu.tools.gendocs import configs_md, supported_ops_md

    cfg = configs_md()
    assert "spark.rapids.tpu.mesh" in cfg
    assert "spark.rapids.shuffle.compression.codec" in cfg
    ops = supported_ops_md()
    assert "TpuShuffledHashJoinExec" in ops
    assert "ArrayTransform" in ops


# ------------------------------------------------------ parse_url / explain

def test_parse_url(spark):
    urls = ["https://user:pw@example.com:8080/a/b?x=1&y=2#frag",
            "http://spark.apache.org/path", "not a url", None]
    df = spark.createDataFrame(pa.table({"u": pa.array(
        urls, type=pa.string())}))
    out = df.select(
        F.parse_url(F.col("u"), "HOST").alias("host"),
        F.parse_url(F.col("u"), "PROTOCOL").alias("proto"),
        F.parse_url(F.col("u"), "PATH").alias("path"),
        F.parse_url(F.col("u"), "QUERY", "y").alias("qy"),
    ).collect_arrow()
    assert out.column("host").to_pylist() == [
        "example.com", "spark.apache.org", None, None]
    assert out.column("proto").to_pylist() == ["https", "http", None,
                                               None]
    assert out.column("qy").to_pylist() == ["2", None, None, None]


def test_explain_potential_plan_api(spark):
    @F.pandas_udf(returnType="long")
    def slow(a):
        return a

    df = _df(spark).select(slow(F.col("a")).alias("x"))
    txt = spark.explainPotentialTpuPlan(df)
    assert "NOT_ON_TPU" in txt and "Arrow worker-process" in txt
    ok = spark.explainPotentialTpuPlan(_df(spark).select("a"))
    assert "NOT_ON_TPU" not in ok or "device" in ok
