"""Structural plan cache suite (serve/plan_cache.py).

The correctness contract under test: literal-only differences share
one cache entry (normalization parameterizes them out); an exact
binding repeat reuses the PLANNED physical; a new binding rebinds the
template and re-plans (literals flow into pushed-down predicates, so
results must track the new values); any spark.* conf change and any
tenant change misses instead of serving a stale or cross-tenant plan.
"""

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.serve.plan_cache import (
    AUTO_PARAM_PREFIX,
    PlanCache,
    binding_key,
    conf_digest,
    normalize_spec,
)
from spark_rapids_tpu.serve.spec import SpecError

N_ROWS = 300


@pytest.fixture(scope="module")
def table_path(tmp_path_factory):
    t = pa.table({
        "a": pa.array(range(N_ROWS), pa.int64()),
        "b": pa.array([float(i) for i in range(N_ROWS)],
                      pa.float64()),
    })
    path = str(tmp_path_factory.mktemp("plan_cache") / "t.parquet")
    pq.write_table(t, path)
    return path


@pytest.fixture(scope="module")
def session():
    s = TpuSparkSession({})
    yield s
    s.stop()


def _spec(path, key="lo"):
    return {"op": "filter",
            "input": {"op": "parquet", "path": path},
            "cond": {"fn": ">=", "args": [{"col": "a"},
                                          {"param": key}]}}


def _lit_spec(path, lo):
    return {"op": "filter",
            "input": {"op": "parquet", "path": path},
            "cond": {"fn": ">=", "args": [{"col": "a"},
                                          {"lit": lo}]}}


def _run(cache, session, tenant, spec, params=None):
    df, info, release = cache.dataframe_for(session, tenant, spec,
                                            params or {})
    ok = False
    try:
        table = df.collect_arrow()
        ok = True
    finally:
        release(ok)
    return table, info


# ------------------------------------------------------ normalization


def test_normalize_spec_parameterizes_literals(table_path):
    p0 = f"{AUTO_PARAM_PREFIX}0"
    norm, auto = normalize_spec(_lit_spec(table_path, 42))
    assert auto == {p0: 42}
    assert norm["cond"]["args"][1] == {"param": p0}
    # two specs differing only in the literal normalize identically
    norm2, auto2 = normalize_spec(_lit_spec(table_path, 7))
    assert norm == norm2
    assert auto2 == {p0: 7}


def test_reserved_prefix_param_refs_rejected(table_path):
    # a spec referencing the reserved auto-param namespace would
    # collide with an extracted literal — rejected, not misbound
    with pytest.raises(SpecError):
        normalize_spec(_spec(table_path, key=f"{AUTO_PARAM_PREFIX}0"))


def test_normalize_spec_keeps_isin_values_structural():
    spec = {"fn": "isin", "args": [{"col": "a"}, {"lit": 1},
                                   {"lit": 2}]}
    norm, auto = normalize_spec(spec)
    # isin values are part of the expression SHAPE — never params
    assert norm["args"][1:] == [{"lit": 1}, {"lit": 2}]
    assert auto == {}


def test_binding_key_distinguishes_type_and_value():
    assert binding_key({"x": 1}) != binding_key({"x": 2})
    assert binding_key({"x": 1}) != binding_key({"x": 1.0})
    assert binding_key({"x": 1}) == binding_key({"x": 1})


def test_conf_digest_only_tracks_spark_keys():
    base = {"spark.rapids.tpu.sql.enabled": True, "noise": 1}
    assert conf_digest(base) == conf_digest({**base, "noise": 2})
    assert conf_digest(base) != conf_digest(
        {**base, "spark.rapids.tpu.sql.enabled": False})


# -------------------------------------------------------- hit & miss


def test_exact_hit_then_rebind_results_track(session, table_path):
    cache = PlanCache()
    t1, i1 = _run(cache, session, "t", _spec(table_path),
                  {"lo": 250})
    assert i1["planCache"] == "miss"
    t2, i2 = _run(cache, session, "t", _spec(table_path),
                  {"lo": 250})
    assert i2["planCache"] == "hit-exact"
    assert t2.equals(t1)
    assert t2.num_rows == 50
    # NEW binding: the template rebinds and RE-PLANS — the pushed-down
    # predicate must carry the new literal, not the cached one
    t3, i3 = _run(cache, session, "t", _spec(table_path), {"lo": 10})
    assert i3["planCache"] == "hit-rebind"
    assert t3.num_rows == N_ROWS - 10
    assert pc.min(t3["a"]).as_py() == 10
    snap = cache.stats.snapshot()
    assert snap["misses"] == 1
    assert snap["hitsExact"] == 1
    assert snap["hitsRebind"] == 1
    assert snap["hitRatio"] == pytest.approx(2 / 3, abs=1e-4)


def test_literal_specs_share_the_entry(session, table_path):
    """Clients that embed literals instead of params still hit: the
    normalizer parameterizes `{"lit": v}` out."""
    cache = PlanCache()
    _run(cache, session, "t", _lit_spec(table_path, 100))
    t2, i2 = _run(cache, session, "t", _lit_spec(table_path, 200))
    assert i2["planCache"] == "hit-rebind"
    assert t2.num_rows == 100
    assert len(cache) == 1


def test_rebound_binding_is_stored_for_exact_reuse(session,
                                                   table_path):
    cache = PlanCache()
    _run(cache, session, "t", _spec(table_path), {"lo": 1})
    _run(cache, session, "t", _spec(table_path), {"lo": 2})
    _, info = _run(cache, session, "t", _spec(table_path), {"lo": 2})
    assert info["planCache"] == "hit-exact"


def test_param_type_change_is_a_different_shape(session, table_path):
    cache = PlanCache()
    _run(cache, session, "t", _spec(table_path), {"lo": 10})
    _, info = _run(cache, session, "t", _spec(table_path),
                   {"lo": 10.0})
    # int vs float binding: different type signature, different key
    assert info["planCache"] == "miss"
    assert len(cache) == 2


# ------------------------------------------------------- invalidation


def test_conf_change_invalidates(session, table_path):
    cache = PlanCache()
    _run(cache, session, "t", _spec(table_path), {"lo": 5})
    old = dict(session._settings)
    session._settings["spark.rapids.tpu.sql.testShim"] = "x"
    try:
        _, info = _run(cache, session, "t", _spec(table_path),
                       {"lo": 5})
        assert info["planCache"] == "miss"
    finally:
        session._settings.clear()
        session._settings.update(old)
    _, info = _run(cache, session, "t", _spec(table_path), {"lo": 5})
    assert info["planCache"] == "hit-exact"


def test_per_tenant_isolation(session, table_path):
    """Tenant A's entries never serve tenant B — the tenant id is part
    of the structural key."""
    cache = PlanCache()
    _run(cache, session, "tenant-a", _spec(table_path), {"lo": 5})
    _, info = _run(cache, session, "tenant-b", _spec(table_path),
                   {"lo": 5})
    assert info["planCache"] == "miss"
    assert len(cache) == 2
    _, info = _run(cache, session, "tenant-b", _spec(table_path),
                   {"lo": 5})
    assert info["planCache"] == "hit-exact"


# ------------------------------------------- bounds & degraded modes


def test_entry_lru_eviction(session, table_path):
    cache = PlanCache(max_entries=1)
    _run(cache, session, "t", _spec(table_path), {"lo": 1})
    _run(cache, session, "u", _spec(table_path), {"lo": 1})
    assert len(cache) == 1
    assert cache.stats.snapshot()["evictions"] == 1
    # the evicted tenant misses again
    _, info = _run(cache, session, "t", _spec(table_path), {"lo": 1})
    assert info["planCache"] == "miss"


def test_binding_lru_bound(session, table_path):
    cache = PlanCache(bindings_per_entry=2)
    for lo in (1, 2, 3):
        _run(cache, session, "t", _spec(table_path), {"lo": lo})
    # lo=1 was evicted from the binding LRU: exact repeat re-plans
    _, info = _run(cache, session, "t", _spec(table_path), {"lo": 1})
    assert info["planCache"] == "hit-rebind"
    _, info = _run(cache, session, "t", _spec(table_path), {"lo": 3})
    assert info["planCache"] == "hit-exact"


def test_reserved_prefix_user_params_rejected(session, table_path):
    """A client param in the reserved auto-literal namespace could
    silently override an extracted literal's value (diverging from
    the cache-disabled path) — both paths reject it up front."""
    for cache in (PlanCache(), PlanCache(enabled=False)):
        with pytest.raises(SpecError) as ei:
            cache.dataframe_for(session, "t", _lit_spec(table_path, 5),
                                {f"{AUTO_PARAM_PREFIX}0": 99})
        assert "reserved" in str(ei.value)


def test_user_params_and_literals_coexist(session, table_path):
    """A spec mixing a literal (auto-parameterized) with ordinary
    user params binds both correctly, identical to the disabled
    path."""
    spec = {"op": "filter",
            "input": {"op": "parquet", "path": table_path},
            "cond": {"fn": "and", "args": [
                {"fn": ">=", "args": [{"col": "a"}, {"lit": 100}]},
                {"fn": "<", "args": [{"col": "a"},
                                     {"param": "hi"}]}]}}
    cache = PlanCache()
    t1, info = _run(cache, session, "t", spec, {"hi": 200})
    assert info["planCache"] == "miss"
    assert t1.num_rows == 100
    assert pc.min(t1["a"]).as_py() == 100
    t2, info2 = _run(PlanCache(enabled=False), session, "t", spec,
                     {"hi": 200})
    assert t2.sort_by("a").equals(t1.sort_by("a"))
    # and the shape stays cacheable across user-param rebinds
    t3, info3 = _run(cache, session, "t", spec, {"hi": 150})
    assert info3["planCache"] == "hit-rebind"
    assert t3.num_rows == 50


def test_disabled_cache_bypasses(session, table_path):
    cache = PlanCache(enabled=False)
    t, info = _run(cache, session, "t", _spec(table_path), {"lo": 5})
    assert info["planCache"] == "bypass"
    assert t.num_rows == N_ROWS - 5
    assert len(cache) == 0


def test_param_in_isin_is_uncacheable_but_correct(session,
                                                  table_path):
    """A parameter inside an isin VALUE list can't live in a template
    (the values embed into the expression shape) — the cache degrades
    to direct compilation and caches nothing."""
    cache = PlanCache()
    spec = {"op": "filter",
            "input": {"op": "parquet", "path": table_path},
            "cond": {"fn": "isin",
                     "args": [{"col": "a"}, {"param": "v1"},
                              {"lit": 7}]}}
    t, info = _run(cache, session, "t", spec, {"v1": 3})
    assert info["planCache"] == "miss"
    assert sorted(t["a"].to_pylist()) == [3, 7]
    assert len(cache) == 0
    # and it stays correct (and uncached) on the next binding
    t2, _ = _run(cache, session, "t", spec, {"v1": 9})
    assert sorted(t2["a"].to_pylist()) == [7, 9]
    assert len(cache) == 0


def test_failed_execution_drops_its_binding(session, table_path):
    cache = PlanCache()
    _run(cache, session, "t", _spec(table_path), {"lo": 4})
    df, info, release = cache.dataframe_for(
        session, "t", _spec(table_path), {"lo": 4})
    assert info["planCache"] == "hit-exact"
    release(False)  # simulated failed execution: poison the binding
    _, info = _run(cache, session, "t", _spec(table_path), {"lo": 4})
    # the poisoned physical was dropped — re-planned, not served again
    assert info["planCache"] == "hit-rebind"


# ------------------------------- fleet affinity & byte-stability


def _affinity_cases(path):
    from spark_rapids_tpu.serve.plan_cache import affinity_key

    return {
        "param": affinity_key("t", _spec(path), {"lo": 5}),
        "param-other-value": affinity_key("t", _spec(path),
                                          {"lo": 99}),
        "lit": affinity_key("t", _lit_spec(path, 5)),
        "lit-other-value": affinity_key("t", _lit_spec(path, 99)),
        "float-binding": affinity_key("t", _spec(path), {"lo": 5.0}),
        "other-tenant": affinity_key("u", _spec(path), {"lo": 5}),
    }


def test_affinity_key_is_structural_not_literal(table_path):
    """The router's hash-ring input must pin repeat SHAPES to one
    replica: binding values don't move it, types and tenants do."""
    k = _affinity_cases(table_path)
    assert k["param"] == k["param-other-value"]
    assert k["lit"] == k["lit-other-value"]
    # a {"lit": v} spec and its {"param": ...} twin differ only in
    # the param NAME (__lit0 vs lo) — structurally distinct, and
    # that is fine: each client style still self-affines
    assert k["param"] != k["float-binding"]  # type signature counts
    assert k["param"] != k["other-tenant"]   # tenant isolation


def test_affinity_key_ignores_planning_conf(table_path):
    """Replicas may run different confs; the conf digest belongs to
    the replica-side structural key, never to routing affinity."""
    from spark_rapids_tpu.serve.plan_cache import (
        PlanCache,
        affinity_key,
    )

    a = affinity_key("t", _spec(table_path), {"lo": 5})
    assert a == affinity_key("t", _spec(table_path), {"lo": 5})
    cache = PlanCache()
    norm, auto = normalize_spec(_spec(table_path))
    s1 = cache.structural_key("t", norm, {"lo": 5}, {})
    s2 = cache.structural_key(
        "t", norm, {"lo": 5}, {"spark.rapids.tpu.sql.x": "1"})
    # replica-side keys DO invalidate on spark.* conf change...
    assert s1 != s2
    # ...while the router-side affinity key is conf-free by
    # construction (no settings input at all) — s1/s2 divergence
    # cannot split a tenant's affinity


def test_keys_are_byte_stable_across_processes(table_path):
    """satellite: affinity routing only works if a FRESH process (a
    restarted router, a respawned replica) digests the same spec to
    the same bytes — no dict-order, hash-seed or repr drift."""
    import json
    import subprocess
    import sys

    prog = (
        "import json,sys\n"
        "from spark_rapids_tpu.serve.plan_cache import (\n"
        "    PlanCache, affinity_key, normalize_spec)\n"
        "path = sys.argv[1]\n"
        "spec = {'op': 'filter',\n"
        "        'input': {'op': 'parquet', 'path': path},\n"
        "        'cond': {'fn': '>=', 'args': [{'col': 'a'},\n"
        "                                      {'lit': 42}]}}\n"
        "norm, auto = normalize_spec(spec)\n"
        "print(json.dumps({\n"
        "    'affinity': affinity_key('acme', spec),\n"
        "    'structural': PlanCache().structural_key(\n"
        "        'acme', norm, auto,\n"
        "        {'spark.rapids.tpu.sql.enabled': True})}))\n")
    out = subprocess.run(
        [sys.executable, "-c", prog, table_path],
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu",
             "PYTHONHASHSEED": "0"},
        check=True)
    theirs = json.loads(out.stdout.strip().splitlines()[-1])
    spec = _lit_spec(table_path, 42)
    norm, auto = normalize_spec(spec)
    from spark_rapids_tpu.serve.plan_cache import (
        PlanCache,
        affinity_key,
    )

    assert theirs["affinity"] == affinity_key("acme", spec)
    assert theirs["structural"] == PlanCache().structural_key(
        "acme", norm, auto, {"spark.rapids.tpu.sql.enabled": True})


def test_lit_normalization_feeds_affinity_types(table_path):
    """__lit auto-params contribute their TYPE to the affinity key:
    an int-literal shape and a float-literal shape route apart, just
    as their plan-cache entries differ."""
    from spark_rapids_tpu.serve.plan_cache import affinity_key

    assert affinity_key("t", _lit_spec(table_path, 5)) != \
        affinity_key("t", _lit_spec(table_path, 5.0))


def test_concurrent_same_binding_does_not_share_physical(
        session, table_path):
    """While a binding is checked OUT, a second identical request
    re-plans from the template instead of sharing the physical tree
    mid-execution."""
    cache = PlanCache()
    _run(cache, session, "t", _spec(table_path), {"lo": 4})
    df1, i1, rel1 = cache.dataframe_for(session, "t",
                                        _spec(table_path), {"lo": 4})
    assert i1["planCache"] == "hit-exact"
    df2, i2, rel2 = cache.dataframe_for(session, "t",
                                        _spec(table_path), {"lo": 4})
    assert i2["planCache"] == "hit-rebind"
    t1 = df1.collect_arrow()
    t2 = df2.collect_arrow()
    rel1(True)
    rel2(True)
    assert t1.equals(t2)
