"""Planner-driven mesh execution tests: the SAME planner output that the
thread-pool engine runs executes as ONE shard_map'd SPMD program over the
virtual 8-device CPU mesh (conftest), with all_to_all collectives as the
shuffle transport. Every result diffs against the CPU oracle."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    with_cpu_session,
    with_tpu_session,
)

MESH = {"spark.rapids.tpu.mesh": 8,
        "spark.sql.shuffle.partitions": 4}


def _mesh_vs_oracle(df_fn, conf=None, ignore_order=True):
    mesh_conf = {**MESH, **(conf or {})}
    got = with_tpu_session(lambda s: df_fn(s).collect_arrow(), mesh_conf)
    want = with_cpu_session(lambda s: df_fn(s).collect_arrow(),
                            conf or {})
    assert_tables_equal(got, want, ignore_order=ignore_order)
    return got


def _tables(s, n=5000, seed=11):
    rng = np.random.default_rng(seed)
    fact = s.createDataFrame(pa.table({
        "store": pa.array(rng.integers(0, 40, n), type=pa.int64()),
        "amount": pa.array(rng.random(n) * 100, type=pa.float64()),
        "qty": pa.array(rng.integers(1, 50, n), type=pa.int64()),
    }))
    dim = s.createDataFrame(pa.table({
        "store": pa.array(np.arange(0, 60), type=pa.int64()),
        "region": pa.array(np.arange(0, 60) % 7, type=pa.int64()),
    }))
    return fact, dim


# ------------------------------------------------------------ aggregate

def test_mesh_groupby_agg():
    def q(s):
        fact, _ = _tables(s)
        return fact.groupBy("store").agg(
            F.sum("amount").alias("rev"),
            F.count("*").alias("n"),
            F.avg("qty").alias("aq"),
            F.min("amount").alias("mn"),
            F.max("amount").alias("mx"))

    _mesh_vs_oracle(q)


def test_mesh_global_agg():
    def q(s):
        fact, _ = _tables(s)
        return fact.agg(F.sum("qty").alias("t"),
                        F.count("*").alias("n"))

    _mesh_vs_oracle(q)


def test_mesh_filter_project_agg():
    def q(s):
        fact, _ = _tables(s)
        return (fact.filter(F.col("amount") > 25.0)
                .select("store",
                        (F.col("amount") * F.col("qty")).alias("rev"))
                .groupBy("store").agg(F.sum("rev").alias("total")))

    _mesh_vs_oracle(q)


# ----------------------------------------------------------------- join

def test_mesh_q5_join_agg():
    """The q5 slice WITH a join: scan -> filter -> shuffled hash join ->
    partial agg -> all_to_all exchange -> final agg, all in one SPMD
    program (the round-2 verdict's done-criterion shape)."""

    def q(s):
        fact, dim = _tables(s)
        return (fact.filter(F.col("amount") > 10.0)
                .join(dim, on="store", how="inner")
                .groupBy("region")
                .agg(F.sum("amount").alias("rev"),
                     F.count("*").alias("n")))

    _mesh_vs_oracle(q, conf={"spark.sql.autoBroadcastJoinThreshold": -1})


def test_mesh_broadcast_join():
    def q(s):
        fact, dim = _tables(s)
        return fact.join(dim, on="store", how="inner") \
            .select("store", "amount", "region")

    _mesh_vs_oracle(q)  # dim under default threshold -> broadcast


@pytest.mark.parametrize("how", ["inner", "left", "left_semi",
                                 "left_anti", "full"])
def test_mesh_join_types(how):
    def q(s):
        rng = np.random.default_rng(3)
        a = s.createDataFrame(pa.table({
            "k": pa.array(rng.integers(0, 30, 800), type=pa.int64()),
            "x": pa.array(rng.random(800), type=pa.float64())}))
        b = s.createDataFrame(pa.table({
            "k": pa.array(rng.integers(15, 45, 600), type=pa.int64()),
            "y": pa.array(rng.random(600), type=pa.float64())}))
        return a.join(b, on="k", how=how)

    _mesh_vs_oracle(q, conf={"spark.sql.autoBroadcastJoinThreshold": -1})


def test_mesh_conditional_join():
    def q(s):
        fact, dim = _tables(s, n=1200)
        return fact.join(
            dim,
            on=(fact["store"] == dim["store"]) & (F.col("amount") > 50.0),
            how="inner")

    _mesh_vs_oracle(q, conf={"spark.sql.autoBroadcastJoinThreshold": -1})


# ----------------------------------------------------------------- sort

def test_mesh_global_sort():
    """Distributed sort: sample-based range exchange + per-shard sort;
    shard order IS global order (exact order compared)."""

    def q(s):
        fact, _ = _tables(s, n=3000)
        return fact.orderBy("store", "amount")

    _mesh_vs_oracle(q, ignore_order=False)


def test_mesh_sort_desc():
    def q(s):
        fact, _ = _tables(s, n=2000)
        return fact.select("store", "qty").orderBy(
            F.col("qty").desc(), F.col("store"))

    _mesh_vs_oracle(q, ignore_order=False)


def test_mesh_sort_after_agg():
    """agg -> sort stage chain over the mesh."""

    def q(s):
        fact, _ = _tables(s)
        return (fact.groupBy("store")
                .agg(F.sum("amount").alias("rev"))
                .orderBy(F.col("rev").desc()))

    _mesh_vs_oracle(q, ignore_order=False)


# ------------------------------------------------------- limit / union

def test_mesh_orderby_limit():
    def q(s):
        fact, _ = _tables(s, n=2000)
        return fact.orderBy("amount").limit(25)

    _mesh_vs_oracle(q, ignore_order=False)


def test_mesh_union():
    def q(s):
        fact, _ = _tables(s, n=1000)
        a = fact.filter(F.col("store") < 10)
        b = fact.filter(F.col("store") >= 30)
        return a.union(b).groupBy("store").agg(
            F.count("*").alias("n"))

    _mesh_vs_oracle(q)


# -------------------------------------------------------- fallback path

def test_mesh_fallback_for_unsupported():
    """Operators without a mesh lowering (nested-loop/cross join) fall
    back to the thread-pool engine and still produce oracle results."""

    def q(s):
        a = s.createDataFrame(pa.table({"x": pa.array(range(40),
                                                      type=pa.int64())}))
        b = s.createDataFrame(pa.table({"y": pa.array(range(25),
                                                      type=pa.int64())}))
        return a.crossJoin(b).groupBy("x").agg(F.count("*").alias("n"))

    _mesh_vs_oracle(q)


def test_mesh_window():
    """Windows lower to a partition-key all_to_all + per-shard window
    program inside the SPMD plan."""
    from spark_rapids_tpu.api.window import Window

    def q(s):
        fact, _ = _tables(s, n=2000)
        w = Window.partitionBy("store").orderBy("amount")
        return fact.select("store", "amount",
                           F.row_number().over(w).alias("rn"))

    _mesh_vs_oracle(q)


def test_mesh_window_bounded_frame():
    from spark_rapids_tpu.api.window import Window

    def q(s):
        fact, _ = _tables(s, n=1500)
        w = (Window.partitionBy("store").orderBy("amount")
             .rowsBetween(-2, 2))
        return fact.select("store", "amount",
                           F.sum("qty").over(w).alias("s5"))

    _mesh_vs_oracle(q)


def test_mesh_explode():
    def q(s):
        rng = np.random.default_rng(9)
        t = s.createDataFrame(pa.table({
            "k": pa.array(rng.integers(0, 10, 600), type=pa.int64()),
            "arr": pa.array(
                [[int(v) for v in rng.integers(0, 50, rng.integers(0, 4))]
                 for _ in range(600)], type=pa.list_(pa.int64()))}))
        return (t.select("k", F.explode(F.col("arr")).alias("v"))
                .groupBy("v").agg(F.count("*").alias("n")))

    _mesh_vs_oracle(q)


def test_mesh_skew_overflow_retry():
    """Heavily skewed keys overflow the default collective slot; the
    executor recompiles with a doubled expansion factor and succeeds."""

    def q(s):
        n = 4000
        t = s.createDataFrame(pa.table({
            "k": pa.array(np.where(np.arange(n) % 10 == 0,
                                   np.arange(n) % 3, 7),
                          type=pa.int64()),
            "v": pa.array(np.random.default_rng(5).random(n),
                          type=pa.float64())}))
        return t.groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("*").alias("n"))

    _mesh_vs_oracle(q)


def test_multihost_helper_single_process():
    """Multi-host helper: device counts + global-mesh executor on one
    process (the virtual 8-device mesh)."""
    import pyarrow as pa

    from spark_rapids_tpu.parallel import multihost as mh

    assert mh.global_device_count() == 8
    assert mh.local_device_count() == 8
    assert mh.process_index() == 0
    from spark_rapids_tpu.api.session import TpuSparkSession

    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        df = (spark.createDataFrame(pa.table({
            "k": pa.array(list(range(100)) * 4),
            "v": pa.array([float(i) for i in range(400)])}))
            .groupBy("k").agg(F.sum("v").alias("s")))
        phys, _ = df._physical()
        out = mh.make_global_executor(spark.rapids_conf).execute(phys)
        assert out.num_rows == 100
    finally:
        spark.stop()


def test_ici_shuffle_mode_selects_mesh_engine(monkeypatch):
    """spark.rapids.shuffle.mode=ICI routes queries through the SPMD
    mesh compiler over every local device (the UCX-transport conf made
    real). The spy proves the mesh path actually executed — the silent
    thread-pool fallback would produce the same rows."""
    from spark_rapids_tpu.parallel.plan_compiler import MeshQueryExecutor

    calls = []
    orig = MeshQueryExecutor.execute

    def spy(self, phys):
        calls.append(self.n)
        return orig(self, phys)

    monkeypatch.setattr(MeshQueryExecutor, "execute", spy)

    def q(s):
        rng = np.random.default_rng(14)
        t = s.createDataFrame(pa.table({
            "k": pa.array(rng.integers(0, 16, 2000), type=pa.int64()),
            "v": pa.array(rng.random(2000), type=pa.float64())}))
        return t.groupBy("k").agg(F.sum("v").alias("sv"),
                                  F.count("*").alias("n"))

    got = with_tpu_session(
        lambda s: q(s).collect_arrow(),
        {"spark.rapids.shuffle.mode": "ICI"})
    assert calls == [8], calls  # ran on the full 8-device mesh
    want = with_cpu_session(lambda s: q(s).collect_arrow(), {})
    assert_tables_equal(got, want)


def test_partitioned_scan_ingestion(tmp_path, monkeypatch):
    """File scans ingest PER SHARD: each mesh shard decodes only its
    own files (MeshQueryExecutor._ingest_scan_sharded) — materializing
    the whole table on one host is forbidden for scan sources
    (round-3 verdict weak #3; reference MultiFileCloudPartitionReader,
    GpuParquetScan.scala:2051)."""
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from spark_rapids_tpu.parallel.plan_compiler import MeshQueryExecutor

    rng = np.random.default_rng(21)
    tabs = []
    for i in range(8):
        t = pa.table({
            "k": pa.array(rng.integers(0, 30, 1500), type=pa.int64()),
            "v": pa.array(rng.random(1500) * 10, type=pa.float64()),
            "s": pa.array([f"tag{j % 7}" for j in range(1500)]),
        })
        tabs.append(t)
        pq.write_table(t, str(tmp_path / f"p{i}.parquet"))
    allt = pa.concat_tables(tabs)

    monkeypatch.setattr(
        MeshQueryExecutor, "_materialize",
        lambda self, s: (_ for _ in ()).throw(
            AssertionError("whole-table materialize for a scan")))

    def q(s):
        return (s.read.parquet(str(tmp_path))
                .filter(F.col("v") > 1.0)
                .groupBy("k").agg(F.sum("v").alias("sv"),
                                  F.count("*").alias("n")))

    got = with_tpu_session(
        lambda s: q(s).collect_arrow(),
        {**MESH,
         "spark.rapids.sql.format.parquet.reader.type": "PERFILE"})
    f = allt.filter(pc.greater(allt.column("v"), 1.0))
    w = f.group_by("k").aggregate([("v", "sum"), ("k", "count")])
    exp = {r["k"]: (r["v_sum"], r["k_count"]) for r in w.to_pylist()}
    gotm = {r["k"]: (r["sv"], r["n"]) for r in got.to_pylist()}
    assert set(gotm) == set(exp)
    for k in exp:
        assert gotm[k][1] == exp[k][1], k
        assert abs(gotm[k][0] - exp[k][0]) < 1e-6 * max(
            1.0, abs(exp[k][0])), k


# ------------------------------------------- collect family (static width)

def test_mesh_collect_list_and_set():
    """collect_list/collect_set/countDistinct lower into the SPMD
    program with a STATIC element width under the expansion-retry
    discipline (round-4 verdict weak #6: the mesh engine must not
    support fewer aggregates than single-chip)."""
    rng = np.random.default_rng(21)
    n = 800
    ks = rng.integers(0, 8, n)
    vs = rng.integers(0, 40, n)

    def q(s):
        t = pa.table({"k": pa.array(ks, type=pa.int64()),
                      "v": pa.array(vs, type=pa.int64())})
        return (s.createDataFrame(t).groupBy("k")
                .agg(F.collect_set("v").alias("cs"),
                     F.countDistinct("v").alias("cd"),
                     F.collect_list("v").alias("cl")))

    got = with_tpu_session(lambda s: q(s).collect_arrow(), MESH)
    assert len(got) == 8
    for r in got.to_pylist():
        mine = vs[ks == r["k"]]
        assert sorted(r["cl"]) == sorted(mine.tolist()), r["k"]
        assert sorted(r["cs"]) == sorted(set(mine.tolist())), r["k"]
        assert r["cd"] == len(set(mine.tolist()))


def test_mesh_collect_overflow_retry():
    """A group wider than the initial static width must overflow and
    recompile bigger, not silently truncate."""
    n = 600  # one group of 600 elements >> initial width 16*expansion
    ks = np.zeros(n, dtype=np.int64)
    vs = np.arange(n, dtype=np.int64)

    def q(s):
        t = pa.table({"k": pa.array(ks), "v": pa.array(vs)})
        return (s.createDataFrame(t).groupBy("k")
                .agg(F.collect_list("v").alias("cl")))

    got = with_tpu_session(lambda s: q(s).collect_arrow(), MESH)
    assert len(got) == 1
    assert sorted(got.column("cl")[0].as_py()) == list(range(n))
