"""Cross-process distributed-execution worker (launched by
test_multiprocess.py, one OS process per "host").

Each worker is the analog of one reference executor process
(RapidsShuffleClient.scala:95 / RapidsShuffleServer.scala:71 peers):
it joins the jax.distributed coordination service, owns a slice of the
global device mesh, decodes ONLY its own shard of the scan's file list,
and participates in the plan's all_to_all / all_gather collectives —
which XLA routes over the cross-process fabric (gloo on CPU here,
ICI/DCN on a real pod). collect() returns the full result on every
process via a process allgather (mesh_exec.fetch_host).

Protocol: argv = [data_dir, out_dir]; env SRTPU_MP_{COORD,NPROC,PID}.
Writes <out_dir>/result_<pid>.parquet plus <out_dir>/ok_<pid> on
success (contents = ingest-stats JSON), or <out_dir>/err_<pid> with
the traceback on failure.
"""

import json
import os
import sys
import traceback


def main() -> None:
    import jax

    # must run before any backend touch: the axon sitecustomize forces
    # jax_platforms=axon,cpu in every interpreter
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    coord = os.environ["SRTPU_MP_COORD"]
    nproc = int(os.environ["SRTPU_MP_NPROC"])
    pid = int(os.environ["SRTPU_MP_PID"])
    data_dir, out_dir = sys.argv[1], sys.argv[2]

    import pyarrow.parquet as pq

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.parallel import multihost, plan_compiler

    # the session joins the cluster itself (multihost.* confs)
    spark = TpuSparkSession({
        "spark.rapids.tpu.multihost.coordinator": coord,
        "spark.rapids.tpu.multihost.numProcesses": nproc,
        "spark.rapids.tpu.multihost.processId": pid,
        "spark.sql.shuffle.partitions": 4,
        "spark.sql.autoBroadcastJoinThreshold": -1,
    })
    assert jax.process_count() == nproc, jax.process_count()
    spark.conf.set("spark.rapids.tpu.mesh",
                   multihost.global_device_count())
    try:
        fact = spark.read.parquet(data_dir)
        dim = spark.createDataFrame(_dim_table())
        df = (fact.filter(F.col("v") > 0.2)
                  .join(dim, on="k", how="inner")
                  .groupBy("g")
                  .agg(F.sum("v").alias("s"), F.count("*").alias("c")))
        got = df.collect_arrow()

        stats = dict(plan_compiler.last_ingest_stats)
        if not stats:
            raise AssertionError(
                "mesh ingestion never ran (thread-pool fallback?)")
        if stats["files"] >= stats["total_files"]:
            raise AssertionError(
                f"process {pid} decoded ALL {stats['total_files']} files"
                " — ingestion is not process-local: " + json.dumps(stats))

        pq.write_table(got, os.path.join(out_dir, f"result_{pid}.parquet"))

        # second scenario, same cluster: HEAVILY SKEWED join keys (90%
        # of rows share one key) — the all_to_all slot-capacity
        # overflow + whole-program recompile discipline must converge
        # cross-process (every process must take the same retry path
        # or the collectives deadlock)
        skew = spark.createDataFrame(_skew_table())
        dim2 = spark.createDataFrame(_dim_table())
        df2 = (skew.join(dim2, on="k", how="inner")
                   .groupBy("g")
                   .agg(F.sum("v").alias("s"), F.count("*").alias("c")))
        got2 = df2.collect_arrow()
        pq.write_table(got2,
                       os.path.join(out_dir, f"result2_{pid}.parquet"))
        with open(os.path.join(out_dir, f"ok_{pid}"), "w") as f:
            json.dump(stats, f)
    finally:
        spark.stop()


def _skew_table():
    """Deterministic (identical on every process — SPMD inputs must
    agree) skewed fact: 90% of rows carry key 7."""
    import numpy as np
    import pyarrow as pa

    rng = np.random.default_rng(3)
    n = 4000
    keys = np.where(rng.random(n) < 0.9, 7,
                    rng.integers(0, 50, n)).astype(np.int64)
    return pa.table({"k": pa.array(keys),
                     "v": pa.array(rng.random(n))})


def _dim_table():
    import numpy as np
    import pyarrow as pa

    ks = np.arange(0, 50, dtype=np.int64)
    return pa.table({"k": pa.array(ks),
                     "g": pa.array(ks % 5, type=pa.int64())})


if __name__ == "__main__":
    try:
        main()
    except Exception:
        out_dir = sys.argv[2] if len(sys.argv) > 2 else "."
        pid = os.environ.get("SRTPU_MP_PID", "x")
        with open(os.path.join(out_dir, f"err_{pid}"), "w") as f:
            f.write(traceback.format_exc())
        raise
