"""Cross-process distributed execution — the reference's multi-executor
story (RapidsShuffleClient/Server peers + heartbeat topology,
SURVEY.md §2.7/§5.8) realized TPU-natively: N OS processes join one
jax.distributed coordination service, the mesh spans every process's
devices, and ONE compiled SPMD program executes the plan with
cross-process collectives as the shuffle transport.

This launches two real worker processes (tests/mp_worker.py), each
owning 4 virtual CPU devices of an 8-device global mesh, and asserts:
- the planned query (scan → filter → shuffled join → group-by) returns
  oracle-identical results on BOTH processes,
- each process decoded only its own half of the scan's files (no
  whole-table host batch on any single host — the per-executor scan
  split, GpuParquetScan.scala:2051 role).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

N_FILES = 8
N_PROC = 2


def _write_data(data_dir: str) -> pa.Table:
    rng = np.random.default_rng(7)
    parts = []
    os.makedirs(data_dir, exist_ok=True)
    for i in range(N_FILES):
        t = pa.table({
            "k": pa.array(rng.integers(0, 50, 600), type=pa.int64()),
            "v": pa.array(rng.random(600), type=pa.float64()),
        })
        pq.write_table(t, os.path.join(data_dir, f"part-{i}.parquet"))
        parts.append(t)
    return pa.concat_tables(parts)


def _oracle(full: pa.Table) -> pa.Table:
    filt = full.filter(pc.greater(full.column("v"), 0.2))
    filt = filt.append_column(
        "g", pa.array(np.asarray(filt.column("k")) % 5, type=pa.int64()))
    agg = filt.group_by("g").aggregate([("v", "sum"), ("v", "count")])
    cols = {n: agg.column(n) for n in agg.column_names}
    return pa.table({"g": cols["g"], "s": cols["v_sum"],
                     "c": pc.cast(cols["v_count"], pa.int64())}
                    ).sort_by([("g", "ascending")])


def test_two_process_distributed_query(tmp_path):
    data_dir = str(tmp_path / "data")
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir)
    full = _write_data(data_dir)

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["SRTPU_MP_COORD"] = "localhost:29677"
    env["SRTPU_MP_NPROC"] = str(N_PROC)
    env.pop("JAX_PLATFORMS", None)  # worker forces cpu itself
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    worker = os.path.join(repo, "tests", "mp_worker.py")
    procs = []
    for pid in range(N_PROC):
        e = dict(env)
        e["SRTPU_MP_PID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, worker, data_dir, out_dir],
            env=e, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=360)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("worker timed out (coordination or collective "
                        "deadlock)")
        outs.append(out.decode(errors="replace"))

    for pid, p in enumerate(procs):
        err_file = os.path.join(out_dir, f"err_{pid}")
        if p.returncode != 0 or os.path.exists(err_file):
            err = (open(err_file).read()
                   if os.path.exists(err_file) else outs[pid][-4000:])
            pytest.fail(f"worker {pid} failed (rc={p.returncode}):\n{err}")

    want = _oracle(full)
    stats = []
    for pid in range(N_PROC):
        got = pq.read_table(
            os.path.join(out_dir, f"result_{pid}.parquet")
        ).select(["g", "s", "c"]).sort_by([("g", "ascending")])
        assert got.column("g").to_pylist() == want.column("g").to_pylist()
        assert got.column("c").to_pylist() == want.column("c").to_pylist()
        np.testing.assert_allclose(
            np.asarray(got.column("s")), np.asarray(want.column("s")),
            rtol=1e-9, err_msg=f"worker {pid} sums diverged")
        stats.append(json.load(open(os.path.join(out_dir, f"ok_{pid}"))))

    # every process decoded exactly its own half of the file list
    assert [s["files"] for s in stats] == [N_FILES // N_PROC] * N_PROC, stats
    assert [s["local_shards"] for s in stats] == [4, 4], stats
    assert sorted(s["process"] for s in stats) == [0, 1], stats

    # scenario 2: the skewed join (90% hot key) must agree with the
    # oracle on BOTH processes — the all_to_all slot overflow/retry
    # path converged cross-process. One source of truth for the data:
    # the worker's own generator.
    from tests.mp_worker import _skew_table

    skew = _skew_table()
    keys = np.asarray(skew.column("k"))
    vals = np.asarray(skew.column("v"))
    g = keys % 5
    want2 = {}
    for gg, vv in zip(g.tolist(), vals.tolist()):
        sacc, cacc = want2.get(gg, (0.0, 0))
        want2[gg] = (sacc + vv, cacc + 1)
    for pid in range(N_PROC):
        got2 = pq.read_table(
            os.path.join(out_dir, f"result2_{pid}.parquet"))
        gm = {gg: (ss, cc) for gg, ss, cc in zip(
            got2.column("g").to_pylist(), got2.column("s").to_pylist(),
            got2.column("c").to_pylist())}
        assert set(gm) == set(want2), (pid, gm.keys())
        for gg, (ss, cc) in want2.items():
            assert gm[gg][1] == cc, (pid, gg, gm[gg], cc)
            np.testing.assert_allclose(gm[gg][0], ss, rtol=1e-9)
