"""Rollup/cube/grouping-sets (Expand lowering; reference
GpuExpandExec.scala + GpuOverrides expand rules) and Bernoulli sampling
(reference GpuSampleExec, basicPhysicalOperators.scala)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)


@pytest.fixture(scope="module")
def cube_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("cubedata")
    rng = np.random.default_rng(11)
    n = 3000
    t = pa.table({
        "a": pa.array(rng.integers(0, 4, n),
                      mask=rng.random(n) < 0.05),
        "b": pa.array([["x", "y", "z"][i]
                       for i in rng.integers(0, 3, n)]),
        "v": pa.array(rng.random(n) * 10,
                      mask=rng.random(n) < 0.1),
    })
    p = str(d / "cube.parquet")
    pq.write_table(t, p)
    return p


def test_rollup_diff(cube_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(cube_path).rollup("a", "b")
        .agg(F.sum("v").alias("s"), F.count("*").alias("c"),
             F.grouping_id().alias("gid")))


def test_cube_diff(cube_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(cube_path).cube("a", "b")
        .agg(F.avg("v").alias("m"), F.grouping("a").alias("ga"),
             F.grouping("b").alias("gb")))


def test_grouping_sets_diff(cube_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(cube_path)
        .groupingSets([["a"], ["b"], []], "a", "b")
        .agg(F.count("*").alias("c"), F.max("v").alias("mx")))


def test_rollup_row_count_and_total(cube_path):
    def q(spark):
        return (spark.read.parquet(cube_path).rollup("a", "b")
                .agg(F.count("*").alias("c"),
                     F.grouping_id().alias("gid"))
                .collect_arrow().to_pandas())

    df = with_tpu_session(q)
    n_total = pq.read_table(cube_path).num_rows
    grand = df[df.gid == 3]
    assert len(grand) == 1
    assert int(grand.c.iloc[0]) == n_total
    # per-a subtotals sum back to the grand total
    assert int(df[df.gid == 1].c.sum()) == n_total


def test_grouping_id_requires_multi_set(cube_path):
    with pytest.raises(ValueError, match="rollup/cube"):
        with_tpu_session(
            lambda spark: spark.read.parquet(cube_path).groupBy("a")
            .agg(F.grouping_id().alias("g")).collect_arrow())


def test_sample_deterministic_and_fraction(cube_path):
    def q(spark):
        return spark.read.parquet(cube_path).sample(0.4, 7) \
            .collect_arrow()

    a = with_tpu_session(q)
    b = with_tpu_session(q)
    assert a.equals(b)
    n = pq.read_table(cube_path).num_rows
    assert 0.3 * n < a.num_rows < 0.5 * n


def test_sample_diff(cube_path):
    # identical hash stream on device and CPU oracle -> identical rows
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(cube_path).sample(0.25, 123))


def test_sample_with_replacement_cpu_fallback(cube_path):
    """With-replacement sampling must be PLANNED on CPU (fallback
    placement assertion), and produce ~fraction x rows."""
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_fallback_collect,
    )

    def q(spark):
        return spark.read.parquet(cube_path).sample(True, 1.5, 3)

    out = assert_tpu_fallback_collect(q, "CpuSampleExec")
    n = pq.read_table(cube_path).num_rows
    # poisson(1.5) mean: expect ~1.5x rows
    assert n < out.num_rows < 2.2 * n


def test_unaliased_grouping_id(cube_path):
    def q(spark):
        return (spark.read.parquet(cube_path).rollup("a")
                .agg(F.sum("v"), F.grouping_id(), F.grouping("a"))
                .collect_arrow())

    out = with_tpu_session(q)
    assert "spark_grouping_id()" in out.column_names


def test_duplicate_grouping_sets(cube_path):
    """GROUPING SETS ((b),(b)) emits each group twice (Spark
    disambiguates duplicate sets by position)."""
    def q(spark):
        return (spark.read.parquet(cube_path)
                .groupingSets([["b"], ["b"]], "b")
                .agg(F.sum("v").alias("s"), F.count("*").alias("c"))
                .collect_arrow().to_pandas()
                .sort_values(["b", "s"]).reset_index(drop=True))

    dup = with_tpu_session(q)

    def single(spark):
        return (spark.read.parquet(cube_path).groupBy("b")
                .agg(F.sum("v").alias("s"), F.count("*").alias("c"))
                .collect_arrow().to_pandas()
                .sort_values("b").reset_index(drop=True))

    base = with_tpu_session(single)
    assert len(dup) == 2 * len(base)
    # values are NOT doubled — each copy equals the plain groupBy row
    merged = dup.drop_duplicates().reset_index(drop=True)
    assert np.allclose(merged.s.to_numpy(), base.s.to_numpy())
    assert (merged.c.to_numpy() == base.c.to_numpy()).all()


def test_sample_with_replacement_multibatch_varies(cube_path):
    """Poisson draws must differ across batches (per-partition RNG
    stream, not per-batch)."""
    def q(spark):
        return (spark.read.parquet(cube_path)
                .sample(True, 1.0, 5).collect_arrow())

    out = with_tpu_session(
        q, conf={"spark.rapids.sql.reader.batchSizeRows": 512})
    n = pq.read_table(cube_path).num_rows
    assert 0.7 * n < out.num_rows < 1.4 * n


def test_sample_then_agg(cube_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(cube_path).sample(0.5, 99)
        .groupBy("b").agg(F.sum("v").alias("s")))
