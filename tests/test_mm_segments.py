"""MXU (one-hot matmul) segmented reductions vs the scatter path.

The binned group-by lowers its reductions to two-level one-hot matmuls
on TPU backends (ops/segmented.py `_mm_pass`); these tests force that
path on the CPU test backend and check it against the scatter
implementation and the pyarrow oracle: counts and bounded-int sums must
be bit-exact, float sums within f32-chunk accumulation tolerance.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnBatch, make_column
from spark_rapids_tpu.ops import segmented
from spark_rapids_tpu.sqltypes import StructField, StructType
from spark_rapids_tpu.sqltypes.datatypes import double, long


def _mk_batch(n, cap, nstores, seed=0, with_nulls=True):
    rng = np.random.default_rng(seed)
    store = rng.integers(0, nstores, n)
    qty = rng.integers(-50, 100, n)
    amt = rng.random(n) * 1e4 - 100.0
    sv = rng.random(n) > 0.1 if with_nulls else np.ones(n, bool)
    av = rng.random(n) > 0.1 if with_nulls else np.ones(n, bool)
    schema = StructType([
        StructField("store", long, True),
        StructField("qty", long, True),
        StructField("amt", double, True),
    ])
    cols = [
        make_column(long, store, sv, cap),
        make_column(long, qty, av, cap),
        make_column(double, amt, av, cap),
    ]
    cols[0].vrange = (0, nstores - 1)
    cols[1].vrange = (-50, 99)
    batch = ColumnBatch(schema, cols, n)
    return batch, store, qty, amt, sv, av


def _agg(mode="partial"):
    from spark_rapids_tpu.exec.operators import TpuHashAggregateExec
    from spark_rapids_tpu.expr import (
        Alias, Average, BoundReference, Count, Sum,
    )

    g = [Alias(BoundReference(0, long, True), "store")]
    aggs = [
        Alias(Sum(BoundReference(1, long, True)), "sq"),
        Alias(Sum(BoundReference(2, double, True)), "sa"),
        Alias(Count(BoundReference(2, double, True)), "ca"),
        Alias(Average(BoundReference(2, double, True)), "avg"),
    ]
    return TpuHashAggregateExec(mode, g, aggs, None, None)


def _collect(agg, part):
    from spark_rapids_tpu.exec.operators import TpuHashAggregateExec

    final = TpuHashAggregateExec("final", agg.grouping, agg.aggs,
                                 None, None)
    out = final._merge_final(part)
    n = int(jnp.asarray(out.num_rows))
    res = {}
    for i in range(n):
        key = (int(out.columns[0].data[i])
               if bool(out.columns[0].validity[i]) else None)
        res[key] = tuple(
            (float(c.data[i]) if bool(c.validity[i]) else None)
            for c in out.columns[1:])
    return res


@pytest.mark.parametrize("nstores", [7, 213, 2050])
def test_mm_matches_scatter(nstores):
    batch, store, qty, amt, sv, av = _mk_batch(5000, 8192, nstores)
    agg = _agg()
    base = _collect(agg, agg._partial(batch))
    before = segmented.mm_traced_sweeps
    with segmented.force_matmul_path():
        mm = _collect(agg, agg._partial(batch))
    # the matmul path must actually have engaged (not scatter-vs-scatter)
    assert segmented.mm_traced_sweeps > before
    assert set(base) == set(mm)
    for k in base:
        for i, (b, m) in enumerate(zip(base[k], mm[k])):
            if b is None or m is None:
                assert b == m, (k, i)
            elif i == 0:  # bounded int sum: exact
                assert b == m, (k, i, b, m)
            else:
                assert m == pytest.approx(b, rel=2e-5, abs=1e-3), (k, i)


def test_mm_exact_vs_numpy_oracle():
    n = 20000
    batch, store, qty, amt, sv, av = _mk_batch(n, 32768, 97, seed=3)
    agg = _agg()
    with segmented.force_matmul_path():
        got = _collect(agg, agg._partial(batch))
    for s in np.unique(store[sv]):
        m = (store == s) & sv
        want_sq = int(qty[m & av].sum()) if (m & av).any() else None
        want_ca = int((m & av).sum())
        row = got[int(s)]
        assert row[0] == want_sq
        assert row[2] == want_ca
        if want_ca:
            assert row[1] == pytest.approx(float(amt[m & av].sum()),
                                           rel=2e-5, abs=1e-3)


def test_mm_null_key_bin_and_empty_bins():
    n, cap = 1000, 1024
    rng = np.random.default_rng(5)
    store = rng.integers(0, 4, n)
    kv = rng.random(n) > 0.5  # half the keys null
    vals = rng.integers(0, 10, n)
    schema = StructType([StructField("k", long, True),
                         StructField("v", long, True)])
    cols = [make_column(long, store, kv, cap),
            make_column(long, vals, None, cap)]
    cols[0].vrange = (0, 40)  # loose bound: most bins empty
    cols[1].vrange = (0, 9)
    batch = ColumnBatch(schema, cols, n)
    from spark_rapids_tpu.expr import Alias, BoundReference, Count, Sum

    from spark_rapids_tpu.exec.operators import TpuHashAggregateExec

    g = [Alias(BoundReference(0, long, True), "k")]
    aggs = [Alias(Sum(BoundReference(1, long, True)), "sv"),
            Alias(Count(None), "c")]
    agg = TpuHashAggregateExec("partial", g, aggs, None, None)
    with segmented.force_matmul_path():
        got = _collect(agg, agg._partial(batch))
    assert None in got  # the null-key group exists
    assert got[None][0] == int(vals[~kv].sum())
    assert got[None][1] == int((~kv).sum())
    for s in range(4):
        m = (store == s) & kv
        assert got[int(s)][0] == int(vals[m].sum())
        assert got[int(s)][1] == int(m.sum())
    assert len(got) == 5  # empty bins compacted away


def test_mm_pass_kernel_direct():
    rng = np.random.default_rng(9)
    for b in (3, 64, 1000, 4096):
        n = 4096
        gid = jnp.asarray(rng.integers(0, b, n).astype(np.int32))
        w = jnp.asarray(rng.random(n).astype(np.float32))
        got = np.asarray(segmented._mm_pass(w, gid, b, 512, jnp.float64))
        want = np.zeros(b)
        np.add.at(want, np.asarray(gid), np.asarray(w, dtype=np.float64))
        assert np.allclose(got, want, rtol=1e-5, atol=1e-5)


def test_mm_nonfinite_confined_to_own_group():
    # an Inf/NaN row must not poison other groups' sums (the masked
    # outer product would turn inf*0 into NaN without the chunk guard)
    n, cap, b = 512, 1024, 8
    rng = np.random.default_rng(13)
    gid_np = rng.integers(0, b, n).astype(np.int32)
    vals_np = rng.random(n)
    vals_np[7] = np.inf
    gid_np[7] = 3
    vals_np[11] = np.nan
    gid_np[11] = 5
    gid = jnp.asarray(gid_np)
    vals = jnp.asarray(vals_np)
    valid = jnp.ones(n, bool)
    with segmented.force_matmul_path(), segmented.binned_bins(b), \
            segmented.unsorted_gids():
        got = np.asarray(segmented.seg_sum(vals, valid, gid, b))
    for s in range(b):
        m = gid_np == s
        want = vals_np[m].sum()
        if s == 3:
            assert np.isinf(got[s]) and got[s] > 0
        elif s == 5:
            assert np.isnan(got[s])
        else:
            assert np.isfinite(got[s])
            assert got[s] == pytest.approx(want, rel=2e-5)


def test_mm_unbounded_int64_falls_back():
    # no vrange + wide values: seg_sum must not take the matmul path
    # (exactness cannot be arranged) — verified by exact wraparound-free
    # result on values > 2^24
    n, cap, b = 256, 1024, 16
    rng = np.random.default_rng(11)
    gid = jnp.asarray(rng.integers(0, b, n).astype(np.int32))
    vals = jnp.asarray(rng.integers(-2**40, 2**40, n))
    valid = jnp.ones(n, bool)
    with segmented.force_matmul_path(), segmented.binned_bins(b), \
            segmented.unsorted_gids():
        got = np.asarray(segmented.seg_sum(vals, valid, gid, b))
    want = np.zeros(b, dtype=np.int64)
    np.add.at(want, np.asarray(gid), np.asarray(vals))
    assert np.array_equal(got, want)
