"""Memory/spill/retry suites — the reference's RmmSparkRetrySuiteBase
family analog (WithRetrySuite, RapidsBufferCatalogSuite, ...): force tiny
pools and injected OOMs to exercise spill tiers and retry/split paths.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import arrow_to_device, device_to_arrow
from spark_rapids_tpu.runtime.errors import (
    TpuOOMError, TpuRetryOOM, TpuSplitAndRetryOOM,
)
from spark_rapids_tpu.runtime.memory import SpillCatalog, SpillTier
from spark_rapids_tpu.runtime.retry import (
    split_spillable_in_half_by_rows,
    with_retry,
    with_retry_no_split,
)
from spark_rapids_tpu.runtime.semaphore import TpuSemaphore


def _batch(n=1000, base=0):
    t = pa.table({"a": pa.array(range(base, base + n), pa.int64()),
                  "b": pa.array([float(i) for i in range(n)], pa.float64())})
    return arrow_to_device(t)


def _mk_catalog(device_limit, host_limit=1 << 30, tmpdir=None, **kw):
    return SpillCatalog(device_limit, host_limit, spill_dir=tmpdir, **kw)


def test_spill_to_host_on_pressure(tmp_path):
    cat = _mk_catalog(device_limit=80_000, tmpdir=str(tmp_path))
    b1 = cat.add_batch(_batch())          # 1024*(8+1+8+1) = 18KB each
    b2 = cat.add_batch(_batch())
    b3 = cat.add_batch(_batch())
    b4 = cat.add_batch(_batch())
    used = cat.device_reserved()
    # next add must evict someone
    b5 = cat.add_batch(_batch())
    tiers = [b.tier for b in (b1, b2, b3, b4, b5)]
    assert SpillTier.HOST in tiers
    assert cat.metrics["spill_to_host"] >= 1
    # unspill works and returns identical data
    got = device_to_arrow(b1.get_batch())
    assert got.column("a").to_pylist()[:3] == [0, 1, 2]
    assert b1.tier == SpillTier.DEVICE
    for b in (b1, b2, b3, b4, b5):
        b.close()
    assert cat.device_reserved() == 0


def test_spill_overflows_to_disk(tmp_path):
    cat = _mk_catalog(device_limit=50_000, host_limit=30_000,
                      tmpdir=str(tmp_path))
    bufs = [cat.add_batch(_batch(base=i * 1000)) for i in range(5)]
    assert cat.metrics["spill_to_disk"] >= 1
    assert any(b.tier == SpillTier.DISK for b in bufs)
    # disk -> device round trip preserves data
    disk_b = next(b for b in bufs if b.tier == SpillTier.DISK)
    idx = bufs.index(disk_b)
    got = device_to_arrow(disk_b.get_batch())
    assert got.column("a").to_pylist()[0] == idx * 1000
    for b in bufs:
        b.close()


def test_split_and_retry_oom_when_nothing_to_spill(tmp_path):
    cat = _mk_catalog(device_limit=10_000, tmpdir=str(tmp_path))
    with pytest.raises(TpuSplitAndRetryOOM):
        cat.add_batch(_batch())  # single batch larger than whole pool


def test_retry_oom_injection_once(tmp_path):
    cat = _mk_catalog(1 << 30, tmpdir=str(tmp_path),
                      oom_injection_mode="once")
    with pytest.raises(TpuRetryOOM):
        cat.add_batch(_batch())
    # second attempt succeeds (injection disarmed)
    b = cat.add_batch(_batch())
    assert cat.metrics["retry_oom_injected"] == 1
    b.close()


def test_with_retry_retries_after_injected_oom(tmp_path):
    cat = _mk_catalog(1 << 30, tmpdir=str(tmp_path))
    sb = cat.add_batch(_batch())
    attempts = {"n": 0}

    def fn(s):
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise TpuRetryOOM("fake transient")
        return s.row_count()

    import spark_rapids_tpu.runtime.memory as mem
    old = mem._catalog
    mem._catalog = cat
    try:
        out = with_retry_no_split(sb, fn)
    finally:
        mem._catalog = old
    assert out == 1000 and attempts["n"] == 2


def test_with_retry_splits_input(tmp_path):
    cat = _mk_catalog(1 << 30, tmpdir=str(tmp_path))
    import spark_rapids_tpu.runtime.memory as mem
    old = mem._catalog
    mem._catalog = cat
    try:
        sb = cat.add_batch(_batch(1000))
        seen = []

        def fn(s):
            if s.row_count() > 300:
                raise TpuSplitAndRetryOOM("too big")
            seen.append(s.row_count())
            return s.row_count()

        results = list(with_retry(sb, fn))
    finally:
        mem._catalog = old
    assert sum(results) == 1000
    assert all(r <= 300 for r in results)
    # order preserved: pieces re-concatenate to original order
    assert cat.buffer_count() == 0  # all closed by the framework


def test_with_retry_split_preserves_order_and_data(tmp_path):
    cat = _mk_catalog(1 << 30, tmpdir=str(tmp_path))
    import spark_rapids_tpu.runtime.memory as mem
    old = mem._catalog
    mem._catalog = cat
    try:
        sb = cat.add_batch(_batch(500))
        calls = {"n": 0}

        def fn(s):
            calls["n"] += 1
            if calls["n"] == 1:
                raise TpuSplitAndRetryOOM("first call too big")
            return device_to_arrow(s.get_batch()).column("a").to_pylist()

        chunks = list(with_retry(sb, fn))
    finally:
        mem._catalog = old
    flat = [x for c in chunks for x in c]
    assert flat == list(range(500))


def test_split_limit_exceeded(tmp_path):
    cat = _mk_catalog(1 << 30, tmpdir=str(tmp_path))
    import spark_rapids_tpu.runtime.memory as mem
    old = mem._catalog
    mem._catalog = cat
    try:
        sb = cat.add_batch(_batch(64))

        def fn(s):
            raise TpuSplitAndRetryOOM("always")

        with pytest.raises(TpuOOMError):
            list(with_retry(sb, fn, split_limit=3))
    finally:
        mem._catalog = old


def test_semaphore_limits_concurrency():
    sem = TpuSemaphore(concurrent_tasks=2)
    sem.acquire_if_necessary(1)
    sem.acquire_if_necessary(2)
    assert sem.holders() == 2
    import threading

    acquired = threading.Event()

    def third():
        sem.acquire_if_necessary(3)
        acquired.set()

    t = threading.Thread(target=third, daemon=True)
    t.start()
    assert not acquired.wait(0.2)  # blocked
    sem.release_if_necessary(1)
    assert acquired.wait(2.0)
    sem.release_if_necessary(2)
    sem.release_if_necessary(3)
    assert sem.holders() == 0


def test_semaphore_reentrant():
    sem = TpuSemaphore(concurrent_tasks=1)
    sem.acquire_if_necessary(7)
    sem.acquire_if_necessary(7)  # no deadlock
    assert sem.holders() == 1
    sem.release_if_necessary(7)


# ------------------- Retryable checkpoint/restore (withRestoreOnRetry)

def test_with_restore_on_retry_restores_on_oom():
    """State mutated by a failed attempt is rolled back before the OOM
    propagates to the enclosing retry loop (reference Retryable.java +
    RmmRapidsRetryIterator.scala:234-261), so the re-attempt runs
    against clean state."""
    from spark_rapids_tpu.runtime.errors import TpuRetryOOM
    from spark_rapids_tpu.runtime.retry import (
        CheckpointedValue,
        retry_on_oom,
        with_restore_on_retry,
    )

    state = CheckpointedValue(0)
    attempts = {"n": 0}

    def body():
        state.value += 10  # mutation an aborted attempt must not keep
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise TpuRetryOOM("injected")
        return state.value

    out = retry_on_oom(lambda: with_restore_on_retry(state, body))
    assert out == 10  # not 20: the first attempt's mutation rolled back
    assert attempts["n"] == 2


def test_pending_batches_restore_closes_orphans():
    """PendingBatches.restore closes spillables appended after the
    checkpoint — an aborted attempt leaks nothing from the catalog."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.runtime.memory import get_catalog
    from spark_rapids_tpu.runtime.retry import PendingBatches

    catalog = get_catalog()
    base = catalog.live_handles() if hasattr(catalog, "live_handles") \
        else None
    t = pa.table({"x": pa.array(np.arange(8), type=pa.int64())})

    p = PendingBatches()
    p.append(catalog.add_batch(arrow_to_device(t)), 8)
    p.checkpoint()
    p.append(catalog.add_batch(arrow_to_device(t)), 8)
    p.append(catalog.add_batch(arrow_to_device(t)), 8)
    assert len(p.items) == 3 and p.rows == 24
    p.restore()
    assert len(p.items) == 1 and p.rows == 8
    p.close()
    if base is not None:
        assert catalog.live_handles() == base


def test_restore_on_retry_split_storm_no_double_count(tmp_path):
    """Satellite (PR 2): with_restore_on_retry + PendingBatches under
    an injected split-and-retry STORM — every input batch suffers
    retry OOMs after partial appends AND split OOMs that halve it, and
    the checkpointed accumulator must come out with EXACTLY the input
    row count (no double counting from re-run attempts) and the spill
    catalog must be empty afterwards (no leaked entries from aborted
    attempts)."""
    from spark_rapids_tpu.runtime.retry import (
        PendingBatches,
        with_restore_on_retry,
    )

    cat = _mk_catalog(1 << 30, tmpdir=str(tmp_path))
    import spark_rapids_tpu.runtime.memory as mem
    old = mem._catalog
    mem._catalog = cat
    try:
        total_rows = 1000
        inputs = [cat.add_batch(_batch(total_rows))]
        pending = PendingBatches()
        storm = {"retries_left": 5}

        def body(sb):
            n = sb.row_count()
            # partial append FIRST — the state a failed attempt must
            # not keep
            pending.append(cat.add_batch(sb.get_batch()), n)
            if n > 300:
                raise TpuSplitAndRetryOOM("storm: too big")
            if storm["retries_left"] > 0:
                storm["retries_left"] -= 1
                raise TpuRetryOOM("storm: transient")
            return n

        done = list(with_retry(
            inputs, lambda sb: with_restore_on_retry(pending,
                                                     lambda: body(sb))))
        assert storm["retries_left"] == 0  # the storm actually fired
        assert sum(done) == total_rows
        assert pending.rows == total_rows  # no double-counted appends
        assert sum(sb.row_count() for sb in pending.items) == total_rows
        # nothing leaked: only the accumulator's own entries remain...
        assert cat.buffer_count() == len(pending.items)
        pending.close()
        # ...and closing it empties the catalog entirely
        assert cat.buffer_count() == 0
        assert cat.check_leaks() == 0
    finally:
        mem._catalog = old


def test_restore_on_retry_storm_checkpointed_value(tmp_path):
    """CheckpointedValue under the same storm: a scalar accumulator
    (e.g. an output-row counter) never counts an aborted attempt."""
    from spark_rapids_tpu.runtime.retry import (
        CheckpointedValue,
        with_restore_on_retry,
    )

    cat = _mk_catalog(1 << 30, tmpdir=str(tmp_path))
    import spark_rapids_tpu.runtime.memory as mem
    old = mem._catalog
    mem._catalog = cat
    try:
        inputs = [cat.add_batch(_batch(800))]
        counter = CheckpointedValue(0)
        fails = {"n": 4}

        def body(sb):
            counter.value += sb.row_count()
            if fails["n"] > 0:
                fails["n"] -= 1
                raise TpuRetryOOM("storm")
            return True

        list(with_retry(inputs,
                        lambda sb: with_restore_on_retry(
                            counter, lambda: body(sb))))
        assert counter.value == 800  # attempts re-ran, count did not
        assert cat.buffer_count() == 0
    finally:
        mem._catalog = old
