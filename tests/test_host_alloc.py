"""Bounded host-memory arbiter (runtime/host_alloc.py — the
HostAlloc.scala + PinnedMemoryPool role): pinned transfer staging and
pageable working memory shared by the spill catalog's HOST tier and
shuffle blocks, with blocking + retryable-OOM semantics."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.runtime.errors import TpuRetryOOM
from spark_rapids_tpu.runtime.host_alloc import HostAlloc, HostPool


def test_blocking_reserve_wakes_on_release():
    pool = HostPool(100, "t")
    assert pool.try_reserve(80)
    woke = {"t": None}

    def waiter():
        t0 = time.monotonic()
        pool.reserve(50, timeout=10.0)
        woke["t"] = time.monotonic() - t0

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.2)
    pool.release(80)
    th.join(timeout=5)
    assert woke["t"] is not None and woke["t"] >= 0.15
    assert pool.used == 50


def test_overlimit_raises_retryable():
    pool = HostPool(100, "t")
    with pytest.raises(TpuRetryOOM):
        pool.reserve(101)


def test_exhausted_raises_retryable_after_timeout():
    pool = HostPool(100, "t")
    assert pool.try_reserve(100)
    with pytest.raises(TpuRetryOOM):
        pool.reserve(10, timeout=0.1)
    pool.release(100)


def test_pinned_staging_scopes_are_balanced():
    ha = HostAlloc(1 << 20, 1 << 20)
    with ha.reserved(1000, pinned=True):
        assert ha.pinned.used == 1000
    assert ha.pinned.used == 0


def test_shuffle_block_goes_to_disk_when_host_budget_gone(tmp_path):
    """CACHE_ONLY shuffle blocks draw from the global pageable pool;
    with no budget left, new blocks degrade straight to disk files and
    results stay correct."""
    from spark_rapids_tpu.runtime import host_alloc as ha_mod
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    ha_mod.initialize(1 << 20, 1 << 20)
    pool = ha_mod.get().pageable
    pool.reserve(1 << 20)  # exhaust the budget
    try:
        mgr = ShuffleManager("CACHE_ONLY", shuffle_dir=str(tmp_path))
        t = pa.table({"x": pa.array(np.arange(100), type=pa.int64())})
        sid = mgr.new_shuffle_id()
        mgr.put(sid, 0, t)
        assert mgr.bytes_in_memory == 0
        assert mgr.blocks_spilled == 1
        [got] = mgr.fetch(sid, 0)
        assert got.column("x").to_pylist() == list(range(100))
        mgr.remove_shuffle(sid)
    finally:
        pool.release(1 << 20)
        ha_mod.initialize(4 << 30, 8 << 30)


def test_catalog_spills_straight_to_disk_without_host_budget():
    """Device spill with an exhausted pageable pool bypasses the HOST
    tier (DEVICE -> DISK) instead of blowing the budget."""
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.runtime import host_alloc as ha_mod
    from spark_rapids_tpu.runtime.memory import SpillCatalog, SpillTier

    cat = SpillCatalog(device_limit=1 << 20, host_limit=1 << 20)
    ha_mod.initialize(1 << 20, 1 << 20)
    pool = ha_mod.get().pageable
    pool.reserve(1 << 20)
    try:
        t = pa.table({"x": pa.array(np.arange(4096), type=pa.int64())})
        sb = cat.add_batch(arrow_to_device(t))
        cat.spill_device_bytes(sb.size_bytes)
        assert sb.tier == SpillTier.DISK
        assert cat.metrics["spill_to_disk"] == 1
        assert cat.metrics["spill_to_host"] == 0
        got = sb.get_batch()  # unspill from disk still round-trips
        assert int(sb.row_count()) == 4096
        sb.close()
    finally:
        pool.release(1 << 20)
        ha_mod.initialize(4 << 30, 8 << 30)
