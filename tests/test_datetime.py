"""Datetime expression family + timezone DB (reference: datetime
expression rules in GpuOverrides.scala, GpuTimeZoneDB JNI, GpuCast
timestamp conversions). Differential tests against the CPU oracle in
UTC and non-UTC session zones, plus DST-boundary spot checks against
zoneinfo."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)

LA = "America/Los_Angeles"
KOLKATA = "Asia/Kolkata"


@pytest.fixture(scope="module")
def dt_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("dtdata")
    rng = np.random.default_rng(23)
    n = 4000
    secs = rng.integers(0, 1_800_000_000, n)
    # concentrate some instants near US DST transitions
    for base in (1710053100, 1730627100, 952041600):
        secs[:200] = base + rng.integers(-86400, 86400, 200)
        rng.shuffle(secs)
    t = pa.table({
        "ts": pa.array(secs * 1_000_000,
                       type=pa.timestamp("us", tz="UTC")),
        "d": pa.array((secs // 86400).astype("int32"),
                      type=pa.date32()),
        "n": pa.array(rng.integers(-40, 40, n).astype("int32")),
        "s": pa.array([f"20{i % 23 + 10}-0{i % 9 + 1}-1{i % 9} "
                       f"0{i % 9}:1{i % 5}:2{i % 7}"
                       for i in range(n)]),
    })
    p = str(d / "dt.parquet")
    pq.write_table(t, p)
    return p


def _diff(path, cols, conf=None):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(path).select(*cols), conf=conf)


def test_calendar_parts(dt_path):
    _diff(dt_path, [
        F.dayofweek("d").alias("dw"), F.weekday("d").alias("wd"),
        F.dayofyear("d").alias("dy"), F.weekofyear("d").alias("wy"),
        F.quarter("d").alias("q"), F.last_day("d").alias("ld")])


def test_date_arithmetic(dt_path):
    _diff(dt_path, [
        F.date_add("d", 31).alias("da"),
        F.date_sub("d", F.col("n")).alias("ds"),
        F.datediff(F.date_add("d", 5), "d").alias("dd"),
        F.add_months("d", F.col("n")).alias("am"),
        F.months_between(F.col("ts"), F.col("d")).alias("mb"),
        F.next_day("d", "Friday").alias("nd")])


def test_truncation(dt_path):
    _diff(dt_path, [
        F.trunc("d", "year").alias("ty"),
        F.trunc("d", "month").alias("tm"),
        F.trunc("d", "week").alias("tw"),
        F.date_trunc("hour", "ts").alias("th"),
        F.date_trunc("day", "ts").alias("td"),
        F.date_trunc("quarter", "ts").alias("tq")])


def test_epoch_and_format(dt_path):
    _diff(dt_path, [
        F.unix_timestamp("ts").alias("ut"),
        F.from_unixtime(F.unix_timestamp("ts")).alias("fu"),
        F.timestamp_seconds(F.unix_timestamp("ts")).alias("tsec"),
        F.date_format("ts", "yyyy-MM-dd HH:mm").alias("dfmt"),
        F.col("ts").cast("string").alias("tss")])


@pytest.mark.parametrize("zone", [LA, KOLKATA])
def test_parts_in_session_zone(dt_path, zone):
    _diff(dt_path, [
        F.hour("ts").alias("h"), F.minute("ts").alias("mi"),
        F.year("ts").alias("y"), F.dayofmonth("ts").alias("dom"),
        F.col("ts").cast("date").alias("tsd"),
        F.col("d").cast("timestamp").alias("dts"),
        F.date_trunc("day", "ts").alias("td"),
        F.col("ts").cast("string").alias("tss")],
        conf={"spark.sql.session.timeZone": zone})


@pytest.mark.parametrize("zone", [LA, KOLKATA])
def test_string_parse_in_session_zone(dt_path, zone):
    _diff(dt_path, [
        F.col("s").cast("timestamp").alias("parsed"),
        F.unix_timestamp(F.col("s")).alias("ut")],
        conf={"spark.sql.session.timeZone": zone})


def test_from_to_utc_timestamp(dt_path):
    _diff(dt_path, [
        F.from_utc_timestamp("ts", LA).alias("f"),
        F.to_utc_timestamp("ts", KOLKATA).alias("t")])


def test_tz_against_zoneinfo(dt_path):
    """Device hour() in LA must agree with python zoneinfo across DST
    boundaries (independent oracle, not the CPU engine)."""
    from datetime import datetime, timezone
    from zoneinfo import ZoneInfo

    def q(spark):
        return (spark.read.parquet(dt_path)
                .select("ts", F.hour("ts").alias("h"))
                .collect_arrow())

    out = with_tpu_session(
        q, conf={"spark.sql.session.timeZone": LA})
    zi = ZoneInfo(LA)
    ts = out.column("ts").to_pylist()
    hs = out.column("h").to_pylist()
    for i in range(0, len(ts), 37):
        want = ts[i].astimezone(zi).hour
        assert hs[i] == want, (ts[i], hs[i], want)


def test_date_format_fallback_pattern(dt_path):
    """Patterns outside the device token subset run on CPU (planner
    tag), still correct."""
    def q(spark):
        return (spark.read.parquet(dt_path)
                .select(F.date_format("ts", "yyyy/MM/dd").alias("a"))
                .collect_arrow())

    out = with_tpu_session(q)
    assert out.column("a")[0].as_py().count("/") == 2


def test_current_date_timestamp(dt_path):
    import datetime as dtm

    def q(spark):
        return (spark.read.parquet(dt_path).limit(3)
                .select(F.current_date().alias("cd"),
                        F.current_timestamp().alias("ct"))
                .collect_arrow())

    out = with_tpu_session(q)
    today = dtm.datetime.now(dtm.timezone.utc).date()
    cd = out.column("cd")[0].as_py()
    assert abs((cd - today).days) <= 1
    ct = out.column("ct")[0].as_py()
    assert abs((ct - dtm.datetime.now(dtm.timezone.utc))
               .total_seconds()) < 3600


def test_dst_gap_and_overlap_rules():
    """Nonexistent local times (spring-forward gap) keep the pre-gap
    offset (pushed later by the gap width), ambiguous times take the
    earlier offset — java.time.ZoneRules/Spark behavior."""
    import datetime as dtm

    from spark_rapids_tpu.ops import tzdb

    la = "America/Los_Angeles"

    def us(*args):
        return int((dtm.datetime(*args)
                    - dtm.datetime(1970, 1, 1)).total_seconds() * 1e6)

    gap = np.array([us(2021, 3, 14, 2, 30)], np.int64)
    out = tzdb.local_to_utc_np(gap, la)
    assert out[0] == us(2021, 3, 14, 10, 30)  # = 03:30 PDT
    amb = np.array([us(2021, 11, 7, 1, 30)], np.int64)
    out = tzdb.local_to_utc_np(amb, la)
    assert out[0] == us(2021, 11, 7, 8, 30)  # earlier (PDT) offset

    # device path agrees with the numpy path
    import jax.numpy as jnp

    dev = np.asarray(tzdb.local_to_utc(jnp.asarray(
        np.concatenate([gap, amb])), la))
    assert dev[0] == us(2021, 3, 14, 10, 30)
    assert dev[1] == us(2021, 11, 7, 8, 30)


def test_pre_epoch_timestamp_to_string():
    """Pre-1970 fractional timestamps format with floored seconds."""
    import datetime as dtm

    def q(spark):
        df = spark.createDataFrame(pa.table({
            "t": pa.array([-500000, -1, 500000],
                          type=pa.timestamp("us", tz="UTC"))}))
        return df.select(F.col("t").cast("string").alias("s")) \
            .collect_arrow()

    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.createDataFrame(pa.table({
            "t": pa.array([-500000, -1, 500000],
                          type=pa.timestamp("us", tz="UTC"))}))
        .select(F.col("t").cast("string").alias("s")))
    out = with_tpu_session(q)
    assert out.column("s").to_pylist() == [
        "1969-12-31 23:59:59.5", "1969-12-31 23:59:59.999999",
        "1970-01-01 00:00:00.5"]


def test_current_timestamp_pinned_per_query():
    def q(spark):
        return (spark.range(3)
                .select(F.current_timestamp().alias("a"),
                        F.current_timestamp().alias("b"))
                .collect_arrow())

    out = with_tpu_session(q)
    assert out.column("a").to_pylist() == out.column("b").to_pylist()


def test_make_date_invalid_is_null():
    def q(spark):
        df = spark.createDataFrame(pa.table({
            "y": pa.array([2024, 2023, 2024]),
            "m": pa.array([2, 2, 13]),
            "dd": pa.array([29, 29, 1])}))
        return (df.select(F.make_date("y", "m", "dd").alias("md"))
                .collect_arrow())

    out = with_tpu_session(q)
    vals = out.column("md").to_pylist()
    assert vals[0] is not None       # 2024-02-29 valid (leap)
    assert vals[1] is None           # 2023-02-29 invalid
    assert vals[2] is None           # month 13


def test_string_to_timestamp_la_dst_on_device():
    """string->timestamp under a non-UTC session zone runs ON DEVICE
    (ops/tzdb.py transition tables; GpuTimeZoneDB role) — differential
    oracle at America/Los_Angeles across the 2024 DST gap (02:00->
    03:00 spring-forward) and overlap (fall-back), resolving ambiguous
    wall-clocks to the EARLIER offset like java.time.ZoneRules."""
    from datetime import datetime
    from zoneinfo import ZoneInfo

    from spark_rapids_tpu.api.session import TpuSparkSession

    strs = ["2024-03-10 01:30:00", "2024-03-10 02:30:00",
            "2024-03-10 03:30:00", "2024-11-03 00:30:00",
            "2024-11-03 01:30:00", "2024-06-15 12:00:00",
            "2024-01-15 12:00:00"]
    s = TpuSparkSession({"spark.sql.session.timeZone": LA,
                         "spark.sql.shuffle.partitions": 2})
    try:
        out = (s.createDataFrame(pa.table({"s": pa.array(strs)}))
               .select(F.col("s").cast("timestamp").alias("ts"))
               .collect_arrow())
        assert s.last_execution["engine"] == "fused"  # stayed on device
        zi = ZoneInfo(LA)
        utc = ZoneInfo("UTC")
        for src, got in zip(strs, out["ts"].to_pylist()):
            want = (datetime.fromisoformat(src).replace(tzinfo=zi)
                    .astimezone(utc).replace(tzinfo=None))
            got_n = (got.astimezone(utc).replace(tzinfo=None)
                     if got.tzinfo else got)
            assert got_n == want, (src, got_n, want)
    finally:
        s.stop()
