"""Delta deletion vectors + column mapping (merge-on-read depth).

Reference: delta protocol PROTOCOL.md deletion-vector format;
delta-24x GpuDeleteCommand / GpuDeltaParquetFileFormat; column mapping
per delta.columnMapping.mode with physicalName field metadata.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.lakehouse import deletion_vectors as dvmod
from spark_rapids_tpu.lakehouse.delta import DeltaTable, load_snapshot


@pytest.fixture()
def spark():
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    yield s
    s.stop()


def _mk_dv_table(spark, path, n=1000):
    t = pa.table({
        "id": pa.array(np.arange(n), type=pa.int64()),
        "v": pa.array(np.arange(n) % 7, type=pa.int64()),
    })
    (spark.createDataFrame(t).write.format("delta")
     .option("delta.enableDeletionVectors", "true").save(path))
    return t


def test_dv_roundtrip_foreign_run_container():
    # parse a hand-built SERIAL_COOKIE (run-container) bitmap — the
    # layout other writers emit for dense deletes
    import struct

    size = 1
    cookie = 12347 | ((size - 1) << 16)
    buf = struct.pack("<I", cookie)
    buf += bytes([0b1])              # run flag for container 0
    buf += struct.pack("<HH", 0, 9)  # key 0, cardinality-1 = 9
    # size < NO_OFFSET_THRESHOLD and run flags present: no offsets
    buf += struct.pack("<H", 2)      # 2 runs
    buf += struct.pack("<HH", 3, 4)  # rows 3..7
    buf += struct.pack("<HH", 100, 4)  # rows 100..104
    blob = struct.pack("<iq", dvmod.MAGIC, 1) + buf
    got = dvmod.parse_blob(blob)
    want = np.array([3, 4, 5, 6, 7, 100, 101, 102, 103, 104])
    assert np.array_equal(got, want)


def test_dv_bitmap_container_roundtrip():
    idx = np.arange(0, 30000, 2, dtype=np.int64)  # card > 4096 -> bitmap
    assert np.array_equal(dvmod.parse_blob(dvmod.serialize_blob(idx)),
                          idx)


def test_dv_empty_2_32_bucket_roundtrip():
    # indexes spanning a fully-empty 2^32 bucket must serialize a valid
    # EMPTY bitmap for it (regression: a spurious offset corrupted the
    # stream -> 'bad roaring cookie')
    idx = np.array([5, (2 << 32) + 3], dtype=np.int64)
    assert np.array_equal(dvmod.parse_blob(dvmod.serialize_blob(idx)),
                          idx)


def test_write_properties_merge_on_overwrite_and_append(spark, tmp_path):
    path = str(tmp_path / "props")
    t = pa.table({"a": pa.array([1, 2], type=pa.int64())})
    (spark.createDataFrame(t).write.format("delta")
     .option("delta.enableDeletionVectors", "true").save(path))
    # overwrite with a DIFFERENT property must keep the old one
    (spark.createDataFrame(t).write.format("delta").mode("overwrite")
     .option("delta.appendOnly", "false").save(path))
    snap = load_snapshot(path)
    assert snap.deletion_vectors_enabled
    assert snap.config.get("delta.appendOnly") == "false"
    # append carrying a property lands it too
    (spark.createDataFrame(t).write.format("delta").mode("append")
     .option("delta.x", "1").save(path))
    snap = load_snapshot(path)
    assert snap.config.get("delta.x") == "1"
    assert snap.deletion_vectors_enabled


def test_delete_writes_dv_not_rewrite(spark, tmp_path):
    path = str(tmp_path / "dvt")
    _mk_dv_table(spark, path)
    before_files = sorted(f for f in os.listdir(path)
                          if f.endswith(".parquet"))
    dt = DeltaTable.forPath(spark, path)
    dt.delete(F.col("v") == 3)
    snap = dt._snapshot() if hasattr(dt, "_snapshot") else \
        load_snapshot(path)
    # data files untouched: same parquet set, adds now carry DVs
    after_files = sorted(f for f in os.listdir(path)
                         if f.endswith(".parquet"))
    assert after_files == before_files
    assert all(a.get("deletionVector") for a in snap.files.values())
    got = (spark.read.format("delta").load(path)
           .collect_arrow().sort_by("id"))
    assert got.num_rows == 1000 - len([i for i in range(1000)
                                       if i % 7 == 3])
    assert 3 not in set(got.column("v").to_pylist())


def test_second_delete_unions_dv(spark, tmp_path):
    path = str(tmp_path / "dvt2")
    _mk_dv_table(spark, path)
    dt = DeltaTable.forPath(spark, path)
    dt.delete(F.col("v") == 3)
    dt.delete(F.col("v") == 5)
    got = spark.read.format("delta").load(path).collect_arrow()
    vs = set(got.column("v").to_pylist())
    assert 3 not in vs and 5 not in vs
    assert got.num_rows == sum(1 for i in range(1000)
                               if i % 7 not in (3, 5))


def test_full_file_delete_emits_remove(spark, tmp_path):
    path = str(tmp_path / "dvt3")
    _mk_dv_table(spark, path)
    dt = DeltaTable.forPath(spark, path)
    dt.delete(F.col("id") >= 0)  # everything
    snap = load_snapshot(path)
    assert snap.files == {}
    got = spark.read.format("delta").load(path).collect_arrow()
    assert got.num_rows == 0


def test_update_on_dv_table_does_not_resurrect(spark, tmp_path):
    path = str(tmp_path / "dvt4")
    _mk_dv_table(spark, path)
    dt = DeltaTable.forPath(spark, path)
    dt.delete(F.col("v") == 3)
    dt.update(F.col("v") == 1, {"v": F.lit(100)})
    got = spark.read.format("delta").load(path).collect_arrow()
    vs = got.column("v").to_pylist()
    assert 3 not in set(vs), "deleted rows resurrected by UPDATE"
    assert 1 not in set(vs)
    assert vs.count(100) == sum(1 for i in range(1000) if i % 7 == 1)
    assert got.num_rows == sum(1 for i in range(1000) if i % 7 != 3)


def test_checkpoint_skips_dv_tables_and_keeps_config(spark, tmp_path):
    from spark_rapids_tpu.lakehouse.delta import write_checkpoint

    path = str(tmp_path / "dvt5")
    _mk_dv_table(spark, path)
    dt = DeltaTable.forPath(spark, path)
    dt.delete(F.col("v") == 3)
    # adds carry deletionVector: the checkpoint writer must refuse
    # rather than silently drop the DV (which would resurrect rows)
    assert write_checkpoint(path) is False
    snap = load_snapshot(path)
    assert snap.deletion_vectors_enabled


def _write_mapped_table(path):
    """Hand-crafted column-mapping table: physical parquet names differ
    from logical schema names (what Spark writes under
    delta.columnMapping.mode=name)."""
    os.makedirs(os.path.join(path, "_delta_log"))
    t = pa.table({
        "col-9aab0d": pa.array([1, 2, 3], type=pa.int64()),
        "col-7ffe11": pa.array(["a", "b", "c"], type=pa.string()),
    })
    pq.write_table(t, os.path.join(path, "part-0.parquet"))
    schema = {"type": "struct", "fields": [
        {"name": "id", "type": "long", "nullable": True, "metadata": {
            "delta.columnMapping.id": 1,
            "delta.columnMapping.physicalName": "col-9aab0d"}},
        {"name": "name", "type": "string", "nullable": True,
         "metadata": {
             "delta.columnMapping.id": 2,
             "delta.columnMapping.physicalName": "col-7ffe11"}},
    ]}
    actions = [
        {"protocol": {"minReaderVersion": 2, "minWriterVersion": 5}},
        {"metaData": {
            "id": "m", "format": {"provider": "parquet", "options": {}},
            "schemaString": json.dumps(schema), "partitionColumns": [],
            "configuration": {"delta.columnMapping.mode": "name"},
            "createdTime": 0}},
        {"add": {"path": "part-0.parquet", "partitionValues": {},
                 "size": os.path.getsize(
                     os.path.join(path, "part-0.parquet")),
                 "modificationTime": 0, "dataChange": True}},
    ]
    with open(os.path.join(path, "_delta_log",
                           "00000000000000000000.json"), "w") as f:
        for a in actions:
            f.write(json.dumps(a) + "\n")
    return schema


def test_column_mapping_read(spark, tmp_path):
    path = str(tmp_path / "mapped")
    _write_mapped_table(path)
    got = spark.read.format("delta").load(path).collect_arrow()
    assert got.column_names == ["id", "name"]
    assert got.column("id").to_pylist() == [1, 2, 3]
    assert got.column("name").to_pylist() == ["a", "b", "c"]
    # projection + filter through the engine
    out = (spark.read.format("delta").load(path)
           .filter(F.col("id") > 1).select("name").collect_arrow())
    assert sorted(out.column("name").to_pylist()) == ["b", "c"]


def test_column_mapping_rename_is_metadata_only(spark, tmp_path):
    path = str(tmp_path / "mapped2")
    schema = _write_mapped_table(path)
    # rename logical column 'name' -> 'label': metaData-only commit
    schema["fields"][1]["name"] = "label"
    action = {"metaData": {
        "id": "m", "format": {"provider": "parquet", "options": {}},
        "schemaString": json.dumps(schema), "partitionColumns": [],
        "configuration": {"delta.columnMapping.mode": "name"},
        "createdTime": 0}}
    with open(os.path.join(path, "_delta_log",
                           "00000000000000000001.json"), "w") as f:
        f.write(json.dumps(action) + "\n")
    got = spark.read.format("delta").load(path).collect_arrow()
    assert got.column_names == ["id", "label"]
    assert got.column("label").to_pylist() == ["a", "b", "c"]
