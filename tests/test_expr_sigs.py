"""Per-parameter TypeSig enforcement (plan/expr_sigs.py).

Two invariants: (1) the signatures must NOT regress placement for the
expression surface the engine actually lowers — a too-narrow sig would
silently drain plans to the CPU path; (2) genuine mismatches must tag
with a per-parameter reason.
"""

import pytest

from spark_rapids_tpu.expr import arith as A
from spark_rapids_tpu.expr import mathexpr as M
from spark_rapids_tpu.expr import predicates as P
from spark_rapids_tpu.expr import strings as S
from spark_rapids_tpu.expr.core import BoundReference, Literal
from spark_rapids_tpu.plan import expr_sigs as ES
from spark_rapids_tpu.plan.typesig import expr_unsupported_reasons
from spark_rapids_tpu.sqltypes.datatypes import (
    boolean,
    date,
    double,
    integer,
    long,
    string,
    timestamp,
)


def _b(i, t):
    return BoundReference(i, t, True)


DEVICE_OK = [
    A.Add(_b(0, long), _b(1, long)),
    A.Add(_b(0, double), _b(1, double)),
    A.Multiply(_b(0, double), _b(1, long)),
    A.Divide(_b(0, double), _b(1, double)),
    A.Abs(_b(0, long)),
    P.EqualTo(_b(0, string), _b(1, string)),
    P.LessThan(_b(0, date), _b(1, date)),
    P.And(P.IsNotNull(_b(0, long)), P.IsNull(_b(1, string))),
    P.IsNaN(_b(0, double)),
    S.Upper(_b(0, string)),
    S.Concat(_b(0, string), _b(1, string)),
    S.Length(_b(0, string)),
    M.Sqrt(_b(0, double)),
    M.Round(_b(0, double), 2),
    M.BitwiseAnd(_b(0, long), _b(1, integer)),
    M.Pow(_b(0, double), _b(1, long)),
]


def _datetime_ok():
    from spark_rapids_tpu.expr import datetimes as D

    return [
        D.Year(_b(0, timestamp)),        # extractors take ts too
        D.Year(_b(0, date)),
        D.MonthsBetween(_b(0, date), _b(1, date)),
        D.DateTrunc("day", _b(0, timestamp)),
        D.TruncDate(_b(0, date), "month"),
        D.FromUnixtime(_b(0, long), "yyyy-MM-dd"),
        D.NextDay(_b(0, date), "monday"),
        D.DateFormat(_b(0, timestamp), "yyyy"),
        D.LastDay(_b(0, timestamp)),
    ]


DEVICE_OK = DEVICE_OK + _datetime_ok()


@pytest.mark.parametrize("e", DEVICE_OK,
                         ids=lambda e: type(e).__name__)
def test_signatures_accept_the_lowered_surface(e):
    reasons = [r for r in expr_unsupported_reasons(e, None)
               if "device lowering" in r]
    assert reasons == [], reasons


def test_signature_rejects_param_mismatch():
    # non-boolean into NOT: per-parameter reason names the param
    e = P.Not(_b(0, long))
    reasons = ES.check_expr(e)
    assert reasons and "input" in reasons[0], reasons
    # non-float into IsNaN; non-string into Upper
    e2 = P.IsNaN(_b(0, string))
    assert ES.check_expr(e2)
    e3 = S.Upper(_b(0, long))
    assert ES.check_expr(e3)
    # and the planner walk surfaces it
    walked = expr_unsupported_reasons(e2, None)
    assert any("device lowering" in r for r in walked)


def test_variadic_signature_covers_tail_params():
    e = S.ConcatWs(",", _b(0, string), _b(1, string))
    assert ES.check_expr(e) == []
    bad = S.ConcatWs(",", _b(0, string), _b(1, long))
    assert ES.check_expr(bad)


def test_null_literals_coerce_everywhere():
    from spark_rapids_tpu.sqltypes.datatypes import null_t

    e = P.EqualTo(_b(0, long), Literal(None, null_t))
    assert ES.check_expr(e) == []


def test_matrix_doc_contains_signatures():
    from spark_rapids_tpu.tools.gendocs import supported_ops_md

    md = supported_ops_md()
    assert "Per-parameter type signatures" in md
    assert "| Add | lhs |" in md
