"""Struct grouping/join keys (plan/struct_keys.py canonical expansion;
round-4 verdict item #4 — reference GpuHashJoin.scala:403 nested keys)
and struct payloads through the MESH tier (collectives/shard assembly
are leaf-wise over the column pytree, so DeviceColumn.children ride
all_to_all like any other per-row leaf).

Spark's struct-comparison semantics are the differential contract:
- null structs GROUP together but never MATCH in a join (EqualTo null
  propagation);
- null FIELDS inside non-null structs compare EQUAL both for grouping
  and for join keys (RowOrdering semantics).
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    with_cpu_session,
    with_tpu_session,
)

MESH = {"spark.rapids.tpu.mesh": 8,
        "spark.sql.shuffle.partitions": 4}

ST = pa.struct([("a", pa.int64()), ("b", pa.string())])


def _struct_table(n=64, seed=3):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        r = rng.random()
        if r < 0.15:
            rows.append(None)
        elif r < 0.3:
            rows.append({"a": None, "b": f"s{int(rng.integers(3))}"})
        elif r < 0.45:
            rows.append({"a": int(rng.integers(4)), "b": None})
        else:
            rows.append({"a": int(rng.integers(4)),
                         "b": f"s{int(rng.integers(3))}"})
    return pa.table({
        "s": pa.array(rows, type=ST),
        "v": pa.array(rng.random(n) * 10),
    })


def _group_oracle(t):
    acc = {}
    for s, v in zip(t["s"].to_pylist(), t["v"].to_pylist()):
        k = None if s is None else (s["a"], s["b"])
        c = acc.setdefault(k, [0.0, 0])
        c[0] += v
        c[1] += 1
    return {k: (round(v, 6), c) for k, (v, c) in acc.items()}


def _group_result(out):
    return {
        (None if s is None else (s["a"], s["b"])): (round(v, 6), c)
        for s, v, c in zip(out["s"].to_pylist(), out["sv"].to_pylist(),
                           out["c"].to_pylist())}


def test_struct_group_key_vs_oracle():
    t = _struct_table()
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        out = (spark.createDataFrame(t).groupBy("s")
               .agg(F.sum("v").alias("sv"), F.count("*").alias("c"))
               .collect_arrow())
        assert _group_result(out) == _group_oracle(t)
        # the expansion kept the query on a device engine
        assert spark.last_execution["engine"] in ("fused", "aqe",
                                                  "eager")
    finally:
        spark.stop()


def test_struct_join_key_semantics():
    lt = pa.table({
        "k": pa.array([{"a": 1, "b": "x"}, {"a": None, "b": "x"},
                       None, {"a": 2, "b": None}, {"a": 9, "b": "q"}],
                      type=ST),
        "lv": pa.array([1, 2, 3, 4, 5]),
    })
    rt = pa.table({
        "k": pa.array([{"a": 1, "b": "x"}, {"a": None, "b": "x"},
                       None, {"a": 2, "b": None}],
                      type=ST),
        "rv": pa.array([10, 20, 30, 40]),
    })
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        j = (spark.createDataFrame(lt)
             .join(spark.createDataFrame(rt), on="k", how="inner")
             .collect_arrow())
        pairs = sorted(zip(j["lv"].to_pylist(), j["rv"].to_pylist()))
        # null struct rows (3/30) never match; null-field rows match
        assert pairs == [(1, 10), (2, 20), (4, 40)], pairs
        # left join: unmatched keep null right side
        lj = (spark.createDataFrame(lt)
              .join(spark.createDataFrame(rt), on="k", how="left")
              .collect_arrow())
        got = dict(zip(lj["lv"].to_pylist(), lj["rv"].to_pylist()))
        assert got == {1: 10, 2: 20, 3: None, 4: 40, 5: None}, got
    finally:
        spark.stop()


def test_struct_semi_anti_join_keys():
    lt = pa.table({
        "k": pa.array([{"a": 1, "b": "x"}, None, {"a": 7, "b": "z"}],
                      type=ST),
        "lv": pa.array([1, 2, 3]),
    })
    rt = pa.table({
        "k": pa.array([{"a": 1, "b": "x"}], type=ST),
        "rv": pa.array([10]),
    })
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        semi = (spark.createDataFrame(lt).join(
            spark.createDataFrame(rt), on="k", how="left_semi")
            .collect_arrow())
        assert semi["lv"].to_pylist() == [1]
        anti = (spark.createDataFrame(lt).join(
            spark.createDataFrame(rt), on="k", how="left_anti")
            .collect_arrow())
        assert sorted(anti["lv"].to_pylist()) == [2, 3]
    finally:
        spark.stop()


# ------------------------------------------------------------- mesh

def test_mesh_struct_payload_through_shuffle():
    """Struct columns shard, ride all_to_all, and gather back — the
    round-4 mesh rejection (plan_compiler._reject_struct_columns) is
    gone; the collectives exchange every pytree leaf incl. children."""
    n = 4000
    rng = np.random.default_rng(5)
    t = pa.table({
        "store": pa.array(rng.integers(0, 16, n), type=pa.int64()),
        "s": pa.array(
            [{"a": int(a), "b": f"b{int(a) % 5}"}
             for a in rng.integers(0, 50, n)], type=ST),
        "v": pa.array(rng.random(n)),
    })

    def q(s):
        df = s.createDataFrame(t)
        # shuffle by store (repartition) then filter on a struct field
        return (df.repartition(4, "store")
                .filter(F.col("s").getField("a") > 10)
                .select("store", "s", "v"))

    got = with_tpu_session(lambda s: q(s).collect_arrow(), MESH)
    want = with_cpu_session(lambda s: q(s).collect_arrow())
    assert_tables_equal(got, want, ignore_order=True)


def test_mesh_struct_group_key():
    n = 3000
    rng = np.random.default_rng(9)
    rows = [None if rng.random() < 0.1 else
            {"a": int(rng.integers(5)),
             "b": None if rng.random() < 0.2 else f"r{int(rng.integers(3))}"}
            for _ in range(n)]
    t = pa.table({"s": pa.array(rows, type=ST),
                  "v": pa.array(rng.random(n))})

    def q(s):
        return (s.createDataFrame(t).groupBy("s")
                .agg(F.sum("v").alias("sv"), F.count("*").alias("c")))

    got = with_tpu_session(lambda s: q(s).collect_arrow(), MESH)
    assert _group_result(got) == _group_oracle(t)
