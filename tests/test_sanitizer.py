"""Concurrency-sanitizer suite (PR 7): wait-for-graph cycle detection
on edge insertion, victim selection + leak-free unwind through the
cancel machinery, acquisition-order inversion warnings, the atomic
per-query permit-group root fix, and disabled-mode inertness.

The acceptance contract: a constructed 2- or 3-query permit cycle is
detected the moment its closing edge is inserted; the victim unwinds
with DeadlockDetectedError naming the cycle, leaving holders()==0 and
check_leaks clean; an A->B then B->A acquisition order is flagged as
an inversion WITHOUT a deadlock; and with the sanitizer disabled every
hook is a None-check that records nothing.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.obs import events as obs_events
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime import sanitizer
from spark_rapids_tpu.runtime.cancellation import CancelToken
from spark_rapids_tpu.runtime.errors import DeadlockDetectedError
from spark_rapids_tpu.runtime.sanitizer import (
    ADMISSION,
    SEMAPHORE,
    ConcurrencySanitizer,
    quota_resource,
)
from spark_rapids_tpu.runtime.semaphore import TpuSemaphore


def _wait_until(pred, timeout_s=10.0, tick=0.002):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return False


@pytest.fixture
def san():
    s = ConcurrencySanitizer()
    sanitizer.install(s)
    yield s
    sanitizer.install(None)


# ------------------------------------------------ graph-level detection

def test_two_query_cycle_detected_on_edge_insertion(san):
    """q1 holds A and waits on B; q2 holds B. The cycle closes the
    MOMENT q2's wait on A is inserted — no polling, no later sweep."""
    res_a, res_b = ("semaphore", "a"), ("semaphore", "b")
    t1, t2 = CancelToken(1), CancelToken(2)
    san.acquired(res_a, 1)
    san.acquired(res_b, 2)
    rec1 = san.begin_wait(res_b, 1, token=t1)
    assert san.counters.cycles == 0  # no cycle yet: q2 isn't waiting
    san.begin_wait(res_a, 2, token=t2)
    assert san.counters.cycles == 1
    assert san.counters.victims == 1
    # youngest policy: q2 is the victim
    assert t2.cancelled and not t1.cancelled
    with pytest.raises(DeadlockDetectedError) as ei:
        t2.check()
    msg = str(ei.value)
    assert "wait-for cycle" in msg and "query 1" in msg \
        and "query 2" in msg
    assert san.last_cycle is not None
    assert {r["queryId"] for r in san.last_cycle} == {1, 2}
    san.end_wait(rec1)


def test_three_query_cycle_detected(san):
    """q1->B(q2), q2->C(q3), then the closing edge q3->A(q1)."""
    a, b, c = [("semaphore", k) for k in "abc"]
    tokens = {q: CancelToken(q) for q in (1, 2, 3)}
    san.acquired(a, 1)
    san.acquired(b, 2)
    san.acquired(c, 3)
    san.begin_wait(b, 1, token=tokens[1])
    san.begin_wait(c, 2, token=tokens[2])
    assert san.counters.cycles == 0
    san.begin_wait(a, 3, token=tokens[3])
    assert san.counters.cycles == 1
    assert tokens[3].cancelled  # youngest
    assert {r["queryId"] for r in san.last_cycle} == {1, 2, 3}


def test_victim_policy_oldest():
    s = ConcurrencySanitizer(victim_policy="oldest")
    sanitizer.install(s)
    try:
        res_a, res_b = ("semaphore", "a"), ("semaphore", "b")
        t1, t2 = CancelToken(1), CancelToken(2)
        s.acquired(res_a, 1)
        s.acquired(res_b, 2)
        s.begin_wait(res_b, 1, token=t1)
        s.begin_wait(res_a, 2, token=t2)
        assert t1.cancelled and not t2.cancelled
    finally:
        sanitizer.install(None)


def test_shared_resource_multi_holder_cycle(san):
    """The real per-operator shape: ONE resource (the device
    semaphore), both queries holding a chunk and both waiting for
    more. Cycle detection must see through the shared-resource
    aliasing."""
    t1, t2 = CancelToken(1), CancelToken(2)
    san.acquired(SEMAPHORE, 1)
    san.acquired(SEMAPHORE, 2)
    san.begin_wait(SEMAPHORE, 1, token=t1)
    assert san.counters.cycles == 0
    san.begin_wait(SEMAPHORE, 2, token=t2)
    assert san.counters.cycles == 1 and t2.cancelled


def test_no_cycle_no_victim(san):
    """A plain waiter behind a running (non-waiting) holder is NOT a
    deadlock."""
    t1, t2 = CancelToken(1), CancelToken(2)
    san.acquired(SEMAPHORE, 1)
    rec = san.begin_wait(SEMAPHORE, 2, token=t2)
    assert san.counters.cycles == 0
    assert not t1.cancelled and not t2.cancelled
    san.end_wait(rec)
    san.released(SEMAPHORE, 1)
    san.check_clean()


def test_quota_soft_wait_closes_cross_class_cycle(san):
    """Cross-class: q1 holds semaphore + spins on quota; q2 holds
    quota bytes + waits on the semaphore. The quota side uses the
    report_holders + note_contention soft path (what
    SpillCatalog.reserve calls on a failed reservation)."""
    t1, t2 = CancelToken(1), CancelToken(2)
    quota = quota_resource()
    san.acquired(SEMAPHORE, 1)
    san.report_holders(quota, {2: time.monotonic()})
    san.begin_wait(SEMAPHORE, 2, token=t2)
    assert san.counters.cycles == 0
    san.note_contention(quota, 1, token=t1)
    assert san.counters.cycles == 1
    assert t2.cancelled or t1.cancelled


# ------------------------------------------------- order inversions

def test_order_inversion_flagged_without_deadlock(san):
    """semaphore-then-quota on one flow, quota-then-semaphore on
    another: flagged once as an inversion, no cycle, no victim."""
    quota = quota_resource("scoped")
    san.acquired(SEMAPHORE, 1)
    san.acquired(quota, 1)       # semaphore -> quota
    san.released(quota, 1)
    san.released(SEMAPHORE, 1)
    assert san.counters.inversions == 0
    san.acquired(quota, 2)
    san.acquired(SEMAPHORE, 2)   # quota -> semaphore: inversion
    assert san.counters.inversions == 1
    assert san.counters.cycles == 0 and san.counters.victims == 0
    assert ("quota", "semaphore") in {
        tuple(sorted(p)) for p in san.inversions()}
    # reported once per pair, not per occurrence
    san.released(SEMAPHORE, 2)
    san.released(quota, 2)
    san.acquired(quota, 3)
    san.acquired(SEMAPHORE, 3)
    assert san.counters.inversions == 1
    san.released(SEMAPHORE, 3)
    san.released(quota, 3)
    san.check_clean()


# --------------------------------------- semaphore integration (legacy)

def _acquire_as_query(semaphore, qid, task_id, token, errs, done):
    """Run one acquire inside a query scope on this thread."""
    from spark_rapids_tpu.runtime import cancellation

    obs_events.begin_query(qid)
    try:
        with cancellation.scope(token):
            semaphore.acquire_if_necessary(task_id)
        done.append(task_id)
    except BaseException as e:
        errs.append((qid, task_id, e))
    finally:
        obs_events.finish_query(qid)


def test_legacy_semaphore_deadlock_detected_and_unwound(san):
    """Reconstruct the pre-fix wedge on a real TpuSemaphore (atomic
    groups OFF): two queries each hold a 500-permit chunk, then each
    needs a second chunk. The closing edge victimizes the youngest,
    whose blocked acquire raises DeadlockDetectedError; everything
    releases, holders()==0, sanitizer graph clean."""
    semaphore = TpuSemaphore(concurrent_tasks=2, acquire_timeout_ms=0,
                             atomic_query_groups=False)
    t1, t2 = CancelToken(1), CancelToken(2)
    errs, done = [], []

    # each query's first chunk, on its own thread (thread-local scope)
    th = [threading.Thread(
        target=_acquire_as_query,
        args=(semaphore, q, tid, tok, errs, done))
        for q, tid, tok in ((1, 11, t1), (2, 21, t2))]
    for t in th:
        t.start()
    for t in th:
        t.join(10)
    assert sorted(done) == [11, 21] and not errs

    # nested second acquires: q1 blocks (no cycle yet) ...
    th1 = threading.Thread(target=_acquire_as_query,
                           args=(semaphore, 1, 12, t1, errs, done))
    th1.start()
    assert _wait_until(lambda: semaphore.waiting() == 1)
    assert san.counters.cycles == 0
    # ... q2's nested acquire inserts the closing edge
    th2 = threading.Thread(target=_acquire_as_query,
                           args=(semaphore, 2, 22, t2, errs, done))
    th2.start()
    th2.join(10)
    assert san.counters.cycles == 1 and san.counters.victims == 1
    # youngest query (2) was unwound with the cycle in the message
    assert t2.cancelled and not t1.cancelled
    assert len(errs) == 1 and errs[0][0] == 2
    assert isinstance(errs[0][2], DeadlockDetectedError)
    assert "wait-for cycle" in str(errs[0][2])
    # survivor q2's FIRST chunk releases on unwind (what the cancel
    # machinery does for a real query); q1's nested acquire proceeds
    semaphore.release_if_necessary(21)
    th1.join(10)
    assert not th1.is_alive() and 12 in done
    for tid in (11, 12):
        semaphore.release_if_necessary(tid)
    assert semaphore.holders() == 0
    assert semaphore.waiting() == 0
    san.check_clean()


def test_atomic_groups_prevent_the_same_deadlock(san):
    """Same schedule, atomic groups ON (the default): nested acquires
    join the owning query's permit group instead of blocking — no
    wait edge, no cycle, both queries complete."""
    semaphore = TpuSemaphore(concurrent_tasks=2, acquire_timeout_ms=0,
                             atomic_query_groups=True)
    t1, t2 = CancelToken(1), CancelToken(2)
    errs, done = [], []
    for q, tid, tok in ((1, 11, t1), (2, 21, t2),
                        (1, 12, t1), (2, 22, t2)):
        th = threading.Thread(
            target=_acquire_as_query,
            args=(semaphore, q, tid, tok, errs, done))
        th.start()
        th.join(10)
        assert not th.is_alive()
    assert sorted(done) == [11, 12, 21, 22] and not errs
    assert san.counters.cycles == 0 and san.counters.victims == 0
    assert semaphore.query_holds(1) == 2 and semaphore.query_holds(2) == 2
    for tid in (11, 12, 21, 22):
        semaphore.release_if_necessary(tid)
    assert semaphore.holders() == 0
    san.check_clean()


# ---------------------------------------------- end-to-end (session)

def _fact_dir(tmp_path):
    rng = np.random.default_rng(7)
    n = 8_000
    d = tmp_path / "fact"
    d.mkdir()
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 50, n), pa.int64()),
        "v": pa.array(rng.random(n) * 100.0),
    }), str(d / "part-0.parquet"))
    return str(d)


def _concurrent_fallback_queries(s, data):
    """The historical wedge: two concurrent queries whose plan has a
    forced CPU-fallback Filter + repartition (per-operator permit
    churn under the fused scaffold's hold)."""
    import spark_rapids_tpu.api.functions as F

    results, errs = [], []

    def worker(i):
        try:
            df = (s.read.parquet(data)
                  .filter(F.col("v") > 10.0)
                  .repartition(4, "k").groupBy("k")
                  .agg(F.sum("v").alias("sv")))
            results.append((i, df.collect_arrow().num_rows))
        except BaseException as e:  # surfaced to the asserting test
            errs.append((i, e))

    th = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in th:
        t.start()
    for t in th:
        t.join(120)
    assert not any(t.is_alive() for t in th), \
        "deadlock: a worker is still wedged"
    return results, errs


def test_e2e_atomic_groups_both_queries_complete(tmp_path):
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.runtime import semaphore as sem_mod
    from spark_rapids_tpu.runtime.memory import get_catalog

    data = _fact_dir(tmp_path)
    s = TpuSparkSession({
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.exec.Filter": False,
    })
    try:
        results, errs = _concurrent_fallback_queries(s, data)
        assert not errs, errs
        assert len(results) == 2
        assert sem_mod.get().holders() == 0
        get_catalog().check_leaks(raise_on_leak=True)
    finally:
        s.stop()


def test_e2e_legacy_sanitizer_recovers_the_deadlock(tmp_path):
    """Regression-gate the backstop path: atomic groups OFF, sanitizer
    ON — the historical hang must end as either both-complete (victim
    retried) or one clean DeadlockDetectedError, with a detected cycle
    on the ledger and zero leaks."""
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.runtime import semaphore as sem_mod
    from spark_rapids_tpu.runtime.memory import get_catalog

    data = _fact_dir(tmp_path)
    s = TpuSparkSession({
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.exec.Filter": False,
        "spark.rapids.tpu.semaphore.atomicQueryGroups": False,
        "spark.rapids.tpu.sanitizer.enabled": True,
        # deterministic cycle formation: every grant keeps holding for
        # a beat (semaphore.partial_hold), so the two queries' partial
        # holds always overlap instead of depending on compile timing
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.sites": "semaphore.partial_hold:every=1",
    })
    try:
        results, errs = _concurrent_fallback_queries(s, data)
        for _i, e in errs:
            assert isinstance(e, DeadlockDetectedError), e
        assert len(results) + len(errs) == 2 and results
        snap = sanitizer.counters()
        assert snap["cycles"] >= 1 and snap["victims"] >= 1
        assert sem_mod.get().holders() == 0
        get_catalog().check_leaks(raise_on_leak=True)
    finally:
        s.stop()
        # disarm the process-wide chaos registry: session stop leaves
        # it installed, and a lingering partial_hold stalls every
        # later acquire in the suite
        faults.configure(None)


# ------------------------------------------------------ disabled mode

def test_disabled_mode_is_inert():
    """sanitizer.enabled=false: active() is None, counters stay a
    zero view, and the semaphore hot path records nothing."""
    sanitizer.install(None)  # a prior session may have configured one
    assert sanitizer.active() is None
    snap = sanitizer.counters()
    assert snap == {"cycles": 0, "inversions": 0, "victims": 0,
                    "enabled": False}
    semaphore = TpuSemaphore(concurrent_tasks=2)
    obs_events.begin_query(900)
    try:
        semaphore.acquire_if_necessary(1)
        semaphore.release_if_necessary(1)
    finally:
        obs_events.finish_query(900)
    # nothing was installed mid-flight by the instrumented paths
    assert sanitizer.active() is None


def test_disabled_mode_overhead_bounded():
    """The disabled hook is one global load + None check per acquire;
    guard the semaphore fast path against a sanitizer-shaped
    regression with a generous wall-clock bound."""
    semaphore = TpuSemaphore(concurrent_tasks=2)
    n = 2_000
    t0 = time.perf_counter()
    for i in range(n):
        semaphore.acquire_if_necessary(i % 2)
        semaphore.release_if_necessary(i % 2)
    dt = time.perf_counter() - t0
    assert dt < 2.0, f"{n} acquire/release pairs took {dt:.3f}s"


def test_configure_from_conf():
    from spark_rapids_tpu.config import rapids_conf as rc

    class FakeConf:
        def __init__(self, on, policy="oldest"):
            self._v = {rc.SANITIZER_ENABLED.key: on,
                       rc.SANITIZER_VICTIM_POLICY.key: policy}

        def get(self, entry):
            return self._v.get(entry.key, entry.default)

    try:
        assert sanitizer.configure(FakeConf(False)) is None
        assert sanitizer.active() is None
        san = sanitizer.configure(FakeConf(True, "oldest"))
        assert san is sanitizer.active()
        assert san.victim_policy == "oldest"
    finally:
        sanitizer.install(None)
