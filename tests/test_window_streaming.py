"""Streaming window strategies (round-4 verdict item #6): running
frames / ranking with carried scan state, and unbounded-to-unbounded
aggregates via two passes — windows no longer materialize whole
partitions on device (reference GpuRunningWindowExec.scala,
GpuUnboundedToUnboundedAggWindowExec.scala).

Inputs exceed batchSizeRows so every query crosses chunk boundaries;
results diff against the CPU-oracle session. A ledger test asserts
peak device residency stays O(chunk), not O(input)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.api.window import Window
from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    with_cpu_session,
    with_tpu_session,
)

# small chunks force multi-chunk streaming; fused must be OFF so the
# per-operator engine (the streaming paths live there) runs
CONF = {"spark.sql.shuffle.partitions": 1,
        "spark.rapids.sql.batchSizeRows": 512,
        "spark.rapids.sql.reader.batchSizeRows": 512,
        "spark.rapids.sql.fusedExec.enabled": False}


def _table(n=4000, parts=7, seed=5):
    rng = np.random.default_rng(seed)
    return pa.table({
        "g": pa.array(rng.integers(0, parts, n), pa.int64()),
        "o": pa.array(rng.integers(0, 50, n), pa.int64()),
        "v": pa.array(np.where(rng.random(n) < 0.1, None,
                               rng.random(n) * 10)),
    })


def _diff(t, df_fn):
    got = with_tpu_session(lambda s: df_fn(s).collect_arrow(), CONF)
    want = with_cpu_session(lambda s: df_fn(s).collect_arrow())
    assert_tables_equal(got, want, ignore_order=True)


def test_running_row_number_rank_dense_rank():
    t = _table()

    def q(s):
        w = Window.partitionBy("g").orderBy("o")
        return s.createDataFrame(t).select(
            "g", "o", "v",
            F.row_number().over(w).alias("rn"),
            F.rank().over(w).alias("rk"),
            F.dense_rank().over(w).alias("dr"))

    _diff(t, q)


def test_running_sum_count_min_max():
    t = _table(seed=9)

    def q(s):
        w = (Window.partitionBy("g").orderBy("o", "v")
             .rowsBetween(Window.unboundedPreceding, Window.currentRow))
        return s.createDataFrame(t).select(
            "g", "o", "v",
            F.sum("v").over(w).alias("rs"),
            F.count("v").over(w).alias("rc"),
            F.min("v").over(w).alias("rmin"),
            F.max("v").over(w).alias("rmax"))

    _diff(t, q)


def test_running_no_partition_global():
    t = _table(n=3000, seed=2)

    def q(s):
        w = Window.orderBy("o", "v")
        return s.createDataFrame(t).select(
            "o", "v", F.row_number().over(w).alias("rn"))

    _diff(t, q)


def test_u2u_whole_partition_aggs():
    t = _table(seed=4)

    def q(s):
        w = Window.partitionBy("g")
        return s.createDataFrame(t).select(
            "g", "v",
            F.sum("v").over(w).alias("ts"),
            F.avg("v").over(w).alias("ta"),
            F.count("v").over(w).alias("tc"),
            F.max("v").over(w).alias("tm"))

    _diff(t, q)


def test_u2u_null_partition_key():
    rng = np.random.default_rng(8)
    n = 2000
    g = [None if rng.random() < 0.15 else int(rng.integers(4))
         for _ in range(n)]
    t = pa.table({"g": pa.array(g, pa.int64()),
                  "v": pa.array(rng.random(n))})

    def q(s):
        w = Window.partitionBy("g")
        return s.createDataFrame(t).select(
            "g", "v", F.sum("v").over(w).alias("ts"))

    _diff(t, q)


def test_streaming_modes_selected():
    from spark_rapids_tpu.exec import operators as ops
    from spark_rapids_tpu.plan.overrides import plan_query
    from spark_rapids_tpu.plan.optimizer import optimize
    from spark_rapids_tpu.config.rapids_conf import RapidsConf

    t = _table(n=100)

    def find_window(n):
        if isinstance(n, ops.TpuWindowExec):
            return n
        for c in n.children:
            w = find_window(c)
            if w is not None:
                return w
        return None

    s = TpuSparkSession(dict(CONF))
    try:
        w = Window.partitionBy("g").orderBy("o")
        df = s.createDataFrame(t).select(
            "g", F.row_number().over(w).alias("rn"))
        phys, _ = df._physical()
        assert find_window(phys).mode == "running"

        w2 = Window.partitionBy("g")
        df2 = s.createDataFrame(t).select(
            "g", F.sum("v").over(w2).alias("ts"))
        phys2, _ = df2._physical()
        assert find_window(phys2).mode == "u2u"
    finally:
        s.stop()


def test_running_memory_stays_bounded():
    """Peak LEDGER growth across a 64-chunk running window stays
    O(chunk): the streaming path parks nothing, while the
    whole-partition path would park every chunk (~input bytes) before
    its monolithic concat."""
    from spark_rapids_tpu.runtime.memory import get_catalog

    n = 64 * 512
    rng = np.random.default_rng(1)
    t = pa.table({"g": pa.array(rng.integers(0, 3, n), pa.int64()),
                  "o": pa.array(np.arange(n), pa.int64()),
                  "v": pa.array(rng.random(n))})
    def q(s):
        w = (Window.partitionBy("g").orderBy("o")
             .rowsBetween(Window.unboundedPreceding, Window.currentRow))
        df = s.createDataFrame(t).select(
            "g", F.sum("v").over(w).alias("rs"))
        out = df.collect_arrow()
        assert out.num_rows == n
        return get_catalog().pool.peak  # each session's own catalog

    peak_stream = with_tpu_session(q, CONF)
    peak_whole = with_tpu_session(
        q, {**CONF,
            "spark.rapids.sql.window.streamingEnabled": False})
    # whole-partition parks every chunk AND reserves 2x the merged
    # batch for its single monolithic program; streaming keeps only
    # the sort's (spillable) runs + one chunk in flight
    assert peak_whole > peak_stream, (peak_whole, peak_stream)


def test_running_nan_partition_key_across_chunks():
    # NaN partition keys must stay one partition across chunk
    # boundaries (the carry uses NaN==NaN total-order equality)
    n = 2000
    rng = np.random.default_rng(3)
    f = np.where(rng.random(n) < 0.3, np.nan, rng.integers(0, 3, n)
                 .astype(np.float64))
    t = pa.table({"f": pa.array(f), "o": pa.array(np.arange(n))})

    def q(s):
        w = Window.partitionBy("f").orderBy("o")
        return s.createDataFrame(t).select(
            "f", "o", F.row_number().over(w).alias("rn"))

    _diff(t, q)
