"""Pandas UDF Arrow worker-process exchange tests
(GpuArrowEvalPythonExec role)."""

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import with_tpu_session

_CONF = {"spark.sql.shuffle.partitions": 2}


def _df(s, n=2000, seed=5):
    rng = np.random.default_rng(seed)
    return s.createDataFrame(pa.table({
        "a": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        "b": pa.array(rng.random(n) * 10, type=pa.float64()),
    }))


def test_pandas_udf_scalar():
    @F.pandas_udf(returnType="double")
    def plus_one(s):
        return s + 1.0

    def run(spark):
        df = _df(spark)
        return df.select(plus_one(df["b"]).alias("x")).collect_arrow()

    out = with_tpu_session(run, _CONF)
    want = with_tpu_session(
        lambda s: _df(s).select((F.col("b") + 1.0).alias("x"))
        .collect_arrow(), _CONF)
    got = np.asarray(out.column("x"))
    exp = np.asarray(want.column("x"))
    assert np.allclose(got, exp)


def test_pandas_udf_two_args_and_chunking():
    @F.pandas_udf(returnType="double")
    def mix(a, b):
        return a * 0.5 + b

    def run(spark):
        df = _df(spark, n=5000)
        return df.select(mix(df["a"], df["b"]).alias("x")) \
            .collect_arrow()

    out = with_tpu_session(run, _CONF)
    assert out.num_rows == 5000
    # spot check
    back = with_tpu_session(
        lambda s: _df(s, n=5000).select(
            (F.col("a") * 0.5 + F.col("b")).alias("x")).collect_arrow(),
        _CONF)
    assert np.allclose(np.asarray(out.column("x")),
                       np.asarray(back.column("x")))


def test_pandas_udf_plans_host_exchange():
    """The planner routes pandas-UDF projections to the host path with
    the exchange reason."""

    @F.pandas_udf(returnType="long")
    def f(a):
        return a * 2

    def run(spark):
        df = _df(spark, n=100)
        df2 = df.select(f(df["a"]).alias("x"))
        phys, meta = df2._physical()
        return type(phys).__name__, meta.explain(only_not_on_device=True)

    name, explain = with_tpu_session(run, _CONF)
    assert name == "CpuProjectExec", name
    assert "Arrow worker-process exchange" in explain


def test_pandas_udf_runs_in_worker_process():
    import os

    parent = os.getpid()

    @F.pandas_udf(returnType="long")
    def pid_probe(a):
        import os as _os

        import pandas as pd

        return pd.Series([_os.getpid()] * len(a))

    def run(spark):
        df = _df(spark, n=10)
        return df.select(pid_probe(df["a"]).alias("p")).collect_arrow()

    out = with_tpu_session(run, _CONF)
    pids = set(out.column("p").to_pylist())
    assert pids and parent not in pids, (parent, pids)
