"""Regex transpiler + device DFA tests (the RegexParser.scala test family
analog): transpiled-DFA vs Python `re` oracle over pattern batteries,
device rlike differential tests, and clean CPU fallback for
untranspilable patterns / capture-group functions.
"""

import re

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.regex import RegexUnsupported, compile_search
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    assert_tpu_fallback_collect,
)

PATTERNS = [
    r"abc",
    r"a.c",
    r"^abc",
    r"abc$",
    r"^abc$",
    r"a*b",
    r"a+b+",
    r"ab?c",
    r"a{2,4}",
    r"a{3}",
    r"a{2,}b",
    r"[abc]+",
    r"[a-f0-9]{2}",
    r"[^0-9]+$",
    r"\d+",
    r"\w+@\w+",
    r"\s",
    r"(ab|cd)+e",
    r"(?:foo|bar|baz)",
    r"x|y|z",
    r"colou?r",
    r"^$",
    r"a|",
    r"\.com$",
    r"ERROR|WARN(ING)?",
    r"[A-Z][a-z]*",
]


def _corpus(rng, n=300):
    alphabet = "abcdefxyz0123456789 .@ABCDE-_|"
    out = []
    for i in range(n):
        ln = int(rng.integers(0, 16))
        out.append("".join(rng.choice(list(alphabet), ln)))
    out += ["", "abc", "aabbcc", "aaaab", "colour", "color",
            "foo@bar", "ERROR", "WARNING", "x", "ab cd e", "abcabc",
            "aaa", "AbcDef", "12.com", "no match here!",
            # `$` matches before one final newline (Java Matcher / re)
            "abc\n", "abc\n\n", "\n", "12.com\n", "abc\ndef"]
    return out


@pytest.mark.parametrize("pattern", PATTERNS)
def test_transpiled_dfa_matches_re(pattern):
    rng = np.random.default_rng(hash(pattern) % 2**31)
    rx = compile_search(pattern)
    prx = re.compile(pattern)
    for s in _corpus(rng):
        want = prx.search(s) is not None
        got = rx.match_host(s.encode())
        assert got == want, (pattern, s, got, want)


@pytest.mark.parametrize("pattern", [r"(a)\1", r"a{100}", r"\bword",
                                     r"(?=look)", r"[À-Ý]", r"\xzz"])
def test_unsupported_patterns_raise(pattern):
    """Untranspilable shapes (backreferences, word boundaries,
    lookaround, non-ASCII ranges — which would silently mis-match —
    and over-bound repeats) raise for CPU fallback. Per-branch anchors
    ("^a|b") are SUPPORTED since round 5 and covered in
    TestDialectBreadth."""
    with pytest.raises(RegexUnsupported):
        compile_search(pattern)


def test_regexp_replace_java_group_refs():
    """Java $N group references in the replacement string."""

    def q(s):
        df = s.createDataFrame({"s": ["a-b", "c-d", "nodash"]})
        return df.select(
            F.regexp_replace(df["s"], r"(\w)-(\w)", "$2_$1")
            .alias("swapped"))

    import pyarrow as pa

    from spark_rapids_tpu.testing.asserts import with_tpu_session

    out = with_tpu_session(lambda s: q(s).collect_arrow())
    assert out.column("swapped").to_pylist() == ["b_a", "d_c", "nodash"]


def test_device_dfa_kernel():
    import jax

    from spark_rapids_tpu.columnar import arrow_to_device
    from spark_rapids_tpu.ops import regexops

    import pyarrow as pa

    vals = ["hello42", "world", "h4x0r", "", "42", "no digits!",
            None, "tail9"]
    t = pa.table({"s": pa.array(vals, type=pa.string())})
    batch = arrow_to_device(t)
    rx = compile_search(r"\d+")
    m = jax.jit(lambda c: regexops.dfa_match(c.data, c.lengths, rx))(
        batch.columns[0])
    got = np.asarray(m)[:batch.row_count()]
    want = [s is not None and re.search(r"\d+", s) is not None
            for s in vals]
    got_masked = [bool(g) and v is not None for g, v in zip(got, vals)]
    assert got_masked == want


@pytest.mark.parametrize("pattern", [r"^name[0-4]$", r"\d{2,}",
                                     r"(?:ab|cd)+", r"e$"])
def test_rlike_query_differential(pattern):
    def q(s):
        df = s.createDataFrame({
            "s": [f"name{i % 7}" if i % 3 else f"v{i}{'ab' * (i % 4)}e"
                  for i in range(100)],
        })
        return df.withColumn("m", df["s"].rlike(pattern))

    assert_tpu_and_cpu_are_equal_collect(q)


def test_rlike_filter_on_device():
    def q(s):
        df = s.createDataFrame({
            "s": [f"id-{i:03d}" if i % 2 else f"x{i}" for i in range(60)],
            "v": list(range(60)),
        })
        return df.filter(df["s"].rlike(r"^id-\d+$")).select("s", "v")

    assert_tpu_and_cpu_are_equal_collect(q)


def test_rlike_unsupported_falls_back():
    """Backreference: untranspilable -> operator runs on CPU, result
    still correct (the reference's fallback tagging path)."""

    def q(s):
        df = s.createDataFrame({
            "s": ["abab", "abcd", "aa", "ab", "xyxy"],
        })
        return df.withColumn("m", df["s"].rlike(r"(ab)\1"))

    assert_tpu_fallback_collect(q, "CpuProjectExec")


def test_regexp_extract_replace_fallback():
    def q(s):
        df = s.createDataFrame({
            "s": [f"user{i}@host{i % 3}.com" for i in range(20)],
        })
        return df.select(
            F.regexp_extract(df["s"], r"(\w+)@", 1).alias("user"),
            F.regexp_replace(df["s"], r"@host\d", "@example")
            .alias("fixed"))

    assert_tpu_and_cpu_are_equal_collect(q)


def test_rlike_with_nulls():
    import pyarrow as pa

    def q(s):
        df = s.createDataFrame(pa.table({
            "s": pa.array(["abc", None, "def", None, "abcdef"],
                          type=pa.string()),
        }))
        return df.withColumn("m", df["s"].rlike("abc"))

    assert_tpu_and_cpu_are_equal_collect(q)


# ------------------------- round-5 dialect breadth (verdict item #10)

class TestDialectBreadth:
    """Per-branch anchors (Java binding), class intersection/nested
    union, octal/unicode/control escapes, and the complexity estimator
    (RegexParser.scala + RegexComplexityEstimator.scala roles)."""

    CASES = [
        ("^a|b", ["abc", "xb", "xa", "ba", "zzz"], None),
        ("^foo$|bar", ["foo", "foox", "xbar", "foobar", ""], None),
        ("a$|^b", ["xa", "ax", "bx", "xb", "a", "b"], None),
        ("[a-z&&[^aeiou]]+", ["xyz", "aei", "bcd", "a"],
         "[b-df-hj-np-tv-z]+"),
        ("[a-c[x-z]]+", ["ax", "m", "byz"], "[a-cx-z]+"),
        ("\\07", ["\x07", "7", ""], "\\x07"),
        ("\\013", ["\x0b", "13"], "\\x0b"),
        ("\\u0041+", ["AAA", "B"], "A+"),
        ("\\cA", ["\x01", "A"], "\\x01"),
        ("^a$|^$", ["a", "", "b", "aa"], None),
    ]

    def test_host_oracle(self):
        import re

        from spark_rapids_tpu.regex.transpiler import compile_search

        for pat, inputs, oracle in self.CASES:
            c = compile_search(pat)
            for s in inputs:
                got = c.match_host(s.encode())
                want = re.search(oracle or pat, s) is not None
                assert got == want, (pat, s, got, want)

    def test_control_escape_lowercase_java_semantics(self):
        """Java's \\cX is `read() ^ 64` on the RAW character — no
        uppercasing. \\cj is 0x6A ^ 0x40 = 0x2A ('*'), NOT Ctrl-J
        (0x0A): uppercasing first would alias \\cj to \\cJ and match
        newlines. Checked against java.util.regex behavior."""
        from spark_rapids_tpu.regex.transpiler import compile_search

        c = compile_search("\\cj")
        assert c.match_host(b"*")          # 0x2A, the Java match
        assert not c.match_host(b"\n")     # Ctrl-J would be the bug
        assert not c.match_host(b"j")
        # uppercase stays a control char: \cJ -> 0x4A ^ 0x40 = 0x0A
        cj = compile_search("\\cJ")
        assert cj.match_host(b"\n")
        assert not cj.match_host(b"*")

    def test_control_escape_accepts_any_char(self):
        """Java accepts ANY character after \\c (e.g. \\c1 -> 0x71
        'q'); rejecting non-alpha crashed Spark-valid patterns."""
        from spark_rapids_tpu.regex.transpiler import compile_search

        c = compile_search("\\c1")  # 0x31 ^ 0x40 = 0x71
        assert c.match_host(b"q")
        assert not c.match_host(b"1")

    def test_python_invalid_pattern_clean_error_on_cpu_eval(self):
        """A Java-valid pattern Python re rejects must produce a clean
        unsupported-pattern error from the CPU evaluator (regexp_
        extract has no DFA path), not a raw re.error traceback."""
        import pyarrow as pa

        from spark_rapids_tpu.regex.transpiler import RegexUnsupported
        from spark_rapids_tpu.testing.asserts import with_tpu_session

        def q(spark):
            t = pa.table({"s": pa.array(["q1", "x"])})
            return (spark.createDataFrame(t)
                    .select(F.regexp_extract("s", "(\\c1)\\d", 1)
                            .alias("e"))
                    .collect_arrow())

        with pytest.raises(RegexUnsupported, match="Python re"):
            with_tpu_session(q)

    def test_complexity_estimator_gates_before_build(self):
        from spark_rapids_tpu.regex.transpiler import (
            RegexUnsupported,
            compile_search,
        )

        with pytest.raises(RegexUnsupported, match="complexity gate"):
            compile_search("(a{50}){50}")

    def test_rlike_per_branch_anchor_vs_cpu(self):
        import pyarrow as pa

        from spark_rapids_tpu.testing.asserts import (
            assert_tpu_and_cpu_are_equal_collect,
        )

        t = pa.table({"s": pa.array(
            ["abc", "xb", "xa", "ba", "zzz", "", "b"])})
        assert_tpu_and_cpu_are_equal_collect(
            lambda spark: spark.createDataFrame(t).select(
                "s", F.col("s").rlike("^a|b").alias("m")))

    def test_rlike_class_intersection_vs_cpu_fallbackless(self):
        # python re (the oracle) has no '&&'; diff against the host
        # reference implementation instead
        from spark_rapids_tpu.regex.transpiler import compile_search

        vals = ["xyz", "aeiou", "bcdf", "a1b", ""]
        c = compile_search("[a-z&&[^aeiou]]+")
        want = [bool(__import__("re").search("[b-df-hj-np-tv-z]+", v))
                for v in vals]
        got = [c.match_host(v.encode()) for v in vals]
        assert got == want
