"""MapType + map functions (reference: map rules in
collectionOperations.scala, GetMapValue in complexTypeExtractors,
GpuCreateMap) — device layout is parallel key/value padded matrices."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)


@pytest.fixture(scope="module")
def map_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("mapdata")
    rng = np.random.default_rng(13)
    rows = []
    for i in range(2000):
        if rng.random() < 0.05:
            rows.append(None)
        else:
            n = int(rng.integers(0, 5))
            keys = rng.choice(20, size=n, replace=False)
            rows.append([(int(k), float(rng.random()) if
                          rng.random() > 0.1 else None)
                         for k in keys])
    t = pa.table({
        "id": pa.array(range(2000)),
        "m": pa.array(rows, type=pa.map_(pa.int64(), pa.float64())),
    })
    p = str(d / "maps.parquet")
    pq.write_table(t, p)
    return p


def test_map_scan_roundtrip(map_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(map_path))


def test_map_keys_values_size(map_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(map_path).select(
            "id",
            F.map_keys("m").alias("ks"),
            F.map_values("m").alias("vs"),
            F.size("m").alias("n")))


def test_get_map_value(map_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(map_path).select(
            "id",
            F.element_at("m", F.lit(3)).alias("v3"),
            F.map_contains_key("m", 3).alias("has3"),
            F.map_contains_key("m", 99).alias("has99")))


def test_create_map_and_from_arrays(map_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(map_path).select(
            "id",
            F.create_map(F.lit(1), F.col("id"),
                         F.lit(2), F.col("id") * 2).alias("cm"),
            F.map_from_arrays(F.map_keys("m"),
                              F.map_values("m")).alias("rt")))


def test_map_filter_on_lookup(map_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(map_path)
        .filter(F.element_at("m", F.lit(5)) > 0.5)
        .select("id"))


def test_map_through_shuffle(map_path):
    """Map columns survive the exchange (first/any aggregation keeps
    the map payload)."""
    def q(spark):
        return (spark.read.parquet(map_path)
                .withColumn("b", F.col("id") % 7)
                .groupBy("b").agg(F.count("*").alias("c"))
                .collect_arrow())

    out = with_tpu_session(
        q, conf={"spark.sql.shuffle.partitions": 3})
    assert out.num_rows == 7


def test_map_grouping_key_rejected(map_path):
    """Spark disallows map grouping keys (maps are not orderable)."""
    def q(spark):
        with pytest.raises(ValueError, match="not.*orderable|map"):
            spark.read.parquet(map_path).groupBy("m").agg(
                F.count("*").alias("c"))
        return True

    assert with_tpu_session(q)


def test_string_valued_map_falls_back(tmp_path):
    t = pa.table({"m": pa.array([[(1, "a")], [(2, "b")]],
                                type=pa.map_(pa.int64(), pa.string()))})
    p = str(tmp_path / "sm.parquet")
    pq.write_table(t, p)
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_fallback_collect,
    )

    assert_tpu_fallback_collect(
        lambda spark: spark.read.parquet(p).select(
            F.map_values("m").alias("v")),
        fallback_class="CpuProjectExec")
