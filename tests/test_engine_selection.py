"""Engine-selection observability (round-4 verdict items #2/#3):
every query records WHICH engine ran it (mesh / fused / aqe / eager /
hostCache) and why faster engines fell back, surfaced through
`session.last_execution`, `session.query_metrics`, and `explain()` —
the whole-query analog of the reference's NOT_ON_GPU diagnostics
discipline (GpuOverrides.scala:4763-4772).

Also covers ANSI mode running INSIDE the fused engine (verdict item
#2): the per-error-class masks of expr/ansicheck.py ride the fused
executor's overflow-flag channel, so ANSI no longer forces the
dispatch-bound eager path."""

import io
import contextlib

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime.errors import (
    TpuArithmeticOverflow,
    TpuDivideByZero,
)

I64MAX = (1 << 63) - 1


@pytest.fixture()
def spark():
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 4})
    yield s
    s.stop()


def _df(s, **cols):
    return s.createDataFrame(pa.table(
        {k: pa.array(v) for k, v in cols.items()}))


def test_fused_engine_recorded(spark):
    df = _df(spark, a=[1, 2, 3, 4], b=[1.0, 2.0, 3.0, 4.0]) \
        .filter(F.col("a") > 1) \
        .groupBy("a").agg(F.sum("b").alias("s"))
    df.collect_arrow()
    assert spark.last_execution["engine"] == "fused"
    assert spark.query_metrics.metric("engine.fused").value >= 1


def test_fallback_reason_recorded_and_in_explain(spark):
    # Sample has no fused lowering
    df = _df(spark, a=[1, 1, 2, 2], v=[1.0, 2.0, 3.0, 4.0]) \
        .sample(fraction=0.9, seed=7) \
        .groupBy("a").agg(F.sum("v").alias("s"))
    df.collect_arrow()
    rec = spark.last_execution
    assert rec["engine"] in ("eager", "aqe")
    engines = [e for e, _ in rec["fallbacks"]]
    assert "fused" in engines
    reason = dict(rec["fallbacks"])["fused"]
    assert reason  # non-empty human-readable reason
    assert spark.query_metrics.metric("engineFallback.fused").value >= 1
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        df.explain()
    text = out.getvalue()
    assert "== Engine ==" in text
    assert "fell back from fused" in text
    assert reason in text


def test_host_cache_engine_recorded(spark):
    df = _df(spark, a=[1, 2, 3])
    df.cache()
    df.collect_arrow()
    df.collect_arrow()
    assert spark.last_execution["engine"] == "hostCache"


# ------------------------------------------------ ANSI inside fused

ANSI_FUSED = {"spark.sql.ansi.enabled": True,
              "spark.rapids.sql.fusedExec.enabled": True}


def _ansi_spark():
    return TpuSparkSession(dict(ANSI_FUSED))


def test_ansi_clean_query_runs_fused():
    s = _ansi_spark()
    try:
        df = _df(s, a=[1, 2, 3, 4], b=[2, 2, 2, 2]) \
            .select((F.col("a") + F.col("b")).alias("r"),
                    (F.col("a") / F.col("b")).alias("q"))
        out = df.collect_arrow()
        assert s.last_execution["engine"] == "fused", \
            s.last_execution
        assert out.column("r").to_pylist() == [3, 4, 5, 6]
    finally:
        s.stop()


def test_ansi_overflow_raises_from_fused():
    s = _ansi_spark()
    try:
        df = _df(s, a=[1, I64MAX], b=[2, 5]) \
            .select((F.col("a") + F.col("b")).alias("r"))
        with pytest.raises(TpuArithmeticOverflow):
            df.collect_arrow()
        # the failure came from the fused engine, not a fallback
        assert s.last_execution["fallbacks"] == [], s.last_execution
    finally:
        s.stop()


def test_ansi_div_by_zero_raises_from_fused():
    s = _ansi_spark()
    try:
        df = _df(s, a=[10, 20], b=[2, 0]) \
            .select((F.col("a") / F.col("b")).alias("q"))
        with pytest.raises(TpuDivideByZero):
            df.collect_arrow()
        assert s.last_execution["fallbacks"] == [], s.last_execution
    finally:
        s.stop()


def test_ansi_filtered_rows_do_not_raise_fused():
    # rows removed by the pending filter mask must not trip ANSI —
    # same visibility the eager engine gets by compacting first
    s = _ansi_spark()
    try:
        df = _df(s, a=[1, I64MAX], b=[2, 5]) \
            .filter(F.col("a") < 100) \
            .select((F.col("a") + F.col("b")).alias("r"))
        out = df.collect_arrow()
        assert s.last_execution["engine"] == "fused"
        assert out.column("r").to_pylist() == [3]
    finally:
        s.stop()


def test_ansi_groupby_overflow_raises_fused():
    s = _ansi_spark()
    try:
        df = _df(s, k=[1, 1, 2, 2], a=[1, I64MAX, 3, 4], b=[2, 5, 1, 1]) \
            .groupBy("k").agg(F.sum(F.col("a") * F.col("b")).alias("s"))
        with pytest.raises(TpuArithmeticOverflow):
            df.collect_arrow()
    finally:
        s.stop()
