"""ANSI mode (spark.sql.ansi.enabled): device-side overflow checks for
cast + add/subtract/multiply/negate and divide-by-zero, with error
surfacing matching the CPU oracle (round-4 verdict item #5; reference
GpuCast.scala ANSI paths + arithmetic.scala overflow checks).

The differential contract: for each failing input the ORACLE raises and
the DEVICE raises the SAME error class (TpuAnsiError taxonomy); for
non-failing inputs both produce identical results with the expressions
still placed on device."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.runtime.errors import (
    TpuAnsiError,
    TpuArithmeticOverflow,
    TpuCastError,
    TpuDivideByZero,
)
from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    with_cpu_session,
    with_tpu_session,
)

ANSI = {"spark.sql.ansi.enabled": True}

I64MAX = (1 << 63) - 1
I64MIN = -(1 << 63)


def _both_raise(df_fn, klass):
    with pytest.raises(klass):
        with_cpu_session(lambda s: df_fn(s).collect_arrow(), ANSI)
    with pytest.raises(klass):
        with_tpu_session(lambda s: df_fn(s).collect_arrow(), ANSI)


def _tbl(s, **cols):
    return s.createDataFrame(pa.table(
        {k: pa.array(v) for k, v in cols.items()}))


def test_add_overflow_both_raise():
    _both_raise(
        lambda s: _tbl(s, a=[1, I64MAX], b=[2, 5]).select(
            (F.col("a") + F.col("b")).alias("r")),
        TpuArithmeticOverflow)


def test_subtract_overflow_both_raise():
    _both_raise(
        lambda s: _tbl(s, a=[0, I64MIN], b=[1, 1]).select(
            (F.col("a") - F.col("b")).alias("r")),
        TpuArithmeticOverflow)


def test_multiply_overflow_both_raise():
    _both_raise(
        lambda s: _tbl(s, a=[3, 1 << 40], b=[4, 1 << 40]).select(
            (F.col("a") * F.col("b")).alias("r")),
        TpuArithmeticOverflow)


def test_divide_by_zero_both_raise():
    _both_raise(
        lambda s: _tbl(s, a=[1.0, 2.0], b=[4.0, 0.0]).select(
            (F.col("a") / F.col("b")).alias("r")),
        TpuDivideByZero)


def test_cast_long_to_int_overflow_both_raise():
    from spark_rapids_tpu.sqltypes.datatypes import integer

    _both_raise(
        lambda s: _tbl(s, a=[5, 1 << 40]).select(
            F.col("a").cast(integer).alias("r")),
        TpuCastError)


def test_cast_double_to_long_overflow_both_raise():
    from spark_rapids_tpu.sqltypes.datatypes import long

    _both_raise(
        lambda s: _tbl(s, a=[1.5, 1e20]).select(
            F.col("a").cast(long).alias("r")),
        TpuCastError)


def test_string_cast_invalid_still_raises_on_cpu_path():
    from spark_rapids_tpu.sqltypes.datatypes import integer

    _both_raise(
        lambda s: _tbl(s, a=["12", "xyz"]).select(
            F.col("a").cast(integer).alias("r")),
        TpuAnsiError)


def test_agg_input_overflow_both_raise():
    _both_raise(
        lambda s: _tbl(s, k=[1, 1], a=[I64MAX, 1]).groupBy("k").agg(
            F.sum((F.col("a") + F.col("a")).alias("x")).alias("r")),
        TpuArithmeticOverflow)


def test_filter_condition_overflow_both_raise():
    _both_raise(
        lambda s: _tbl(s, a=[1, I64MAX]).filter(
            (F.col("a") + 1) > 0),
        TpuArithmeticOverflow)


def test_nulls_do_not_raise_and_results_match():
    def q(s):
        t = pa.table({
            "a": pa.array([1, None, 5], type=pa.int64()),
            "b": pa.array([2, 7, None], type=pa.int64())})
        return s.createDataFrame(t).select(
            (F.col("a") + F.col("b")).alias("add"),
            (F.col("a") * F.col("b")).alias("mul"))

    got = with_tpu_session(lambda s: q(s).collect_arrow(), ANSI)
    want = with_cpu_session(lambda s: q(s).collect_arrow(), ANSI)
    assert_tables_equal(got, want)


def test_numeric_cast_stays_on_device_under_ansi():
    """The plan keeps device placement for checked casts (the old
    behavior sent every failable cast to CPU under ANSI)."""
    from spark_rapids_tpu.sqltypes.datatypes import integer

    def explain(s):
        df = _tbl(s, a=[1, 2]).select(F.col("a").cast(integer).alias("r"))
        return s.explainPotentialTpuPlan(df)

    txt = with_tpu_session(explain, ANSI)
    assert "runs on CPU" not in txt, txt
