"""Array-function breadth v2 (reference collectionOperations.scala:
slice, array_position/remove/distinct, reverse, exists/forall, set
operations, concat, arrays_overlap) + approx_count_distinct."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)


@pytest.fixture(scope="module")
def arr_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("arrdata")
    rng = np.random.default_rng(17)
    rows_a, rows_b = [], []
    for i in range(1500):
        if rng.random() < 0.05:
            rows_a.append(None)
        else:
            n = int(rng.integers(0, 6))
            rows_a.append([int(x) if rng.random() > 0.1 else None
                           for x in rng.integers(0, 8, n)])
        rows_b.append([int(x) for x in
                       rng.integers(0, 8, rng.integers(0, 4))])
    t = pa.table({
        "id": pa.array(range(1500)),
        "a": pa.array(rows_a, type=pa.list_(pa.int64())),
        "b": pa.array(rows_b, type=pa.list_(pa.int64())),
        "s": pa.array([f"str{i % 37}" for i in range(1500)]),
    })
    p = str(d / "arr.parquet")
    pq.write_table(t, p)
    return p


def test_slice_and_position(arr_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(arr_path).select(
            "id",
            F.slice("a", 2, 2).alias("sl"),
            F.slice("a", -2, 3).alias("slneg"),
            F.array_position("a", 3).alias("p3")))


def test_remove_distinct_reverse(arr_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(arr_path).select(
            "id",
            F.array_remove("a", 2).alias("rm"),
            F.array_distinct("a").alias("dd"),
            F.reverse("a").alias("rv"),
            F.reverse("s").alias("rs")))


def test_set_operations(arr_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(arr_path).select(
            "id",
            F.array_union("a", "b").alias("u"),
            F.array_intersect("a", "b").alias("i"),
            F.array_except("a", "b").alias("x"),
            F.arrays_overlap("a", "b").alias("o"),
            F.concat_arrays("a", "b").alias("c")))


def test_exists_forall(arr_path):
    """Higher-order predicates are device-evaluated (no CPU lambda
    oracle); verify against python semantics."""
    def q(spark):
        return (spark.read.parquet(arr_path).select(
            "id",
            F.exists("a", lambda x: x > 5).alias("ex"),
            F.forall("a", lambda x: x >= 0).alias("fa"))
            .collect_arrow().to_pandas())

    out = with_tpu_session(q)
    src = pq.read_table(arr_path).column("a").to_pylist()
    for i, a in enumerate(src[:400]):
        if a is None:
            got0 = out.ex[i]
            assert got0 is None or (
                not isinstance(got0, (bool, np.bool_))
                and np.isnan(got0)), (i, got0)
            continue
        vals = [x for x in a if x is not None]
        has_null = any(x is None for x in a)
        want_ex = (True if any(x > 5 for x in vals)
                   else (None if has_null else False))
        got = out.ex[i]
        if want_ex is None:
            assert got is None or (not isinstance(
                got, (bool, np.bool_)) and np.isnan(got))
        else:
            assert bool(got) == want_ex, (i, a, got, want_ex)


def test_approx_count_distinct(arr_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(arr_path)
        .withColumn("g", F.col("id") % 4)
        .groupBy("g").agg(F.approx_count_distinct("s").alias("d")))


def test_reverse_is_character_aware():
    """F.reverse on strings must reverse CODEPOINTS, not UTF-8 bytes
    (regression: collections.Reverse shadowed StringReverse)."""
    t = pa.table({"s": pa.array(["café", "日本語", "ab"])})

    def q(spark):
        return (spark.createDataFrame(t)
                .select(F.reverse("s").alias("r")).collect_arrow())

    out = with_tpu_session(q)
    assert out.column("r").to_pylist() == ["éfac", "語本日", "ba"]


def test_exists_decides_on_null_element():
    """exists(a, x -> isnull(x)) decides TRUE on a null entry."""
    t = pa.table({"a": pa.array([[1, None], [1, 2], []],
                                type=pa.list_(pa.int64()))})

    def q(spark):
        return (spark.createDataFrame(t)
                .select(F.exists("a", lambda x: x.isNull()).alias("e"))
                .collect_arrow())

    out = with_tpu_session(q)
    assert out.column("e").to_pylist() == [True, False, False]


# ------------------------------------------------ array<string> on device

@pytest.fixture()
def spark():
    from spark_rapids_tpu.api.session import TpuSparkSession

    s = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    yield s
    s.stop()


class TestArrayOfString:
    """array<string> rides the string padded-matrix layout one level up
    (round-4 verdict item #5): data [cap, max_elems, max_bytes] uint8
    with per-element byte lengths (DeviceColumn.elem_lengths) — filter,
    explode, getItem, element_at, contains, shuffle, and sort all run
    on device with no CPU fallback (reference collectionOperations.scala
    handles list<string> natively in cuDF)."""

    ROWS = [["a", "bb", None], None, ["ccc"], [], ["a", "dddd"],
            ["bb", "bb"], ["", "a"]]

    def _df(self, spark):
        t = pa.table({
            "id": pa.array(range(len(self.ROWS)), pa.int64()),
            "tags": pa.array(self.ROWS, type=pa.list_(pa.string()))})
        return spark.createDataFrame(t)

    def test_round_trip_and_sort(self, spark):
        df = self._df(spark)
        out = df.orderBy("id").collect_arrow()
        assert out["tags"].to_pylist() == self.ROWS

    def test_explode_groupby_string_on_device(self, spark):
        df = self._df(spark)
        out = (df.filter(F.size(F.col("tags")) > 0)
               .select(F.explode(F.col("tags")).alias("tag"))
               .groupBy("tag").agg(F.count("*").alias("c"))
               .collect_arrow())
        got = dict(zip(out["tag"].to_pylist(), out["c"].to_pylist()))
        assert got == {"a": 3, "bb": 3, "ccc": 1, "dddd": 1, None: 1,
                       "": 1}, got
        assert spark.last_execution["engine"] == "fused"

    def test_get_item_element_at(self, spark):
        df = self._df(spark)
        out = df.select(
            F.col("tags").getItem(0).alias("t0"),
            F.element_at(F.col("tags"), F.lit(-1)).alias("last"),
        ).collect_arrow()
        assert out["t0"].to_pylist() == \
            ["a", None, "ccc", None, "a", "bb", ""]
        assert out["last"].to_pylist() == \
            [None, None, "ccc", None, "dddd", "bb", "a"]

    def test_array_contains_string(self, spark):
        df = self._df(spark)
        out = df.select(F.array_contains(
            F.col("tags"), F.lit("bb")).alias("has")).collect_arrow()
        # Spark: null if no hit AND the array has a null element
        assert out["has"].to_pylist() == \
            [True, None, False, False, False, True, False]

    def test_shuffle_round_trip(self, spark):
        df = self._df(spark)
        out = df.repartition(3, "id").collect_arrow()
        got = sorted(zip(out["id"].to_pylist(),
                         [tuple(x) if x is not None else None
                          for x in out["tags"].to_pylist()]))
        want = sorted(zip(range(len(self.ROWS)),
                          [tuple(x) if x is not None else None
                           for x in self.ROWS]))
        assert got == want

    def test_parquet_scan(self, spark, tmp_path):
        import pyarrow.parquet as pq

        t = pa.table({
            "id": pa.array(range(len(self.ROWS)), pa.int64()),
            "tags": pa.array(self.ROWS, type=pa.list_(pa.string()))})
        p = str(tmp_path / "astr.parquet")
        pq.write_table(t, p)
        out = spark.read.parquet(p).orderBy("id").collect_arrow()
        assert out["tags"].to_pylist() == self.ROWS

    def test_out_of_core_sort_payload(self):
        # multiple sorted runs force the merge path (sortops merge_col
        # scatters every leaf of the cube)
        from spark_rapids_tpu.api.session import TpuSparkSession

        n = 3000
        rng = np.random.default_rng(21)
        rows = [None if rng.random() < 0.05 else
                [f"w{int(x)}" for x in
                 rng.integers(0, 30, rng.integers(0, 4))]
                for _ in range(n)]
        keys = rng.permutation(n)
        t = pa.table({"k": pa.array(keys, pa.int64()),
                      "tags": pa.array(rows, type=pa.list_(pa.string()))})
        s = TpuSparkSession({"spark.sql.shuffle.partitions": 1,
                             "spark.rapids.sql.batchSizeRows": 256,
                             "spark.rapids.sql.fusedExec.enabled": False})
        try:
            out = s.createDataFrame(t).orderBy("k").collect_arrow()
            order = np.argsort(keys, kind="stable")
            assert out["tags"].to_pylist() == [rows[i] for i in order]
        finally:
            s.stop()

    def test_mesh_payload(self):
        from spark_rapids_tpu.testing.asserts import (
            assert_tables_equal, with_cpu_session, with_tpu_session)

        t = pa.table({
            "id": pa.array(range(len(self.ROWS)), pa.int64()),
            "tags": pa.array(self.ROWS, type=pa.list_(pa.string()))})

        def q(s):
            return (s.createDataFrame(t).repartition(4, "id")
                    .filter(F.size(F.col("tags")) >= 0))

        got = with_tpu_session(
            lambda s: q(s).collect_arrow(),
            {"spark.rapids.tpu.mesh": 8,
             "spark.sql.shuffle.partitions": 4})
        want = with_cpu_session(lambda s: q(s).collect_arrow())
        assert_tables_equal(got, want, ignore_order=True)

    def test_conditional_select(self, spark):
        df = self._df(spark)
        out = df.select(
            F.when(F.col("id") % 2 == 0, F.col("tags"))
            .otherwise(F.col("tags")).alias("t2"),
            F.coalesce(F.col("tags"), F.col("tags")).alias("t3"),
        ).collect_arrow()
        assert out["t2"].to_pylist() == self.ROWS
        exp = [r if r is not None else None for r in self.ROWS]
        assert out["t3"].to_pylist() == exp

    def test_lead_lag_payload(self, spark):
        from spark_rapids_tpu.api.window import Window

        df = self._df(spark)
        w = Window.orderBy("id")
        out = (df.select("id",
                         F.lag(F.col("tags"), 1).over(w).alias("prev"))
               .orderBy("id").collect_arrow())
        assert out["prev"].to_pylist() == [None] + self.ROWS[:-1]

    def test_case_when_no_else(self, spark):
        df = self._df(spark)
        out = df.select(
            F.when(F.col("id") < 3, F.col("tags")).alias("w")
        ).collect_arrow()
        assert out["w"].to_pylist() == self.ROWS[:3] + [None] * 4

    def test_left_join_null_side_payload(self, spark):
        # outer join null-fill builds an empty array<string> column
        lt = pa.table({"j": pa.array([0, 9], pa.int64())})
        df = self._df(spark).withColumnRenamed("id", "j")
        out = (spark.createDataFrame(lt).join(df, on="j", how="left")
               .select("j", "tags").collect_arrow())
        got = dict(zip(out["j"].to_pylist(), out["tags"].to_pylist()))
        assert got == {0: self.ROWS[0], 9: None}, got

    def test_window_first_over_cube_falls_back(self, spark):
        from spark_rapids_tpu.api.window import Window

        df = self._df(spark)
        w = Window.orderBy("id")
        out = (df.select("id", F.first(F.col("tags")).over(w).alias("f"))
               .orderBy("id").collect_arrow())
        assert out["f"].to_pylist() == [self.ROWS[0]] * len(self.ROWS)

    def test_array_string_literal_falls_back(self, spark):
        # Literal.eval builds flat columns only; an array<string>
        # literal must keep the plan on CPU, not crash
        df = self._df(spark)
        out = df.select(
            F.when(F.col("id") < 2, F.col("tags"))
            .otherwise(F.lit(["z"])).alias("w")).collect_arrow()
        assert out["w"].to_pylist() == self.ROWS[:2] + [["z"]] * 5

    def test_array_int_literal_falls_back(self, spark):
        t = pa.table({"id": pa.array([0, 1, 2], pa.int64()),
                      "arr": pa.array([[1], [2, 2], None],
                                      type=pa.list_(pa.int64()))})
        out = (spark.createDataFrame(t).select(
            F.when(F.col("id") < 2, F.col("arr"))
            .otherwise(F.lit([9, 9])).alias("w")).collect_arrow())
        assert out["w"].to_pylist() == [[1], [2, 2], [9, 9]]
