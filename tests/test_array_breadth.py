"""Array-function breadth v2 (reference collectionOperations.scala:
slice, array_position/remove/distinct, reverse, exists/forall, set
operations, concat, arrays_overlap) + approx_count_distinct."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)


@pytest.fixture(scope="module")
def arr_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("arrdata")
    rng = np.random.default_rng(17)
    rows_a, rows_b = [], []
    for i in range(1500):
        if rng.random() < 0.05:
            rows_a.append(None)
        else:
            n = int(rng.integers(0, 6))
            rows_a.append([int(x) if rng.random() > 0.1 else None
                           for x in rng.integers(0, 8, n)])
        rows_b.append([int(x) for x in
                       rng.integers(0, 8, rng.integers(0, 4))])
    t = pa.table({
        "id": pa.array(range(1500)),
        "a": pa.array(rows_a, type=pa.list_(pa.int64())),
        "b": pa.array(rows_b, type=pa.list_(pa.int64())),
        "s": pa.array([f"str{i % 37}" for i in range(1500)]),
    })
    p = str(d / "arr.parquet")
    pq.write_table(t, p)
    return p


def test_slice_and_position(arr_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(arr_path).select(
            "id",
            F.slice("a", 2, 2).alias("sl"),
            F.slice("a", -2, 3).alias("slneg"),
            F.array_position("a", 3).alias("p3")))


def test_remove_distinct_reverse(arr_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(arr_path).select(
            "id",
            F.array_remove("a", 2).alias("rm"),
            F.array_distinct("a").alias("dd"),
            F.reverse("a").alias("rv"),
            F.reverse("s").alias("rs")))


def test_set_operations(arr_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(arr_path).select(
            "id",
            F.array_union("a", "b").alias("u"),
            F.array_intersect("a", "b").alias("i"),
            F.array_except("a", "b").alias("x"),
            F.arrays_overlap("a", "b").alias("o"),
            F.concat_arrays("a", "b").alias("c")))


def test_exists_forall(arr_path):
    """Higher-order predicates are device-evaluated (no CPU lambda
    oracle); verify against python semantics."""
    def q(spark):
        return (spark.read.parquet(arr_path).select(
            "id",
            F.exists("a", lambda x: x > 5).alias("ex"),
            F.forall("a", lambda x: x >= 0).alias("fa"))
            .collect_arrow().to_pandas())

    out = with_tpu_session(q)
    src = pq.read_table(arr_path).column("a").to_pylist()
    for i, a in enumerate(src[:400]):
        if a is None:
            got0 = out.ex[i]
            assert got0 is None or (
                not isinstance(got0, (bool, np.bool_))
                and np.isnan(got0)), (i, got0)
            continue
        vals = [x for x in a if x is not None]
        has_null = any(x is None for x in a)
        want_ex = (True if any(x > 5 for x in vals)
                   else (None if has_null else False))
        got = out.ex[i]
        if want_ex is None:
            assert got is None or (not isinstance(
                got, (bool, np.bool_)) and np.isnan(got))
        else:
            assert bool(got) == want_ex, (i, a, got, want_ex)


def test_approx_count_distinct(arr_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(arr_path)
        .withColumn("g", F.col("id") % 4)
        .groupBy("g").agg(F.approx_count_distinct("s").alias("d")))


def test_reverse_is_character_aware():
    """F.reverse on strings must reverse CODEPOINTS, not UTF-8 bytes
    (regression: collections.Reverse shadowed StringReverse)."""
    t = pa.table({"s": pa.array(["café", "日本語", "ab"])})

    def q(spark):
        return (spark.createDataFrame(t)
                .select(F.reverse("s").alias("r")).collect_arrow())

    out = with_tpu_session(q)
    assert out.column("r").to_pylist() == ["éfac", "語本日", "ba"]


def test_exists_decides_on_null_element():
    """exists(a, x -> isnull(x)) decides TRUE on a null entry."""
    t = pa.table({"a": pa.array([[1, None], [1, 2], []],
                                type=pa.list_(pa.int64()))})

    def q(spark):
        return (spark.createDataFrame(t)
                .select(F.exists("a", lambda x: x.isNull()).alias("e"))
                .collect_arrow())

    out = with_tpu_session(q)
    assert out.column("e").to_pylist() == [True, False, False]
