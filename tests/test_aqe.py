"""Adaptive query execution (plan/aqe.py): stats-driven re-planning at
exchange boundaries — broadcast-join promotion with probe-side shuffle
cancellation, and tiny-partition coalescing (reference: GpuOverrides
applied per AQE query stage, GpuOverrides.scala:517-580)."""

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.plan.aqe import AdaptiveQueryExecutor

_CONF = {
    "spark.sql.shuffle.partitions": 8,
    # static planner must not choose broadcast up front: file-scan
    # estimates are unknown, so equi-joins plan as shuffled hash
    "spark.sql.autoBroadcastJoinThreshold": 64 << 10,
    "spark.rapids.sql.fusedExec.enabled": False,
    "spark.rapids.sql.format.parquet.reader.type": "PERFILE",
}


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


def _write_parts(d, table, nfiles):
    import os

    os.makedirs(d, exist_ok=True)
    per = table.num_rows // nfiles
    for i in range(nfiles):
        pq.write_table(table.slice(i * per,
                                   per if i < nfiles - 1 else None),
                       f"{d}/p{i}.parquet")
    return str(d)


def _probe_build_tables():
    rng = np.random.default_rng(3)
    probe_t = pa.table({
        "k": pa.array(rng.integers(0, 500, 20_000), type=pa.int64()),
        "v": pa.array(rng.random(20_000))})
    build_t = pa.table({
        "k": pa.array(np.arange(40_000) % 500, type=pa.int64()),
        "w": pa.array(np.arange(40_000), type=pa.int64())})
    return probe_t, build_t


def _find(n, cls):
    if isinstance(n, cls):
        return n
    for c in n.children:
        r = _find(c, cls)
        if r is not None:
            return r


def test_aqe_broadcast_promotion_and_correctness(spark, tmp_path):
    from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec

    probe_t, build_t = _probe_build_tables()
    pd_ = _write_parts(tmp_path / "probe", probe_t, 4)
    bd = _write_parts(tmp_path / "build", build_t, 4)
    probe = spark.read.parquet(pd_)
    # filters down to ~100 rows at runtime; static planner cannot know
    build = spark.read.parquet(bd).filter(F.col("w") < 100)
    df = probe.join(build, on="k", how="inner")
    phys, _ = df._physical()
    # static plan chose the shuffled join
    assert _find(phys, TpuShuffledHashJoinExec) is not None
    ex = AdaptiveQueryExecutor(spark.rapids_conf)
    out = ex.execute(phys)
    assert any("broadcast promotion" in d for d in ex.decisions), \
        ex.decisions
    assert any("cancelled" in d for d in ex.decisions), ex.decisions
    want = probe_t.join(build_t.filter(pc.less(build_t.column("w"),
                                               100)),
                        keys="k", join_type="inner")
    assert out.num_rows == want.num_rows


def test_aqe_partition_coalescing(spark, tmp_path):
    rng = np.random.default_rng(5)
    t = pa.table({"k": pa.array(rng.integers(0, 30, 5000),
                                type=pa.int64()),
                  "v": pa.array(rng.random(5000))})
    d = _write_parts(tmp_path / "agg", t, 4)
    df = (spark.read.parquet(d)
          .groupBy("k").agg(F.sum("v").alias("s"),
                            F.count("*").alias("n")))
    phys, _ = df._physical()
    ex = AdaptiveQueryExecutor(spark.rapids_conf)
    out = ex.execute(phys)
    got = {r["k"]: (r["s"], r["n"]) for r in out.to_pylist()}
    w = t.group_by("k").aggregate([("v", "sum"), ("k", "count")])
    exp = {r["k"]: (r["v_sum"], r["k_count"]) for r in w.to_pylist()}
    assert set(got) == set(exp)
    for k in exp:
        assert got[k][1] == exp[k][1]
        assert abs(got[k][0] - exp[k][0]) < 1e-9 * max(
            1.0, abs(exp[k][0]))
    assert any("coalesced" in d for d in ex.decisions), ex.decisions


def test_aqe_through_public_api_matches_oracle(tmp_path):
    """collect_arrow routes through AQE by default for exchange-bearing
    eager plans; results match the CPU oracle."""
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_and_cpu_are_equal_collect,
    )

    probe_t, build_t = _probe_build_tables()
    pd_ = _write_parts(tmp_path / "probe", probe_t, 3)
    bd = _write_parts(tmp_path / "build", build_t, 3)

    def q(s):
        probe = s.read.parquet(pd_)
        build = s.read.parquet(bd).filter(F.col("w") < 100)
        return (probe.join(build, on="k", how="inner")
                .groupBy("k").agg(F.sum("v").alias("sv"),
                                  F.count("*").alias("n")))

    assert_tpu_and_cpu_are_equal_collect(q, conf=dict(_CONF))


def test_aqe_join_sides_coalesce_together(tmp_path):
    """Coalescing must never break a shuffled join's co-partitioning:
    both sides coalesce with ONE shared grouping (or not at all) —
    independent groupings would pair mismatched pids and silently drop
    matches (Spark coordinates join-side coalescing the same way)."""
    rng = np.random.default_rng(5)
    n = 3000
    left = pa.table({
        "k": pa.array(rng.integers(0, 200, n), type=pa.int64()),
        "x": pa.array(rng.random(n))})
    right = pa.table({
        "k": pa.array(rng.integers(0, 200, n), type=pa.int64()),
        "y": pa.array(rng.random(n))})
    _write_parts(str(tmp_path / "l"), left, 4)
    _write_parts(str(tmp_path / "r"), right, 4)

    conf = dict(_CONF)
    conf["spark.sql.autoBroadcastJoinThreshold"] = -1  # keep shuffled
    s = TpuSparkSession(conf)
    try:
        df = (s.read.parquet(str(tmp_path / "l"))
              .join(s.read.parquet(str(tmp_path / "r")), on="k",
                    how="inner")
              .groupBy().agg(F.count("*").alias("c"),
                             F.sum(F.col("x") + F.col("y")).alias("sxy")))
        got = df.collect_arrow()
    finally:
        s.stop()

    lk = np.asarray(left.column("k"))
    rk = np.asarray(right.column("k"))
    lx = np.asarray(left.column("x"))
    ry = np.asarray(right.column("y"))
    # oracle pair count and sum over the inner join
    import collections

    rcnt = collections.Counter(rk.tolist())
    rsum = collections.defaultdict(float)
    for k, y in zip(rk.tolist(), ry.tolist()):
        rsum[k] += y
    want_c = sum(rcnt.get(k, 0) for k in lk.tolist())
    want_s = sum(x * rcnt.get(k, 0) + rsum.get(k, 0.0)
                 for k, x in zip(lk.tolist(), lx.tolist()))
    assert got.column("c")[0].as_py() == want_c
    np.testing.assert_allclose(got.column("sxy")[0].as_py(), want_s,
                               rtol=1e-9)


def test_skew_join_split(tmp_path):
    # one probe key dominates: AQE must slice the skewed partition and
    # re-read the build side per slice, preserving join results
    rng = np.random.default_rng(11)
    skew_n, tail_n = 60_000, 100
    lk = np.concatenate([np.zeros(skew_n, np.int64),
                         np.repeat(np.arange(1, 31), tail_n)])
    left = pa.table({"k": pa.array(lk),
                     "x": pa.array(rng.random(len(lk)))})
    rk = np.repeat(np.arange(0, 31), 3)
    right = pa.table({"k": pa.array(rk),
                      "y": pa.array(rng.random(len(rk)))})
    _write_parts(str(tmp_path / "l"), left, 4)
    _write_parts(str(tmp_path / "r"), right, 2)
    conf = dict(_CONF)
    conf.update({
        "spark.sql.autoBroadcastJoinThreshold": -1,
        "spark.rapids.sql.batchSizeBytes": 200_000,
        "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes":
            50_000,
        "spark.sql.shuffle.partitions": 4,
    })
    s = TpuSparkSession(conf)
    try:
        df = (s.read.parquet(str(tmp_path / "l"))
              .join(s.read.parquet(str(tmp_path / "r")), on="k",
                    how="inner"))
        phys, _ = df._physical()
        ex = AdaptiveQueryExecutor(s.rapids_conf)
        got = ex.execute(phys)
        assert any("skew split" in d for d in ex.decisions), ex.decisions
        want_rows = skew_n * 3 + 30 * tail_n * 3
        assert got.num_rows == want_rows
        # spot-check the join result on the skewed key (column 0 is the
        # join key; the joined schema may carry k from both sides)
        k0_rows = pc.sum(pc.cast(pc.equal(got.column(0), 0),
                                 pa.int64())).as_py()
        assert k0_rows == skew_n * 3
    finally:
        s.stop()


def test_skew_split_disabled_by_conf(tmp_path):
    rng = np.random.default_rng(12)
    lk = np.concatenate([np.zeros(30_000, np.int64),
                         np.repeat(np.arange(1, 11), 50)])
    left = pa.table({"k": pa.array(lk),
                     "x": pa.array(rng.random(len(lk)))})
    right = pa.table({"k": pa.array(np.arange(0, 11)),
                      "y": pa.array(rng.random(11))})
    _write_parts(str(tmp_path / "l"), left, 2)
    _write_parts(str(tmp_path / "r"), right, 1)
    conf = dict(_CONF)
    conf.update({
        "spark.sql.autoBroadcastJoinThreshold": -1,
        "spark.rapids.sql.batchSizeBytes": 100_000,
        "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes":
            20_000,
        "spark.sql.adaptive.skewJoin.enabled": False,
        "spark.sql.shuffle.partitions": 4,
    })
    s = TpuSparkSession(conf)
    try:
        df = (s.read.parquet(str(tmp_path / "l"))
              .join(s.read.parquet(str(tmp_path / "r")), on="k",
                    how="inner"))
        phys, _ = df._physical()
        ex = AdaptiveQueryExecutor(s.rapids_conf)
        got = ex.execute(phys)
        assert not any("skew split" in d for d in ex.decisions)
        assert got.num_rows == 30_000 + 10 * 50
    finally:
        s.stop()


def test_skew_split_single_hot_partition(tmp_path):
    # ALL rows share one key (sizes like [0,0,0,big]): the median must
    # be taken over every partition, zeros included, or the hot
    # partition becomes its own median and never qualifies
    rng = np.random.default_rng(13)
    n = 40_000
    left = pa.table({"k": pa.array(np.zeros(n, np.int64)),
                     "x": pa.array(rng.random(n))})
    right = pa.table({"k": pa.array([0], type=pa.int64()),
                      "y": pa.array([1.5])})
    _write_parts(str(tmp_path / "l"), left, 2)
    _write_parts(str(tmp_path / "r"), right, 1)
    conf = dict(_CONF)
    conf.update({
        "spark.sql.autoBroadcastJoinThreshold": -1,
        "spark.rapids.sql.batchSizeBytes": 150_000,
        "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes":
            50_000,
        "spark.sql.shuffle.partitions": 4,
    })
    s = TpuSparkSession(conf)
    try:
        df = (s.read.parquet(str(tmp_path / "l"))
              .join(s.read.parquet(str(tmp_path / "r")), on="k",
                    how="inner"))
        phys, _ = df._physical()
        ex = AdaptiveQueryExecutor(s.rapids_conf)
        got = ex.execute(phys)
        assert any("skew split" in d for d in ex.decisions), ex.decisions
        assert got.num_rows == n
    finally:
        s.stop()
