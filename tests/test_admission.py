"""Query governance suite — admission control, deadlines, cooperative
cancellation, per-query quotas, poison-query quarantine, and semaphore
fairness (PR 5).

The acceptance contract under test: over-capacity submissions always
get a clean QueryRejectedError (never an unbounded wait); a query
cancelled mid-execution — including while blocked on the semaphore and
inside retry/split loops — unwinds within a bounded latency, releases
its permits, and leaves the spill catalog leak-free; concurrent queries
through one session stay oracle-identical with chaos armed.
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.columnar import arrow_to_device
from spark_rapids_tpu.obs import events as obs_events
from spark_rapids_tpu.runtime import admission, cancellation
from spark_rapids_tpu.runtime import semaphore as sem_mod
from spark_rapids_tpu.runtime.admission import AdmissionController
from spark_rapids_tpu.runtime.cancellation import CancelToken
from spark_rapids_tpu.runtime.errors import (
    QueryCancelledError,
    QueryDeadlineExceeded,
    QueryQuarantinedError,
    QueryQueueTimeout,
    QueryRejectedError,
    SemaphoreTimeout,
    TpuRetryOOM,
    TpuSplitAndRetryOOM,
    TpuSplitAndRetryOOM as _SplitOOM,  # noqa: F401 (alias clarity)
)
from spark_rapids_tpu.runtime.memory import SpillCatalog, get_catalog
from spark_rapids_tpu.runtime.retry import with_retry
from spark_rapids_tpu.runtime.semaphore import TpuSemaphore


def _batch(n=1000, base=0):
    t = pa.table({"a": pa.array(range(base, base + n), pa.int64()),
                  "b": pa.array([float(i) for i in range(n)],
                                pa.float64())})
    return arrow_to_device(t)


def _wait_until(pred, timeout_s=5.0, tick=0.002):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return False


# ------------------------------------------------ controller unit tests

def test_shed_immediately_when_queue_full():
    ctrl = AdmissionController(max_concurrent=1, queue_depth=0)
    hog = ctrl.submit(101, description="hog")
    t0 = time.monotonic()
    with pytest.raises(QueryRejectedError) as ei:
        ctrl.submit(102, description="victim")
    # a shed is an IMMEDIATE clean error carrying the running table
    assert time.monotonic() - t0 < 1.0
    assert "query=101" in str(ei.value)
    assert "hog" in str(ei.value)
    ctrl.finish(hog)
    ok = ctrl.submit(103)
    assert ok.state == "running"
    ctrl.finish(ok)


def test_queue_timeout_names_running_queries():
    ctrl = AdmissionController(max_concurrent=1, queue_depth=4,
                               queue_timeout_ms=80)
    hog = ctrl.submit(201, description="the-culprit")
    t0 = time.monotonic()
    with pytest.raises(QueryQueueTimeout) as ei:
        ctrl.submit(202)
    assert 0.05 < time.monotonic() - t0 < 3.0
    assert "the-culprit" in str(ei.value)
    assert admission.stats.snapshot()["queueTimeouts"] >= 1
    ctrl.finish(hog)


def test_priority_then_fifo_admission_order():
    ctrl = AdmissionController(max_concurrent=1, queue_depth=8,
                               queue_timeout_ms=10_000)
    hog = ctrl.submit(300, description="hog")
    order, threads = [], []

    def submit(qid, prio):
        h = ctrl.submit(qid, priority=prio)
        order.append(qid)
        ctrl.finish(h)

    for qid, prio in ((301, 0), (302, 5), (303, 0)):
        t = threading.Thread(target=submit, args=(qid, prio))
        t.start()
        threads.append(t)
        assert _wait_until(
            lambda n=len(threads): len(ctrl.queued_table()) == n)
    ctrl.finish(hog)
    for t in threads:
        t.join(10)
    # highest priority first, FIFO within equal priority
    assert order == [302, 301, 303]


def test_cancel_queued_query_leaves_queue_promptly():
    ctrl = AdmissionController(max_concurrent=1, queue_depth=8,
                               queue_timeout_ms=60_000)
    hog = ctrl.submit(400)
    errs = []

    def submit():
        try:
            ctrl.submit(401)
        except BaseException as e:
            errs.append(e)

    t = threading.Thread(target=submit)
    t.start()
    assert _wait_until(lambda: len(ctrl.queued_table()) == 1)
    t0 = time.monotonic()
    assert ctrl.cancel(401, "operator said so")
    t.join(5)
    assert time.monotonic() - t0 < 2.0
    assert len(errs) == 1 and isinstance(errs[0], QueryCancelledError)
    assert "operator said so" in str(errs[0])
    assert ctrl.queued_table() == []
    ctrl.finish(hog)


# -------------------------------------------------- cancel-token basics

def test_token_deadline_turns_into_cancel():
    tok = CancelToken(1, timeout_ms=10)
    assert _wait_until(lambda: tok.expired, 2.0)
    with pytest.raises(QueryDeadlineExceeded):
        tok.check()
    assert tok.cancelled  # expiry latched as a cancel → waiters wake


def test_token_quarantine_after_crashes():
    tok = CancelToken(2, quarantine_threshold=3)
    tok.record_worker_crash(1, 0, "w0")
    tok.record_worker_crash(1, 0, "w1")
    assert not tok.cancelled
    tok.record_worker_crash(2, 1, "w2")
    with pytest.raises(QueryQuarantinedError) as ei:
        tok.check()
    assert "crash history" in str(ei.value)
    assert "w1" in str(ei.value)


def test_cancel_unwinds_split_retry_loop_leak_free(tmp_path):
    cat = SpillCatalog(1 << 30, 1 << 30, spill_dir=str(tmp_path))
    from spark_rapids_tpu.runtime import memory as mem_mod

    old = mem_mod._catalog
    mem_mod._catalog = cat
    try:
        tok = CancelToken(3)
        calls = []

        def fn(sb):
            calls.append(sb.row_count())
            if len(calls) == 3:
                tok.cancel("mid-split cancel")
            raise TpuSplitAndRetryOOM("never fits")

        with cancellation.scope(tok):
            with pytest.raises(QueryCancelledError):
                list(with_retry(cat.add_batch(_batch()), fn))
        # the current piece AND every queued split piece must be closed
        assert cat.check_leaks() == 0
        assert cat.device_reserved() == 0
    finally:
        mem_mod._catalog = old


# ------------------------------------------------- semaphore governance

def test_semaphore_fifo_ticket_fairness():
    """Satellite: acquirers are served strictly in arrival order — a
    parked waiter can no longer starve behind later arrivals racing the
    wakeup (the regression the ticket queue exists to prevent)."""
    for _ in range(10):
        sem = TpuSemaphore(concurrent_tasks=1, acquire_timeout_ms=20_000)
        sem.acquire_if_necessary(0)
        order, threads = [], []
        for i in range(1, 6):
            def run(i=i):
                sem.acquire_if_necessary(i)
                order.append(i)
                sem.release_if_necessary(i)

            t = threading.Thread(target=run)
            t.start()
            threads.append(t)
            assert _wait_until(lambda n=i: sem.waiting() == n)
        sem.release_if_necessary(0)
        for t in threads:
            t.join(10)
        assert order == [1, 2, 3, 4, 5]


def test_semaphore_timeout_table_names_query_and_hold_time():
    """Satellite: the held-permit table names the holder's QUERY id and
    elapsed hold seconds, so a wedged-query diagnosis reads off which
    query to session.cancel()."""
    sem = TpuSemaphore(concurrent_tasks=1, acquire_timeout_ms=80)
    qid = obs_events.begin_query()
    try:
        sem.acquire_if_necessary(7)
    finally:
        obs_events.finish_query(qid)
    with pytest.raises(SemaphoreTimeout) as ei:
        sem.acquire_if_necessary(8)
    msg = str(ei.value)
    assert f"query={qid}" in msg
    assert "held_s=" in msg
    sem.release_if_necessary(7)


def test_semaphore_wait_cancelled_promptly():
    sem = TpuSemaphore(concurrent_tasks=1, acquire_timeout_ms=60_000)
    sem.acquire_if_necessary(1)
    tok = CancelToken(9)
    errs = []

    def blocked():
        try:
            sem.acquire_if_necessary(2, cancel=tok)
        except BaseException as e:
            errs.append(e)

    t = threading.Thread(target=blocked)
    t.start()
    assert _wait_until(lambda: sem.waiting() == 1)
    t0 = time.monotonic()
    tok.cancel("cut the line")
    t.join(5)
    assert time.monotonic() - t0 < 2.0  # bounded cancel latency
    assert len(errs) == 1 and isinstance(errs[0], QueryCancelledError)
    assert sem.waiting() == 0  # the dead waiter's ticket is gone
    sem.release_if_necessary(1)
    sem.acquire_if_necessary(3)  # queue not wedged
    sem.release_if_necessary(3)


# ------------------------------------------------- per-query mem quotas

def test_per_query_device_quota_isolates_offender(tmp_path):
    cat = SpillCatalog(1 << 30, 1 << 30, spill_dir=str(tmp_path),
                       query_quota_bytes=40_000)
    # two tenants, each within quota: both fine
    cat.reserve(30_000, tag="t", query_id=11)
    cat.reserve(30_000, tag="t", query_id=12)
    assert cat.query_device_reserved(11) == 30_000
    # tenant 11 over quota with nothing of its own to spill: split OOM
    # for tenant 11 ONLY — the message names the quota
    with pytest.raises(TpuSplitAndRetryOOM, match="quota"):
        cat.reserve(20_000, tag="t", query_id=11)
    assert cat.metrics["quota_oom"] == 1
    # tenant 12 is untouched by 11's pressure
    cat.reserve(9_000, tag="t", query_id=12)
    cat.release(30_000, query_id=11)
    cat.release(39_000, query_id=12)
    assert cat.device_reserved() == 0


def test_quota_spills_own_buffers_first(tmp_path):
    cat = SpillCatalog(1 << 30, 1 << 30, spill_dir=str(tmp_path),
                       query_quota_bytes=40_000)
    qid = obs_events.begin_query()
    try:
        bufs = [cat.add_batch(_batch(base=i * 1000)) for i in range(2)]
        assert cat.query_device_reserved(qid) > 0
        # the third batch crosses the quota: the gate spills THIS
        # query's own device buffers to make room instead of raising
        b3 = cat.add_batch(_batch(base=9000))
        assert cat.metrics["spill_to_host"] >= 1
        assert cat.query_device_reserved(qid) <= 40_000
        for b in bufs + [b3]:
            b.close()
    finally:
        obs_events.finish_query(qid)
    assert cat.check_leaks() == 0


# ---------------------------------------------- end-to-end session tests

def _mk_parquet(tmp_path, rows=60_000):
    rng = np.random.default_rng(7)
    path = os.path.join(str(tmp_path), "fact")
    os.makedirs(path, exist_ok=True)
    for i in range(2):
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 64, rows // 2), pa.int64()),
            "v": pa.array(rng.random(rows // 2) * 100.0),
        }), os.path.join(path, f"p{i}.parquet"))
    return path


def test_session_shed_and_recover(tmp_path):
    data = _mk_parquet(tmp_path, rows=4_000)
    s = TpuSparkSession({
        "spark.rapids.tpu.admission.maxConcurrentQueries": 1,
        "spark.rapids.tpu.admission.queue.maxDepth": 0,
    })
    try:
        ctrl = admission.get()
        hog = ctrl.submit(obs_events.allocate_query_id(),
                          description="hog")
        df = s.read.parquet(data).groupBy("k").agg(
            F.sum("v").alias("sv"))
        with pytest.raises(QueryRejectedError) as ei:
            df.collect_arrow()
        assert "hog" in str(ei.value)
        ctrl.finish(hog)
        out = df.collect_arrow()  # capacity back: the query runs
        assert out.num_rows == 64
        assert s.last_execution["admission"]["queueWaitMs"] >= 0
    finally:
        s.stop()


def test_session_deadline_exceeded_is_clean(tmp_path):
    data = _mk_parquet(tmp_path, rows=4_000)
    s = TpuSparkSession({
        "spark.rapids.tpu.query.timeoutMs": 1,
    })
    try:
        df = s.read.parquet(data).groupBy("k").agg(
            F.count("*").alias("n"))
        with pytest.raises(QueryDeadlineExceeded):
            df.collect_arrow()
        assert get_catalog().check_leaks() == 0
        assert sem_mod.get().holders() == 0
        # the session recovers for deadline-free queries
        s.conf.set("spark.rapids.tpu.query.timeoutMs", 0)
        assert df.collect_arrow().num_rows == 64
    finally:
        s.stop()


def test_cancel_while_blocked_on_semaphore(tmp_path):
    """Acceptance case: a query cancelled WHILE WAITING for device
    permits unwinds within a bounded latency and takes no permits."""
    data = _mk_parquet(tmp_path, rows=4_000)
    s = TpuSparkSession({
        "spark.rapids.sql.concurrentGpuTasks": 1,
        "spark.rapids.tpu.semaphore.acquireTimeoutMs": 60_000,
    })
    try:
        sem = sem_mod.get()
        sem.acquire_if_necessary(987_654)  # wedge: all permits held
        errs = []

        def run():
            try:
                s.read.parquet(data).groupBy("k").agg(
                    F.sum("v").alias("sv")).collect_arrow()
            except BaseException as e:
                errs.append(e)

        t = threading.Thread(target=run)
        t.start()
        assert _wait_until(lambda: sem.waiting() >= 1, 30.0)
        running = s.admission_status()["running"]
        assert len(running) == 1
        t0 = time.monotonic()
        assert s.cancel(running[0]["queryId"])
        t.join(15)
        assert not t.is_alive()
        assert time.monotonic() - t0 < 10.0
        assert len(errs) == 1 and \
            isinstance(errs[0], QueryCancelledError)
        sem.release_if_necessary(987_654)
        assert sem.holders() == 0  # the cancelled query took nothing
        get_catalog().check_leaks(raise_on_leak=True)
        assert s.admission_status()["running"] == []
    finally:
        s.stop()


def test_poison_query_quarantined_with_history(tmp_path):
    data = _mk_parquet(tmp_path, rows=4_000)
    s = TpuSparkSession({
        "spark.rapids.sql.fusedExec.enabled": False,
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.sites": "worker.crash:p=1.0",
        "spark.rapids.tpu.stage.maxAttempts": 50,
        "spark.rapids.tpu.admission.quarantine.maxWorkerCrashes": 3,
    })
    try:
        df = s.read.parquet(data).groupBy("k").agg(
            F.count("*").alias("n"))
        before = admission.stats.snapshot()["queriesQuarantined"]
        with pytest.raises(QueryQuarantinedError) as ei:
            df.collect_arrow()
        assert "crash history" in str(ei.value)
        assert admission.stats.snapshot()["queriesQuarantined"] == \
            before + 1
        get_catalog().check_leaks(raise_on_leak=True)
    finally:
        s.stop()


def test_concurrent_queries_oracle_identical_under_chaos(tmp_path):
    """Satellite: N threads submitting distinct queries through ONE
    session, admission capacity below N (so queueing happens), chaos
    armed — every thread's every round matches the clean oracle, and
    the catalog is leak-free after."""
    data = _mk_parquet(tmp_path, rows=20_000)

    def build(s):
        fact = s.read.parquet(data)
        return [
            ("sum", fact.groupBy("k").agg(F.sum("v").alias("x"))
             .orderBy("k")),
            ("cnt", fact.filter(F.col("v") > 50.0).groupBy("k")
             .agg(F.count("*").alias("x")).orderBy("k")),
            ("top", fact.orderBy("v", ascending=False)
             .select("k", "v").limit(20)),
            ("avg", fact.groupBy("k").agg(F.avg("v").alias("x"))
             .orderBy("k")),
        ]

    clean = TpuSparkSession({})
    try:
        want = {name: df.collect_arrow().to_pydict()
                for name, df in build(clean)}
    finally:
        clean.stop()

    s = TpuSparkSession({
        "spark.rapids.tpu.admission.maxConcurrentQueries": 2,
        "spark.rapids.tpu.admission.queue.maxDepth": 16,
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.seed": 11,
        "spark.rapids.tpu.chaos.sites":
            "io.read:p=0.2;worker.crash:p=0.05",
        "spark.rapids.tpu.stage.maxAttempts": 8,
        "spark.rapids.tpu.io.retry.backoffMs": 1,
        "spark.rapids.tpu.io.retry.maxBackoffMs": 5,
        "spark.rapids.tpu.io.retry.attempts": 6,
    })
    try:
        queries = build(s)
        errs, results = [], {}

        def worker(idx):
            try:
                name, df = queries[idx]
                for _ in range(2):
                    results[(idx, _)] = (name,
                                         df.collect_arrow().to_pydict())
            except BaseException as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errs, errs
        for (_idx, _r), (name, got) in results.items():
            assert got == want[name] or _float_close(got, want[name]), \
                f"{name} diverged under concurrent chaos"
        snap = admission.stats.snapshot()
        assert snap["queriesAdmitted"] >= 8
        get_catalog().check_leaks(raise_on_leak=True)
    finally:
        s.stop()


def _float_close(a, b, rel=1e-6):
    if set(a) != set(b):
        return False
    import math

    for col in a:
        if len(a[col]) != len(b[col]):
            return False
        for x, y in zip(a[col], b[col]):
            if isinstance(x, float) or isinstance(y, float):
                if not math.isclose(x, y, rel_tol=rel, abs_tol=1e-8):
                    return False
            elif x != y:
                return False
    return True


def test_cancel_storm_leaves_no_leaks(tmp_path):
    """Satellite acceptance: a storm of mid-flight cancels (landing in
    the planner, scheduler, shuffle, retry loops — wherever the query
    happens to be) leaves zero leaked buffers and zero held permits;
    check_leaks(raise_on_leak=True) passes."""
    data = _mk_parquet(tmp_path, rows=40_000)
    s = TpuSparkSession({
        "spark.rapids.sql.fusedExec.enabled": False,
        "spark.rapids.shuffle.mode": "MULTITHREADED",
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.reader.batchSizeRows": 4096,
        "spark.rapids.memory.gpu.maxAllocBytes": 8 << 20,
    })
    try:
        df = s.read.parquet(data).repartition(4, "k").groupBy("k").agg(
            F.sum("v").alias("sv"))
        outcomes = []
        for i in range(6):
            err = []

            def run():
                try:
                    df.collect_arrow()
                    err.append(None)
                except QueryCancelledError as e:
                    err.append(e)

            t = threading.Thread(target=run)
            t.start()
            time.sleep(0.01 * i)  # cancel lands at varied depths
            s.cancel_all("storm")
            t.join(60)
            assert not t.is_alive()
            outcomes.append(err[0] if err else "hung")
        # a mix of cancelled and completed-before-cancel is fine; what
        # is NOT fine is leaks, held permits, or stuck slots
        assert all(o is None or isinstance(o, QueryCancelledError)
                   for o in outcomes), outcomes
        assert sem_mod.get().holders() == 0
        get_catalog().check_leaks(raise_on_leak=True)
        assert s.admission_status()["running"] == []
        out = df.collect_arrow()  # and the session still works
        assert out.num_rows == 64
    finally:
        s.stop()


def test_chaos_sites_cancel_race_and_slow_drain(tmp_path):
    """New chaos sites are result-equivalent: a cancel racing with
    completion and a delayed slot handoff change nothing observable."""
    data = _mk_parquet(tmp_path, rows=4_000)
    clean = TpuSparkSession({})
    try:
        want = clean.read.parquet(data).groupBy("k").agg(
            F.sum("v").alias("sv")).orderBy("k").collect_arrow()
    finally:
        clean.stop()
    s = TpuSparkSession({
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.sites":
            "query.cancel_race:p=1.0;admission.slow_drain:p=1.0",
    })
    try:
        df = s.read.parquet(data).groupBy("k").agg(
            F.sum("v").alias("sv")).orderBy("k")
        for _ in range(3):
            got = df.collect_arrow()
            assert got.to_pydict() == want.to_pydict()
        assert s.admission_status()["running"] == []
        get_catalog().check_leaks(raise_on_leak=True)
    finally:
        s.stop()


def test_admission_events_and_queue_wait_span(tmp_path):
    data = _mk_parquet(tmp_path, rows=4_000)
    s = TpuSparkSession({
        "spark.rapids.tpu.admission.maxConcurrentQueries": 1,
    })
    try:
        ctrl = admission.get()
        hog = ctrl.submit(obs_events.allocate_query_id(),
                          description="hog")
        done = []

        def run():
            done.append(s.read.parquet(data).groupBy("k").agg(
                F.count("*").alias("n")).collect_arrow())

        t = threading.Thread(target=run)
        t.start()
        assert _wait_until(lambda: len(ctrl.queued_table()) == 1, 30.0)
        time.sleep(0.05)  # measurable queue wait
        ctrl.finish(hog)
        t.join(60)
        assert done and done[0].num_rows == 64
        counts = s.obs.bus.counts
        assert counts.get("admission.queued", 0) >= 1
        assert counts.get("admission.admitted", 0) >= 1
        assert s.last_execution["admission"]["queueWaitMs"] >= 40
        # the queue wait hangs on the query's span tree
        root = s.obs.last_spans
        names = [sp.name for sp in root.walk()]
        assert "AdmissionQueue" in names
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# drain ordering (serve/) — the intake valve vs queued work
# ---------------------------------------------------------------------------


def test_begin_drain_sheds_new_submissions_with_reason():
    ctrl = AdmissionController(max_concurrent=2, queue_depth=4)
    h = ctrl.submit(9001, description="pre-drain")
    ctrl.begin_drain("rolling restart")
    with pytest.raises(QueryRejectedError) as ei:
        ctrl.submit(9002, description="post-drain")
    assert ei.value.reason == "draining"
    assert "rolling restart" in str(ei.value)
    assert ctrl.status()["draining"] is True
    # in-flight work is untouched by the valve
    ctrl.finish(h)
    assert ctrl.quiescent()
    ctrl.end_drain()
    ok = ctrl.submit(9003)
    assert ok.state == "running"
    ctrl.finish(ok)
    assert ctrl.status()["draining"] is False


def test_drain_preserves_queued_queries():
    """Queries already IN the queue when the drain begins keep their
    slots and deadlines — drain is an intake valve, not a kill
    switch."""
    ctrl = AdmissionController(max_concurrent=1, queue_depth=4,
                               queue_timeout_ms=30_000)
    hog = ctrl.submit(9101, description="hog")
    admitted = []

    def queued_runner():
        h = ctrl.submit(9102, description="queued-before-drain")
        admitted.append(h)
        ctrl.finish(h)

    t = threading.Thread(target=queued_runner)
    t.start()
    assert _wait_until(lambda: len(ctrl.queued_table()) == 1, 10.0)
    ctrl.begin_drain()
    # a NEW submission sheds immediately...
    with pytest.raises(QueryRejectedError) as ei:
        ctrl.submit(9103)
    assert ei.value.reason == "draining"
    # ...but the queued query still gets its turn when capacity frees
    ctrl.finish(hog)
    t.join(30)
    assert admitted and admitted[0].query_id == 9102
    assert ctrl.quiescent()
    ctrl.end_drain()


def test_request_overrides_thread_priority_and_timeout(tmp_path):
    """serve/ threads a connection's priority class + per-request
    timeout through admission.request_overrides — thread-local, so
    concurrent connections on one session can't race each other's
    conf."""
    data = _mk_parquet(tmp_path, rows=2_000)
    s = TpuSparkSession({
        "spark.rapids.tpu.admission.maxConcurrentQueries": 1,
    })
    try:
        with admission.request_overrides(priority=42,
                                         description="vip"):
            got = s.read.parquet(data).groupBy("k").agg(
                F.count("*").alias("n")).collect_arrow()
        assert got.num_rows == 64
        rec = s.last_execution["admission"]
        assert rec["priority"] == 42
        # the override is scoped: the next query is back on conf
        s.range(0, 10).count()
        assert s.last_execution["admission"]["priority"] == 0
        assert admission.current_overrides() == {}
    finally:
        s.stop()
