"""Iceberg read tests over self-built spec-conformant fixtures:
metadata JSON + avro manifest list + avro manifests (written with the
engine's own nested-avro writer) + parquet data files."""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.io.avro import write_avro_records

_CONF = {"spark.sql.shuffle.partitions": 2}

_MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"]},
        {"name": "data_file", "type": {
            "type": "record", "name": "data_file", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
                {"name": "column_sizes", "type": ["null", {
                    "type": "map", "values": "long"}]},
            ]}},
    ]}

_MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "content", "type": "int"},
        {"name": "added_snapshot_id", "type": ["null", "long"]},
    ]}


def build_iceberg_table(root: str, tables, deleted_paths=()):
    """Create an iceberg table dir from [(name, pa.Table)] data files."""
    os.makedirs(os.path.join(root, "data"), exist_ok=True)
    os.makedirs(os.path.join(root, "metadata"), exist_ok=True)
    entries = []
    for name, t in tables:
        p = os.path.join(root, "data", f"{name}.parquet")
        pq.write_table(t, p)
        entries.append({
            "status": 2 if name in deleted_paths else 1,
            "snapshot_id": 99,
            "data_file": {
                "content": 0,
                "file_path": p,
                "file_format": "PARQUET",
                "record_count": t.num_rows,
                "file_size_in_bytes": os.path.getsize(p),
                "column_sizes": {"c1": 10},
            }})
    mpath = os.path.join(root, "metadata", "manifest-1.avro")
    write_avro_records(mpath, _MANIFEST_ENTRY_SCHEMA, entries)
    mlist = os.path.join(root, "metadata", "snap-99-manifest-list.avro")
    write_avro_records(mlist, _MANIFEST_LIST_SCHEMA, [{
        "manifest_path": mpath,
        "manifest_length": os.path.getsize(mpath),
        "partition_spec_id": 0, "content": 0,
        "added_snapshot_id": 99}])
    schema_fields = []
    at = tables[0][1].schema
    type_map = {pa.int64(): "long", pa.float64(): "double",
                pa.string(): "string", pa.int32(): "int"}
    for i, f in enumerate(at):
        schema_fields.append({"id": i + 1, "name": f.name,
                              "required": False,
                              "type": type_map[f.type]})
    meta = {
        "format-version": 2,
        "table-uuid": "0000-t",
        "location": root,
        "current-snapshot-id": 99,
        "schemas": [{"schema-id": 0, "type": "struct",
                     "fields": schema_fields}],
        "current-schema-id": 0,
        "snapshots": [{"snapshot-id": 99,
                       "manifest-list": mlist,
                       "timestamp-ms": 0}],
    }
    with open(os.path.join(root, "metadata", "v1.metadata.json"),
              "w") as f:
        json.dump(meta, f)
    with open(os.path.join(root, "metadata", "version-hint.text"),
              "w") as f:
        f.write("1")
    return root


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


def _tables(n=400):
    rng = np.random.default_rng(17)
    mk = lambda lo: pa.table({
        "k": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        "v": pa.array(rng.random(n), type=pa.float64()),
        "id": pa.array(np.arange(lo, lo + n), type=pa.int64()),
    })
    return [("f0", mk(0)), ("f1", mk(n)), ("f2", mk(2 * n))]


def test_iceberg_scan(spark, tmp_path):
    tabs = _tables()
    root = build_iceberg_table(str(tmp_path / "ice"), tabs)
    df = spark.read.format("iceberg").load(root)
    out = df.collect_arrow()
    assert out.num_rows == sum(t.num_rows for _, t in tabs)
    agg = df.groupBy("k").agg(F.count("*").alias("n")).collect_arrow()
    assert sum(agg.column("n").to_pylist()) == out.num_rows


def test_iceberg_deleted_entries_skipped(spark, tmp_path):
    tabs = _tables()
    root = build_iceberg_table(str(tmp_path / "ice2"), tabs,
                               deleted_paths=("f1",))
    out = spark.read.format("iceberg").load(root).collect_arrow()
    assert out.num_rows == 2 * 400
    ids = out.column("id").to_pylist()
    assert 400 not in ids and 500 not in ids  # f1's range dropped


def test_iceberg_schema_from_metadata(spark, tmp_path):
    tabs = _tables()
    root = build_iceberg_table(str(tmp_path / "ice3"), tabs)
    df = spark.read.format("iceberg").load(root)
    assert df.columns == ["k", "v", "id"]
    out = df.filter(F.col("id") < 100).collect_arrow()
    assert out.num_rows == 100
