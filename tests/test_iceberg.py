"""Iceberg read tests over self-built spec-conformant fixtures:
metadata JSON + avro manifest list + avro manifests (written with the
engine's own nested-avro writer) + parquet data files."""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.io.avro import write_avro_records

_CONF = {"spark.sql.shuffle.partitions": 2}

_MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"]},
        {"name": "data_file", "type": {
            "type": "record", "name": "data_file", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
                {"name": "column_sizes", "type": ["null", {
                    "type": "map", "values": "long"}]},
            ]}},
    ]}

_MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "content", "type": "int"},
        {"name": "added_snapshot_id", "type": ["null", "long"]},
    ]}


def build_iceberg_table(root: str, tables, deleted_paths=()):
    """Create an iceberg table dir from [(name, pa.Table)] data files."""
    os.makedirs(os.path.join(root, "data"), exist_ok=True)
    os.makedirs(os.path.join(root, "metadata"), exist_ok=True)
    entries = []
    for name, t in tables:
        p = os.path.join(root, "data", f"{name}.parquet")
        pq.write_table(t, p)
        entries.append({
            "status": 2 if name in deleted_paths else 1,
            "snapshot_id": 99,
            "data_file": {
                "content": 0,
                "file_path": p,
                "file_format": "PARQUET",
                "record_count": t.num_rows,
                "file_size_in_bytes": os.path.getsize(p),
                "column_sizes": {"c1": 10},
            }})
    mpath = os.path.join(root, "metadata", "manifest-1.avro")
    write_avro_records(mpath, _MANIFEST_ENTRY_SCHEMA, entries)
    mlist = os.path.join(root, "metadata", "snap-99-manifest-list.avro")
    write_avro_records(mlist, _MANIFEST_LIST_SCHEMA, [{
        "manifest_path": mpath,
        "manifest_length": os.path.getsize(mpath),
        "partition_spec_id": 0, "content": 0,
        "added_snapshot_id": 99}])
    schema_fields = []
    at = tables[0][1].schema
    type_map = {pa.int64(): "long", pa.float64(): "double",
                pa.string(): "string", pa.int32(): "int"}
    for i, f in enumerate(at):
        schema_fields.append({"id": i + 1, "name": f.name,
                              "required": False,
                              "type": type_map[f.type]})
    meta = {
        "format-version": 2,
        "table-uuid": "0000-t",
        "location": root,
        "current-snapshot-id": 99,
        "schemas": [{"schema-id": 0, "type": "struct",
                     "fields": schema_fields}],
        "current-schema-id": 0,
        "snapshots": [{"snapshot-id": 99,
                       "manifest-list": mlist,
                       "timestamp-ms": 0}],
    }
    with open(os.path.join(root, "metadata", "v1.metadata.json"),
              "w") as f:
        json.dump(meta, f)
    with open(os.path.join(root, "metadata", "version-hint.text"),
              "w") as f:
        f.write("1")
    return root


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


def _tables(n=400):
    rng = np.random.default_rng(17)
    mk = lambda lo: pa.table({
        "k": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        "v": pa.array(rng.random(n), type=pa.float64()),
        "id": pa.array(np.arange(lo, lo + n), type=pa.int64()),
    })
    return [("f0", mk(0)), ("f1", mk(n)), ("f2", mk(2 * n))]


def test_iceberg_scan(spark, tmp_path):
    tabs = _tables()
    root = build_iceberg_table(str(tmp_path / "ice"), tabs)
    df = spark.read.format("iceberg").load(root)
    out = df.collect_arrow()
    assert out.num_rows == sum(t.num_rows for _, t in tabs)
    agg = df.groupBy("k").agg(F.count("*").alias("n")).collect_arrow()
    assert sum(agg.column("n").to_pylist()) == out.num_rows


def test_iceberg_deleted_entries_skipped(spark, tmp_path):
    tabs = _tables()
    root = build_iceberg_table(str(tmp_path / "ice2"), tabs,
                               deleted_paths=("f1",))
    out = spark.read.format("iceberg").load(root).collect_arrow()
    assert out.num_rows == 2 * 400
    ids = out.column("id").to_pylist()
    assert 400 not in ids and 500 not in ids  # f1's range dropped


def test_iceberg_schema_from_metadata(spark, tmp_path):
    tabs = _tables()
    root = build_iceberg_table(str(tmp_path / "ice3"), tabs)
    df = spark.read.format("iceberg").load(root)
    assert df.columns == ["k", "v", "id"]
    out = df.filter(F.col("id") < 100).collect_arrow()
    assert out.num_rows == 100


# ---- v2 merge-on-read deletes + schema evolution (round-4 item #6) ----

_ENTRY_SCHEMA_V2 = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"]},
        {"name": "sequence_number", "type": ["null", "long"]},
        {"name": "data_file", "type": {
            "type": "record", "name": "data_file", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
                {"name": "equality_ids", "type": ["null", {
                    "type": "array", "items": "int"}]},
            ]}},
    ]}


def _ice_field(name, typ, fid):
    return pa.field(name, typ,
                    metadata={b"PARQUET:field_id": str(fid).encode()})


def build_v2_table(root, schema_fields, files, version=1):
    """files: [(path_rel, content, seq, pa.Table, equality_ids)]"""
    os.makedirs(os.path.join(root, "data"), exist_ok=True)
    os.makedirs(os.path.join(root, "metadata"), exist_ok=True)
    entries = []
    for rel, content, seq, t, eq_ids in files:
        p = os.path.join(root, "data", rel)
        pq.write_table(t, p)
        entries.append({
            "status": 1, "snapshot_id": 99, "sequence_number": seq,
            "data_file": {
                "content": content, "file_path": p,
                "file_format": "PARQUET", "record_count": t.num_rows,
                "file_size_in_bytes": os.path.getsize(p),
                "equality_ids": eq_ids}})
    mpath = os.path.join(root, "metadata", "manifest-1.avro")
    write_avro_records(mpath, _ENTRY_SCHEMA_V2, entries)
    mlist = os.path.join(root, "metadata", "snap-99.avro")
    write_avro_records(mlist, _MANIFEST_LIST_SCHEMA, [{
        "manifest_path": mpath,
        "manifest_length": os.path.getsize(mpath),
        "partition_spec_id": 0, "content": 0,
        "added_snapshot_id": 99}])
    meta = {
        "format-version": 2, "table-uuid": "0000-t", "location": root,
        "current-snapshot-id": 99,
        "schemas": [{"schema-id": 0, "type": "struct",
                     "fields": schema_fields}],
        "current-schema-id": 0,
        "snapshots": [{"snapshot-id": 99, "manifest-list": mlist,
                       "timestamp-ms": 0}],
    }
    with open(os.path.join(root, "metadata",
                           f"v{version}.metadata.json"), "w") as f:
        json.dump(meta, f)
    with open(os.path.join(root, "metadata", "version-hint.text"),
              "w") as f:
        f.write(str(version))
    return root


_SCHEMA_KV = [
    {"id": 1, "name": "k", "required": False, "type": "long"},
    {"id": 2, "name": "v", "required": False, "type": "double"},
]


def _kv_table(ids):
    return pa.table({"k": pa.array(ids, type=pa.int64()),
                     "v": pa.array([float(i) for i in ids],
                                   type=pa.float64())})


def test_positional_deletes(spark, tmp_path):
    root = str(tmp_path / "posdel")
    data = _kv_table(range(100))
    data_path = os.path.join(root, "data", "d0.parquet")
    pos_del = pa.table({
        "file_path": pa.array([data_path] * 3),
        "pos": pa.array([0, 7, 99], type=pa.int64())})
    build_v2_table(root, _SCHEMA_KV, [
        ("d0.parquet", 0, 1, data, None),
        ("del0.parquet", 1, 2, pos_del, None)])
    out = spark.read.format("iceberg").load(root).collect_arrow()
    ks = sorted(out.column("k").to_pylist())
    assert len(ks) == 97 and 0 not in ks and 7 not in ks and 99 not in ks


def test_positional_delete_older_than_data_ignored(spark, tmp_path):
    root = str(tmp_path / "posdel_old")
    data = _kv_table(range(10))
    data_path = os.path.join(root, "data", "d0.parquet")
    pos_del = pa.table({"file_path": pa.array([data_path]),
                        "pos": pa.array([1], type=pa.int64())})
    build_v2_table(root, _SCHEMA_KV, [
        ("d0.parquet", 0, 5, data, None),
        ("del0.parquet", 1, 2, pos_del, None)])  # seq 2 < data seq 5
    out = spark.read.format("iceberg").load(root).collect_arrow()
    assert out.num_rows == 10


def test_equality_deletes_sequence_scoped(spark, tmp_path):
    """Equality deletes apply only to data files with STRICTLY smaller
    sequence numbers (a re-inserted key in a newer file survives)."""
    root = str(tmp_path / "eqdel")
    old = _kv_table([1, 2, 3, 4])       # seq 1
    newer = _kv_table([3, 5])           # seq 3: re-inserts k=3
    eq_del = pa.table({"k": pa.array([2, 3], type=pa.int64())})  # seq 2
    build_v2_table(root, _SCHEMA_KV, [
        ("old.parquet", 0, 1, old, None),
        ("new.parquet", 0, 3, newer, None),
        ("eqdel.parquet", 2, 2, eq_del, [1])])
    out = spark.read.format("iceberg").load(root).collect_arrow()
    assert sorted(out.column("k").to_pylist()) == [1, 3, 4, 5]


def test_schema_evolution_rename_and_add(spark, tmp_path):
    """Field-id resolution: the file was written when column 2 was
    named 'val'; the current schema renames it to 'v' and adds id 3."""
    root = str(tmp_path / "evolve")
    file_schema = pa.schema([
        _ice_field("k", pa.int64(), 1),
        _ice_field("val", pa.float64(), 2)])
    t = pa.table({"k": pa.array([1, 2], type=pa.int64()),
                  "val": pa.array([1.5, 2.5], type=pa.float64())})
    t = t.cast(file_schema)
    schema_fields = [
        {"id": 1, "name": "k", "required": False, "type": "long"},
        {"id": 2, "name": "v", "required": False, "type": "double"},
        {"id": 3, "name": "extra", "required": False, "type": "long"},
    ]
    build_v2_table(root, schema_fields, [("d0.parquet", 0, 1, t, None)])
    df = spark.read.format("iceberg").load(root)
    assert df.columns == ["k", "v", "extra"]
    out = df.collect_arrow()
    assert out.column("v").to_pylist() == [1.5, 2.5]   # renamed col read
    assert out.column("extra").to_pylist() == [None, None]  # added col


def test_equality_delete_with_pruned_projection(spark, tmp_path):
    """Column pruning must not resurrect equality-deleted rows: the
    delete key column is read for the join even when the query projects
    it away (review finding, round 4)."""
    root = str(tmp_path / "eqprune")
    data = _kv_table([1, 2, 3, 4])
    eq_del = pa.table({"k": pa.array([2, 4], type=pa.int64())})
    build_v2_table(root, _SCHEMA_KV, [
        ("d0.parquet", 0, 1, data, None),
        ("eqdel.parquet", 2, 2, eq_del, [1])])
    out = (spark.read.format("iceberg").load(root)
           .select("v").collect_arrow())
    assert sorted(out.column("v").to_pylist()) == [1.0, 3.0]
