"""srtpu-lint engine suite (PR 7): one positive + one negative fixture
per rule, pragma suppression, and the engine-level contract that the
committed tree itself is clean (the ci/static_check.sh gate).

Fixtures are written into a synthetic mini-repo (tmp_path) shaped like
the real one — a spark_rapids_tpu/ package, docs/configs.md, and an
obs/events.py EVENT_TYPES — so the rules run exactly as they do in CI,
including the repo-context loading paths.
"""

import os
import textwrap

import pytest

from spark_rapids_tpu.tools.lint.engine import (
    FileContext,
    LintEngine,
    RepoContext,
    repo_root,
)
from spark_rapids_tpu.tools.lint.rules import all_rules

RAPIDS_CONF_STUB = '''
_REGISTRY = {}


class ConfEntry:
    def __init__(self, key, internal=False):
        self.key = key
        self.internal = internal


def conf(key, internal=False):
    _REGISTRY[key] = ConfEntry(key, internal)


conf("spark.rapids.tpu.known.enabled")
conf("spark.rapids.tpu.known.child.timeoutMs")
conf("spark.rapids.tpu.secret.internalKnob", internal=True)
'''

EVENTS_STUB = '''
EVENT_TYPES = {
    "query.start": "queryId",
    "sanitizer.deadlock": "cycle",
}
'''

CONFIGS_MD = """# configs
spark.rapids.tpu.known.enabled | desc
spark.rapids.tpu.known.child.timeoutMs | desc
"""


@pytest.fixture
def mini_repo(tmp_path):
    root = tmp_path
    pkg = root / "spark_rapids_tpu"
    (pkg / "config").mkdir(parents=True)
    (pkg / "obs").mkdir()
    (pkg / "runtime").mkdir()
    (pkg / "exec").mkdir()
    (pkg / "shuffle").mkdir()
    (root / "docs").mkdir()
    (pkg / "config" / "rapids_conf.py").write_text(RAPIDS_CONF_STUB)
    (pkg / "obs" / "events.py").write_text(EVENTS_STUB)
    (root / "docs" / "configs.md").write_text(CONFIGS_MD)
    return root


def _lint_file(root, rel, source):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(source))
    engine = LintEngine(str(root), all_rules())
    return [f for f in engine.run([path])]


def _rule_hits(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------- conf rules

def test_conf_registered_positive_and_negative(mini_repo):
    bad = _lint_file(mini_repo, "spark_rapids_tpu/runtime/x.py",
                     'KEY = "spark.rapids.tpu.unregistered.flag"\n')
    assert len(_rule_hits(bad, "conf-registered")) == 1
    good = _lint_file(mini_repo, "spark_rapids_tpu/runtime/y.py",
                      'KEY = "spark.rapids.tpu.known.enabled"\n')
    assert not _rule_hits(good, "conf-registered")


def test_conf_registered_family_prefix_ok(mini_repo):
    """Doc-prose family references resolve as registered-key
    prefixes."""
    good = _lint_file(
        mini_repo, "spark_rapids_tpu/runtime/fam.py",
        'DOC = "see spark.rapids.tpu.known.* and '
        'spark.rapids.tpu.known.child settings"\n')
    assert not _rule_hits(good, "conf-registered")


def test_conf_documented_repo_check(mini_repo):
    """A registered-but-undocumented key surfaces once, against
    docs/configs.md; internal keys are exempt."""
    (mini_repo / "spark_rapids_tpu" / "config" /
     "rapids_conf.py").write_text(
        RAPIDS_CONF_STUB +
        'conf("spark.rapids.tpu.freshly.added")\n')
    engine = LintEngine(str(mini_repo), all_rules())
    findings = engine.run([])
    hits = _rule_hits(findings, "conf-documented")
    assert len(hits) == 1
    assert "spark.rapids.tpu.freshly.added" in hits[0].message
    assert hits[0].path == "docs/configs.md"
    assert not any("internalKnob" in f.message for f in findings)


# ----------------------------------------------------------- raw-sleep

def test_raw_sleep_positive_negative_and_allowlist(mini_repo):
    bad = _lint_file(mini_repo, "spark_rapids_tpu/runtime/w.py", """
        import time

        def slow():
            time.sleep(1.0)
    """)
    assert len(_rule_hits(bad, "raw-sleep")) == 1
    ok = _lint_file(mini_repo, "spark_rapids_tpu/runtime/backoff.py", """
        import time

        def backoff():
            time.sleep(0.1)
    """)
    assert not _rule_hits(ok, "raw-sleep")
    aliased = _lint_file(mini_repo, "spark_rapids_tpu/runtime/w2.py", """
        from time import sleep

        def slow():
            sleep(1.0)
    """)
    assert len(_rule_hits(aliased, "raw-sleep")) == 1


def test_pragma_suppression(mini_repo):
    src = """
        import time

        def chaos():
            time.sleep(0.5)  # srtpu-lint: disable=raw-sleep
    """
    ok = _lint_file(mini_repo, "spark_rapids_tpu/runtime/w3.py", src)
    assert not _rule_hits(ok, "raw-sleep")


# ----------------------------------------------------- unyielding-wait

def test_unyielding_wait_positive(mini_repo):
    bad = _lint_file(mini_repo, "spark_rapids_tpu/exec/operators.py", """
        def fetch(result_q):
            return result_q.get()
    """)
    assert len(_rule_hits(bad, "unyielding-wait")) == 1


def test_unyielding_wait_negatives(mini_repo):
    # timeout'd wait, cancel-aware function, singleton getter, and a
    # module outside the permit-holding list are all clean
    ok = _lint_file(mini_repo, "spark_rapids_tpu/exec/base.py", """
        def fetch_bounded(result_q):
            return result_q.get(timeout=5)

        def fetch_cancellable(result_q, cancel_token):
            cancel_token.check()
            return result_q.get()

        def singleton(sem):
            return sem.get()
    """)
    assert not _rule_hits(ok, "unyielding-wait")
    elsewhere = _lint_file(mini_repo, "spark_rapids_tpu/io/r.py", """
        def fetch(result_q):
            return result_q.get()
    """)
    assert not _rule_hits(elsewhere, "unyielding-wait")


def test_unyielding_wait_acquire_and_join(mini_repo):
    bad = _lint_file(mini_repo, "spark_rapids_tpu/shuffle/manager.py", """
        def wait_all(lock, thread):
            lock.acquire()
            thread.join()
    """)
    assert len(_rule_hits(bad, "unyielding-wait")) == 2
    ok = _lint_file(mini_repo, "spark_rapids_tpu/exec/fused.py", """
        def try_lock(lock, thread):
            lock.acquire(blocking=False)
            thread.join(5.0)
    """)
    assert not _rule_hits(ok, "unyielding-wait")


# -------------------------------------------------------- raw-transfer

def test_raw_transfer_positive_and_instrumented(mini_repo):
    bad = _lint_file(mini_repo, "spark_rapids_tpu/exec/up.py", """
        import jax

        def upload(batch):
            return jax.device_put(batch)
    """)
    assert len(_rule_hits(bad, "raw-transfer")) == 1
    ok = _lint_file(mini_repo, "spark_rapids_tpu/exec/up2.py", """
        import jax
        from spark_rapids_tpu.obs import telemetry

        def upload(batch, nbytes):
            out = jax.device_put(batch)
            telemetry.record("h2d", "x.upload", nbytes)
            return out
    """)
    assert not _rule_hits(ok, "raw-transfer")


def test_raw_transfer_nested_closure_inherits_instrumentation(mini_repo):
    ok = _lint_file(mini_repo, "spark_rapids_tpu/shuffle/manager.py", """
        from spark_rapids_tpu.obs import telemetry

        def put(table, path, pool):
            telemetry.record("shuffle", "shuffle.write", 10)

            def write():
                with open(path, "wb") as f:
                    f.write(table)

            return pool.submit(write)
    """)
    assert not _rule_hits(ok, "raw-transfer")


def test_raw_transfer_shuffle_binary_write_positive(mini_repo):
    bad = _lint_file(mini_repo, "spark_rapids_tpu/shuffle/spiller.py", """
        def spill(path, payload):
            with open(path, "wb") as f:
                f.write(payload)
    """)
    assert len(_rule_hits(bad, "raw-transfer")) == 1


# ------------------------------------------------------- unknown-event

def test_unknown_event_positive_and_negative(mini_repo):
    bad = _lint_file(mini_repo, "spark_rapids_tpu/runtime/e.py", """
        from spark_rapids_tpu.obs import events as obs_events

        def go():
            obs_events.emit("sanitizer.oops", a=1)
    """)
    assert len(_rule_hits(bad, "unknown-event")) == 1
    ok = _lint_file(mini_repo, "spark_rapids_tpu/runtime/e2.py", """
        from spark_rapids_tpu.obs import events as obs_events

        def go():
            obs_events.emit("sanitizer.deadlock", cycle=[])
    """)
    assert not _rule_hits(ok, "unknown-event")


# -------------------------------------------------------- bare-except

def test_bare_except_positive_and_negative(mini_repo):
    bad = _lint_file(mini_repo, "spark_rapids_tpu/runtime/b.py", """
        def f():
            try:
                return 1
            except:
                return 2
    """)
    assert len(_rule_hits(bad, "bare-except")) == 1
    ok = _lint_file(mini_repo, "spark_rapids_tpu/runtime/b2.py", """
        def f():
            try:
                return 1
            except Exception:
                return 2
    """)
    assert not _rule_hits(ok, "bare-except")


# ------------------------------------------------------ engine-level

def test_parse_error_is_a_finding(mini_repo):
    findings = _lint_file(mini_repo, "spark_rapids_tpu/runtime/s.py",
                          "def broken(:\n")
    assert _rule_hits(findings, "parse-error")


def test_enclosing_function_innermost_first(mini_repo):
    path = mini_repo / "spark_rapids_tpu" / "runtime" / "nest.py"
    path.write_text(textwrap.dedent("""
        def outer():
            def inner():
                x = 1
                return x
            return inner
    """))
    ctx = FileContext.parse(str(path), "spark_rapids_tpu/runtime/nest.py")
    fns = ctx.enclosing_functions(4)
    assert [f.name for f in fns] == ["inner", "outer"]


def test_real_tree_is_clean():
    """The committed tree passes with zero findings — the same
    invariant ci/static_check.sh gates on."""
    engine = LintEngine(repo_root(), all_rules())
    findings = engine.run()
    assert not findings, "\n".join(f.render() for f in findings)


def test_rule_ids_stable():
    assert {r.id for r in all_rules()} == {
        "conf-registered", "conf-documented", "raw-sleep",
        "unyielding-wait", "raw-transfer", "unknown-event",
        "bare-except"}
