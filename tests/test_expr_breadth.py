"""Extended expression family: math/bitwise/string-breadth/conditional.

Each case is checked two ways, mirroring the reference's differential
strategy (integration_tests asserts.py): (1) device result vs the CPU
oracle for the same expression tree, and (2) anchored expectations
hand-derived from Spark 3.5 semantics for the corner cases.
"""

import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import arrow_to_device, device_to_arrow
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.exec.cpu_eval import eval_expr
from spark_rapids_tpu.expr import (
    Acos, Ascii, Asin, Atan2, BitwiseAnd, BitwiseNot, BitwiseOr, BitwiseXor,
    BoundReference, BRound, Cbrt, Ceil, Chr, ConcatWs, Cos, EvalContext,
    Exp, Floor, Greatest, Hex, Hypot, InitCap, Least, Literal, Log, Log1p,
    Logarithm, NaNvl, Nvl2, Pow, Rint, Round, ShiftLeft, ShiftRight,
    ShiftRightUnsigned, Signum, Sin, Sqrt, StringInstr, StringLocate,
    StringLPad, StringRepeat, StringReplace, StringReverse, StringRPad,
    StringTranslate, StringTrim, StringTrimLeft, StringTrimRight,
    SubstringIndex, Tanh, XxHash64,
)
from spark_rapids_tpu.sqltypes import StructField, StructType
from spark_rapids_tpu.sqltypes.datatypes import (
    double, integer, long, string,
)


def _device_eval(table: pa.Table, expr):
    b = arrow_to_device(table)
    col = expr.eval(EvalContext(b))
    out = ColumnBatch(StructType([StructField("r", col.dtype, True)]),
                      [col], b.num_rows)
    return device_to_arrow(out).column("r").to_pylist()


def _both(table: pa.Table, expr):
    dev = _device_eval(table, expr)
    cpu = eval_expr(expr, table).to_pylist()
    return dev, cpu


def _assert_parity(table, expr, rel=1e-9):
    dev, cpu = _both(table, expr)
    assert len(dev) == len(cpu)
    for d, c in zip(dev, cpu):
        if c is None:
            assert d is None, (d, c)
        elif isinstance(c, float):
            if math.isnan(c):
                assert d is not None and math.isnan(d), (d, c)
            else:
                assert d == pytest.approx(c, rel=rel), (d, c)
        else:
            assert d == c, (d, c)


def ref(i, dt=long):
    return BoundReference(i, dt, True)


FL = pa.table({"x": pa.array([4.0, -1.0, 0.0, None, 2.25, float("nan")],
                             pa.float64())})
IN = pa.table({"a": pa.array([7, -7, 0, None, 123456], pa.int64()),
               "b": pa.array([3, 2, 5, 4, None], pa.int64())})
ST = pa.table({"s": pa.array(["  hi  ", "héllo wörld", "", None, "a.b.c"],
                             pa.string())})


@pytest.mark.parametrize("cls", [Sqrt, Exp, Cbrt, Sin, Cos, Tanh, Signum,
                                 Rint])
def test_unary_math_parity(cls):
    _assert_parity(FL, cls(ref(0, double)))


@pytest.mark.parametrize("cls", [Asin, Acos])
def test_inverse_trig_domain(cls):
    t = pa.table({"x": pa.array([0.5, -2.0, 1.0, None], pa.float64())})
    _assert_parity(t, cls(ref(0, double)))


def test_log_domain_nulls():
    dev, cpu = _both(FL, Log(ref(0, double)))
    assert dev[1] is None and dev[2] is None  # log(-1), log(0) -> NULL
    assert math.isnan(dev[5]) and math.isnan(cpu[5])  # log(NaN) -> NaN
    assert dev[0] == pytest.approx(cpu[0])


def test_log1p_domain():
    t = pa.table({"x": pa.array([-0.5, -1.0, -2.0, 1.0], pa.float64())})
    dev, cpu = _both(t, Log1p(ref(0, double)))
    assert dev[1] is None and dev[2] is None
    assert dev[0] == pytest.approx(cpu[0])


def test_logarithm_base():
    t = pa.table({"b": pa.array([2.0, 10.0, -1.0], pa.float64()),
                  "x": pa.array([8.0, 1000.0, 5.0], pa.float64())})
    dev, cpu = _both(t, Logarithm(ref(0, double), ref(1, double)))
    assert dev[0] == pytest.approx(3.0)
    assert dev[1] == pytest.approx(3.0)
    assert dev[2] is None and cpu[2] is None


def test_pow_atan2_hypot():
    t = pa.table({"a": pa.array([2.0, 3.0, None], pa.float64()),
                  "b": pa.array([10.0, 4.0, 1.0], pa.float64())})
    for cls in (Pow, Atan2, Hypot):
        _assert_parity(t, cls(ref(0, double), ref(1, double)))


def test_round_half_up_vs_bround_half_even():
    t = pa.table({"x": pa.array([2.5, 3.5, -2.5, 2.45, None], pa.float64())})
    assert _device_eval(t, Round(ref(0, double), 0)) == \
        [3.0, 4.0, -3.0, 2.0, None]
    assert _device_eval(t, BRound(ref(0, double), 0)) == \
        [2.0, 4.0, -2.0, 2.0, None]
    assert _device_eval(t, Round(ref(0, double), 1)) == \
        [2.5, 3.5, -2.5, 2.5, None]


def test_round_integral_negative_scale():
    t = pa.table({"x": pa.array([125, -125, 114, None], pa.int64())})
    assert _device_eval(t, Round(ref(0), -1)) == [130, -130, 110, None]
    assert _device_eval(t, BRound(ref(0), -1)) == [120, -120, 110, None]


def test_ceil_floor_long():
    t = pa.table({"x": pa.array([2.1, -2.1, 5.0, None], pa.float64())})
    assert _device_eval(t, Ceil(ref(0, double))) == [3, -2, 5, None]
    assert _device_eval(t, Floor(ref(0, double))) == [2, -3, 5, None]


def test_bitwise_ops():
    for cls in (BitwiseAnd, BitwiseOr, BitwiseXor):
        _assert_parity(IN, cls(ref(0), ref(1)))
    _assert_parity(IN, BitwiseNot(ref(0)))


def test_shifts_java_mask():
    t = pa.table({"x": pa.array([1, -8, 1], pa.int64()),
                  "n": pa.array([65, 1, 63], pa.int64())})
    # 65 & 63 == 1 (Java masks the count)
    assert _device_eval(t, ShiftLeft(ref(0), ref(1))) == \
        [2, -16, -9223372036854775808]
    assert _device_eval(t, ShiftRight(ref(0), ref(1))) == [0, -4, 0]
    assert _device_eval(t, ShiftRightUnsigned(ref(0), ref(1))) == \
        [0, 9223372036854775804, 0]
    for cls in (ShiftLeft, ShiftRight, ShiftRightUnsigned):
        _assert_parity(t, cls(ref(0), ref(1)))


def test_hex():
    t = pa.table({"x": pa.array([255, 0, -1, 291, None], pa.int64())})
    assert _device_eval(t, Hex(ref(0))) == \
        ["FF", "0", "FFFFFFFFFFFFFFFF", "123", None]
    _assert_parity(t, Hex(ref(0)))


def test_greatest_least_skip_nulls():
    t = pa.table({"a": pa.array([1, None, None, 5], pa.int64()),
                  "b": pa.array([3, 2, None, 1], pa.int64()),
                  "c": pa.array([2, None, None, None], pa.int64())})
    e = Greatest(ref(0), ref(1), ref(2))
    assert _device_eval(t, e) == [3, 2, None, 5]
    _assert_parity(t, e)
    e = Least(ref(0), ref(1), ref(2))
    assert _device_eval(t, e) == [1, 2, None, 1]
    _assert_parity(t, e)


def test_greatest_nan_is_largest():
    t = pa.table({"a": pa.array([1.0, float("nan")], pa.float64()),
                  "b": pa.array([float("nan"), 2.0], pa.float64())})
    r = _device_eval(t, Greatest(ref(0, double), ref(1, double)))
    assert all(math.isnan(v) for v in r)
    r = _device_eval(t, Least(ref(0, double), ref(1, double)))
    assert r == [1.0, 2.0]


def test_nvl2_nanvl():
    t = pa.table({"a": pa.array([1.0, None, float("nan")], pa.float64()),
                  "b": pa.array([10.0, 20.0, 30.0], pa.float64())})
    assert _device_eval(t, Nvl2(ref(0, double), ref(1, double),
                                Literal(-1.0))) == [10.0, -1.0, 30.0]
    assert _device_eval(t, NaNvl(ref(0, double), ref(1, double))) == \
        [1.0, None, 30.0]


# --- strings ---


def test_trim_family():
    for cls, exp in [(StringTrim, ["hi", "héllo wörld", "", None, "a.b.c"]),
                     (StringTrimLeft, ["hi  ", "héllo wörld", "", None,
                                       "a.b.c"]),
                     (StringTrimRight, ["  hi", "héllo wörld", "", None,
                                        "a.b.c"])]:
        assert _device_eval(ST, cls(BoundReference(0, string, True))) == exp
        _assert_parity(ST, cls(BoundReference(0, string, True)))


def test_trim_custom_chars():
    t = pa.table({"s": pa.array(["xxabcxx", "xyyx", "abc"], pa.string())})
    e = StringTrim(BoundReference(0, string, True), "xy")
    assert _device_eval(t, e) == ["abc", "", "abc"]


def test_pad():
    t = pa.table({"s": pa.array(["abc", "abcdef", "", None], pa.string())})
    s = BoundReference(0, string, True)
    assert _device_eval(t, StringLPad(s, 5, "*")) == \
        ["**abc", "abcde", "*****", None]
    assert _device_eval(t, StringRPad(s, 5, "*")) == \
        ["abc**", "abcde", "*****", None]
    assert _device_eval(t, StringLPad(s, 5, "xy")) == \
        ["xyabc", "abcde", "xyxyx", None]
    for e in (StringLPad(s, 5, "xy"), StringRPad(s, 6, "ab")):
        _assert_parity(t, e)


def test_repeat_reverse():
    t = pa.table({"s": pa.array(["ab", "", "xyz", None], pa.string())})
    s = BoundReference(0, string, True)
    assert _device_eval(t, StringRepeat(s, 3)) == \
        ["ababab", "", "xyzxyzxyz", None]
    assert _device_eval(t, StringRepeat(s, 0)) == ["", "", "", None]
    assert _device_eval(t, StringReverse(s)) == ["ba", "", "zyx", None]


def test_reverse_utf8_chars():
    t = pa.table({"s": pa.array(["héllo"], pa.string())})
    assert _device_eval(t, StringReverse(
        BoundReference(0, string, True))) == ["olléh"]


def test_initcap():
    t = pa.table({"s": pa.array(["hello world", "SPARK sql", "a  b", None],
                                pa.string())})
    e = InitCap(BoundReference(0, string, True))
    assert _device_eval(t, e) == ["Hello World", "Spark Sql", "A  B", None]
    _assert_parity(t, e)


def test_instr_locate():
    t = pa.table({"s": pa.array(["hello", "ababab", "", None], pa.string())})
    s = BoundReference(0, string, True)
    assert _device_eval(t, StringInstr(s, "l")) == [3, 0, 0, None]
    assert _device_eval(t, StringInstr(s, "ab")) == [0, 1, 0, None]
    assert _device_eval(t, StringLocate(s, "ab", 2)) == [0, 3, 0, None]
    assert _device_eval(t, StringLocate(s, "ab", 0)) == [0, 0, 0, None]
    for e in (StringInstr(s, "ab"), StringLocate(s, "ab", 2)):
        _assert_parity(t, e)


def test_translate_with_delete():
    t = pa.table({"s": pa.array(["AaBbCc", "translate"], pa.string())})
    s = BoundReference(0, string, True)
    e = StringTranslate(s, "abc", "12")  # c deleted
    assert _device_eval(t, e) == ["A1B2C", "tr1nsl1te"]
    _assert_parity(t, e)


def test_replace_expanding_and_deleting():
    t = pa.table({"s": pa.array(["aaa", "banana", "", None], pa.string())})
    s = BoundReference(0, string, True)
    assert _device_eval(t, StringReplace(s, "a", "XY")) == \
        ["XYXYXY", "bXYnXYnXY", "", None]
    assert _device_eval(t, StringReplace(s, "an", "")) == \
        ["aaa", "ba", "", None]
    assert _device_eval(t, StringReplace(s, "aa", "b")) == \
        ["ba", "banana", "", None]
    for e in (StringReplace(s, "a", "XY"), StringReplace(s, "an", "")):
        _assert_parity(t, e)


def test_concat_ws_skips_nulls():
    t = pa.table({"a": pa.array(["x", None, "p"], pa.string()),
                  "b": pa.array(["y", "z", None], pa.string())})
    e = ConcatWs(",", BoundReference(0, string, True),
                 BoundReference(1, string, True))
    assert _device_eval(t, e) == ["x,y", "z", "p"]
    _assert_parity(t, e)


def test_ascii_chr():
    t = pa.table({"s": pa.array(["Abc", "", None], pa.string())})
    e = Ascii(BoundReference(0, string, True))
    assert _device_eval(t, e) == [65, 0, None]
    _assert_parity(t, e)
    t2 = pa.table({"n": pa.array([65, 97 + 256, 0, -5, 200, None],
                                 pa.int64())})
    # Spark: n<0 -> "", (n & 0xFF)==0 -> NUL char, 128-255 -> 2-byte UTF-8
    assert _device_eval(t2, Chr(ref(0))) == \
        ["A", "a", "\x00", "", "È", None]
    _assert_parity(t2, Chr(ref(0)))


def test_substring_index():
    t = pa.table({"s": pa.array(["a.b.c", "abc", "", None], pa.string())})
    s = BoundReference(0, string, True)
    assert _device_eval(t, SubstringIndex(s, ".", 2)) == \
        ["a.b", "abc", "", None]
    assert _device_eval(t, SubstringIndex(s, ".", -2)) == \
        ["b.c", "abc", "", None]
    assert _device_eval(t, SubstringIndex(s, ".", 5)) == \
        ["a.b.c", "abc", "", None]
    assert _device_eval(t, SubstringIndex(s, ".", 0)) == ["", "", "", None]
    for e in (SubstringIndex(s, ".", 2), SubstringIndex(s, ".", -1)):
        _assert_parity(t, e)


# --- xxhash64 vs canonical reference implementation ---

_XP1 = 0x9E3779B185EBCA87
_XP2 = 0xC2B2AE3D27D4EB4F
_XP3 = 0x165667B19E3779F9
_XP4 = 0x85EBCA77C2B2AE63
_XP5 = 0x27D4EB2F165667C5
_M = (1 << 64) - 1


def _rotl(x, r):
    return ((x << r) | (x >> (64 - r))) & _M


def _fmix(h):
    h ^= h >> 33
    h = (h * _XP2) & _M
    h ^= h >> 29
    h = (h * _XP3) & _M
    h ^= h >> 32
    return h


def _xxh64_py(data: bytes, seed: int) -> int:
    """Canonical XXH64 (public spec), little-endian."""
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + _XP1 + _XP2) & _M
        v2 = (seed + _XP2) & _M
        v3 = seed & _M
        v4 = (seed - _XP1) & _M
        while i + 32 <= n:
            for off, v in enumerate((v1, v2, v3, v4)):
                pass
            w = [int.from_bytes(data[i + 8 * k:i + 8 * k + 8], "little")
                 for k in range(4)]
            v1 = (_rotl((v1 + w[0] * _XP2) & _M, 31) * _XP1) & _M
            v2 = (_rotl((v2 + w[1] * _XP2) & _M, 31) * _XP1) & _M
            v3 = (_rotl((v3 + w[2] * _XP2) & _M, 31) * _XP1) & _M
            v4 = (_rotl((v4 + w[3] * _XP2) & _M, 31) * _XP1) & _M
            i += 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) +
             _rotl(v4, 18)) & _M
        for v in (v1, v2, v3, v4):
            h = ((h ^ (_rotl((v * _XP2) & _M, 31) * _XP1) & _M)
                 * _XP1 + _XP4) & _M
    else:
        h = (seed + _XP5) & _M
    h = (h + n) & _M
    while i + 8 <= n:
        w = int.from_bytes(data[i:i + 8], "little")
        h = (_rotl(h ^ ((_rotl((w * _XP2) & _M, 31) * _XP1) & _M), 27)
             * _XP1 + _XP4) & _M
        i += 8
    if i + 4 <= n:
        w = int.from_bytes(data[i:i + 4], "little")
        h = (_rotl(h ^ ((w * _XP1) & _M), 23) * _XP2 + _XP3) & _M
        i += 4
    while i < n:
        h = (_rotl(h ^ ((data[i] * _XP5) & _M), 11) * _XP1) & _M
        i += 1
    return _fmix(h)


def _signed(x):
    return x - (1 << 64) if x >= (1 << 63) else x


def test_xxhash64_long_matches_reference():
    vals = [0, 1, -1, 42, 2**62, -(2**40)]
    t = pa.table({"x": pa.array(vals, pa.int64())})
    dev = _device_eval(t, XxHash64(ref(0)))
    exp = [_signed(_xxh64_py((v & _M).to_bytes(8, "little"), 42))
           for v in vals]
    assert dev == exp


def test_xxhash64_int_matches_reference():
    vals = [0, 1, -1, 123456]
    t = pa.table({"x": pa.array(vals, pa.int32())})
    dev = _device_eval(t, XxHash64(ref(0, integer)))
    exp = [_signed(_xxh64_py((v & 0xFFFFFFFF).to_bytes(4, "little"), 42))
           for v in vals]
    assert dev == exp


def test_xxhash64_string_matches_reference():
    vals = ["", "a", "abcd", "hello wo", "The quick brown fox jumps over",
            "0123456789012345678901234567890123456789"]  # >32 bytes
    t = pa.table({"s": pa.array(vals, pa.string())})
    dev = _device_eval(t, XxHash64(BoundReference(0, string, True)))
    exp = [_signed(_xxh64_py(v.encode(), 42)) for v in vals]
    assert dev == exp


def test_xxhash64_null_keeps_seed_chain():
    t = pa.table({"a": pa.array([1, None], pa.int64()),
                  "b": pa.array([2, 2], pa.int64())})
    dev = _device_eval(t, XxHash64(ref(0), ref(1)))
    h0 = _xxh64_py((2).to_bytes(8, "little"),
                   _xxh64_py((1).to_bytes(8, "little"), 42))
    h1 = _xxh64_py((2).to_bytes(8, "little"), 42)
    assert dev == [_signed(h0), _signed(h1)]
    _assert_parity(t, XxHash64(ref(0), ref(1)))
