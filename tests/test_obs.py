"""Observability subsystem (obs/): event bus, span trees, event log,
reports, Prometheus dump, metrics-level filtering.

Covers the PR-4 contracts: bus subscription under concurrency, span-tree
construction under speculation (losing attempt marked discarded), event
log rotation + atomic finalize + round-trip identity, qualification on
a CPU-fallback query matching the NOT_ON_TPU explain, and the
<5% overhead guard with the event log disabled.
"""

import itertools
import json
import os
import threading
import time

import pytest

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.obs import eventlog, report
from spark_rapids_tpu.obs import spans as S
from spark_rapids_tpu.obs.events import (
    SCHEMA_VERSION,
    EventBus,
    EventHistory,
)


def _session(**conf):
    from spark_rapids_tpu.api.session import TpuSparkSession

    return TpuSparkSession(conf)


def _query(s, rows=600):
    df = s.createDataFrame({
        "k": [i % 7 for i in range(rows)],
        "v": [float(i) for i in range(rows)],
    })
    return (df.filter(F.col("v") > 5.0).groupBy("k")
            .agg(F.sum("v").alias("sv"), F.count("*").alias("n")))


# ------------------------------------------------------------- event bus

def test_bus_concurrent_emission_total_order():
    bus = EventBus()
    got = []
    bus.subscribe(got.append)
    n_threads, per = 8, 250

    def worker(t):
        for i in range(per):
            bus.emit("operator.span", operator=f"op{t}", wallNs=i,
                     deviceNs=0)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(got) == n_threads * per
    seqs = [e["seq"] for e in got]
    # a total order, no drops, no duplicates
    assert sorted(seqs) == list(range(1, n_threads * per + 1))
    assert bus.counts["operator.span"] == n_threads * per
    for e in got[:10]:
        assert e["schemaVersion"] == SCHEMA_VERSION
        assert "ts" in e and "queryId" in e


def test_bus_subscriber_errors_do_not_propagate():
    bus = EventBus()
    ok = []

    def bad(_ev):
        raise RuntimeError("subscriber bug")

    bus.subscribe(bad)
    bus.subscribe(ok.append)
    bus.emit("chaos", site="x")
    assert len(ok) == 1
    assert bus.subscriber_errors == 1
    bus.unsubscribe(bad)
    bus.emit("chaos", site="y")
    assert bus.subscriber_errors == 1


def test_event_history_ring_and_query_filter():
    h = EventHistory(capacity=100)
    for q in (1, 2):
        for i in range(10):
            h({"event": "compile", "queryId": q, "seq": i})
    assert h.last_query_id() == 2
    assert len(h.events(1)) == 10
    assert all(e["queryId"] == 2 for e in h.events(2))


# ------------------------------------------- span trees (incl. speculation)

def _synthetic_speculation_events():
    seq = itertools.count(1)

    def ev(event, **f):
        return {"event": event, "seq": next(seq), "ts": 0.0,
                "schemaVersion": SCHEMA_VERSION, "queryId": 1, **f}

    return [
        ev("query.start"),
        ev("stage.start", stage=5, name="result", tasks=2),
        ev("task.attempt.start", stage=5, task=0, attempt=0,
           worker="w0", speculative=False),
        ev("task.attempt.start", stage=5, task=1, attempt=0,
           worker="w1", speculative=False),
        ev("operator.span", stage=5, task=1, attempt=0,
           operator="TpuProjectExec", metric="opTime", wallNs=10_000,
           deviceNs=10_000),
        # the straggler gets a speculative duplicate...
        ev("task.attempt.start", stage=5, task=1, attempt=1,
           worker="w2", speculative=True),
        ev("operator.span", stage=5, task=1, attempt=1,
           operator="TpuProjectExec", metric="opTime", wallNs=4_000,
           deviceNs=4_000),
        # ...which commits first; the original attempt is discarded
        ev("task.attempt.end", stage=5, task=1, attempt=1, status="ok",
           wallMs=0.5, rows=10),
        ev("task.attempt.end", stage=5, task=1, attempt=0,
           status="discarded", wallMs=1.5, rows=None),
        ev("task.attempt.end", stage=5, task=0, attempt=0, status="ok",
           wallMs=0.3, rows=7),
        ev("stage.end", stage=5, name="result", status="ok"),
        ev("query.end", engine="eager", status="ok"),
    ]


def test_span_tree_speculation_loser_marked_discarded():
    trees = S.build_from_events(_synthetic_speculation_events())
    assert len(trees) == 1
    root = trees[0]
    assert root.status == "ok" and root.extra["engine"] == "eager"
    stage = root.children[0]
    assert stage.kind == "stage" and stage.name == "result"
    by_key = {(t.task, t.attempt): t for t in stage.children}
    loser = by_key[(1, 0)]
    winner = by_key[(1, 1)]
    assert loser.status == "discarded"
    assert winner.status == "ok" and winner.speculative
    # the losing attempt's operator spans are marked discarded too
    assert [c.status for c in loser.children] == ["discarded"]
    assert [c.status for c in winner.children] == ["ok"]
    # aggregation excludes discarded time but reports it separately
    totals = S.operator_totals(root)
    assert totals["TpuProjectExec"]["wallNs"] == 4_000
    assert totals["TpuProjectExec"]["discardedNs"] == 10_000
    # committed result rows come only from winning result-stage tasks
    assert S.task_rows(root) == 17
    assert S.tree_depth(root) == 4


def test_span_builder_live_query(tmp_path):
    s = _session(**{"spark.sql.shuffle.partitions": 2})
    try:
        out = _query(s).collect_arrow()
        root = s.obs.last_spans
        assert root is not None
        assert root.query_id == s.last_execution["queryId"]
        assert root.status == "ok"
        kinds = {sp.kind for sp in root.walk()}
        assert {"query", "stage", "task", "operator"} <= kinds
        assert out.num_rows == 7
    finally:
        s.stop()


# ------------------------------------------------------------- event log

def test_eventlog_rotation_and_finalize(tmp_path):
    d = str(tmp_path / "log")
    w = eventlog.EventLogWriter(d, rotate_bytes=4096)
    seq = itertools.count(1)

    def ev(event, **f):
        return {"event": event, "seq": next(seq), "ts": 1.5,
                "schemaVersion": SCHEMA_VERSION, "queryId": 3, **f}

    w(ev("query.start"))
    sent = [ev("operator.span", operator="Op" + "x" * 80,
               metric="opTime", wallNs=i, deviceNs=0)
            for i in range(120)]
    for e in sent:
        w(e)
    # still in progress: nothing finalized yet
    assert eventlog.log_files(d) == []
    assert any(p.endswith(".inprogress") for p in os.listdir(d))
    w(ev("query.end", engine="eager", status="ok"))
    files = eventlog.log_files(d, 3)
    assert len(files) > 1, "rotation should have produced parts"
    assert not any(p.endswith(".inprogress") for p in os.listdir(d))
    loaded = eventlog.load(d, 3)
    assert len(loaded) == 122
    # write order preserved across parts
    assert [e["seq"] for e in loaded] == list(range(1, 123))
    for e in loaded:
        assert eventlog.validate_event(e) == []


def test_eventlog_close_finalizes_crashed_query(tmp_path):
    d = str(tmp_path / "log")
    w = eventlog.EventLogWriter(d, rotate_bytes=1 << 20)
    w({"event": "query.start", "seq": 1, "ts": 0.0,
       "schemaVersion": SCHEMA_VERSION, "queryId": 9})
    w.close()  # session stop without query.end
    files = eventlog.log_files(d, 9)
    assert len(files) == 1
    trees = eventlog.load_spans(d, 9)
    assert trees[0].status == "unfinished"


def test_eventlog_round_trip_identical_span_tree(tmp_path):
    d = str(tmp_path / "log")
    s = _session(**{
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": d,
        "spark.sql.shuffle.partitions": 2,
    })
    try:
        _query(s).collect_arrow()
        qid = s.last_execution["queryId"]
        live = s.obs.last_spans
        trees = eventlog.load_spans(d, qid)
        assert len(trees) == 1
        assert trees[0].to_dict() == live.to_dict()
        # every line schema-validates
        for path in eventlog.log_files(d, qid):
            with open(path) as f:
                for line in f:
                    assert eventlog.validate_event(
                        json.loads(line)) == []
    finally:
        s.stop()


def test_eventlog_loader_rejects_bad_schema(tmp_path):
    p = tmp_path / "eventlog-q1-p1.jsonl"
    p.write_text('{"event": "nope.unknown", "seq": 1, "ts": 0, '
                 '"schemaVersion": 1, "queryId": 1}\n')
    with pytest.raises(eventlog.EventLogError):
        eventlog.load(str(p))
    assert eventlog.load(str(p), strict=False)


# --------------------------------------------------------------- reports

def test_qualification_on_cpu_fallback_query():
    import re

    from spark_rapids_tpu.explain import explain_potential_tpu_plan

    s = _session(**{
        "spark.rapids.sql.exec.Filter": False,
        "spark.sql.shuffle.partitions": 2,
    })
    try:
        q = _query(s)
        q.collect_arrow()
        rows = report.qualification_data(s)
        assert rows, "forced Filter fallback must appear"
        pairs = {(r["node"], r["reason"]) for r in rows}
        explain_pairs = set()
        for line in explain_potential_tpu_plan(
                q, mode="NOT_ON_TPU").splitlines():
            m = re.match(r"\s*(\w+) !NOT_ON_TPU (.+)$", line)
            if m:
                explain_pairs.add((m.group(1), m.group(2)))
        assert pairs == explain_pairs
        txt = report.qualification(s)
        assert "Filter" in txt and "kept on CPU" in txt
        prof = report.profile(s)
        assert "TPU profile" in prof and "top operators" in prof
        assert report.profile_data(s)["spanTreeDepth"] >= 3
    finally:
        s.stop()


def test_explain_executed_mode():
    from spark_rapids_tpu.explain import explain_potential_tpu_plan

    s = _session(**{"spark.sql.shuffle.partitions": 2})
    try:
        q = _query(s)
        q.collect_arrow()
        txt = explain_potential_tpu_plan(q, mode="EXECUTED")
        assert "Executed Plan" in txt
        assert "wall=" in txt and "total:" in txt
    finally:
        s.stop()


def test_prometheus_render():
    s = _session()
    try:
        _query(s).collect_arrow()
        txt = s.prometheus_metrics()
        assert "# TYPE srtpu_robustness_scheduler_tasksLaunched" in txt
        assert 'srtpu_events_total{event="query.start"}' in txt
        for line in txt.splitlines():
            assert line.startswith(("#", "srtpu_")), line
    finally:
        s.stop()


def test_robustness_metrics_keys_unchanged():
    """The unified-registry refactor must keep the exact key surface
    test_chaos.py / test_scheduler.py / bench.py consume."""
    s = _session()
    try:
        rm = s.robustness_metrics
        assert set(rm) == {"chaos", "retries", "shuffle", "scheduler",
                           "degrade", "admission", "sanitizer",
                           "device", "spill",
                           "artifactsQuarantined", "semaphoreTimeouts"}
        assert "queriesAdmitted" in rm["admission"]
        assert {"epoch", "fences", "recoveries"} <= set(rm["device"])
        assert "orphanedFilesSwept" in rm["spill"]
        assert set(rm["sanitizer"]) == {"cycles", "inversions",
                                        "victims", "enabled"}
        assert set(rm["shuffle"]) == {"fetchRetries", "checksumFailures",
                                      "orphanedFiles",
                                      "speculativeDiscards"}
        assert "tasksLaunched" in rm["scheduler"]
    finally:
        s.stop()


# ------------------------------------------------- metrics.level satellite

def test_metrics_level_filters_collection():
    from spark_rapids_tpu.runtime import metrics as M

    reg = M.MetricsRegistry(M.ESSENTIAL)
    dbg = reg.metric("debugOnly", M.DEBUG)
    mod = reg.metric("moderate", M.MODERATE)
    ess = reg.metric("essential", M.ESSENTIAL)
    dbg.add(5)
    mod.add(5)
    ess.add(5)
    # filtered metrics skip collection entirely (shared null sink)
    assert dbg is M.NULL_METRIC and dbg.value == 0
    assert mod is M.NULL_METRIC
    assert ess.value == 5
    assert set(reg.snapshot()) == {"essential"}
    with dbg.ns():
        pass  # no-op timing must still be a working context manager

    full = M.MetricsRegistry(M.DEBUG)
    d2 = full.metric("debugOnly", M.DEBUG)
    d2.add(3)
    assert full.snapshot()["debugOnly"] == 3


def test_metrics_level_conf_threads_into_plans():
    from spark_rapids_tpu.runtime import metrics as M

    s = _session(**{"spark.rapids.sql.metrics.level": "ESSENTIAL"})
    try:
        phys, _ = _query(s)._physical()
        assert phys.metrics.level == M.ESSENTIAL
    finally:
        s.stop()
    s = _session(**{"spark.rapids.sql.metrics.level": "DEBUG"})
    try:
        phys, _ = _query(s)._physical()
        assert phys.metrics.level == M.DEBUG
    finally:
        s.stop()


# ------------------------------------------------------- overhead guard

def test_obs_overhead_under_5pct_with_eventlog_disabled():
    """With the event log off, the always-on bus + span builder + the
    PR 6 transfer ledger (telemetry enabled, every H2D/D2H/shuffle site
    recording) must cost <5% of query wall time (plus a small absolute
    allowance for timer noise on shared CI hosts)."""

    def best_time(**conf):
        s = _session(**{"spark.sql.shuffle.partitions": 2, **conf})
        try:
            df = _query(s)
            df.collect_arrow()  # warm compiles
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                df.collect_arrow()
                best = min(best, time.perf_counter() - t0)
            return best
        finally:
            s.stop()

    t_off = best_time(**{"spark.rapids.tpu.obs.enabled": False,
                         "spark.rapids.tpu.telemetry.enabled": False})
    t_on = best_time(**{"spark.rapids.tpu.obs.enabled": True,
                        "spark.rapids.tpu.telemetry.enabled": True})
    assert t_on <= t_off * 1.05 + 0.05, (
        f"obs+telemetry overhead too high: {t_on:.4f}s with bus+ledger "
        f"vs {t_off:.4f}s without")


def test_obs_disabled_session_emits_nothing():
    from spark_rapids_tpu.obs import events as obs_events

    s = _session(**{"spark.rapids.tpu.obs.enabled": False})
    try:
        assert s.obs.bus is None and not obs_events.armed()
        _query(s).collect_arrow()
        assert s.obs.last_spans is None
        assert s.last_execution["engine"] is not None
    finally:
        s.stop()
