"""Hive UDF surface + session UDF registry (round-4 item: hiveUDFs +
the RapidsUDF dual interface; reference
org/apache/spark/sql/hive/rapids/hiveUDFs.scala,
sql-plugin-api/.../RapidsUDF.java)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.sqltypes.datatypes import double, long
from spark_rapids_tpu.udf.hive_udf import HiveGenericUDF, HiveSimpleUDF


@pytest.fixture()
def spark():
    s = TpuSparkSession({})
    yield s
    s.stop()


def _df(spark):
    return spark.createDataFrame(pa.table({
        "a": pa.array([1.0, 2.0, None, 4.0], type=pa.float64()),
        "b": pa.array([10.0, 20.0, 30.0, None], type=pa.float64()),
    }))


def test_hive_udf_cpu_rowwise(spark):
    class MulUdf(HiveSimpleUDF):
        returnType = double

        def evaluate(self, x, y):
            if x is None or y is None:
                return None
            return x * y

    spark.udf.registerHive("mymul", MulUdf())
    out = _df(spark).select(
        F.call_udf("mymul", F.col("a"), F.col("b")).alias("m")
    ).collect_arrow()
    assert out.column("m").to_pylist() == [10.0, 40.0, None, None]


def test_hive_udf_rapids_dual_interface_on_device(spark):
    """A Hive UDF that ALSO provides evaluate_columnar runs on device
    (the RapidsUDF contract) — asserted via explain placement."""
    import jax.numpy as jnp

    class MulUdf(HiveGenericUDF):
        def initialize(self, arg_types):
            return double

        def evaluate(self, x, y):  # pragma: no cover - device path wins
            return None if x is None or y is None else x * y

        def evaluate_columnar(self, x, y, xv, yv):
            # DeviceUDF convention: values..., then validities...
            return x * y, xv & yv

    spark.udf.registerHive("dmul", MulUdf())
    df = _df(spark).select(
        F.call_udf("dmul", F.col("a"), F.col("b")).alias("m"))
    txt = spark.explainPotentialTpuPlan(df)
    assert "CPU" not in txt, txt
    out = df.collect_arrow()
    assert out.column("m").to_pylist() == [10.0, 40.0, None, None]


def test_register_plain_function_compiles(spark):
    spark.udf.register("double_it", lambda x: x * 2 + 1,
                       returnType=long)
    t = spark.createDataFrame(pa.table({
        "v": pa.array([1, 2, 3], type=pa.int64())}))
    out = t.select(F.call_udf("double_it", F.col("v")).alias("o")
                   ).collect_arrow()
    assert out.column("o").to_pylist() == [3, 5, 7]


def test_register_device_udf(spark):
    import jax.numpy as jnp

    spark.udf.registerDevice(
        "clip10", lambda v, val: (jnp.minimum(v, 10.0), val), double)
    t = spark.createDataFrame(pa.table({
        "v": pa.array([5.0, 15.0, None], type=pa.float64())}))
    out = t.select(F.call_udf("clip10", F.col("v")).alias("o")
                   ).collect_arrow()
    assert out.column("o").to_pylist() == [5.0, 10.0, None]


def test_unregistered_raises(spark):
    with pytest.raises(KeyError, match="not registered"):
        F.call_udf("nope", F.col("a"))
