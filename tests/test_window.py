"""Window function differential tests (reference:
integration_tests/src/main/python/window_function_test.py pattern —
same query on device and CPU-oracle sessions, diff results)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
)


def _table(n=500, seed=0, with_nulls=True):
    rng = np.random.default_rng(seed)
    cat = rng.integers(0, 7, n)
    val = rng.integers(-50, 50, n).astype("int64")
    ts = rng.permutation(n).astype("int64")  # unique -> deterministic order
    amt = rng.random(n) * 100.0
    val_mask = rng.random(n) < 0.15 if with_nulls else np.zeros(n, bool)
    return pa.table({
        "cat": pa.array(cat, type=pa.int64()),
        "ts": pa.array(ts, type=pa.int64()),
        "val": pa.array(val, type=pa.int64(), mask=val_mask),
        "amt": pa.array(amt, type=pa.float64()),
    })


def _df(spark, **kw):
    return spark.createDataFrame(_table(**kw))


def test_row_number_rank_dense_rank():
    w = Window.partitionBy("cat").orderBy("ts")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts",
            F.row_number().over(w).alias("rn"),
            F.rank().over(w).alias("rk"),
            F.dense_rank().over(w).alias("drk")))


def test_rank_with_ties():
    # order by a low-cardinality key -> real peer groups
    w = Window.partitionBy("cat").orderBy("val")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark, with_nulls=False).select(
            "cat", "val",
            F.rank().over(w).alias("rk"),
            F.dense_rank().over(w).alias("drk"),
            F.percent_rank().over(w).alias("prk"),
            F.cume_dist().over(w).alias("cd")))


def test_ntile():
    w = Window.partitionBy("cat").orderBy("ts")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts", F.ntile(4).over(w).alias("q")))


def test_lead_lag():
    w = Window.partitionBy("cat").orderBy("ts")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts", "val",
            F.lead("val", 1).over(w).alias("nxt"),
            F.lag("val", 2).over(w).alias("prv"),
            F.lead("val", 1, default=-999).over(w).alias("nxt_d"),
            F.lag("amt", 1).over(w).alias("prv_amt")))


def test_running_aggregates():
    # default frame with ORDER BY: range unbounded preceding..current row
    w = Window.partitionBy("cat").orderBy("ts")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts", "val",
            F.sum("val").over(w).alias("run_sum"),
            F.count("val").over(w).alias("run_cnt"),
            F.min("val").over(w).alias("run_min"),
            F.max("val").over(w).alias("run_max"),
            F.avg("amt").over(w).alias("run_avg")))


def test_running_aggregates_with_peer_ties():
    # low-cardinality order key: the default RANGE frame includes full
    # peer runs — a real semantic difference from ROWS
    w = Window.partitionBy("cat").orderBy("val")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark, with_nulls=False).select(
            "cat", "val",
            F.sum("amt").over(w).alias("run_sum"),
            F.count("*").over(w).alias("run_cnt")))


def test_whole_partition_aggregate():
    w = Window.partitionBy("cat")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "val",
            F.sum("val").over(w).alias("part_sum"),
            F.max("amt").over(w).alias("part_max"),
            F.count("*").over(w).alias("part_cnt")))


@pytest.mark.parametrize("lo,hi", [(-2, 2), (-3, 0), (0, 3),
                                   (Window.unboundedPreceding, 1),
                                   (-1, Window.unboundedFollowing)])
def test_rows_frames(lo, hi):
    w = Window.partitionBy("cat").orderBy("ts").rowsBetween(lo, hi)
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts", "val",
            F.sum("val").over(w).alias("s"),
            F.min("val").over(w).alias("mn"),
            F.max("val").over(w).alias("mx"),
            F.count("val").over(w).alias("c"),
            F.avg("amt").over(w).alias("a")))


def test_range_frame_value_offsets():
    w = Window.partitionBy("cat").orderBy("val").rangeBetween(-10, 10)
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "val",
            F.sum("amt").over(w).alias("s"),
            F.count("amt").over(w).alias("c"),
            F.min("val").over(w).alias("mn")))


def test_range_frame_double_key():
    w = Window.partitionBy("cat").orderBy("amt").rangeBetween(-25.0, 25.0)
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark, with_nulls=False).select(
            "cat", "amt",
            F.count("*").over(w).alias("c"),
            F.sum("amt").over(w).alias("s")))


def test_desc_order():
    from spark_rapids_tpu.api.functions import col

    w = Window.partitionBy("cat").orderBy(col("ts").desc())
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts",
            F.row_number().over(w).alias("rn"),
            F.sum("val").over(w).alias("s")))


def test_no_partition_by():
    w = Window.orderBy("ts")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark, n=200).select(
            "ts", F.row_number().over(w).alias("rn"),
            F.sum("val").over(w).alias("s")))


def test_multiple_specs_in_one_select():
    w1 = Window.partitionBy("cat").orderBy("ts")
    w2 = Window.partitionBy("val").orderBy("ts")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts", "val",
            F.row_number().over(w1).alias("rn_cat"),
            F.count("*").over(w2).alias("cnt_val")))


def test_window_then_filter():
    w = Window.partitionBy("cat").orderBy("ts")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark)
        .withColumn("rn", F.row_number().over(w))
        .filter(F.col("rn") <= 3))


def test_string_min_max_falls_back():
    from spark_rapids_tpu.testing.asserts import assert_tpu_fallback_collect

    w = Window.partitionBy("cat")

    def q(spark):
        t = pa.table({
            "cat": pa.array([1, 1, 2, 2, 3], type=pa.int64()),
            "s": pa.array(["b", "a", "z", "x", "m"]),
        })
        return spark.createDataFrame(t).select(
            "cat", F.min("s").over(w).alias("mn"))

    assert_tpu_fallback_collect(q, "CpuWindowExec")


def test_first_value_over_window():
    w = Window.partitionBy("cat").orderBy("ts")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts",
            F.first("val").over(w).alias("fv")))


def test_range_frame_nulls_last():
    from spark_rapids_tpu.api.functions import col

    w = (Window.partitionBy("cat").orderBy(col("val").asc_nulls_last())
         .rangeBetween(-2, 2))
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "val", F.sum("val").over(w).alias("s"),
            F.count("val").over(w).alias("c")))


def test_range_frame_desc_cpu_oracle_semantics():
    # desc RANGE offsets fall back to CpuWindowExec; check Spark truth
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    w = (Window.partitionBy("cat").orderBy(col("v").desc())
         .rangeBetween(-2, 2))

    def q(spark):
        t = pa.table({"cat": pa.array([1, 1, 1, 1], type=pa.int64()),
                      "v": pa.array([1, 3, 7, 9], type=pa.int64()),
                      "amt": pa.array([1.0, 2.0, 4.0, 6.0])})
        return (spark.createDataFrame(t)
                .select("v", F.sum("amt").over(w).alias("s"))
                .orderBy("v"))

    out = with_tpu_session(lambda s: q(s).collect_arrow())
    assert out.column("s").to_pylist() == [3.0, 3.0, 10.0, 10.0]


def test_fractional_range_bounds():
    w = Window.partitionBy("cat").orderBy("amt").rangeBetween(-0.5, 0.5)
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark, with_nulls=False).select(
            "cat", "amt", F.count("*").over(w).alias("c")))


def test_negative_zero_order_key():
    w = Window.orderBy("x")

    def q(spark):
        t = pa.table({"x": pa.array([-0.0, 0.0, 1.0], type=pa.float64())})
        return spark.createDataFrame(t).select(
            "x", F.rank().over(w).alias("rk"))

    assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)


def test_window_in_filter_rejected():
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    w = Window.partitionBy("cat").orderBy("ts")

    def q(spark):
        df = _df(spark, n=50)
        try:
            df.filter(F.row_number().over(w) <= 1)
        except ValueError as e:
            return str(e)
        return None

    msg = with_tpu_session(q)
    assert msg and "window functions are not allowed" in msg


def test_nan_min_max_over_frames():
    w = Window.partitionBy("cat").orderBy("ts").rowsBetween(-10, 10)

    def q(spark):
        t = pa.table({
            "cat": pa.array([1, 1, 1, 2, 2], type=pa.int64()),
            "ts": pa.array([1, 2, 3, 1, 2], type=pa.int64()),
            "v": pa.array([1.0, float("nan"), 3.0, float("nan"),
                           float("nan")]),
        })
        return spark.createDataFrame(t).select(
            "cat", "ts",
            F.min("v").over(w).alias("mn"),
            F.max("v").over(w).alias("mx"))

    assert_tpu_and_cpu_are_equal_collect(q)


def test_float_range_offsets_over_int_key():
    w = (Window.partitionBy("cat").orderBy("val")
         .rangeBetween(-1.5, 1.5))
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark, with_nulls=False).select(
            "cat", "val", F.count("*").over(w).alias("c")))


def test_negative_lag_is_lead():
    w = Window.partitionBy("cat").orderBy("ts")
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts",
            F.lag("val", -1).over(w).alias("a"),
            F.lead("val", 1).over(w).alias("b")))


def test_range_frame_without_order_rejected():
    import pytest as _pytest

    w = Window.partitionBy("cat").rangeBetween(0, 0)
    with _pytest.raises(ValueError, match="requires\\s+ORDER BY"):
        F.sum("val").over(w)


def test_window_in_orderby_rejected():
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    w = Window.partitionBy("cat").orderBy("ts")

    def q(spark):
        df = _df(spark, n=50)
        try:
            df.orderBy(F.row_number().over(w))
        except ValueError as e:
            return str(e)
        return None

    assert "not allowed in orderBy" in with_tpu_session(q)


def test_last_aggregate_and_window():
    """last() as a group aggregate and over window frames (device vs
    oracle)."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.window import Window
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_and_cpu_are_equal_collect,
    )

    rng = np.random.default_rng(31)
    n = 1500
    vals = [float(v) if v % 7 else None
            for v in rng.integers(0, 100, n)]
    t = pa.table({
        "k": pa.array(rng.integers(0, 12, n), type=pa.int64()),
        "o": pa.array(np.arange(n), type=pa.int64()),
        "v": pa.array(vals, type=pa.float64())})
    mk = lambda s: s.createDataFrame(t)

    # group-agg last is order-sensitive (Spark calls it
    # non-deterministic): check the well-defined identity
    # last(o) == max(o) when input arrives in o-order, on the device
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    out = with_tpu_session(
        lambda s: mk(s).groupBy("k")
        .agg(F.last("o", ignorenulls=True).alias("lo"),
             F.max("o").alias("mo")).collect_arrow(),
        {"spark.sql.shuffle.partitions": 1})
    assert out.column("lo").to_pylist() == out.column("mo").to_pylist()

    w = Window.partitionBy("k").orderBy("o").rowsBetween(-3, 0)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: mk(s).select(
            "k", "o", F.last("v", ignorenulls=True).over(w).alias("lw"),
            F.first("v", ignorenulls=True).over(w).alias("fw")),
        conf={"spark.sql.shuffle.partitions": 2})


def test_window_stddev_variance_on_device():
    """Moment aggregates over windows run ON DEVICE via prefix-sum
    frame kernels (round-4 verdict item #8; reference RollingAggregation
    moment family)."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.window import Window
    from spark_rapids_tpu.testing.asserts import with_tpu_session

    rng = np.random.default_rng(5)
    t = pa.table({"k": pa.array(rng.integers(0, 4, 400)),
                  "v": pa.array(rng.random(400))})

    def q(spark):
        w = Window.partitionBy("k")
        return (spark.createDataFrame(t)
                .select("k", "v",
                        F.stddev("v").over(w).alias("sd"),
                        F.var_pop("v").over(w).alias("vp"))
                .collect_arrow().to_pandas())

    out = with_tpu_session(q)
    pdf = t.to_pandas()
    # compare per GROUP (row order across partitions is not guaranteed)
    want_sd = pdf.groupby("k").v.std()
    want_vp = pdf.groupby("k").v.var(ddof=0)
    got = out.groupby("k")[["sd", "vp"]].first()
    assert np.allclose(got.sd.to_numpy(),
                       want_sd.reindex(got.index).to_numpy())
    assert np.allclose(got.vp.to_numpy(),
                       want_vp.reindex(got.index).to_numpy())
    # and the value is constant within each group
    assert (out.groupby("k").sd.nunique() == 1).all()


def test_window_moments_place_on_device():
    """Placement check: no CPU fallback reason for moment windows."""
    import pyarrow as pa

    from spark_rapids_tpu.testing.asserts import with_tpu_session

    t = pa.table({"k": pa.array([1, 1, 2]), "v": pa.array([1.0, 2.0, 3.0])})

    def explain(spark):
        w = Window.partitionBy("k").orderBy("v")
        df = spark.createDataFrame(t).select(
            "k",
            F.stddev("v").over(w).alias("sd"),
            F.var_samp("v").over(w).alias("vs"),
            F.collect_list("v").over(
                w.rowsBetween(-2, 0)).alias("cl"))
        return spark.explainPotentialTpuPlan(df)

    txt = with_tpu_session(explain)
    assert "CPU" not in txt and "no device implementation" not in txt, txt


def test_window_collect_list_bounded_rows_device():
    w = Window.partitionBy("cat").orderBy("ts").rowsBetween(-2, 0)
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts",
            F.collect_list("val").over(w).alias("cl")),
        conf={"spark.sql.shuffle.partitions": 2})


def test_window_collect_set_bounded_rows_device():
    import numpy as np
    import pyarrow as pa

    rng = np.random.default_rng(9)
    t = pa.table({
        "cat": pa.array(rng.integers(0, 3, 200), type=pa.int64()),
        "ts": pa.array(rng.permutation(200), type=pa.int64()),
        "val": pa.array(rng.integers(0, 4, 200), type=pa.int64()),
    })
    from spark_rapids_tpu.testing.asserts import (
        with_cpu_session,
        with_tpu_session,
    )

    def q(spark):
        return spark.createDataFrame(t).select(
            "cat", "ts",
            F.collect_set("val").over(
                Window.partitionBy("cat").orderBy("ts")
                .rowsBetween(-3, 0)).alias("cs")).collect_arrow()

    got = with_tpu_session(q)
    want = with_cpu_session(q)
    gm = {(r["cat"], r["ts"]): frozenset(r["cs"])
          for r in got.to_pylist()}
    wm = {(r["cat"], r["ts"]): frozenset(r["cs"])
          for r in want.to_pylist()}
    assert gm == wm


def test_window_moments_over_rows_frames_device():
    w = Window.partitionBy("cat").orderBy("ts").rowsBetween(-3, 3)
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: _df(spark).select(
            "cat", "ts",
            F.stddev("amt").over(w).alias("sd"),
            F.var_pop("amt").over(w).alias("vp"),
            F.var_samp("amt").over(w).alias("vs")),
        conf={"spark.sql.shuffle.partitions": 2})
