"""Join family differential tests: cross, conditional (mixed), broadcast
hash, nested-loop, and existence joins — TPU device path vs CPU oracle
(reference: integration_tests join_test.py; GpuHashJoin.scala,
GpuBroadcastHashJoinExecBase.scala, GpuBroadcastNestedLoopJoinExecBase
.scala, ExistenceJoin.scala).
"""

import pytest

from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_cpu_session,
    with_tpu_session,
)

_CONF = {"spark.sql.shuffle.partitions": 4}
_NO_BROADCAST = {"spark.sql.shuffle.partitions": 4,
                 "spark.sql.autoBroadcastJoinThreshold": -1}


def _ab(s, n=40):
    a = s.createDataFrame({
        "k": [i % 7 for i in range(n)],
        "x": [i * 3 % 11 for i in range(n)],
    })
    b = s.createDataFrame({
        "k": [i % 5 for i in range(15)],
        "y": [i * 2 for i in range(15)],
    })
    return a, b


def test_cross_join():
    def q(s):
        a, b = _ab(s, 12)
        return a.crossJoin(b)

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


def test_cross_join_empty_side():
    def q(s):
        import pyarrow as pa

        a, _ = _ab(s, 6)
        e = s.createDataFrame(pa.table({
            "k": pa.array([], type=pa.int64()),
            "y": pa.array([], type=pa.int64())}))
        return a.crossJoin(e)

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


@pytest.mark.parametrize("how", ["inner", "left", "full", "left_semi",
                                 "left_anti"])
def test_conditional_equi_join(how):
    """Equi keys + an extra non-equi condition (cuDF mixed-join analog)."""

    def q(s):
        a, b = _ab(s)
        joined = a.join(b, (a["k"] == b["k"]) & (a["x"] < b["y"]), how=how)
        if how in ("left_semi", "left_anti"):
            return joined.select("k", "x")
        return joined

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


@pytest.mark.parametrize("how", ["inner", "left"])
def test_condition_only_join(how):
    """No equi keys at all -> nested loop join."""

    def q(s):
        a, b = _ab(s, 15)
        return a.join(b, a["x"] < b["y"], how=how)

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


def test_condition_only_full_join():
    def q(s):
        a, b = _ab(s, 10)
        return a.join(b, a["x"] < b["y"], how="full")

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


@pytest.mark.parametrize("how", ["inner", "left", "right", "full"])
def test_expression_join_keys_only(how):
    """Expression condition that is entirely equi-conjuncts."""

    def q(s):
        a, b = _ab(s)
        return a.join(b, a["k"] == b["k"], how=how)

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


def test_right_join_with_condition():
    def q(s):
        a, b = _ab(s)
        return a.join(b, (a["k"] == b["k"]) & (b["y"] > 4), how="right")

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_broadcast_vs_shuffled_same_result(how):
    """The broadcast planning path (small build side) must agree with the
    forced-shuffle path."""

    def q(s):
        a, b = _ab(s, 60)
        joined = a.join(b, on="k", how=how)
        cols = ["k", "x"] if how in ("left_semi", "left_anti") \
            else ["k", "x", "y"]
        return joined.select(*cols)

    bcast = with_tpu_session(lambda s: q(s).collect_arrow(), conf=_CONF)
    shuf = with_tpu_session(lambda s: q(s).collect_arrow(),
                            conf=_NO_BROADCAST)
    cpu = with_cpu_session(lambda s: q(s).collect_arrow(), conf=_CONF)
    from spark_rapids_tpu.testing.asserts import assert_tables_equal

    assert_tables_equal(bcast, cpu)
    assert_tables_equal(shuf, cpu)


def test_broadcast_plan_selected():
    """Plan inspection: small build side -> broadcast hash join exec."""
    from spark_rapids_tpu.exec.joins import (
        TpuBroadcastHashJoinExec,
        TpuShuffledHashJoinExec,
    )

    def plan_of(s, conf_threshold):
        a, b = _ab(s, 60)
        df = a.join(b, on="k", how="inner")
        phys, _ = df._physical()
        kinds = set()

        def walk(p):
            kinds.add(type(p))
            for c in p.children:
                walk(c)

        walk(phys)
        return kinds

    kinds = with_tpu_session(lambda s: plan_of(s, None), conf=_CONF)
    assert TpuBroadcastHashJoinExec in kinds
    kinds = with_tpu_session(lambda s: plan_of(s, -1), conf=_NO_BROADCAST)
    assert TpuShuffledHashJoinExec in kinds
    assert TpuBroadcastHashJoinExec not in kinds


def test_existence_join():
    """Existence join (IN-subquery planning shape): left rows + bool."""
    import pyarrow as pa

    from spark_rapids_tpu.plan import logical as L
    from spark_rapids_tpu.testing.asserts import assert_tables_equal

    def run(s):
        a, b = _ab(s, 20)
        plan = L.Join(a._plan, b._plan, "existence",
                      [a["k"].expr], [b["k"].expr], exists_name="has_dim")
        from spark_rapids_tpu.api.dataframe import DataFrame

        return DataFrame(plan, s).collect_arrow()

    tpu = with_tpu_session(run, conf=_CONF)
    cpu = with_cpu_session(run, conf=_CONF)
    assert isinstance(tpu, pa.Table)
    assert tpu.column("has_dim").type == pa.bool_()
    assert_tables_equal(tpu, cpu)


def test_join_key_type_promotion_expression():
    def q(s):
        a = s.createDataFrame({"k": [1, 2, 3, 4],
                               "x": [1.0, 2.0, 3.0, 4.0]})
        b = s.createDataFrame({"j": [2.0, 3.0, 5.0], "y": [20, 30, 50]})
        return a.join(b, a["k"] == b["j"], how="inner")

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


def test_self_join_same_names():
    def q(s):
        a, b = _ab(s, 25)
        c = b.withColumnRenamed("y", "x")
        return a.join(c, on="k", how="inner")

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


def test_conditional_join_with_nulls():
    def q(s):
        import pyarrow as pa

        a = s.createDataFrame(pa.table({
            "k": pa.array([1, None, 2, 3, None, 2], type=pa.int64()),
            "x": pa.array([1, 2, None, 4, 5, 6], type=pa.int64()),
        }))
        b = s.createDataFrame(pa.table({
            "k": pa.array([2, 3, None, 4], type=pa.int64()),
            "y": pa.array([5, None, 7, 8], type=pa.int64()),
        }))
        return a.join(b, (a["k"] == b["k"]) & (a["x"] < b["y"]), how="left")

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)
