"""New tuning/disable confs are actually wired (not doc-only entries)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.sqltypes.datatypes import long


@pytest.fixture()
def pq_dir(tmp_path):
    t = pa.table({"a": pa.array(np.arange(100), type=pa.int64()),
                  "s": pa.array([f"x{i}" for i in range(100)],
                                type=pa.string())})
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))
    return str(d)


def test_format_read_disable_falls_back_to_cpu_scan(pq_dir):
    spark = TpuSparkSession({
        "spark.rapids.sql.format.parquet.read.enabled": False,
        "spark.rapids.sql.explain": "NOT_ON_GPU",
    })
    try:
        df = spark.read.parquet(pq_dir).filter(F.col("a") > 10)
        phys, meta = df._physical()
        from spark_rapids_tpu.exec.operators import CpuFileScanExec

        def find_scan(n):
            if isinstance(n, CpuFileScanExec):
                return n
            for c in n.children:
                r = find_scan(c)
                if r is not None:
                    return r
            return None

        assert find_scan(phys) is not None, "scan must be on CPU path"
        assert df.collect_arrow().num_rows == 89
    finally:
        spark.stop()


def test_regexp_disable_moves_rlike_to_cpu(pq_dir):
    spark = TpuSparkSession({"spark.rapids.sql.regexp.enabled": False})
    try:
        df = spark.read.parquet(pq_dir).filter(
            F.col("s").rlike("x[0-9]"))
        from spark_rapids_tpu.plan.typesig import (
            expr_unsupported_reasons,
        )

        got = df.collect_arrow()
        assert got.num_rows == 100  # all rows match x[0-9]
        # the type-check engine reports the disable reason
        from spark_rapids_tpu.expr.core import BoundReference, Literal
        from spark_rapids_tpu.expr.regexexpr import RLike
        from spark_rapids_tpu.sqltypes.datatypes import string

        e = RLike(BoundReference(0, string, True), "x1")
        reasons = expr_unsupported_reasons(e, spark.rapids_conf)
        assert any("regexp.enabled" in r for r in reasons), reasons
    finally:
        spark.stop()


def test_udf_compiler_disable_uses_rowwise_fallback(pq_dir):
    spark = TpuSparkSession(
        {"spark.rapids.sql.udfCompiler.enabled": False})
    try:
        fn = F.udf(lambda x: x * 2 + 1, returnType=long)
        df = spark.read.parquet(pq_dir).select(
            fn(F.col("a")).alias("y"))
        got = df.collect_arrow()
        assert got.column("y").to_pylist() == [
            i * 2 + 1 for i in range(100)]
        # the marker kept its fallback (not compiled to device exprs)
        from spark_rapids_tpu.udf.pyudf import PythonUDF

        phys, _ = df._physical()

        def has_pyudf(n):
            for e in getattr(n, "exprs", []) or []:
                stack = [e]
                while stack:
                    x = stack.pop()
                    if isinstance(x, PythonUDF):
                        return True
                    stack.extend(x.children)
            return any(has_pyudf(c) for c in n.children)

        assert has_pyudf(phys)
    finally:
        spark.stop()


def test_matmul_knobs_respected(pq_dir):
    # maxBins below the key space forces the scatter path even when
    # forced on; chunkRows flows into the plan
    from spark_rapids_tpu.ops import segmented

    with segmented.force_matmul_path(), \
            segmented.binned_bins(1000, max_bins=512):
        assert segmented.mm_bins_active() is None
    with segmented.force_matmul_path(), \
            segmented.binned_bins(1000, max_bins=2048, chunk=4096):
        assert segmented.mm_bins_active() == 1000
        assert segmented.mm_chunk() == 4096


def test_fused_knobs_construct():
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.exec.fused import FusedSingleChipExecutor

    conf = rc.RapidsConf({
        "spark.rapids.sql.fusedExec.expansionFactor": 8,
        "spark.rapids.sql.fusedExec.groupCapacity": 1 << 12,
        "spark.rapids.sql.fusedExec.maxExpansionFactor": 32,
        "spark.rapids.sql.fusedExec.singleSyncFetchMaxBytes": 1 << 10,
    })
    ex = FusedSingleChipExecutor(conf)
    assert ex._expansion == 8
    assert ex._group_cap == 1 << 12
    assert ex._max_expansion == 32
    assert ex._fetch_fused_bytes == 1 << 10
