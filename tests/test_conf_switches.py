"""New tuning/disable confs are actually wired (not doc-only entries)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.sqltypes.datatypes import long


@pytest.fixture()
def pq_dir(tmp_path):
    t = pa.table({"a": pa.array(np.arange(100), type=pa.int64()),
                  "s": pa.array([f"x{i}" for i in range(100)],
                                type=pa.string())})
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(t, str(d / "p.parquet"))
    return str(d)


def test_format_read_disable_falls_back_to_cpu_scan(pq_dir):
    spark = TpuSparkSession({
        "spark.rapids.sql.format.parquet.read.enabled": False,
        "spark.rapids.sql.explain": "NOT_ON_GPU",
    })
    try:
        df = spark.read.parquet(pq_dir).filter(F.col("a") > 10)
        phys, meta = df._physical()
        from spark_rapids_tpu.exec.operators import CpuFileScanExec

        def find_scan(n):
            if isinstance(n, CpuFileScanExec):
                return n
            for c in n.children:
                r = find_scan(c)
                if r is not None:
                    return r
            return None

        assert find_scan(phys) is not None, "scan must be on CPU path"
        assert df.collect_arrow().num_rows == 89
    finally:
        spark.stop()


def test_regexp_disable_moves_rlike_to_cpu(pq_dir):
    spark = TpuSparkSession({"spark.rapids.sql.regexp.enabled": False})
    try:
        df = spark.read.parquet(pq_dir).filter(
            F.col("s").rlike("x[0-9]"))
        from spark_rapids_tpu.plan.typesig import (
            expr_unsupported_reasons,
        )

        got = df.collect_arrow()
        assert got.num_rows == 100  # all rows match x[0-9]
        # the type-check engine reports the disable reason
        from spark_rapids_tpu.expr.core import BoundReference, Literal
        from spark_rapids_tpu.expr.regexexpr import RLike
        from spark_rapids_tpu.sqltypes.datatypes import string

        e = RLike(BoundReference(0, string, True), "x1")
        reasons = expr_unsupported_reasons(e, spark.rapids_conf)
        assert any("regexp.enabled" in r for r in reasons), reasons
    finally:
        spark.stop()


def test_udf_compiler_disable_uses_rowwise_fallback(pq_dir):
    spark = TpuSparkSession(
        {"spark.rapids.sql.udfCompiler.enabled": False})
    try:
        fn = F.udf(lambda x: x * 2 + 1, returnType=long)
        df = spark.read.parquet(pq_dir).select(
            fn(F.col("a")).alias("y"))
        got = df.collect_arrow()
        assert got.column("y").to_pylist() == [
            i * 2 + 1 for i in range(100)]
        # the marker kept its fallback (not compiled to device exprs)
        from spark_rapids_tpu.udf.pyudf import PythonUDF

        phys, _ = df._physical()

        def has_pyudf(n):
            for e in getattr(n, "exprs", []) or []:
                stack = [e]
                while stack:
                    x = stack.pop()
                    if isinstance(x, PythonUDF):
                        return True
                    stack.extend(x.children)
            return any(has_pyudf(c) for c in n.children)

        assert has_pyudf(phys)
    finally:
        spark.stop()


def test_matmul_knobs_respected(pq_dir):
    # maxBins below the key space forces the scatter path even when
    # forced on; chunkRows flows into the plan
    from spark_rapids_tpu.ops import segmented

    with segmented.force_matmul_path(), \
            segmented.binned_bins(1000, max_bins=512):
        assert segmented.mm_bins_active() is None
    with segmented.force_matmul_path(), \
            segmented.binned_bins(1000, max_bins=2048, chunk=4096):
        assert segmented.mm_bins_active() == 1000
        assert segmented.mm_chunk() == 4096


def test_fused_knobs_construct():
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.exec.fused import FusedSingleChipExecutor

    conf = rc.RapidsConf({
        "spark.rapids.sql.fusedExec.expansionFactor": 8,
        "spark.rapids.sql.fusedExec.groupCapacity": 1 << 12,
        "spark.rapids.sql.fusedExec.maxExpansionFactor": 32,
        "spark.rapids.sql.fusedExec.singleSyncFetchMaxBytes": 1 << 10,
    })
    ex = FusedSingleChipExecutor(conf)
    assert ex._expansion == 8
    assert ex._group_cap == 1 << 12
    assert ex._max_expansion == 32
    assert ex._fetch_fused_bytes == 1 << 10


def test_round5_knobs_wired():
    """The round-5 machinery's knobs are real: disabling the lookup
    join / shrinking the regex limits changes engine behavior."""
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSparkSession

    # regex complexity limit: a small limit rejects what the default
    # accepts — observe the MECHANISM (transpiler raises under the
    # session conf; compiles fine with conf limits bypassed) and the
    # end-to-end answer via CPU fallback
    from spark_rapids_tpu.regex.transpiler import (
        RegexUnsupported,
        compile_search,
    )

    s = TpuSparkSession({"spark.rapids.sql.regexp.complexityLimit": 4})
    try:
        import pytest as _pt

        with _pt.raises(RegexUnsupported, match="complexity gate"):
            compile_search("(ab){2}")  # reads the ACTIVE session conf
        compile_search("(ab){2}", loose_limits=True)  # default ok
        t = pa.table({"x": pa.array(["abab", "zz"])})
        out = (s.createDataFrame(t)
               .select(F.col("x").rlike("(ab){2}").alias("m"))
               .collect_arrow())
        assert out["m"].to_pylist() == [True, False]
    finally:
        s.stop()

    # lookup join off: the lowering predicate itself flips (mechanism)
    # and the star query stays correct via the blocking path
    s2 = TpuSparkSession({
        "spark.rapids.sql.fusedExec.lookupJoin.enabled": False,
        "spark.sql.shuffle.partitions": 2})
    try:
        from spark_rapids_tpu.exec.fused import FusedSingleChipExecutor

        assert FusedSingleChipExecutor(
            s2.rapids_conf)._lookup_conf is False
        fact = pa.table({"k": pa.array([0, 1, 0], pa.int64()),
                         "v": pa.array([1.0, 2.0, 4.0])})
        dim = pa.table({"k": pa.array([0, 1], pa.int64()),
                        "g": pa.array(["a", "b"])})
        out = (s2.createDataFrame(fact)
               .join(s2.createDataFrame(dim), on="k", how="inner")
               .groupBy("g").agg(F.sum("v").alias("sv"))
               .collect_arrow())
        assert dict(zip(out["g"].to_pylist(), out["sv"].to_pylist())) \
            == {"a": 5.0, "b": 2.0}
    finally:
        s2.stop()


def test_round5_maxstates_and_fold_knobs():
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.api.window import Window
    from spark_rapids_tpu.regex.transpiler import (
        RegexUnsupported,
        compile_search,
    )

    # maxStates: a tiny ceiling rejects under the session conf but the
    # LOOSE (CPU-path) compile still succeeds at the default
    s = TpuSparkSession({"spark.rapids.sql.regexp.maxStates": 2})
    try:
        with pytest.raises(RegexUnsupported, match="states"):
            compile_search("abc")
        compile_search("abc", loose_limits=True)
    finally:
        s.stop()

    # unboundedFoldEvery=1: fold after EVERY chunk, still exact
    s2 = TpuSparkSession({
        "spark.rapids.sql.window.unboundedFoldEvery": 1,
        "spark.rapids.sql.batchSizeRows": 128,
        "spark.rapids.sql.reader.batchSizeRows": 128,
        "spark.rapids.sql.fusedExec.enabled": False})
    try:
        n = 600
        rng = np.random.default_rng(4)
        t = pa.table({"g": pa.array(rng.integers(0, 3, n), pa.int64()),
                      "v": pa.array(rng.random(n))})
        w = Window.partitionBy("g")
        out = (s2.createDataFrame(t)
               .select("g", F.sum("v").over(w).alias("ts"))
               .collect_arrow())
        import collections
        acc = collections.defaultdict(float)
        for g, v in zip(t["g"].to_pylist(), t["v"].to_pylist()):
            acc[g] += v
        for g, ts in zip(out["g"].to_pylist(), out["ts"].to_pylist()):
            assert abs(ts - acc[g]) < 1e-9
    finally:
        s2.stop()
