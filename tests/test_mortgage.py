"""Mortgage ETL workload + external-source SPI + leak tracking."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    with_cpu_session,
    with_tpu_session,
)
from spark_rapids_tpu.testing.mortgage import (
    generate_mortgage_data,
    mortgage_etl,
    mortgage_summary,
)

_CONF = {"spark.sql.shuffle.partitions": 4}


@pytest.fixture(scope="module")
def paths(tmp_path_factory):
    return generate_mortgage_data(str(tmp_path_factory.mktemp("mtg")),
                                  scale_factor=0.05)


def test_mortgage_etl_matches_oracle(paths):
    got = with_tpu_session(
        lambda s: mortgage_etl(s, paths).collect_arrow(), _CONF)
    want = with_cpu_session(
        lambda s: mortgage_etl(s, paths).collect_arrow(), _CONF)
    assert_tables_equal(got, want)


def test_mortgage_summary_matches_oracle(paths):
    got = with_tpu_session(
        lambda s: mortgage_summary(s, paths).collect_arrow(), _CONF)
    want = with_cpu_session(
        lambda s: mortgage_summary(s, paths).collect_arrow(), _CONF)
    assert_tables_equal(got, want, ignore_order=False)


def test_mortgage_ml_handoff(paths):
    """ETL result exports zero-copy to device arrays (the
    XGBoost-feature handoff role)."""
    import jax

    from spark_rapids_tpu.api.columnar_rdd import ColumnarRdd

    def run(spark):
        return ColumnarRdd.to_jax(
            mortgage_etl(spark, paths).select("orig_rate", "dti",
                                              "credit_score"))

    arrays = with_tpu_session(run, _CONF)
    assert set(arrays) == {"orig_rate", "dti", "credit_score"}
    vals, valid = arrays["orig_rate"]
    assert isinstance(vals, jax.Array) and vals.shape == valid.shape


# ------------------------------------------------- external-source SPI

def test_external_source_registration():
    from spark_rapids_tpu.io.datasource import (
        register_format,
        unregister_format,
    )

    calls = []

    def ranges_reader(session, path, schema, options):
        calls.append(path)
        n = int(options.get("n", 10))
        return session.createDataFrame(pa.table({
            "i": pa.array(np.arange(n), type=pa.int64())}))

    register_format("ranges", ranges_reader)
    try:
        spark = TpuSparkSession(dict(_CONF))
        try:
            df = (spark.read.format("ranges").option("n", 25)
                  .load("dummy://x"))
            assert df.count() == 25
            assert calls == ["dummy://x"]
        finally:
            spark.stop()
    finally:
        unregister_format("ranges")


# ----------------------------------------------------- leak detection

def test_leak_detection_raises_on_unclosed_buffer():
    import pyarrow as _pa

    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.runtime.memory import get_catalog

    spark = TpuSparkSession({**_CONF,
                             "spark.rapids.memory.leakDetection": True})
    b = arrow_to_device(_pa.table({"x": _pa.array([1, 2, 3])}))
    sb = get_catalog().add_batch(b)
    with pytest.raises(AssertionError, match="leaked"):
        spark.stop()
    sb.close()
    spark.stop()  # clean now


def test_queries_do_not_leak(paths):
    """The engine's own operators close every spillable: a full query
    leaves the catalog empty under strict leak detection."""
    spark = TpuSparkSession({**_CONF,
                             "spark.rapids.memory.leakDetection": True})
    try:
        out = mortgage_summary(spark, paths).collect_arrow()
        assert out.num_rows > 0
    finally:
        spark.stop()  # raises if anything leaked
