import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession


def test_string_over_cap_falls_back_to_cpu():
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        big = "x" * 10000  # > 8192 default ceiling
        t = pa.table({"k": pa.array([1, 1, 2], pa.int64()),
                      "s": pa.array(["a", big, "b"])})
        df = (s.createDataFrame(t).filter(F.col("k") >= 1)
              .groupBy("k").agg(F.count("*").alias("c")))
        out = df.collect_arrow()
        got = dict(zip(out["k"].to_pylist(), out["c"].to_pylist()))
        assert got == {1: 2, 2: 1}, got
        rec = s.last_execution
        assert rec["engine"] == "cpu", rec
        assert any(e == "device" and "exceeds" in r
                   for e, r in rec["fallbacks"]), rec
        # select of the oversized value itself also round-trips
        o2 = s.createDataFrame(t).filter(F.col("k") == 1).collect_arrow()
        assert big in o2["s"].to_pylist()
    finally:
        s.stop()


def test_device_cached_over_cap_falls_back():
    # the CPU-fallback re-plan must NOT re-substitute device-cached
    # relations (their materialization re-raises the ceiling)
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        big = "y" * 9000
        t = pa.table({"k": pa.array([1, 2], pa.int64()),
                      "s": pa.array(["a", big])})
        df = s.createDataFrame(t).cache(storage="device")
        out = df.collect_arrow()
        assert sorted(out["k"].to_pylist()) == [1, 2]
        assert big in out["s"].to_pylist()
        assert s.last_execution["engine"] == "cpu"
    finally:
        s.stop()
