"""Struct columns on device (DeviceColumn.children struct-of-arrays):
scan, field extraction, construction, filters over fields, nulls,
shuffle serde, and planner key/aggregate gating."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession


@pytest.fixture()
def spark():
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    yield s
    s.stop()


def _struct_table(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 50, n)
    y = rng.random(n) * 100
    name = [f"n{i % 17}" for i in range(n)]
    svalid = rng.random(n) > 0.1
    s = pa.array(
        [{"x": int(a), "y": float(b), "name": c} if ok else None
         for a, b, c, ok in zip(x, y, name, svalid)],
        type=pa.struct([("x", pa.int64()), ("y", pa.float64()),
                        ("name", pa.string())]))
    return pa.table({"s": s,
                     "k": pa.array(rng.integers(0, 8, n),
                                   type=pa.int64())})


def test_struct_scan_roundtrip(spark, tmp_path):
    t = _struct_table()
    pq.write_table(t, str(tmp_path / "p.parquet"))
    got = spark.read.parquet(str(tmp_path)).collect_arrow()
    assert got.column("s").to_pylist() == t.column("s").to_pylist()


def test_struct_field_extraction_and_filter(spark, tmp_path):
    t = _struct_table()
    pq.write_table(t, str(tmp_path / "p.parquet"))
    df = spark.read.parquet(str(tmp_path))
    got = (df.select(F.col("s").getField("x").alias("x"),
                     F.col("s").getField("y").alias("y"))
           .filter(F.col("x") > 25)
           .collect_arrow())
    want = [(r["x"], r["y"]) for r in t.column("s").to_pylist()
            if r is not None and r["x"] > 25]
    assert sorted(got.column("x").to_pylist()) == sorted(
        w[0] for w in want)
    assert got.num_rows == len(want)
    # parent null -> field null
    nulls = (df.select(F.col("s").getField("x").alias("x"))
             .collect_arrow())
    want_x = [None if r is None else r["x"]
              for r in t.column("s").to_pylist()]
    assert nulls.column("x").to_pylist() == want_x


def test_struct_aggregate_over_field(spark, tmp_path):
    t = _struct_table()
    pq.write_table(t, str(tmp_path / "p.parquet"))
    df = spark.read.parquet(str(tmp_path))
    got = (df.groupBy("k")
           .agg(F.sum(F.col("s").getField("x")).alias("sx"))
           .collect_arrow())
    import collections

    want = collections.defaultdict(int)
    for r, k in zip(t.column("s").to_pylist(),
                    t.column("k").to_pylist()):
        if r is not None:
            want[k] += r["x"]
    got_m = dict(zip(got.column("k").to_pylist(),
                     got.column("sx").to_pylist()))
    assert got_m == dict(want)


def test_create_named_struct(spark):
    t = pa.table({"a": pa.array([1, 2, 3], type=pa.int64()),
                  "b": pa.array([1.5, 2.5, 3.5])})
    df = spark.createDataFrame(t)
    got = (df.select(F.struct(F.col("a"), F.col("b")).alias("s"))
           .collect_arrow())
    assert got.column("s").to_pylist() == [
        {"a": 1, "b": 1.5}, {"a": 2, "b": 2.5}, {"a": 3, "b": 3.5}]
    # extract back out of a constructed struct
    got2 = (df.select(F.struct(F.col("a"), F.col("b")).alias("s"))
            .select(F.col("s").getField("b").alias("b2"))
            .collect_arrow())
    assert got2.column("b2").to_pylist() == [1.5, 2.5, 3.5]


def test_struct_through_shuffle_serde():
    from spark_rapids_tpu.shuffle import serde

    t = _struct_table(300)
    r = serde.deserialize_table(serde.serialize_table(t, codec="zstd"))
    assert r.equals(t)


def test_struct_group_key_rejected_fields_work(spark, tmp_path):
    # struct keys have no orderable device lowering (and the CPU oracle
    # tier — pyarrow — cannot group by struct either): the planner
    # rejects them with a reason; grouping by the extracted FIELDS is
    # the supported shape
    t = _struct_table(500)
    pq.write_table(t, str(tmp_path / "p.parquet"))
    from spark_rapids_tpu.plan.typesig import key_type_supported
    from spark_rapids_tpu.sqltypes.datatypes import from_arrow_type

    assert "struct" in key_type_supported(
        from_arrow_type(t.column("s").type))
    df = spark.read.parquet(str(tmp_path))
    got = (df.select(F.col("s").getField("x").alias("x"))
           .groupBy("x").agg(F.count("*").alias("c"))
           .collect_arrow())
    import collections

    want = collections.Counter(
        None if r is None else r["x"]
        for r in t.column("s").to_pylist())
    got_c = dict(zip(got.column("x").to_pylist(),
                     got.column("c").to_pylist()))
    assert got_c == dict(want)


def test_struct_payload_left_join(spark):
    # struct columns riding through a join's null-padded build side:
    # unmatched probe rows must yield a NULL struct, matched rows the
    # right field values (the validity rebuild must not drop children)
    left = pa.table({"k": pa.array([1, 2, 3, 4], type=pa.int64())})
    s = pa.array([{"x": 10, "y": 1.0}, {"x": 20, "y": 2.0}],
                 type=pa.struct([("x", pa.int64()), ("y", pa.float64())]))
    right = pa.table({"k": pa.array([1, 3], type=pa.int64()), "s": s})
    got = (spark.createDataFrame(left)
           .join(spark.createDataFrame(right), on="k", how="left")
           .collect_arrow())
    pairs = sorted(zip(got.column(0).to_pylist(),
                       got.column("s").to_pylist()))
    assert pairs == [(1, {"x": 10, "y": 1.0}), (2, None),
                     (3, {"x": 20, "y": 2.0}), (4, None)]


def test_struct_payload_sort_on_device(spark):
    # struct payloads ride the device out-of-core sort (merge_sorted
    # recurses into children)
    t = _struct_table(400, seed=5)
    df = spark.createDataFrame(t).orderBy("k")
    got = df.collect_arrow()
    assert got.column("k").to_pylist() == sorted(
        t.column("k").to_pylist())
    import collections

    assert (collections.Counter(
        None if r is None else (r["x"], r["name"])
        for r in got.column("s").to_pylist())
        == collections.Counter(
            None if r is None else (r["x"], r["name"])
            for r in t.column("s").to_pylist()))


def test_struct_payload_multi_run_merge_sort(tmp_path):
    # small batch rows force MULTIPLE sort runs -> the merge kernel's
    # children-aware scatter path; rows must keep their struct fields
    # paired with the sort key
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 1,
                         "spark.rapids.sql.batchSizeRows": 128,
                         "spark.rapids.sql.reader.batchSizeRows": 128})
    try:
        t = _struct_table(1000, seed=7)
        pq.write_table(t, str(tmp_path / "p.parquet"))
        got = (s.read.parquet(str(tmp_path))
               .orderBy("k").collect_arrow())
        ks = got.column("k").to_pylist()
        assert ks == sorted(t.column("k").to_pylist())
        # field values stay row-paired through the merge
        import collections

        want_pairs = collections.Counter(
            (k, None if r is None else (r["x"], r["name"]))
            for k, r in zip(t.column("k").to_pylist(),
                            t.column("s").to_pylist()))
        got_pairs = collections.Counter(
            (k, None if r is None else (r["x"], r["name"]))
            for k, r in zip(ks, got.column("s").to_pylist()))
        assert got_pairs == want_pairs
    finally:
        s.stop()


def test_struct_mesh_falls_back(tmp_path):
    # the mesh tier has no struct lowering: MeshCompileError routes the
    # query to the single-chip engines, results correct
    s = TpuSparkSession({"spark.rapids.tpu.mesh": 4,
                         "spark.sql.shuffle.partitions": 4})
    try:
        t = _struct_table(300, seed=9)
        pq.write_table(t, str(tmp_path / "p.parquet"))
        got = (s.read.parquet(str(tmp_path))
               .select(F.col("s").getField("x").alias("x"))
               .collect_arrow())
        want = [None if r is None else r["x"]
                for r in t.column("s").to_pylist()]
        assert sorted([v for v in got.column("x").to_pylist()
                       if v is not None]) == sorted(
            [v for v in want if v is not None])
    finally:
        s.stop()


def test_struct_device_concat_and_cache(spark, tmp_path):
    # multi-file scan concatenates struct batches on device; the
    # device-resident cache serves them back
    t = _struct_table(1200, seed=3)
    pq.write_table(t.slice(0, 600), str(tmp_path / "p0.parquet"))
    pq.write_table(t.slice(600), str(tmp_path / "p1.parquet"))
    base = spark.read.parquet(str(tmp_path)).cache(storage="device")
    got = base.collect_arrow()
    assert sorted(got.column("k").to_pylist()) == sorted(
        t.column("k").to_pylist())
    xs = [None if r is None else r["x"]
          for r in got.column("s").to_pylist()]
    want_xs = [None if r is None else r["x"]
               for r in t.column("s").to_pylist()]
    assert sorted(x for x in xs if x is not None) == sorted(
        x for x in want_xs if x is not None)


def test_empty_struct_is_legal(spark):
    # struct() with no fields is legal Spark
    t = pa.table({"a": pa.array([1, 2, 3], type=pa.int64())})
    got = (spark.createDataFrame(t)
           .select(F.struct().alias("s"), F.col("a")).collect_arrow())
    assert got.column("s").to_pylist() == [{}, {}, {}]


def test_sliced_nested_serde_no_copy_path():
    from spark_rapids_tpu.shuffle import serde

    big = pa.table({"s": pa.array(
        [{"x": i} for i in range(100)],
        type=pa.struct([("x", pa.int64())]))})
    sl = big.slice(37, 20)  # offset != 0: the shuffle map-slice shape
    r = serde.deserialize_table(serde.serialize_table(sl))
    assert r.column("s").to_pylist() == sl.column("s").to_pylist()


def test_struct_conditionals_fall_back(spark):
    # If/Coalesce/CaseWhen device lowerings rebuild columns without
    # children: struct operands must tag to the CPU path (regression:
    # the ALL signature briefly admitted structs and crashed)
    t = pa.table({"a": pa.array([1, None, 3], type=pa.int64()),
                  "b": pa.array([10, 20, 30], type=pa.int64())})
    df = spark.createDataFrame(t)
    s1 = F.struct(F.col("a"))
    s2 = F.struct(F.col("b").alias("a"))
    got = df.select(F.coalesce(s1, s2).alias("s")).collect_arrow()
    assert got.column("s").to_pylist() == [
        {"a": 1}, {"a": None}, {"a": 3}]
    got2 = (df.select(F.when(F.col("a").isNull(), s2)
                      .otherwise(s1).alias("s")).collect_arrow())
    assert got2.column("s").to_pylist() == [
        {"a": 1}, {"a": 20}, {"a": 3}]
