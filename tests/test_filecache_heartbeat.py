"""File cache (FileCache role), Alluxio path rewriting
(AlluxioUtils.scala), and the heartbeat control plane
(RapidsShuffleHeartbeatManager.scala)."""

import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import with_tpu_session


@pytest.fixture()
def mem_fs(tmp_path):
    """A fake remote filesystem: mem://<name> backed by a dict."""
    from spark_rapids_tpu.io import filecache

    store = {}
    reads = {"n": 0}

    def stat(path):
        data, ver = store[path]
        return filecache.RemoteFile(len(data), ver)

    def read(path):
        reads["n"] += 1
        return store[path][0]

    filecache.register_filesystem("mem", stat, read)
    return store, reads


def _parquet_bytes(t: pa.Table) -> bytes:
    import io

    buf = io.BytesIO()
    pq.write_table(t, buf)
    return buf.getvalue()


def test_remote_scan_through_filecache(tmp_path, mem_fs):
    store, reads = mem_fs
    rng = np.random.default_rng(1)
    t = pa.table({"k": pa.array(rng.integers(0, 3, 2000)),
                  "v": pa.array(rng.random(2000))})
    store["mem://bucket/data.parquet"] = (_parquet_bytes(t), "v1")

    conf = {"spark.rapids.filecache.enabled": True,
            "spark.rapids.filecache.path": str(tmp_path / "fc")}

    def q(spark):
        return (spark.read.parquet("mem://bucket/data.parquet")
                .groupBy("k").agg(F.sum("v").alias("s"))
                .collect_arrow().sort_by("k"))

    out1 = with_tpu_session(q, conf=conf)
    n_reads_first = reads["n"]
    out2 = with_tpu_session(q, conf=conf)
    assert out1.equals(out2)
    # second query served from the cache: no extra remote reads
    assert reads["n"] == n_reads_first
    want = t.to_pandas().groupby("k").v.sum()
    got = out1.to_pandas().set_index("k").s
    assert np.allclose(got.to_numpy(), want.to_numpy())


def test_filecache_version_invalidation(tmp_path, mem_fs):
    store, reads = mem_fs
    t1 = pa.table({"v": pa.array([1.0, 2.0])})
    t2 = pa.table({"v": pa.array([5.0, 6.0, 7.0])})
    store["mem://b/t.parquet"] = (_parquet_bytes(t1), "v1")
    conf = {"spark.rapids.filecache.enabled": True,
            "spark.rapids.filecache.path": str(tmp_path / "fc")}

    def q(spark):
        return spark.read.parquet("mem://b/t.parquet").collect_arrow()

    assert with_tpu_session(q, conf=conf).num_rows == 2
    store["mem://b/t.parquet"] = (_parquet_bytes(t2), "v2")
    # changed etag -> refetch, not a stale hit
    assert with_tpu_session(q, conf=conf).num_rows == 3


def test_filecache_eviction(tmp_path, mem_fs):
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.io import filecache

    store, _ = mem_fs
    conf = rc.RapidsConf({
        "spark.rapids.filecache.enabled": True,
        "spark.rapids.filecache.path": str(tmp_path / "fc"),
        "spark.rapids.filecache.maxBytes": 4096})
    cache = filecache.FileCache(conf)
    for i in range(8):
        store[f"mem://b/f{i}"] = (os.urandom(1024), "v")
        cache.localize(f"mem://b/f{i}")
        time.sleep(0.01)
    files = os.listdir(cache.base)
    total = sum(os.path.getsize(os.path.join(cache.base, f))
                for f in files)
    assert total <= 4096, (total, files)


def test_alluxio_rewrite_rules():
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.io.alluxio import rewrite_paths

    conf = rc.RapidsConf({
        "spark.rapids.alluxio.pathsToReplace":
            "s3://bucket1->alluxio://m:19998/bucket1;"
            "s3://b2->/local/b2"})
    out = rewrite_paths(
        ["s3://bucket1/x/y.parquet", "s3://b2/z.parquet",
         "/plain/path.parquet"], conf)
    assert out == ["alluxio://m:19998/bucket1/x/y.parquet",
                   "/local/b2/z.parquet", "/plain/path.parquet"]


def test_alluxio_automount_regex():
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.io.alluxio import rewrite_paths

    conf = rc.RapidsConf({
        "spark.rapids.alluxio.automount.regex": r"^s3://data-.*",
        "spark.rapids.alluxio.master": "am:19998"})
    out = rewrite_paths(
        ["s3://data-prod/t/p.parquet", "s3://other/x.parquet"], conf)
    assert out == ["alluxio://am:19998/data-prod/t/p.parquet",
                   "s3://other/x.parquet"]


def test_alluxio_rewrite_to_local_dir_end_to_end(tmp_path):
    """Rule targets a plain local dir: the scan reads the co-located
    copy without any remote fetch."""
    rng = np.random.default_rng(2)
    t = pa.table({"v": pa.array(rng.random(100))})
    local = tmp_path / "mirror" / "tbl"
    local.mkdir(parents=True)
    pq.write_table(t, str(local / "part-0.parquet"))

    conf = {"spark.rapids.alluxio.pathsToReplace":
            f"s3://warehouse->{tmp_path / 'mirror'}"}

    def q(spark):
        return (spark.read.parquet("s3://warehouse/tbl")
                .agg(F.sum("v").alias("s")).collect_arrow())

    out = with_tpu_session(q, conf=conf)
    assert abs(out.column("s")[0].as_py()
               - float(np.asarray(t.column("v")).sum())) < 1e-9


# ------------------------------------------------------------- heartbeat


def test_heartbeat_topology_convergence():
    from spark_rapids_tpu.parallel.heartbeat import (
        HeartbeatClient,
        HeartbeatServer,
    )

    srv = HeartbeatServer(timeout_ms=60000)
    try:
        seen_a = []
        a = HeartbeatClient(srv.address, "exec-a", "hostA", 7001,
                            interval_ms=60000,
                            on_new_peers=seen_a.extend)
        b = HeartbeatClient(srv.address, "exec-b", "hostB", 7002,
                            interval_ms=60000)
        # b registered after a: a learns about b on its next heartbeat
        a.poke()
        assert [p["executor_id"] for p in seen_a] == ["exec-b"]
        assert [p["executor_id"] for p in b.peers] == ["exec-a"]
        c = HeartbeatClient(srv.address, "exec-c", "hostC", 7003,
                            interval_ms=60000)
        a.poke()
        b.poke()
        assert {p["executor_id"] for p in a.peers} == {"exec-b",
                                                       "exec-c"}
        assert {p["executor_id"] for p in b.peers} == {"exec-a",
                                                       "exec-c"}
        assert {p["executor_id"] for p in c.peers} == {"exec-a",
                                                       "exec-b"}
        a.close()
        b.close()
        c.close()
    finally:
        srv.close()


def test_heartbeat_prunes_dead_executors():
    from spark_rapids_tpu.parallel.heartbeat import HeartbeatManager

    mgr = HeartbeatManager(timeout_ms=50)
    mgr.register("e1", "h1", 1)
    _, seq = mgr.register("e2", "h2", 2)
    assert len(mgr.live_peers()) == 2
    time.sleep(0.08)
    mgr.heartbeat("e2", last_seq=seq)  # only e2 stays alive
    live = [p["executor_id"] for p in mgr.live_peers()]
    assert live == ["e2"]
    # pruned executor heartbeats again -> told to re-register; the
    # registry must keep serving (no poisoned state)
    fresh, _ = mgr.heartbeat("e1", last_seq=0)
    assert fresh is None
    others, seq2 = mgr.register("e1", "h1", 1)
    assert [p["executor_id"] for p in others] == ["e2"]
    # e2 discovers the re-registered e1 via seq (prune-safe protocol)
    fresh2, _ = mgr.heartbeat("e2", last_seq=seq)
    assert [p["executor_id"] for p in fresh2] == ["e1"]


def test_heartbeat_dead_peers_snapshot_and_death_callbacks():
    """PR 3 satellite: expired executors surface via dead_peers() and
    on_death callbacks — the stage scheduler's eviction feed."""
    from spark_rapids_tpu.parallel.heartbeat import HeartbeatManager

    mgr = HeartbeatManager(timeout_ms=50)
    deaths = []
    mgr.on_death(deaths.append)
    mgr.register("e1", "h1", 1)
    _, seq = mgr.register("e2", "h2", 2)
    assert mgr.dead_peers() == []
    time.sleep(0.08)
    mgr.heartbeat("e2", last_seq=seq)  # triggers the prune of e1
    assert mgr.dead_peers() == ["e1"]
    assert deaths == ["e1"]
    # dead_peers is a snapshot, not a drain: still dead until rejoin
    assert mgr.dead_peers() == ["e1"] and deaths == ["e1"]


def test_heartbeat_evicted_executor_reregisters_with_fresh_seq():
    """PR 3 satellite: explicit eviction excludes the executor (fires
    the death callback once); a re-register RESURRECTS it with a fresh,
    strictly higher seq so peers re-discover it via the incremental
    protocol."""
    from spark_rapids_tpu.parallel.heartbeat import HeartbeatManager

    mgr = HeartbeatManager(timeout_ms=60000)
    deaths = []
    mgr.on_death(deaths.append)
    _, seq1 = mgr.register("e1", "h1", 1)
    mgr.register("e2", "h2", 2)
    mgr.evict("e1")
    assert "e1" in mgr.dead_peers() and deaths == ["e1"]
    assert [p["executor_id"] for p in mgr.live_peers()] == ["e2"]
    # an evicted executor's heartbeat gets the re-register signal
    fresh, _ = mgr.heartbeat("e1", last_seq=seq1)
    assert fresh is None
    others, seq2 = mgr.register("e1", "h1", 1)
    assert seq2 > seq1  # fresh seq: discovery replays it to peers
    assert [p["executor_id"] for p in others] == ["e2"]
    assert "e1" not in mgr.dead_peers()
    assert [p["executor_id"] for p in mgr.live_peers()] \
        == ["e2", "e1"] or \
        [p["executor_id"] for p in sorted(
            mgr.live_peers(), key=lambda p: p["seq"])] == ["e2", "e1"]
    # evicting an already-dead executor must not re-fire callbacks
    mgr.evict("e2")
    mgr.evict("e2")
    assert deaths == ["e1", "e2"]
