"""Scale-test harness at tiny scale: every query runs under both
backends and matches (the harness doubles as an integration sweep)."""

import pytest

from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    with_cpu_session,
    with_tpu_session,
)
from spark_rapids_tpu.testing.scaletest import (
    QUERIES,
    generate_data,
    run_scale_test,
)

_CONF = {"spark.sql.shuffle.partitions": 4}


@pytest.fixture(scope="module")
def paths(tmp_path_factory):
    d = tmp_path_factory.mktemp("scale")
    return generate_data(str(d), scale_factor=0.03, files_per_table=3)


@pytest.mark.parametrize("q", sorted(QUERIES))
def test_scale_query_matches_oracle(paths, q):
    ordered = q in ("q5", "q7", "q10")
    got = with_tpu_session(
        lambda s: QUERIES[q](s, paths).collect_arrow(), _CONF)
    want = with_cpu_session(
        lambda s: QUERIES[q](s, paths).collect_arrow(), _CONF)
    # ordered queries may tie on the sort key: compare as sets then
    assert_tables_equal(got, want, ignore_order=True)


def test_harness_runner(paths):
    res = with_tpu_session(
        lambda s: run_scale_test(s, paths, queries=["q1", "q5"]), _CONF)
    assert set(res) == {"q1", "q5"}
    assert all(v["rows"] > 0 and v["elapsed_s"] >= 0
               for v in res.values())
