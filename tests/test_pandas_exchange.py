"""Grouped/map/cogrouped pandas exchange (reference
GpuArrowEvalPythonExec family: GpuFlatMapGroupsInPandasExec,
GpuMapInPandasExec, GpuFlatMapCoGroupsInPandasExec — host-side execs
over the Arrow worker-process pool)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import with_tpu_session


def _data(n=500, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({"k": pa.array(rng.integers(0, 4, n)),
                     "v": pa.array(rng.random(n))})


def test_apply_in_pandas_grouped():
    t = _data()

    def center(pdf):
        pdf = pdf.copy()
        pdf["v"] = pdf["v"] - pdf["v"].mean()
        return pdf

    def q(spark):
        return (spark.createDataFrame(t).groupBy("k")
                .applyInPandas(center, "k bigint, v double")
                .collect_arrow())

    out = with_tpu_session(q)
    assert out.num_rows == t.num_rows
    assert abs(out.to_pandas().groupby("k").v.mean()).max() < 1e-12


def test_apply_in_pandas_changes_shape():
    """Result cardinality may differ per group (Spark contract)."""
    t = _data()

    def summarize(pdf):
        import pandas as pd

        return pd.DataFrame({"k": [pdf.k.iloc[0]],
                             "mean_v": [pdf.v.mean()],
                             "n": [len(pdf)]})

    def q(spark):
        return (spark.createDataFrame(t).groupBy("k")
                .applyInPandas(summarize, "k bigint, mean_v double, "
                                          "n bigint")
                .collect_arrow().sort_by("k").to_pandas())

    out = with_tpu_session(q)
    want = t.to_pandas().groupby("k").v.agg(["mean", "size"])
    assert np.allclose(out.mean_v.to_numpy(),
                       want["mean"].to_numpy())
    assert (out.n.to_numpy() == want["size"].to_numpy()).all()


def test_map_in_pandas():
    t = _data()

    def doubler(it):
        for pdf in it:
            pdf = pdf.copy()
            pdf["v"] = pdf["v"] * 2
            yield pdf[["v"]]

    def q(spark):
        return (spark.createDataFrame(t)
                .mapInPandas(doubler, "v double").collect_arrow())

    out = with_tpu_session(q)
    assert np.allclose(sorted(out.column("v").to_pylist()),
                       sorted((t.to_pandas().v * 2).tolist()))


def test_map_in_pandas_filtering_iterator():
    """The fn may drop rows / yield multiple frames per chunk."""
    t = _data()

    def keep_big(it):
        for pdf in it:
            yield pdf[pdf.v > 0.5][["k", "v"]]

    def q(spark):
        return (spark.createDataFrame(t)
                .mapInPandas(keep_big, "k bigint, v double")
                .collect_arrow())

    out = with_tpu_session(q)
    want = t.to_pandas().query("v > 0.5")
    assert out.num_rows == len(want)


def test_cogroup_apply_in_pandas():
    t1 = _data(500, 0)
    t2 = pa.table({"k": pa.array([0, 0, 1, 9]),
                   "w": pa.array([1.0, 2.0, 3.0, 4.0])})

    def merge_counts(lf, rf):
        import pandas as pd

        k = lf.k.iloc[0] if len(lf) else rf.k.iloc[0]
        return pd.DataFrame({"k": [k], "nl": [len(lf)],
                             "nr": [len(rf)]})

    def q(spark):
        a = spark.createDataFrame(t1).groupBy("k")
        b = spark.createDataFrame(t2).groupBy("k")
        return (a.cogroup(b)
                .applyInPandas(merge_counts,
                               "k bigint, nl bigint, nr bigint")
                .collect_arrow().sort_by("k").to_pandas())

    out = with_tpu_session(q)
    # key 9 exists only on the right: left side is an empty frame
    row9 = out[out.k == 9]
    assert len(row9) == 1 and int(row9.nl.iloc[0]) == 0 \
        and int(row9.nr.iloc[0]) == 1
    nl = t1.to_pandas().groupby("k").size()
    for k in (0, 1, 2, 3):
        assert int(out[out.k == k].nl.iloc[0]) == int(nl[k])


def test_cogroup_key_name_mismatch():
    t = _data(20)

    def q(spark):
        a = spark.createDataFrame(t).groupBy("k")
        b = spark.createDataFrame(
            pa.table({"j": pa.array([1])})).groupBy("j")
        with pytest.raises(ValueError, match="identical grouping"):
            a.cogroup(b)
        return True

    assert with_tpu_session(q)


def test_apply_in_pandas_after_device_ops():
    """The pandas exec consumes device-operator output through the
    host transition."""
    t = _data()

    def tag(pdf):
        pdf = pdf.copy()
        pdf["r"] = pdf["v"].rank()
        return pdf[["k", "r"]]

    def q(spark):
        return (spark.createDataFrame(t)
                .filter(F.col("v") > 0.2)
                .withColumn("v", F.col("v") * 10)
                .groupBy("k").applyInPandas(tag, "k bigint, r double")
                .collect_arrow())

    out = with_tpu_session(q)
    want_n = (t.to_pandas().v > 0.2).sum()
    assert out.num_rows == want_n


def test_map_in_pandas_partition_iterator_contract():
    """Spark contract: ONE invocation per partition over an iterator of
    all batches (state carries across chunks)."""
    t = _data(2000)

    def summarize(it):
        import pandas as pd

        total = sum(len(pdf) for pdf in it)
        yield pd.DataFrame({"n": [total]})

    def q(spark):
        return (spark.createDataFrame(t)
                .mapInPandas(summarize, "n bigint").collect_arrow())

    out = with_tpu_session(q)
    assert out.num_rows == 1
    assert out.column("n")[0].as_py() == 2000


def test_map_in_pandas_empty_yield():
    t = _data(100)

    def nothing(it):
        for pdf in it:
            if False:
                yield pdf

    def q(spark):
        return (spark.createDataFrame(t)
                .mapInPandas(nothing, "k bigint, v double")
                .collect_arrow())

    out = with_tpu_session(q)
    assert out.num_rows == 0
