"""Device-resident relation cache (exec/relation_cache.py): the Spark
CacheManager + InMemoryRelation pair with HBM as the storage tier.

The load-bearing property: after `df.cache(storage="device")` is
materialized, derived queries serve the relation from device batches —
no file re-read, no re-upload. Proven by deleting the source files and
re-querying.
"""

import os
import shutil

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.default_rng(7)
    n = 50_000
    t = pa.table({
        "store": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        "amount": pa.array(rng.random(n) * 100.0, type=pa.float64()),
        "qty": pa.array(rng.integers(1, 50, n), type=pa.int64()),
    })
    d = tmp_path / "cache_data"
    d.mkdir()
    pq.write_table(t, str(d / "part-0.parquet"), compression="NONE",
                   use_dictionary=False)
    return str(d), t


def _oracle(t):
    f = t.filter(pc.greater(t.column("amount"), 20.0))
    return {int(s): (c,) for s, c in zip(
        *[f.group_by("store").aggregate([("store", "count")]).column(i)
          .to_pylist() for i in (0, 1)])}


def _engine(df):
    out = (df.filter(F.col("amount") > 20.0)
           .groupBy("store").agg(F.count("*").alias("c"))
           .collect_arrow())
    return {int(s): (c,) for s, c in zip(
        out.column("store").to_pylist(), out.column("c").to_pylist())}


def test_device_cache_serves_after_source_deleted(data_dir):
    d, t = data_dir
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        base = spark.read.parquet(d).cache(storage="device")
        want = _oracle(t)
        assert _engine(base) == want  # first use materializes
        shutil.rmtree(d)  # files gone: only the device cache can serve
        assert not os.path.exists(d)
        assert _engine(base) == want
        # a second derived query shape also serves from the entry
        s = (base.groupBy("store")
             .agg(F.sum("qty").alias("sq")).collect_arrow())
        want_sq = {int(k): v for k, v in zip(
            *[t.group_by("store").aggregate([("qty", "sum")]).column(i)
              .to_pylist() for i in (0, 1)])}
        got_sq = {int(k): v for k, v in zip(
            s.column("store").to_pylist(), s.column("sq").to_pylist())}
        assert got_sq == want_sq
    finally:
        spark.stop()


def test_device_cache_eager_engine_path(data_dir):
    # with whole-stage fusion disabled, the per-operator engine consumes
    # the cached device parts through TpuCachedRelationExec
    d, t = data_dir
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2,
                             "spark.rapids.sql.fusedExec.enabled": False})
    try:
        base = spark.read.parquet(d).cache(storage="device")
        want = _oracle(t)
        assert _engine(base) == want
        shutil.rmtree(d)
        assert _engine(base) == want
    finally:
        spark.stop()


def test_device_cache_of_derived_plan(data_dir):
    d, t = data_dir
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        filtered = spark.read.parquet(d).filter(F.col("amount") > 20.0)
        filtered.cache(storage="device")
        out = filtered.groupBy("store").agg(
            F.count("*").alias("c")).collect_arrow()
        got = {int(s): (c,) for s, c in zip(
            out.column("store").to_pylist(), out.column("c").to_pylist())}
        assert got == _oracle(t)
        shutil.rmtree(d)
        out2 = filtered.groupBy("store").agg(
            F.count("*").alias("c")).collect_arrow()
        got2 = {int(s): (c,) for s, c in zip(
            out2.column("store").to_pylist(),
            out2.column("c").to_pylist())}
        assert got2 == _oracle(t)
    finally:
        spark.stop()


def test_unpersist_releases_entry(data_dir):
    d, t = data_dir
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        base = spark.read.parquet(d).cache(storage="device")
        _ = _engine(base)
        assert spark.cache_manager.lookup(base._plan) is not None
        base.unpersist()
        assert spark.cache_manager.lookup(base._plan) is None
        # files still exist: the query simply re-reads them
        assert _engine(base) == _oracle(t)
    finally:
        spark.stop()


def test_cached_df_collect_itself(data_dir):
    d, t = data_dir
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        base = spark.read.parquet(d).cache(storage="device")
        got = base.collect_arrow().sort_by("store")
        assert got.num_rows == t.num_rows
        assert (pc.sum(got.column("qty")).as_py()
                == pc.sum(t.column("qty")).as_py())
    finally:
        spark.stop()


def test_cold_cache_query_with_single_permit_no_deadlock(data_dir):
    # entry materialization runs a nested execute with a fresh task id;
    # with concurrentGpuTasks=1 a nested semaphore acquire under held
    # permits would deadlock — the fused executor must materialize
    # BEFORE taking permits
    import threading

    d, t = data_dir
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2,
                             "spark.rapids.sql.concurrentGpuTasks": 1})
    try:
        base = spark.read.parquet(d).cache(storage="device")
        result = {}

        def run():
            result["got"] = _engine(base)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        th.join(timeout=120)
        assert not th.is_alive(), "cold cached query deadlocked"
        assert result["got"] == _oracle(t)
    finally:
        spark.stop()


def test_cold_cache_eager_single_permit_no_deadlock(data_dir):
    # same deadlock shape on the PER-OPERATOR engine: operators acquire
    # permits before pulling their cached-relation child, so base
    # collect() must pre-materialize entries first
    import threading

    d, t = data_dir
    spark = TpuSparkSession({
        "spark.sql.shuffle.partitions": 2,
        "spark.rapids.sql.concurrentGpuTasks": 1,
        "spark.rapids.sql.fusedExec.enabled": False,
        "spark.sql.adaptive.enabled": False,
    })
    try:
        base = spark.read.parquet(d).cache(storage="device")
        result = {}

        def run():
            result["got"] = _engine(base)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        th.join(timeout=120)
        assert not th.is_alive(), "cold cached eager query deadlocked"
        assert result["got"] == _oracle(t)
    finally:
        spark.stop()


def test_host_blob_cache_still_works(data_dir):
    # the default cache() tier (result-blob, ParquetCachedBatchSerializer
    # analog) is unchanged
    d, t = data_dir
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        df = (spark.read.parquet(d).groupBy("store")
              .agg(F.sum("qty").alias("sq")).cache())
        a = df.collect_arrow()
        assert df._cache_blob is not None
        b = df.collect_arrow()
        assert a.equals(b)
    finally:
        spark.stop()


def test_canonical_match_across_independent_dataframes(data_dir):
    # Spark CacheManager canonicalization: caching ONE DataFrame makes
    # a freshly-built DataFrame over the same path serve from the cache
    # (round-4 verdict weak #9 — matching was object-identity before).
    d, t = data_dir
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        base = spark.read.parquet(d).cache(storage="device")
        fresh = spark.read.parquet(d)  # brand-new plan object
        want = _oracle(t)
        assert _engine(base) == want  # materializes the entry
        shutil.rmtree(d)  # only the cache can serve now
        assert _engine(fresh) == want
    finally:
        spark.stop()


def test_canonical_key_distinguishes_different_plans(data_dir, tmp_path):
    # a scan of a DIFFERENT path must not hit the cached entry
    d, t = data_dir
    d2 = tmp_path / "other"
    d2.mkdir()
    t2 = pa.table({"store": pa.array([1, 2], type=pa.int64()),
                   "amount": pa.array([1.0, 99.0]),
                   "qty": pa.array([3, 4], type=pa.int64())})
    pq.write_table(t2, str(d2 / "part-0.parquet"))
    spark = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    try:
        spark.read.parquet(d).cache(storage="device").collect_arrow()
        out = _engine(spark.read.parquet(str(d2)))
        assert out == _oracle(t2)
    finally:
        spark.stop()
