"""Delta Lake v1 tests: transaction-log replay, append/overwrite
commits, MERGE/DELETE/UPDATE rewrites — including interop with the
_delta_log JSON protocol (reference delta-lake/ module family)."""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.lakehouse.delta import DeltaTable, load_snapshot

_CONF = {"spark.sql.shuffle.partitions": 2}


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


def _df(spark, n=500, seed=0, key_start=0):
    rng = np.random.default_rng(seed)
    return spark.createDataFrame(pa.table({
        "id": pa.array(np.arange(key_start, key_start + n),
                       type=pa.int64()),
        "v": pa.array(rng.random(n), type=pa.float64()),
        "tag": pa.array([f"t{i % 5}" for i in range(n)],
                        type=pa.string()),
    }))


def test_write_read_roundtrip(spark, tmp_path):
    p = str(tmp_path / "t1")
    df = _df(spark)
    df.write.format("delta").mode("error").save(p)
    snap = load_snapshot(p)
    assert snap.version == 0 and len(snap.files) >= 1
    back = spark.read.format("delta").load(p).collect_arrow()
    assert back.sort_by("id").equals(df.collect_arrow().sort_by("id"))


def test_append_and_overwrite(spark, tmp_path):
    p = str(tmp_path / "t2")
    _df(spark, n=100).write.format("delta").save(p)
    _df(spark, n=50, key_start=100).write.format("delta") \
        .mode("append").save(p)
    assert spark.read.delta(p).count() == 150
    assert load_snapshot(p).version == 1
    _df(spark, n=30).write.format("delta").mode("overwrite").save(p)
    assert spark.read.delta(p).count() == 30
    assert load_snapshot(p).version == 2


def test_log_is_protocol_json(spark, tmp_path):
    """Commit files follow the open Delta layout other readers expect."""
    p = str(tmp_path / "t3")
    _df(spark, n=10).write.format("delta").save(p)
    log = os.path.join(p, "_delta_log", f"{0:020d}.json")
    actions = [json.loads(l) for l in open(log) if l.strip()]
    kinds = set()
    for a in actions:
        kinds.update(a.keys())
    assert "metaData" in kinds and "add" in kinds and \
        "commitInfo" in kinds
    meta = next(a["metaData"] for a in actions if "metaData" in a)
    schema = json.loads(meta["schemaString"])
    assert [f["name"] for f in schema["fields"]] == ["id", "v", "tag"]


def test_merge_upsert(spark, tmp_path):
    p = str(tmp_path / "t4")
    _df(spark, n=100, seed=1).write.format("delta").save(p)
    # source: updates ids 50..99, inserts 100..119
    src = _df(spark, n=70, seed=2, key_start=50)
    (DeltaTable.forPath(spark, p)
     .merge(src, "id")
     .whenMatchedUpdateAll()
     .whenNotMatchedInsertAll()
     .execute())
    out = spark.read.delta(p).collect_arrow().sort_by("id")
    assert out.num_rows == 120
    want_src = src.collect_arrow().sort_by("id")
    got_upper = out.slice(50, 70)
    assert got_upper.column("v").to_pylist() == \
        want_src.column("v").to_pylist()


def test_merge_delete_matched(spark, tmp_path):
    p = str(tmp_path / "t5")
    _df(spark, n=100).write.format("delta").save(p)
    src = _df(spark, n=20, key_start=10)
    (DeltaTable.forPath(spark, p)
     .merge(src, "id").whenMatchedDelete().execute())
    out = spark.read.delta(p).collect_arrow()
    ids = sorted(out.column("id").to_pylist())
    assert len(ids) == 80 and 10 not in ids and 29 not in ids


def test_delete_with_predicate(spark, tmp_path):
    p = str(tmp_path / "t6")
    _df(spark, n=100).write.format("delta").save(p)
    DeltaTable.forPath(spark, p).delete(F.col("id") < 40)
    out = spark.read.delta(p).collect_arrow()
    assert out.num_rows == 60
    assert min(out.column("id").to_pylist()) == 40


def test_update(spark, tmp_path):
    p = str(tmp_path / "t7")
    _df(spark, n=50).write.format("delta").save(p)
    DeltaTable.forPath(spark, p).update(
        F.col("id") >= 25, {"v": F.lit(0.0)})
    out = spark.read.delta(p).collect_arrow().sort_by("id")
    vs = out.column("v").to_pylist()
    assert all(v == 0.0 for v in vs[25:])
    assert all(v != 0.0 for v in vs[:25])


def test_read_runs_on_engine_scan(spark, tmp_path):
    p = str(tmp_path / "t8")
    _df(spark, n=100).write.format("delta").save(p)
    df = spark.read.delta(p).filter(F.col("id") > 50) \
        .groupBy("tag").agg(F.count("*").alias("n"))
    phys, _ = df._physical()

    def walk(x):
        yield x
        for c in x.children:
            yield from walk(c)

    names = [type(x).__name__ for x in walk(phys)]
    assert "TpuFileScanExec" in names, names
    total = sum(df.collect_arrow().column("n").to_pylist())
    assert total == 49


def test_checkpoint_roundtrip(spark, tmp_path):
    """Parquet checkpoints: written explicitly (or every 10th commit)
    and replayed through _last_checkpoint, with newer JSON commits
    layered on top."""
    from spark_rapids_tpu.lakehouse.delta import (
        load_snapshot,
        write_checkpoint,
    )

    p = str(tmp_path / "cp")
    _df(spark, n=60).write.format("delta").save(p)
    _df(spark, n=40, key_start=60).write.format("delta") \
        .mode("append").save(p)
    write_checkpoint(p)
    assert os.path.exists(os.path.join(p, "_delta_log",
                                       "_last_checkpoint"))
    # a commit after the checkpoint must layer on top of it
    _df(spark, n=10, key_start=100).write.format("delta") \
        .mode("append").save(p)
    snap = load_snapshot(p)
    assert snap.version == 2
    assert spark.read.delta(p).count() == 110


def test_auto_checkpoint_every_10_commits(spark, tmp_path):
    p = str(tmp_path / "cp10")
    _df(spark, n=10).write.format("delta").save(p)
    for i in range(10):
        _df(spark, n=5, key_start=10 + i * 5).write.format("delta") \
            .mode("append").save(p)
    assert os.path.exists(os.path.join(
        p, "_delta_log", f"{10:020d}.checkpoint.parquet"))
    assert spark.read.delta(p).count() == 60


# ---------------- file-level DML pruning (round-4 verdict item #7) ----


def _ranged_df(spark, lo, n=500, seed=None):
    rng = np.random.default_rng(seed if seed is not None else lo)
    return spark.createDataFrame(pa.table({
        "id": pa.array(np.arange(lo, lo + n), type=pa.int64()),
        "v": pa.array(rng.random(n), type=pa.float64()),
    }))


def _three_file_table(spark, p):
    """Three data files with disjoint id ranges [0,500) [1000,1500)
    [2000,2500) — one per append commit."""
    for i, lo in enumerate((0, 1000, 2000)):
        _ranged_df(spark, lo).write.format("delta").mode(
            "error" if i == 0 else "append").save(p)
    return load_snapshot(p)


def test_delete_prunes_untouched_files(spark, tmp_path):
    p = str(tmp_path / "prune1")
    snap0 = _three_file_table(spark, p)
    assert len(snap0.files) == 3
    by_range = {json.loads(a["stats"])["minValues"]["id"]: path
                for path, a in snap0.files.items()}
    DeltaTable.forPath(spark, p).delete(F.col("id") < 500)
    snap1 = load_snapshot(p)
    # files [1000,1500) and [2000,2500) kept their ORIGINAL add actions
    assert by_range[1000] in snap1.files
    assert by_range[2000] in snap1.files
    assert by_range[0] not in snap1.files
    out = spark.read.format("delta").load(p).collect_arrow()
    ids = sorted(out.column("id").to_pylist())
    assert len(ids) == 1000 and ids[0] == 1000 and ids[-1] == 2499
    # the commit records how many files pruning skipped
    with open(os.path.join(p, "_delta_log",
                           f"{snap1.version:020d}.json")) as f:
        infos = [json.loads(ln) for ln in f if ln.strip()]
    ci = next(a["commitInfo"] for a in infos if "commitInfo" in a)
    assert ci["prunedFiles"] == 2


def test_delete_provably_empty_is_noop(spark, tmp_path):
    p = str(tmp_path / "prune2")
    snap0 = _three_file_table(spark, p)
    DeltaTable.forPath(spark, p).delete(F.col("id") > 99_999)
    snap1 = load_snapshot(p)
    assert snap1.version == snap0.version  # no commit at all
    assert set(snap1.files) == set(snap0.files)


def test_update_prunes_untouched_files(spark, tmp_path):
    p = str(tmp_path / "prune3")
    snap0 = _three_file_table(spark, p)
    by_range = {json.loads(a["stats"])["minValues"]["id"]: path
                for path, a in snap0.files.items()}
    DeltaTable.forPath(spark, p).update(
        F.col("id") >= 2000, {"v": F.lit(-1.0)})
    snap1 = load_snapshot(p)
    assert by_range[0] in snap1.files
    assert by_range[1000] in snap1.files
    assert by_range[2000] not in snap1.files
    out = spark.read.format("delta").load(p).collect_arrow()
    got = {r["id"]: r["v"] for r in out.to_pylist()}
    assert all(got[i] == -1.0 for i in range(2000, 2500))
    assert all(got[i] != -1.0 for i in range(0, 500))


def test_merge_prunes_by_source_key_range(spark, tmp_path):
    p = str(tmp_path / "prune4")
    snap0 = _three_file_table(spark, p)
    by_range = {json.loads(a["stats"])["minValues"]["id"]: path
                for path, a in snap0.files.items()}
    src = spark.createDataFrame(pa.table({
        "id": pa.array([10, 20, 600], type=pa.int64()),
        "v": pa.array([9.0, 9.0, 9.0], type=pa.float64()),
    }))
    (DeltaTable.forPath(spark, p).merge(src, "id")
     .whenMatchedUpdateAll().whenNotMatchedInsertAll().execute())
    snap1 = load_snapshot(p)
    # source ids [10, 600] overlap only file [0,500): others untouched
    assert by_range[1000] in snap1.files
    assert by_range[2000] in snap1.files
    assert by_range[0] not in snap1.files
    out = spark.read.format("delta").load(p).collect_arrow()
    got = {r["id"]: r["v"] for r in out.to_pylist()}
    assert got[10] == 9.0 and got[20] == 9.0 and got[600] == 9.0
    assert len(got) == 1501  # 1500 original + inserted id 600
