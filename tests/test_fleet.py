"""Fleet layer suite (serve/router.py, serve/supervisor.py, server
dedupe window, client connect retry) — the fault-tolerant serving
fleet end to end.

Router routing/failover semantics run against STUB replicas (tiny
socket servers speaking serve/protocol.py with canned behaviors):
the process-global admission controller means two real daemons in one
process would share a drain valve, and stubs make death/refusal
deterministic. Real-execution fleet correctness (subprocess replicas,
kill -9 mid-soak, billing reconciliation) is covered by the
supervisor test here plus ci/fleet_check.sh.
"""

import hashlib
import socket
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime import admission, backoff
from spark_rapids_tpu.runtime.errors import (
    QueryCancelledError,
    QueryRejectedError,
)
from spark_rapids_tpu.serve import protocol
from spark_rapids_tpu.serve.client import ServeClient, ServeError
from spark_rapids_tpu.serve.plan_cache import affinity_key
from spark_rapids_tpu.serve.router import FleetRouter
from spark_rapids_tpu.serve.server import QueryServiceDaemon

STUB_TABLE = pa.table({"x": pa.array([1, 2, 3], pa.int64())})


class StubReplica:
    """A minimal protocol-speaking replica with a canned behavior:
    'ok' serves STUB_TABLE, 'busy'/'draining' refuse with a
    retryAfterMs hint, 'die' drops the connection mid-query (the
    kill -9 shape as the router sees it)."""

    def __init__(self, behavior: str = "ok"):
        self.behavior = behavior
        self.retry_after_ms = 40
        self.requests = []
        self.hellos = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._conns = []
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        self.port = self._listener.getsockname()[1]
        self._threads = [threading.Thread(target=self._accept,
                                          daemon=True)]
        self._threads[0].start()

    def _accept(self):
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                self._conns.append(sock)
            t = threading.Thread(target=self._serve, args=(sock,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, sock):
        try:
            hello = protocol.recv_json(sock, 1 << 20)
            with self._lock:
                self.hellos += 1
            protocol.send_json(sock, {
                "type": "hello_ok", "id": hello.get("id"),
                "version": 1, "tenant": hello.get("tenant"),
                "priorityClass": hello.get("priorityClass"),
                "priority": 0})
            sock.settimeout(0.2)
            while not self._stop.is_set():
                try:
                    msg = protocol.recv_json(sock, 1 << 20)
                except socket.timeout:
                    continue
                mtype = msg.get("type")
                if mtype == "query":
                    with self._lock:
                        self.requests.append(msg)
                    b = self.behavior
                    if b == "die":
                        sock.close()
                        return
                    if b in ("busy", "draining"):
                        protocol.send_json(sock, {
                            "type": "error", "id": msg.get("id"),
                            "code": b, "message": f"stub {b}",
                            "retryAfterMs": self.retry_after_ms})
                        continue
                    protocol.send_result(sock, {
                        "id": msg.get("id"), "queryId": 1,
                        "rows": STUB_TABLE.num_rows,
                        "planCache": "miss", "wallMs": 1.0},
                        STUB_TABLE)
                elif mtype == "cancel":
                    protocol.send_json(sock, {
                        "type": "cancel_ok", "id": msg.get("id"),
                        "cancelled": 1})
                elif mtype == "bye":
                    protocol.send_json(sock, {"type": "bye_ok",
                                              "id": msg.get("id")})
                    return
        except (ConnectionError, OSError, protocol.ProtocolError):
            return
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def stop(self):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for s in conns:
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)


def _endpoints(stubs):
    return [{"name": name, "host": "127.0.0.1", "port": s.port,
             "httpPort": None} for name, s in stubs.items()]


def _winner(akey, names):
    """The router's rendezvous choice for this affinity key (same
    formula as FleetRouter._candidates)."""
    return max(names, key=lambda n: hashlib.sha256(
        f"{akey}|{n}".encode()).hexdigest())


RANGE_SPEC = {"op": "range", "end": 10}


@pytest.fixture()
def stub_pair():
    stubs = {"a": StubReplica(), "b": StubReplica()}
    try:
        yield stubs
    finally:
        for s in stubs.values():
            s.stop()


@pytest.fixture()
def router(stub_pair):
    r = FleetRouter(endpoints=_endpoints(stub_pair),
                    conf={"spark.rapids.tpu.fleet.health.intervalMs":
                          100}).start()
    try:
        yield r
    finally:
        r.stop()


# ----------------------------------------------------------- routing


def test_router_routes_and_relays(router, stub_pair):
    with ServeClient("127.0.0.1", router.port, "acme") as c:
        t = c.query(RANGE_SPEC)
        assert t.equals(STUB_TABLE)
        assert c.last_result["replica"] in stub_pair
        assert c.last_result["requestId"].startswith("rt-")
    snap = router.stats_snapshot()
    assert snap["queriesRouted"] == 1
    assert snap["mintedRequestIds"] == 1


def test_router_forwards_client_request_id(router, stub_pair):
    with ServeClient("127.0.0.1", router.port, "acme") as c:
        c.query(RANGE_SPEC, request_id="my-idem-key")
        assert c.last_result["requestId"] == "my-idem-key"
    got = [m["requestId"] for s in stub_pair.values()
           for m in s.requests]
    assert got == ["my-idem-key"]


def test_router_affinity_consistent_and_spread(router, stub_pair):
    """Repeat specs pin to the rendezvous winner; distinct specs
    spread across the fleet."""
    akey = affinity_key("acme", RANGE_SPEC, {})
    w = _winner(akey, list(stub_pair))
    with ServeClient("127.0.0.1", router.port, "acme") as c:
        for _ in range(5):
            c.query(RANGE_SPEC)
            assert c.last_result["replica"] == w
        for n in range(30):
            c.query({"op": "range", "end": 100 + n})
    counts = {name: len(s.requests)
              for name, s in stub_pair.items()}
    assert counts[w] >= 5
    assert all(v > 0 for v in counts.values()), counts


def test_router_failover_on_dead_replica(router, stub_pair):
    """The rendezvous winner dies mid-query: the SAME requestId
    resubmits to the survivor and the client never sees the death."""
    akey = affinity_key("acme", RANGE_SPEC, {})
    w = _winner(akey, list(stub_pair))
    other = next(n for n in stub_pair if n != w)
    stub_pair[w].behavior = "die"
    with ServeClient("127.0.0.1", router.port, "acme") as c:
        t = c.query(RANGE_SPEC, request_id="failover-1")
        assert t.equals(STUB_TABLE)
        assert c.last_result["replica"] == other
    assert router.stats_snapshot()["failovers"] >= 1
    # both replicas saw the SAME idempotency key — that is what makes
    # the resubmit safe against a replica that died after executing
    assert [m["requestId"] for m in stub_pair[w].requests] == \
        ["failover-1"]
    assert [m["requestId"] for m in stub_pair[other].requests] == \
        ["failover-1"]


def test_router_reroutes_draining_with_cooldown(router, stub_pair):
    akey = affinity_key("acme", RANGE_SPEC, {})
    w = _winner(akey, list(stub_pair))
    other = next(n for n in stub_pair if n != w)
    stub_pair[w].behavior = "draining"
    stub_pair[w].retry_after_ms = 5000
    with ServeClient("127.0.0.1", router.port, "acme") as c:
        t = c.query(RANGE_SPEC)
        assert t.equals(STUB_TABLE)
        assert c.last_result["replica"] == other
    snap = router.stats_snapshot()
    assert snap["rerouted"] >= 1
    # the refusal's retryAfterMs hint cooled the drainer down
    assert router.health()["replicas"][w]["coolingDown"]


def test_router_unavailable_when_fleet_refuses(stub_pair):
    for s in stub_pair.values():
        s.behavior = "draining"
    r = FleetRouter(
        endpoints=_endpoints(stub_pair),
        conf={"spark.rapids.tpu.fleet.failover.maxAttempts": 2,
              "spark.rapids.tpu.serve.retryAfterMs": 30}).start()
    try:
        with ServeClient("127.0.0.1", r.port, "acme") as c:
            with pytest.raises(QueryRejectedError) as ei:
                c.query(RANGE_SPEC)
        assert getattr(ei.value, "reason", "") == "unavailable"
        assert getattr(ei.value, "retry_after_ms", 0) > 0
        assert r.stats_snapshot()["unavailable"] == 1
    finally:
        r.stop()


def test_router_readyz_aggregates_members(router, stub_pair):
    import json
    import urllib.request

    assert router.http_port
    with urllib.request.urlopen(
            f"http://127.0.0.1:{router.http_port}/readyz",
            timeout=5.0) as resp:
        body = json.loads(resp.read().decode())
    assert body["ready"] is True
    assert set(body["replicas"]) == set(stub_pair)
    # kill every stub: readiness degrades to 503 once probes notice
    for s in stub_pair.values():
        s.stop()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if not router.health()["ready"]:
            break
        time.sleep(0.05)
    assert not router.health()["ready"]


def test_router_fans_cancel_out(router, stub_pair):
    with ServeClient("127.0.0.1", router.port, "acme") as c:
        c.query(RANGE_SPEC)
        assert c.cancel() >= 1  # every touched replica answered


def test_router_leak_free_stop(stub_pair):
    r = FleetRouter(endpoints=_endpoints(stub_pair)).start()
    c = ServeClient("127.0.0.1", r.port, "acme")
    c.query(RANGE_SPEC)
    r.stop()
    assert r.leak_report() == {"connections": 0,
                               "handlerThreads": 0, "listener": 0}
    c.close()


# ------------------------------------------------- dedupe (real daemon)


@pytest.fixture(scope="module")
def fleet_session():
    s = TpuSparkSession({})
    yield s
    s.stop()


@pytest.fixture()
def daemon(fleet_session):
    d = QueryServiceDaemon(session=fleet_session).start()
    try:
        yield d
    finally:
        d.stop()


def test_dedupe_replays_exactly_once(daemon):
    """Resubmitting a completed requestId answers from the window:
    identical result, dedupe-flagged header, ONE execution, ONE bill."""
    with ServeClient.connect(daemon, "acme") as c:
        t1 = c.query(RANGE_SPEC, request_id="idem-1")
        assert not c.last_result.get("dedupe")
        served = daemon.status()["queriesServed"]
        t2 = c.query(RANGE_SPEC, request_id="idem-1")
        assert c.last_result["dedupe"] is True
        assert t2.equals(t1)
    st = daemon.status()
    assert st["queriesServed"] == served  # no second execution
    assert st["dedupe"]["replays"] == 1
    assert st["dedupe"]["completed"] == 1
    # billed once: the tenant ledger saw exactly one query
    assert st["tenants"]["acme"]["queries"] == 1


def test_dedupe_is_tenant_scoped(daemon):
    """The same requestId from two tenants is two executions — one
    tenant can never replay (or observe) another's results."""
    with ServeClient.connect(daemon, "acme") as a:
        a.query(RANGE_SPEC, request_id="shared-key")
    with ServeClient.connect(daemon, "globex") as b:
        b.query(RANGE_SPEC, request_id="shared-key")
        assert not b.last_result.get("dedupe")
    st = daemon.status()["dedupe"]
    assert st["completed"] == 2
    assert st["replays"] == 0


def test_dedupe_window_bounded():
    from spark_rapids_tpu.serve.server import _DedupeWindow

    w = _DedupeWindow(max_entries=2, max_bytes=1 << 20)
    for i in range(4):
        verdict, e = w.claim("t", f"k{i}")
        assert verdict == "run"
        w.complete(e, {"rows": 1}, b"x" * 10)
    snap = w.snapshot()
    assert snap["entries"] == 2
    assert snap["evictions"] == 2
    # an evicted id re-executes (claim says run, not replay)
    verdict, _e = w.claim("t", "k0")
    assert verdict == "run"
    # a retained id replays
    verdict, _e = w.claim("t", "k3")
    assert verdict == "replay"


# ------------------------------------------- SIGTERM drain escalation


def test_second_sigterm_escalates_wedged_drain(daemon):
    """Regression: a second TERM during an active drain cancels the
    stragglers and aborts the drain wait instead of being swallowed
    by the already-draining guard."""
    from spark_rapids_tpu.obs import events as obs_events

    daemon.drain_timeout_ms = 60_000  # a wedged drain would sit here
    ctrl = admission.get()
    holds = [ctrl.submit(obs_events.allocate_query_id(),
                         description="test:hold")
             for _ in range(ctrl.max_concurrent)]
    errors = []

    def submit_wedged():
        try:
            with ServeClient.connect(daemon, "acme") as c:
                c.query(RANGE_SPEC)
        except (QueryRejectedError, QueryCancelledError,
                ServeError, ConnectionError, OSError) as e:
            errors.append(e)

    t = threading.Thread(target=submit_wedged, daemon=True)
    try:
        t.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                daemon.status()["inFlight"] == 0:
            time.sleep(0.02)
        assert daemon.status()["inFlight"] == 1
        t0 = time.monotonic()
        daemon.handle_term_signal()  # first TERM: graceful stop
        while time.monotonic() < deadline and \
                daemon.state != "draining":
            time.sleep(0.02)
        assert daemon.state == "draining"
        daemon.handle_term_signal()  # second TERM: escalate
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and \
                daemon.state != "stopped":
            time.sleep(0.05)
        elapsed = time.monotonic() - t0
        assert daemon.state == "stopped"
        assert elapsed < 15.0  # nowhere near the 60s drain window
        t.join(timeout=5.0)
        assert errors, "the wedged query must have been unwound"
    finally:
        for h in holds:
            ctrl.finish(h, status="cancelled")
        ctrl.end_drain()


# --------------------------------------------- client connect retry


def test_connect_retry_rides_out_replica_boot(fleet_session):
    """satellite: a replica that is still booting (connection refused)
    must not surface ConnectionRefusedError when retry is conf'd."""
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    late = QueryServiceDaemon(session=fleet_session)
    late._conf_port = port
    before = backoff.counters().get("serve.connect", 0)

    def start_late():
        time.sleep(0.7)
        late.start()

    t = threading.Thread(target=start_late, daemon=True)
    t.start()
    try:
        with ServeClient("127.0.0.1", port, "acme",
                         connect_attempts=40,
                         connect_backoff_ms=100,
                         connect_max_backoff_ms=200) as c:
            assert c.ping()["type"] == "pong"
    finally:
        t.join(timeout=5.0)
        late.stop()
    # the retries landed in the shared backoff counter surface
    assert backoff.counters().get("serve.connect", 0) > before


def test_connect_exhaustion_surfaces_original_error():
    with pytest.raises(OSError):
        ServeClient("127.0.0.1", 1, "acme", connect_attempts=2,
                    connect_backoff_ms=10, connect_max_backoff_ms=20)


# --------------------------------------------- retryAfterMs hints


def test_draining_frames_carry_retry_after_hint(daemon):
    with ServeClient.connect(daemon, "acme") as c:
        daemon.drain(timeout_ms=500)
        with pytest.raises(QueryRejectedError) as ei:
            c.query(RANGE_SPEC)
        assert getattr(ei.value, "reason", "") == "draining"
        assert ei.value.retry_after_ms == 250  # the conf default


def test_busy_refusal_carries_retry_after_hint(daemon):
    daemon.max_connections = 0
    with pytest.raises(ServeError) as ei:
        ServeClient.connect(daemon, "acme")
    assert ei.value.code == "busy"
    assert ei.value.retry_after_ms == 250


def test_status_over_the_wire(daemon):
    with ServeClient.connect(daemon, "acme") as c:
        c.query(RANGE_SPEC, request_id="s1")
        st = c.status()
    assert st["queriesServed"] == 1
    assert st["dedupe"]["completed"] == 1


# ------------------------------------- real subprocess fleet (e2e)


@pytest.mark.slow
def test_supervisor_fleet_end_to_end():
    """Two real replica processes under a supervisor behind a router:
    serve, kill -9 the affinity target mid-stream, fail over with the
    same requestId, crash-loop the victim back, stop leak-free."""
    from spark_rapids_tpu.serve.supervisor import ReplicaSupervisor

    sup = ReplicaSupervisor(conf={}, replica_confs=[{}, {}]).start()
    rtr = None
    try:
        sup.wait_ready(timeout_ms=180_000)
        rtr = FleetRouter(
            supervisor=sup,
            conf={"spark.rapids.tpu.fleet.health.intervalMs": 100,
                  "spark.rapids.tpu.fleet.failover.maxAttempts": 6}
        ).start()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and \
                len(rtr.health()["routable"]) < 2:
            time.sleep(0.1)
        assert len(rtr.health()["routable"]) == 2
        with ServeClient("127.0.0.1", rtr.port, "acme",
                         connect_attempts=10) as c:
            t = c.query(RANGE_SPEC, request_id="e2e-1")
            assert t.num_rows == 10
            victim = c.last_result["replica"]
            assert sup.kill(victim)  # SIGKILL, the chaos shape
            # same spec, same affinity target — now dead: the router
            # must fail over to the survivor transparently
            t2 = c.query(RANGE_SPEC, request_id="e2e-2")
            assert t2.num_rows == 10
            assert c.last_result["replica"] != victim
        # the supervisor crash-loops the victim back to ready
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline and \
                len(sup.endpoints()) < 2:
            time.sleep(0.2)
        assert len(sup.endpoints()) == 2
        assert sup.stats_snapshot()["restarts"] >= 1
    finally:
        if rtr is not None:
            rtr.stop()
        sup.stop()
    # zero leaks: every replica process reaped
    for r in sup._replicas:
        assert r.proc is not None and r.proc.poll() is not None
    if rtr is not None:
        assert rtr.leak_report()["connections"] == 0
