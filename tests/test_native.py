"""Native C++ runtime tests: wire format, spark-exact host hashing parity
with the device kernels, row<->column conversion, host buffer pool, and
the file-backed MULTITHREADED shuffle end to end.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import native


def _require_native():
    if not native.available():
        pytest.skip("native toolchain unavailable")


def test_pack_unpack_roundtrip():
    _require_native()
    bufs = [np.arange(100, dtype=np.int64).view(np.uint8),
            np.array([], dtype=np.uint8),
            np.random.default_rng(0).integers(
                0, 255, 1000).astype(np.uint8)]
    packed = native.pack_buffers(bufs)
    out = native.unpack_buffers(packed)
    assert len(out) == 3
    for orig, got in zip(bufs, out):
        assert np.array_equal(orig.view(np.uint8).reshape(-1), got)


def test_pack_python_fallback_compatible():
    """The pure-Python pack and the native pack produce identical bytes
    (format stability across fallback)."""
    _require_native()
    bufs = [np.arange(17, dtype=np.int32).view(np.uint8),
            np.frombuffer(b"hello world", dtype=np.uint8)]
    sizes = np.array([b.nbytes for b in bufs], dtype=np.int64)
    a = native.pack_buffers(bufs)
    b = native._py_pack([b.reshape(-1) for b in bufs], sizes)
    assert np.array_equal(a, b)
    for orig, got in zip(bufs, native._py_unpack(a)):
        assert np.array_equal(orig.reshape(-1), got)


def _device_hash(table, fn_name, seed=42):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.columnar import arrow_to_device
    from spark_rapids_tpu.ops import hashing

    batch = arrow_to_device(table)
    n = batch.row_count()
    if fn_name == "murmur3":
        h = hashing.murmur3_columns(batch.columns, seed)
        return np.asarray(h)[:n]
    h = hashing.xxhash64_columns(batch.columns, seed)
    return np.asarray(h).view(np.int64)[:n]


def _host_columns(table):
    cols = []
    for col in table.columns:
        arr = col.combine_chunks()
        valid = (np.ones(len(arr), dtype=np.uint8)
                 if arr.null_count == 0 else
                 np.asarray(arr.is_valid()).astype(np.uint8))
        if pa.types.is_string(arr.type):
            pys = arr.to_pylist()
            bs = [(s or "").encode() for s in pys]
            mb = max(1, max((len(b) for b in bs), default=1))
            mat = np.zeros((len(bs), mb), dtype=np.uint8)
            lens = np.zeros(len(bs), dtype=np.int32)
            for i, b in enumerate(bs):
                mat[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
                lens[i] = len(b)
            cols.append((mat, lens, valid))
        else:
            vals = np.asarray(
                arr.fill_null(0) if arr.null_count else arr)
            cols.append((vals, valid))
    return cols


@pytest.fixture
def hash_table():
    rng = np.random.default_rng(11)
    n = 500
    ints = rng.integers(-10**9, 10**9, n)
    mask = rng.random(n) < 0.1
    return pa.table({
        "a": pa.array(ints, type=pa.int64(),
                      mask=mask),
        "b": pa.array(rng.integers(-1000, 1000, n), type=pa.int32()),
        "c": pa.array(rng.random(n) * 1000 - 500, type=pa.float64()),
        "f": pa.array((rng.random(n) * 10 - 5).astype(np.float32),
                      type=pa.float32()),
        "s": pa.array([f"key-{i % 37}-{'x' * (i % 11)}"
                       for i in range(n)]),
    })


def test_native_murmur3_matches_device(hash_table):
    _require_native()
    host = native.murmur3_host(_host_columns(hash_table))
    dev = _device_hash(hash_table, "murmur3")
    assert np.array_equal(host, dev)


def test_native_xxhash64_matches_device(hash_table):
    _require_native()
    host = native.xxhash64_host(_host_columns(hash_table))
    dev = _device_hash(hash_table, "xxhash64")
    assert np.array_equal(host, dev)


def test_rows_to_columns_roundtrip():
    _require_native()
    rng = np.random.default_rng(5)
    n = 257
    cols = [
        (rng.integers(-100, 100, n).astype(np.int64),
         (rng.random(n) < 0.9)),
        (rng.random(n).astype(np.float64), None),
        (rng.integers(0, 2, n).astype(np.int8),
         (rng.random(n) < 0.8)),
    ]
    rows, stride = native.columns_to_rows(cols)
    assert rows.shape == (n, stride)
    out = native.rows_to_columns(
        rows, [np.int64, np.float64, np.int8])
    for (vals, valid), (ovals, ovalid) in zip(cols, out):
        want_valid = np.ones(n, bool) if valid is None else valid
        assert np.array_equal(ovalid, want_valid)
        assert np.array_equal(vals[want_valid], ovals[want_valid])


def test_host_buffer_pool():
    _require_native()
    pool = native.HostBufferPool(1 << 20)
    a = pool.alloc(1000)
    b = pool.alloc(2000)
    assert a is not None and b is not None
    assert pool.in_use == 3000
    pool.free(a)
    assert pool.in_use == 2000
    # freelist reuse: same-size alloc reuses the freed block
    c = pool.alloc(1000)
    assert c is not None
    assert pool.in_use == 3000
    # budget exhaustion returns None
    d = pool.alloc(2 << 20)
    assert d is None
    assert pool.peak == 3000
    pool.close()


def test_serde_roundtrip_types():
    from spark_rapids_tpu.shuffle import serde

    rng = np.random.default_rng(9)
    n = 123
    t = pa.table({
        "i": pa.array(rng.integers(-100, 100, n), type=pa.int64(),
                      mask=rng.random(n) < 0.2),
        "f": pa.array(rng.random(n), type=pa.float64()),
        "s": pa.array([None if i % 7 == 0 else f"s{i}"
                       for i in range(n)]),
        "d": pa.array(rng.integers(0, 10000, n),
                      type=pa.int32()).cast(pa.date32()),
        "b": pa.array(rng.random(n) < 0.5),
    })
    out = serde.deserialize_table(serde.serialize_table(t))
    assert out.equals(t)


def test_serde_sliced_table():
    from spark_rapids_tpu.shuffle import serde

    t = pa.table({"x": list(range(100)),
                  "s": [f"v{i}" for i in range(100)]})
    sl = t.slice(13, 40)
    out = serde.deserialize_table(serde.serialize_table(sl))
    assert out.equals(pa.table({"x": list(range(13, 53)),
                                "s": [f"v{i}" for i in range(13, 53)]}))


def test_multithreaded_shuffle_query():
    """End-to-end query through the file-backed MULTITHREADED shuffle."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_and_cpu_are_equal_collect,
    )

    def q(s):
        df = s.createDataFrame({
            "k": [i % 13 for i in range(300)],
            "v": [float(i) for i in range(300)],
            "s": [f"name{i % 5}" for i in range(300)],
        })
        return df.groupBy("k").agg(F.sum("v").alias("sv"),
                                   F.count("*").alias("n"))

    assert_tpu_and_cpu_are_equal_collect(
        q, conf={"spark.sql.shuffle.partitions": 4,
                 "spark.rapids.shuffle.mode": "MULTITHREADED"})


def test_string_minmax_agg_falls_back():
    """String min/max aggregation is tagged to CPU (v1) but stays
    correct, including through the MULTITHREADED shuffle."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_and_cpu_are_equal_collect,
    )

    def q(s):
        df = s.createDataFrame({
            "k": [i % 7 for i in range(100)],
            "s": [f"name{(i * 13) % 23}" for i in range(100)],
        })
        return df.groupBy("k").agg(F.max("s").alias("ms"),
                                   F.min("s").alias("mn"))

    assert_tpu_and_cpu_are_equal_collect(
        q, conf={"spark.sql.shuffle.partitions": 3,
                 "spark.rapids.shuffle.mode": "MULTITHREADED"})
