"""Nested types v1 (round-2 verdict item 3): ArrayType device layout,
explode/posexplode Generate exec, collection expressions — differential
against the CPU oracle, including explode of a parquet-read array
column (GpuGenerateExec.scala / collectionOperations.scala roles)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.exec import operators as ops
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)

_CONF = {"spark.sql.shuffle.partitions": 2}


def _arr_table(n=2000, seed=13):
    rng = np.random.default_rng(seed)
    lens = rng.integers(0, 6, n)
    arrs = []
    for i, ln in enumerate(lens):
        if i % 17 == 0:
            arrs.append(None)
        else:
            row = [int(v) if v % 5 else None
                   for v in rng.integers(0, 100, ln)]
            arrs.append(row)
    return pa.table({
        "id": pa.array(np.arange(n), type=pa.int64()),
        "vals": pa.array(arrs, type=pa.list_(pa.int64())),
        "w": pa.array(rng.random(n), type=pa.float64()),
    })


@pytest.fixture(scope="module")
def arr_parquet(tmp_path_factory):
    d = tmp_path_factory.mktemp("nested")
    t = _arr_table()
    pq.write_table(t.slice(0, 1000), os.path.join(d, "p0.parquet"))
    pq.write_table(t.slice(1000, 1000), os.path.join(d, "p1.parquet"))
    return str(d)


def test_array_scan_roundtrip(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet).select("id", "vals"),
        conf=_CONF)


def test_size(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id", F.size(F.col("vals")).alias("n")),
        conf=_CONF)


def test_array_contains(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id",
                F.array_contains(F.col("vals"), 42).alias("has42")),
        conf=_CONF)


def test_get_item_and_element_at(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id",
                F.col("vals").getItem(0).alias("first"),
                F.element_at(F.col("vals"), 2).alias("second"),
                F.element_at(F.col("vals"), -1).alias("last")),
        conf=_CONF)


def test_create_array():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.createDataFrame(pa.table({
            "a": pa.array([1, 2, 3], type=pa.int64()),
            "b": pa.array([4, None, 6], type=pa.int64())}))
        .select(F.array(F.col("a"), F.col("b")).alias("arr")),
        conf=_CONF)


def test_explode_parquet(arr_parquet):
    """The verdict's done-criterion: explode of a parquet-read array
    column, device vs oracle."""
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id", F.explode(F.col("vals")).alias("v")),
        conf=_CONF)


def test_explode_runs_on_device(arr_parquet):
    def run(spark):
        df = spark.read.parquet(arr_parquet).select(
            "id", F.explode(F.col("vals")).alias("v"))
        phys, _ = df._physical()
        return phys

    phys = with_tpu_session(run, _CONF)

    def walk(p):
        yield p
        for c in p.children:
            yield from walk(c)

    names = [type(p).__name__ for p in walk(phys)]
    assert "TpuGenerateExec" in names, names


def test_posexplode(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id", F.posexplode(F.col("vals")).alias("v")),
        conf=_CONF)


def test_explode_then_agg(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id", F.explode(F.col("vals")).alias("v"))
        .groupBy("v").agg(F.count("*").alias("n")),
        conf=_CONF)


def test_array_group_key_falls_back(arr_parquet):
    """Array-typed grouping keys have no orderable device keys: the agg
    places on CPU and still matches."""

    def run(spark):
        df = (spark.read.parquet(arr_parquet)
              .groupBy("vals").agg(F.count("*").alias("n")))
        phys, meta = df._physical()
        return meta.explain(only_not_on_device=True)

    explain = with_tpu_session(run, _CONF)
    assert "array-typed keys" in explain


# --------------------- higher-order functions / reductions / json

def test_transform_on_device(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id", F.transform(F.col("vals"),
                                  lambda x: x * 2 + 1).alias("t")),
        conf=_CONF)


def test_filter_array(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id", F.filter_array(F.col("vals"),
                                     lambda x: x > 50).alias("f")),
        conf=_CONF)


def test_array_min_max(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id", F.array_max(F.col("vals")).alias("mx"),
                F.array_min(F.col("vals")).alias("mn")),
        conf=_CONF)


def test_sort_array(arr_parquet):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(arr_parquet)
        .select("id", F.sort_array(F.col("vals")).alias("sa"),
                F.sort_array(F.col("vals"), asc=False).alias("sd")),
        conf=_CONF)


def test_get_json_object():
    docs = ['{"a": 1, "b": {"c": "x"}}', '{"a": [10, 20, 30]}',
            'not json', None, '{"b": null}', '{"a": true}']
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.createDataFrame(pa.table({
            "j": pa.array(docs, type=pa.string())}))
        .select(F.get_json_object(F.col("j"), "$.a").alias("a"),
                F.get_json_object(F.col("j"), "$.b.c").alias("bc"),
                F.get_json_object(F.col("j"), "$.a[1]").alias("a1")),
        conf=_CONF, ignore_order=False)


def test_transform_in_device_plan(arr_parquet):
    """higher-order lambda stays on device (no CPU fallback)."""

    def run(spark):
        df = spark.read.parquet(arr_parquet).select(
            "id", F.transform(F.col("vals"), lambda x: x + 1).alias("t"))
        phys, _ = df._physical()
        return phys

    phys = with_tpu_session(run, _CONF)

    def walk(p):
        yield p
        for c in p.children:
            yield from walk(c)

    names = [type(p).__name__ for p in walk(phys)]
    assert "TpuProjectExec" in names and "CpuProjectExec" not in names
