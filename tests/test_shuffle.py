"""Shuffle transport tests: compression codec round-trip, CACHE_ONLY
host-ledger spill to disk, parallel map stage correctness."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.shuffle import serde
from spark_rapids_tpu.shuffle.manager import ShuffleManager
from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    with_cpu_session,
    with_tpu_session,
)


def _table(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 50, n), type=pa.int64()),
        "v": pa.array(rng.random(n), type=pa.float64()),
        "s": pa.array([f"row-{i % 17}" for i in range(n)],
                      type=pa.string()),
    })


@pytest.mark.parametrize("codec", ["none", "zstd", "zlib"])
def test_serde_codec_roundtrip(codec):
    t = _table(777, seed=3)
    buf = serde.serialize_table(t, codec=codec)
    back = serde.deserialize_table(buf)
    assert back.equals(t)


def test_zstd_compresses():
    t = _table(5000, seed=4)
    raw = serde.serialize_table(t, codec="none")
    z = serde.serialize_table(t, codec="zstd")
    assert z.nbytes < raw.nbytes


def test_cache_only_spills_blocks_to_disk(tmp_path):
    mgr = ShuffleManager("CACHE_ONLY", shuffle_dir=str(tmp_path),
                         codec="zstd", spill_threshold=20_000)
    sid = mgr.new_shuffle_id()
    tables = [_table(500, seed=i) for i in range(8)]
    for i, t in enumerate(tables):
        mgr.put(sid, i % 2, t)
    assert mgr.blocks_spilled > 0, "threshold never triggered spill"
    assert mgr.bytes_in_memory <= 20_000
    got0 = pa.concat_tables(mgr.fetch(sid, 0))
    got1 = pa.concat_tables(mgr.fetch(sid, 1))
    want0 = pa.concat_tables([t for i, t in enumerate(tables)
                              if i % 2 == 0])
    want1 = pa.concat_tables([t for i, t in enumerate(tables)
                              if i % 2 == 1])
    assert got0.equals(want0)
    assert got1.equals(want1)
    mgr.remove_shuffle(sid)
    assert mgr.bytes_in_memory == 0


@pytest.mark.parametrize("mode", ["CACHE_ONLY", "MULTITHREADED"])
def test_parallel_map_stage_matches_oracle(mode):
    """Multi-partition scan -> keyed exchange -> final agg with map tasks
    running on the shuffle-map thread pool; results equal the oracle."""
    conf = {"spark.rapids.shuffle.mode": mode,
            "spark.sql.shuffle.partitions": 5,
            "spark.rapids.sql.reader.batchSizeRows": 300}

    def q(s):
        df = s.createDataFrame(_table(4000, seed=9))
        # repartition forces a multi-partition child under the agg
        return (df.repartition(6, "k")
                .groupBy("k")
                .agg(F.sum("v").alias("sv"), F.count("*").alias("n")))

    got = with_tpu_session(lambda s: q(s).collect_arrow(), conf)
    want = with_cpu_session(lambda s: q(s).collect_arrow(), {})
    assert_tables_equal(got, want)


def test_multithreaded_shuffle_with_compression():
    conf = {"spark.rapids.shuffle.mode": "MULTITHREADED",
            "spark.rapids.shuffle.compression.codec": "zstd",
            "spark.sql.shuffle.partitions": 4}

    def q(s):
        df = s.createDataFrame(_table(3000, seed=11))
        return df.groupBy("s").agg(F.sum("v").alias("sv"))

    got = with_tpu_session(lambda s: q(s).collect_arrow(), conf)
    want = with_cpu_session(lambda s: q(s).collect_arrow(), {})
    assert_tables_equal(got, want)


# ------------------------------------------- device-resident shuffle mode

_DEV_CONF = {"spark.rapids.shuffle.mode": "DEVICE",
             "spark.sql.shuffle.partitions": 4,
             "spark.rapids.sql.reader.batchSizeRows": 500}


@pytest.mark.parametrize("q", ["agg", "join", "sort"])
def test_device_shuffle_matches_oracle(q):
    """DEVICE mode: blocks stay HBM-resident spillables; no host round
    trip. Same results as the oracle for agg/join/sort exchanges."""

    def build(s):
        df = s.createDataFrame(_table(4000, seed=21)).repartition(5, "k")
        if q == "agg":
            return df.groupBy("k").agg(F.sum("v").alias("sv"),
                                       F.count("*").alias("n"))
        if q == "join":
            dim = s.createDataFrame(_table(50, seed=22)) \
                .select("k", "v").distinct()
            return df.join(dim, on="k", how="inner") \
                .groupBy("k").agg(F.count("*").alias("n"))
        return df.select("k", "v").orderBy("k", "v")

    got = with_tpu_session(lambda s: build(s).collect_arrow(),
                           _DEV_CONF)
    want = with_cpu_session(lambda s: build(s).collect_arrow(), {})
    assert_tables_equal(got, want, ignore_order=(q != "sort"))


def test_device_shuffle_blocks_in_spill_catalog():
    """Device shuffle blocks register as spillables: under a tiny device
    budget the query still completes by spilling blocks to host."""
    conf = {**_DEV_CONF,
            "spark.rapids.memory.gpu.maxAllocBytes": 1 << 20}

    def run(s):
        from spark_rapids_tpu.runtime.memory import get_catalog

        df = s.createDataFrame(_table(20000, seed=23)) \
            .repartition(4, "k")
        out = df.groupBy("k").agg(F.sum("v").alias("sv")).collect_arrow()
        return out, dict(get_catalog().metrics)

    got, metrics = with_tpu_session(run, conf)
    assert metrics["spill_to_host"] > 0, metrics
    want = with_cpu_session(
        lambda s: s.createDataFrame(_table(20000, seed=23))
        .groupBy("k").agg(F.sum("v").alias("sv")).collect_arrow(), {})
    assert_tables_equal(got, want)
