"""Cost-based optimizer (reference CostBasedOptimizer.scala) and the
public explain API (explainPotentialGpuPlan, GpuOverrides.scala:4500)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import explain_potential_tpu_plan
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import with_tpu_session


@pytest.fixture(scope="module")
def small_big(tmp_path_factory):
    d = tmp_path_factory.mktemp("cbo")
    rng = np.random.default_rng(3)
    small = pa.table({"k": pa.array(rng.integers(0, 5, 50)),
                      "v": pa.array(rng.random(50))})
    big = pa.table({"k": pa.array(rng.integers(0, 5, 200_000)),
                    "v": pa.array(rng.random(200_000))})
    ps, pb = str(d / "small.parquet"), str(d / "big.parquet")
    pq.write_table(small, ps)
    pq.write_table(big, pb)
    return ps, pb


def _placement(spark, df):
    phys, meta = df._physical()
    names = []

    def walk(p):
        names.append(type(p).__name__)
        for c in p.children:
            walk(c)

    walk(phys)
    return names


def test_cbo_reverts_tiny_input(small_big):
    ps, _ = small_big

    def q(spark):
        df = (spark.read.parquet(ps).filter(F.col("v") > 0.1)
              .groupBy("k").agg(F.sum("v").alias("s")))
        return _placement(spark, df)

    on = with_tpu_session(
        q, conf={"spark.rapids.sql.optimizer.enabled": True})
    off = with_tpu_session(q)
    # 50 rows never pay for the transfer: everything reverts to CPU
    assert any(n.startswith("Cpu") for n in on)
    assert not any(n.startswith("Tpu") for n in on), on
    assert any(n.startswith("Tpu") for n in off)


def test_cbo_keeps_large_input(small_big):
    _, pb = small_big

    def q(spark):
        df = (spark.read.parquet(pb).filter(F.col("v") > 0.1)
              .groupBy("k").agg(F.sum("v").alias("s")))
        return _placement(spark, df)

    on = with_tpu_session(
        q, conf={"spark.rapids.sql.optimizer.enabled": True})
    assert any(n.startswith("Tpu") for n in on), on


def test_cbo_results_still_correct(small_big):
    ps, _ = small_big
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_and_cpu_are_equal_collect,
    )

    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(ps).groupBy("k")
        .agg(F.sum("v").alias("s")),
        conf={"spark.rapids.sql.optimizer.enabled": True})


def test_explain_potential_plan(small_big):
    _, pb = small_big

    def q(spark):
        df = (spark.read.parquet(pb)
              .select(F.col("v").cast("string").alias("s"),
                      F.date_format(F.current_timestamp(),
                                    "EEE yyyy").alias("bad"))
              .limit(5))
        return explain_potential_tpu_plan(df, "NOT_ON_TPU"), \
            explain_potential_tpu_plan(df, "ALL")

    not_on, full = with_tpu_session(q)
    assert "NOT_ON_TPU" in not_on
    assert "date_format" in not_on
    assert "Limit" in full


def test_explain_all_device(small_big):
    _, pb = small_big

    def q(spark):
        return explain_potential_tpu_plan(
            spark.read.parquet(pb).filter(F.col("v") > 0.5), "NOT_ON_TPU")

    out = with_tpu_session(q)
    assert out == "(every operator runs on device)"


# ---------------- per-operator enable/disable switches (dynamic confs)

def test_expression_disable_switch_falls_back():
    """spark.rapids.sql.expression.<Name>=false tags the expression
    NOT_ON_TPU; the query takes the CPU path and stays correct
    (reference GpuOverrides expr-registry disable surface)."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.explain import explain_potential_tpu_plan

    t = pa.table({"s": pa.array(["a", "Bc", "dE"])})
    s = TpuSparkSession({"spark.rapids.sql.expression.Upper": False})
    try:
        df = s.createDataFrame(t).select(F.upper(F.col("s")).alias("u"))
        txt = explain_potential_tpu_plan(df, "NOT_ON_TPU")
        assert "spark.rapids.sql.expression.Upper" in txt, txt
        assert df.collect_arrow().column("u").to_pylist() == \
            ["A", "BC", "DE"]
    finally:
        s.stop()
    # and the same query WITH the switch on runs without the reason
    s = TpuSparkSession({})
    try:
        df = s.createDataFrame(t).select(F.upper(F.col("s")).alias("u"))
        txt = explain_potential_tpu_plan(df, "NOT_ON_TPU")
        assert "expression.Upper" not in txt
    finally:
        s.stop()


def test_exec_disable_switch_falls_back():
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.explain import explain_potential_tpu_plan

    rng = np.random.default_rng(1)
    t = pa.table({"k": pa.array(rng.integers(0, 5, 100),
                                type=pa.int64()),
                  "v": pa.array(rng.random(100))})
    s = TpuSparkSession({"spark.rapids.sql.exec.Aggregate": "false"})
    try:
        df = (s.createDataFrame(t).groupBy("k")
              .agg(F.sum("v").alias("sv")))
        txt = explain_potential_tpu_plan(df, "NOT_ON_TPU")
        assert "spark.rapids.sql.exec.Aggregate" in txt, txt
        got = {r["k"]: r["sv"] for r in df.collect_arrow().to_pylist()}
        ks = np.asarray(t.column("k"))
        vs = np.asarray(t.column("v"))
        for k in np.unique(ks):
            np.testing.assert_allclose(got[int(k)], vs[ks == k].sum(),
                                       rtol=1e-9)
    finally:
        s.stop()
