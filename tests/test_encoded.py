"""Encoded (dictionary) execution — columnar/encoding.py and its
operator lowerings: codes stay compressed in HBM, decode defers to the
last consumer, and every path diff-tests against the plain (decoded)
representation and the pyarrow oracle."""

import os

import jax
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.columnar import encoding
from spark_rapids_tpu.columnar.arrow_bridge import (
    arrow_to_device,
    device_to_arrow,
)
from spark_rapids_tpu.exec.fused import upload_narrowed


@pytest.fixture()
def spark():
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 4})
    yield s
    s.stop()


def _dict_table(values, extra=None):
    cols = {"s": pa.array(values).dictionary_encode()}
    if extra:
        cols.update(extra)
    return pa.table(cols)


# ------------------------------------------------------ representation

def test_upload_roundtrip_with_nulls():
    vals = ["apple", "banana", None, "apple", "cherry", None, "banana"]
    b = upload_narrowed(_dict_table(vals))
    col = b.columns[0]
    assert col.is_encoded
    assert col.data.ndim == 1  # codes, not a byte matrix
    assert col.vrange == (0, 2)
    out = device_to_arrow(b)
    assert out.column("s").to_pylist() == vals


def test_null_inside_dictionary_roundtrip():
    # satellite bugfix coverage: a NULL VALUE in the dictionary must
    # fold into row validity identically on every upload path
    idx = pa.array([0, 1, 2, 0, None, 1], type=pa.int32())
    dic = pa.array(["x", None, "y"])
    arr = pa.DictionaryArray.from_arrays(idx, dic)
    want = ["x", None, "y", "x", None, None]
    t = pa.table({"s": arr})
    for upload in (upload_narrowed, arrow_to_device):
        b = upload(t)
        assert device_to_arrow(b).column("s").to_pylist() == want
    # the encoded column itself carries the folded validity
    b = upload_narrowed(t)
    assert np.asarray(b.columns[0].validity[:6]).tolist() == \
        [True, False, True, True, False, False]


def test_duplicate_dictionary_values_canonicalize():
    idx = pa.array([0, 1, 2, 3], type=pa.int32())
    dic = pa.array(["a", "b", "a", "c"])  # duplicate "a"
    arr = pa.DictionaryArray.from_arrays(idx, dic)
    b = upload_narrowed(pa.table({"s": arr}))
    col = b.columns[0]
    codes = np.asarray(col.data[:4])
    assert codes[0] == codes[2], "duplicate values must share one code"
    assert device_to_arrow(b).column("s").to_pylist() == \
        ["a", "b", "a", "c"]


def test_empty_dictionary_and_empty_table():
    # all-null dictionary column and a zero-row table
    arr = pa.DictionaryArray.from_arrays(
        pa.array([None, None], type=pa.int32()), pa.array([], pa.string()))
    b = upload_narrowed(pa.table({"s": arr}))
    assert device_to_arrow(b).column("s").to_pylist() == [None, None]
    empty = pa.table({"s": pa.array([], pa.string()).dictionary_encode()})
    b0 = upload_narrowed(empty)
    assert device_to_arrow(b0).num_rows == 0


def test_dictionary_interning_dedup():
    vals = ["p", "q", "r"]
    a1 = pa.array(vals).dictionary_encode()
    a2 = pa.array(["r", "q", "p", "q"]).dictionary_encode()
    b1 = upload_narrowed(pa.table({"s": a1}))
    b2 = upload_narrowed(pa.table({"s": pa.array(vals)
                                   .dictionary_encode()}))
    # identical content -> one dict_id, one device-cache entry (the
    # batch device_put re-unflattens the pytree, so object identity is
    # not the contract — the interned id and cache slot are)
    did = b1.columns[0].encoding.dict_id
    assert b2.columns[0].encoding.dict_id == did
    assert did in encoding._device_dicts
    b3 = upload_narrowed(pa.table({"s": a2}))
    assert b3.columns[0].encoding.dict_id != \
        b1.columns[0].encoding.dict_id


def test_decode_column_traced():
    vals = ["aa", None, "bbb", "aa"]
    b = upload_narrowed(_dict_table(vals))

    @jax.jit
    def dec(batch):
        from spark_rapids_tpu.columnar.batch import ColumnBatch

        cols = [encoding.decode_column(c) for c in batch.columns]
        return ColumnBatch(batch.schema, cols, batch.num_rows)

    out = device_to_arrow(dec(b))
    assert out.column("s").to_pylist() == vals


# --------------------------------------------------- operator lowerings

def _write(tmpdir, name, table, **kw):
    path = os.path.join(str(tmpdir), name)
    os.makedirs(path, exist_ok=True)
    pq.write_table(table, os.path.join(path, "part-0.parquet"), **kw)
    return path


@pytest.fixture()
def dict_data(tmp_path):
    rng = np.random.default_rng(7)
    n, stores, regions = 20_000, 100, 6
    fact = pa.table({
        "store": pa.array(rng.integers(0, stores, n), pa.int64()),
        "amount": pa.array(rng.random(n) * 100.0),
    })
    region_vals = [None if i % 17 == 0 else f"region_{i % regions:02d}"
                   for i in range(stores)]
    dim = pa.table({
        "store": pa.array(np.arange(stores), pa.int64()),
        "region": pa.array(region_vals),
    })
    return (_write(tmp_path, "fact", fact),
            _write(tmp_path, "dim", dim, use_dictionary=True))


def _canon(t):
    cols = [c.to_pylist() for c in t.columns]
    rows = list(zip(*cols)) if cols else []
    return sorted(
        (tuple(round(v, 6) if isinstance(v, float) else v for v in r)
         for r in rows),
        key=lambda r: tuple((x is None, x) for x in r))


def _both_sessions(extra=None):
    base = {"spark.sql.shuffle.partitions": 4}
    base.update(extra or {})
    on = dict(base)
    off = dict(base)
    off["spark.rapids.tpu.encoded.enabled"] = False
    return on, off


@pytest.mark.parametrize("engine_conf", [
    {},  # fused
    {"spark.rapids.sql.fusedExec.enabled": False},  # per-operator
])
def test_filter_groupby_join_oracle(dict_data, engine_conf):
    fact_dir, dim_dir = dict_data

    def q(s):
        return (s.read.parquet(fact_dir)
                .filter(F.col("amount") > 20.0)
                .join(s.read.parquet(dim_dir), on="store", how="inner")
                .filter(F.col("region") != "region_02")
                .groupBy("region")
                .agg(F.sum("amount").alias("sv"),
                     F.count("*").alias("n")))

    on_conf, off_conf = _both_sessions(engine_conf)
    s_on = TpuSparkSession(on_conf)
    got = q(s_on).collect_arrow()
    tel = (s_on.last_execution or {}).get("telemetry") or {}
    s_on.stop()
    s_off = TpuSparkSession(off_conf)
    want = q(s_off).collect_arrow()
    s_off.stop()
    assert _canon(got) == _canon(want)
    # the encoded run must report its savings
    assert tel.get("bytesSavedEncoded", 0) > 0
    assert tel.get("effectiveCompressionRatio", 0) > 1


def test_in_and_isnull_predicates(dict_data, spark):
    _, dim_dir = dict_data
    df = spark.read.parquet(dim_dir)
    got = (df.filter(F.col("region").isin("region_00", "region_01",
                                          "absent"))
           .groupBy("region").agg(F.count("*").alias("n"))
           ).collect_arrow()
    host = pq.read_table(dim_dir)
    mask = pc.is_in(host.column("region"),
                    value_set=pa.array(["region_00", "region_01",
                                        "absent"]))
    want = (host.filter(pc.fill_null(mask, False))
            .group_by("region").aggregate([("region", "count")]))
    assert _canon(got) == _canon(want)

    got_null = (spark.read.parquet(dim_dir)
                .filter(F.col("region").isNull())).collect_arrow()
    n_null = pc.sum(pc.is_null(host.column("region"))).as_py()
    assert got_null.num_rows == n_null


def test_string_key_join_same_and_mismatched_dicts(tmp_path):
    cats = [f"cat_{i:02d}" for i in range(12)]
    rng = np.random.default_rng(11)
    left = pa.table({
        "k": pa.array([None if i % 19 == 0 else cats[i % 12]
                       for i in rng.integers(0, 1000, 4000)]),
        "v": pa.array(rng.random(4000)),
    })
    # reversed value order -> same domain, DIFFERENT dictionary content
    right = pa.table({
        "k": pa.array([cats[11 - (i % 12)] for i in range(300)]
                      + ["right_only"]),
        "w": pa.array(rng.random(301)),
    })
    ldir = _write(tmp_path, "l", left, use_dictionary=True)
    rdir = _write(tmp_path, "r", right, use_dictionary=True)

    def q(s):
        return (s.read.parquet(ldir)
                .join(s.read.parquet(rdir), on="k", how="inner")
                .groupBy("k").agg(F.count("*").alias("n")))

    on_conf, off_conf = _both_sessions(
        {"spark.rapids.sql.fusedExec.enabled": False})
    s_on = TpuSparkSession(on_conf)
    got = q(s_on).collect_arrow()
    s_on.stop()
    s_off = TpuSparkSession(off_conf)
    want = q(s_off).collect_arrow()
    s_off.stop()
    assert _canon(got) == _canon(want)


def test_codesof_remap_mismatched_dictionaries():
    # the re-encode fallback in isolation: same values interned from
    # two different dictionaries remap into one code space
    a = pa.array(["x", "y", "z"]).dictionary_encode()
    b = pa.array(["z", "y", "absent"]).dictionary_encode()
    id_a, _ = encoding.intern_dictionary(a.dictionary)
    id_b, _ = encoding.intern_dictionary(b.dictionary)
    table = encoding.remap_table(id_b, id_a)
    vals_b = b.dictionary.to_pylist()
    idx_a = {v: i for i, v in enumerate(a.dictionary.to_pylist())}
    for code_b, v in enumerate(vals_b):
        assert table[code_b] == idx_a.get(v, -1)


def test_sort_on_encoded_is_value_order(dict_data, spark):
    _, dim_dir = dict_data
    got = (spark.read.parquet(dim_dir)
           .filter(F.col("region").isNotNull())
           .select("region").orderBy("region")).collect_arrow()
    vals = got.column("region").to_pylist()
    assert vals == sorted(vals), "sort must use string order, not codes"


def test_concat_mismatched_dictionaries_decodes(tmp_path):
    # two files, same column, different dictionaries -> one scan
    d = os.path.join(str(tmp_path), "multi")
    os.makedirs(d)
    pq.write_table(pa.table({"s": pa.array(["a", "b", "a"])}),
                   os.path.join(d, "p0.parquet"), use_dictionary=True)
    pq.write_table(pa.table({"s": pa.array(["c", "b", "c"])}),
                   os.path.join(d, "p1.parquet"), use_dictionary=True)
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 2})
    out = (s.read.parquet(d).groupBy("s")
           .agg(F.count("*").alias("n"))).collect_arrow()
    s.stop()
    assert _canon(out) == [("a", 2), ("b", 2), ("c", 2)]


# ----------------------------------------------------- spill round-trip

def test_spill_unspill_preserves_encoding():
    from spark_rapids_tpu.runtime.memory import get_catalog

    vals = ["alpha", "beta", None, "alpha", "gamma"]
    b = upload_narrowed(_dict_table(vals))
    dict_id = b.columns[0].encoding.dict_id
    catalog = get_catalog()
    sb = catalog.add_batch(b)
    try:
        with catalog._lock:
            sb._to_host()          # DEVICE -> HOST
            sb._to_disk()          # HOST -> DISK
        back = sb.get_batch()      # DISK -> DEVICE (reserves)
        col = back.columns[0]
        assert col.is_encoded
        assert col.encoding.dict_id == dict_id
        assert device_to_arrow(back).column("s").to_pylist() == vals
    finally:
        sb.close()


# -------------------------------------------------- shuffle wire format

def test_serde_dictionary_roundtrip():
    from spark_rapids_tpu.shuffle import serde

    vals = ["u", None, "v", "u", "w"]
    t = pa.table({"s": pa.array(vals).dictionary_encode(),
                  "x": pa.array(range(5), pa.int64())})
    for codec in ("none", "zlib"):
        buf = serde.serialize_table(t, codec=codec)
        rt = serde.deserialize_table(buf)
        assert pa.types.is_dictionary(rt.schema.field("s").type)
        assert rt.column("s").to_pylist() == vals
        assert rt.column("x").to_pylist() == list(range(5))


def test_device_to_arrow_encoded_wire():
    vals = ["m", "n", None, "m"]
    b = upload_narrowed(_dict_table(vals))
    t = device_to_arrow(b, encoded=True)
    assert pa.types.is_dictionary(t.schema.field("s").type)
    assert t.column("s").to_pylist() == vals
    # and the re-upload re-interns to the SAME dictionary id
    b2 = arrow_to_device(t)
    assert b2.columns[0].is_encoded
    assert b2.columns[0].encoding.dict_id == \
        b.columns[0].encoding.dict_id
