"""Variable-width device strings (round-4 verdict item #4): the padded
byte matrix adapts per column; filter/sort/join/group-by run on device
for >= 200-byte strings with no CPU fallback (the binary search over
packed key words compiles in O(words) via fori_loop)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession

LONG = ["x" * 180 + f"suffix{i % 13}" for i in range(800)]


@pytest.fixture()
def spark():
    s = TpuSparkSession({})
    yield s
    s.stop()


@pytest.fixture()
def df(spark):
    rng = np.random.default_rng(0)
    return spark.createDataFrame(pa.table({
        "s": pa.array(LONG), "v": pa.array(rng.random(len(LONG)))}))


def test_long_string_filter(df):
    out = df.filter(F.col("s") == "x" * 180 + "suffix3").collect_arrow()
    assert out.num_rows == sum(1 for s in LONG if s.endswith("suffix3"))


def test_long_string_sort(df):
    out = df.orderBy(F.col("s").desc()).limit(2).collect_arrow()
    assert out.column("s").to_pylist() == sorted(LONG, reverse=True)[:2]


def test_long_string_join(spark, df):
    dim = pa.table({"s": pa.array(sorted(set(LONG))),
                    "g": pa.array(range(13))})
    out = df.join(spark.createDataFrame(dim), on="s").collect_arrow()
    assert out.num_rows == len(LONG)
    want = {s: g for s, g in zip(sorted(set(LONG)), range(13))}
    for r in out.to_pylist()[:50]:
        assert r["g"] == want[r["s"]]


def test_long_string_groupby(df):
    out = df.groupBy("s").agg(F.count("*").alias("n")).collect_arrow()
    assert out.num_rows == 13
    import collections

    want = collections.Counter(LONG)
    got = {r["s"]: r["n"] for r in out.to_pylist()}
    assert got == dict(want)


def test_string_ceiling_falls_back_to_cpu(spark):
    # over-ceiling strings no longer raise: the engine dispatch re-runs
    # the query on the CPU plan with a recorded reason (data-shape
    # fallback; round-5 verdict item #7)
    spark.conf.set("spark.rapids.tpu.string.maxBytes", 64)
    df = spark.createDataFrame(pa.table(
        {"s": pa.array(["y" * 200] * 8 + ["y"])}))
    out = df.filter(F.col("s") == "y").collect_arrow()
    assert out.num_rows == 1
    rec = spark.last_execution
    assert rec["engine"] == "cpu", rec
    assert any("maxBytes" in r for _, r in rec["fallbacks"]), rec
