"""Persistent cross-process compilation layer
(runtime/compile_cache.py) + fused variant dedup (exec/fused.py
run_program canonical keys): the round-5 cold-start killer.

Covers the acceptance surface: cross-process executable reuse, warmup
serving, version-skew invalidation, digest-collision safety, concurrent
writers, per-query compile metrics, and the canonical-key dedup that
stops expansion retries / re-lowerings / the ANSI channel from
recompiling the whole pipeline."""

import json
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime import compile_cache as cc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def cache_session(tmp_path):
    """Session bound to an isolated cache dir; deconfigures after.
    The process jit cache is cleared so earlier tests' structurally
    identical programs don't turn this test's builds into hits."""
    from spark_rapids_tpu.runtime import jit_cache

    jit_cache.clear()
    cc.reset_for_tests()
    s = TpuSparkSession({
        "spark.rapids.tpu.compileCache.dir": str(tmp_path / "cache"),
        "spark.rapids.tpu.compileCache.warmup.enabled": False,
    })
    yield s
    s.stop()
    cc.reset_for_tests()


def _mini_q5(spark):
    """The bench shape in miniature: scan -> filter -> broadcast
    lookup join -> string-key aggregate."""
    fact = spark.createDataFrame(pa.table({
        "store": pa.array(np.arange(4000) % 50, type=pa.int64()),
        "amount": pa.array(np.arange(4000, dtype=np.float64)),
    }))
    dim = spark.createDataFrame(pa.table({
        "store": pa.array(np.arange(50), type=pa.int64()),
        "region": pa.array([f"r{i % 4}" for i in range(50)]),
    }))
    return (fact.filter(F.col("amount") > 10.0)
            .join(dim, on="store", how="inner")
            .groupBy("region")
            .agg(F.sum("amount").alias("s"),
                 F.count("*").alias("n")))


# ------------------------------------------------- per-query metrics

def test_compile_metrics_in_last_execution(cache_session):
    s = cache_session
    q = _mini_q5(s)
    out = q.collect_arrow()
    assert out.num_rows == 4
    comp = s.last_execution["compile"]
    assert s.last_execution["engine"] == "fused"
    assert comp["programsCompiled"] > 0
    assert comp["cacheHits"] == 0
    assert comp["variantCount"] == comp["programsCompiled"]
    assert comp["compileSeconds"] > 0
    # second run: everything structural-hits, nothing compiles
    q.collect_arrow()
    comp2 = s.last_execution["compile"]
    assert comp2["programsCompiled"] == 0
    assert comp2["cacheHits"] == comp["variantCount"]
    assert comp2["variantCount"] == comp["variantCount"]
    # ledger counters surfaced in session metrics
    snap = s.query_metrics.snapshot()
    assert snap["compile.programsCompiled"] == comp["programsCompiled"]
    assert snap["compile.cacheHits"] >= comp2["cacheHits"]


# ---------------------------------------------------- variant dedup

def test_expansion_change_recompiles_nothing_without_consumers(
        cache_session):
    """The dedup acceptance: canonical keys carry only consumed
    parameters, so re-running the bench-shaped query at a DIFFERENT
    expansion factor (the retry sweep's axis) recompiles zero programs
    — no program in this plan consumes the expansion factor. The old
    keys stamped every program with it: the sweep recompiled the
    whole pipeline."""
    from spark_rapids_tpu.exec.fused import FusedSingleChipExecutor

    s = cache_session
    q = _mini_q5(s)
    phys, _ = q._physical()

    ex1 = FusedSingleChipExecutor(s.rapids_conf, expansion=4)
    ex1.execute(phys)
    m1 = ex1.last_compile_metrics
    assert m1["programsCompiled"] > 0

    ex2 = FusedSingleChipExecutor(s.rapids_conf, expansion=8)
    ex2.execute(phys)
    m2 = ex2.last_compile_metrics
    assert m2["programsCompiled"] == 0, m2
    assert m2["cacheHits"] == m1["variantCount"]

    # group_cap IS consumed (aggregate shrink): only the agg-bearing
    # programs recompile, strictly fewer than the whole pipeline
    ex3 = FusedSingleChipExecutor(s.rapids_conf, expansion=4,
                                  group_cap=1 << 15)
    ex3.execute(phys)
    m3 = ex3.last_compile_metrics
    assert 0 < m3["programsCompiled"] < m1["variantCount"], m3


def test_ansi_flag_without_checks_shares_programs(tmp_path):
    """ANSI dedup: with no checkable expression in the plan, ANSI on
    traces byte-identically to ANSI off — the hoisted ansi_live key
    component lets both share compiled programs (the old key split
    them)."""
    from spark_rapids_tpu.runtime import jit_cache

    jit_cache.clear()
    cc.reset_for_tests()
    cache = str(tmp_path / "cache")
    base_conf = {
        "spark.rapids.tpu.compileCache.dir": cache,
        "spark.rapids.tpu.compileCache.warmup.enabled": False,
    }
    t = pa.table({"k": pa.array(np.arange(512) % 7, type=pa.int64()),
                  "v": pa.array(np.arange(512, dtype=np.float64))})

    def q(spark):
        # comparison + sum: nothing here raises under ANSI
        return (spark.createDataFrame(t)
                .filter(F.col("v") > 3.0)
                .groupBy("k").agg(F.min("v").alias("m"))
                .collect_arrow())

    s1 = TpuSparkSession(base_conf)
    try:
        q(s1)
        n1 = s1.last_execution["compile"]["programsCompiled"]
        assert n1 > 0
    finally:
        s1.stop()
    s2 = TpuSparkSession({**base_conf, "spark.sql.ansi.enabled": True})
    try:
        q(s2)
        comp = s2.last_execution["compile"]
        assert comp["programsCompiled"] == 0, comp
        assert comp["cacheHits"] == comp["variantCount"]
    finally:
        s2.stop()
        cc.reset_for_tests()


def test_shape_bucketing_shares_programs_across_similar_sizes():
    from spark_rapids_tpu.exec.fused import bucket_capacity

    # below the alignment floor: identical to the old 64Ki alignment
    assert bucket_capacity(1) == 1 << 16
    assert bucket_capacity((1 << 16) + 1) == 1 << 17
    # large caps land on 1/8-octave steps: similar sizes -> same bucket
    a, b = bucket_capacity(4_500_000), bucket_capacity(4_600_000)
    assert a == b
    # padding bounded by 12.5% + one step
    for n in (4_500_000, 9_000_001, 36_000_000):
        cap = bucket_capacity(n)
        assert n <= cap <= int(n * 1.126) + (1 << 16), (n, cap)


# ------------------------------------------- cross-process + warmup

_PROC_SCRIPT = textwrap.dedent("""
    import json, sys, time
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np, pyarrow as pa
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.runtime import compile_cache as cc

    cache_dir, warm = sys.argv[1], sys.argv[2] == "warm"
    s = TpuSparkSession({
        "spark.rapids.tpu.compileCache.dir": cache_dir,
        "spark.rapids.tpu.compileCache.warmup.enabled": warm,
        # tiny test programs must still export warmup artifacts
        "spark.rapids.tpu.compileCache.artifact.minCompileSecs": 0.0,
    })
    if warm:
        cc.warmup_join(120)
    t = pa.table({"k": pa.array(np.arange(2000) % 11,
                                type=pa.int64()),
                  "v": pa.array(np.arange(2000, dtype=np.float64))})
    out = (s.createDataFrame(t).filter(F.col("v") > 5.0)
           .groupBy("k").agg(F.sum("v").alias("s"))
           .collect_arrow())
    total = sum(out.column("s").to_pylist())
    cc.flush()
    print(json.dumps({"engine": s.last_execution["engine"],
                      "compile": s.last_execution["compile"],
                      "total": total}))
    s.stop()
""")


def _run_proc(cache_dir: str, mode: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run(
        [sys.executable, "-c", _PROC_SCRIPT, cache_dir, mode],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(line)


@pytest.mark.slow
def test_cross_process_warm_start(tmp_path):
    """The tentpole end-to-end: process 1 compiles cold and persists;
    process 2 (fresh interpreter, warmup on) serves every fused
    program from artifacts — zero XLA compile seconds — and produces
    identical results."""
    cache = str(tmp_path / "xproc")
    cold = _run_proc(cache, "cold")
    assert cold["engine"] == "fused"
    assert cold["compile"]["programsCompiled"] > 0
    assert cold["compile"]["warmHits"] == 0

    warm = _run_proc(cache, "warm")
    assert warm["engine"] == "fused"
    assert warm["total"] == cold["total"]  # warm executables correct
    assert warm["compile"]["programsCompiled"] == 0, warm
    assert warm["compile"]["warmHits"] == \
        cold["compile"]["programsCompiled"]
    assert warm["compile"]["compileSeconds"] == 0.0


@pytest.mark.slow
def test_version_skew_invalidates_artifacts(tmp_path):
    """Stale-artifact invalidation: a VERSION stamp mismatch (jax or
    plugin upgrade) wipes index + artifacts + XLA entries before any
    program loads."""
    cache = str(tmp_path / "skew")
    _run_proc(cache, "cold")
    assert os.listdir(os.path.join(cache, "index"))
    # simulate a plugin upgrade
    stamp = os.path.join(cache, "VERSION.json")
    tok = json.load(open(stamp))
    tok["plugin"] = tok["plugin"] + ".post-upgrade"
    with open(stamp, "w") as f:
        json.dump(tok, f)
    again = _run_proc(cache, "warm")
    # nothing served stale: the run recompiled from scratch
    assert again["compile"]["warmHits"] == 0
    assert again["compile"]["programsCompiled"] > 0


# ------------------------------------------------- index unit layer

def test_collision_mismatch_ignores_artifact(tmp_path):
    cc.reset_for_tests()
    s = TpuSparkSession({
        "spark.rapids.tpu.compileCache.dir": str(tmp_path / "c"),
        "spark.rapids.tpu.compileCache.warmup.enabled": False,
    })
    try:
        adir = os.path.join(cc.cache_dir(), "artifacts")
        # a digest whose .key sidecar names a DIFFERENT structural key
        with open(os.path.join(adir, "deadbeef.key"), "wb") as f:
            f.write(b"('some', 'other', 'key')")
        with open(os.path.join(adir, "deadbeef.bin"), "wb") as f:
            f.write(b"garbage")
        assert cc._load_artifact("deadbeef", "('the', 'real', 'key')") \
            is None
    finally:
        s.stop()
        cc.reset_for_tests()


def test_concurrent_index_writers_never_tear(tmp_path):
    cc.reset_for_tests()
    s = TpuSparkSession({
        "spark.rapids.tpu.compileCache.dir": str(tmp_path / "c"),
        "spark.rapids.tpu.compileCache.warmup.enabled": False,
    })
    try:
        digest = cc.key_digest(("t", "concurrent"))
        errs = []

        def hammer(i):
            try:
                for _ in range(30):
                    cc._record_index(digest, repr(("t", "concurrent")),
                                     "fused", 0.01, False)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=hammer, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        # the entry parses (atomic-rename discipline: no torn JSON);
        # counts are best-effort last-writer-wins, only >= 1 guaranteed
        idx = cc.read_index()
        assert idx[digest]["tag"] == "fused"
        assert idx[digest]["count"] >= 1
    finally:
        s.stop()
        cc.reset_for_tests()


def test_disabled_conf_writes_nothing(tmp_path):
    cc.reset_for_tests()
    s = TpuSparkSession({
        "spark.rapids.tpu.compileCache.enabled": False,
        "spark.rapids.tpu.compileCache.dir": str(tmp_path / "off"),
    })
    try:
        t = pa.table({"v": pa.array(np.arange(64, dtype=np.float64))})
        s.createDataFrame(t).filter(F.col("v") > 1.0).collect_arrow()
        assert not cc.enabled()
        assert not os.path.exists(str(tmp_path / "off"))
    finally:
        s.stop()
        cc.reset_for_tests()
