"""Query service daemon suite (serve/) — the multi-tenant serving
layer end to end over real sockets.

The acceptance contract under test: a daemon multiplexes >=3
concurrent tenants with distinct priority classes onto ONE warm
session with oracle-identical results; the structural plan cache
serves repeats without re-planning; per-tenant billing reconciles
exactly with the transfer ledger; drain rejects NEW work with
reason='draining' while /readyz flips 503; and stop() leaves zero
leaked connections, threads, permits or sockets.
"""

import json
import os
import socket
import threading
import urllib.request

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime import admission
from spark_rapids_tpu.runtime.errors import QueryRejectedError
from spark_rapids_tpu.serve import protocol
from spark_rapids_tpu.serve.client import ServeClient, ServeError
from spark_rapids_tpu.serve.server import (
    QueryServiceDaemon,
    parse_priority_classes,
)
from spark_rapids_tpu.serve.tenants import TenantLedger

N_ROWS = 400


@pytest.fixture(scope="module")
def table_path(tmp_path_factory):
    t = pa.table({
        "a": pa.array(range(N_ROWS), pa.int64()),
        "b": pa.array([float(i) * 0.5 for i in range(N_ROWS)],
                      pa.float64()),
        "k": pa.array([i % 7 for i in range(N_ROWS)], pa.int64()),
    })
    path = str(tmp_path_factory.mktemp("serve") / "t.parquet")
    pq.write_table(t, path)
    return path


@pytest.fixture(scope="module")
def serve_session():
    s = TpuSparkSession({})
    yield s
    s.stop()


@pytest.fixture()
def daemon(serve_session, table_path):
    # daemons are cheap (a thread + a socket); the warm session is the
    # expensive part and stop() contractually leaves a borrowed session
    # usable, so every test gets a fresh daemon over one shared session
    d = QueryServiceDaemon(session=serve_session).start()
    try:
        yield d
    finally:
        d.stop()


def _filter_spec(path, key="lo"):
    return {"op": "filter",
            "input": {"op": "parquet", "path": path},
            "cond": {"fn": ">", "args": [{"col": "a"},
                                         {"param": key}]}}


def _oracle_filter(path, lo):
    t = pq.read_table(path)
    return t.filter(pc.greater(t["a"], lo))


# ---------------------------------------------------------- protocol


def test_protocol_frame_roundtrip():
    a, b = socket.socketpair()
    try:
        protocol.send_json(a, {"type": "ping", "id": 7})
        assert protocol.recv_json(b, 1 << 20) == {"type": "ping",
                                                  "id": 7}
        t = pa.table({"x": pa.array([1, 2, 3], pa.int64())})
        protocol.send_result(a, {"id": 1, "queryId": 42}, t)
        header, got = protocol.recv_message(b, 1 << 20)
        assert header["type"] == "result"
        assert header["queryId"] == 42
        assert header["payloadBytes"] > 0
        assert got.equals(t)
    finally:
        a.close()
        b.close()


def test_protocol_oversized_frame_is_clean_error():
    a, b = socket.socketpair()
    try:
        protocol.send_frame(a, b"x" * 1024)
        with pytest.raises(protocol.ProtocolError) as ei:
            protocol.recv_frame(b, 100)
        assert "maxFrameBytes" in str(ei.value)
    finally:
        a.close()
        b.close()


def test_parse_priority_classes():
    assert parse_priority_classes("interactive=100,standard=0,"
                                  "batch=-100") == {
        "interactive": 100, "standard": 0, "batch": -100}
    with pytest.raises(ValueError):
        parse_priority_classes("nope")
    with pytest.raises(ValueError):
        parse_priority_classes("")


# ------------------------------------------------- multi-tenant serve


def test_three_tenants_concurrent_oracle_identical(daemon, table_path):
    """>=3 tenants with DISTINCT priority classes through one daemon,
    interleaved; every result must equal the pyarrow oracle."""
    classes = [("acme", "interactive"), ("globex", "standard"),
               ("initech", "batch")]
    errors, results = [], {}

    def run(tenant, pclass, los):
        try:
            with ServeClient.connect(daemon, tenant, pclass) as c:
                assert c.priority == \
                    daemon.priority_classes[pclass]
                for lo in los:
                    got = c.query(_filter_spec(table_path),
                                  params={"lo": lo})
                    results[(tenant, lo)] = got
        except Exception as e:  # surfaced below with context
            errors.append((tenant, e))

    threads = [threading.Thread(target=run,
                                args=(t, p, [50 + 10 * i, 300]))
               for i, (t, p) in enumerate(classes)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for (tenant, lo), got in results.items():
        want = _oracle_filter(table_path, lo)
        assert got.sort_by("a").equals(want.sort_by("a")), \
            (tenant, lo)
    snap = daemon.tenants.snapshot()
    assert set(snap) == {"acme", "globex", "initech"}
    for t in snap.values():
        assert t["queries"] == 2
        assert t["active"] == 0
        assert t["payloadBytesOut"] > 0


def test_plan_cache_hits_over_the_wire(daemon, table_path):
    """Identical binding repeats serve the CACHED physical plan;
    new bindings rebind the template; last_execution['serve'] carries
    the verdict + hit-ratio counters."""
    with ServeClient.connect(daemon, "acme", "standard") as c:
        c.query(_filter_spec(table_path), params={"lo": 100})
        assert c.last_result["planCache"] == "miss"
        c.query(_filter_spec(table_path), params={"lo": 100})
        assert c.last_result["planCache"] == "hit-exact"
        got = c.query(_filter_spec(table_path), params={"lo": 7})
        assert c.last_result["planCache"] == "hit-rebind"
        # a rebind re-plans with the NEW literal — results must track
        assert got.num_rows == N_ROWS - 8
    serve_rec = daemon.session.last_execution["serve"]
    assert serve_rec["tenant"] == "acme"
    assert serve_rec["planCache"] == "hit-rebind"
    stats = serve_rec["planCacheStats"]
    assert stats["hitsExact"] >= 1
    assert stats["hitsRebind"] >= 1
    assert 0.0 < stats["hitRatio"] <= 1.0


def test_billing_reconciles_with_transfer_ledger(daemon, table_path):
    """Tenant bytesMovedTotal == the sum of the transfer-ledger
    summaries of exactly that tenant's query ids, and those summaries
    carry the tenant label."""
    from spark_rapids_tpu.obs import telemetry

    with ServeClient.connect(daemon, "billing-t", "standard") as c:
        for lo in (10, 20, 30):
            c.query(_filter_spec(table_path), params={"lo": lo})
    qids = daemon.tenants.query_ids("billing-t")
    assert len(qids) == 3
    summaries = telemetry.ledger.recent_query_summaries()
    moved = 0
    for qid in qids:
        s = summaries[qid]
        assert s["labels"]["tenant"] == "billing-t"
        moved += int(s.get("bytesMovedTotal", 0) or 0)
    snap = daemon.tenants.snapshot()["billing-t"]
    assert snap["bytesMovedTotal"] == moved
    assert snap["deviceSeconds"] > 0


def test_registry_unified_snapshot_has_serve_block(daemon,
                                                  table_path):
    from spark_rapids_tpu.obs import registry

    with ServeClient.connect(daemon, "acme", "standard") as c:
        c.query(_filter_spec(table_path), params={"lo": 1})
    snap = registry.unified_snapshot(daemon.session)
    assert snap["serve"]["queriesServed"] >= 1
    flat = registry.flatten(snap)
    assert flat["serve.queriesServed"] >= 1
    assert "serve.planCache.hitRatio" in flat


def test_bad_spec_is_clean_error(daemon):
    with ServeClient.connect(daemon, "acme", "standard") as c:
        with pytest.raises(ServeError) as ei:
            c.query({"op": "no-such-op"})
        assert ei.value.code == "bad_spec"
        # the connection survives a bad spec
        assert c.ping()["type"] == "pong"


def test_slow_reader_gets_large_result_intact(daemon):
    """Regression: the 0.5s idle poll timeout must NOT apply to result
    sends — sendall treats it as a total deadline, so a client that
    stalls mid-receive of a multi-MB payload used to desync the
    stream on a partial frame. The stalled client must receive the
    full result and the connection must stay usable."""
    import time

    spec = {"op": "select",
            "input": {"op": "range", "start": 0, "end": 2_000_000},
            "cols": ["id"]}
    with ServeClient.connect(daemon, "slow", "standard") as c:
        protocol.send_json(c._sock, {"type": "query", "id": 1,
                                     "spec": spec})
        # stall well past the old 0.5s send deadline while the ~16MB
        # Arrow payload backs up in the socket buffers
        time.sleep(2.0)
        header, table = protocol.recv_message(c._sock,
                                              daemon.max_frame_bytes)
        assert header["type"] == "result"
        assert table.num_rows == 2_000_000
        # the stream is still in sync: a ping round-trips
        assert c.ping()["type"] == "pong"


def test_unknown_priority_class_refused(daemon):
    with pytest.raises(ServeError) as ei:
        ServeClient.connect(daemon, "acme", "platinum")
    assert ei.value.code == "protocol"


def test_cancel_unknown_id_returns_zero(daemon):
    with ServeClient.connect(daemon, "acme", "standard") as c:
        assert c.cancel(999_999_999) == 0


def test_cancel_is_tenant_scoped(daemon):
    """A tenant can cancel only its OWN queries: another tenant's id
    (or a bare cancel-all from another tenant) touches nothing."""
    from spark_rapids_tpu.obs import events as obs_events

    ctrl = admission.get()
    qid = obs_events.allocate_query_id()
    h = ctrl.submit(qid, description="serve:acme:standard")
    try:
        with ServeClient.connect(daemon, "globex", "standard") as c:
            assert c.cancel(qid) == 0  # someone else's query
            assert c.cancel() == 0     # cancel-all is scoped too
        with ServeClient.connect(daemon, "acme", "standard") as c:
            assert c.cancel(qid) == 1  # the owner cancels it
    finally:
        ctrl.finish(h, status="cancelled")


def test_tenant_id_with_colon_refused(daemon):
    # ':' delimits the serve:<tenant>:<class> cancel-scoping prefix —
    # a tenant id containing it could forge another tenant's scope
    with pytest.raises(ServeError) as ei:
        ServeClient.connect(daemon, "acme:standard", "standard")
    assert ei.value.code == "protocol"


def test_error_code_taxonomy():
    from spark_rapids_tpu.serve.spec import SpecError

    assert protocol.error_code_for(SpecError("x")) == "bad_spec"
    assert protocol.error_code_for(
        protocol.ProtocolError("x")) == "protocol"
    # builtins raised by engine internals MID-EXECUTION are not spec
    # errors — they report (and count) as internal faults
    assert protocol.error_code_for(ValueError("x")) == "internal"
    assert protocol.error_code_for(KeyError("x")) == "internal"
    assert protocol.error_code_for(TypeError("x")) == "internal"


# ------------------------------------------------------ tenant quotas


def test_tenant_concurrency_cap_sheds():
    led = TenantLedger(max_concurrent=2)
    led.admit("t")
    led.admit("t")
    with pytest.raises(QueryRejectedError) as ei:
        led.admit("t")
    assert ei.value.reason == "tenant quota"
    # another tenant is untouched by t's burst
    led.admit("other")
    led.settle("t", 1, "ok")
    led.admit("t")  # slot released -> admitted again
    snap = led.snapshot()
    assert snap["t"]["sheds"] == 1


def test_tenant_byte_budget_sheds(table_path):
    s = TpuSparkSession({
        "spark.rapids.tpu.serve.tenant.maxDeviceBytes": 1})
    d = QueryServiceDaemon(session=s).start()
    try:
        with ServeClient.connect(d, "meter-t", "standard") as c:
            c.query(_filter_spec(table_path), params={"lo": 1})
            with pytest.raises(QueryRejectedError) as ei:
                c.query(_filter_spec(table_path), params={"lo": 2})
            assert ei.value.reason == "tenant quota"
        snap = d.tenants.snapshot()["meter-t"]
        assert snap["queries"] == 1
        assert snap["sheds"] == 1
        assert snap["bytesMovedTotal"] > 1
        # the operator lever: zero the budget, traffic resumes
        d.tenants.reset_usage("meter-t")
        with ServeClient.connect(d, "meter-t", "standard") as c:
            c.query(_filter_spec(table_path), params={"lo": 3})
    finally:
        d.stop()
        s.stop()


# ------------------------------------------------- drain & readiness


def test_drain_rejects_new_work_and_stop_restores(daemon,
                                                  table_path):
    from spark_rapids_tpu.obs.http import ObsHttpServer

    http = ObsHttpServer(daemon.session, port=0)
    try:
        url = f"http://127.0.0.1:{http.port}/readyz"
        with urllib.request.urlopen(url) as r:
            assert r.status == 200
            assert json.loads(r.read())["ready"] is True

        with ServeClient.connect(daemon, "acme", "standard") as c:
            c.query(_filter_spec(table_path), params={"lo": 1})
            report = daemon.drain()
            assert report["cancelled"] == 0  # nothing in flight
            # the EXISTING connection's new submission sheds cleanly
            with pytest.raises(QueryRejectedError) as ei:
                c.query(_filter_spec(table_path), params={"lo": 2})
            assert ei.value.reason == "draining"
            # liveness stays 200, readiness flips 503 + draining flag
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}/healthz") as r:
                assert r.status == 200
            try:
                urllib.request.urlopen(url)
                assert False, "expected 503"
            except urllib.error.HTTPError as e:
                assert e.code == 503
                body = json.loads(e.read())
                assert body["draining"] is True
                assert body["ready"] is False
        # NEW connections are refused at the TCP level while draining
        # (the listener is closed — the LB-visible signal)
        with pytest.raises(OSError):
            ServeClient.connect(daemon, "late", "standard")
        daemon.stop()
        # stop() reopens the intake valve: the borrowed session is
        # usable again and readiness recovers
        assert admission.get().draining is False
        assert daemon.session.range(0, 10).count() == 10
        with urllib.request.urlopen(url) as r:
            assert r.status == 200
    finally:
        http.close()


def test_drain_before_start_is_a_noop(serve_session):
    d = QueryServiceDaemon(session=serve_session)
    assert d.drain() == {"state": "new", "cancelled": 0}
    # the daemon is not wedged: it still starts and serves
    d.start()
    try:
        assert d.status()["state"] == "serving"
    finally:
        d.stop()


def test_readiness_503_while_fenced(daemon):
    from spark_rapids_tpu.obs.http import ObsHttpServer
    from spark_rapids_tpu.runtime import device_monitor

    http = ObsHttpServer(daemon.session, port=0)
    mon = device_monitor.get()
    try:
        mon._fenced = True
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/readyz")
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["fenced"] is True
    finally:
        mon._fenced = False
        http.close()


def test_stop_leaves_zero_leaks(table_path):
    s = TpuSparkSession({})
    d = QueryServiceDaemon(session=s).start()
    try:
        clients = [ServeClient.connect(d, f"t{i}", "standard")
                   for i in range(3)]
        for i, c in enumerate(clients):
            c.query(_filter_spec(table_path), params={"lo": i})
        port = d.port
        d.stop()
        assert d.leak_report() == {"connections": 0, "inFlight": 0,
                                   "handlerThreads": 0,
                                   "listener": 0}
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("srtpu-serve")]
        # the port is actually released
        probe = socket.socket()
        probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        probe.bind(("127.0.0.1", port))
        probe.close()
        for c in clients:
            c.close()
    finally:
        d.stop()
        s.stop()


def test_session_serve_convenience(table_path):
    s = TpuSparkSession({})
    try:
        d = s.serve()
        try:
            assert d.session is s
            with ServeClient.connect(d, "conv", "batch") as c:
                got = c.query(_filter_spec(table_path),
                              params={"lo": 390})
                assert got.num_rows == N_ROWS - 391
        finally:
            d.stop()
    finally:
        s.stop()


def test_daemon_owned_session_fresh_process_shape(table_path):
    """The ISSUE acceptance shape: a daemon with its OWN session (the
    fresh-process deployment), serving immediately."""
    d = QueryServiceDaemon().start()
    try:
        with ServeClient.connect(d, "fresh", "interactive") as c:
            got = c.query({"op": "agg",
                           "input": {"op": "parquet",
                                     "path": table_path},
                           "groupBy": ["k"],
                           "aggs": [{"fn": "sum", "col": "a",
                                     "as": "s"}]})
            want = pq.read_table(table_path) \
                .group_by("k").aggregate([("a", "sum")]) \
                .rename_columns(["k", "s"])
            assert got.sort_by("k").equals(want.sort_by("k"))
    finally:
        d.stop()
    assert d.leak_report()["connections"] == 0


def test_serve_env_smoke():
    # tests must run on the virtual CPU mesh, same as the rest of CI
    assert os.environ.get("XLA_FLAGS", "").find(
        "host_platform_device_count") >= 0
