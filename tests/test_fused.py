"""Whole-stage fused single-chip execution (exec/fused.py) and its
supporting kernels: O(n) compaction, binned group-by, PLAIN-parquet
device-direct scan. Oracle is pyarrow throughout (the reference's
CPU-vs-device differential discipline, SURVEY.md section 4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession


@pytest.fixture()
def spark():
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 4})
    yield s
    s.stop()


# ------------------------------------------------------- compact_perm

def test_compact_perm_stable_order():
    from spark_rapids_tpu.ops.filterops import compact_perm

    rng = np.random.default_rng(0)
    keep = jnp.asarray(rng.random(257) < 0.3)
    perm, n = compact_perm(keep, 257)
    vals = jnp.arange(257)
    out = np.asarray(jnp.take(vals, perm))[: int(n)]
    want = np.arange(257)[np.asarray(keep)]
    assert np.array_equal(out, want)
    # all-keep and none-keep edges
    for k in (jnp.ones(64, bool), jnp.zeros(64, bool)):
        perm, n = compact_perm(k, 64)
        assert int(n) == (64 if bool(k[0]) else 0)
        assert sorted(np.asarray(perm).tolist()) == list(range(64))


# --------------------------------------------------- binned group-by

def test_binned_groupby_matches_sorted_path():
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.columnar.batch import DeviceColumn
    from spark_rapids_tpu.exec.operators import TpuHashAggregateExec
    from spark_rapids_tpu.expr import Alias, Average, BoundReference, Count, Sum
    from spark_rapids_tpu.sqltypes.datatypes import double, long

    rng = np.random.default_rng(3)
    n = 4000
    keys = rng.integers(0, 37, n)
    vals = rng.random(n) * 10
    null_mask = rng.random(n) < 0.1
    key_arr = pa.array(keys, type=pa.int64())
    t = pa.table({
        "k": pa.array(np.where(null_mask, None, keys), type=pa.int64()),
        "v": pa.array(vals, type=pa.float64()),
    })
    batch = arrow_to_device(t)
    agg = TpuHashAggregateExec(
        "complete",
        [Alias(BoundReference(0, long, True), "k")],
        [Alias(Sum(BoundReference(1, double, True)), "s"),
         Alias(Count(None), "c"),
         Alias(Average(BoundReference(1, double, True)), "a")],
        None, None)

    part_sorted = agg._partial(batch)

    # stamp vrange on the key column -> binned path
    kcol = batch.columns[0]
    batch.columns[0] = DeviceColumn(kcol.dtype, kcol.data, kcol.validity,
                                    vrange=(0, 63))
    assert agg._bin_ranges(batch, 1) is not None
    part_binned = agg._partial(batch)

    def as_map(part):
        out = agg._merge_final(part)
        from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow

        tt = device_to_arrow(out)
        return {r["k"]: (r["s"], r["c"], r["a"]) for r in tt.to_pylist()}

    a, b = as_map(part_sorted), as_map(part_binned)
    assert set(a) == set(b)
    for k in a:
        assert a[k][1] == b[k][1], k
        assert abs((a[k][0] or 0) - (b[k][0] or 0)) < 1e-9, k


# ---------------------------------------------- PLAIN parquet scanner

def _write_plain(path, table):
    pq.write_table(table, path, compression="NONE", use_dictionary=False,
                   row_group_size=1 << 20, data_page_size=16 << 20)


def test_read_plain_columns_exact(tmp_path):
    from spark_rapids_tpu.io.parquet_plain import read_plain_columns

    rng = np.random.default_rng(5)
    t = pa.table({
        "a": pa.array(rng.integers(-50, 3000, 10_000), type=pa.int64()),
        "b": pa.array(rng.random(10_000), type=pa.float64()),
        "c": pa.array(rng.integers(0, 100, 10_000), type=pa.int32()),
    })
    p = str(tmp_path / "plain.parquet")
    _write_plain(p, t)
    cols = read_plain_columns(p, ["a", "b", "c"])
    assert cols is not None
    for name in ("a", "b", "c"):
        assert np.array_equal(cols[name], np.asarray(t.column(name)))


def test_read_plain_columns_fallbacks(tmp_path):
    from spark_rapids_tpu.io.parquet_plain import read_plain_columns

    t = pa.table({"a": pa.array([1, 2, None, 4], type=pa.int64())})
    p1 = str(tmp_path / "nulls.parquet")
    _write_plain(p1, t)
    assert read_plain_columns(p1, ["a"]) is None  # nulls -> fallback

    t2 = pa.table({"a": pa.array(np.arange(1000), type=pa.int64())})
    p2 = str(tmp_path / "snappy.parquet")
    pq.write_table(t2, p2, compression="snappy")
    assert read_plain_columns(p2, ["a"]) is None  # compressed -> fallback

    t3 = pa.table({"s": pa.array(["x", "y"] * 50)})
    p3 = str(tmp_path / "strs.parquet")
    _write_plain(p3, t3)
    assert read_plain_columns(p3, ["s"]) is None  # byte-array physical


def test_plain_multi_row_group_and_pages(tmp_path):
    from spark_rapids_tpu.io.parquet_plain import read_plain_columns

    rng = np.random.default_rng(6)
    t = pa.table({"a": pa.array(rng.integers(0, 9, 50_000),
                                type=pa.int64()),
                  "b": pa.array(rng.random(50_000), type=pa.float64())})
    p = str(tmp_path / "multi.parquet")
    pq.write_table(t, p, compression="NONE", use_dictionary=False,
                   row_group_size=7_000, data_page_size=8 << 10)
    cols = read_plain_columns(p, ["a", "b"])
    assert cols is not None
    assert np.array_equal(cols["a"], np.asarray(t.column("a")))
    assert np.array_equal(cols["b"], np.asarray(t.column("b")))


# ----------------------------------------------- fused executor e2e

def _q5_files(tmp_path, nfiles=3, rows=20_000, plain=True):
    rng = np.random.default_rng(11)
    d = tmp_path / "data"
    os.makedirs(d, exist_ok=True)
    tabs = []
    for i in range(nfiles):
        t = pa.table({
            "store": pa.array(rng.integers(0, 100, rows), type=pa.int64()),
            "amount": pa.array(rng.random(rows) * 100, type=pa.float64()),
            "qty": pa.array(rng.integers(1, 50, rows), type=pa.int64()),
        })
        tabs.append(t)
        if plain:
            _write_plain(str(d / f"p{i}.parquet"), t)
        else:
            pq.write_table(t, str(d / f"p{i}.parquet"))
    return str(d), pa.concat_tables(tabs)


def _q5_oracle(t):
    f = t.filter(pc.greater(t.column("amount"), 10.0))
    rev = pc.multiply(f.column("amount"),
                      pc.cast(f.column("qty"), pa.float64()))
    w = pa.table({"store": f.column("store"), "revenue": rev})
    return {r["store"]: r["revenue_sum"] for r in
            w.group_by("store").aggregate(
                [("revenue", "sum")]).to_pylist()}


@pytest.mark.parametrize("plain", [True, False])
def test_fused_q5_vs_oracle(spark, tmp_path, plain):
    from spark_rapids_tpu.exec.fused import FusedSingleChipExecutor

    d, all_t = _q5_files(tmp_path, plain=plain)
    df = (spark.read.parquet(d)
          .filter(F.col("amount") > 10.0)
          .select("store",
                  (F.col("amount") * F.col("qty")).alias("revenue"))
          .groupBy("store").agg(F.sum("revenue").alias("rev")))
    phys, _ = df._physical()
    out = FusedSingleChipExecutor(spark.rapids_conf).execute(phys)
    got = {r["store"]: r["rev"] for r in out.to_pylist()}
    exp = _q5_oracle(all_t)
    assert set(got) == set(exp)
    for k in exp:
        assert abs(got[k] - exp[k]) < 1e-6 * max(1.0, abs(exp[k])), k


def test_fused_retry_on_group_overflow(spark, tmp_path):
    """A tiny initial group cap must transparently recompile larger."""
    from spark_rapids_tpu.exec.fused import FusedSingleChipExecutor

    d, all_t = _q5_files(tmp_path, nfiles=1, rows=9_000)
    df = spark.read.parquet(d).groupBy("store").agg(
        F.count("*").alias("n"))
    phys, _ = df._physical()
    ex = FusedSingleChipExecutor(spark.rapids_conf, group_cap=16)
    out = ex.execute(phys)
    assert out.num_rows == len(set(all_t.column("store").to_pylist()))


def test_fused_fallback_collect_arrow(spark, tmp_path):
    """collect_arrow uses the fused path by default and falls back to
    the per-operator engine for plans without a fused lowering."""
    d, all_t = _q5_files(tmp_path, nfiles=2)
    df = spark.read.parquet(d).groupBy("store").agg(
        F.collect_list("qty").alias("qs"))  # non-jittable aggregate
    out = df.collect_arrow()  # must not raise: eager fallback
    assert out.num_rows == len(set(all_t.column("store").to_pylist()))


def test_fused_join_sort_limit(spark):
    rng = np.random.default_rng(2)
    fact = pa.table({
        "k": pa.array(rng.integers(0, 40, 3000), type=pa.int64()),
        "v": pa.array(rng.random(3000) * 10, type=pa.float64())})
    dim = pa.table({"k": pa.array(np.arange(50), type=pa.int64()),
                    "g": pa.array(np.arange(50) % 4, type=pa.int64())})
    out = (spark.createDataFrame(fact)
           .join(spark.createDataFrame(dim), on="k", how="inner")
           .groupBy("g").agg(F.sum("v").alias("s"))
           .orderBy(F.col("s").desc()).limit(2)).collect_arrow()
    j = fact.join(dim, keys="k", join_type="inner")
    w = j.group_by("g").aggregate([("v", "sum")]).to_pylist()
    top = sorted((r["v_sum"] for r in w), reverse=True)[:2]
    assert [round(v, 6) for v in out.column("s").to_pylist()] == \
        [round(v, 6) for v in top]


def test_narrowed_upload_roundtrip():
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    from spark_rapids_tpu.exec.fused import upload_narrowed, widen_traced

    t = pa.table({
        "i": pa.array([-300, 5, None, 120], type=pa.int64()),
        "f": pa.array([1.5, None, 3.0, 4.0], type=pa.float64()),
        "s": pa.array(["a", "bb", None, "dddd"]),
    })
    b = upload_narrowed(t)
    assert b.columns[0].data.dtype == np.int16  # narrowed
    assert b.columns[0].vrange is not None
    wide = jax.jit(widen_traced)(b)
    back = device_to_arrow(wide)
    assert back.column("i").to_pylist() == [-300, 5, None, 120]
    assert back.column("f").to_pylist() == [1.5, None, 3.0, 4.0]
    assert back.column("s").to_pylist() == ["a", "bb", None, "dddd"]
