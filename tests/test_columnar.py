"""Arrow <-> device round trips and batch utilities."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import (
    arrow_to_device,
    concat_batches,
    device_to_arrow,
    next_capacity,
)


def _roundtrip(table: pa.Table) -> pa.Table:
    return device_to_arrow(arrow_to_device(table))


def test_primitive_roundtrip():
    t = pa.table({
        "a": pa.array([1, 2, None, 4], type=pa.int64()),
        "b": pa.array([1.5, None, 3.25, 4.0], type=pa.float64()),
        "c": pa.array([True, None, False, True]),
        "d": pa.array([7, None, -3, 0], type=pa.int32()),
    })
    assert _roundtrip(t).to_pydict() == t.to_pydict()


def test_string_roundtrip():
    t = pa.table({
        "s": pa.array(["hello", None, "", "world-longer-string!", "é↑"]),
    })
    assert _roundtrip(t).to_pydict() == t.to_pydict()


def test_date_timestamp_roundtrip():
    t = pa.table({
        "d": pa.array([0, 1, None, 20000], type=pa.date32()),
        "ts": pa.array([0, 1_000_000, None, 2_000_000_000_000],
                       type=pa.timestamp("us", tz="UTC")),
    })
    assert _roundtrip(t).to_pydict() == t.to_pydict()


def test_empty_table():
    t = pa.table({"a": pa.array([], type=pa.int64())})
    assert _roundtrip(t).num_rows == 0


def test_next_capacity_buckets():
    assert next_capacity(0) == 1024
    assert next_capacity(1024) == 1024
    assert next_capacity(1025) == 2048
    assert next_capacity(1_000_000) == 1 << 20


def test_concat_batches():
    t1 = pa.table({"a": pa.array([1, 2], type=pa.int64()),
                   "s": pa.array(["x", "yy"])})
    t2 = pa.table({"a": pa.array([3, None], type=pa.int64()),
                   "s": pa.array([None, "zzz"])})
    out = device_to_arrow(
        concat_batches([arrow_to_device(t1), arrow_to_device(t2)]))
    assert out.to_pydict() == {
        "a": [1, 2, 3, None], "s": ["x", "yy", None, "zzz"]}
