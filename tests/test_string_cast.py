"""Device string-cast tests (GpuCast.scala:1-120 edge-case list):
leading/trailing whitespace, signs, overflow, inf/nan, malformed input —
device parse vs the host oracle, plus ANSI raise behavior."""

import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.exec import operators as ops
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)

_CONF = {"spark.sql.shuffle.partitions": 2}

_INT_STRINGS = [
    "0", "1", "-1", "+42", "  17  ", "\t-8\n", "2147483647",
    "2147483648", "-2147483648", "-2147483649",
    "9223372036854775807", "-9223372036854775808",
    "9223372036854775808", "-9223372036854775809",
    "", "  ", "abc", "1.5", "1e3", "--5", "+-5", "5-", "00012",
    "999999999999999999999999", None, "+", "-",
]

_FLOAT_STRINGS = [
    "0", "1.5", "-2.25", "+3.", ".5", "-.5", "1e3", "1E-3", "2.5e+2",
    "  7.25 ", "Infinity", "-Infinity", "+Infinity", "inf", "-inf",
    "NaN", "nan", "1e999", "1e-999", "", "abc", "1.2.3", "1e", "e5",
    "1.5e2.5", None, "00.50", "9007199254740993",
]

_DATE_STRINGS = [
    "2020-01-01", "2020-1-1", "2020-12-31", "2020-02-29", "2021-02-29",
    "1999-13-01", "1999-00-10", "2020-06-31", "2020", "2020-06",
    "2020-06-15T12:00:00", "2020-06-15 anything", "  2020-06-15  ",
    "0001-01-01", "20-1-1", "abc", "", None, "2020-6-15-3",
]

_TS_STRINGS = [
    "2020-01-01 00:00:00", "2020-01-01T23:59:59", "2020-01-01 12:30",
    "2020-01-01 1:2:3", "2020-01-01 12:30:45.5",
    "2020-01-01 12:30:45.123456", "2020-01-01", "2020-02-29 10:00:00",
    "2021-02-29 10:00:00", "2020-01-01 24:00:00", "2020-01-01 12:61:00",
    "abc", "", None, "  2020-01-01 06:07:08  ",
]

_BOOL_STRINGS = ["true", "TRUE", " t ", "yes", "Y", "1", "false", "F",
                 "no", "N", "0", "tr", "2", "", None]

_DEC_STRINGS = ["0", "1.23", "-4.567", "  12.5  ", "1e2", "0.005",
                "123456789.12", "99999999999", "abc", "", None, "-0.004"]


def _cast_query(values, to_type):
    def q(s):
        df = s.createDataFrame(pa.table({"s": pa.array(values,
                                                       type=pa.string())}))
        return df.select(F.col("s").cast(to_type).alias("v"))

    return q


@pytest.mark.parametrize("to_type,vals", [
    ("int", _INT_STRINGS),
    ("long", _INT_STRINGS),
    ("short", _INT_STRINGS),
    ("double", _FLOAT_STRINGS),
    ("float", _FLOAT_STRINGS),
    ("boolean", _BOOL_STRINGS),
    ("date", _DATE_STRINGS),
    ("timestamp", _TS_STRINGS),
])
def test_string_cast_matches_oracle(to_type, vals):
    assert_tpu_and_cpu_are_equal_collect(
        _cast_query(vals, to_type), conf=_CONF, ignore_order=False)


def test_string_cast_decimal_matches_oracle():
    from spark_rapids_tpu.sqltypes import DecimalType

    assert_tpu_and_cpu_are_equal_collect(
        _cast_query(_DEC_STRINGS, DecimalType(12, 3)), conf=_CONF,
        ignore_order=False)


def test_string_cast_runs_on_device():
    """The planner no longer tags string casts for CPU fallback."""

    def run(spark):
        df = spark.createDataFrame(
            pa.table({"s": pa.array(["1", "2"], type=pa.string())}))
        df = df.select(F.col("s").cast("long").alias("v"))
        phys, meta = df._physical()
        return phys, meta

    phys, meta = with_tpu_session(run, _CONF)

    def walk(p):
        yield p
        for c in p.children:
            yield from walk(c)

    names = [type(p).__name__ for p in walk(phys)]
    assert "TpuProjectExec" in names, names
    assert "CpuProjectExec" not in names, names


# ------------------------------------------------------------- ANSI mode

def test_ansi_invalid_string_cast_raises():
    from spark_rapids_tpu.exec.cpu_eval import CastError

    conf = {**_CONF, "spark.sql.ansi.enabled": True}
    with pytest.raises(CastError, match="CAST_INVALID_INPUT"):
        with_tpu_session(
            lambda s: _cast_query(["1", "abc"], "long")(s)
            .collect_arrow(), conf)


def test_ansi_overflow_raises():
    # the DEVICE ANSI check fires (overflow detected in the jitted
    # check program, raise_if_set); the public contract is the
    # TpuCastError base, which the CPU oracle's CastError subclasses
    from spark_rapids_tpu.runtime.errors import TpuCastError

    conf = {**_CONF, "spark.sql.ansi.enabled": True}

    def q(s):
        df = s.createDataFrame(pa.table({
            "v": pa.array([1.0, 3.0e10], type=pa.float64())}))
        return df.select(F.col("v").cast("int").alias("i"))

    with pytest.raises(TpuCastError, match="CAST_OVERFLOW"):
        with_tpu_session(lambda s: q(s).collect_arrow(), conf)


def test_ansi_valid_cast_still_works():
    conf = {**_CONF, "spark.sql.ansi.enabled": True}
    out = with_tpu_session(
        lambda s: _cast_query(["1", " 2 ", "-3"], "long")(s)
        .collect_arrow(), conf)
    assert out.column("v").to_pylist() == [1, 2, -3]


def test_ansi_failable_cast_falls_back_to_cpu():
    """ANSI mode places failable casts on the CPU path (errors must
    raise eagerly; device ANSI kernels are future work)."""

    def run(spark):
        df = spark.createDataFrame(
            pa.table({"s": pa.array(["1"], type=pa.string())}))
        df = df.select(F.col("s").cast("long").alias("v"))
        phys, _ = df._physical()
        return phys

    conf = {**_CONF, "spark.sql.ansi.enabled": True}
    phys = with_tpu_session(run, conf)

    def walk(p):
        yield p
        for c in p.children:
            yield from walk(c)

    names = [type(p).__name__ for p in walk(phys)]
    assert "CpuProjectExec" in names, names
