"""Hive-partitioned parquet reads (col=value/ directory layout) with
static + DYNAMIC partition pruning (round-4 verdict missing #6;
reference GpuFileSourceScanExec.scala:68,360-420)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession

_CONF = {"spark.sql.shuffle.partitions": 4,
         "spark.rapids.sql.fusedExec.enabled": False,
         "spark.sql.autoBroadcastJoinThreshold": -1}


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


def _write_partitioned(root, n_dates=6, rows=400):
    rng = np.random.default_rng(0)
    all_rows = []
    for d in range(n_dates):
        dirp = os.path.join(root, f"date={d}")
        os.makedirs(dirp, exist_ok=True)
        t = pa.table({
            "k": pa.array(rng.integers(0, 20, rows), type=pa.int64()),
            "v": pa.array(rng.random(rows)),
        })
        pq.write_table(t, os.path.join(dirp, "part-0.parquet"))
        all_rows.append(t.append_column(
            "date", pa.array([d] * rows, type=pa.int64())))
    return pa.concat_tables(all_rows)


def test_partition_column_materializes(spark, tmp_path):
    allt = _write_partitioned(str(tmp_path))
    df = spark.read.parquet(str(tmp_path))
    assert "date" in df.columns
    out = df.collect_arrow()
    assert out.num_rows == allt.num_rows
    import collections

    want = collections.Counter(allt.column("date").to_pylist())
    got = collections.Counter(out.column("date").to_pylist())
    assert got == want


def test_static_partition_pruning(spark, tmp_path):
    from spark_rapids_tpu.exec.operators import TpuFileScanExec

    _write_partitioned(str(tmp_path))
    df = spark.read.parquet(str(tmp_path)).filter(F.col("date") == 3)
    phys, _ = df._physical()

    def find(n):
        if isinstance(n, TpuFileScanExec):
            return n
        for c in n.children:
            r = find(c)
            if r is not None:
                return r

    scan = find(phys)
    files = [f for t in scan._tasks for f in t]
    assert len(files) == 1 and "date=3" in files[0]
    out = df.collect_arrow()
    assert set(out.column("date").to_pylist()) == {3}


def test_dynamic_partition_pruning_via_aqe(spark, tmp_path):
    from spark_rapids_tpu.plan.aqe import AdaptiveQueryExecutor

    allt = _write_partitioned(str(tmp_path))
    fact = spark.read.parquet(str(tmp_path))
    # dim filters to dates {1, 4} at runtime; static planner cannot know
    dim = spark.createDataFrame(pa.table({
        "date": pa.array(np.arange(20), type=pa.int64()),
        "grp": pa.array(np.arange(20) % 3, type=pa.int64()),
    })).filter((F.col("date") == 1) | (F.col("date") == 4)) \
       .repartition(2, "date")
    df = fact.join(dim, on="date", how="inner")
    phys, _ = df._physical()
    ex = AdaptiveQueryExecutor(spark.rapids_conf)
    out = ex.execute(phys)
    assert any("dynamic partition pruning" in d
               for d in ex.decisions), ex.decisions
    want = sum(1 for d in allt.column("date").to_pylist()
               if d in (1, 4))
    assert out.num_rows == want


def test_eq_in_parent_dir_is_not_a_partition(tmp_path, spark):
    """A `name=value` segment ABOVE the input base path is part of the
    location, not a partition column (PartitioningAwareFileIndex
    derives partitions relative to the scanned root only)."""
    root = tmp_path / "run=3" / "data"
    os.makedirs(root)
    t = pa.table({"k": pa.array([1, 2, 3], type=pa.int64())})
    pq.write_table(t, str(root / "part.parquet"))
    df = spark.read.parquet(str(root))
    assert [f.name for f in df.schema.fields] == ["k"]
    assert df.collect_arrow().column("k").to_pylist() == [1, 2, 3]

    # ...while real partition dirs BELOW that base still materialize
    sub = root / "date=7"
    os.makedirs(sub)
    pq.write_table(t, str(sub / "p.parquet"))
