"""Data-movement telemetry (obs/telemetry.py, PR 6): transfer-ledger
totals vs real collect sizes, HBM occupancy high-water vs the spill
catalog's own peak, roofline summary plumbing into
last_execution/profile/Prometheus, per-query event-log isolation for
concurrent tenants, process-pool event forwarding, Prometheus label
escaping, and the live HTTP endpoint's lifecycle."""

import itertools
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.obs import eventlog, prom, telemetry
from spark_rapids_tpu.obs import events as obs_events
from spark_rapids_tpu.obs.events import SCHEMA_VERSION


def _session(**conf):
    from spark_rapids_tpu.api.session import TpuSparkSession

    return TpuSparkSession(conf)


def _table(rows=4096):
    return pa.table({
        "k": pa.array(np.arange(rows) % 11, type=pa.int64()),
        "v": pa.array(np.arange(rows, dtype=np.float64)),
    })


# ---------------------------------------------------------- the ledger

def test_ledger_totals_match_collect_sizes():
    """The h2d side of the ledger must cover the uploaded input (padded
    capacity buckets inflate it by a bounded factor), the d2h side the
    collected output — per query, within tolerance."""
    s = _session(**{"spark.sql.shuffle.partitions": 2})
    try:
        t = _table()
        df = (s.createDataFrame(t).filter(F.col("v") >= 0.0)
              .groupBy("k").agg(F.sum("v").alias("sv")))
        out = df.collect_arrow()
        tel = s.last_execution["telemetry"]
        assert tel is not None
        h2d = tel["bytesMoved"].get("h2d", 0)
        d2h = tel["bytesMoved"].get("d2h", 0)
        # uploads cover the input within a bounded factor: integer
        # narrowing can SHRINK the on-wire bytes (int64 keys ship at
        # observed width), padding/validity/variants can inflate them
        assert h2d >= 0.4 * t.nbytes, (h2d, t.nbytes)
        assert h2d <= 64 * t.nbytes, (h2d, t.nbytes)
        assert d2h > 0
        assert tel["bytesMovedTotal"] == sum(
            tel["bytesMoved"].values())
        assert tel["transfers"] >= 2
        assert tel["bytesPerOutputRow"] == pytest.approx(
            tel["bytesMovedTotal"] / out.num_rows, rel=1e-3)
        assert tel["wallMs"] > 0 and 0 <= tel["rooflineFrac"] <= 1.0
        # the per-site view decomposes the same bytes
        site_total = sum(c["bytes"] for c in tel["perSite"].values())
        assert site_total == tel["bytesMovedTotal"]
    finally:
        s.stop()


def test_hbm_highwater_matches_catalog_peak():
    """The occupancy timeline's per-query high-water must equal the
    catalog pool's own peak when one query owns every reservation."""
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.runtime.memory import SpillCatalog

    s = _session()
    try:
        qid = obs_events.begin_query()
        try:
            cat = SpillCatalog(device_limit=1 << 30,
                               host_limit=1 << 30)
            b1 = cat.add_batch(arrow_to_device(_table(2048)))
            b2 = cat.add_batch(arrow_to_device(_table(1024)))
            b1.close()
            b3 = cat.add_batch(arrow_to_device(_table(512)))
            b2.close()
            b3.close()
        finally:
            obs_events.finish_query(qid)
        summ = telemetry.query_summary(qid)
        assert summ["hbmPeakBytes"] == cat.pool.peak > 0
        # the process high-water covers this catalog's peak too
        assert telemetry.ledger.hbm_peak >= cat.pool.peak
        assert cat.buffer_count() == 0
    finally:
        s.stop()


def test_spill_transfers_recorded_per_direction():
    """Forced spill down to disk and back records d2h, spill-disk and
    h2d entries attributed to the owning query."""
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.runtime.memory import SpillCatalog, SpillTier

    s = _session()
    try:
        qid = obs_events.begin_query()
        try:
            cat = SpillCatalog(device_limit=1 << 30,
                               host_limit=1 << 30)
            sb = cat.add_batch(arrow_to_device(_table(2048)))
            cat.spill_device_bytes(sb.size_bytes)     # -> HOST (d2h)
            assert sb.tier == SpillTier.HOST
            cat.spill_host_bytes(sb.size_bytes)       # -> DISK
            assert sb.tier == SpillTier.DISK
            sb.get_batch()                            # unspill (h2d)
            assert sb.tier == SpillTier.DEVICE
            sb.close()
        finally:
            obs_events.finish_query(qid)
        sites = telemetry.query_summary(qid)["perSite"]
        assert sites["spill.toHost"]["bytes"] == sb.size_bytes
        assert sites["spill.toDisk"]["bytes"] == sb.size_bytes
        assert sites["spill.fromDisk"]["bytes"] == sb.size_bytes
        assert sites["spill.unspill"]["bytes"] == sb.size_bytes
        moved = telemetry.query_summary(qid)["bytesMoved"]
        assert moved["spill-disk"] == 2 * sb.size_bytes
    finally:
        s.stop()


def test_telemetry_disabled_is_inert():
    s = _session(**{"spark.rapids.tpu.telemetry.enabled": False})
    try:
        df = s.createDataFrame(_table(256)).groupBy("k").agg(
            F.count("*").alias("n"))
        df.collect_arrow()
        assert s.last_execution["telemetry"] is None
    finally:
        telemetry.ledger.enabled = True  # process state: restore
        s.stop()


def test_link_peaks_probe_and_cache():
    peaks = telemetry.link_peaks()
    assert peaks["devicePeakBytesPerS"] > 0
    assert peaks is telemetry.link_peaks()  # in-process cache


# ----------------------------------------------- telemetry.summary event

def test_summary_event_in_stream_and_profile(tmp_path):
    from spark_rapids_tpu.obs import report

    d = str(tmp_path / "log")
    s = _session(**{
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": d,
        "spark.sql.shuffle.partitions": 2,
    })
    try:
        (s.createDataFrame(_table()).groupBy("k")
         .agg(F.sum("v").alias("sv"))).collect_arrow()
        qid = s.last_execution["queryId"]
        tel = s.last_execution["telemetry"]
        events = eventlog.load(d, qid)
        summaries = [e for e in events
                     if e["event"] == "telemetry.summary"]
        assert len(summaries) == 1
        assert summaries[0]["bytesMoved"] == tel["bytesMoved"]
        by_dir = {}
        for e in events:
            if e["event"] == "transfer":
                by_dir[e["direction"]] = \
                    by_dir.get(e["direction"], 0) + e["bytes"]
        assert by_dir == tel["bytesMoved"]
        prof = report.profile_data(d)
        assert prof["telemetry"]["bytesMovedTotal"] == \
            tel["bytesMovedTotal"]
        assert {k: v["bytes"] for k, v in
                prof["dataMovement"].items()} == tel["bytesMoved"]
        txt = report.profile(d)
        assert "data movement:" in txt and "roofline:" in txt
    finally:
        s.stop()


def test_explain_executed_reports_data_moved():
    from spark_rapids_tpu.explain import explain_potential_tpu_plan

    s = _session(**{"spark.sql.shuffle.partitions": 2})
    try:
        q = (s.createDataFrame(_table()).groupBy("k")
             .agg(F.sum("v").alias("sv")))
        q.collect_arrow()
        txt = explain_potential_tpu_plan(q, mode="EXECUTED")
        assert "data moved:" in txt and "roofline_frac" in txt
    finally:
        s.stop()


# ------------------------------------------------- per-query event logs

def test_eventlog_concurrent_queries_isolated(tmp_path):
    """Two queries interleaving on the bus land in isolated per-query
    files, each replaying to its own identical span tree."""
    d = str(tmp_path / "log")
    w = eventlog.EventLogWriter(d, rotate_bytes=4096)
    seq = itertools.count(1)

    def ev(event, qid, **f):
        return {"event": event, "seq": next(seq), "ts": 0.0,
                "schemaVersion": SCHEMA_VERSION, "queryId": qid, **f}

    w(ev("query.start", 1))
    w(ev("query.start", 2))
    for i in range(60):  # crosses the rotation threshold for both
        w(ev("operator.span", 1, operator="OpA" + "x" * 60,
             metric="m", wallNs=i, deviceNs=0))
        w(ev("operator.span", 2, operator="OpB" + "y" * 60,
             metric="m", wallNs=i, deviceNs=0))
    w(ev("query.end", 1, engine="eager", status="ok"))
    # query 2 keeps writing AFTER query 1 finalized
    w(ev("operator.span", 2, operator="late", metric="m", wallNs=1,
         deviceNs=0))
    w(ev("query.end", 2, engine="eager", status="ok"))
    assert w.open_query_ids() == []
    l1 = eventlog.load(d, 1)
    l2 = eventlog.load(d, 2)
    assert all(e["queryId"] == 1 for e in l1) and len(l1) == 62
    assert all(e["queryId"] == 2 for e in l2) and len(l2) == 63
    assert len(eventlog.log_files(d, 1)) > 1  # rotation still works
    t1 = eventlog.load_spans(d, 1)
    t2 = eventlog.load_spans(d, 2)
    assert [t.query_id for t in t1] == [1]
    assert [t.query_id for t in t2] == [2]
    ops2 = [sp.name for sp in t2[0].walk() if sp.kind == "operator"]
    assert "late" in ops2 and not any("OpA" in o for o in ops2)


def test_eventlog_live_concurrent_sessions_round_trip(tmp_path):
    """Two live queries submitted from two threads of one session get
    isolated logs that replay to the live trees (the PR 5 NOTE)."""
    d = str(tmp_path / "log")
    s = _session(**{
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": d,
        "spark.sql.shuffle.partitions": 2,
    })
    try:
        start = threading.Barrier(2)

        def run():
            start.wait()
            (s.createDataFrame(_table()).filter(F.col("v") > 1.0)
             .groupBy("k").agg(F.sum("v").alias("sv"))).collect_arrow()

        threads = [threading.Thread(target=run) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        live = {t.query_id: t for t in s.obs.spans.completed}
        qids = sorted(live)[-2:]
        assert len(qids) == 2
        for q in qids:
            trees = eventlog.load_spans(d, q)
            assert len(trees) == 1
            assert trees[0].to_dict() == live[q].to_dict()
            for e in eventlog.load(d, q):
                assert e["queryId"] == q
    finally:
        s.stop()


# -------------------------------------------- process-pool forwarding

def test_process_pool_forwards_spans_and_transfers(tmp_path):
    """ProcessBackend attempts forward their operator spans + transfer
    records to the driver bus: the span tree matches an in-process
    shape, the event log round-trips identically, and worker bytes
    land in the driver ledger."""
    import pyarrow.parquet as pq

    from spark_rapids_tpu.parallel.process_pool import (
        ProcessBackend,
        ProcessWorkerPool,
    )
    from spark_rapids_tpu.runtime.scheduler import StageScheduler, Task

    frag = ("spark_rapids_tpu.parallel.process_pool:"
            "run_scan_agg_fragment")
    files = []
    for i in range(4):
        p = str(tmp_path / f"p{i}.parquet")
        pq.write_table(_table(512), p)
        files.append(p)
    d = str(tmp_path / "log")
    s = _session(**{
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": d,
    })
    pool = ProcessWorkerPool(2)
    try:
        qid = obs_events.begin_query()
        try:
            tasks = [Task(i, payload=(frag, {
                "files": [f], "keys": ["k"], "aggs": [("v", "sum")]}))
                for i, f in enumerate(files)]
            out = StageScheduler(
                None, name="mp-obs",
                backend=ProcessBackend(pool)).run(tasks)
            assert len(out) == 4
        finally:
            obs_events.finish_query(qid, engine="mp", status="ok",
                                    fallbacks=0, degradations=0)
        live = s.obs.spans.last
        assert live is not None and live.query_id == qid
        frag_spans = [sp for sp in live.walk()
                      if sp.kind == "operator"
                      and sp.name == "ScanAggFragment"]
        assert len(frag_spans) == 4
        # every forwarded span hangs under its task attempt
        assert all(sp.task is not None and sp.stage is not None
                   for sp in frag_spans)
        assert all(sp.wall_ns > 0 and sp.rows for sp in frag_spans)
        trees = eventlog.load_spans(d, qid)
        assert trees[0].to_dict() == live.to_dict()
        moved = telemetry.query_summary(qid)["bytesMoved"]
        assert moved.get("shuffle", 0) > 0  # worker result bytes
    finally:
        pool.close()
        s.stop()


# ---------------------------------------------------- prometheus format

def test_prom_label_escaping():
    assert prom.escape_label('plain') == 'plain'
    assert prom.escape_label('a"b') == r'a\"b'
    assert prom.escape_label('a\\b') == r'a\\b'
    assert prom.escape_label('a\nb') == r'a\nb'
    # backslash escapes FIRST: a literal \" must not double-escape
    assert prom.escape_label('\\"') == r'\\\"'


def test_prom_render_escapes_hostile_site_labels():
    """A site/operator name carrying quotes, backslashes or newlines
    must still produce parseable exposition text."""
    hostile = 'we"ird\\site\nname'
    telemetry.record("h2d", hostile, 1234, emit=False)
    try:
        txt = prom.render()
        line = next(l for l in txt.splitlines()
                    if "srtpu_transfer_bytes_total" in l
                    and "weird" not in l and "we" in l and "1234" in l)
        assert "\n" not in line
        assert r'we\"ird\\site\nname' in line
        # label section has balanced, parseable quoting once escape
        # sequences are consumed
        labels = line[line.index("{") + 1:line.rindex("}")]
        unescaped = labels.replace("\\\\", "").replace('\\"', "")
        assert unescaped.count('"') % 2 == 0, labels
        assert "\\" not in unescaped.replace("\\n", ""), labels
        for sample in txt.splitlines():
            assert sample.startswith(("#", "srtpu_")), sample
    finally:
        with telemetry.ledger._lock:
            telemetry.ledger.sites.pop(hostile, None)
            telemetry.ledger._site_dir.pop(hostile, None)


def test_prom_per_query_telemetry_families():
    s = _session()
    try:
        (s.createDataFrame(_table()).groupBy("k")
         .agg(F.sum("v").alias("sv"))).collect_arrow()
        qid = s.last_execution["queryId"]
        txt = s.prometheus_metrics()
        assert f'srtpu_query_bytes_moved{{queryId="{qid}"' in txt
        assert f'srtpu_query_hbm_peak_bytes{{queryId="{qid}"}}' in txt
        assert f'srtpu_query_roofline_frac{{queryId="{qid}"}}' in txt
        assert "srtpu_hbm_peakBytes" in txt
        assert "srtpu_transfer_bytes_total{" in txt
    finally:
        s.stop()


# -------------------------------------------------------- http endpoint

def test_http_endpoint_serves_and_shuts_down():
    s = _session(**{"spark.rapids.tpu.obs.http.enabled": True})
    try:
        (s.createDataFrame(_table()).groupBy("k")
         .agg(F.sum("v").alias("sv"))).collect_arrow()
        port = s.obs.http.port
        assert port > 0
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        for line in body.splitlines():
            assert line.startswith(("#", "srtpu_")), line
        q = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/queries", timeout=10
        ).read().decode())
        assert "admission" in q and "queries" in q
        qid = str(s.last_execution["queryId"])
        assert qid in q["queries"]
        assert q["queries"][qid]["bytesMovedTotal"] > 0
        assert urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ).read() == b"ok\n"
    finally:
        s.stop()
    # leak-free: the thread is gone and the socket refuses
    assert not any(t.name == "srtpu-obs-http" and t.is_alive()
                   for t in threading.enumerate())
    with pytest.raises((urllib.error.URLError, ConnectionError,
                        OSError)):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                               timeout=2)


def test_http_disabled_by_default():
    s = _session()
    try:
        assert s.obs.http is None
    finally:
        s.stop()
