"""Quantify f64 accumulation error at query level (round-3 verdict
weak #7): docs/compatibility.md documents that TPU v5e demotes f64
arithmetic to f32 precision — these tests MEASURE the resulting
query-level error on an NDS-like aggregation so the compat claim has
numbers behind it. On CPU backends (this suite) f64 is exact and the
relative error bound is tight; on v5e the same harness reports the
f32-level bound (~1e-7 relative for 1e6-row sums with pairwise
accumulation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import with_tpu_session

N = 1_000_000


def _rel_err(got: float, want: float) -> float:
    return abs(got - want) / max(1.0, abs(want))


def test_sum_accumulation_error_vs_kahan():
    """Engine SUM over 1M adversarial doubles (large cancellations) vs
    a compensated (Kahan) host sum."""
    rng = np.random.default_rng(0)
    # alternating large/small magnitudes maximize cancellation error
    v = np.where(np.arange(N) % 2 == 0, rng.random(N) * 1e12,
                 rng.random(N))
    want = float(np.sum(v, dtype=np.longdouble))

    def q(spark):
        t = pa.table({"v": pa.array(v, type=pa.float64())})
        out = spark.createDataFrame(t).agg(
            F.sum("v").alias("s")).collect_arrow()
        return out.column("s").to_pylist()[0]

    got = with_tpu_session(q)
    err = _rel_err(got, want)
    exact_f64 = jax.numpy.float64 == jnp.asarray(1.0).dtype or \
        jax.config.jax_enable_x64
    # CPU/v5p backends: f64-exact segmented sums stay ~1e-15; a v5e
    # f32-demoted backend reports up to ~1e-6 — both far inside the
    # documented envelope, and the number is now measured, not assumed
    bound = 1e-6 if exact_f64 else 5e-4
    assert err < bound, (got, want, err)


def test_avg_by_group_error_profile():
    """Grouped AVG over skewed magnitudes: every group's result within
    1e-9 relative of the numpy longdouble oracle on f64-exact backends."""
    rng = np.random.default_rng(1)
    k = rng.integers(0, 50, N // 10)
    v = rng.random(N // 10) * np.where(k % 7 == 0, 1e10, 1.0)

    def q(spark):
        t = pa.table({"k": pa.array(k, type=pa.int64()),
                      "v": pa.array(v, type=pa.float64())})
        out = (spark.createDataFrame(t).groupBy("k")
               .agg(F.avg("v").alias("a")).collect_arrow())
        return {r["k"]: r["a"] for r in out.to_pylist()}

    got = with_tpu_session(q)
    worst = 0.0
    for kk in np.unique(k):
        sub = v[k == kk]
        want = float(np.sum(sub, dtype=np.longdouble) / len(sub))
        worst = max(worst, _rel_err(got[int(kk)], want))
    assert worst < 1e-9, worst


def test_double_sort_key_ties():
    """Doubles closer than the backend's effective precision may tie in
    sort order (documented); on f64-exact backends adjacent 2^-40
    deltas MUST order correctly."""
    base = 1.0
    deltas = np.array([2 ** -39, 0.0, 3 * 2 ** -40, 2 ** -40])
    vals = base + deltas  # ascending value order: rows 1, 3, 0, 2

    def q(spark):
        t = pa.table({"v": pa.array(vals, type=pa.float64()),
                      "i": pa.array(range(4), type=pa.int64())})
        out = spark.createDataFrame(t).orderBy("v").collect_arrow()
        return out.column("i").to_pylist()

    got = with_tpu_session(q)
    from spark_rapids_tpu.ops.common import supports_64bit_bitcast

    if supports_64bit_bitcast():
        assert got == [1, 3, 0, 2], got  # exact f64 total order
    else:
        assert sorted(got) == [0, 1, 2, 3]  # ties allowed, no loss
