"""Out-of-core + fault-injection tests — the reference's *RetrySuite
family (HashAggregateRetrySuite, GpuSortRetrySuite, RmmSparkRetrySuiteBase
forced-OOM pattern, SURVEY.md section 4 tier 2): force OOM/split at
specific allocation points and assert queries still produce oracle-equal
results; force tiny budgets and assert spill actually happened.
"""

import os

import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_cpu_session,
    with_tpu_session,
)
from spark_rapids_tpu.testing.datagen import (
    DoubleGen,
    IntGen,
    LongGen,
    RepeatSeqGen,
    StringGen,
    gen_table,
)

_CONF = {"spark.sql.shuffle.partitions": 2}


@pytest.fixture(scope="module")
def data_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("ooc")
    t = gen_table([
        ("store", RepeatSeqGen(IntGen(0, 60, nullable=True), 17)),
        ("amount", DoubleGen(include_specials=False)),
        ("qty", LongGen(lo=-50, hi=50)),
        ("name", StringGen(max_len=8, cardinality=40)),
    ], n=4000, seed=7)
    for i in range(4):
        pq.write_table(t.slice(i * 1000, 1000),
                       os.path.join(d, f"p{i}.parquet"))
    return str(d)


def _agg_query(s, path):
    return (s.read.parquet(path)
            .groupBy("store")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n"),
                 F.max("qty").alias("mq")))


def _sort_query(s, path):
    return s.read.parquet(path).select("store", "qty", "name") \
        .orderBy("store", "qty", "name")


def test_agg_small_batches_merge_and_fallback(data_path):
    """Tiny batch target forces incremental buffer merges AND the
    high-cardinality re-partition finalize fallback."""
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _agg_query(s, data_path),
        conf={**_CONF,
              "spark.rapids.sql.batchSizeRows": 32,
              "spark.rapids.sql.reader.batchSizeRows": 512})


def test_sort_out_of_core_merge(data_path):
    """Many small scan batches -> many sorted runs -> pairwise merges."""
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _sort_query(s, data_path),
        conf={**_CONF,
              "spark.rapids.sql.reader.batchSizeRows": 300},
        ignore_order=False)


@pytest.mark.parametrize("tag", ["agg_partial", "agg_merge"])
def test_agg_retry_oom_injection(data_path, tag):
    """Injected TpuRetryOOM at each agg allocation point: query retries
    and still matches the oracle."""
    conf = {**_CONF,
            "spark.rapids.sql.reader.batchSizeRows": 512,
            "spark.rapids.sql.batchSizeRows": 16,
            "spark.rapids.memory.gpu.oomInjection.mode": "once",
            "spark.rapids.memory.gpu.oomInjection.filter": tag}

    def run(s):
        from spark_rapids_tpu.runtime.memory import get_catalog

        out = _agg_query(s, data_path).collect_arrow()
        return out, dict(get_catalog().metrics)

    tpu, metrics = with_tpu_session(run, conf=conf)
    assert metrics["retry_oom_injected"] >= 1, metrics
    cpu = with_cpu_session(
        lambda s: _agg_query(s, data_path).collect_arrow(), conf=_CONF)
    from spark_rapids_tpu.testing.asserts import assert_tables_equal

    assert_tables_equal(tpu, cpu)


def test_agg_split_and_retry_injection(data_path):
    """Injected TpuSplitAndRetryOOM: the input batch is halved and both
    halves aggregated; result still matches."""
    conf = {**_CONF,
            "spark.rapids.memory.gpu.oomInjection.mode": "split_once",
            "spark.rapids.memory.gpu.oomInjection.filter": "agg_partial"}

    def run(s):
        from spark_rapids_tpu.runtime.memory import get_catalog

        out = _agg_query(s, data_path).collect_arrow()
        return out, dict(get_catalog().metrics)

    tpu, metrics = with_tpu_session(run, conf=conf)
    assert metrics["retry_oom_injected"] >= 1, metrics
    cpu = with_cpu_session(
        lambda s: _agg_query(s, data_path).collect_arrow(), conf=_CONF)
    from spark_rapids_tpu.testing.asserts import assert_tables_equal

    assert_tables_equal(tpu, cpu)


def test_sort_retry_oom_injection(data_path):
    conf = {**_CONF,
            "spark.rapids.sql.reader.batchSizeRows": 600,
            "spark.rapids.memory.gpu.oomInjection.mode": "once",
            "spark.rapids.memory.gpu.oomInjection.filter": "sort_batch"}

    def run(s):
        from spark_rapids_tpu.runtime.memory import get_catalog

        out = _sort_query(s, data_path).collect_arrow()
        return out, dict(get_catalog().metrics)

    tpu, metrics = with_tpu_session(run, conf=conf)
    assert metrics["retry_oom_injected"] >= 1, metrics
    cpu = with_cpu_session(
        lambda s: _sort_query(s, data_path).collect_arrow(), conf=_CONF)
    from spark_rapids_tpu.testing.asserts import assert_tables_equal

    assert_tables_equal(tpu, cpu, ignore_order=False)


def test_sort_spills_under_memory_pressure(data_path):
    """A pool far smaller than the working set forces device->host spill
    of parked runs; the query still completes correctly."""
    conf = {**_CONF,
            "spark.rapids.sql.reader.batchSizeRows": 500,
            "spark.rapids.memory.gpu.maxAllocBytes": 150_000}

    def run(s):
        from spark_rapids_tpu.runtime.memory import get_catalog

        out = _sort_query(s, data_path).collect_arrow()
        return out, dict(get_catalog().metrics)

    tpu, metrics = with_tpu_session(run, conf=conf)
    assert metrics["spill_to_host"] >= 1, metrics
    cpu = with_cpu_session(
        lambda s: _sort_query(s, data_path).collect_arrow(), conf=_CONF)
    from spark_rapids_tpu.testing.asserts import assert_tables_equal

    assert_tables_equal(tpu, cpu, ignore_order=False)


def test_sub_partitioned_join(data_path):
    """Build side larger than batchSizeBytes -> key-hash sub-partitioned
    join, still oracle-equal."""
    def q(s):
        fact = s.read.parquet(data_path)
        dim = s.createDataFrame({
            "store": list(range(0, 60)),
            "city": [f"c{i % 9}" for i in range(60)],
        })
        return fact.join(dim, on="store", how="inner") \
            .select("store", "qty", "city")

    assert_tpu_and_cpu_are_equal_collect(
        q, conf={**_CONF,
                 "spark.sql.autoBroadcastJoinThreshold": -1,
                 "spark.rapids.sql.batchSizeBytes": 4096})


def test_merge_sorted_kernel_direct():
    """Unit: merge of two sorted runs == sort of the concat."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.columnar import arrow_to_device, device_to_arrow
    from spark_rapids_tpu.columnar.batch import concat_batches
    from spark_rapids_tpu.expr import BoundReference
    from spark_rapids_tpu.ops import sortops
    from spark_rapids_tpu.plan.logical import SortOrder
    from spark_rapids_tpu.sqltypes.datatypes import long

    rng = np.random.default_rng(3)
    orders = [SortOrder(BoundReference(0, long, True), ascending=True)]

    a_vals = np.sort(rng.integers(0, 100, 37))
    b_vals = np.sort(rng.integers(0, 100, 53))
    a = arrow_to_device(pa.table({"k": pa.array(a_vals, type=pa.int64())}))
    b = arrow_to_device(pa.table({"k": pa.array(b_vals, type=pa.int64())}))
    merged = sortops.merge_sorted(a, b, orders)
    expect = sortops.sort_batch(concat_batches([a, b]), orders)
    got = device_to_arrow(merged).column("k").to_pylist()
    want = device_to_arrow(expect).column("k").to_pylist()
    assert got == want
    assert got == sorted(list(a_vals) + list(b_vals))


def _nlj_query(s, how="inner"):
    import pyarrow as pa

    left = s.createDataFrame(pa.table({
        "a": list(range(400)),
        "x": [float(i % 7) for i in range(400)],
    }))
    right = s.createDataFrame(pa.table({
        "b": list(range(0, 800, 2)),
        "y": [float(i % 5) for i in range(400)],
    }))
    return left.join(right, on=F.col("a") < F.col("b"), how=how)


@pytest.mark.parametrize("how", ["inner", "left", "full"])
def test_nested_loop_join_split_injection(how):
    """Injected TpuSplitAndRetryOOM at the nested-loop pair-expansion
    reservation: the probe side is halved (possibly repeatedly) and the
    chunked join still matches the oracle — including full-outer
    build-side padding accumulated across chunks."""
    conf = {"spark.rapids.memory.gpu.oomInjection.mode": "split_once",
            "spark.rapids.memory.gpu.oomInjection.filter": "nlj_pairs"}

    def run(s):
        from spark_rapids_tpu.runtime.memory import get_catalog

        out = _nlj_query(s, how).collect_arrow()
        return out, dict(get_catalog().metrics)

    tpu, metrics = with_tpu_session(run, conf=conf)
    assert metrics["retry_oom_injected"] >= 1, metrics
    cpu = with_cpu_session(
        lambda s: _nlj_query(s, how).collect_arrow())
    from spark_rapids_tpu.testing.asserts import assert_tables_equal

    assert_tables_equal(tpu, cpu)


def test_nested_loop_join_reserves_pair_bytes():
    """The pair-expansion reservation must be visible to the ledger: peak
    reserved bytes during a cross join >= the expanded pair-set size."""

    def run(s):
        from spark_rapids_tpu.runtime.memory import get_catalog

        left = s.createDataFrame({"a": list(range(512))})
        right = s.createDataFrame({"b": list(range(512))})
        out = left.crossJoin(right).count()
        return out, get_catalog().pool.peak

    n, peak = with_tpu_session(run, conf={})
    assert n == 512 * 512
    # 512*512 pairs x 2 int64 columns = 4 MiB minimum
    assert peak >= 512 * 512 * 16, peak
