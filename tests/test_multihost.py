"""Multi-host failure-domain suite (PR 17): 2D (hosts x chips) mesh
semantics on the virtual 8-device CPU mesh via simulated host groups.

Covers the pod-scale contract end to end:
- host_groups topology oracle (real process grouping is exercised by
  tests/test_multiprocess.py; here the simulated split);
- multihost.initialize(): idempotent for identical args, a clear
  RuntimeError for different args (the old silent return hid
  misconfiguration), and a multihost.init obs event on first wiring;
- heartbeat host failure domains: one silent member evicts its WHOLE
  host group atomically, fires on_host_death, and a re-registering
  executor rejoins with a fresh seq;
- device_monitor.fence_host / unfence_host: one epoch step for the
  whole host, fencedHosts in counters(), capacity-only semantics;
- the 2D mesh itself: a simulated two-host query is oracle-identical
  with DCN bytes ledgered BELOW ICI bytes (hierarchical placement),
  and host.fatal chaos recovers over the survivor host.
"""

import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.obs import events as obs_events
from spark_rapids_tpu.parallel import multihost
from spark_rapids_tpu.parallel.heartbeat import HeartbeatManager
from spark_rapids_tpu.runtime import device_monitor as dm
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.testing.asserts import (
    assert_tables_equal,
    with_cpu_session,
    with_tpu_session,
)

MH = {"spark.rapids.tpu.mesh": 8,
      "spark.rapids.tpu.multihost.simulatedHosts": 2,
      "spark.sql.shuffle.partitions": 4,
      "spark.sql.autoBroadcastJoinThreshold": -1}


@pytest.fixture(autouse=True)
def _isolated_host_state():
    faults.install(faults.FaultRegistry())
    dm.clear_chip_fences()
    yield
    faults.install(faults.FaultRegistry())
    dm.clear_chip_fences()


# ------------------------------------------------------ topology oracle

def test_host_groups_simulated_split(cpu_devices):
    groups = multihost.host_groups(cpu_devices, simulated_hosts=2)
    assert len(groups) == 2
    assert [len(g) for g in groups] == [4, 4]
    # host-major contiguous: group i is devices [4i, 4i+4)
    assert [d.id for d in groups[0]] == [d.id for d in cpu_devices[:4]]
    assert [d.id for d in groups[1]] == [d.id for d in cpu_devices[4:]]


def test_host_groups_defaults_to_one(cpu_devices):
    assert multihost.host_groups(cpu_devices) == [list(cpu_devices)]
    assert multihost.host_groups(cpu_devices, 0) == [list(cpu_devices)]
    # more hosts than devices: cannot split, stays 1D
    assert multihost.host_groups(cpu_devices[:1], 4) \
        == [list(cpu_devices[:1])]


def test_host_groups_drops_ragged_remainder(cpu_devices):
    groups = multihost.host_groups(cpu_devices[:7], 2)
    assert [len(g) for g in groups] == [3, 3]


# ------------------------------------------------- initialize contract

@pytest.fixture
def _fresh_multihost(monkeypatch):
    calls = []
    monkeypatch.setattr(multihost.jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(multihost, "_initialized", False)
    monkeypatch.setattr(multihost, "_init_args", None)
    return calls


def test_initialize_idempotent_same_args(_fresh_multihost):
    multihost.initialize("10.0.0.1:8476", 2, 0)
    multihost.initialize("10.0.0.1:8476", 2, 0)  # silent no-op
    assert len(_fresh_multihost) == 1


def test_initialize_different_args_raises(_fresh_multihost):
    multihost.initialize("10.0.0.1:8476", 2, 0)
    with pytest.raises(RuntimeError, match="different arguments"):
        multihost.initialize("10.0.0.2:8476", 2, 1)
    assert len(_fresh_multihost) == 1  # never re-wired


def test_initialize_emits_obs_event(_fresh_multihost):
    seen = []
    bus = obs_events.EventBus()
    bus.subscribe(seen.append)
    prev = obs_events.install(bus)
    try:
        multihost.initialize("10.0.0.1:8476", 2, 0)
    finally:
        obs_events.install(prev)
    inits = [e for e in seen if e["event"] == "multihost.init"]
    assert len(inits) == 1
    ev = inits[0]
    assert ev["processes"] >= 1 and ev["devices"] >= 1
    assert ev["localDevices"] >= 1 and ev["processIndex"] >= 0


# ------------------------------------------- heartbeat failure domains

def test_host_group_evicts_atomically():
    mgr = HeartbeatManager(timeout_ms=50)
    dead, dead_hosts = [], []
    mgr.on_death(dead.append)
    mgr.on_host_death(dead_hosts.append)
    mgr.register("w1", "127.0.0.1", 1001, host_id="hostA")
    mgr.register("w2", "127.0.0.1", 1002, host_id="hostA")
    mgr.register("w3", "127.0.0.1", 1003, host_id="hostB")
    # only w1 goes silent; w2 beat recently — but its host is gone
    mgr._last_seen["w1"] = time.monotonic() - 10.0
    assert sorted(mgr.dead_peers()) == ["w1", "w2"]
    assert dead_hosts == ["hostA"]
    assert sorted(dead) == ["w1", "w2"]
    live = [p["executor_id"] for p in mgr.live_peers()]
    assert live == ["w3"], "hostB must be untouched"


def test_no_host_id_keeps_independent_timeouts():
    mgr = HeartbeatManager(timeout_ms=50)
    mgr.register("w1", "127.0.0.1", 1001)
    mgr.register("w2", "127.0.0.1", 1002)
    mgr._last_seen["w1"] = time.monotonic() - 10.0
    assert mgr.dead_peers() == ["w1"]
    assert [p["executor_id"] for p in mgr.live_peers()] == ["w2"]


def test_reregister_after_host_eviction_gets_fresh_seq():
    mgr = HeartbeatManager(timeout_ms=50)
    _, seq1 = mgr.register("w1", "127.0.0.1", 1001, host_id="hostA")
    mgr.register("w2", "127.0.0.1", 1002, host_id="hostA")
    mgr._last_seen["w1"] = time.monotonic() - 10.0
    assert sorted(mgr.dead_peers()) == ["w1", "w2"]
    _, seq2 = mgr.register("w1", "127.0.0.1", 1001, host_id="hostA")
    assert seq2 > seq1
    assert mgr.dead_peers() == ["w2"]
    assert [p["executor_id"] for p in mgr.live_peers()] == ["w1"]


def test_condemn_host_evicts_group_without_timeout():
    """External death evidence (OS process sentinel) must not wait out
    a heartbeat timeout: condemn_host evicts the whole group NOW."""
    mgr = HeartbeatManager(timeout_ms=60_000)
    dead, dead_hosts = [], []
    mgr.on_death(dead.append)
    mgr.on_host_death(dead_hosts.append)
    mgr.register("w1", "127.0.0.1", 1001, host_id="hostA")
    mgr.register("w2", "127.0.0.1", 1002, host_id="hostA")
    mgr.register("w3", "127.0.0.1", 1003, host_id="hostB")
    mgr.condemn_host("hostA")
    assert sorted(mgr.dead_peers()) == ["w1", "w2"]
    assert dead_hosts == ["hostA"] and sorted(dead) == ["w1", "w2"]
    mgr.condemn_host("hostA")  # no live members left: no-op
    assert dead_hosts == ["hostA"]
    assert [p["executor_id"] for p in mgr.live_peers()] == ["w3"]


def test_evict_condemns_one_worker_not_its_host():
    mgr = HeartbeatManager(timeout_ms=60_000)
    mgr.register("w1", "127.0.0.1", 1001, host_id="hostA")
    mgr.register("w2", "127.0.0.1", 1002, host_id="hostA")
    mgr.evict("w1")  # observed TASK failure: not host evidence
    assert mgr.dead_peers() == ["w1"]
    assert [p["executor_id"] for p in mgr.live_peers()] == ["w2"]


# --------------------------------------------------- host fence ladder

def test_fence_host_one_epoch_step():
    ep0 = dm.chip_epoch()
    before = dm.counters()
    ep1 = dm.fence_host("simH", [6, 7], cause="test")
    after = dm.counters()
    assert ep1 == ep0 + 1, "whole host must fence in ONE epoch step"
    assert dm.fenced_chips() == {6, 7}
    assert dm.fenced_hosts() == ["simH"]
    assert after["fencedHosts"] == 1
    assert after["hostFences"] == before["hostFences"] + 1
    assert after["fences"] == before["fences"], \
        "host fence must not escalate to a process-wide fence"
    dm.unfence_host("simH")
    assert dm.fenced_chips() == set()
    assert dm.fenced_hosts() == []
    assert dm.chip_epoch() == ep1 + 1


def test_fence_host_idempotent():
    ep1 = dm.fence_host("simH", [7], cause="test")
    assert dm.fence_host("simH", [7], cause="dup") == ep1
    assert dm.counters()["fencedHosts"] == 1
    dm.unfence_host("simH")
    dm.unfence_host("simH")  # no-op
    assert dm.fenced_hosts() == []


# ----------------------------------------------------- the 2D mesh SQL

def _mk_table(n=4096, seed=11):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 40, n), pa.int64()),
        "v": pa.array(rng.random(n) * 10.0),
    })


def _agg(s, t):
    return (s.createDataFrame(t)
            .filter(F.col("v") > 1.0)
            .groupBy("k")
            .agg(F.sum("v").alias("s"), F.count("*").alias("c")))


def test_two_host_agg_oracle_and_dcn_below_ici():
    t = _mk_table()
    captured = {}

    def run(s):
        out = _agg(s, t).collect_arrow()
        captured.update(s.last_execution)
        return out

    got = with_tpu_session(run, MH)
    want = with_cpu_session(lambda s: _agg(s, t).collect_arrow(), {})
    assert_tables_equal(got, want, ignore_order=True)
    assert captured["engine"] == "mesh"
    tel = captured.get("telemetry") or {}
    moved = tel.get("bytesMoved") or {}
    assert moved.get("dcn", 0) > 0, f"no DCN bytes ledgered: {moved}"
    assert moved.get("ici", 0) > 0, moved
    assert moved["dcn"] < moved["ici"], \
        f"hierarchical placement must keep DCN below ICI: {moved}"
    assert tel.get("dcnBytes") == moved["dcn"]


def test_two_host_agg_low_reduction_recompiles_and_agrees():
    """The DCN slot is sized BETTING on per-host merge reduction (a
    1/n global-shard share). Near-distinct keys break that bet: the
    slot overflows and the query must converge through the doubled-
    expansion recompile ladder, still oracle-identical."""
    rng = np.random.default_rng(19)
    n = 4096
    t = pa.table({
        "k": pa.array(rng.permutation(n).astype(np.int64)),
        "v": pa.array(rng.random(n) * 10.0),
    })

    def q(s):
        return (s.createDataFrame(t).groupBy("k")
                .agg(F.sum("v").alias("s"), F.count("*").alias("c")))

    got = with_tpu_session(lambda s: q(s).collect_arrow(), MH)
    want = with_cpu_session(lambda s: q(s).collect_arrow(), {})
    assert_tables_equal(got, want, ignore_order=True)


def test_host_fatal_recovers_over_survivor():
    t = _mk_table(seed=13)
    conf = {**MH,
            "spark.rapids.tpu.chaos.enabled": True,
            "spark.rapids.tpu.chaos.seed": 5,
            "spark.rapids.tpu.chaos.sites": "host.fatal:once"}
    captured = {}

    def run(s):
        out = _agg(s, t).collect_arrow()
        # session init installs a FRESH DeviceMonitor (configure()), so
        # counters must be read inside THIS session — the CPU-oracle
        # session below would zero them
        captured["counters"] = dm.counters()
        captured["kinds"] = [e["event"] for e in s.obs.history.events()]
        return out

    got = with_tpu_session(run, conf)
    want = with_cpu_session(lambda s: _agg(s, t).collect_arrow(), {})
    assert_tables_equal(got, want, ignore_order=True)
    after = captured["counters"]
    assert after["hostFences"] == 1
    assert after["hostRecoveries"] == 1
    assert after["fences"] == 0, \
        "host loss must not escalate to a process-wide fence"
    assert "host.fence" in captured["kinds"]
    assert "host.recovery" in captured["kinds"]


def test_multihost_unsupported_sort_falls_back():
    t = _mk_table(seed=17)

    def q(s):
        return (s.createDataFrame(t)
                .orderBy("v")
                .limit(16))

    captured = {}

    def run(s):
        out = q(s).collect_arrow()
        captured.update(s.last_execution)
        return out

    got = with_tpu_session(run, MH)
    want = with_cpu_session(lambda s: q(s).collect_arrow(), {})
    assert_tables_equal(got, want, ignore_order=False)
    assert captured["engine"] != "mesh", \
        "global sort must fall back off the multi-host mesh"
