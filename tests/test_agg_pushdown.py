"""Partial-aggregation pushdown through fused lookup joins
(exec/agg_pushdown.py): the q5 star shape pre-aggregates the probe
side by the join key, joins ~|dim| buffer rows, and merges by the dim
attribute. Oracle is plain Python/pyarrow recomputation; the
duplicate-build-key case must fall back (lookup overflow retry) and
stay correct."""

import collections

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession


@pytest.fixture()
def spark():
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 4})
    yield s
    s.stop()


def _data(n=20000, stores=40, seed=2):
    rng = np.random.default_rng(seed)
    fact = pa.table({
        "store": pa.array(rng.integers(0, stores, n), pa.int64()),
        "amount": pa.array(rng.random(n) * 100),
        "qty": pa.array(rng.integers(1, 9, n), pa.int64()),
    })
    dim = pa.table({
        "store": pa.array(np.arange(stores), pa.int64()),
        "region": pa.array([f"R{i % 6}" for i in range(stores)]),
        "opened": pa.array(rng.integers(0, 100, stores), pa.int64()),
    })
    return fact, dim


def _oracle(fact, dim, amount_min, skip_region):
    reg = {int(s): r for s, r in zip(dim["store"].to_pylist(),
                                     dim["region"].to_pylist())}
    acc = collections.defaultdict(lambda: [0.0, 0.0, 0])
    for s, a, q in zip(fact["store"].to_pylist(),
                       fact["amount"].to_pylist(),
                       fact["qty"].to_pylist()):
        r = reg[int(s)]
        if a > amount_min and r != skip_region:
            acc[r][0] += a * q
            acc[r][1] += a
            acc[r][2] += 1
    return {r: (round(v[0], 4), round(v[1] / v[2], 6), v[2])
            for r, v in acc.items()}


def _q(spark, fact, dim):
    f = spark.createDataFrame(fact)
    d = spark.createDataFrame(dim)
    return (f.filter(F.col("amount") > 10.0)
            .join(d, on="store", how="inner")
            .filter(F.col("region") != "R3")
            .select("region",
                    (F.col("amount") * F.col("qty")).alias("rev"),
                    "amount")
            .groupBy("region")
            .agg(F.sum("rev").alias("s"), F.avg("amount").alias("a"),
                 F.count("*").alias("c")))


def _result(out):
    return {r: (round(s, 4), round(a, 6), c) for r, s, a, c in zip(
        out["region"].to_pylist(), out["s"].to_pylist(),
        out["a"].to_pylist(), out["c"].to_pylist())}


def test_pushdown_star_query_vs_oracle(spark):
    fact, dim = _data()
    out = _q(spark, fact, dim).collect_arrow()
    assert spark.last_execution["engine"] == "fused"
    assert _result(out) == _oracle(fact, dim, 10.0, "R3")


def test_pushdown_disabled_same_result():
    fact, dim = _data(seed=7)
    s = TpuSparkSession({
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.fusedExec.aggPushdownThroughJoin": False})
    try:
        out = _q(s, fact, dim).collect_arrow()
        assert _result(out) == _oracle(fact, dim, 10.0, "R3")
    finally:
        s.stop()


def test_pushdown_duplicate_build_keys_fall_back(spark):
    # duplicate dim keys break the unique-build bet; the overflow
    # retry must re-lower via the expanded join and stay correct
    fact = pa.table({"store": pa.array([0, 0, 1, 2], pa.int64()),
                     "v": pa.array([1.0, 2.0, 4.0, 8.0])})
    dim = pa.table({"store": pa.array([0, 0, 1], pa.int64()),
                    "region": pa.array(["A", "B", "C"])})
    out = (spark.createDataFrame(fact)
           .join(spark.createDataFrame(dim), on="store", how="inner")
           .groupBy("region").agg(F.sum("v").alias("s"))
           .collect_arrow())
    got = dict(zip(out["region"].to_pylist(), out["s"].to_pylist()))
    assert got == {"A": 3.0, "B": 3.0, "C": 4.0}, got


def test_pushdown_left_join_null_extension(spark):
    # probe rows without a dim match keep a NULL region group
    fact = pa.table({"store": pa.array([0, 1, 9, 9], pa.int64()),
                     "v": pa.array([1.0, 2.0, 4.0, 8.0])})
    dim = pa.table({"store": pa.array([0, 1], pa.int64()),
                    "region": pa.array(["A", "B"])})
    out = (spark.createDataFrame(fact)
           .join(spark.createDataFrame(dim), on="store", how="left")
           .groupBy("region").agg(F.sum("v").alias("s"))
           .collect_arrow())
    got = {r: v for r, v in zip(out["region"].to_pylist(),
                                out["s"].to_pylist())}
    assert got == {"A": 1.0, "B": 2.0, None: 12.0}, got


def test_pushdown_mixed_grouping_probe_and_build(spark):
    # grouping by BOTH a probe column and a build column
    fact = pa.table({"store": pa.array([0, 0, 1, 1, 0], pa.int64()),
                     "day": pa.array([1, 2, 1, 1, 1], pa.int64()),
                     "v": pa.array([1.0, 2.0, 4.0, 8.0, 16.0])})
    dim = pa.table({"store": pa.array([0, 1], pa.int64()),
                    "region": pa.array(["A", "B"])})
    out = (spark.createDataFrame(fact)
           .join(spark.createDataFrame(dim), on="store", how="inner")
           .groupBy("region", "day").agg(F.sum("v").alias("s"))
           .collect_arrow())
    got = {(r, d): v for r, d, v in zip(out["region"].to_pylist(),
                                        out["day"].to_pylist(),
                                        out["s"].to_pylist())}
    assert got == {("A", 1): 17.0, ("A", 2): 2.0, ("B", 1): 12.0}, got


def test_pushdown_min_max_buffers(spark):
    fact, dim = _data(n=5000, seed=4)
    f = spark.createDataFrame(fact)
    d = spark.createDataFrame(dim)
    out = (f.join(d, on="store", how="inner")
           .groupBy("region")
           .agg(F.min("amount").alias("lo"), F.max("amount").alias("hi"))
           .collect_arrow())
    reg = {int(s): r for s, r in zip(dim["store"].to_pylist(),
                                     dim["region"].to_pylist())}
    acc = {}
    for s, a in zip(fact["store"].to_pylist(),
                    fact["amount"].to_pylist()):
        r = reg[int(s)]
        lo, hi = acc.get(r, (float("inf"), float("-inf")))
        acc[r] = (min(lo, a), max(hi, a))
    got = {r: (round(lo, 6), round(hi, 6)) for r, lo, hi in zip(
        out["region"].to_pylist(), out["lo"].to_pylist(),
        out["hi"].to_pylist())}
    want = {r: (round(lo, 6), round(hi, 6)) for r, (lo, hi) in acc.items()}
    assert got == want


def test_duplicate_keys_at_max_expansion_config():
    # uniqueness loss must NOT ride the capacity-overflow retry: with
    # expansionFactor == maxExpansionFactor a dup-key broadcast join
    # still executes (lookup re-lowers via the blocking path at the
    # SAME factors instead of failing the retry loop)
    s = TpuSparkSession({
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.fusedExec.expansionFactor": 4,
        "spark.rapids.sql.fusedExec.maxExpansionFactor": 4})
    try:
        fact = pa.table({"store": pa.array([0, 1], pa.int64()),
                         "v": pa.array([1.0, 2.0])})
        dim = pa.table({"store": pa.array([0, 0, 1], pa.int64()),
                        "region": pa.array(["A", "B", "C"])})
        out = (s.createDataFrame(fact)
               .join(s.createDataFrame(dim), on="store", how="inner")
               .groupBy("region").agg(F.sum("v").alias("x"))
               .collect_arrow())
        got = dict(zip(out["region"].to_pylist(), out["x"].to_pylist()))
        assert got == {"A": 1.0, "B": 1.0, "C": 2.0}, got
        assert s.last_execution["engine"] == "fused"
    finally:
        s.stop()


def test_high_cardinality_probe_keys_fall_back():
    # more distinct probe join keys than groupCapacity: the pushdown
    # bet must re-lower WITHOUT blowing up the plan's own capacities
    s = TpuSparkSession({
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.fusedExec.groupCapacity": 256})
    try:
        n = 4096  # distinct keys >> groupCapacity
        fact = pa.table({"k": pa.array(np.arange(n), pa.int64()),
                         "v": pa.array(np.ones(n))})
        dim = pa.table({"k": pa.array(np.arange(n), pa.int64()),
                        "g": pa.array([f"g{i % 3}" for i in range(n)])})
        out = (s.createDataFrame(fact)
               .join(s.createDataFrame(dim), on="k", how="inner")
               .groupBy("g").agg(F.sum("v").alias("x"))
               .collect_arrow())
        got = dict(zip(out["g"].to_pylist(), out["x"].to_pylist()))
        want = {"g0": 1366.0, "g1": 1365.0, "g2": 1365.0}
        assert got == want, got
    finally:
        s.stop()


def test_ansi_disables_pushdown_join_visibility():
    # ANSI checks must see POST-join row visibility: the unmatched
    # probe row's overflowing expression must not raise (the inner
    # join drops it before the aggregate evaluates its inputs)
    from spark_rapids_tpu.sqltypes.datatypes import long as _long  # noqa

    big = 1 << 62
    s = TpuSparkSession({"spark.sql.shuffle.partitions": 4,
                         "spark.sql.ansi.enabled": True})
    try:
        fact = pa.table({"store": pa.array([1, 2, 99], pa.int64()),
                         "amount": pa.array([10, 20, big], pa.int64())})
        dim = pa.table({"store": pa.array([1, 2], pa.int64()),
                        "region": pa.array(["a", "b"])})
        out = (s.createDataFrame(fact)
               .join(s.createDataFrame(dim), on="store", how="inner")
               .groupBy("region")
               .agg(F.sum(F.col("amount") * F.col("amount")).alias("x"))
               .collect_arrow())
        got = dict(zip(out["region"].to_pylist(), out["x"].to_pylist()))
        assert got == {"a": 100, "b": 400}, got
    finally:
        s.stop()
