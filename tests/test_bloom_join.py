"""Build-side bloom runtime filters for hash joins (round-4 verdict
item #10; reference spark-rapids-jni BloomFilter via
GpuBloomFilterMightContain): probe rows whose keys are provably absent
from the build side drop BEFORE the hash probe, with correctness held
by differential tests."""

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    with_tpu_session,
)


def test_bloom_kernel_exact_and_probabilistic():
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.ops import bloom

    rng = np.random.default_rng(0)
    build_keys = rng.choice(100_000, size=500, replace=False)
    b = arrow_to_device(pa.table({"k": pa.array(build_keys,
                                                type=pa.int64())}))
    bits = bloom.build([b.columns[0]], b.live_mask(),
                       bloom.size_for(500))
    probe = arrow_to_device(pa.table({"k": pa.array(
        np.arange(100_000), type=pa.int64())}))
    hit = np.asarray(bloom.might_contain(bits, [probe.columns[0]]))[
        :100_000]
    # no false negatives
    assert hit[build_keys].all()
    # false positive rate ~1% at 10 bits/key
    fp = hit.sum() - 500
    assert fp < 100_000 * 0.05, fp


def _tables(spark, n_probe=60_000, n_build=600):
    rng = np.random.default_rng(7)
    probe = spark.createDataFrame(pa.table({
        "k": pa.array(rng.integers(0, 1_000_000, n_probe),
                      type=pa.int64()),
        "v": pa.array(rng.random(n_probe)),
    }))
    build = spark.createDataFrame(pa.table({
        "k": pa.array(rng.choice(1_000_000, size=n_build,
                                 replace=False), type=pa.int64()),
        "g": pa.array(rng.integers(0, 5, n_build), type=pa.int64()),
    }))
    return probe, build


def test_bloom_join_correct_and_reduces_probe():
    """Differential correctness + the filter actually removed rows
    (metric-backed row reduction on a selective join)."""
    from spark_rapids_tpu.runtime import metrics as M

    captured = {}

    def q(spark):
        probe, build = _tables(spark)
        df = probe.join(build, on="k", how="inner")
        phys, _ = df._physical()
        captured["phys"] = phys
        out = phys.collect()
        return out

    conf = {"spark.sql.autoBroadcastJoinThreshold": -1,
            "spark.rapids.sql.fusedExec.enabled": False,
            "spark.sql.shuffle.partitions": 2}
    got = with_tpu_session(q, conf)

    def find_join(n):
        from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec

        if isinstance(n, TpuShuffledHashJoinExec):
            return n
        for c in n.children:
            r = find_join(c)
            if r is not None:
                return r

    j = find_join(captured["phys"])
    assert j is not None
    filtered = j.metrics[M.BLOOM_FILTERED_ROWS].value
    assert filtered > 40_000, filtered  # most probe rows dropped early
    # correctness vs pyarrow
    import pyarrow.compute as pc

    rng = np.random.default_rng(7)
    probe_t = pa.table({
        "k": pa.array(rng.integers(0, 1_000_000, 60_000),
                      type=pa.int64()),
        "v": pa.array(rng.random(60_000))})
    build_t = pa.table({
        "k": pa.array(rng.choice(1_000_000, size=600, replace=False),
                      type=pa.int64()),
        "g": pa.array(rng.integers(0, 5, 600), type=pa.int64())})
    want = probe_t.join(build_t, keys="k", join_type="inner")
    assert got.num_rows == want.num_rows
    # raw plan output keeps both sides' key columns; compare (k, v)
    # multisets by index
    gk = sorted(zip(got.column(0).to_pylist(),
                    got.column(1).to_pylist()))
    wk = sorted(zip(want.column("k").to_pylist(),
                    want.column("v").to_pylist()))
    assert gk == wk


def test_bloom_join_with_nulls_differential():
    def q(spark):
        probe = spark.createDataFrame(pa.table({
            "k": pa.array([1, None, 3, 4, None, 6] * 2000,
                          type=pa.int64()),
            "v": pa.array(list(range(12000)), type=pa.int64())}))
        build = spark.createDataFrame(pa.table({
            "k": pa.array([3, 6], type=pa.int64()),
            "g": pa.array([30, 60], type=pa.int64())}))
        return probe.join(build, on="k", how="inner")

    assert_tpu_and_cpu_are_equal_collect(
        q, conf={"spark.sql.autoBroadcastJoinThreshold": -1,
                 "spark.rapids.sql.fusedExec.enabled": False,
                 "spark.sql.shuffle.partitions": 2})


def test_bloom_semi_join_differential():
    def q(spark):
        probe, build = _tables(spark, n_probe=20_000, n_build=300)
        return probe.join(build, on="k", how="left_semi")

    assert_tpu_and_cpu_are_equal_collect(
        q, conf={"spark.sql.autoBroadcastJoinThreshold": -1,
                 "spark.rapids.sql.fusedExec.enabled": False,
                 "spark.sql.shuffle.partitions": 2})


def test_bloom_disabled_conf():
    def q(spark):
        probe, build = _tables(spark, n_probe=20_000, n_build=300)
        return probe.join(build, on="k", how="inner")

    assert_tpu_and_cpu_are_equal_collect(
        q, conf={"spark.sql.autoBroadcastJoinThreshold": -1,
                 "spark.rapids.sql.join.bloomFilter.enabled": False,
                 "spark.rapids.sql.fusedExec.enabled": False,
                 "spark.sql.shuffle.partitions": 2})
