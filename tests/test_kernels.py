"""Relational kernel unit tests: filter, groupby, join, partition, sort."""

import numpy as np
import pyarrow as pa
import pytest

import jax.numpy as jnp

from spark_rapids_tpu.columnar import arrow_to_device, device_to_arrow
from spark_rapids_tpu.ops import filterops, joinops, partition, segmented
from spark_rapids_tpu.ops.common import orderable_keys, sort_permutation


def _table():
    return pa.table({
        "k": pa.array([3, 1, 2, 1, None, 3, 1], type=pa.int64()),
        "v": pa.array([10.0, 20.0, None, 40.0, 50.0, 60.0, 70.0],
                      type=pa.float64()),
        "s": pa.array(["c", "a", "b", "a", None, "c", "a"]),
    })


def test_filter_compact():
    b = arrow_to_device(_table())
    keep = b.columns[1].validity & (b.columns[1].data > 15.0)
    out = device_to_arrow(filterops.compact(b, keep))
    assert out.column("v").to_pylist() == [20.0, 40.0, 50.0, 60.0, 70.0]
    assert out.column("k").to_pylist() == [1, 1, None, 3, 1]


def test_slice_head():
    b = arrow_to_device(_table())
    out = device_to_arrow(filterops.slice_head(b, 3))
    assert out.num_rows == 3
    assert out.column("k").to_pylist() == [3, 1, 2]


def test_group_by_with_nulls():
    b = arrow_to_device(_table())
    g = segmented.group_by(b, [0])
    cap = b.capacity
    assert int(g.num_groups) == 4  # null, 1, 2, 3
    vcol = g.sorted_batch.columns[1]
    valid = vcol.validity & g.live
    cnt = np.asarray(segmented.seg_count(valid, g.gid, cap))[:4]
    sm = np.asarray(segmented.seg_sum(vcol.data, valid, g.gid, cap))[:4]
    # group order: null first, then 1, 2, 3
    assert list(cnt) == [1, 3, 0, 2]
    assert list(sm) == [50.0, 130.0, 0.0, 70.0]


def test_group_by_string_keys():
    b = arrow_to_device(_table())
    g = segmented.group_by(b, [2])
    assert int(g.num_groups) == 4  # null, a, b, c


def test_inner_join_gather_maps():
    b = arrow_to_device(_table())
    dim = arrow_to_device(pa.table({
        "k": pa.array([1, 2, 4], type=pa.int64()),
        "name": pa.array(["one", "two", "four"]),
    }))
    bt = joinops.build_side(dim, [0])
    lo, counts = joinops.probe_ranges(bt, b, [0])
    assert list(np.asarray(counts)[:7]) == [0, 1, 1, 1, 0, 0, 1]
    pi, bi, total = joinops.expand_gather_maps(lo, counts, 16)
    assert int(total) == 4
    probe_rows = list(np.asarray(pi)[:4])
    build_rows = list(np.asarray(bi)[:4])
    assert probe_rows == [1, 2, 3, 6]
    # dim sorted by key: row0=k1, row1=k2
    assert build_rows == [0, 1, 0, 0]


def test_join_duplicate_build_keys():
    probe = arrow_to_device(pa.table({"k": pa.array([1, 2], pa.int64())}))
    build = arrow_to_device(pa.table({
        "k": pa.array([1, 1, 1, 2], pa.int64())}))
    bt = joinops.build_side(build, [0])
    lo, counts = joinops.probe_ranges(bt, probe, [0])
    assert list(np.asarray(counts)[:2]) == [3, 1]
    pi, bi, total = joinops.expand_gather_maps(lo, counts, 8)
    assert int(total) == 4


def test_hash_partition_covers_all_rows():
    b = arrow_to_device(_table())
    pb = partition.hash_partition(b, [0], 4)
    assert int(np.asarray(pb.counts).sum()) == 7


def test_sort_floats_total_order():
    t = pa.table({"f": pa.array(
        [1.0, -0.0, 0.0, np.nan, -np.inf, np.inf, -2.5], pa.float64())})
    b = arrow_to_device(t)
    keys = orderable_keys(b.columns[0], True, True, b.live_mask())
    perm = sort_permutation(keys, b.capacity)
    out = b.gather(perm, b.num_rows)
    vals = np.asarray(out.columns[0].data)[:7]
    # -inf, -2.5, -0.0, 0.0, 1.0, inf, nan (Spark/Java double order)
    assert vals[0] == -np.inf and vals[5] == np.inf and np.isnan(vals[6])
    assert list(vals[1:5]) == [-2.5, -0.0, 0.0, 1.0]
    assert np.signbit(vals[2]) and not np.signbit(vals[3])


def test_sort_strings_desc_nulls_last():
    t = pa.table({"s": pa.array(["b", "abc", None, "ab", "z", ""])})
    b = arrow_to_device(t)
    keys = orderable_keys(b.columns[0], False, False, b.live_mask())
    perm = sort_permutation(keys, b.capacity)
    out = device_to_arrow(b.gather(perm, b.num_rows))
    assert out.column("s").to_pylist() == ["z", "b", "abc", "ab", "", None]
