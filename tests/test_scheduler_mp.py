"""Multiprocess stage-scheduler recovery: real OS worker processes
(parallel/process_pool.py), heartbeat-fed liveness, and the acceptance
scenario — a worker `kill -9`'d mid-stage no longer fails the query:
its in-flight partitions re-run on surviving workers
(scheduler.recomputedPartitions > 0), the worker stays excluded for
the session, and results match the single-process oracle exactly.

Unlike tests/test_multiprocess.py (the SPMD collective engine, where a
dead process deadlocks the mesh), this pool is task-parallel: lineage
descriptors (input split + plan fragment) make every partition
recomputable anywhere."""

import os
import signal
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.parallel.process_pool import (
    ProcessBackend,
    ProcessWorkerPool,
    run_scan_agg_fragment,
)
from spark_rapids_tpu.runtime import scheduler as sched
from spark_rapids_tpu.runtime.scheduler import StageScheduler, Task

N_FILES = 8
FRAGMENT = "spark_rapids_tpu.parallel.process_pool:run_scan_agg_fragment"


def _write_data(data_dir):
    rng = np.random.default_rng(7)
    os.makedirs(data_dir, exist_ok=True)
    files, parts = [], []
    for i in range(N_FILES):
        t = pa.table({
            "k": pa.array(rng.integers(0, 50, 600), type=pa.int64()),
            "v": pa.array(rng.random(600), type=pa.float64()),
        })
        p = os.path.join(data_dir, f"part-{i}.parquet")
        pq.write_table(t, p)
        files.append(p)
        parts.append(t)
    return files, pa.concat_tables(parts)


def _oracle(full):
    filt = full.filter(pc.greater(full.column("v"), 0.2))
    g = np.asarray(filt.column("k")) % 5
    filt = filt.append_column("g", pa.array(g, type=pa.int64()))
    return filt.group_by("g").aggregate([("v", "sum"), ("v", "count")])


def _spec(files, sleep_s=0.0):
    return {"files": files, "filter": ("v", "greater", 0.2),
            "derive_mod": ("g", "k", 5), "keys": ["g"],
            "aggs": [("v", "sum"), ("v", "count")], "sleep_s": sleep_s}


def _merge(partials):
    t = pa.concat_tables(partials)
    merged = t.group_by("g").aggregate(
        [("v_sum", "sum"), ("v_count", "sum")])
    return {g: (s, c) for g, s, c in zip(
        merged.column("g").to_pylist(),
        merged.column("v_sum_sum").to_pylist(),
        merged.column("v_count_sum").to_pylist())}


def _want(full):
    agg = _oracle(full)
    return {g: (s, c) for g, s, c in zip(
        agg.column("g").to_pylist(),
        agg.column("v_sum").to_pylist(),
        agg.column("v_count").to_pylist())}


def _assert_same(got, want):
    assert set(got) == set(want)
    for g, (s, c) in want.items():
        assert got[g][1] == c, (g, got[g], c)
        np.testing.assert_allclose(got[g][0], s, rtol=1e-9)


def test_fragment_runner_matches_oracle(tmp_path):
    files, full = _write_data(str(tmp_path / "d"))
    partials = [run_scan_agg_fragment(_spec([f])) for f in files]
    _assert_same(_merge(partials), _want(full))


def test_process_pool_stage_clean_run(tmp_path):
    files, full = _write_data(str(tmp_path / "d"))
    pool = ProcessWorkerPool(2, hb_interval_ms=100, hb_timeout_ms=1500)
    try:
        tasks = [Task(i, payload=(FRAGMENT, _spec([f])))
                 for i, f in enumerate(files)]
        out = StageScheduler(None, name="mp-clean",
                             backend=ProcessBackend(pool)).run(tasks)
        _assert_same(_merge(out), _want(full))
        assert len(pool.live_workers()) == 2
    finally:
        pool.close()


def test_query_survives_worker_kill9_mid_stage(tmp_path):
    """The acceptance scenario: SIGKILL one of three workers while the
    stage is in flight. The scheduler evicts it (heartbeat expiry +
    process sentinel), re-dispatches its partitions, and the merged
    result is oracle-identical with recomputedPartitions > 0."""
    files, full = _write_data(str(tmp_path / "d"))
    pool = ProcessWorkerPool(3, hb_interval_ms=100, hb_timeout_ms=1200)
    before = sched.stats.snapshot()
    try:
        # every task sleeps so the victim is guaranteed to hold
        # in-flight partitions when the kill lands
        tasks = [Task(i, payload=(FRAGMENT, _spec([f], sleep_s=0.4)))
                 for i, f in enumerate(files)]
        victim = "worker-0"
        pid = pool.worker_pid(victim)

        def killer():
            time.sleep(0.6)
            os.kill(pid, signal.SIGKILL)

        threading.Thread(target=killer, daemon=True).start()
        out = StageScheduler(None, name="mp-kill",
                             backend=ProcessBackend(pool)).run(tasks)
        _assert_same(_merge(out), _want(full))
        d = sched.stats.delta(before, sched.stats.snapshot())
        assert d["recomputedPartitions"] >= 1, d
        assert d["evictedWorkers"] == 1, d
        assert d["tasksRetried"] >= 1, d
        # excluded for the session — later stages avoid the dead worker
        assert victim in pool.evicted_workers()
        assert victim not in pool.live_workers()
        tasks2 = [Task(i, payload=(FRAGMENT, _spec([f])))
                  for i, f in enumerate(files)]
        out2 = StageScheduler(None, name="mp-after",
                              backend=ProcessBackend(pool)).run(tasks2)
        _assert_same(_merge(out2), _want(full))
    finally:
        pool.close()


def test_all_workers_dead_is_clean_worker_lost(tmp_path):
    from spark_rapids_tpu.runtime.errors import WorkerLost

    files, _full = _write_data(str(tmp_path / "d"))
    pool = ProcessWorkerPool(1, hb_interval_ms=100, hb_timeout_ms=1000)
    try:
        tasks = [Task(i, payload=(FRAGMENT, _spec([f], sleep_s=0.5)))
                 for i, f in enumerate(files[:3])]
        pid = pool.worker_pid("worker-0")

        def killer():
            time.sleep(0.3)
            os.kill(pid, signal.SIGKILL)

        threading.Thread(target=killer, daemon=True).start()
        with pytest.raises(WorkerLost):
            StageScheduler(None, name="mp-dead",
                           backend=ProcessBackend(pool)).run(tasks)
    finally:
        pool.close()


def test_worker_error_propagates_not_retried(tmp_path):
    pool = ProcessWorkerPool(2, heartbeat=False)
    try:
        bad = {"files": [str(tmp_path / "missing.parquet")],
               "keys": ["g"], "aggs": [("v", "sum")]}
        with pytest.raises(RuntimeError, match="missing.parquet"):
            StageScheduler(None, name="mp-err",
                           backend=ProcessBackend(pool)).run(
                [Task(0, payload=(FRAGMENT, bad))])
    finally:
        pool.close()
