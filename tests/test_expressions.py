"""Expression library: Spark null semantics, arithmetic, strings, dates.

The CPU oracle for these unit tests is hand-computed Spark behavior
(cross-checked against Spark 3.5 semantics documented in the reference's
compatibility notes).
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import arrow_to_device, device_to_arrow
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.expr import (
    Add, And, Average, Cast, CaseWhen, Coalesce, Concat, Contains, Count,
    Divide, EndsWith, EqualNullSafe, EqualTo, First, GreaterThan, If, In,
    IntegralDivide, IsNaN, IsNotNull, IsNull, Length, LessThan, Literal,
    Lower, Max, Min, Multiply, Murmur3Hash, Not, Or, Pmod, Remainder,
    StartsWith, Substring, Subtract, Sum, Upper, Year, Month, DayOfMonth,
    BoundReference, EvalContext,
)
from spark_rapids_tpu.sqltypes.datatypes import (
    DecimalType, boolean, date, double, integer, long, string,
)


def _eval(table: pa.Table, expr, out_name="r"):
    b = arrow_to_device(table)
    ctx = EvalContext(b)
    col = expr.eval(ctx)
    from spark_rapids_tpu.sqltypes import StructType, StructField

    out = ColumnBatch(StructType([StructField(out_name, col.dtype,
                                              True)]), [col], b.num_rows)
    return device_to_arrow(out).column(out_name).to_pylist()


def ref(i, dt=long, nullable=True):
    return BoundReference(i, dt, nullable)


def test_add_null_propagation():
    t = pa.table({"a": pa.array([1, None, 3], pa.int64()),
                  "b": pa.array([10, 20, None], pa.int64())})
    assert _eval(t, Add(ref(0), ref(1))) == [11, None, None]


def test_divide_returns_double_and_null_on_zero():
    t = pa.table({"a": pa.array([10, 7, 5], pa.int64()),
                  "b": pa.array([4, 0, None], pa.int64())})
    assert _eval(t, Divide(ref(0), ref(1))) == [2.5, None, None]


def test_integral_divide_truncates_toward_zero():
    t = pa.table({"a": pa.array([-7, 7, -7], pa.int64()),
                  "b": pa.array([2, 2, 0], pa.int64())})
    assert _eval(t, IntegralDivide(ref(0), ref(1))) == [-3, 3, None]


def test_remainder_sign_follows_dividend():
    t = pa.table({"a": pa.array([-7, 7, 5], pa.int64()),
                  "b": pa.array([3, -3, 0], pa.int64())})
    assert _eval(t, Remainder(ref(0), ref(1))) == [-1, 1, None]


def test_pmod_positive():
    t = pa.table({"a": pa.array([-7, 7], pa.int64()),
                  "b": pa.array([3, 3], pa.int64())})
    assert _eval(t, Pmod(ref(0), ref(1))) == [2, 1]


def test_decimal_add_and_multiply():
    import decimal

    t = pa.table({
        "a": pa.array([decimal.Decimal("1.25"), decimal.Decimal("-0.75")],
                      pa.decimal128(10, 2)),
        "b": pa.array([decimal.Decimal("0.50"), decimal.Decimal("2.00")],
                      pa.decimal128(10, 2)),
    })
    dt = DecimalType(10, 2)
    got = _eval(t, Add(ref(0, dt), ref(1, dt)))
    assert [str(v) for v in got] == ["1.75", "1.25"]
    got = _eval(t, Multiply(ref(0, dt), ref(1, dt)))
    assert [str(v) for v in got] == ["0.6250", "-1.5000"]


def test_kleene_and_or():
    t = pa.table({"a": pa.array([True, True, False, None], pa.bool_()),
                  "b": pa.array([None, False, None, None], pa.bool_())})
    a, b = ref(0, boolean), ref(1, boolean)
    assert _eval(t, And(a, b)) == [None, False, False, None]
    assert _eval(t, Or(a, b)) == [True, True, None, None]


def test_comparisons_and_null_safe_eq():
    t = pa.table({"a": pa.array([1, None, 3, None], pa.int64()),
                  "b": pa.array([1, 2, None, None], pa.int64())})
    assert _eval(t, EqualTo(ref(0), ref(1))) == [True, None, None, None]
    assert _eval(t, EqualNullSafe(ref(0), ref(1))) == [
        True, False, False, True]
    assert _eval(t, LessThan(ref(0), ref(1))) == [False, None, None, None]


def test_string_comparison_lexicographic():
    t = pa.table({"a": pa.array(["apple", "b", "abc"]),
                  "b": pa.array(["apricot", "a", "abc"])})
    a, b = ref(0, string), ref(1, string)
    assert _eval(t, LessThan(a, b)) == [True, False, False]
    assert _eval(t, EqualTo(a, b)) == [False, False, True]


def test_float_nan_semantics():
    t = pa.table({"a": pa.array([np.nan, 1.0, np.nan], pa.float64()),
                  "b": pa.array([np.nan, np.nan, 1.0], pa.float64())})
    a, b = ref(0, double), ref(1, double)
    # Spark: NaN == NaN is true; NaN is greatest for ordering.
    assert _eval(t, EqualTo(a, b)) == [True, False, False]
    assert _eval(t, LessThan(a, b)) == [False, True, False]
    assert _eval(t, GreaterThan(a, b)) == [False, False, True]
    assert _eval(t, IsNaN(a)) == [True, False, True]


def test_conditional_if_case_coalesce():
    t = pa.table({"a": pa.array([1, 5, None], pa.int64())})
    a = ref(0)
    e = If(GreaterThan(a, Literal(3, long)), Literal(100, long),
           Literal(-100, long))
    assert _eval(t, e) == [-100, 100, -100]  # null pred -> else
    e = CaseWhen([(EqualTo(a, Literal(1, long)), Literal(10, long)),
                  (EqualTo(a, Literal(5, long)), Literal(50, long))])
    assert _eval(t, e) == [10, 50, None]
    assert _eval(t, Coalesce(a, Literal(0, long))) == [1, 5, 0]


def test_in_expression():
    t = pa.table({"a": pa.array([1, 2, 3, None], pa.int64())})
    assert _eval(t, In(ref(0), [1, 3])) == [True, False, True, None]
    assert _eval(t, In(ref(0), [1, None])) == [True, None, None, None]


def test_is_null_not():
    t = pa.table({"a": pa.array([1, None], pa.int64())})
    assert _eval(t, IsNull(ref(0))) == [False, True]
    assert _eval(t, IsNotNull(ref(0))) == [True, False]
    t2 = pa.table({"a": pa.array([True, None], pa.bool_())})
    assert _eval(t2, Not(ref(0, boolean))) == [False, None]


def test_cast_numeric():
    t = pa.table({"a": pa.array([1.9, -1.9, np.nan, 1e20], pa.float64())})
    assert _eval(t, Cast(ref(0, double), long)) == [
        1, -1, 0, 2**63 - 1]  # trunc toward zero, NaN->0, saturate
    t2 = pa.table({"a": pa.array([300], pa.int64())})
    from spark_rapids_tpu.sqltypes.datatypes import byte
    assert _eval(t2, Cast(ref(0), byte)) == [44]  # wraps like Java


def test_cast_int_to_string():
    t = pa.table({"a": pa.array([0, 7, -42, 1234567890123, None,
                                 -(2**63)], pa.int64())})
    assert _eval(t, Cast(ref(0), string)) == [
        "0", "7", "-42", "1234567890123", None, str(-(2**63))]


def test_cast_date_to_string_and_parts():
    t = pa.table({"d": pa.array([0, 19723, -1], pa.date32())})
    assert _eval(t, Cast(ref(0, date), string)) == [
        "1970-01-01", "2024-01-01", "1969-12-31"]
    assert _eval(t, Year(ref(0, date))) == [1970, 2024, 1969]
    assert _eval(t, Month(ref(0, date))) == [1, 1, 12]
    assert _eval(t, DayOfMonth(ref(0, date))) == [1, 1, 31]


def test_cast_bool_decimal_string():
    t = pa.table({"b": pa.array([True, False, None])})
    assert _eval(t, Cast(ref(0, boolean), string)) == ["true", "false", None]
    import decimal
    t2 = pa.table({"d": pa.array([decimal.Decimal("12.34"),
                                  decimal.Decimal("-0.05")],
                                 pa.decimal128(9, 2))})
    assert _eval(t2, Cast(ref(0, DecimalType(9, 2)), string)) == [
        "12.34", "-0.05"]


def test_string_functions():
    t = pa.table({"s": pa.array(["Hello", "wORLD", None, "héllo"])})
    s = ref(0, string)
    assert _eval(t, Upper(s)) == ["HELLO", "WORLD", None, "HéLLO"]
    assert _eval(t, Lower(s)) == ["hello", "world", None, "héllo"]
    assert _eval(t, Length(s)) == [5, 5, None, 5]  # chars, not bytes


def test_substring_utf8():
    t = pa.table({"s": pa.array(["hello", "héllo", "ab"])})
    s = ref(0, string)
    assert _eval(t, Substring(s, 2, 3)) == ["ell", "éll", "b"]
    assert _eval(t, Substring(s, -2, 2)) == ["lo", "lo", "ab"]


def test_concat():
    t = pa.table({"a": pa.array(["ab", None, "x"]),
                  "b": pa.array(["cd", "ef", ""])})
    assert _eval(t, Concat(ref(0, string), ref(1, string))) == [
        "abcd", None, "x"]


def test_starts_ends_contains():
    t = pa.table({"s": pa.array(["spark", "park", "spar", None])})
    s = ref(0, string)
    assert _eval(t, StartsWith(s, "sp")) == [True, False, True, None]
    assert _eval(t, EndsWith(s, "ark")) == [True, True, False, None]
    assert _eval(t, Contains(s, "par")) == [True, True, True, None]


def test_murmur3_expression():
    t = pa.table({"a": pa.array([1], pa.int64())})
    assert _eval(t, Murmur3Hash(ref(0))) == [-1712319331]


def test_mixed_type_comparison_coercion():
    """Regression: comparing a double column with an INT literal keyed
    a raw integer against the float total-order transform, passing
    every row (predicates._coerce_numeric)."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_and_cpu_are_equal_collect,
        with_tpu_session,
    )

    rng = np.random.default_rng(3)
    t = pa.table({
        "d": pa.array(rng.random(1000) * 100),
        "f": pa.array((rng.random(1000) * 10).astype("float32")),
        "i": pa.array(rng.integers(0, 100, 1000).astype("int32")),
    })

    def q(spark):
        df = spark.createDataFrame(t)
        return df.select(
            (F.col("d") > 5).alias("a"),        # double vs int lit
            (F.col("i") > F.lit(4.5)).alias("b"),  # int vs double lit
            (F.col("f") <= 3).alias("c"),       # float vs int lit
            (F.col("d") == F.col("i")).alias("e"),
            (F.col("f") < F.col("d")).alias("g"),  # float vs double
        )

    assert_tpu_and_cpu_are_equal_collect(q)
    out = with_tpu_session(lambda s: q(s).collect_arrow())
    want = (np.asarray(t.column("d")) > 5)
    assert (np.asarray(out.column("a")) == want).all()


def test_decimal_int_comparison_coercion():
    import decimal

    import pyarrow as pa

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing.asserts import (
        assert_tpu_and_cpu_are_equal_collect,
    )

    t = pa.table({"p": pa.array([decimal.Decimal("4.99"),
                                 decimal.Decimal("5.00"),
                                 decimal.Decimal("5.01")],
                                type=pa.decimal128(10, 2))})
    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.createDataFrame(t).select(
            (F.col("p") > 5).alias("gt"),
            (F.col("p") >= F.lit(5)).alias("ge"),
            (F.col("p") < F.lit(5.005)).alias("ltf")))
