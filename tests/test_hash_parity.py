"""Spark Murmur3 parity: device kernels vs a pure-Python reference
implementation of org.apache.spark.unsafe.hash.Murmur3_x86_32.

The reference gets this parity from the JNI `Hash` kernel
(spark-rapids-jni); hash partitioning must agree with CPU Spark.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.ops import hashing
from spark_rapids_tpu.sqltypes.datatypes import (
    boolean, double, float_t, integer, long, string,
)

M = (1 << 32) - 1


def _rotl(x, r):
    return ((x << r) | (x >> (32 - r))) & M


def _toi32(x):
    x &= M
    return x - (1 << 32) if x >= 1 << 31 else x


def _mix_k1(k1):
    k1 = (k1 * 0xCC9E2D51) & M
    k1 = _rotl(k1, 15)
    return (k1 * 0x1B873593) & M


def _mix_h1(h1, k1):
    h1 ^= k1
    h1 = _rotl(h1, 13)
    return (h1 * 5 + 0xE6546B64) & M


def _fmix(h1, n):
    h1 ^= n
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & M
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & M
    h1 ^= h1 >> 16
    return h1


def ref_hash_int(v, seed=42):
    return _toi32(_fmix(_mix_h1(seed & M, _mix_k1(v & M)), 4))


def ref_hash_long(v, seed=42):
    v &= (1 << 64) - 1
    h1 = _mix_h1(seed & M, _mix_k1(v & M))
    h1 = _mix_h1(h1, _mix_k1((v >> 32) & M))
    return _toi32(_fmix(h1, 8))


def ref_hash_bytes(b, seed=42):
    h1 = seed & M
    aligned = (len(b) // 4) * 4
    for i in range(0, aligned, 4):
        w = b[i] | (b[i + 1] << 8) | (b[i + 2] << 16) | (b[i + 3] << 24)
        h1 = _mix_h1(h1, _mix_k1(w))
    for i in range(aligned, len(b)):
        x = b[i] - 256 if b[i] >= 128 else b[i]
        h1 = _mix_h1(h1, _mix_k1(x & M))
    return _toi32(_fmix(h1, len(b)))


def _device_hash(dtype, np_vals, lengths=None):
    n = len(np_vals)
    if lengths is not None:
        col = DeviceColumn(dtype, jnp.asarray(np_vals),
                           jnp.ones(n, bool), jnp.asarray(lengths))
    else:
        col = DeviceColumn(dtype, jnp.asarray(np_vals), jnp.ones(n, bool))
    return list(np.asarray(hashing.hash_column(
        col, jnp.full(n, jnp.int32(42)))))


def test_hash_int32():
    vals = np.array([0, 1, -1, 42, 2**31 - 1, -(2**31), 12345], np.int32)
    assert _device_hash(integer, vals) == [ref_hash_int(int(v)) for v in vals]


def test_hash_int64():
    vals = np.array([0, 1, -1, 2**63 - 1, -(2**63), 987654321012], np.int64)
    assert _device_hash(long, vals) == [ref_hash_long(int(v)) for v in vals]


def test_hash_known_spark_vectors():
    # Cross-checked against org.apache.spark.sql.functions.hash on Spark 3.5.
    assert ref_hash_int(1) == -559580957
    assert ref_hash_long(1) == -1712319331
    assert _device_hash(integer, np.array([1], np.int32)) == [-559580957]
    assert _device_hash(long, np.array([1], np.int64)) == [-1712319331]


def test_hash_double():
    import struct

    vals = np.array([0.0, -0.0, 1.5, -3.25, np.nan, np.inf], np.float64)

    def bits(d):
        if d != d:
            return 0x7FF8000000000000
        if d == 0.0:
            d = 0.0
        return struct.unpack("<q", struct.pack("<d", d))[0]

    assert _device_hash(double, vals) == [
        ref_hash_long(bits(float(v))) for v in vals
    ]


def test_hash_float():
    import struct

    vals = np.array([0.0, -0.0, 2.5, np.nan], np.float32)

    def bits(f):
        if f != f:
            return 0x7FC00000
        if f == 0.0:
            f = 0.0
        return struct.unpack("<i", struct.pack("<f", np.float32(f)))[0]

    assert _device_hash(float_t, vals) == [
        ref_hash_int(bits(float(v))) for v in vals
    ]


@pytest.mark.parametrize("mb", [8, 16, 32])
def test_hash_string(mb):
    strs = [b"", b"a", b"ab", b"abc", b"abcd", b"hello world",
            b"\xc3\xa9tat", b"abcdefg"]
    strs = [s for s in strs if len(s) <= mb]
    mat = np.zeros((len(strs), mb), np.uint8)
    lens = np.zeros(len(strs), np.int32)
    for i, s in enumerate(strs):
        mat[i, :len(s)] = list(s)
        lens[i] = len(s)
    assert _device_hash(string, mat, lens) == [
        ref_hash_bytes(list(s)) for s in strs
    ]


def test_hash_null_chaining():
    # Null column leaves running hash unchanged (Spark HashExpression).
    a = DeviceColumn(integer, jnp.asarray(np.array([1, 1], np.int32)),
                     jnp.asarray(np.array([True, True])))
    b = DeviceColumn(integer, jnp.asarray(np.array([7, 0], np.int32)),
                     jnp.asarray(np.array([False, False])))
    h = np.asarray(hashing.murmur3_columns([a, b]))
    expect = ref_hash_int(1, 42)
    assert list(h) == [expect, expect]


def test_pmod_non_negative():
    x = jnp.asarray(np.array([-5, -1, 0, 3, 7], np.int32))
    r = np.asarray(hashing.pmod(x, 4))
    assert (r >= 0).all() and list(r) == [3, 3, 0, 3, 3]
