"""Broadcast-exchange reuse (plan/broadcast_reuse.py): joins against
the same dimension subtree share one build node and one materialized
device build (reference GpuBroadcastExchangeExec reuse /
ReusedExchangeExec, SURVEY.md §2.5 Broadcast)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.exec import joins as J

_CONF = {"spark.sql.shuffle.partitions": 2,
         "spark.sql.autoBroadcastJoinThreshold": 10 << 20,
         "spark.rapids.sql.fusedExec.enabled": False}


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


def _find_bcast_joins(phys):
    out = []

    def walk(n):
        if isinstance(n, (J.TpuBroadcastHashJoinExec,
                          J.TpuBroadcastNestedLoopJoinExec)):
            out.append(n)
        for c in n.children:
            walk(c)

    walk(phys)
    return out


def test_same_dim_scan_builds_once(tmp_path, spark):
    """Two plan branches join the IDENTICAL dim subtree (the classic
    union-of-joins shape): one shared build node, one materialization."""
    rng = np.random.default_rng(2)
    dim = pa.table({"k": pa.array(np.arange(30), type=pa.int64()),
                    "w": pa.array(np.arange(30) * 1.0)})
    pq.write_table(dim, str(tmp_path / "dim.parquet"))
    n = 4000
    ks = rng.integers(0, 40, n)   # some keys miss the dim
    k2s = rng.integers(0, 40, n)
    fact_a = spark.createDataFrame(pa.table({
        "k": pa.array(ks, type=pa.int64())}))
    fact_b = spark.createDataFrame(pa.table({
        "k": pa.array(k2s, type=pa.int64())}))

    d1 = spark.read.parquet(str(tmp_path / "dim.parquet"))
    d2 = spark.read.parquet(str(tmp_path / "dim.parquet"))
    df = (fact_a.join(d1, on="k", how="inner")
          .union(fact_b.join(d2, on="k", how="inner"))
          .groupBy().agg(F.count("*").alias("c")))

    phys, _ = df._physical()
    joins = _find_bcast_joins(phys)
    assert len(joins) == 2, [type(j).__name__ for j in joins]
    assert joins[0].children[1] is joins[1].children[1], \
        "identical dim subtrees did not dedup"

    # count build-side executions; a shared (deduped) child is counted
    # once however many joins consume it
    calls = {"n": 0}
    seen = set()
    for j in joins:
        child = j.children[1]
        if id(child) in seen:
            continue
        seen.add(id(child))
        orig = child.execute_partition

        def counted(pid, ctx, _orig=orig):
            calls["n"] += 1
            return _orig(pid, ctx)

        child.execute_partition = counted

    got = phys.collect()

    want = int((ks < 30).sum()) + int((k2s < 30).sum())
    assert got.column("c")[0].as_py() == want
    assert calls["n"] == 1, (
        f"dim build executed {calls['n']} times; reuse failed")


def test_renamed_projection_still_dedups_or_not_wrong(tmp_path, spark):
    """d2 projects/renames on top of the same scan — whether or not the
    differing projections dedup, results must be correct. (The pass
    dedups the BUILD SUBTREES, which here differ by the rename
    projection, so they stay separate.)"""
    rng = np.random.default_rng(3)
    fact = spark.createDataFrame(pa.table({
        "k": pa.array(rng.integers(0, 20, 1000), type=pa.int64()),
        "v": pa.array(rng.random(1000))}))
    d1 = spark.createDataFrame(pa.table({
        "k": pa.array(np.arange(20), type=pa.int64()),
        "a": pa.array(np.arange(20) * 1.0)}))
    d2 = spark.createDataFrame(pa.table({
        "k": pa.array(np.arange(10), type=pa.int64()),
        "b": pa.array(np.arange(10) * 2.0)}))
    df = (fact.join(d1, on="k").join(d2, on="k")
          .groupBy().agg(F.count("*").alias("c")))
    phys, _ = df._physical()
    joins = _find_bcast_joins(phys)
    if len(joins) == 2:
        # different local tables must never collapse to one build
        assert joins[0].children[1] is not joins[1].children[1]
    got = df.collect_arrow()
    kf = np.asarray(fact.collect_arrow().column("k"))
    assert got.column("c")[0].as_py() == int((kf < 10).sum())
