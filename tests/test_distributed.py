"""Distributed execution over a virtual 8-device mesh: the ICI
all-to-all shuffle + fused distributed aggregation (the accelerated
shuffle transport test tier; reference tests the UCX client/server with
mocks — here the collective path runs for real on the host mesh).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_rapids_tpu.columnar import arrow_to_device, device_to_arrow
from spark_rapids_tpu.columnar.batch import ColumnBatch, DeviceColumn
from spark_rapids_tpu.expr import Alias, BoundReference, Count, Sum
from spark_rapids_tpu.parallel import mesh_exec
from spark_rapids_tpu.parallel.collective import (
    all_to_all_batch,
    slot_capacity,
)
from spark_rapids_tpu.sqltypes.datatypes import double, long

N = 8


def _mesh():
    if len(jax.devices()) < N:
        pytest.skip(f"need {N} devices")
    return mesh_exec.make_mesh(N)


def test_all_to_all_routes_rows_to_keyed_device():
    from spark_rapids_tpu.shims import get_shim

    shard_map = get_shim().shard_map

    mesh = _mesh()
    cap = 1024
    t = pa.table({"k": pa.array(np.arange(cap) % N, type=pa.int64()),
                  "v": pa.array(np.arange(cap, dtype=np.float64))})
    batch = arrow_to_device(t)
    sharded = mesh_exec.shard_batch(mesh, batch)
    slot = slot_capacity(cap // N, N)

    def step(local):
        pid = (local.columns[0].data % N).astype(jnp.int32)
        out, _overflow = all_to_all_batch(local, pid, N, slot,
                                          mesh_exec.AXIS)
        return ColumnBatch(out.schema, out.columns,
                           jnp.asarray(out.num_rows, jnp.int32).reshape(1))

    # out leaves have per-shard shape [N*slot]; build the spec stub
    stub_cols = [
        DeviceColumn(f.dataType,
                     jax.ShapeDtypeStruct((N * slot,), c.data.dtype),
                     jax.ShapeDtypeStruct((N * slot,), jnp.bool_), None)
        for f, c in zip(batch.schema.fields, batch.columns)]
    stub = ColumnBatch(batch.schema, stub_cols,
                       jax.ShapeDtypeStruct((1,), jnp.int32))
    out_specs = mesh_exec.batch_specs(stub, P(mesh_exec.AXIS))
    in_specs = mesh_exec.input_batch_specs(batch, P(mesh_exec.AXIS))
    fn = shard_map(step, mesh, (in_specs,), out_specs)
    out = jax.jit(fn)(sharded)
    table = device_to_arrow(mesh_exec.gather_result(out, N))
    ks = table.column("k").to_pylist()
    vs = table.column("v").to_pylist()
    assert sorted(vs) == [float(i) for i in range(cap)]  # nothing lost
    # each device's contiguous block holds exactly one key (k == device)
    changes = sum(1 for a, b in zip(ks, ks[1:]) if a != b)
    assert changes == N - 1, f"expected {N} contiguous key blocks: {ks[:20]}"


def test_distributed_groupby_agg_matches_pandas():
    mesh = _mesh()
    cap = 2048
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 37, cap)
    vals = rng.random(cap) * 100
    t = pa.table({"k": pa.array(keys, type=pa.int64()),
                  "v": pa.array(vals, type=pa.float64())})
    batch = arrow_to_device(t)

    exp = (pd.DataFrame({"k": keys, "v": vals}).groupby("k")["v"]
           .agg(["sum", "count"]))

    from spark_rapids_tpu.exec.operators import TpuHashAggregateExec

    grouping = [Alias(BoundReference(0, long, True), "k")]
    aggs = [Alias(Sum(BoundReference(1, double, True)), "s"),
            Alias(Count(None), "n")]
    agg_op = TpuHashAggregateExec("complete", grouping, aggs, None, None)

    slot = slot_capacity(cap // N, N)
    step = mesh_exec.make_distributed_agg(
        mesh, batch, agg_op._partial, agg_op._merge_final,
        key_ordinals=[0], slot=slot)
    sharded = mesh_exec.shard_batch(mesh, batch)
    out = step(sharded)
    host = device_to_arrow(mesh_exec.gather_result(out, N))
    got = host.to_pandas().set_index("k")
    assert set(got.index) == set(exp.index)
    for k in exp.index:
        assert abs(got.loc[k, "s"] - exp.loc[k, "sum"]) < 1e-6
        assert got.loc[k, "n"] == exp.loc[k, "count"]


def test_distributed_agg_overflow_raises():
    """Slot overflow must surface as TpuSplitAndRetryOOM, not silent
    row loss (the split-retry discipline crossing the collective)."""
    from spark_rapids_tpu.exec.operators import TpuHashAggregateExec
    from spark_rapids_tpu.runtime.errors import TpuSplitAndRetryOOM

    mesh = _mesh()
    cap = 2048
    rng = np.random.default_rng(5)
    # high-cardinality keys: each shard emits ~256 distinct groups, far
    # exceeding a deliberately tiny slot
    keys = rng.integers(0, 100_000, cap)
    t = pa.table({"k": pa.array(keys, type=pa.int64()),
                  "v": pa.array(rng.random(cap), type=pa.float64())})
    batch = arrow_to_device(t)
    grouping = [Alias(BoundReference(0, long, True), "k")]
    aggs = [Alias(Count(None), "n")]
    agg_op = TpuHashAggregateExec("complete", grouping, aggs, None, None)
    step = mesh_exec.make_distributed_agg(
        mesh, batch, agg_op._partial, agg_op._merge_final,
        key_ordinals=[0], slot=4)
    sharded = mesh_exec.shard_batch(mesh, batch)
    with pytest.raises(TpuSplitAndRetryOOM):
        step(sharded)
