"""Exactly-once output: transactional task/job commit protocol
(io/commit.py), crash-safe overwrite, and optimistic lakehouse
concurrency (lakehouse/delta.py / iceberg.py).

The reference proves its writer with HadoopMapReduceCommitProtocol
semantics tests; this suite does the same for the engine's analog:
six-format round-trips through the staged path, the deferred overwrite
swap surviving an injected job-commit failure byte-identical, a
`kill -9`'d process worker's re-attempt landing oracle-identical
output, the orphan sweep never touching a live job, and two concurrent
Delta appenders both committing under the optimistic-transaction loop.
"""

import glob
import json
import os
import signal
import threading
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F  # noqa: F401
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.io import commit as iocommit
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime.errors import RetryExhausted

_CONF = {
    "spark.rapids.tpu.io.retry.backoffMs": 1,
    "spark.rapids.tpu.io.retry.maxBackoffMs": 4,
}


@pytest.fixture()
def spark():
    s = TpuSparkSession(dict(_CONF))
    yield s
    s.stop()


@pytest.fixture(autouse=True)
def _isolated_faults():
    faults.install(faults.FaultRegistry())
    yield
    faults.install(faults.FaultRegistry())


def _arm(spec, seed=42):
    return faults.install(faults.FaultRegistry(
        seed, faults.parse_sites(spec, 0.05)))


def _table(n=60):
    return pa.table({
        "a": pa.array(range(n), type=pa.int64()),
        "s": pa.array([f"v{i % 3}" for i in range(n)]),
    })


def _tree(path):
    """{relpath: (size, crc)} of every visible file under path."""
    out = {}
    for dirpath, _dirs, names in os.walk(path):
        for nm in names:
            full = os.path.join(dirpath, nm)
            rel = os.path.relpath(full, path)
            if any(seg.startswith(("_", "."))
                   for seg in rel.split(os.sep)):
                continue
            out[rel] = (os.path.getsize(full), iocommit._crc32(full))
    return out


def _no_debris(root):
    bad = [f for f in glob.glob(os.path.join(root, "**", "*"),
                                recursive=True)
           if iocommit.TEMP_DIR in f or ".__new-" in f
           or ".__old-" in f or ".inprogress-" in f]
    assert not bad, bad


# ----------------------------------------------------- format round-trip

def test_six_format_roundtrip_committed(spark, tmp_path):
    df = spark.createDataFrame(_table())
    schema = pa.schema([("a", pa.int64()), ("s", pa.string())])
    for fmt in ("parquet", "orc", "csv", "json", "avro", "hivetext"):
        p = str(tmp_path / fmt)
        stats = df.write.format(fmt).save(p)
        assert stats.num_rows == 60 and stats.num_files == 1, fmt
        # the manifest is the commit point and validates clean
        man = iocommit.read_manifest(p)
        assert man is not None and len(man["files"]) == 1, fmt
        assert iocommit.validate_output(p) == 1, fmt
        reader = spark.read if fmt in ("parquet", "orc") \
            else spark.read.schema(schema)
        back = getattr(reader, "hivetext"
                       if fmt == "hivetext" else fmt)(p).collect_arrow()
        assert back.num_rows == 60, fmt
        assert sorted(back.column("a").to_pylist()) == list(range(60)), \
            fmt
    _no_debris(str(tmp_path))


def test_partitionby_special_chars_roundtrip(spark, tmp_path):
    """Hive layout with `/`, `=`, `%` and None in partition values:
    the escaped dirs stay flat and the read side decodes them back."""
    t = pa.table({
        "a": pa.array(range(8), type=pa.int64()),
        "k": pa.array(["x/y", "p=q", "50%", None] * 2),
    })
    p = str(tmp_path / "parts")
    spark.createDataFrame(t).write.partitionBy("k").parquet(p)
    dirs = sorted(d for d in os.listdir(p) if not d.startswith("_"))
    assert dirs == ["k=50%25", "k=__HIVE_DEFAULT_PARTITION__",
                    "k=p%3Dq", "k=x%2Fy"], dirs
    back = spark.read.parquet(p).collect_arrow()
    assert back.num_rows == 8
    assert sorted(set(back.column("k").to_pylist()),
                  key=lambda v: (v is None, v)) == \
        ["50%", "p=q", "x/y", None]


def test_append_and_job_unique_file_names(spark, tmp_path):
    df = spark.createDataFrame(_table(10))
    p = str(tmp_path / "app")
    df.write.parquet(p)
    df.write.mode("append").parquet(p)
    parts = glob.glob(os.path.join(p, "part-*.parquet"))
    assert len(parts) == 2  # job-tagged names never collide
    assert spark.read.parquet(p).collect_arrow().num_rows == 20


# ------------------------------------------- crash-safe overwrite swap

def test_overwrite_failure_leaves_old_bytes_identical(spark, tmp_path):
    p = str(tmp_path / "ow")
    spark.createDataFrame(_table(40)).write.parquet(p)
    before = _tree(p)
    assert before
    # every commit.job attempt fails -> the job aborts; the prior
    # output must survive byte-identical, with zero staging debris
    _arm("commit.job:p=1.0")
    with pytest.raises(RetryExhausted):
        spark.createDataFrame(_table(5)).write.mode(
            "overwrite").parquet(p)
    faults.install(faults.FaultRegistry())
    assert _tree(p) == before
    _no_debris(str(tmp_path))
    back = spark.read.parquet(p).collect_arrow()
    assert back.num_rows == 40


def test_overwrite_swaps_atomically_on_success(spark, tmp_path):
    p = str(tmp_path / "ow2")
    spark.createDataFrame(_table(40)).write.parquet(p)
    spark.createDataFrame(_table(7)).write.mode("overwrite").parquet(p)
    assert spark.read.parquet(p).collect_arrow().num_rows == 7
    assert iocommit.validate_output(p) == 1
    _no_debris(str(tmp_path))


def test_chaos_on_write_sites_still_exactly_once(spark, tmp_path):
    """io.write + commit.task faults are absorbed by the shared backoff
    discipline; the published output still counts every row once."""
    _arm("io.write:every=3;commit.task:every=2")
    p = str(tmp_path / "chaos")
    stats = spark.createDataFrame(_table(30)).write.parquet(p)
    assert stats.num_rows == 30
    assert iocommit.validate_output(p) == 1
    assert spark.read.parquet(p).collect_arrow().num_rows == 30
    _no_debris(str(tmp_path))


# ------------------------------------------------- reader-side contract

def test_reader_skips_staging_and_validates_manifest(spark, tmp_path):
    p = str(tmp_path / "val")
    spark.createDataFrame(_table(20)).write.parquet(p)
    # plant staging debris a scan must never surface
    os.makedirs(os.path.join(p, iocommit.TEMP_DIR, "deadjob"),
                exist_ok=True)
    pq.write_table(_table(5), os.path.join(
        p, iocommit.TEMP_DIR, "deadjob", "part-zzz.parquet"))
    assert spark.read.parquet(p).collect_arrow().num_rows == 20
    # corrupt a listed file -> validateOnRead surfaces the tear
    [data] = glob.glob(os.path.join(p, "part-*.parquet"))
    with open(data, "ab") as f:
        f.write(b"x")
    s2 = TpuSparkSession({
        **_CONF,
        "spark.rapids.tpu.write.manifest.validateOnRead": True})
    try:
        with pytest.raises(iocommit.ManifestMismatch):
            s2.read.parquet(p).collect_arrow()
    finally:
        s2.stop()


# ------------------------------------------------- kill -9 mid-write

def test_kill9_writer_mid_task_output_oracle_identical(tmp_path):
    """SIGKILL a process worker holding an in-flight write task: the
    re-attempt (different worker, different attempt dir) is the one
    that commits, and the published output equals the oracle exactly —
    no double-counted, partial, or missing rows."""
    from spark_rapids_tpu.parallel.process_pool import (
        ProcessBackend,
        ProcessWorkerPool,
    )
    from spark_rapids_tpu.runtime.scheduler import StageScheduler, Task

    src = str(tmp_path / "src.parquet")
    table = _table(120)
    pq.write_table(table, src)
    out = str(tmp_path / "out")
    committer = iocommit.JobCommitter(out, mode="error", fmt="parquet")
    assert committer.setup_job()
    n, step = 6, 20
    FRAG = "spark_rapids_tpu.io.commit:run_write_fragment"

    def spec(i, sleep_s):
        return {"fmt": "parquet", "src": src, "offset": i * step,
                "count": step, "staging": committer.staging, "task": i,
                "file_tag": committer.job_id, "sleep_s": sleep_s}

    pool = ProcessWorkerPool(3, hb_interval_ms=100, hb_timeout_ms=1200)
    try:
        tasks = [Task(i, payload=(FRAG, spec(i, 0.4)),
                      commit=lambda res, att, i=i:
                          committer.commit_task(i, res),
                      abort=lambda att, i=i: None)
                 for i in range(n)]
        pid = pool.worker_pid("worker-0")

        def killer():
            time.sleep(0.6)
            os.kill(pid, signal.SIGKILL)

        threading.Thread(target=killer, daemon=True).start()
        StageScheduler(None, name="write-kill",
                       backend=ProcessBackend(pool)).run(tasks)
        manifest = committer.commit_job()
    finally:
        pool.close()
    assert len(manifest["files"]) == n
    assert iocommit.validate_output(out) == n
    back = pq.read_table(out)
    assert back.num_rows == 120
    assert sorted(back.column("a").to_pylist()) == \
        table.column("a").to_pylist()
    _no_debris(str(tmp_path))


# ------------------------------------------------------- orphan sweep

def test_sweep_reclaims_dead_never_live(tmp_path):
    out = str(tmp_path / "t")
    os.makedirs(out)
    tmp_root = os.path.join(out, iocommit.TEMP_DIR)
    dead = os.path.join(tmp_root, "deadjob")
    live = os.path.join(tmp_root, "livejob")
    os.makedirs(dead)
    os.makedirs(live)
    import socket

    json.dump({"pid": 2 ** 22 + 11, "host": socket.gethostname()},
              open(os.path.join(dead, iocommit.OWNER_FILE), "w"))
    json.dump({"pid": os.getpid(), "host": socket.gethostname()},
              open(os.path.join(live, iocommit.OWNER_FILE), "w"))
    assert iocommit.sweep_orphans(out) == 1
    assert not os.path.isdir(dead)
    assert os.path.isdir(live)  # live job's staging untouched
    # fresh foreign staging (no readable owner) is inside the TTL: kept
    foreign = os.path.join(tmp_root, "foreign")
    os.makedirs(foreign)
    open(os.path.join(foreign, "f"), "w").write("x")
    assert iocommit.sweep_orphans(out) == 0
    assert os.path.isdir(foreign)
    # ...but expired foreign staging is reclaimed
    assert iocommit.sweep_orphans(out, ttl_s=0.0) == 1
    assert not os.path.isdir(foreign)


def test_sweep_restores_old_after_crashed_swap(tmp_path):
    """Crash exactly between the swap's two renames leaves only
    `<out>.__old-<job>`: the sweep puts the old data back."""
    out = str(tmp_path / "t")
    old = out + iocommit._OLD_TAG + "deadbeef"
    os.makedirs(old)
    pq.write_table(_table(9), os.path.join(old, "part-0.parquet"))
    assert iocommit.sweep_orphans(out) == 1
    assert pq.read_table(out).num_rows == 9


# --------------------------------------------- optimistic delta commits

def test_concurrent_delta_appends_both_land(spark, tmp_path):
    p = str(tmp_path / "d")
    spark.createDataFrame(_table(10)).write.format("delta").save(p)
    barrier = threading.Barrier(2)
    errs = []

    def appender(n):
        try:
            df = spark.createDataFrame(_table(n))
            barrier.wait(timeout=10)
            df.write.format("delta").mode("append").save(p)
        except BaseException as e:  # pragma: no cover - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=appender, args=(n,))
          for n in (20, 30)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs, errs
    back = spark.read.delta(p).collect_arrow()
    assert back.num_rows == 60  # 10 + 20 + 30: nothing lost
    from spark_rapids_tpu.lakehouse.delta import _list_versions

    assert _list_versions(p) == [0, 1, 2]
    assert iocommit.write_totals()["conflicts"] >= 1


def test_delta_rewrite_conflict_is_concurrent_modification(spark,
                                                           tmp_path):
    """A DELETE retrying on top of a commit that removed its read set
    must fail with DeltaConcurrentModification, not silently resurrect
    or drop rows."""
    from spark_rapids_tpu.lakehouse import delta as dmod

    p = str(tmp_path / "d")
    spark.createDataFrame(_table(10)).write.format("delta").save(p)
    snap = dmod.load_snapshot(p)
    cur_files = set(snap.file_paths)
    # simulate: our read set was a file an interim commit removed
    with pytest.raises(dmod.DeltaConcurrentModification):
        dmod._check_rewrite_conflict(
            0, snap, cur_files | {"part-gone.parquet"}, False, "DELETE")
    # full-table rewrite + interim append -> also non-retryable
    with pytest.raises(dmod.DeltaConcurrentModification):
        dmod._check_rewrite_conflict(0, snap, set(), True, "OPTIMIZE")
    # partial rewrite + compatible interim append -> no conflict
    dmod._check_rewrite_conflict(0, snap, cur_files, False, "DELETE")


def test_delta_commit_conflict_chaos_site(spark, tmp_path):
    """commit.conflict chaos forces optimistic-loop retries; the write
    still lands exactly once."""
    _arm("commit.conflict:once")
    p = str(tmp_path / "d")
    spark.createDataFrame(_table(10)).write.format("delta").save(p)
    assert spark.read.delta(p).collect_arrow().num_rows == 10


# --------------------------------------------------- iceberg occ claim

def test_iceberg_commit_metadata_claim_and_retry(tmp_path):
    from spark_rapids_tpu.lakehouse import iceberg as ice

    p = str(tmp_path / "ice")

    def build_v1(cur):
        assert cur is None
        return {"n": 1}

    assert ice.commit_metadata(p, build_v1) == 1
    # loser path: claim v2 out from under the builder ONCE, the retry
    # must rebuild against the new current metadata and land v3
    state = {"stolen": False}

    def build_racing(cur):
        if not state["stolen"]:
            state["stolen"] = True
            with open(os.path.join(
                    p, "metadata", "v2.metadata.json"), "w") as f:
                json.dump({"n": "thief"}, f)
        return {"n": cur["n"]}

    assert ice.commit_metadata(p, build_racing) == 3
    assert ice._load_metadata(p) == {"n": "thief"}
    hint = open(os.path.join(p, "metadata", "version-hint.text")).read()
    assert hint.strip() == "3"


# ----------------------------------------------------- stats + events

def test_write_stats_stat_failure_counted(tmp_path):
    from spark_rapids_tpu.io.writers import WriteStats

    st = WriteStats()
    st.file_written(str(tmp_path / "missing.bin"), rows=5)
    assert st.stat_failures == 1 and st.num_rows == 5
    assert st.num_bytes == 0
    st.file_written("anything", rows=2, nbytes=17)  # staged-rename path
    assert st.num_bytes == 17 and st.num_files == 2


def test_unknown_options_once_per_job_event(spark, tmp_path):
    from spark_rapids_tpu.obs import events as obs

    seen = []
    bus = obs.get()
    assert bus is not None
    unsub = bus.subscribe(
        lambda ev: seen.append(ev) if ev["event"] == "write.options"
        else None)
    try:
        (spark.createDataFrame(_table(12)).write
         .option("bogus_option", 1).option("compression", "snappy")
         .parquet(str(tmp_path / "o")))
    finally:
        bus.unsubscribe(unsub)
    assert len(seen) == 1  # once per JOB, not per file
    assert seen[0]["ignored"] == ["bogus_option"]


def test_write_events_and_telemetry_block(spark, tmp_path):
    from spark_rapids_tpu.obs import events as obs
    from spark_rapids_tpu.obs import telemetry as tel

    seen = []
    bus = obs.get()
    assert bus is not None
    unsub = bus.subscribe(
        lambda ev: seen.append(ev)
        if ev["event"].startswith("write.") else None)
    try:
        spark.createDataFrame(_table(25)).write.parquet(
            str(tmp_path / "ev"))
    finally:
        bus.unsubscribe(unsub)
    kinds = [e["event"] for e in seen]
    assert kinds[0] == "write.start" and kinds[-1] == "write.commit"
    assert "write.task" in kinds
    commit_ev = seen[-1]
    assert commit_ev["rows"] == 25 and commit_ev["files"] == 1
    qid = commit_ev["queryId"]
    assert qid  # attributed to the save()'s query scope
    summ = tel.ledger.recent_query_summaries().get(qid)
    assert summ and summ["write"]["rows"] == 25
    # prometheus families render
    from spark_rapids_tpu.obs import prom

    text = prom.render(spark)
    assert "srtpu_write_jobs_total" in text
    assert "srtpu_query_write_bytes" in text
