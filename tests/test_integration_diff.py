"""Differential integration tests: TPU backend vs CPU (pyarrow) oracle —
the reference's primary correctness net
(`assert_gpu_and_cpu_are_equal_collect`, integration_tests/asserts.py:579),
over seeded generated data with nulls and special values.
"""

import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
    assert_tpu_fallback_collect,
)
from spark_rapids_tpu.testing.datagen import (
    DateGen,
    DecimalGen,
    DoubleGen,
    IntGen,
    LongGen,
    RepeatSeqGen,
    StringGen,
    gen_table,
)

_CONF = {"spark.sql.shuffle.partitions": 4}


@pytest.fixture(scope="module")
def sales_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("data")
    t = gen_table([
        ("store", RepeatSeqGen(IntGen(0, 50, nullable=True), 40)),
        ("amount", DoubleGen(include_specials=False)),
        ("qty", LongGen(lo=-1000, hi=1000)),
        ("name", StringGen(max_len=10, cardinality=30)),
        ("day", DateGen()),
    ], n=5000, seed=42)
    # write as several files to exercise multi-file scan
    for i in range(3):
        pq.write_table(t.slice(i * 1700, 1700),
                       os.path.join(d, f"part-{i}.parquet"))
    return str(d)


def test_scan_roundtrip(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path), conf=_CONF)


def test_filter_project(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path)
        .filter(F.col("amount") > 0.0)
        .select("store", (F.col("amount") * 2 + 1).alias("x"),
                F.col("qty").alias("q")),
        conf=_CONF)


def test_groupby_agg(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path)
        .groupBy("store")
        .agg(F.sum("amount").alias("total"),
             F.count("*").alias("n"),
             F.min("qty").alias("mn"),
             F.max("qty").alias("mx"),
             F.avg("amount").alias("m")),
        conf=_CONF)


def test_global_agg(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path)
        .agg(F.sum("qty").alias("t"), F.count("*").alias("n")),
        conf=_CONF)


def test_groupby_string_key(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path)
        .groupBy("name").agg(F.count("*").alias("n"),
                             F.sum("qty").alias("q")),
        conf=_CONF)


def test_distinct(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path).select("store").distinct(),
        conf=_CONF)


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_joins(sales_path, how):
    def q(s):
        fact = s.read.parquet(sales_path)
        dim = s.createDataFrame({
            "store": list(range(0, 50, 2)),
            "city": [f"city{i}" for i in range(25)],
        })
        joined = fact.join(dim, on="store", how=how)
        if how in ("left_semi", "left_anti"):
            return joined.select("store", "qty")
        return joined.select("store", "qty", "city")

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


def test_sort(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path)
        .select("store", "qty").orderBy("store", "qty"),
        conf=_CONF, ignore_order=False)


def test_sort_desc(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path)
        .select("qty").orderBy("qty", ascending=False),
        conf=_CONF, ignore_order=False)


def test_conditional_and_case(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path).select(
            "store",
            F.when(F.col("qty") > 0, "pos").when(F.col("qty") < 0, "neg")
            .otherwise("zero").alias("sign"),
            F.coalesce("store", F.lit(-1)).alias("s2")),
        conf=_CONF)


def test_string_functions(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path).select(
            F.upper("name").alias("u"),
            F.length("name").alias("l"),
            F.substring("name", 2, 3).alias("sub"),
            F.concat("name", F.lit("_x")).alias("c")),
        conf=_CONF)


def test_date_functions(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path).select(
            F.year("day").alias("y"), F.month("day").alias("m"),
            F.dayofmonth("day").alias("d")),
        conf=_CONF)


def test_union_and_limit(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path).select("store")
        .union(s.read.parquet(sales_path).select("store")),
        conf=_CONF)


def test_decimal_agg():
    t = gen_table([
        ("k", RepeatSeqGen(IntGen(0, 10), 8)),
        ("d", DecimalGen(precision=12, scale=2)),
    ], n=500, seed=7)

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.createDataFrame(t).groupBy("k")
        .agg(F.sum("d").alias("t"), F.min("d").alias("mn"),
             F.max("d").alias("mx")),
        conf=_CONF)


def test_hash_expression_matches(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path).select(
            "store", F.hash("store", "qty").alias("h")),
        conf=_CONF)


def test_string_cast_on_device(sales_path):
    """Cast(string -> int) runs on device (ops/stringcast.py); result
    parity with the oracle."""
    from spark_rapids_tpu.sqltypes.datatypes import integer

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.createDataFrame({"x": ["1", "22", "333", "nope"]})
        .select(F.col("x").cast(integer).alias("i")),
        conf=_CONF)


def test_timestamp_to_string_cast_on_device(sales_path):
    """Cast(timestamp -> string) runs on device since the
    _timestamp_to_string kernel landed; diff it against the oracle."""
    import datetime

    from spark_rapids_tpu.sqltypes.datatypes import string as string_t

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.createDataFrame({"t": [
            datetime.datetime(2020, 1, 1, 12, 0, 0),
            datetime.datetime(2021, 6, 15, 23, 59, 59, 120000)]})
        .select(F.col("t").cast(string_t).alias("s")),
        conf=_CONF)


def test_fallback_date_format_pattern(sales_path):
    """date_format with a pattern outside the device token subset is
    tagged NOT_ON_TPU (assert_gpu_fallback_collect analog)."""
    import datetime

    assert_tpu_fallback_collect(
        lambda s: s.createDataFrame({"t": [
            datetime.datetime(2020, 1, 1, 12, 0, 0),
            datetime.datetime(2021, 6, 15, 23, 59, 59)]})
        .select(F.date_format("t", "EEE yyyy").alias("s")),
        fallback_class="CpuProjectExec",
        conf=_CONF)


def test_q5_shape(sales_path):
    """The minimum end-to-end slice (SURVEY.md section 7): scan ->
    filter -> project -> partial agg -> exchange -> final agg -> sort."""
    def q(s):
        fact = s.read.parquet(sales_path)
        dim = s.createDataFrame({
            "store": list(range(0, 50, 2)),
            "city": [f"city{i}" for i in range(25)],
        })
        return (fact.filter(F.col("amount") > 0.0)
                .join(dim, on="store", how="inner")
                .groupBy("city")
                .agg(F.sum("amount").alias("revenue"),
                     F.count("*").alias("sales"))
                .orderBy("city"))

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF,
                                         ignore_order=False)


def test_right_join(sales_path):
    """Right outer = swapped left outer + reorder (planner rewrite)."""
    def q(s):
        fact = s.read.parquet(sales_path)
        dim = s.createDataFrame({
            "store": list(range(45, 60)),  # some stores unmatched
            "city": [f"c{i}" for i in range(15)],
        })
        return fact.join(dim, on="store", how="right") \
            .select("store", "qty", "city")

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


def test_full_outer_join():
    def q(s):
        a = s.createDataFrame({"k": [1, 2, 3], "x": [10, 20, 30]})
        b = s.createDataFrame({"k": [2, 3, 4], "y": [200, 300, 400]})
        return a.join(b, on="k", how="full").select("x", "y")

    assert_tpu_and_cpu_are_equal_collect(q, conf=_CONF)


def test_substring_negative_past_start():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.createDataFrame({"s": ["abc", "ab", "a", ""]})
        .select(F.substring("s", -5, 2).alias("r"),
                F.substring("s", -2, 5).alias("r2")),
        conf=_CONF)


def test_repartition_round_robin(sales_path):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read.parquet(sales_path).repartition(3)
        .select("store", "qty"),
        conf=_CONF)
