#!/usr/bin/env bash
# Multichip gate — the planner-native SPMD contract (PR 12):
# sharded-vs-single oracle equality on the virtual 8-device mesh
# (plain AND encoded columns, per-shard dictionaries reconciled), zero
# host-direction shuffle bytes for an ICI-resident hash exchange,
# chip-loss recovery leak-free (permits/buffers, 10s quiesce) with
# other chips still serving, and srtpu-lint at zero findings.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== multichip SPMD gate (virtual 8-device mesh) =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import os
import tempfile
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime import device_monitor as dm
from spark_rapids_tpu.runtime import semaphore as sem_mod
from spark_rapids_tpu.runtime.memory import get_catalog

root = tempfile.mkdtemp(prefix="srtpu_multichip_")
rng = np.random.default_rng(29)
N, FILES, STORES = 48_000, 8, 64
fact_dir = os.path.join(root, "fact")
dim_dir = os.path.join(root, "dim")
os.makedirs(fact_dir)
os.makedirs(dim_dir)
per = N // FILES
for i in range(FILES):
    # per-file string vocabularies DIFFER: the mesh path (one file per
    # shard) must reconcile per-shard dictionaries before its codes can
    # meet in an exchange
    vocab = [f"f{i}_c{j}" for j in range(4)] + ["shared_x", "shared_y"]
    pq.write_table(pa.table({
        "cat": pa.array(rng.choice(vocab, per), pa.large_string()),
        "store": pa.array(rng.integers(0, STORES, per), pa.int64()),
        "amount": pa.array(rng.random(per) * 100.0),
    }), os.path.join(fact_dir, f"part-{i}.parquet"),
        use_dictionary=["cat"], row_group_size=per)
pq.write_table(pa.table({
    "store": pa.array(np.arange(STORES), pa.int64()),
    "region": pa.array([f"r{i % 7}" for i in range(STORES)],
                       pa.large_string()),
}), os.path.join(dim_dir, "dim.parquet"), use_dictionary=["region"])


def q(s):
    # q5 shape: filter -> shuffled equi-join -> group-by, with an
    # encoded string column riding through the exchanges as codes
    return (s.read.parquet(fact_dir)
            .filter(F.col("amount") > 10.0)
            .join(s.read.parquet(dim_dir), on="store", how="inner")
            .groupBy("region")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n")))


def q_cat(s):
    return (s.read.parquet(fact_dir).groupBy("cat")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n")))


def canon(t):
    cols = t.column_names
    return sorted(zip(t.column(cols[0]).to_pylist(),
                      [round(v, 5) for v in
                       t.column(cols[1]).to_pylist()],
                      t.column(cols[2]).to_pylist()))


def quiesce_clean(label):
    deadline = time.monotonic() + 10.0
    sem = sem_mod.get()
    cat = get_catalog()
    while time.monotonic() < deadline:
        if sem.holders() == 0 and cat.buffer_count() == 0:
            break
        time.sleep(0.05)
    assert sem.holders() == 0, \
        f"{label}: leaked permits: {sem._holder_diagnostics()}"
    cat.check_leaks(raise_on_leak=True)


BASE = {"spark.sql.shuffle.partitions": 4,
        "spark.sql.autoBroadcastJoinThreshold": -1}
MESH = {**BASE, "spark.rapids.tpu.mesh": 8}

# -------- single-chip oracle --------
s = TpuSparkSession(BASE)
want = canon(q(s).collect_arrow())
want_cat = canon(q_cat(s).collect_arrow())
s.stop()

# -------- 1. sharded == single, zero host shuffle bytes --------
s = TpuSparkSession(MESH)
got = canon(q(s).collect_arrow())
rec = s.last_execution
assert rec["engine"] == "mesh", f"engine={rec['engine']}"
assert got == want, "sharded join+agg diverges from single-chip"
tel = rec.get("telemetry") or {}
moved = tel.get("bytesMoved") or {}
assert moved.get("ici", 0) > 0, f"no ici bytes ledgered: {moved}"
assert moved.get("shuffle", 0) == 0, \
    f"ICI-resident exchange staged host shuffle bytes: {moved}"
assert tel.get("iciBytes", 0) > 0 and tel.get("hostBytesAvoided", 0) > 0
print(f"ici-resident exchange: ici={moved['ici']}B shuffle_host=0B "
      f"hostBytesAvoided={tel['hostBytesAvoided']}B")

got_cat = canon(q_cat(s).collect_arrow())
assert s.last_execution["engine"] == "mesh"
assert got_cat == want_cat, \
    "per-shard dictionary reconciliation diverges from single-chip"
print(f"encoded group-by: {len(got_cat)} groups reconciled across "
      f"{FILES} per-shard dictionaries")
s.stop()
quiesce_clean("sharded-vs-single")

# -------- 2. chip-loss recovery: leak-free, others keep serving -----
conf = {**MESH,
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.seed": 7,
        "spark.rapids.tpu.chaos.sites": "chip.fatal:once"}
before = dm.counters()
s = TpuSparkSession(conf)
got = canon(q(s).collect_arrow())
after = dm.counters()
assert got == want, "post-chip-loss results diverge"
assert after["chipFences"] == before["chipFences"] + 1, \
    "chip.fatal did not fence the chip"
assert after["chipRecoveries"] == before["chipRecoveries"] + 1, \
    "no chip recovery ran"
assert after["fences"] == before["fences"], \
    "chip loss escalated to a PROCESS-wide fence"
evs = s.obs.history.events()
kinds = [e["event"] for e in evs]
assert "chip.fence" in kinds and "chip.recovery" in kinds, \
    f"missing chip fence/recovery events: {sorted(set(kinds))}"
# the fenced mesh keeps serving new queries over the survivors
got2 = canon(q(s).collect_arrow())
assert got2 == want and s.last_execution["engine"] == "mesh"
s.stop()
quiesce_clean("chip-loss")
dm.clear_chip_fences()
print(f"chip-loss recovery: oracle-identical over survivors "
      f"(chipFences={after['chipFences'] - before['chipFences']}, "
      f"chipEpoch={after['chipEpoch']}), leak-free")

print("MULTICHIP CHECK PASS")
import sys

sys.stdout.flush()
# skip interpreter teardown: XLA's CPU backend can abort in its exit
# handlers after a session cycle (pre-existing, see test_chaos notes)
os._exit(0)
PY

echo "== static gate stays clean (srtpu-lint, zero findings) =="
python -m spark_rapids_tpu.tools.lint

echo "MULTICHIP CHECK PASS"
