#!/usr/bin/env bash
# Data-movement telemetry gate — the PR 6 end-to-end contract:
# a query run with telemetry + the event log + chaos on shuffle.fetch
# reports per-query bytesMoved/hbmPeakBytes/rooflineFrac consistently
# across last_execution["telemetry"], the transfer events in the
# per-query event log, and the profile report; the live HTTP endpoint
# serves parseable Prometheus text at /metrics and the running-query
# table at /queries; and session.stop() tears the server down
# leak-free (no lingering thread, socket closed).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== telemetry ledger + eventlog consistency + HTTP gate =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import json
import os
import tempfile
import threading
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.obs import eventlog

root = tempfile.mkdtemp(prefix="srtpu_telcheck_")
log_dir = os.path.join(root, "eventlog")
fact_dir = os.path.join(root, "fact")
os.makedirs(fact_dir)
rng = np.random.default_rng(11)
N = 20_000
pq.write_table(pa.table({
    "k": pa.array(rng.integers(0, 50, N), pa.int64()),
    "v": pa.array(rng.random(N) * 100.0),
}), os.path.join(fact_dir, "part-0.parquet"))

s = TpuSparkSession({
    "spark.rapids.tpu.eventLog.enabled": True,
    "spark.rapids.tpu.eventLog.dir": log_dir,
    "spark.rapids.tpu.obs.http.enabled": True,
    "spark.sql.shuffle.partitions": 4,
    # the per-operator engine so the repartition MATERIALIZES through
    # the shuffle manager (the fused engine would compile it away)...
    "spark.rapids.sql.fusedExec.enabled": False,
    # ...with survivable chaos on the fetch path: telemetry numbers
    # must stay consistent while the retry machinery is live
    "spark.rapids.tpu.chaos.enabled": True,
    "spark.rapids.tpu.chaos.seed": 7,
    "spark.rapids.tpu.chaos.sites": "shuffle.fetch=p0.3",
})
df = (s.read.parquet(fact_dir)
      .filter(F.col("v") > 10.0)
      .repartition(4, "k").groupBy("k")
      .agg(F.sum("v").alias("sv"), F.count("*").alias("n")))
out = df.collect_arrow()
assert out.num_rows > 0
qid = s.last_execution["queryId"]
tel = s.last_execution["telemetry"]
assert tel, "telemetry missing from last_execution"
for key in ("bytesMoved", "bytesMovedTotal", "hbmPeakBytes",
            "rooflineFrac"):
    assert key in tel, (key, sorted(tel))
assert tel["bytesMovedTotal"] > 0
assert tel["bytesMoved"].get("shuffle", 0) > 0, \
    "repartition must move shuffle bytes on the per-operator engine"
print(f"query {qid}: moved {tel['bytesMovedTotal']} B "
      f"{dict(tel['bytesMoved'])}, roofline_frac {tel['rooflineFrac']}")

# --- 1. ledger <-> eventlog consistency: per-direction sums of the
# --- logged transfer events equal the summary the query reported ---
events = eventlog.load(log_dir, qid)
by_dir = {}
for ev in events:
    if ev["event"] == "transfer":
        d = by_dir.setdefault(ev["direction"], 0)
        by_dir[ev["direction"]] = d + ev["bytes"]
summaries = [e for e in events if e["event"] == "telemetry.summary"]
assert len(summaries) == 1, f"{len(summaries)} summary events"
assert summaries[0]["bytesMoved"] == by_dir, (
    summaries[0]["bytesMoved"], by_dir)
assert tel["bytesMoved"] == by_dir, (tel["bytesMoved"], by_dir)
print(f"eventlog transfer sums match the ledger summary ({by_dir})")

# --- 2. profile report carries the same numbers ---
from spark_rapids_tpu.obs import report

prof = report.profile_data(log_dir)
assert prof["telemetry"]["bytesMovedTotal"] == tel["bytesMovedTotal"]
got_mv = {d: v["bytes"] for d, v in prof["dataMovement"].items()}
assert got_mv == by_dir, (got_mv, by_dir)
print("profile report data-movement section consistent")

# --- 3. the HTTP endpoint serves parseable Prometheus text ---
port = s.obs.http.port
threads_before = {t.name for t in threading.enumerate()}
assert "srtpu-obs-http" in str(threads_before)
body = urllib.request.urlopen(
    f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
n_samples = 0
for line in body.splitlines():
    if not line or line.startswith("#"):
        continue
    assert line.startswith("srtpu_"), line
    name_part, _, value = line.rpartition(" ")
    float(value)  # every sample value parses
    n_samples += 1
assert n_samples > 20, n_samples
assert f'srtpu_query_bytes_moved{{queryId="{qid}"' in body
assert f'srtpu_query_roofline_frac{{queryId="{qid}"}}' in body
assert f'srtpu_query_hbm_peak_bytes{{queryId="{qid}"}}' in body
qjson = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/queries", timeout=10).read().decode())
assert str(qid) in qjson["queries"], sorted(qjson["queries"])
assert qjson["queries"][str(qid)]["bytesMoved"] == by_dir
print(f"/metrics parseable ({n_samples} samples), /queries lists "
      f"query {qid}")

# --- 4. leak-free shutdown: no lingering thread, socket closed ---
s.stop()
import time as _t

deadline = _t.monotonic() + 5.0
while _t.monotonic() < deadline and any(
        t.name == "srtpu-obs-http" and t.is_alive()
        for t in threading.enumerate()):
    _t.sleep(0.05)
assert not any(t.name == "srtpu-obs-http" and t.is_alive()
               for t in threading.enumerate()), "http thread lingers"
try:
    urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                           timeout=2)
    raise AssertionError("socket still serving after stop()")
except (urllib.error.URLError, ConnectionError, OSError):
    pass
print("server shut down leak-free (thread joined, socket closed)")
print("TELEMETRY CHECK PASS")
import sys

sys.stdout.flush()
# skip interpreter teardown: XLA's CPU backend can abort in its exit
# handlers after a session cycle (pre-existing, see test_chaos notes)
os._exit(0)
PY
