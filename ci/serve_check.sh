#!/usr/bin/env bash
# Serving gate — the multi-tenant query service daemon under load.
# A fresh-process daemon serves closed-loop clients across THREE
# tenants with distinct priority classes while a seeded device.fatal
# fences the engine mid-soak and a cancel storm rains on the running
# table. The acceptance contract: every completed result is
# oracle-identical, the plan cache serves hits (> 0) that skip
# re-planning, per-tenant billing reconciles exactly with the
# transfer ledger, /healthz (liveness) stays 200 throughout while
# /readyz (readiness) flips 503 during the fence, and after drain +
# stop ZERO permits, buffers, sockets, connections or handler threads
# leak. Ends with srtpu-lint at zero findings over the tree.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== serving soak (3 tenants x priorities + device.fatal + cancel storm) =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import json
import math
import os
import random
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.obs import telemetry
from spark_rapids_tpu.obs.http import ObsHttpServer
from spark_rapids_tpu.runtime import semaphore as sem_mod
from spark_rapids_tpu.runtime.errors import (
    QueryCancelledError,
    QueryDeadlineExceeded,
    QueryRejectedError,
)
from spark_rapids_tpu.runtime.memory import get_catalog
from spark_rapids_tpu.serve.client import ServeClient
from spark_rapids_tpu.serve.server import QueryServiceDaemon

root = tempfile.mkdtemp(prefix="srtpu_serve_gate_")
rng = np.random.default_rng(11)
N = 40_000
data = os.path.join(root, "fact")
os.makedirs(data)
for i in range(2):
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 64, N // 2), pa.int64()),
        "v": pa.array(rng.random(N // 2) * 100.0),
    }), os.path.join(data, f"p{i}.parquet"))

SPECS = {
    "sum": {"op": "orderBy",
            "input": {"op": "agg",
                      "input": {"op": "parquet", "path": data},
                      "groupBy": ["k"],
                      "aggs": [{"fn": "sum", "col": "v", "as": "x"}]},
            "keys": ["k"]},
    "cnt": {"op": "orderBy",
            "input": {"op": "agg",
                      "input": {"op": "filter",
                                "input": {"op": "parquet",
                                          "path": data},
                                "cond": {"fn": ">",
                                         "args": [{"col": "v"},
                                                  {"param": "lo"}]}},
                      "groupBy": ["k"],
                      "aggs": [{"fn": "count", "col": "*",
                                "as": "x"}]},
            "keys": ["k"]},
    "top": {"op": "limit",
            "input": {"op": "orderBy",
                      "input": {"op": "select",
                                "input": {"op": "parquet",
                                          "path": data},
                                "cols": ["k", "v"]},
                      "keys": [{"col": "v", "asc": False}]},
            "n": 20},
}
PARAMS = {"cnt": [{"lo": 25.0}, {"lo": 50.0}, {"lo": 75.0}]}


def bindings(name):
    return PARAMS.get(name, [None])


def same(a, b):
    if set(a) != set(b):
        return False
    for col in a:
        if len(a[col]) != len(b[col]):
            return False
        for x, y in zip(a[col], b[col]):
            if isinstance(x, float) or isinstance(y, float):
                if not math.isclose(x, y, rel_tol=1e-6, abs_tol=1e-8):
                    return False
            elif x != y:
                return False
    return True


# --- clean oracle: the SAME specs through an embedded chaos-free
# session (serve and embedded must agree bit-for-bit) ---
from spark_rapids_tpu.serve.spec import compile_spec

s0 = TpuSparkSession({})
want = {}
for name in SPECS:
    for p in bindings(name):
        want[(name, json.dumps(p))] = compile_spec(
            SPECS[name], s0, p or {}).collect_arrow().to_pydict()
s0.stop()

# --- the daemon under chaos: one warm session, device.fatal armed ---
s = TpuSparkSession({
    "spark.sql.shuffle.partitions": 4,
    "spark.rapids.tpu.admission.maxConcurrentQueries": 3,
    "spark.rapids.tpu.admission.queue.maxDepth": 32,
    "spark.rapids.tpu.chaos.enabled": True,
    "spark.rapids.tpu.chaos.seed": 17,
    "spark.rapids.tpu.chaos.sites": "device.fatal:once",
})
d = QueryServiceDaemon(session=s).start()
http = ObsHttpServer(s, port=0)

TENANTS = [("acme", "interactive"), ("globex", "standard"),
           ("initech", "batch")]
errors, mismatches = [], []
completed, cancelled, shed = [0], [0], [0]
lock = threading.Lock()
stop_probes = threading.Event()
not_ready_seen = [0]
live_failures = [0]


def probe_loop():
    """Liveness must NEVER fail; readiness must flip 503 during the
    fence window the seeded device.fatal opens."""
    while not stop_probes.is_set():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}/healthz",
                    timeout=2) as r:
                if r.status != 200:
                    live_failures[0] += 1
        except Exception:
            live_failures[0] += 1
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}/readyz",
                    timeout=2) as r:
                pass
        except urllib.error.HTTPError as e:
            if e.code == 503:
                not_ready_seen[0] += 1
        except Exception:
            pass
        time.sleep(0.004)


def worker(tenant, pclass, rounds, seed):
    prng = random.Random(seed)
    try:
        with ServeClient.connect(d, tenant, pclass) as c:
            for _ in range(rounds):
                name = prng.choice(sorted(SPECS))
                p = prng.choice(bindings(name))
                try:
                    got = c.query(SPECS[name], params=p,
                                  timeout_ms=120_000)
                    with lock:
                        completed[0] += 1
                        if not same(got.to_pydict(),
                                    want[(name, json.dumps(p))]):
                            mismatches.append((tenant, name, p))
                except QueryCancelledError:
                    with lock:
                        cancelled[0] += 1
                except QueryDeadlineExceeded:
                    with lock:
                        cancelled[0] += 1
                except QueryRejectedError:
                    with lock:
                        shed[0] += 1
    except BaseException as e:
        with lock:
            errors.append((tenant, repr(e)))


probe = threading.Thread(target=probe_loop, daemon=True)
probe.start()
threads = [threading.Thread(target=worker, args=(t, p, 8, i))
           for i, (t, p) in enumerate(TENANTS)]
# two connections per tenant -> intra-tenant concurrency too
threads += [threading.Thread(target=worker, args=(t, p, 4, 100 + i))
            for i, (t, p) in enumerate(TENANTS)]
for t in threads:
    t.start()

# cancel storm against the live running table. Wire cancels are
# TENANT-SCOPED — the admin tenant owns none of these queries, so
# every wire cancel must count 0; the storm itself goes through the
# in-process operator surface (admission.cancel).
from spark_rapids_tpu.runtime import admission as adm

prng = random.Random(4321)
cross_tenant_cancels = [0]
with ServeClient.connect(d, "admin", "interactive") as admin:
    deadline = time.monotonic() + 90
    while any(t.is_alive() for t in threads) and \
            time.monotonic() < deadline:
        time.sleep(prng.uniform(0.05, 0.2))
        running = s.admission_status()["running"]
        if running and prng.random() < 0.4:
            qid = prng.choice(running)["queryId"]
            cross_tenant_cancels[0] += admin.cancel(qid)
            adm.get().cancel(qid, "operator cancel storm")
for t in threads:
    t.join(240)
assert not any(t.is_alive() for t in threads), "serve worker hung"
stop_probes.set()
probe.join(10)

assert not errors, f"unexpected client errors: {errors}"
assert not mismatches, f"serve/embedded result mismatch: {mismatches}"
assert completed[0] > 0, "storm cancelled literally everything"
assert cross_tenant_cancels[0] == 0, \
    f"wire cancel crossed a tenant boundary " \
    f"({cross_tenant_cancels[0]} cancels counted)"
assert live_failures[0] == 0, \
    f"liveness failed {live_failures[0]}x — the service went DOWN"
assert not_ready_seen[0] >= 1, \
    "readiness never flipped 503 during the seeded fence"

# plan cache actually served (the whole point of a resident daemon)
pc_stats = d.plan_cache.stats.snapshot()
assert pc_stats["hits"] > 0, pc_stats

# billing reconciles with the transfer ledger, tenant by tenant
summaries = telemetry.ledger.recent_query_summaries()
for tenant, _ in TENANTS:
    snap = d.tenants.snapshot()[tenant]
    billed = sum(
        int(summaries[qid].get("bytesMovedTotal", 0) or 0)
        for qid in d.tenants.query_ids(tenant) if qid in summaries)
    assert snap["bytesMovedTotal"] == billed, (tenant, snap, billed)

# graceful drain: readiness 503 while draining, then a leak-free stop
report = d.drain()
try:
    urllib.request.urlopen(f"http://127.0.0.1:{http.port}/readyz",
                           timeout=2)
    raise AssertionError("readyz not 503 while draining")
except urllib.error.HTTPError as e:
    assert e.code == 503 and json.loads(e.read())["draining"], e.code
d.stop()
leaks = d.leak_report()
assert leaks == {"connections": 0, "inFlight": 0,
                 "handlerThreads": 0, "listener": 0}, leaks
assert not [t for t in threading.enumerate()
            if t.name.startswith("srtpu-serve")], "leaked thread"
assert sem_mod.get().holders() == 0, "leaked semaphore permits"
get_catalog().check_leaks(raise_on_leak=True)
assert s.admission_status()["running"] == [], "stuck admission slot"
assert s.admission_status()["queued"] == [], "stuck queued query"
assert s.admission_status()["draining"] is False, "valve not reopened"
# the session survives its daemon: still serving embedded queries
assert s.range(0, 100).count() == 100

print(f"serve gate: {completed[0]} completed, {cancelled[0]} "
      f"cancelled, {shed[0]} shed, drain={report}, "
      f"planCache={pc_stats}, notReadySamples={not_ready_seen[0]}")
http.close()
s.stop()
print("SERVE SOAK PASS")
os._exit(0)  # pre-existing XLA exit-time abort after session cycling
PY

echo "== serving suites (daemon + plan cache) =="
python -m pytest tests/test_serve.py tests/test_plan_cache.py -q \
    -p no:cacheprovider

echo "== srtpu-lint over the tree (zero findings required) =="
python -m spark_rapids_tpu.tools.lint

echo "SERVE GATE PASS"
