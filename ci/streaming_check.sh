#!/usr/bin/env bash
# Streaming-executor gate — the out-of-core contract: a table many
# times the conf'd device window must stream oracle-identically to the
# resident engines with the window high-water bounded, pipeline overlap
# reported, chaos at the streaming sites (io.read, device.fatal
# mid-stream) recovered leak-free, and srtpu-lint at zero findings.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== stream-vs-resident equality + bounded-window gate =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import os
import tempfile
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession

WINDOW = 2 << 20  # 2 MiB window; dataset decodes to many times this

root = tempfile.mkdtemp(prefix="srtpu_streamcheck_")
fact_dir = os.path.join(root, "fact")
dim_dir = os.path.join(root, "dim")
os.makedirs(fact_dir)
os.makedirs(dim_dir)
rng = np.random.default_rng(23)
STORES = 50
for i in range(4):
    N = 150_000
    pq.write_table(pa.table({
        "store": pa.array(rng.integers(0, STORES, N), pa.int64()),
        "amount": pa.array(rng.integers(0, 100, N), pa.int64()),
    }), os.path.join(fact_dir, f"part-{i}.parquet"),
        row_group_size=25_000)
pq.write_table(pa.table({
    "store": pa.array(np.arange(STORES), pa.int64()),
    "region": pa.array([f"region_{i % 7:02d}" for i in range(STORES)]),
}), os.path.join(dim_dir, "dim-0.parquet"), use_dictionary=True)

STREAM_CONF = {
    "spark.sql.shuffle.partitions": 4,
    "spark.rapids.tpu.stream.enabled": "true",
    "spark.rapids.tpu.stream.window.maxBytes": str(WINDOW),
    # trip the selection gate for a test-sized table
    "spark.rapids.tpu.stream.window.quotaFraction": "0.0001",
}


def q(s):
    # the q5 shape: streamed scan -> filter -> broadcast join ->
    # filter on the dim column -> string-keyed shuffle -> final agg
    return (s.read.parquet(fact_dir)
            .filter(F.col("amount") > 15)
            .join(s.read.parquet(dim_dir), on="store", how="inner")
            .filter(F.col("region") != "region_03")
            .repartition(4, "region")
            .groupBy("region")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n")))


def canon(t):
    cols = [t.column(i).to_pylist() for i in range(t.num_columns)]
    return sorted(map(tuple, zip(*cols))) if cols else []


def run(conf):
    s = TpuSparkSession(conf)
    try:
        out = q(s).collect_arrow()
        rec = dict(s.last_execution or {})
        return canon(out), rec
    finally:
        s.stop()


rows_stream, rec = run(STREAM_CONF)
rows_resident, _ = run({"spark.sql.shuffle.partitions": 4,
                        "spark.rapids.tpu.stream.enabled": "false"})
tel = rec.get("telemetry") or {}
assert rec["engine"] == "stream", f"engine={rec.get('engine')}"
assert rows_stream == rows_resident, "stream and resident results differ"
parts = tel.get("partitionsStreamed", 0)
assert parts >= 8, f"expected many window-sized partitions, got {parts}"
peak = tel.get("windowPeakBytes", 0)
assert 0 < peak <= 2 * WINDOW, (
    f"window high-water {peak} outside budget+slack ({2 * WINDOW})")
overlap = tel.get("overlapFraction")
assert overlap is not None and overlap > 0.0, (
    f"prefetch/compute overlap missing ({overlap}) — pipeline stalled")
print(f"stream == resident over {parts} partitions; "
      f"window peak {peak} B <= {2 * WINDOW} B, overlap {overlap}")

# ----------------------------------------------- chaos at stream sites
from spark_rapids_tpu.runtime import admission
from spark_rapids_tpu.runtime.memory import get_catalog


def chaos_run(faults):
    conf = dict(STREAM_CONF)
    conf.update({"spark.rapids.tpu.chaos.enabled": "true",
                 "spark.rapids.tpu.chaos.sites": faults,
                 "spark.rapids.tpu.chaos.seed": "7"})
    rows, rec = run(conf)
    # the encoded-dictionary device cache intentionally outlives the
    # query (reuse across queries); release it so the hygiene check
    # below measures the STREAM's residue, not the shared cache
    from spark_rapids_tpu.columnar import encoding
    encoding.invalidate_device_cache()
    cat = get_catalog()
    deadline = time.time() + 10
    while time.time() < deadline and (
            cat.buffer_count() or cat.pool.reserved):
        time.sleep(0.1)
    assert rows == rows_resident, f"{faults}: result diverged"
    assert cat.check_leaks() == 0, f"{faults}: leaked buffers"
    assert cat.buffer_count() == 0, f"{faults}: buffers left behind"
    assert cat.pool.reserved == 0, f"{faults}: device bytes left behind"
    assert admission.current_handle() is None
    return rec


chaos_run("io.read:once")
chaos_run("stream.prefetch:once")
chaos_run("stream.window_evict:once")
print("io.read / stream.prefetch / stream.window_evict: "
      "oracle-identical, leak-free")

# mid-stream device loss: lineage resume must re-stream only the
# unretired tail, not the whole table (cadence chosen to land the
# fault inside the 24-partition stream, not in the remainder plan)
rec = chaos_run("device.fatal:every=20")
tel = rec.get("telemetry") or {}
assert tel.get("streamRecoveries", 0) >= 1, "no recovery recorded"
assert tel.get("partitionsStreamed", 0) < parts, (
    "resume re-streamed every partition — lineage cache not used")
print(f"device.fatal mid-stream: resumed from lineage, re-streamed "
      f"{tel.get('partitionsStreamed')}/{parts} partitions, "
      f"recoveries {tel.get('streamRecoveries')}")
print("STREAMING CHECK PASS")
import sys

sys.stdout.flush()
# skip interpreter teardown: XLA's CPU backend can abort in its exit
# handlers after a session cycle (pre-existing, see test_chaos notes)
os._exit(0)
PY

echo "== static gate stays clean (srtpu-lint, zero findings) =="
python -m spark_rapids_tpu.tools.lint
