#!/usr/bin/env bash
# Nightly perf job — the jenkins/spark-nightly-build.sh role: run the
# engine benchmark on real hardware and archive the JSON line.
set -euo pipefail
cd "$(dirname "$0")/.."
out="bench-$(date +%Y%m%d).json"
timeout 900 python bench.py | tee "$out"
