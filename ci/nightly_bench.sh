#!/usr/bin/env bash
# Nightly perf job — the jenkins/spark-nightly-build.sh role: run the
# engine benchmark on real hardware, archive the JSON line, and track
# COLD START (cold_s and warm-persistent-cache cold_warm_cache_s) so a
# time-to-first-query regression fails the job instead of drifting.
set -euo pipefail
cd "$(dirname "$0")/.."
out="bench-$(date +%Y%m%d).json"
timeout 2400 python bench.py --fleet | tee "$out"

python - "$out" <<'PY'
import json, sys, datetime, os

line = [l for l in open(sys.argv[1]) if l.strip().startswith("{")][-1]
d = json.loads(line)
serve = d.get("serve") or {}
fleet = d.get("fleet") or {}
hosts = (d.get("multichip") or {}).get("hosts") or {}
entry = {
    "date": datetime.date.today().isoformat(),
    "value_gbps": d.get("value"),
    "cold_s": d.get("cold_s"),
    "cold_warm_cache_s": d.get("cold_warm_cache_s"),
    "compile_cold": d.get("compile_cold"),
    "serve_qps": serve.get("qps"),
    "serve_p99_ms": serve.get("latencyMsP99"),
    "serve_plan_cache_hit_ratio": serve.get("planCacheHitRatio"),
    # fleet tracking (PR 18): front-door qps at 1/3 replicas, the
    # kill -9 failover blip, and affinity routing quality
    "fleet_qps_1": (fleet.get("scaling") or {}).get("1", {}).get("qps"),
    "fleet_qps_3": (fleet.get("scaling") or {}).get("3", {}).get("qps"),
    "fleet_p99_ms_3":
        (fleet.get("scaling") or {}).get("3", {}).get("latencyMsP99"),
    "fleet_failover_blip_ms": fleet.get("failoverBlipMs"),
    "fleet_affinity_hit_ratio": fleet.get("affinityHitRatio"),
    # DCN placement tracking (PR 17): q5 at 2x4 host domains must keep
    # cross-host bytes a constant factor below intra-host bytes
    "multihost_dcn_vs_ici": (hosts.get("q5_2x4") or {}).get("dcn_vs_ici"),
    "multihost_dcn_reduction": hosts.get("dcn_reduction_factor"),
    # out-of-core streaming (PR 19): streamed q5 GB/s at a forced
    # window plus the pipeline overlap fraction — the trajectory
    # tracks whether tables >> HBM keep running at link speed
    "streaming_gbps": (d.get("streaming") or {}).get("streamed_gbps"),
    "streaming_overlap":
        (d.get("streaming") or {}).get("overlapFraction"),
    "streaming_window_peak_bytes":
        (d.get("streaming") or {}).get("windowPeakBytes"),
    # transactional writes (PR 20): per-format GB/s through the
    # exactly-once committer and the job-commit publish latency — the
    # trajectory tracks what the two-phase protocol costs
    "write_gbps_parquet":
        ((d.get("write") or {}).get("gbps") or {}).get("parquet"),
    "write_gbps_csv":
        ((d.get("write") or {}).get("gbps") or {}).get("csv"),
    "write_commit_p50_ms": (d.get("write") or {}).get("commit_p50_ms"),
    "write_commit_p99_ms": (d.get("write") or {}).get("commit_p99_ms"),
}
hist = "bench-history.jsonl"
prev = None
if os.path.exists(hist):
    lines = [json.loads(l) for l in open(hist) if l.strip()]
    prev = lines[-1] if lines else None
with open(hist, "a") as f:
    f.write(json.dumps(entry) + "\n")

warm = entry["cold_warm_cache_s"]
if warm is None:
    sys.exit("nightly: cold_warm_cache_s missing from bench JSON "
             "(persistent compile cache probe failed)")
# regression gates: warm-cache cold start must beat the cold compile
# path by 4x (the persistent cache's contract), and must not regress
# >2x against the previous nightly on the same hardware
if entry["cold_s"] and warm > max(entry["cold_s"] / 4.0, 30.0):
    sys.exit(f"nightly: warm-cache cold start {warm}s lost the 4x "
             f"contract vs cold_s={entry['cold_s']}s")
if prev and prev.get("cold_warm_cache_s") and \
        warm > 2.0 * prev["cold_warm_cache_s"] + 5.0:
    sys.exit(f"nightly: warm-cache cold start regressed {warm}s vs "
             f"previous {prev['cold_warm_cache_s']}s")
print(f"nightly: cold_s={entry['cold_s']}s "
      f"cold_warm_cache_s={warm}s (recorded to {hist})")
PY
