#!/usr/bin/env bash
# Static-analysis gate — srtpu-lint (spark_rapids_tpu/tools/lint) must
# pass with ZERO findings on the tree: every spark.rapids.tpu.* conf
# read registered AND documented, no raw time.sleep outside the
# backoff/cancellation primitives, no unyielding blocking waits in
# permit-holding modules, every byte-crossing site telemetry-ledgered,
# every emitted event type schema-registered, no bare excepts.
# The lint unit suite (fixture files per rule, positive + negative)
# runs first so a broken rule can never green-light a dirty tree.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== lint-engine unit suite (per-rule fixtures) =="
python -m pytest tests/test_lint.py -q -p no:cacheprovider

echo "== srtpu-lint over the tree (zero findings required) =="
python -m spark_rapids_tpu.tools.lint

echo "STATIC CHECK PASS"
