#!/usr/bin/env bash
# Event-log gate — the observability subsystem's end-to-end contract:
# a query run with the event log enabled writes a JSONL log in which
# EVERY line validates against the schema (envelope keys +
# schema_version + known event type), the loader reconstructs the
# IDENTICAL span tree the live session built, and the qualification
# report read from the log lists every CPU-fallback operator with the
# same reasons explain_potential_tpu_plan(mode="NOT_ON_TPU") prints.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== event-log schema + round-trip + qualification gate =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import json
import os
import re
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.api.session import TpuSparkSession
import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.explain import explain_potential_tpu_plan
from spark_rapids_tpu.obs import eventlog, report
from spark_rapids_tpu.obs.events import SCHEMA_VERSION, EVENT_TYPES

root = tempfile.mkdtemp(prefix="srtpu_evcheck_")
log_dir = os.path.join(root, "eventlog")
fact_dir = os.path.join(root, "fact")
os.makedirs(fact_dir)
rng = np.random.default_rng(7)
N = 20_000
pq.write_table(pa.table({
    "k": pa.array(rng.integers(0, 50, N), pa.int64()),
    "v": pa.array(rng.random(N) * 100.0),
}), os.path.join(fact_dir, "part-0.parquet"))

s = TpuSparkSession({
    "spark.rapids.tpu.eventLog.enabled": True,
    "spark.rapids.tpu.eventLog.dir": log_dir,
    "spark.sql.shuffle.partitions": 4,
    # a forced CPU fallback so the qualification report is non-trivial
    "spark.rapids.sql.exec.Filter": False,
})
df = (s.read.parquet(fact_dir)
      .filter(F.col("v") > 10.0)
      .repartition(4, "k").groupBy("k")
      .agg(F.sum("v").alias("sv"), F.count("*").alias("n")))
out = df.collect_arrow()
assert out.num_rows > 0
qid = s.last_execution["queryId"]
live = s.obs.last_spans
assert live is not None and live.query_id == qid

# --- 1. every line validates against the schema ---
files = eventlog.log_files(log_dir, qid)
assert files, f"no finalized event log for query {qid} in {log_dir}"
n_lines = 0
for path in files:
    assert not path.endswith(".inprogress")
    with open(path) as f:
        for i, line in enumerate(f, 1):
            ev = json.loads(line)
            errs = eventlog.validate_event(ev)
            assert not errs, f"{path}:{i}: {errs}"
            assert ev["schemaVersion"] == SCHEMA_VERSION
            assert ev["event"] in EVENT_TYPES
            n_lines += 1
assert n_lines > 10, f"suspiciously small log ({n_lines} events)"
print(f"validated {n_lines} events across {len(files)} file(s)")

# --- 2. the loader round-trips into the identical span tree ---
trees = eventlog.load_spans(log_dir, qid)
assert len(trees) == 1, [t.query_id for t in trees]
assert trees[0].to_dict() == live.to_dict(), \
    "loaded span tree differs from the live session's"
print("span-tree round trip identical")

# --- 3. qualification (from the LOG) matches NOT_ON_TPU explain ---
qual_rows = report.qualification_data(log_dir)
assert qual_rows, "qualification report is empty despite a forced " \
    "CPU fallback"
explain_pairs = set()
for line in explain_potential_tpu_plan(
        df, mode="NOT_ON_TPU").splitlines():
    m = re.match(r"\s*(\w+) !NOT_ON_TPU (.+)$", line)
    if m:
        explain_pairs.add((m.group(1), m.group(2)))
qual_pairs = {(r["node"], r["reason"]) for r in qual_rows}
assert qual_pairs == explain_pairs, (qual_pairs, explain_pairs)
print(f"qualification matches NOT_ON_TPU explain "
      f"({len(qual_pairs)} fallback(s))")
print(report.qualification(log_dir))
print(report.profile(log_dir))

# --- 4. two INTERLEAVED queries write isolated per-query logs that
# --- each replay to the identical span tree the live session built ---
import threading

start = threading.Barrier(2)
done = []


def run_one():
    start.wait()
    # no .filter(): the forced Filter fallback above would route these
    # through the per-operator engine, whose cross-query semaphore
    # deadlock predates this gate (two per-operator queries can each
    # hold permits the other needs — concurrency_check.sh covers the
    # governed/fused concurrent path). The fused engine runs these
    # concurrently and still emits full event streams.
    (s.read.parquet(fact_dir)
     .repartition(4, "k").groupBy("k")
     .agg(F.sum("v").alias("sv"), F.count("*").alias("n"))
     ).collect_arrow()


threads = [threading.Thread(target=run_one) for _ in range(2)]
for t in threads:
    t.start()
for t in threads:
    t.join(300)
live_by_qid = {t.query_id: t for t in s.obs.spans.completed}
new_qids = sorted(q for q in live_by_qid if q > qid)[-2:]
assert len(new_qids) == 2, new_qids
for q in new_qids:
    files_q = eventlog.log_files(log_dir, q)
    assert files_q, f"no isolated log for concurrent query {q}"
    for path in files_q:
        with open(path) as f:
            for line in f:
                ev = json.loads(line)
                assert ev["queryId"] == q, (path, ev["queryId"], q)
    trees_q = eventlog.load_spans(log_dir, q)
    assert len(trees_q) == 1, [t.query_id for t in trees_q]
    assert trees_q[0].to_dict() == live_by_qid[q].to_dict(), \
        f"concurrent query {q}: loaded tree differs from live"
print(f"interleaved queries {new_qids} wrote isolated logs; "
      f"round trips identical")
s.stop()
print("EVENTLOG CHECK PASS")
import sys

sys.stdout.flush()
# skip interpreter teardown: XLA's CPU backend can abort in its exit
# handlers after a session cycle (pre-existing, see test_chaos notes);
# every assertion above already ran
os._exit(0)
PY
