#!/usr/bin/env bash
# Concurrency / governance gate — concurrent queries through one
# session with seeded chaos armed and RANDOM CANCELS raining on them,
# asserting the admission-control acceptance contract: every completed
# query is oracle-identical, every cancelled query unwinds within a
# bounded latency, zero spill-catalog buffers and zero semaphore
# permits leak, no admission slot sticks, and over-capacity
# submissions always get a clean QueryRejectedError.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== concurrency stress gate (admission + chaos + cancel storm) =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import os
import random
import tempfile
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime import admission
from spark_rapids_tpu.runtime import semaphore as sem_mod
from spark_rapids_tpu.runtime.errors import (
    QueryCancelledError,
    QueryRejectedError,
)
from spark_rapids_tpu.runtime.memory import get_catalog

CANCEL_LATENCY_BOUND_S = 20.0  # generous CI bound; typical is <0.1s

root = tempfile.mkdtemp(prefix="srtpu_governance_")
rng = np.random.default_rng(3)
N = 60_000
data = os.path.join(root, "fact")
os.makedirs(data)
for i in range(2):
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 64, N // 2), pa.int64()),
        "v": pa.array(rng.random(N // 2) * 100.0),
    }), os.path.join(data, f"p{i}.parquet"))


def build(s):
    fact = s.read.parquet(data)
    return [
        ("sum", fact.groupBy("k").agg(F.sum("v").alias("x"))
         .orderBy("k")),
        ("cnt", fact.filter(F.col("v") > 50.0).groupBy("k")
         .agg(F.count("*").alias("x")).orderBy("k")),
        ("rep", fact.repartition(4, "k").groupBy("k")
         .agg(F.avg("v").alias("x")).orderBy("k")),
        ("top", fact.orderBy("v", ascending=False)
         .select("k", "v").limit(20)),
    ]


# clean oracle
s0 = TpuSparkSession({})
want = {name: df.collect_arrow().to_pydict() for name, df in build(s0)}
s0.stop()

s = TpuSparkSession({
    "spark.rapids.sql.fusedExec.enabled": False,
    "spark.rapids.shuffle.mode": "MULTITHREADED",
    "spark.sql.shuffle.partitions": 4,
    "spark.rapids.sql.reader.batchSizeRows": 8192,
    "spark.rapids.tpu.admission.maxConcurrentQueries": 2,
    "spark.rapids.tpu.admission.queue.maxDepth": 16,
    "spark.rapids.tpu.chaos.enabled": True,
    "spark.rapids.tpu.chaos.seed": 99,
    "spark.rapids.tpu.chaos.sites":
        "io.read:p=0.15;shuffle.fetch:p=0.1;worker.crash:p=0.05;"
        "query.cancel_race:p=0.3;admission.slow_drain:p=0.3",
    "spark.rapids.tpu.stage.maxAttempts": 8,
    "spark.rapids.tpu.io.retry.backoffMs": 1,
    "spark.rapids.tpu.io.retry.maxBackoffMs": 5,
    "spark.rapids.tpu.io.retry.attempts": 6,
})

import math


def same(a, b):
    if set(a) != set(b):
        return False
    for col in a:
        if len(a[col]) != len(b[col]):
            return False
        for x, y in zip(a[col], b[col]):
            if isinstance(x, float) or isinstance(y, float):
                if not math.isclose(x, y, rel_tol=1e-6, abs_tol=1e-8):
                    return False
            elif x != y:
                return False
    return True


queries = build(s)
prng = random.Random(1234)
errors, mismatches, completed, cancelled = [], [], [0], [0]
lock = threading.Lock()


def worker(tid):
    for r in range(3):
        name, df = queries[(tid + r) % len(queries)]
        try:
            got = df.collect_arrow().to_pydict()
            with lock:
                completed[0] += 1
                if not same(got, want[name]):
                    mismatches.append((tid, r, name))
        except QueryCancelledError:
            with lock:
                cancelled[0] += 1
        except QueryRejectedError:
            pass  # shed under burst: the clean over-capacity verdict
        except BaseException as e:
            with lock:
                errors.append((tid, r, name, repr(e)))


threads = [threading.Thread(target=worker, args=(i,)) for i in range(5)]
for t in threads:
    t.start()

# random cancel storm while the fleet runs
storm_deadline = time.monotonic() + 20
while any(t.is_alive() for t in threads) and \
        time.monotonic() < storm_deadline:
    time.sleep(prng.uniform(0.02, 0.12))
    running = s.admission_status()["running"]
    if running and prng.random() < 0.5:
        victim = prng.choice(running)["queryId"]
        t0 = time.monotonic()
        s.cancel(victim, "storm")
for t in threads:
    t.join(180)
assert not any(t.is_alive() for t in threads), "worker hung"

assert not errors, f"unexpected errors: {errors}"
assert not mismatches, f"result mismatches: {mismatches}"
assert completed[0] > 0, "storm cancelled literally everything"

# bounded cancel latency, straight from the admission ledger
snap = admission.stats.snapshot()
assert snap["cancelLatencyMsMax"] <= CANCEL_LATENCY_BOUND_S * 1000, snap

# zero leaked permits, buffers, or admission slots. Cancelled queries
# unwind COOPERATIVELY: a pool attempt may still be releasing its
# permit / closing its parked batches when the last collect returns —
# quiesce briefly, then assert strictly (a true leak still fails).
deadline = time.monotonic() + 10
while (sem_mod.get().holders() or get_catalog().check_leaks()) \
        and time.monotonic() < deadline:
    time.sleep(0.05)
assert sem_mod.get().holders() == 0, "leaked semaphore permits"
get_catalog().check_leaks(raise_on_leak=True)
assert s.admission_status()["running"] == [], "stuck admission slot"
assert s.admission_status()["queued"] == [], "stuck queued query"

# over-capacity verdict is ALWAYS a clean immediate error
ctrl = admission.get()
from spark_rapids_tpu.obs import events as obs_events

hogs = [ctrl.submit(obs_events.allocate_query_id(), description="hog")
        for _ in range(2)]
ctrl.queue_depth = 0
t0 = time.monotonic()
try:
    queries[0][1].collect_arrow()
    raise AssertionError("over-capacity submission was not shed")
except QueryRejectedError as e:
    assert time.monotonic() - t0 < 2.0, "shed was not immediate"
    assert "hog" in str(e), "shed lacks the running-query table"
for h in hogs:
    ctrl.finish(h)

print(f"governance gate: {completed[0]} completed, "
      f"{cancelled[0]} cancelled, "
      f"queueWait p99={snap['queueWaitMsP99']}ms, "
      f"cancelLatency max={snap['cancelLatencyMsMax']}ms")
s.stop()
print("CONCURRENCY PASS")
# XLA's exit-time abort after heavy session cycling is pre-existing
# (see ci/eventlog_check.sh); the gate's verdict is already printed
os._exit(0)
PY

echo "== sanitizer deadlock-recovery gate (per-operator concurrency) =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import os
import tempfile
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime import sanitizer
from spark_rapids_tpu.runtime import semaphore as sem_mod
from spark_rapids_tpu.runtime.errors import DeadlockDetectedError
from spark_rapids_tpu.runtime.memory import get_catalog

root = tempfile.mkdtemp(prefix="srtpu_deadlock_gate_")
fact = os.path.join(root, "fact")
os.makedirs(fact)
rng = np.random.default_rng(7)
N = 20_000
pq.write_table(pa.table({
    "k": pa.array(rng.integers(0, 50, N), pa.int64()),
    "v": pa.array(rng.random(N) * 100.0),
}), os.path.join(fact, "part-0.parquet"))


def run_pair(extra_conf):
    """Two concurrent queries with a forced CPU-fallback Filter +
    repartition — the shape that WEDGED the device semaphore before
    this PR (each query's fused scaffold held a permit chunk while its
    nested per-operator collect starved on the other's). Returns
    (completed, errors); asserts nobody hangs and nothing leaks."""
    s = TpuSparkSession({
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.exec.Filter": False,
        **extra_conf,
    })
    results, errors = [], []

    def worker(i):
        try:
            df = (s.read.parquet(fact)
                  .filter(F.col("v") > 10.0)
                  .repartition(4, "k").groupBy("k")
                  .agg(F.sum("v").alias("sv")))
            results.append((i, df.collect_arrow().num_rows))
        except BaseException as e:
            errors.append((i, e))

    th = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in th:
        t.start()
    for t in th:
        t.join(120)
    assert not any(t.is_alive() for t in th), \
        "DEADLOCK: a per-operator query is still wedged"
    # a deadlock victim unwinds cooperatively — quiesce briefly before
    # the strict zero-leak asserts (a true leak still fails)
    deadline = time.monotonic() + 10
    while (sem_mod.get().holders() or get_catalog().check_leaks()) \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    assert sem_mod.get().holders() == 0, "leaked semaphore permits"
    get_catalog().check_leaks(raise_on_leak=True)
    s.stop()
    return results, errors


# 1. the root fix (atomic per-query permit groups, default on):
#    the historical hang now completes outright
results, errors = run_pair({})
assert not errors, f"atomic-group path errored: {errors}"
assert len(results) == 2, results
print(f"atomic groups: both queries completed ({results})")

# 2. the sanitizer backstop (legacy acquisition + wait-for graph):
#    detected cycle, victim unwound leak-free, then either retried to
#    completion or surfaced as a clean DeadlockDetectedError
results, errors = run_pair({
    "spark.rapids.tpu.semaphore.atomicQueryGroups": False,
    "spark.rapids.tpu.sanitizer.enabled": True,
    # deterministic cycle formation (semaphore.partial_hold widens the
    # hold-and-wait window) — the gate must witness the cycle on every
    # run, not only when compile timing cooperates
    "spark.rapids.tpu.chaos.enabled": True,
    "spark.rapids.tpu.chaos.sites": "semaphore.partial_hold:every=1",
})
for _i, e in errors:
    assert isinstance(e, DeadlockDetectedError), \
        f"unexpected error class: {e!r}"
    assert "wait-for cycle" in str(e), e
assert len(results) + len(errors) == 2 and results, (results, errors)
snap = sanitizer.counters()
assert snap["cycles"] >= 1 and snap["victims"] >= 1, snap
print(f"sanitizer backstop: {len(results)} completed, "
      f"{len(errors)} clean deadlock error(s), "
      f"cycles={snap['cycles']} victims={snap['victims']}")
print("DEADLOCK RECOVERY PASS")
os._exit(0)  # pre-existing XLA exit-time abort after session cycling
PY

echo "== targeted governance suite =="
python -m pytest tests/test_admission.py -q -p no:cacheprovider

echo "== sanitizer + lint suites =="
python -m pytest tests/test_sanitizer.py tests/test_lint.py -q \
    -p no:cacheprovider

echo "CONCURRENCY GATE PASS"
