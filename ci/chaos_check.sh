#!/usr/bin/env bash
# Chaos gate — the *RetrySuite forced-fault strategy applied end to
# end: re-run a fast tier-1 query subset with seeded fault injection
# armed at EVERY site (runtime/faults.py), one site at a time and then
# all together, and assert the results match the clean run (keys
# exactly; float aggregates to 1e-6 relative, since a demotion down
# the engine ladder legitimately changes accumulation order). A query
# that survives chaos by producing WRONG data is the failure mode this
# gate exists to catch.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== chaos equivalence harness (per-site + all-site) =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")
# f64 device math: engine demotions then differ only by summation
# order (~1e-12 relative), so the comparison tolerance can stay tight
jax.config.update("jax_enable_x64", True)

import math
import os
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.api.session import TpuSparkSession
import spark_rapids_tpu.api.functions as F

# --- dataset: small enough to be fast, shaped like the bench (fact +
# --- string dim join + agg), written once per run
root = tempfile.mkdtemp(prefix="srtpu_chaos_")
rng = np.random.default_rng(0)
N, STORES = 40_000, 64
fact_dir = os.path.join(root, "fact")
dim_dir = os.path.join(root, "dim")
os.makedirs(fact_dir), os.makedirs(dim_dir)
for i in range(2):
    pq.write_table(pa.table({
        "store": pa.array(rng.integers(0, STORES, N // 2), pa.int64()),
        "amount": pa.array(rng.random(N // 2) * 100.0),
        "qty": pa.array(rng.integers(1, 50, N // 2), pa.int64()),
    }), os.path.join(fact_dir, f"part-{i}.parquet"))
pq.write_table(pa.table({
    "store": pa.array(np.arange(STORES), pa.int64()),
    "region": pa.array([f"r{i % 7}" for i in range(STORES)]),
}), os.path.join(dim_dir, "dim.parquet"))


def queries(s):
    fact = s.read.parquet(fact_dir)
    dim = s.read.parquet(dim_dir)
    yield ("join_agg", fact.filter(F.col("amount") > 10.0)
           .join(dim, on="store", how="inner")
           .groupBy("region")
           .agg(F.sum("amount").alias("rev"), F.count("*").alias("n")))
    yield ("sort_limit", fact.orderBy("amount", ascending=False)
           .select("store", "amount").limit(50))
    # key repartition forces a REAL shuffle exchange (blocks through
    # the manager), so shuffle.fetch/deserialize sites actually fire
    yield ("repart_agg", fact.repartition(4, "store").groupBy("store")
           .agg(F.avg("qty").alias("aq")).orderBy("store"))


def run_all(conf):
    s = TpuSparkSession(conf)
    try:
        out = {}
        for name, df in queries(s):
            t = df.collect_arrow()
            keys = [c for c, f in zip(t.column_names, t.schema.types)
                    if not pa.types.is_floating(f)]
            out[name] = t.sort_by(
                [(c, "ascending") for c in keys or t.column_names]
            ).to_pydict()
        return out, s.robustness_metrics
    finally:
        s.stop()


def same(a, b):
    """Key columns byte-equal; float columns to 1e-6 relative."""
    if set(a) != set(b):
        return False
    for col in a:
        va, vb = a[col], b[col]
        if len(va) != len(vb):
            return False
        for x, y in zip(va, vb):
            if isinstance(x, float) or isinstance(y, float):
                if not math.isclose(x, y, rel_tol=1e-6, abs_tol=1e-8):
                    return False
            elif x != y:
                return False
    return True


# shuffle-exercising conf: eager engine + MULTITHREADED file shuffle
# so shuffle.fetch/deserialize sites actually fire; a small device
# pool with a ZERO host spill store forces disk-tier spills so the
# spill.disk site fires too
BASE_EAGER = {"spark.rapids.sql.fusedExec.enabled": False,
              "spark.rapids.shuffle.mode": "MULTITHREADED",
              "spark.sql.shuffle.partitions": 4,
              "spark.rapids.sql.reader.batchSizeRows": 4096,
              "spark.rapids.memory.gpu.maxAllocBytes": 4 << 20,
              "spark.rapids.memory.host.spillStorageSize": 0,
              # fast chaos retries: the gate budget is seconds
              "spark.rapids.tpu.io.retry.backoffMs": 1,
              "spark.rapids.tpu.io.retry.maxBackoffMs": 5,
              "spark.rapids.tpu.io.retry.attempts": 6}

baseline, _ = run_all({})
baseline_eager, _ = run_all(BASE_EAGER)

# scheduler-domain sites (PR 3) fire in the eager engine's stage
# scheduler (result + shuffle map stages). worker.crash retries whole
# task attempts and shuffle.lost_output recomputes map tasks, so these
# runs are SLOW-AWARE: the task attempt budget is widened and the
# straggler probability kept low (each injected straggler stalls an
# attempt ~0.2s before speculation's duplicate wins).
SITES = ["io.read:p=0.3", "shuffle.fetch:p=0.3",
         "shuffle.deserialize:p=0.2", "compile.cache_load:every=2",
         "spill.disk:p=0.3", "device.dispatch:once",
         "worker.crash:p=0.2", "task.straggler:p=0.1",
         "shuffle.lost_output:once"]

SCHED_CONF = {"spark.rapids.tpu.stage.maxAttempts": 8,
              "spark.rapids.tpu.speculation.enabled": True,
              "spark.rapids.tpu.speculation.quantile": 0.5,
              "spark.rapids.tpu.speculation.multiplier": 1.3,
              "spark.rapids.tpu.speculation.minTaskRuntimeMs": 40}

failures = 0
for spec in SITES + [";".join(SITES)]:
    label = spec if len(spec) < 40 else "ALL-SITES"
    for base, want in (({}, baseline), (BASE_EAGER, baseline_eager)):
        conf = {**base, **SCHED_CONF,
                "spark.rapids.tpu.chaos.enabled": True,
                "spark.rapids.tpu.chaos.seed": 42,
                "spark.rapids.tpu.chaos.sites": spec,
                "spark.rapids.tpu.io.retry.backoffMs": 1,
                "spark.rapids.tpu.io.retry.maxBackoffMs": 5,
                "spark.rapids.tpu.io.retry.attempts": 6}
        got, robust = run_all(conf)
        mode = "eager" if base else "fused"
        for name in want:
            if not same(got[name], want[name]):
                print(f"FAIL {label} [{mode}] {name}: results differ")
                failures += 1
        inj = sum(v["injected"] for v in robust["chaos"].values())
        sch = {k: v for k, v in robust["scheduler"].items()
               if v and k != "tasksLaunched" and k != "stagesRun"}
        print(f"ok   {label} [{mode}]: {inj} faults injected, "
              f"retries={robust['retries']}, "
              f"sched={sch}, "
              f"degrade={ {k: v for k, v in robust['degrade'].items() if v} }")
assert failures == 0, f"{failures} chaos mismatches"
print("chaos equivalence: PASS")
PY

echo "== targeted fault-injection suite =="
python -m pytest tests/test_chaos.py tests/test_memory_retry.py \
    tests/test_scheduler.py tests/test_scheduler_mp.py -q \
    -p no:cacheprovider

echo "CHAOS PASS"
