#!/usr/bin/env bash
# Multi-host gate — the pod-scale failure-domain contract (PR 17):
# a REAL two-process (gloo) cluster answers q5 oracle-identically with
# plain AND encoded columns; the simulated two-host mesh keeps DCN
# bytes BELOW ICI bytes on an exchange-bearing plan (hierarchical
# placement) and ledgers them as the `dcn` direction; a mid-query
# host.fatal fences the whole host in one epoch step and recovers over
# the survivor host with /readyz 200 throughout (fencedHosts reported,
# capacity-only); a kill -9'd pool worker evicts its WHOLE host group
# atomically and the stage completes oracle-identical on the surviving
# host — all leak-free (permits/buffers, 10s quiesce) and with
# srtpu-lint at zero findings.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

WORK="$(mktemp -d "${TMPDIR:-/tmp}/srtpu_multihost.XXXXXX")"
trap 'rm -rf "$WORK"' EXIT

echo "== multi-host gate 1/2: two-process (gloo) q5, plain + encoded =="
cat > "$WORK/mh_worker.py" <<'PY'
"""Gate worker: one process of a two-host gloo cluster (4 virtual CPU
devices each). Runs q5 (filter -> shuffled join -> group-by) plain and
an encoded group-by, writes results + its DCN/ICI ledger for the
launcher to check."""
import json
import os
import sys
import traceback


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    coord = os.environ["SRTPU_MH_COORD"]
    nproc = int(os.environ["SRTPU_MH_NPROC"])
    pid = int(os.environ["SRTPU_MH_PID"])
    fact_dir, dim_dir, out_dir = sys.argv[1], sys.argv[2], sys.argv[3]

    import pyarrow.parquet as pq

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.parallel import multihost

    spark = TpuSparkSession({
        "spark.rapids.tpu.multihost.coordinator": coord,
        "spark.rapids.tpu.multihost.numProcesses": nproc,
        "spark.rapids.tpu.multihost.processId": pid,
        "spark.sql.shuffle.partitions": 4,
        "spark.sql.autoBroadcastJoinThreshold": -1,
    })
    assert jax.process_count() == nproc, jax.process_count()
    spark.conf.set("spark.rapids.tpu.mesh",
                   multihost.global_device_count())
    try:
        got = (spark.read.parquet(fact_dir)
               .filter(F.col("amount") > 10.0)
               .join(spark.read.parquet(dim_dir), on="store",
                     how="inner")
               .groupBy("region")
               .agg(F.sum("amount").alias("rev"),
                    F.count("*").alias("n"))).collect_arrow()
        rec = dict(spark.last_execution)
        assert rec["engine"] == "mesh", rec
        pq.write_table(got, os.path.join(out_dir,
                                         f"result_{pid}.parquet"))

        # encoded path: per-shard dictionaries reconcile CROSS-PROCESS
        # (content-addressed union over a process allgather)
        got_cat = (spark.read.parquet(fact_dir).groupBy("cat")
                   .agg(F.sum("amount").alias("rev"),
                        F.count("*").alias("n"))).collect_arrow()
        assert spark.last_execution["engine"] == "mesh"
        pq.write_table(got_cat,
                       os.path.join(out_dir, f"result_cat_{pid}.parquet"))

        tel = rec.get("telemetry") or {}
        with open(os.path.join(out_dir, f"ok_{pid}"), "w") as f:
            json.dump({"process": jax.process_index(),
                       "moved": tel.get("bytesMoved") or {},
                       "dcnBytes": tel.get("dcnBytes", 0)}, f)
    finally:
        spark.stop()


if __name__ == "__main__":
    try:
        main()
    except Exception:
        with open(os.path.join(
                sys.argv[3],
                f"err_{os.environ.get('SRTPU_MH_PID', 'x')}"),
                "w") as f:
            f.write(traceback.format_exc())
        raise
PY

python - "$WORK" <<'PY'
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

work = sys.argv[1]
fact_dir = os.path.join(work, "fact")
dim_dir = os.path.join(work, "dim")
out_dir = os.path.join(work, "out")
os.makedirs(fact_dir)
os.makedirs(dim_dir)
os.makedirs(out_dir)

rng = np.random.default_rng(29)
N, FILES, STORES = 24_000, 8, 64
per = N // FILES
parts = []
for i in range(FILES):
    # per-file vocabularies differ: reconciliation must cross hosts
    vocab = [f"f{i}_c{j}" for j in range(4)] + ["shared_x", "shared_y"]
    t = pa.table({
        "cat": pa.array(rng.choice(vocab, per), pa.large_string()),
        "store": pa.array(rng.integers(0, STORES, per), pa.int64()),
        "amount": pa.array(rng.random(per) * 100.0),
    })
    pq.write_table(t, os.path.join(fact_dir, f"part-{i}.parquet"),
                   use_dictionary=["cat"], row_group_size=per)
    parts.append(t)
fact = pa.concat_tables(parts)
dim = pa.table({
    "store": pa.array(np.arange(STORES), pa.int64()),
    "region": pa.array([f"r{i % 7}" for i in range(STORES)],
                       pa.large_string()),
})
pq.write_table(dim, os.path.join(dim_dir, "dim.parquet"),
               use_dictionary=["region"])


def canon(t):
    cols = t.column_names
    return sorted(zip(t.column(cols[0]).to_pylist(),
                      [round(v, 5) for v in
                       t.column(cols[1]).to_pylist()],
                      t.column(cols[2]).to_pylist()))


# pyarrow oracle (no engine code in the checker)
filt = fact.filter(pc.greater(fact.column("amount"), 10.0))
joined = filt.join(dim, keys="store", join_type="inner")
want = canon(joined.group_by("region").aggregate(
    [("amount", "sum"), ("amount", "count")]))
want_cat = canon(fact.group_by("cat").aggregate(
    [("amount", "sum"), ("amount", "count")]))

NPROC = 2
env = dict(os.environ)
env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
env["SRTPU_MH_COORD"] = "localhost:29681"
env["SRTPU_MH_NPROC"] = str(NPROC)
env.pop("JAX_PLATFORMS", None)  # worker forces cpu itself
repo = os.getcwd()
env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

procs = []
for pid in range(NPROC):
    e = dict(env)
    e["SRTPU_MH_PID"] = str(pid)
    procs.append(subprocess.Popen(
        [sys.executable, os.path.join(work, "mh_worker.py"),
         fact_dir, dim_dir, out_dir],
        env=e, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
outs = []
for p in procs:
    try:
        out, _ = p.communicate(timeout=420)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise AssertionError("gloo worker timed out (coordination or "
                             "collective deadlock)")
    outs.append(out.decode(errors="replace"))
for pid, p in enumerate(procs):
    err_file = os.path.join(out_dir, f"err_{pid}")
    if p.returncode != 0 or os.path.exists(err_file):
        err = (open(err_file).read() if os.path.exists(err_file)
               else outs[pid][-4000:])
        raise AssertionError(f"worker {pid} failed "
                             f"(rc={p.returncode}):\n{err}")

import json

for pid in range(NPROC):
    got = canon(pq.read_table(
        os.path.join(out_dir, f"result_{pid}.parquet")))
    assert got == want, f"process {pid}: q5 diverges from oracle"
    got_cat = canon(pq.read_table(
        os.path.join(out_dir, f"result_cat_{pid}.parquet")))
    assert got_cat == want_cat, \
        f"process {pid}: encoded group-by diverges (dictionary " \
        f"reconciliation across processes)"
    stats = json.load(open(os.path.join(out_dir, f"ok_{pid}")))
    print(f"process {pid}: q5 + encoded oracle-identical, "
          f"moved={stats['moved']}")
assert sorted(json.load(open(os.path.join(out_dir, f"ok_{p}")))
              ["process"] for p in range(NPROC)) == [0, 1]
print("two-process (gloo) cluster: PASS")
PY

echo "== multi-host gate 2/2: simulated two-host mesh (in-process) =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import json
import os
import signal
import tempfile
import threading
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.obs.http import ObsHttpServer
from spark_rapids_tpu.runtime import device_monitor as dm
from spark_rapids_tpu.runtime import semaphore as sem_mod
from spark_rapids_tpu.runtime.memory import get_catalog

root = tempfile.mkdtemp(prefix="srtpu_multihost_")
rng = np.random.default_rng(31)
N, FILES, STORES = 48_000, 8, 64
fact_dir = os.path.join(root, "fact")
dim_dir = os.path.join(root, "dim")
os.makedirs(fact_dir)
os.makedirs(dim_dir)
per = N // FILES
for i in range(FILES):
    vocab = [f"f{i}_c{j}" for j in range(4)] + ["shared_x", "shared_y"]
    pq.write_table(pa.table({
        "cat": pa.array(rng.choice(vocab, per), pa.large_string()),
        "store": pa.array(rng.integers(0, STORES, per), pa.int64()),
        "amount": pa.array(rng.random(per) * 100.0),
    }), os.path.join(fact_dir, f"part-{i}.parquet"),
        use_dictionary=["cat"], row_group_size=per)
pq.write_table(pa.table({
    "store": pa.array(np.arange(STORES), pa.int64()),
    "region": pa.array([f"r{i % 7}" for i in range(STORES)],
                       pa.large_string()),
}), os.path.join(dim_dir, "dim.parquet"), use_dictionary=["region"])


def q(s):
    return (s.read.parquet(fact_dir)
            .filter(F.col("amount") > 10.0)
            .join(s.read.parquet(dim_dir), on="store", how="inner")
            .groupBy("region")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n")))


def q_cat(s):
    return (s.read.parquet(fact_dir).groupBy("cat")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n")))


def canon(t):
    cols = t.column_names
    return sorted(zip(t.column(cols[0]).to_pylist(),
                      [round(v, 5) for v in
                       t.column(cols[1]).to_pylist()],
                      t.column(cols[2]).to_pylist()))


def quiesce_clean(label):
    deadline = time.monotonic() + 10.0
    sem = sem_mod.get()
    cat = get_catalog()
    while time.monotonic() < deadline:
        if sem.holders() == 0 and cat.buffer_count() == 0:
            break
        time.sleep(0.05)
    assert sem.holders() == 0, \
        f"{label}: leaked permits: {sem._holder_diagnostics()}"
    cat.check_leaks(raise_on_leak=True)


BASE = {"spark.sql.shuffle.partitions": 4,
        "spark.sql.autoBroadcastJoinThreshold": -1}
MH = {**BASE, "spark.rapids.tpu.mesh": 8,
      "spark.rapids.tpu.multihost.simulatedHosts": 2}

# -------- single-chip oracle --------
s = TpuSparkSession(BASE)
want = canon(q(s).collect_arrow())
want_cat = canon(q_cat(s).collect_arrow())
s.stop()

# -------- 1. 2x4 mesh == single, DCN below ICI, dcn ledgered --------
s = TpuSparkSession(MH)
got = canon(q(s).collect_arrow())
rec = s.last_execution
assert rec["engine"] == "mesh", f"engine={rec['engine']}"
assert got == want, "two-host join+agg diverges from single-chip"
tel = rec.get("telemetry") or {}
moved = tel.get("bytesMoved") or {}
assert moved.get("dcn", 0) > 0, f"no DCN bytes ledgered: {moved}"
assert moved.get("ici", 0) > 0, f"no ICI bytes ledgered: {moved}"
assert moved["dcn"] < moved["ici"], (
    f"DCN-aware placement must keep cross-host bytes below "
    f"intra-host bytes: {moved}")
assert tel.get("dcnBytes") == moved["dcn"], tel
print(f"hierarchical placement: dcn={moved['dcn']}B < "
      f"ici={moved['ici']}B")

got_cat = canon(q_cat(s).collect_arrow())
assert s.last_execution["engine"] == "mesh"
assert got_cat == want_cat, \
    "two-host dictionary reconciliation diverges from single-chip"
print(f"encoded group-by: {len(got_cat)} groups reconciled across "
      f"{FILES} per-shard dictionaries on a 2x4 mesh")
s.stop()
quiesce_clean("two-host-vs-single")

# -------- 2. host.fatal mid-query: survivor remesh, /readyz 200 -----
conf = {**MH,
        "spark.rapids.tpu.obs.http.enabled": True,
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.seed": 7,
        "spark.rapids.tpu.chaos.sites": "host.fatal:once"}
s = TpuSparkSession(conf)
http = ObsHttpServer(s, port=0)
url = f"http://127.0.0.1:{http.port}/readyz"
probe = {"bad": 0, "n": 0, "stop": False}


def probe_loop():
    # capacity-only contract: host loss must NEVER flip readiness
    while not probe["stop"]:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                probe["n"] += 1
                if r.status != 200:
                    probe["bad"] += 1
        except Exception:
            probe["bad"] += 1
        time.sleep(0.01)


th = threading.Thread(target=probe_loop, daemon=True)
th.start()
got = canon(q(s).collect_arrow())
after = dm.counters()
probe["stop"] = True
th.join(timeout=5)
assert got == want, "post-host-loss results diverge"
assert after["hostFences"] == 1, after
assert after["hostRecoveries"] == 1, after
assert after["fences"] == 0, \
    f"host loss escalated to a PROCESS-wide fence: {after}"
kinds = [e["event"] for e in s.obs.history.events()]
assert "host.fence" in kinds and "host.recovery" in kinds, \
    f"missing host fence/recovery events: {sorted(set(kinds))}"
assert probe["n"] > 0 and probe["bad"] == 0, \
    f"/readyz failed during host loss: {probe}"
with urllib.request.urlopen(url, timeout=5) as r:
    body = json.loads(r.read())
assert r.status == 200 and body["ready"] and body["fencedHosts"], \
    f"fenced host must be REPORTED in a still-ready /readyz: {body}"
# the fenced mesh keeps serving new queries over the survivor host
got2 = canon(q(s).collect_arrow())
assert got2 == want and s.last_execution["engine"] == "mesh"
http.close()
s.stop()
quiesce_clean("host-loss")
dm.clear_chip_fences()
print(f"host-loss recovery: oracle-identical over the survivor host "
      f"(hostFences=1, chipEpoch={after['chipEpoch']}), /readyz 200 "
      f"throughout ({probe['n']} probes, fencedHosts={body['fencedHosts']})")

# -------- 3. kill -9 one pool worker: whole host group evicted ------
from spark_rapids_tpu.parallel.process_pool import (
    ProcessBackend,
    ProcessWorkerPool,
    run_scan_agg_fragment,
)
from spark_rapids_tpu.runtime.scheduler import StageScheduler, Task

pp_dir = os.path.join(root, "pp")
os.makedirs(pp_dir)
rng2 = np.random.default_rng(5)
files, tables = [], []
for i in range(8):
    t = pa.table({
        "k": pa.array(rng2.integers(0, 50, 600), pa.int64()),
        "v": pa.array(rng2.random(600)),
    })
    p = os.path.join(pp_dir, f"part-{i}.parquet")
    pq.write_table(t, p)
    files.append(p)
    tables.append(t)
full = pa.concat_tables(tables)
g_all = np.asarray(full.column("k")) % 5
want_pp = {}
for gg, vv in zip(g_all.tolist(), full.column("v").to_pylist()):
    sacc, cacc = want_pp.get(gg, (0.0, 0))
    want_pp[gg] = (sacc + vv, cacc + 1)

FRAG = "spark_rapids_tpu.parallel.process_pool:run_scan_agg_fragment"
pool = ProcessWorkerPool(4, hosts=2, hb_interval_ms=100,
                         hb_timeout_ms=1200)
fenced_cb = []
# the device-monitor glue: heartbeat host death -> fence_host
pool.on_host_death(lambda h: fenced_cb.append(
    dm.fence_host(h, [], cause="heartbeat host loss")))
try:
    assert pool.worker_host("worker-0") == "host0"
    assert pool.host_workers("host0") == ["worker-0", "worker-1"]
    tasks = [Task(i, payload=(FRAG, {
        "files": [f], "keys": ["g"], "derive_mod": ("g", "k", 5),
        "aggs": [("v", "sum"), ("v", "count")], "sleep_s": 0.4}))
        for i, f in enumerate(files)]
    victim_pid = pool.worker_pid("worker-0")

    def killer():
        time.sleep(0.6)
        os.kill(victim_pid, signal.SIGKILL)

    threading.Thread(target=killer, daemon=True).start()
    out = StageScheduler(None, name="mh-kill9",
                         backend=ProcessBackend(pool)).run(tasks)
    merged = pa.concat_tables(out).group_by("g").aggregate(
        [("v_sum", "sum"), ("v_count", "sum")])
    got_pp = {g: (sv, cv) for g, sv, cv in zip(
        merged.column("g").to_pylist(),
        merged.column("v_sum_sum").to_pylist(),
        merged.column("v_count_sum").to_pylist())}
    assert set(got_pp) == set(want_pp)
    for gg, (sv, cv) in want_pp.items():
        assert got_pp[gg][1] == cv, (gg, got_pp[gg], cv)
        np.testing.assert_allclose(got_pp[gg][0], sv, rtol=1e-9)
    # ONE SIGKILL evicted the WHOLE host group (worker-1 was healthy)
    assert pool.evicted_workers() == ["worker-0", "worker-1"], \
        pool.evicted_workers()
    assert sorted(pool.live_workers()) == ["worker-2", "worker-3"]
    assert fenced_cb, "host death never reached the device monitor"
    cnt = dm.counters()
    assert cnt["hostFences"] >= 1 and dm.fenced_hosts() == ["host0"]
finally:
    pool.close()
dm.clear_chip_fences()
print("kill -9 host eviction: oracle-identical on the surviving host "
      f"(evicted={['worker-0', 'worker-1']}, fence glue fired)")

print("MULTIHOST CHECK PASS")
import sys

sys.stdout.flush()
# skip interpreter teardown: XLA's CPU backend can abort in its exit
# handlers after a session cycle (pre-existing, see test_chaos notes)
os._exit(0)
PY

echo "== static gate stays clean (srtpu-lint, zero findings) =="
python -m spark_rapids_tpu.tools.lint

echo "MULTIHOST CHECK PASS"
