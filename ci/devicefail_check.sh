#!/usr/bin/env bash
# Device-failure gate — the warm-recovery contract:
# a seeded mid-query device.fatal on BOTH engines must yield
# oracle-identical results after fence -> epoch bump (exactly once per
# fence) -> backend rebuild -> resubmission, with zero leaked
# permits/buffers, the recovery visible as epoch-tagged obs events,
# stale pre-epoch handles (device.lost_buffer) deterministically
# raising, and srtpu-lint at zero findings.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== device-loss warm-recovery gate =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import os
import tempfile
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.runtime import device_monitor
from spark_rapids_tpu.runtime import semaphore as sem_mod
from spark_rapids_tpu.runtime.memory import get_catalog

root = tempfile.mkdtemp(prefix="srtpu_devfail_")
rng = np.random.default_rng(23)
N, STORES = 40_000, 64
fact_dir = os.path.join(root, "fact")
dim_dir = os.path.join(root, "dim")
os.makedirs(fact_dir)
os.makedirs(dim_dir)
pq.write_table(pa.table({
    "store": pa.array(rng.integers(0, STORES, N), pa.int64()),
    "amount": pa.array(rng.random(N) * 100.0),
}), os.path.join(fact_dir, "part-0.parquet"))
pq.write_table(pa.table({
    "store": pa.array(np.arange(STORES), pa.int64()),
    "region": pa.array([f"r{i % 7}" for i in range(STORES)]),
}), os.path.join(dim_dir, "dim.parquet"))


def q(s):
    return (s.read.parquet(fact_dir)
            .filter(F.col("amount") > 10.0)
            .join(s.read.parquet(dim_dir), on="store", how="inner")
            .repartition(4, "region")
            .groupBy("region")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n")))


def canon(t):
    return sorted(zip(t.column(0).to_pylist(),
                      [round(v, 6) for v in t.column(1).to_pylist()],
                      t.column(2).to_pylist()))


def quiesce_clean(label):
    # cancelled unwinds complete cooperatively; give them a beat
    deadline = time.monotonic() + 10.0
    sem = sem_mod.get()
    cat = get_catalog()
    while time.monotonic() < deadline:
        if sem.holders() == 0 and cat.buffer_count() == 0:
            break
        time.sleep(0.05)
    assert sem.holders() == 0, \
        f"{label}: leaked permits: {sem._holder_diagnostics()}"
    cat.check_leaks(raise_on_leak=True)


BASE = {"spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.reader.batchSizeRows": 4096}

s = TpuSparkSession(BASE)
want = canon(q(s).collect_arrow())
s.stop()

for fused in (True, False):
    name = "fused" if fused else "per-operator"
    # device.lost_buffer fires at spill-catalog registration, which
    # this query shape only exercises on the per-operator engine (the
    # fused engine keeps its parts as plain device batches)
    sites = ("device.fatal",) if fused else \
        ("device.fatal", "device.lost_buffer")
    for site in sites:
        conf = {**BASE,
                "spark.rapids.tpu.chaos.enabled": True,
                "spark.rapids.tpu.chaos.seed": 7,
                "spark.rapids.tpu.chaos.sites": f"{site}:once"}
        if not fused:
            conf["spark.rapids.sql.fusedExec.enabled"] = False
        s = TpuSparkSession(conf)
        mon = device_monitor.get()
        before = mon.counters()
        got = canon(q(s).collect_arrow())
        after = mon.counters()
        assert got == want, f"{name}/{site}: results diverge"
        fences = after["fences"] - before["fences"]
        bumps = after["epoch"] - before["epoch"]
        assert bumps == fences, (
            f"{name}/{site}: epoch must bump exactly once per fence "
            f"({bumps} bumps over {fences} fences)")
        if site == "device.fatal":
            assert fences == 1 and after["recoveries"] > \
                before["recoveries"], f"{name}/{site}: no recovery ran"
            evs = s.obs.history.events()
            kinds = [e["event"] for e in evs]
            for k in ("device.fatal", "device.fence",
                      "device.recovery"):
                assert k in kinds, f"{name}/{site}: missing {k} event"
            rec = [e for e in evs if e["event"] == "device.recovery"][-1]
            assert rec["epoch"] == after["epoch"]
        else:
            assert after["staleHandles"] > before["staleHandles"], (
                f"{name}/{site}: stale handle never raised")
        assert not mon.fenced, f"{name}/{site}: fence never lifted"
        quiesce_clean(f"{name}/{site}")
        s.stop()
        print(f"{name}/{site}: identical results after recovery "
              f"(fences={fences}, epoch={after['epoch']}, "
              f"resubmits={after['resubmits'] - before['resubmits']})")

print("DEVICEFAIL CHECK PASS")
import sys

sys.stdout.flush()
# skip interpreter teardown: XLA's CPU backend can abort in its exit
# handlers after a session cycle (pre-existing, see test_chaos notes)
os._exit(0)
PY

echo "== static gate stays clean (srtpu-lint, zero findings) =="
python -m spark_rapids_tpu.tools.lint

echo "DEVICEFAIL CHECK PASS"
