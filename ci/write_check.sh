#!/usr/bin/env bash
# Exactly-once write gate — the transactional commit protocol's
# contract (io/commit.py): with chaos armed at every write site a
# polling reader never observes a partial or uncommitted file, retried
# jobs land oracle-identical output, an overwrite that dies mid-job
# leaves the prior data byte-identical, a kill -9'd process writer's
# re-attempt publishes exactly once, two concurrent Delta appenders
# both commit under the optimistic-transaction loop, staging is
# leak-free after quiesce, and srtpu-lint stays at zero findings.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== chaos-armed writes: reader never sees partials, output oracle-identical =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import glob
import os
import tempfile
import threading
import time

import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.io import commit as iocommit

root = tempfile.mkdtemp(prefix="srtpu_writecheck_")
N = 5_000
oracle = pa.table({
    "a": pa.array(range(N), pa.int64()),
    "s": pa.array([f"g{i % 13}" for i in range(N)]),
})


def no_debris(path):
    bad = [f for f in glob.glob(os.path.join(path, "**", "*"),
                                recursive=True)
           if iocommit.TEMP_DIR in f or ".__new-" in f
           or ".__old-" in f or ".inprogress-" in f]
    assert not bad, f"staging debris after quiesce: {bad}"


class PollingReader(threading.Thread):
    """The acceptance oracle: while a (chaos-ridden) write runs, every
    visible data file must parse COMPLETELY, and whenever _SUCCESS is
    present the directory must validate against it. Stops on flag."""

    def __init__(self, path):
        super().__init__(daemon=True)
        self.path = path
        self.stop = threading.Event()
        self.polls = 0
        self.errors = []

    def run(self):
        while not self.stop.is_set():
            self.polls += 1
            try:
                for f in sorted(glob.glob(
                        os.path.join(self.path, "**", "*.parquet"),
                        recursive=True)):
                    rel = os.path.relpath(f, self.path)
                    if any(seg.startswith(("_", "."))
                           for seg in rel.split(os.sep)):
                        continue  # hidden = not reader-visible
                    pq.read_table(f)  # a partial file would not parse
                if iocommit.read_manifest(self.path) is not None:
                    iocommit.validate_output(self.path)
            except FileNotFoundError:
                pass  # the overwrite swap's one tolerated window
            except BaseException as e:
                self.errors.append(repr(e))
            time.sleep(0.002)


# four chaos sites armed together; every write must still publish
# exactly-once output (faults absorbed by the backoff/OCC loops)
CHAOS = ("io.write:every=5;commit.task:every=3;"
         "commit.job:once;commit.conflict:once")
spark = TpuSparkSession({
    "spark.rapids.tpu.chaos.enabled": "true",
    "spark.rapids.tpu.chaos.sites": CHAOS,
    "spark.rapids.tpu.chaos.seed": "11",
    "spark.rapids.tpu.io.retry.backoffMs": "1",
    "spark.rapids.tpu.io.retry.maxBackoffMs": "4",
    "spark.rapids.tpu.write.tasks": "4",
})
df = spark.createDataFrame(oracle)

for fmt in ("parquet", "orc", "csv", "json", "avro", "hivetext"):
    p = os.path.join(root, fmt)
    reader = PollingReader(p) if fmt == "parquet" else None
    if reader:
        reader.start()
    stats = df.write.format(fmt).save(p)
    if reader:
        time.sleep(0.05)
        reader.stop.set()
        reader.join(timeout=5)
        assert reader.polls > 0
        assert not reader.errors, reader.errors[:3]
    assert stats.num_rows == N, (fmt, stats.num_rows)
    assert iocommit.validate_output(p) >= 1, fmt
back = spark.read.parquet(os.path.join(root, "parquet")).collect_arrow()
assert back.num_rows == N
assert sorted(back.column("a").to_pylist()) == list(range(N))
no_debris(root)
print(f"6 formats under chaos [{CHAOS}]: oracle-identical, "
      f"no reader-visible partials, no staging debris")

# retried job (commit.job fault absorbed) is oracle-identical: rerun
# parquet with a fresh dir and a poll loop racing the whole job
p2 = os.path.join(root, "retried")
reader = PollingReader(p2)
reader.start()
df.write.parquet(p2)
reader.stop.set()
reader.join(timeout=5)
assert not reader.errors, reader.errors[:3]
back = spark.read.parquet(p2).collect_arrow()
assert sorted(back.column("a").to_pylist()) == list(range(N))
print(f"retried job oracle-identical over {reader.polls} reader polls")
spark.stop()
print("CHAOS WRITE DRILL PASS")
import sys

sys.stdout.flush()
os._exit(0)
PY

echo "== overwrite + injected job failure: prior data byte-identical =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import glob
import os
import tempfile
import zlib

import pyarrow as pa

from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.io import commit as iocommit
from spark_rapids_tpu.runtime.errors import RetryExhausted


def tree(path):
    out = {}
    for f in sorted(glob.glob(os.path.join(path, "**", "*"),
                              recursive=True)):
        if os.path.isfile(f):
            rel = os.path.relpath(f, path)
            out[rel] = zlib.crc32(open(f, "rb").read())
    return out


root = tempfile.mkdtemp(prefix="srtpu_writecheck_ow_")
p = os.path.join(root, "t")
good = TpuSparkSession({})
good.createDataFrame(pa.table({"a": list(range(1000))})).write.parquet(p)
good.stop()
before = tree(p)
assert before

bad = TpuSparkSession({
    "spark.rapids.tpu.chaos.enabled": "true",
    "spark.rapids.tpu.chaos.sites": "commit.job:p=1.0",
    "spark.rapids.tpu.io.retry.backoffMs": "1",
    "spark.rapids.tpu.io.retry.maxBackoffMs": "4",
})
try:
    bad.createDataFrame(pa.table({"a": [1]})).write.mode(
        "overwrite").parquet(p)
    raise SystemExit("overwrite should have failed under commit.job chaos")
except RetryExhausted:
    pass
bad.stop()
assert tree(p) == before, "prior output not byte-identical after failed overwrite"
swept = iocommit.sweep_orphans(p, ttl_s=0.0)
assert tree(p) == before
back = TpuSparkSession({})
assert back.read.parquet(p).collect_arrow().num_rows == 1000
back.stop()
print(f"failed overwrite: {len(before)} files byte-identical "
      f"(sweep reclaimed {swept} orphan dirs)")
import sys

sys.stdout.flush()
os._exit(0)
PY

echo "== kill -9 mid-job drill: re-attempt publishes exactly once =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import os
import signal
import tempfile
import threading
import time

import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.io import commit as iocommit
from spark_rapids_tpu.parallel.process_pool import (
    ProcessBackend,
    ProcessWorkerPool,
)
from spark_rapids_tpu.runtime.scheduler import StageScheduler, Task

root = tempfile.mkdtemp(prefix="srtpu_writecheck_k9_")
src = os.path.join(root, "src.parquet")
N, TASKS = 2_400, 8
STEP = N // TASKS
table = pa.table({"a": pa.array(range(N), pa.int64())})
pq.write_table(table, src)
out = os.path.join(root, "out")
committer = iocommit.JobCommitter(out, mode="error", fmt="parquet")
assert committer.setup_job()
FRAG = "spark_rapids_tpu.io.commit:run_write_fragment"
specs = [{"fmt": "parquet", "src": src, "offset": i * STEP,
          "count": STEP, "staging": committer.staging, "task": i,
          "file_tag": committer.job_id, "sleep_s": 0.4}
         for i in range(TASKS)]
pool = ProcessWorkerPool(3, hb_interval_ms=100, hb_timeout_ms=1200)
try:
    tasks = [Task(i, payload=(FRAG, specs[i]),
                  commit=lambda res, att, i=i: committer.commit_task(i, res),
                  abort=lambda att: None)
             for i in range(TASKS)]
    pid = pool.worker_pid("worker-0")
    threading.Timer(0.6, lambda: os.kill(pid, signal.SIGKILL)).start()
    StageScheduler(None, name="write-k9",
                   backend=ProcessBackend(pool)).run(tasks)
    manifest = committer.commit_job()
finally:
    pool.close()
assert len(manifest["files"]) == TASKS
assert iocommit.validate_output(out) == TASKS
back = pq.read_table(out)
assert back.num_rows == N, back.num_rows
assert sorted(back.column("a").to_pylist()) == list(range(N))
import glob

bad = [f for f in glob.glob(os.path.join(root, "**", "*"), recursive=True)
       if iocommit.TEMP_DIR in f or ".inprogress-" in f]
assert not bad, bad
print(f"kill -9 mid-job: {TASKS} tasks re-attempted to exactly-once "
      f"output ({N} rows, manifest-validated, no debris)")
import sys

sys.stdout.flush()
os._exit(0)
PY

echo "== concurrent Delta appenders: both optimistic commits land =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import os
import tempfile
import threading

import pyarrow as pa

from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.io import commit as iocommit
from spark_rapids_tpu.lakehouse.delta import _list_versions

root = tempfile.mkdtemp(prefix="srtpu_writecheck_delta_")
p = os.path.join(root, "t")
spark = TpuSparkSession({"spark.rapids.tpu.io.retry.backoffMs": "1",
                         "spark.rapids.tpu.io.retry.maxBackoffMs": "4"})


def mk(n, tag):
    return spark.createDataFrame(pa.table({
        "a": pa.array(range(n), pa.int64()),
        "w": pa.array([tag] * n)}))


mk(10, "seed").write.format("delta").save(p)
barrier = threading.Barrier(2)
errs = []


def appender(n, tag):
    try:
        df = mk(n, tag)
        barrier.wait(timeout=10)
        df.write.format("delta").mode("append").save(p)
    except BaseException as e:
        errs.append(repr(e))


ts = [threading.Thread(target=appender, args=(20, "w1")),
      threading.Thread(target=appender, args=(30, "w2"))]
for t in ts:
    t.start()
for t in ts:
    t.join(timeout=60)
assert not errs, errs
back = spark.read.delta(p).collect_arrow()
assert back.num_rows == 60, back.num_rows  # 10 + 20 + 30, nothing lost
assert _list_versions(p) == [0, 1, 2]
conflicts = iocommit.write_totals()["conflicts"]
assert conflicts >= 1, "appenders never actually raced"
spark.stop()
print(f"2 concurrent appenders both landed (versions 0..2, "
      f"{conflicts} optimistic conflict retry)")
import sys

sys.stdout.flush()
os._exit(0)
PY

echo "== static gate stays clean (srtpu-lint, zero findings) =="
python -m spark_rapids_tpu.tools.lint

echo "WRITE CHECK PASS"
