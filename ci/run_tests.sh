#!/usr/bin/env bash
# Premerge gate — the jenkins/spark-premerge-build.sh role.
# Runs the suite on the virtual 8-device CPU mesh (no hardware needed),
# then the driver-facing entry points, mirroring what the round driver
# checks: tests green, dryrun compiles+executes, bench emits its JSON.
set -euo pipefail
cd "$(dirname "$0")/.."

export XLA_FLAGS="--xla_force_host_platform_device_count=8"
export JAX_PLATFORMS=cpu

echo "== static-analysis gate (srtpu-lint, zero findings) =="
ci/static_check.sh

echo "== unit + differential suite (virtual 8-device mesh) =="
python -m pytest tests/ -q

echo "== chaos gate (seeded fault injection at every site) =="
ci/chaos_check.sh

echo "== event-log gate (schema, round-trip, qualification) =="
ci/eventlog_check.sh

echo "== concurrency gate (admission + chaos + cancel storm) =="
ci/concurrency_check.sh

echo "== telemetry gate (ledger/eventlog consistency + HTTP) =="
ci/telemetry_check.sh

echo "== encoded-execution gate (bytes moved + oracle equality) =="
ci/encoded_check.sh

echo "== streaming gate (out-of-core window + overlap + chaos) =="
ci/streaming_check.sh

echo "== write gate (exactly-once commit + crash-safe overwrite + Delta OCC) =="
ci/write_check.sh

echo "== device-failure gate (fence + warm recovery + epoch) =="
ci/devicefail_check.sh

echo "== multichip gate (SPMD oracle + ICI bytes + chip loss) =="
ci/multichip_check.sh

echo "== multi-host gate (gloo cluster + DCN placement + host loss) =="
ci/multihost_check.sh

echo "== serving gate (multi-tenant daemon + plan cache + drain) =="
ci/serve_check.sh

echo "== fleet gate (replica supervisor + front door + failover) =="
ci/fleet_check.sh

echo "== multichip dryrun (virtual mesh) =="
SPARK_RAPIDS_TPU_DRYRUN_REEXEC=1 python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__
__graft_entry__.dryrun_multichip(8)
PY

echo "== packaging =="
python -m spark_rapids_tpu.tools.package_dist --check 2>/dev/null || \
    python -c "import spark_rapids_tpu; print('import ok')"

echo "CI PASS"
