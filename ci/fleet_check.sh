#!/usr/bin/env bash
# Fleet gate — the fault-tolerant serving fleet under chaos.
# A 3-replica fleet (process-per-replica supervisor) behind the
# health-routed front door serves a 3-tenant closed-loop soak. The
# acceptance contract, in three phases:
#   1. calm soak: every result oracle-identical, and per-tenant
#      billing reconciles EXACTLY across the replica ledgers — each
#      completed query billed once, on exactly one replica; the
#      idempotency window proves a resubmitted requestId replays
#      without re-executing or re-billing.
#   2. chaos soak: kill -9 a ready replica mid-soak — queries shed to
#      the survivors transparently (ZERO client-visible failures),
#      results stay oracle-identical, and the supervisor crash-loops
#      the victim back to ready.
#   3. rolling restart drill: restart every replica one at a time
#      under live traffic — zero failed queries, and the router's
#      plan-cache affinity keeps a repeated spec pinned to one
#      replica (hit ratio strictly above the 1/N random baseline).
# Ends leak-free: zero router connections/threads, every replica
# process reaped, then the fleet pytest suite.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== fleet soak (3 replicas x 3 tenants + kill -9 + rolling restart) =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import json
import math
import os
import random
import tempfile
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.api.session import TpuSparkSession
from spark_rapids_tpu.serve.client import ServeClient
from spark_rapids_tpu.serve.plan_cache import affinity_key
from spark_rapids_tpu.serve.router import FleetRouter
from spark_rapids_tpu.serve.supervisor import ReplicaSupervisor

root = tempfile.mkdtemp(prefix="srtpu_fleet_gate_")
rng = np.random.default_rng(23)
N = 20_000
data = os.path.join(root, "fact")
os.makedirs(data)
pq.write_table(pa.table({
    "k": pa.array(rng.integers(0, 32, N), pa.int64()),
    "v": pa.array(rng.random(N) * 100.0),
}), os.path.join(data, "p0.parquet"))

SPECS = {
    "sum": {"op": "orderBy",
            "input": {"op": "agg",
                      "input": {"op": "parquet", "path": data},
                      "groupBy": ["k"],
                      "aggs": [{"fn": "sum", "col": "v", "as": "x"}]},
            "keys": ["k"]},
    "cnt": {"op": "orderBy",
            "input": {"op": "agg",
                      "input": {"op": "filter",
                                "input": {"op": "parquet",
                                          "path": data},
                                "cond": {"fn": ">",
                                         "args": [{"col": "v"},
                                                  {"param": "lo"}]}},
                      "groupBy": ["k"],
                      "aggs": [{"fn": "count", "col": "*",
                                "as": "x"}]},
            "keys": ["k"]},
}
PARAMS = {"cnt": [{"lo": 25.0}, {"lo": 75.0}]}


def bindings(name):
    return PARAMS.get(name, [None])


def same(a, b):
    if set(a) != set(b):
        return False
    for col in a:
        if len(a[col]) != len(b[col]):
            return False
        for x, y in zip(a[col], b[col]):
            if isinstance(x, float) or isinstance(y, float):
                if not math.isclose(x, y, rel_tol=1e-6, abs_tol=1e-8):
                    return False
            elif x != y:
                return False
    return True


# --- oracle: the SAME specs through an embedded session ---
from spark_rapids_tpu.serve.spec import compile_spec

s0 = TpuSparkSession({})
want = {}
for name in SPECS:
    for p in bindings(name):
        want[(name, json.dumps(p))] = compile_spec(
            SPECS[name], s0, p or {}).collect_arrow().to_pydict()
s0.stop()

# --- the fleet: 3 replica processes behind the front door ---
REPLICA_CONF = {"spark.sql.shuffle.partitions": 4}
sup = ReplicaSupervisor(conf={}, replica_confs=[dict(REPLICA_CONF)
                                                for _ in range(3)])
sup.start()
eps = sup.wait_ready(timeout_ms=300_000)
assert len(eps) == 3, eps
rtr = FleetRouter(
    supervisor=sup,
    conf={"spark.rapids.tpu.fleet.health.intervalMs": 100,
          "spark.rapids.tpu.fleet.failover.maxAttempts": 6}).start()
deadline = time.monotonic() + 30
while time.monotonic() < deadline and \
        len(rtr.health()["routable"]) < 3:
    time.sleep(0.1)
assert len(rtr.health()["routable"]) == 3, rtr.health()

# fleet observability actually flows: srtpu_fleet_* on the prom surface
from spark_rapids_tpu.obs import prom

text = prom.render(None)
assert "srtpu_fleet_router_replicas" in text, text[:400]
assert "srtpu_fleet_supervisor_spawns" in text, text[:400]

TENANTS = ["acme", "globex", "initech"]
errors, mismatches = [], []
completed = {t: 0 for t in TENANTS}
lock = threading.Lock()
rid_seq = [0]


def worker(tenant, rounds, seed, phase):
    prng = random.Random(seed)
    try:
        with ServeClient("127.0.0.1", rtr.port, tenant,
                         connect_attempts=10) as c:
            for _ in range(rounds):
                name = prng.choice(sorted(SPECS))
                p = prng.choice(bindings(name))
                with lock:
                    rid_seq[0] += 1
                    rid = f"{phase}-{tenant}-{rid_seq[0]}"
                got = c.query(SPECS[name], params=p, request_id=rid,
                              timeout_ms=120_000)
                with lock:
                    completed[tenant] += 1
                    if not same(got.to_pydict(),
                                want[(name, json.dumps(p))]):
                        mismatches.append((tenant, name, p))
    except BaseException as e:
        with lock:
            errors.append((tenant, repr(e)))


def run_phase(phase, rounds, chaos=None):
    threads = [threading.Thread(target=worker,
                                args=(t, rounds, i + hash(phase) % 97,
                                      phase))
               for i, t in enumerate(TENANTS)
               for _ in range(2)]
    for t in threads:
        t.start()
    if chaos is not None:
        chaos()
    for t in threads:
        t.join(300)
    assert not any(t.is_alive() for t in threads), \
        f"{phase}: fleet worker hung"
    assert not errors, f"{phase}: client-visible failures: {errors}"
    assert not mismatches, f"{phase}: result mismatch: {mismatches}"


# ---- phase 1: calm soak, then billing reconciliation ----
run_phase("calm", rounds=4)
ledgers = {}
for ep in sup.endpoints():
    with ServeClient(ep["host"], ep["port"], "auditor") as a:
        ledgers[ep["name"]] = a.status()["tenants"]
for t in TENANTS:
    billed = sum(led.get(t, {}).get("queries", 0)
                 for led in ledgers.values())
    assert billed == completed[t], \
        f"billing skew for {t}: {billed} billed vs " \
        f"{completed[t]} completed ({ledgers})"
print(f"fleet calm phase: {dict(completed)} completed, billing "
      f"reconciles across {len(ledgers)} replica ledgers")

# ---- idempotency: a resubmitted requestId replays, never re-executes
with ServeClient("127.0.0.1", rtr.port, "acme") as c:
    t1 = c.query(SPECS["sum"], request_id="idem-ci")
    first = dict(c.last_result)
    t2 = c.query(SPECS["sum"], request_id="idem-ci")
    assert c.last_result.get("dedupe") is True, c.last_result
    assert c.last_result["replica"] == first["replica"]
    assert t2.to_pydict() == t1.to_pydict()
with ServeClient("127.0.0.1",
                 [e for e in sup.endpoints()
                  if e["name"] == first["replica"]][0]["port"],
                 "auditor") as a:
    st = a.status()
    assert st["dedupe"]["replays"] >= 1, st["dedupe"]
    assert st["tenants"]["acme"]["queries"] == \
        ledgers[first["replica"]].get("acme", {}).get("queries", 0) \
        + 1, "dedupe replay was billed"
print("fleet idempotency: replayed once, billed once")

# ---- phase 2: kill -9 a ready replica mid-soak ----
victims = [0]


def kill_one():
    time.sleep(0.3)
    name = sup.endpoints()[0]["name"]
    assert sup.kill(name)
    victims[0] += 1
    print(f"fleet chaos: kill -9 {name} mid-soak")


run_phase("chaos", rounds=6, chaos=kill_one)
deadline = time.monotonic() + 300
while time.monotonic() < deadline and len(sup.endpoints()) < 3:
    time.sleep(0.2)
assert len(sup.endpoints()) == 3, sup.stats_snapshot()
assert sup.stats_snapshot()["restarts"] >= 1, sup.stats_snapshot()
print(f"fleet chaos phase: {dict(completed)} completed, zero "
      f"client-visible failures, victim crash-looped back "
      f"(router: {rtr.stats_snapshot()})")

# ---- phase 3: rolling restart drill under live traffic ----
# affinity first: a repeated spec must pin to its rendezvous replica
hits = {}
with ServeClient("127.0.0.1", rtr.port, "acme") as c:
    for i in range(12):
        c.query(SPECS["cnt"], params={"lo": 25.0},
                request_id=f"aff-{i}")
        hits[c.last_result["replica"]] = \
            hits.get(c.last_result["replica"], 0) + 1
ratio = max(hits.values()) / sum(hits.values())
assert ratio > 1.0 / 3.0 + 0.2, \
    f"affinity hit ratio {ratio} not above the random baseline ({hits})"

drill_done = threading.Event()


def drill():
    try:
        for ep in list(sup.endpoints()):
            sup.restart_replica(ep["name"], timeout_ms=300_000)
    finally:
        drill_done.set()


d = threading.Thread(target=drill)
d.start()
while not drill_done.is_set():
    run_phase("drill", rounds=2)
d.join(600)
assert not d.is_alive(), "rolling restart drill hung"
assert len(sup.endpoints()) == 3
print(f"fleet drill phase: {dict(completed)} completed, rolling "
      f"restart with zero failures, affinity hit ratio {ratio:.2f} "
      f"(random baseline 0.33)")

# ---- teardown: leak-free ----
rtr.stop()
sup.stop()
leaks = rtr.leak_report()
assert leaks == {"connections": 0, "handlerThreads": 0,
                 "listener": 0}, leaks
for r in sup._replicas:
    assert r.proc is not None and r.proc.poll() is not None, \
        f"leaked replica process {r.name}"
assert not [t for t in threading.enumerate()
            if t.name.startswith("srtpu-fleet")], "leaked thread"
print("FLEET SOAK PASS")
os._exit(0)  # pre-existing XLA exit-time abort after session cycling
PY

echo "== fleet suite (router + supervisor + dedupe + escalation) =="
python -m pytest tests/test_fleet.py -q -p no:cacheprovider

echo "FLEET GATE PASS"
