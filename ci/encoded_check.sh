#!/usr/bin/env bash
# Encoded-execution gate — the compressed-execution contract:
# on a dictionary dataset the encoded path must move STRICTLY fewer
# H2D+shuffle bytes than the plain path (PR 6 transfer ledger) while
# producing byte-identical results on BOTH engines, report
# bytesSavedEncoded / effectiveCompressionRatio, keep encoding across
# a spill round-trip, and leave srtpu-lint at zero findings.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

echo "== encoded-vs-plain equality + bytes-moved gate =="
python - <<'PY'
import jax

jax.config.update("jax_platforms", "cpu")

import os
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import spark_rapids_tpu.api.functions as F
from spark_rapids_tpu.api.session import TpuSparkSession

root = tempfile.mkdtemp(prefix="srtpu_enccheck_")
fact_dir = os.path.join(root, "fact")
dim_dir = os.path.join(root, "dim")
os.makedirs(fact_dir)
os.makedirs(dim_dir)
rng = np.random.default_rng(17)
N, STORES, REGIONS = 60_000, 400, 9
pq.write_table(pa.table({
    "store": pa.array(rng.integers(0, STORES, N), pa.int64()),
    "amount": pa.array(rng.random(N) * 100.0),
}), os.path.join(fact_dir, "part-0.parquet"))
pq.write_table(pa.table({
    "store": pa.array(np.arange(STORES), pa.int64()),
    "region": pa.array(
        [None if i % 23 == 0 else f"region_{i % REGIONS:02d}"
         for i in range(STORES)]),
}), os.path.join(dim_dir, "dim-0.parquet"), use_dictionary=True)


def q(s):
    # the q5 shape with a forced string-column shuffle so the encoded
    # wire format is exercised, not just the upload
    return (s.read.parquet(fact_dir)
            .filter(F.col("amount") > 15.0)
            .join(s.read.parquet(dim_dir), on="store", how="inner")
            .filter(F.col("region") != "region_04")
            .repartition(4, "region")
            .groupBy("region")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n")))


def canon(t):
    return sorted(
        zip(t.column(0).to_pylist(),
            [round(v, 4) for v in t.column(1).to_pylist()],
            t.column(2).to_pylist()),
        key=lambda r: (r[0] is None, r[0]))


def run(engine_fused: bool, encoded: bool):
    conf = {"spark.sql.shuffle.partitions": 4,
            "spark.rapids.tpu.encoded.enabled": encoded}
    if not engine_fused:
        conf["spark.rapids.sql.fusedExec.enabled"] = False
    s = TpuSparkSession(conf)
    out = q(s).collect_arrow()
    tel = (s.last_execution or {}).get("telemetry") or {}
    moved = tel.get("bytesMoved") or {}
    s.stop()
    return canon(out), {
        "h2d": moved.get("h2d", 0),
        "shuffle": moved.get("shuffle", 0),
        "saved": tel.get("bytesSavedEncoded", 0),
        "ecr": tel.get("effectiveCompressionRatio"),
    }


for engine in (True, False):
    name = "fused" if engine else "per-operator"
    rows_enc, enc = run(engine, True)
    rows_plain, plain = run(engine, False)
    assert rows_enc == rows_plain, (
        f"{name}: encoded and plain results differ")
    enc_link = enc["h2d"] + enc["shuffle"]
    plain_link = plain["h2d"] + plain["shuffle"]
    assert enc_link < plain_link, (
        f"{name}: encoded path must move strictly fewer H2D+shuffle "
        f"bytes ({enc_link} vs {plain_link})")
    assert enc["saved"] > 0, f"{name}: bytesSavedEncoded missing"
    assert enc["ecr"] and enc["ecr"] > 1.0, (
        f"{name}: effectiveCompressionRatio missing")
    print(f"{name}: identical results; H2D+shuffle {plain_link} -> "
          f"{enc_link} B ({plain_link / max(enc_link, 1):.2f}x), "
          f"saved {enc['saved']} B, ratio {enc['ecr']}")

# spill round-trip preserves the encoding
from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
from spark_rapids_tpu.exec.fused import upload_narrowed
from spark_rapids_tpu.runtime.memory import get_catalog

vals = ["alpha", None, "beta", "alpha"]
b = upload_narrowed(pa.table({"s": pa.array(vals).dictionary_encode()}))
did = b.columns[0].encoding.dict_id
catalog = get_catalog()
sb = catalog.add_batch(b)
with catalog._lock:
    sb._to_host()
    sb._to_disk()
back = sb.get_batch()
assert back.columns[0].is_encoded
assert back.columns[0].encoding.dict_id == did
assert device_to_arrow(back).column("s").to_pylist() == vals
sb.close()
print("spill/unspill preserves dictionary encoding")
print("ENCODED CHECK PASS")
import sys

sys.stdout.flush()
# skip interpreter teardown: XLA's CPU backend can abort in its exit
# handlers after a session cycle (pre-existing, see test_chaos notes)
os._exit(0)
PY

echo "== static gate stays clean (srtpu-lint, zero findings) =="
python -m spark_rapids_tpu.tools.lint
