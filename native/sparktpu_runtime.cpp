// sparktpu native host runtime — the C++ layer the reference gets from
// cuDF-Java/spark-rapids-jni (SURVEY.md section 2.12), re-provided for the
// TPU engine's HOST side (device compute is XLA):
//
// - columnar wire format pack/unpack (JCudfSerialization analog,
//   reference GpuColumnarBatchSerializer.scala:82,170): N raw buffers ->
//   one contiguous 64-byte-aligned framed buffer, and back.
// - spark-exact Murmur3_x86_32 and XXH64 batch hashing over typed column
//   arrays (the JNI `Hash` kernel analog) for host-side partitioning that
//   bit-agrees with the device kernels in ops/hashing.py.
// - fixed-width row<->column transpose (the JNI `RowConversion` analog,
//   reference InternalRowToColumnarBatchIterator.java / CudfUnsafeRow).
// - a bounded host buffer pool with freelist reuse + stats (HostAlloc
//   analog, reference HostAlloc.scala).
//
// Pure C++17, no dependencies; built by spark_rapids_tpu/native/__init__.py
// with g++ -O3 and loaded via ctypes.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- wire format

static const uint64_t STPU_MAGIC = 0x53545055434F4C31ULL;  // "STPUCOL1"
static const int64_t ALIGN = 64;

static inline int64_t align_up(int64_t v) { return (v + ALIGN - 1) & ~(ALIGN - 1); }

// header: [u64 magic][i32 version][i32 nbufs][i64 sizes[nbufs]] padded to 64
static inline int64_t header_size(int32_t n) {
  return align_up(8 + 4 + 4 + 8 * (int64_t)n);
}

int64_t stpu_packed_size(const int64_t* sizes, int32_t n) {
  int64_t total = header_size(n);
  for (int32_t i = 0; i < n; i++) total += align_up(sizes[i]);
  return total;
}

int64_t stpu_pack(const uint8_t** bufs, const int64_t* sizes, int32_t n,
                  uint8_t* out) {
  uint8_t* p = out;
  std::memcpy(p, &STPU_MAGIC, 8);
  int32_t version = 1;
  std::memcpy(p + 8, &version, 4);
  std::memcpy(p + 12, &n, 4);
  std::memcpy(p + 16, sizes, 8 * (size_t)n);
  int64_t off = header_size(n);
  for (int32_t i = 0; i < n; i++) {
    if (sizes[i] > 0) std::memcpy(out + off, bufs[i], (size_t)sizes[i]);
    off += align_up(sizes[i]);
  }
  return off;
}

int32_t stpu_unpack_count(const uint8_t* data) {
  uint64_t magic;
  std::memcpy(&magic, data, 8);
  if (magic != STPU_MAGIC) return -1;
  int32_t n;
  std::memcpy(&n, data + 12, 4);
  return n;
}

// offsets[i], sizes[i] filled; returns total packed length or -1
int64_t stpu_unpack_offsets(const uint8_t* data, int64_t* offsets,
                            int64_t* sizes) {
  int32_t n = stpu_unpack_count(data);
  if (n < 0) return -1;
  std::memcpy(sizes, data + 16, 8 * (size_t)n);
  int64_t off = header_size(n);
  for (int32_t i = 0; i < n; i++) {
    offsets[i] = off;
    off += align_up(sizes[i]);
  }
  return off;
}

// -------------------------------------------------------- murmur3 (Spark)

static inline int32_t rotl32(int32_t x, int32_t r) {
  uint32_t u = (uint32_t)x;
  return (int32_t)((u << r) | (u >> (32 - r)));
}

static inline int32_t mm_mix_k1(int32_t k1) {
  k1 = (int32_t)((uint32_t)k1 * 0xCC9E2D51u);
  k1 = rotl32(k1, 15);
  return (int32_t)((uint32_t)k1 * 0x1B873593u);
}

static inline int32_t mm_mix_h1(int32_t h1, int32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  return (int32_t)((uint32_t)h1 * 5u + 0xE6546B64u);
}

static inline int32_t mm_fmix(int32_t h1, int32_t length) {
  h1 ^= length;
  h1 ^= (int32_t)((uint32_t)h1 >> 16);
  h1 = (int32_t)((uint32_t)h1 * 0x85EBCA6Bu);
  h1 ^= (int32_t)((uint32_t)h1 >> 13);
  h1 = (int32_t)((uint32_t)h1 * 0xC2B2AE35u);
  return h1 ^ (int32_t)((uint32_t)h1 >> 16);
}

static inline int32_t mm_hash_int(int32_t v, int32_t seed) {
  return mm_fmix(mm_mix_h1(seed, mm_mix_k1(v)), 4);
}

static inline int32_t mm_hash_long(int64_t v, int32_t seed) {
  int32_t low = (int32_t)v;
  int32_t high = (int32_t)((uint64_t)v >> 32);
  int32_t h1 = mm_mix_h1(seed, mm_mix_k1(low));
  h1 = mm_mix_h1(h1, mm_mix_k1(high));
  return mm_fmix(h1, 8);
}

// Spark hashUnsafeBytes: 4-byte LE words then one signed byte at a time.
static inline int32_t mm_hash_bytes(const uint8_t* p, int32_t len,
                                    int32_t seed) {
  int32_t h1 = seed;
  int32_t nwords = len / 4;
  for (int32_t i = 0; i < nwords; i++) {
    int32_t w;
    std::memcpy(&w, p + i * 4, 4);  // little-endian host
    h1 = mm_mix_h1(h1, mm_mix_k1(w));
  }
  for (int32_t i = nwords * 4; i < len; i++) {
    h1 = mm_mix_h1(h1, mm_mix_k1((int32_t)(int8_t)p[i]));
  }
  return mm_fmix(h1, len);
}

// h: inout running hash per row (seed chaining across columns); null rows
// (valid[i]==0) leave the hash unchanged, matching Spark HashExpression.
void stpu_murmur3_int(const int32_t* v, const uint8_t* valid, int64_t n,
                      int32_t* h) {
  for (int64_t i = 0; i < n; i++)
    if (!valid || valid[i]) h[i] = mm_hash_int(v[i], h[i]);
}

void stpu_murmur3_long(const int64_t* v, const uint8_t* valid, int64_t n,
                       int32_t* h) {
  for (int64_t i = 0; i < n; i++)
    if (!valid || valid[i]) h[i] = mm_hash_long(v[i], h[i]);
}

void stpu_murmur3_double(const double* v, const uint8_t* valid, int64_t n,
                         int32_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (valid && !valid[i]) continue;
    double d = v[i] == 0.0 ? 0.0 : v[i];
    int64_t bits;
    if (d != d) bits = 0x7FF8000000000000LL;  // canonical NaN
    else std::memcpy(&bits, &d, 8);
    h[i] = mm_hash_long(bits, h[i]);
  }
}

void stpu_murmur3_float(const float* v, const uint8_t* valid, int64_t n,
                        int32_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (valid && !valid[i]) continue;
    float f = v[i] == 0.0f ? 0.0f : v[i];
    int32_t bits;
    if (f != f) bits = 0x7FC00000;
    else std::memcpy(&bits, &f, 4);
    h[i] = mm_hash_int(bits, h[i]);
  }
}

// data: [n, stride] padded byte matrix; lens: per-row byte counts
void stpu_murmur3_bytes(const uint8_t* data, const int32_t* lens,
                        int64_t stride, const uint8_t* valid, int64_t n,
                        int32_t* h) {
  for (int64_t i = 0; i < n; i++)
    if (!valid || valid[i])
      h[i] = mm_hash_bytes(data + i * stride, lens[i], h[i]);
}

// ---------------------------------------------------------- XXH64 (Spark)

static const uint64_t XP1 = 0x9E3779B185EBCA87ULL;
static const uint64_t XP2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t XP3 = 0x165667B19E3779F9ULL;
static const uint64_t XP4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t XP5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t xx_fmix(uint64_t h) {
  h ^= h >> 33; h *= XP2; h ^= h >> 29; h *= XP3; h ^= h >> 32;
  return h;
}

static inline uint64_t xx_hash_int(int32_t v, uint64_t seed) {
  uint64_t h = seed + XP5 + 4;
  h ^= ((uint64_t)(uint32_t)v) * XP1;
  h = rotl64(h, 23) * XP2 + XP3;
  return xx_fmix(h);
}

static inline uint64_t xx_hash_long(int64_t v, uint64_t seed) {
  uint64_t h = seed + XP5 + 8;
  uint64_t k1 = rotl64((uint64_t)v * XP2, 31) * XP1;
  h ^= k1;
  h = rotl64(h, 27) * XP1 + XP4;
  return xx_fmix(h);
}

static inline uint64_t xx_hash_bytes(const uint8_t* p, int32_t len,
                                     uint64_t seed) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + XP1 + XP2, v2 = seed + XP2, v3 = seed,
             v4 = seed - XP1;
    const uint8_t* limit = end - 32;
    do {
      uint64_t w;
      std::memcpy(&w, p, 8); v1 = rotl64(v1 + w * XP2, 31) * XP1; p += 8;
      std::memcpy(&w, p, 8); v2 = rotl64(v2 + w * XP2, 31) * XP1; p += 8;
      std::memcpy(&w, p, 8); v3 = rotl64(v3 + w * XP2, 31) * XP1; p += 8;
      std::memcpy(&w, p, 8); v4 = rotl64(v4 + w * XP2, 31) * XP1; p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = (h ^ (rotl64(v1 * XP2, 31) * XP1)) * XP1 + XP4;
    h = (h ^ (rotl64(v2 * XP2, 31) * XP1)) * XP1 + XP4;
    h = (h ^ (rotl64(v3 * XP2, 31) * XP1)) * XP1 + XP4;
    h = (h ^ (rotl64(v4 * XP2, 31) * XP1)) * XP1 + XP4;
  } else {
    h = seed + XP5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    uint64_t w;
    std::memcpy(&w, p, 8);
    h = rotl64(h ^ (rotl64(w * XP2, 31) * XP1), 27) * XP1 + XP4;
    p += 8;
  }
  if (p + 4 <= end) {
    uint32_t w;
    std::memcpy(&w, p, 4);
    h = rotl64(h ^ ((uint64_t)w * XP1), 23) * XP2 + XP3;
    p += 4;
  }
  while (p < end) {
    h = rotl64(h ^ ((uint64_t)*p * XP5), 11) * XP1;
    p++;
  }
  return xx_fmix(h);
}

void stpu_xxhash64_int(const int32_t* v, const uint8_t* valid, int64_t n,
                       uint64_t* h) {
  for (int64_t i = 0; i < n; i++)
    if (!valid || valid[i]) h[i] = xx_hash_int(v[i], h[i]);
}

void stpu_xxhash64_long(const int64_t* v, const uint8_t* valid, int64_t n,
                        uint64_t* h) {
  for (int64_t i = 0; i < n; i++)
    if (!valid || valid[i]) h[i] = xx_hash_long(v[i], h[i]);
}

void stpu_xxhash64_float(const float* v, const uint8_t* valid, int64_t n,
                         uint64_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (valid && !valid[i]) continue;
    float f = v[i] == 0.0f ? 0.0f : v[i];
    int32_t bits;
    if (f != f) bits = 0x7FC00000;
    else std::memcpy(&bits, &f, 4);
    h[i] = xx_hash_int(bits, h[i]);
  }
}

void stpu_xxhash64_double(const double* v, const uint8_t* valid, int64_t n,
                          uint64_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (valid && !valid[i]) continue;
    double d = v[i] == 0.0 ? 0.0 : v[i];
    int64_t bits;
    if (d != d) bits = 0x7FF8000000000000LL;
    else std::memcpy(&bits, &d, 8);
    h[i] = xx_hash_long(bits, h[i]);
  }
}

void stpu_xxhash64_bytes(const uint8_t* data, const int32_t* lens,
                         int64_t stride, const uint8_t* valid, int64_t n,
                         uint64_t* h) {
  for (int64_t i = 0; i < n; i++)
    if (!valid || valid[i])
      h[i] = xx_hash_bytes(data + i * stride, lens[i], h[i]);
}

// ------------------------------------------------- row <-> column transpose

// Fixed-width columns to packed rows. Row layout: one validity byte per
// column, then each column's value at its offset (naturally packed in
// column order). widths[i] in {1,2,4,8}.
void stpu_columns_to_rows(int32_t ncols, const uint8_t** col_data,
                          const int32_t* widths, const uint8_t** valids,
                          int64_t nrows, uint8_t* rows_out,
                          int64_t row_stride) {
  int64_t val_base = 0;  // validity bytes first
  std::vector<int64_t> offs(ncols);
  int64_t off = ncols;  // after validity bytes
  for (int32_t c = 0; c < ncols; c++) { offs[c] = off; off += widths[c]; }
  for (int64_t r = 0; r < nrows; r++) {
    uint8_t* row = rows_out + r * row_stride;
    for (int32_t c = 0; c < ncols; c++) {
      row[val_base + c] = valids[c] ? valids[c][r] : 1;
      std::memcpy(row + offs[c], col_data[c] + r * widths[c], widths[c]);
    }
  }
}

void stpu_rows_to_columns(int32_t ncols, uint8_t** col_data,
                          const int32_t* widths, uint8_t** valids,
                          int64_t nrows, const uint8_t* rows_in,
                          int64_t row_stride) {
  std::vector<int64_t> offs(ncols);
  int64_t off = ncols;
  for (int32_t c = 0; c < ncols; c++) { offs[c] = off; off += widths[c]; }
  for (int64_t r = 0; r < nrows; r++) {
    const uint8_t* row = rows_in + r * row_stride;
    for (int32_t c = 0; c < ncols; c++) {
      if (valids[c]) valids[c][r] = row[c];
      std::memcpy(col_data[c] + r * widths[c], row + offs[c], widths[c]);
    }
  }
}

int64_t stpu_row_stride(int32_t ncols, const int32_t* widths) {
  int64_t off = ncols;
  for (int32_t c = 0; c < ncols; c++) off += widths[c];
  return (off + 7) & ~7LL;  // 8-byte aligned row size
}

// ------------------------------------------------------- host buffer pool

struct StpuPool {
  int64_t capacity;
  std::atomic<int64_t> in_use{0};
  std::atomic<int64_t> peak{0};
  std::atomic<int64_t> alloc_count{0};
  std::mutex mu;
  std::multimap<int64_t, void*> freelist;  // size -> block
  std::map<void*, int64_t> sizes;          // live + freed block sizes
};

void* stpu_pool_create(int64_t capacity) {
  return new (std::nothrow) StpuPool{capacity};
}

void stpu_pool_destroy(void* pv) {
  auto* p = (StpuPool*)pv;
  if (!p) return;
  // `sizes` tracks every block ever allocated (freelist is a subset)
  for (auto& kv : p->sizes) ::operator delete(kv.first);
  delete p;
}

// nullptr when the pool budget would be exceeded (caller spills and
// retries — the HostAlloc blocking/retry analog, done Python-side).
void* stpu_pool_alloc(void* pv, int64_t n) {
  auto* p = (StpuPool*)pv;
  if (n <= 0) n = 1;
  {
    std::lock_guard<std::mutex> g(p->mu);
    auto it = p->freelist.lower_bound(n);
    if (it != p->freelist.end() && it->first <= n * 2) {
      // Reused blocks also reserve budget via CAS: the block's bytes left
      // in_use at free time, so taking it back must re-check capacity or
      // the freelist path oversubscribes the hard bound.
      int64_t sz = it->first;
      int64_t cur = p->in_use.load();
      bool fits = true;
      do {
        if (cur + sz > p->capacity) { fits = false; break; }
      } while (!p->in_use.compare_exchange_weak(cur, cur + sz));
      if (fits) {
        void* blk = it->second;
        p->freelist.erase(it);
        int64_t now = cur + sz;
        int64_t pk = p->peak.load();
        while (now > pk && !p->peak.compare_exchange_weak(pk, now)) {}
        p->alloc_count++;
        return blk;
      }
      // an oversized reuse block does not fit the budget; fall through to
      // an exact-size fresh allocation, which re-checks capacity
    }
  }
  // Reserve budget with a CAS loop so capacity is a hard bound even under
  // concurrent allocations (non-atomic check-then-add could oversubscribe).
  int64_t cur = p->in_use.load();
  do {
    if (cur + n > p->capacity) return nullptr;
  } while (!p->in_use.compare_exchange_weak(cur, cur + n));
  void* blk = ::operator new((size_t)n, std::nothrow);
  if (!blk) { p->in_use.fetch_sub(n); return nullptr; }
  {
    std::lock_guard<std::mutex> g(p->mu);
    p->sizes[blk] = n;
  }
  int64_t now = cur + n;
  int64_t pk = p->peak.load();
  while (now > pk && !p->peak.compare_exchange_weak(pk, now)) {}
  p->alloc_count++;
  return blk;
}

void stpu_pool_free(void* pv, void* blk) {
  auto* p = (StpuPool*)pv;
  std::lock_guard<std::mutex> g(p->mu);
  auto it = p->sizes.find(blk);
  if (it == p->sizes.end()) return;
  p->in_use.fetch_sub(it->second);
  p->freelist.emplace(it->second, blk);
}

int64_t stpu_pool_in_use(void* pv) { return ((StpuPool*)pv)->in_use.load(); }
int64_t stpu_pool_peak(void* pv) { return ((StpuPool*)pv)->peak.load(); }
int64_t stpu_pool_alloc_count(void* pv) {
  return ((StpuPool*)pv)->alloc_count.load();
}

}  // extern "C"
