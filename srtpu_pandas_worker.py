"""Worker-process entry for the pandas-UDF Arrow exchange
(spark_rapids_tpu/udf/pandas_udf.py).

Deliberately a TOP-LEVEL module with only pyarrow/cloudpickle imports:
worker processes unpickle functions by module reference, and importing
the spark_rapids_tpu package would initialize the JAX backend inside
every worker (slow on TPU machines, and fatal when the device tunnel is
unavailable). The reference keeps its Python workers equally minimal
(python/rapids/worker.py) for the same reason.
"""

from __future__ import annotations

import pyarrow as pa


def ipc_bytes(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def ipc_table(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.py_buffer(data)) as r:
        return r.read_all()


def worker_apply(fn_bytes: bytes, payload: bytes,
                 schema_blob: bytes) -> bytes:
    """Arrow in, pandas apply, Arrow out."""
    import cloudpickle

    fn = cloudpickle.loads(fn_bytes)
    table = ipc_table(payload)
    series = [table.column(i).to_pandas()
              for i in range(table.num_columns)]
    result = fn(*series)
    out_type = pa.ipc.read_schema(
        pa.py_buffer(schema_blob)).field(0).type
    arr = pa.Array.from_pandas(result, type=out_type)
    return ipc_bytes(pa.table({"r": arr}))


def _df_of(table: pa.Table):
    return table.to_pandas()


def _table_of(df, schema_blob: bytes) -> pa.Table:
    schema = pa.ipc.read_schema(pa.py_buffer(schema_blob))
    cols = []
    for f in schema:
        if f.name not in df.columns:
            raise ValueError(
                f"pandas function result is missing column {f.name!r}; "
                f"got {list(df.columns)}")
        cols.append(pa.Array.from_pandas(df[f.name], type=f.type))
    return pa.Table.from_arrays(cols, schema=schema)


def worker_apply_df(fn_bytes: bytes, payload: bytes,
                    schema_blob: bytes) -> bytes:
    """pandas.DataFrame -> pandas.DataFrame function (applyInPandas /
    mapInPandas worker side)."""
    import cloudpickle

    fn = cloudpickle.loads(fn_bytes)
    out = fn(_df_of(ipc_table(payload)))
    return ipc_bytes(_table_of(out, schema_blob))


def worker_apply_cogroup(fn_bytes: bytes, payload_l: bytes,
                         payload_r: bytes, schema_blob: bytes) -> bytes:
    """(left_df, right_df) -> pandas.DataFrame (cogrouped
    applyInPandas worker side)."""
    import cloudpickle

    fn = cloudpickle.loads(fn_bytes)
    out = fn(_df_of(ipc_table(payload_l)), _df_of(ipc_table(payload_r)))
    return ipc_bytes(_table_of(out, schema_blob))


# ---------------------------------------------------------------- daemon
#
# Stdin/stdout framed-pickle server (the reference's python worker
# daemon pattern, python/rapids/daemon.py): the driver launches
# `python srtpu_pandas_worker.py serve` subprocesses directly, so no
# multiprocessing start method ever re-imports the USER's __main__
# (fork/spawn/forkserver all break unguarded user scripts).

import struct as _struct
import sys as _sys


def _read_frame(stream):
    head = stream.read(8)
    if len(head) < 8:
        return None
    (ln,) = _struct.unpack("<q", head)
    return stream.read(ln)


def _write_frame(stream, data: bytes):
    stream.write(_struct.pack("<q", len(data)))
    stream.write(data)
    stream.flush()


def serve():
    import io
    import os
    import pickle
    import traceback

    fns = {
        "worker_apply": worker_apply,
        "worker_apply_df": worker_apply_df,
        "worker_apply_cogroup": worker_apply_cogroup,
    }
    stdin = _sys.stdin.buffer
    # the framing channel owns a PRIVATE dup of fd 1; fd 1 is then
    # redirected to stderr so print() inside user UDFs cannot corrupt
    # the length-prefixed protocol
    stdout = io.FileIO(os.dup(1), "wb")
    os.dup2(2, 1)
    _sys.stdout = _sys.stderr
    while True:
        frame = _read_frame(stdin)
        if frame is None:
            return
        try:
            name, args = pickle.loads(frame)
            result = fns[name](*args)
            _write_frame(stdout, pickle.dumps(("ok", result)))
        except BaseException:
            _write_frame(stdout,
                         pickle.dumps(("err", traceback.format_exc())))


if __name__ == "__main__":
    if len(_sys.argv) > 1 and _sys.argv[1] == "serve":
        serve()
