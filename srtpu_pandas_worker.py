"""Worker-process entry for the pandas-UDF Arrow exchange
(spark_rapids_tpu/udf/pandas_udf.py).

Deliberately a TOP-LEVEL module with only pyarrow/cloudpickle imports:
worker processes unpickle functions by module reference, and importing
the spark_rapids_tpu package would initialize the JAX backend inside
every worker (slow on TPU machines, and fatal when the device tunnel is
unavailable). The reference keeps its Python workers equally minimal
(python/rapids/worker.py) for the same reason.
"""

from __future__ import annotations

import pyarrow as pa


def ipc_bytes(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def ipc_table(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.py_buffer(data)) as r:
        return r.read_all()


def worker_apply(fn_bytes: bytes, payload: bytes,
                 schema_blob: bytes) -> bytes:
    """Arrow in, pandas apply, Arrow out."""
    import cloudpickle

    fn = cloudpickle.loads(fn_bytes)
    table = ipc_table(payload)
    series = [table.column(i).to_pandas()
              for i in range(table.num_columns)]
    result = fn(*series)
    out_type = pa.ipc.read_schema(
        pa.py_buffer(schema_blob)).field(0).type
    arr = pa.Array.from_pandas(result, type=out_type)
    return ipc_bytes(pa.table({"r": arr}))
