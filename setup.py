"""Builds the native host runtime into the wheel.

The reference ships its native code as a prebuilt Maven artifact
(spark-rapids-jni bundling libcudf, pom.xml:904-911); here the C++
host runtime (wire-format pack, spark-exact hashing, row transpose,
host buffer pool) compiles at package build time and lands next to the
python package so `spark_rapids_tpu.native` loads it without a
toolchain at runtime. A missing/failed toolchain is NOT an install
error: the runtime falls back to building from source at first use,
and then to pure-python (native/__init__.py)."""

import os
import shutil

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        here = os.path.dirname(os.path.abspath(__file__))
        # load the native module FILE directly: importing the package
        # would pull in jax, which need not exist in the build env
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_srtpu_native_build",
            os.path.join(here, "spark_rapids_tpu", "native",
                         "__init__.py"))
        native_mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(native_mod)
        compile_runtime = native_mod.compile_runtime

        src = os.path.join(here, "native", "sparktpu_runtime.cpp")
        out_dir = os.path.join(here, "native", "build")
        so = os.path.join(out_dir, "libsparktpu.so")
        built = None
        if os.path.exists(src):
            os.makedirs(out_dir, exist_ok=True)
            # portable flags for a distributable wheel
            built = compile_runtime(src, so, timeout=300,
                                    native_arch=False)
            if built is None:
                print("warning: native runtime not built "
                      "(toolchain missing?); wheel ships pure-python "
                      "with on-demand build fallback")
        super().run()
        if built:
            pkg_native = os.path.join(self.build_lib,
                                      "spark_rapids_tpu", "native")
            os.makedirs(pkg_native, exist_ok=True)
            shutil.copy2(built, os.path.join(pkg_native,
                                             "libsparktpu.so"))


setup(cmdclass={"build_py": BuildWithNative})
