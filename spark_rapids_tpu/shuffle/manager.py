"""Shuffle manager v1 — the MULTITHREADED / CACHE_ONLY transport analog.

Reference (`RapidsShuffleInternalManagerBase.scala:238,569,1183`): the
MULTITHREADED mode serializes device batches on a writer thread pool into
host shuffle storage, readers fetch and coalesce back onto the device
(`GpuShuffleCoalesceExec`). The UCX device-to-device transport is the ICI
collective path in shuffle/ici.py.

This in-process manager keeps shuffle blocks as host Arrow tables
registered with the spill catalog's host budget (CACHE_ONLY semantics);
a multi-host version would write the same blocks through the
serialization in shuffle/serde.py.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Tuple

import pyarrow as pa


class ShuffleManager:
    """Maps (shuffle_id, reduce_pid) -> list of host tables."""

    def __init__(self):
        self._blocks: Dict[Tuple[int, int], List[pa.Table]] = defaultdict(
            list)
        self._lock = threading.Lock()
        self._next_id = 0
        self.bytes_written = 0

    def new_shuffle_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def put(self, shuffle_id: int, reduce_pid: int, table: pa.Table):
        with self._lock:
            self._blocks[(shuffle_id, reduce_pid)].append(table)
            self.bytes_written += table.nbytes

    def fetch(self, shuffle_id: int, reduce_pid: int) -> List[pa.Table]:
        with self._lock:
            return list(self._blocks.get((shuffle_id, reduce_pid), []))

    def remove_shuffle(self, shuffle_id: int):
        with self._lock:
            for k in [k for k in self._blocks if k[0] == shuffle_id]:
                del self._blocks[k]


_manager = ShuffleManager()


def get_shuffle_manager() -> ShuffleManager:
    return _manager
