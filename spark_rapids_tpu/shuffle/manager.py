"""Shuffle manager — the MULTITHREADED / CACHE_ONLY transport analog.

Reference (`RapidsShuffleInternalManagerBase.scala:238,569,1183`): the
MULTITHREADED mode serializes device batches on a writer thread pool into
host shuffle storage (files), readers fetch and coalesce back onto the
device (`GpuShuffleCoalesceExec`). The UCX device-to-device transport's
analog is the ICI collective path (parallel/collective.py).

Modes here (conf spark.rapids.shuffle.mode):
- CACHE_ONLY: blocks stay as in-process host Arrow tables.
- MULTITHREADED: blocks are serialized through the native wire format
  (shuffle/serde.py, the JCudfSerialization analog) and written to
  shuffle files by a writer thread pool; readers block on the in-flight
  writes for their partition then deserialize.
"""

from __future__ import annotations

import os
import tempfile
import threading
from collections import defaultdict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Tuple

import numpy as np
import pyarrow as pa


class ShuffleManager:
    """Maps (shuffle_id, reduce_pid) -> shuffle blocks."""

    def __init__(self, mode: str = "CACHE_ONLY", shuffle_dir: str = None,
                 num_threads: int = 8):
        self.mode = mode
        self._blocks: Dict[Tuple[int, int], List[pa.Table]] = defaultdict(
            list)
        self._files: Dict[Tuple[int, int], List[Future]] = defaultdict(
            list)
        self._lock = threading.Lock()
        self._next_id = 0
        self.bytes_written = 0
        self._dir = shuffle_dir
        self._pool = None
        self._seq = 0
        if mode == "MULTITHREADED":
            self._dir = shuffle_dir or tempfile.mkdtemp(
                prefix="srtpu-shuffle-")
            self._pool = ThreadPoolExecutor(
                max_workers=max(1, num_threads),
                thread_name_prefix="shuffle-writer")

    def new_shuffle_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def put(self, shuffle_id: int, reduce_pid: int, table: pa.Table):
        if self.mode != "MULTITHREADED":
            with self._lock:
                self._blocks[(shuffle_id, reduce_pid)].append(table)
                self.bytes_written += table.nbytes
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
        path = os.path.join(
            self._dir, f"shuffle-{shuffle_id}-{reduce_pid}-{seq}.stpu")

        def write():
            from spark_rapids_tpu.shuffle import serde

            buf = serde.serialize_table(table)
            with open(path, "wb") as f:
                buf.tofile(f)
            with self._lock:
                self.bytes_written += buf.nbytes
            return path

        fut = self._pool.submit(write)
        with self._lock:
            self._files[(shuffle_id, reduce_pid)].append(fut)

    def fetch(self, shuffle_id: int, reduce_pid: int) -> List[pa.Table]:
        if self.mode != "MULTITHREADED":
            with self._lock:
                return list(self._blocks.get((shuffle_id, reduce_pid), []))
        with self._lock:
            futs = list(self._files.get((shuffle_id, reduce_pid), []))
        from spark_rapids_tpu.shuffle import serde

        tables = []
        for fut in futs:
            path = fut.result()  # blocks on in-flight writes
            data = np.fromfile(path, dtype=np.uint8)
            tables.append(serde.deserialize_table(data))
        return tables

    def remove_shuffle(self, shuffle_id: int):
        with self._lock:
            for k in [k for k in self._blocks if k[0] == shuffle_id]:
                del self._blocks[k]
            futs = []
            for k in [k for k in self._files if k[0] == shuffle_id]:
                futs.extend(self._files.pop(k))
        # wait + unlink OUTSIDE the lock so unrelated shuffles proceed
        for fut in futs:
            try:
                os.unlink(fut.result())
            except Exception:
                pass

    def shutdown(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)


_manager = ShuffleManager()
_mgr_lock = threading.Lock()


def configure_shuffle(mode: str, shuffle_dir: str = None,
                      num_threads: int = 8):
    """Install a manager for the session's shuffle settings (reference
    GpuShuffleEnv.initShuffleManager, Plugin.scala:531)."""
    global _manager
    with _mgr_lock:
        settings = (mode, shuffle_dir, num_threads)
        if getattr(_manager, "_settings", None) != settings:
            _manager.shutdown()
            _manager = ShuffleManager(mode, shuffle_dir, num_threads)
            _manager._settings = settings
    return _manager


def get_shuffle_manager() -> ShuffleManager:
    return _manager
