"""Shuffle manager — the MULTITHREADED / CACHE_ONLY transport analog.

Reference (`RapidsShuffleInternalManagerBase.scala:238,569,1183`): the
MULTITHREADED mode serializes device batches on a writer thread pool into
host shuffle storage (files), readers fetch and coalesce back onto the
device (`GpuShuffleCoalesceExec`). The UCX device-to-device transport's
analog is the ICI collective path (parallel/collective.py +
parallel/plan_compiler.py).

Failure domain (PR 2 hardening): every block carries a per-block CRC
(shuffle/serde.py, conf spark.rapids.shuffle.checksum.enabled) and
every fetch/decode of an on-disk block runs under the shared
exponential-backoff policy (runtime/backoff.py) — torn files, bit
rot, and injected shuffle.fetch / shuffle.deserialize faults
(runtime/faults.py) are retried `io.retry.attempts` times before a
clean ShuffleFetchError names the exact block. Retries are counted
(`fetch_retries`) so the bench tracks robustness overhead.

Attempt-tagged map output (PR 3, the stage-scheduler integration —
Spark's MapStatus/attempt-id discipline): map tasks `put` blocks
tagged (map_id, attempt) which land STAGED, invisible to reducers,
until `commit_map_output` publishes them. Commit is FIRST-WINS per
(shuffle_id, map_id): a losing speculative attempt's staged blocks are
discarded (`speculative_discards`), never double-counted. Recovery
commits pass `replace=True` to atomically swap a lost map task's
blocks with its recomputed output (deterministic lineage makes old and
new identical, so concurrent readers of other partitions stay
consistent). A fetch failure that survives the block retry budget
raises ShuffleFetchError carrying the owning `map_id`, which
`TpuShuffleExchangeExec.fetch_blocks` uses to re-run exactly that map
task. Cleanup failures are counted (`orphaned_files`) so leaked spill
files are visible instead of silently swallowed.

Modes here (conf spark.rapids.shuffle.mode):
- CACHE_ONLY: blocks live as in-process host Arrow tables under a host
  byte ledger; when in-memory block bytes exceed the spill threshold the
  coldest blocks degrade to compressed disk files (the
  ShuffleBufferCatalog spill-integration role — blocks are never lost,
  they move tiers).
- MULTITHREADED: blocks are serialized through the native wire format
  (shuffle/serde.py, the JCudfSerialization analog), optionally
  compressed (TableCompressionCodec role), and written to shuffle files
  by a writer thread pool; readers block on the in-flight writes for
  their partition then deserialize.
"""

from __future__ import annotations

import os
import tempfile
import threading
from collections import defaultdict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa


class _MemBlock:
    __slots__ = ("table", "path", "nbytes", "seq", "map_id", "attempt")

    def __init__(self, table: Optional[pa.Table], nbytes: int, seq: int,
                 map_id: Optional[int] = None, attempt: int = 0):
        self.table = table          # None once spilled
        self.path: Optional[str] = None
        self.nbytes = nbytes
        self.seq = seq
        self.map_id = map_id        # owning map task (None = legacy put)
        self.attempt = attempt


class _FileBlock:
    """MULTITHREADED-mode block: a writer-pool future resolving to the
    block's file path, tagged with the owning map task."""

    __slots__ = ("future", "map_id", "attempt")

    def __init__(self, future: Future, map_id: Optional[int] = None,
                 attempt: int = 0):
        self.future = future
        self.map_id = map_id
        self.attempt = attempt


class ShuffleManager:
    """Maps (shuffle_id, reduce_pid) -> shuffle blocks."""

    def __init__(self, mode: str = "CACHE_ONLY", shuffle_dir: str = None,
                 num_threads: int = 8, codec: str = "none",
                 spill_threshold: int = 2 << 30, checksum: bool = True):
        self.mode = mode
        self.codec = codec
        self.checksum = checksum
        self.spill_threshold = spill_threshold
        self.fetch_retries = 0
        self.checksum_failures = 0
        self.orphaned_files = 0
        self.speculative_discards = 0
        self._blocks: Dict[Tuple[int, int], List[_MemBlock]] = defaultdict(
            list)
        self._files: Dict[Tuple[int, int], List[_FileBlock]] = defaultdict(
            list)
        # attempt-staged map output, invisible until committed:
        # (shuffle_id, map_id, attempt) -> [(reduce_pid, block)]
        self._staged: Dict[Tuple[int, int, int], List[tuple]] = \
            defaultdict(list)
        self._committed: Dict[Tuple[int, int], int] = {}
        self._recompute_seq = 0
        self._lock = threading.Lock()
        self._next_id = 0
        self.bytes_written = 0
        self.bytes_in_memory = 0
        self.blocks_spilled = 0
        self._dir = shuffle_dir
        self._pool = None
        self._seq = 0
        if mode == "MULTITHREADED":
            self._dir = shuffle_dir or tempfile.mkdtemp(
                prefix="srtpu-shuffle-")
            self._pool = ThreadPoolExecutor(
                max_workers=max(1, num_threads),
                thread_name_prefix="shuffle-writer")

    def new_shuffle_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def _spill_dir(self) -> str:
        if self._dir is None:
            self._dir = tempfile.mkdtemp(prefix="srtpu-shuffle-")
        return self._dir

    def _spill_block(self, b: _MemBlock):
        """Under lock: move one in-memory block to a compressed disk
        file (does not touch ledgers — callers own the accounting)."""
        from spark_rapids_tpu.shuffle import serde

        path = os.path.join(self._spill_dir(),
                            f"shuffle-spill-{b.seq}.stpu")
        serde.serialize_table(b.table, codec=self.codec,
                              checksum=self.checksum).tofile(path)
        # path BEFORE table: fetch() snapshots (table, path) and
        # must never observe both unset
        b.path = path
        b.table = None
        self.blocks_spilled += 1
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import telemetry

        obs_events.emit("spill", component="shuffle", direction="down",
                        fromTier="HOST", toTier="DISK", bytes=b.nbytes)
        telemetry.record("spill-disk", "shuffle.spill", b.nbytes)

    def _spill_mem_blocks(self):
        """Under lock: move coldest (oldest) in-memory blocks to
        compressed disk files until under the threshold."""
        from spark_rapids_tpu.runtime import host_alloc

        pageable = host_alloc.get().pageable
        victims: List[_MemBlock] = []
        for blocks in self._blocks.values():
            victims.extend(b for b in blocks if b.table is not None)
        for staged in self._staged.values():
            victims.extend(b for _rp, b in staged
                           if isinstance(b, _MemBlock)
                           and b.table is not None)
        victims.sort(key=lambda b: b.seq)
        for b in victims:
            if self.bytes_in_memory <= self.spill_threshold:
                break
            self._spill_block(b)
            self.bytes_in_memory -= b.nbytes
            pageable.release(b.nbytes)

    def put(self, shuffle_id: int, reduce_pid: int, table: pa.Table,
            map_id: Optional[int] = None, attempt: int = 0):
        """Store one block. With `map_id` the block is STAGED under
        (map_id, attempt) — invisible to fetch until commit_map_output
        publishes the attempt (the scheduler's commit-once discipline).
        Without it the block commits immediately (legacy single-attempt
        writers: range exchange, mesh spill paths, tests)."""
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import telemetry

        obs_events.emit("shuffle.write", shuffleId=shuffle_id,
                        reducePid=reduce_pid, bytes=table.nbytes,
                        staged=map_id is not None)
        telemetry.record("shuffle", "shuffle.write", table.nbytes)
        if self.mode != "MULTITHREADED":
            from spark_rapids_tpu.runtime import host_alloc

            # in-memory shuffle blocks draw from the GLOBAL pageable
            # host budget (runtime/host_alloc.py, HostAlloc role); when
            # the budget is gone this block goes straight to disk
            in_mem = host_alloc.get().pageable.try_reserve(table.nbytes)
            with self._lock:
                self._seq += 1
                blk = _MemBlock(table, table.nbytes, self._seq,
                                map_id, attempt)
                dest_key = (shuffle_id, reduce_pid)
                if map_id is None:
                    self._blocks[dest_key].append(blk)
                else:
                    self._staged[(shuffle_id, map_id, attempt)].append(
                        (reduce_pid, blk))
                self.bytes_written += table.nbytes
                if in_mem:
                    self.bytes_in_memory += table.nbytes
                    if self.bytes_in_memory > self.spill_threshold:
                        self._spill_mem_blocks()
                else:
                    try:
                        self._spill_block(blk)
                    except BaseException:
                        # drop the half-registered block: it holds no
                        # reservation, and remove_shuffle's
                        # table-means-reserved accounting must never
                        # see it
                        if map_id is None:
                            self._blocks[dest_key].remove(blk)
                        else:
                            self._staged[
                                (shuffle_id, map_id, attempt)].remove(
                                (reduce_pid, blk))
                        raise
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
        path = os.path.join(
            self._dir, f"shuffle-{shuffle_id}-{reduce_pid}-{seq}.stpu")

        def write():
            from spark_rapids_tpu.shuffle import serde

            buf = serde.serialize_table(table, codec=self.codec,
                                        checksum=self.checksum)
            with open(path, "wb") as f:
                buf.tofile(f)
            with self._lock:
                self.bytes_written += buf.nbytes
            return path

        fb = _FileBlock(self._pool.submit(write), map_id, attempt)
        with self._lock:
            if map_id is None:
                self._files[(shuffle_id, reduce_pid)].append(fb)
            else:
                self._staged[(shuffle_id, map_id, attempt)].append(
                    (reduce_pid, fb))

    # --- attempt lifecycle (stage-scheduler integration) ---

    def recompute_attempt(self, shuffle_id: int, map_id: int) -> int:
        """Fresh attempt id for a lost-output recomputation — disjoint
        from the scheduler's small attempt ordinals so a recompute can
        never collide with a still-staged speculative attempt."""
        with self._lock:
            self._recompute_seq += 1
            return 1_000_000 + self._recompute_seq

    def commit_map_output(self, shuffle_id: int, map_id: int,
                          attempt: int, replace: bool = False) -> bool:
        """Publish a staged attempt's blocks. First commit wins per
        (shuffle_id, map_id); a later commit's blocks are discarded and
        False returns (the losing speculative attempt). `replace=True`
        (lost-output recovery) atomically swaps any previously
        committed blocks of this map task with the recomputed ones."""
        discard: List = []
        with self._lock:
            staged = self._staged.pop((shuffle_id, map_id, attempt), [])
            cur = self._committed.get((shuffle_id, map_id))
            if cur is not None and not replace:
                self.speculative_discards += len(staged)
                self._release_blocks_locked(
                    [b for _rp, b in staged], discard)
                won = False
            else:
                if replace and cur is not None:
                    for (sid, rp), blks in list(self._blocks.items()):
                        if sid != shuffle_id:
                            continue
                        keep = [b for b in blks if b.map_id != map_id]
                        gone = [b for b in blks if b.map_id == map_id]
                        if gone:
                            self._blocks[(sid, rp)] = keep
                            self._release_blocks_locked(gone, discard)
                    for (sid, rp), fbs in list(self._files.items()):
                        if sid != shuffle_id:
                            continue
                        keep = [f for f in fbs if f.map_id != map_id]
                        gone = [f for f in fbs if f.map_id == map_id]
                        if gone:
                            self._files[(sid, rp)] = keep
                            discard.extend(gone)
                dest = self._files if self.mode == "MULTITHREADED" \
                    else self._blocks
                for rp, blk in staged:
                    dest[(shuffle_id, rp)].append(blk)
                self._committed[(shuffle_id, map_id)] = attempt
                won = True
        self._dispose_blocks(discard)
        return won

    def discard_attempt(self, shuffle_id: int, map_id: int,
                        attempt: int) -> None:
        """Drop a failed/aborted attempt's staged blocks (idempotent)."""
        discard: List = []
        with self._lock:
            staged = self._staged.pop((shuffle_id, map_id, attempt), [])
            self._release_blocks_locked([b for _rp, b in staged],
                                        discard)
        self._dispose_blocks(discard)

    def _release_blocks_locked(self, blocks, discard: List) -> None:
        """Under lock: return in-memory bytes to the host ledger; queue
        on-disk artifacts for out-of-lock disposal."""
        from spark_rapids_tpu.runtime import host_alloc

        pageable = host_alloc.get().pageable
        for b in blocks:
            if isinstance(b, _MemBlock):
                if b.table is not None:
                    self.bytes_in_memory -= b.nbytes
                    pageable.release(b.nbytes)
                elif b.path:
                    discard.append(b)
            else:
                discard.append(b)

    def _dispose_blocks(self, blocks) -> None:
        """Outside the lock: unlink spilled/written block files; a
        writer future still in flight unlinks via callback once done.
        Failures count as orphaned files instead of vanishing."""
        def _unlink(path: str) -> None:
            try:
                os.unlink(path)
            except OSError:
                with self._lock:
                    self.orphaned_files += 1

        for b in blocks:
            if isinstance(b, _MemBlock):
                _unlink(b.path)
            else:
                fut = b.future
                if fut.done():
                    try:
                        _unlink(fut.result())
                    except Exception:
                        pass  # write failed: no file to remove
                else:
                    def _cb(f):
                        try:
                            _unlink(f.result())
                        except Exception:
                            pass

                    fut.add_done_callback(_cb)

    def partition_sizes(self, shuffle_id: int, nparts: int) -> List[int]:
        """Per-reduce-partition byte sizes of a materialized shuffle —
        the MapOutputStatistics role AQE re-planning consumes."""
        out = [0] * nparts
        with self._lock:
            for (sid, rp), blks in self._blocks.items():
                if sid == shuffle_id and rp < nparts:
                    out[rp] += sum(b.nbytes for b in blks)
            futs = [((sid, rp), list(fs))
                    for (sid, rp), fs in self._files.items()
                    if sid == shuffle_id and rp < nparts]
        import os as _os

        for (sid, rp), fs in futs:
            for fb in fs:
                try:
                    out[rp] += _os.path.getsize(fb.future.result())
                except Exception:
                    pass  # lost/failed block: recovery happens at fetch
        return out

    def _fetch_block(self, path: str, shuffle_id: int,
                     reduce_pid: int,
                     map_id: Optional[int] = None) -> pa.Table:
        """Read + decode one on-disk block under the backoff policy:
        OSError / checksum mismatch / injected shuffle.fetch or
        shuffle.deserialize faults each consume an attempt (re-reading
        the file is the repair for all of them); the exhausted budget
        surfaces as a ShuffleFetchError naming the block — and, for
        attempt-tagged blocks, the owning map task, so the scheduler
        can recompute it."""
        from spark_rapids_tpu.runtime import backoff
        from spark_rapids_tpu.runtime.errors import (
            RetryExhausted,
            ShuffleChecksumError,
            ShuffleFetchError,
        )
        from spark_rapids_tpu.shuffle import serde

        def read_decode():
            data = np.fromfile(path, dtype=np.uint8)
            try:
                return serde.deserialize_table(data)
            except ShuffleChecksumError:
                self.checksum_failures += 1
                raise

        def count_retry(_exc):
            from spark_rapids_tpu.obs import events as obs_events

            with self._lock:
                self.fetch_retries += 1
            obs_events.emit("shuffle.retry", shuffleId=shuffle_id,
                            reducePid=reduce_pid,
                            block=os.path.basename(path))

        try:
            return backoff.retry_io(
                read_decode,
                what=f"shuffle block ({shuffle_id}, {reduce_pid}) "
                     f"{os.path.basename(path)}",
                site="shuffle.fetch",
                retry_on=(OSError, ShuffleChecksumError),
                absorb_sites=("shuffle.deserialize",),
                counter="shuffle.fetch",
                on_retry=count_retry)
        except RetryExhausted as e:
            raise ShuffleFetchError(
                f"shuffle block (shuffle_id={shuffle_id}, "
                f"reduce_pid={reduce_pid}) unrecoverable after retry "
                f"budget: {path}", map_id=map_id) from e

    def _maybe_lose_block(self, shuffle_id: int, reduce_pid: int,
                          map_id: Optional[int]) -> None:
        """Chaos site shuffle.lost_output: the block vanished AFTER the
        block-level retry budget (disk died, peer gone) — modeled only
        for attempt-tagged blocks, whose lineage the scheduler can
        recompute."""
        if map_id is None:
            return
        from spark_rapids_tpu.runtime import faults
        from spark_rapids_tpu.runtime.errors import ShuffleFetchError

        if faults.should_inject("shuffle.lost_output"):
            raise ShuffleFetchError(
                f"shuffle block (shuffle_id={shuffle_id}, "
                f"reduce_pid={reduce_pid}) lost (injected "
                f"shuffle.lost_output)", map_id=map_id)

    def fetch(self, shuffle_id: int, reduce_pid: int) -> List[pa.Table]:
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import telemetry
        from spark_rapids_tpu.runtime.errors import ShuffleFetchError

        from spark_rapids_tpu.runtime import cancellation

        if self.mode != "MULTITHREADED":
            with self._lock:
                snap = [(b.table, b.path, b.map_id) for b in
                        self._blocks.get((shuffle_id, reduce_pid), [])]
            out = []
            for table, path, map_id in snap:
                # per-block yield point: a cancelled query stops
                # fetching instead of finishing the reduce partition
                cancellation.check_current()
                self._maybe_lose_block(shuffle_id, reduce_pid, map_id)
                if table is not None:
                    out.append(table)
                else:
                    out.append(self._fetch_block(path, shuffle_id,
                                                 reduce_pid, map_id))
            obs_events.emit("shuffle.fetch", shuffleId=shuffle_id,
                            reducePid=reduce_pid, blocks=len(out),
                            bytes=sum(t.nbytes for t in out))
            telemetry.record("shuffle", "shuffle.fetch",
                             sum(t.nbytes for t in out))
            return out
        with self._lock:
            fbs = list(self._files.get((shuffle_id, reduce_pid), []))
        tables = []
        for fb in fbs:
            cancellation.check_current()
            self._maybe_lose_block(shuffle_id, reduce_pid, fb.map_id)
            try:
                path = fb.future.result()  # blocks on in-flight writes
            except Exception as e:
                # a writer-thread failure surfaces as the read path's
                # clean engine error, not a raw codec/IO traceback
                raise ShuffleFetchError(
                    f"shuffle block (shuffle_id={shuffle_id}, "
                    f"reduce_pid={reduce_pid}) writer failed: "
                    f"{type(e).__name__}: {e}",
                    map_id=fb.map_id) from e
            tables.append(self._fetch_block(path, shuffle_id,
                                            reduce_pid, fb.map_id))
        obs_events.emit("shuffle.fetch", shuffleId=shuffle_id,
                        reducePid=reduce_pid, blocks=len(tables),
                        bytes=sum(t.nbytes for t in tables))
        telemetry.record("shuffle", "shuffle.fetch",
                         sum(t.nbytes for t in tables))
        return tables

    def remove_shuffle(self, shuffle_id: int):
        from spark_rapids_tpu.runtime import host_alloc

        pageable = host_alloc.get().pageable
        with self._lock:
            spilled_paths = []
            for k in [k for k in self._blocks if k[0] == shuffle_id]:
                for b in self._blocks.pop(k):
                    if b.table is not None:
                        self.bytes_in_memory -= b.nbytes
                        pageable.release(b.nbytes)
                    elif b.path:
                        spilled_paths.append(b.path)
            futs = []
            for k in [k for k in self._files if k[0] == shuffle_id]:
                futs.extend(self._files.pop(k))
            # staged attempts that never committed (abandoned
            # speculative losers, failed map stages) go with the
            # shuffle too — nothing may outlive remove_shuffle
            for k in [k for k in self._staged if k[0] == shuffle_id]:
                for _rp, b in self._staged.pop(k):
                    if isinstance(b, _MemBlock):
                        if b.table is not None:
                            self.bytes_in_memory -= b.nbytes
                            pageable.release(b.nbytes)
                        elif b.path:
                            spilled_paths.append(b.path)
                    else:
                        futs.append(b)
            for k in [k for k in self._committed if k[0] == shuffle_id]:
                del self._committed[k]
        # wait + unlink OUTSIDE the lock so unrelated shuffles proceed;
        # failures are counted (shuffle.orphanedFiles), not swallowed —
        # a leaked spill file must be visible
        for p in spilled_paths:
            try:
                os.unlink(p)
            except OSError:
                with self._lock:
                    self.orphaned_files += 1
        for fb in futs:
            fut = fb.future if isinstance(fb, _FileBlock) else fb
            try:
                path = fut.result()
            except Exception:
                continue  # write never landed: no file to remove
            try:
                os.unlink(path)
            except OSError:
                with self._lock:
                    self.orphaned_files += 1

    def shutdown(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)


_manager = ShuffleManager()
_mgr_lock = threading.Lock()


def configure_shuffle(mode: str, shuffle_dir: str = None,
                      num_threads: int = 8, codec: str = "none",
                      spill_threshold: int = 2 << 30,
                      checksum: bool = True):
    """Install a manager for the session's shuffle settings (reference
    GpuShuffleEnv.initShuffleManager, Plugin.scala:531)."""
    global _manager
    with _mgr_lock:
        settings = (mode, shuffle_dir, num_threads, codec,
                    spill_threshold, checksum)
        if getattr(_manager, "_settings", None) != settings:
            _manager.shutdown()
            _manager = ShuffleManager(mode, shuffle_dir, num_threads,
                                      codec, spill_threshold, checksum)
            _manager._settings = settings
    return _manager


def get_shuffle_manager() -> ShuffleManager:
    return _manager
