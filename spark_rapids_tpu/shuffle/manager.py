"""Shuffle manager — the MULTITHREADED / CACHE_ONLY transport analog.

Reference (`RapidsShuffleInternalManagerBase.scala:238,569,1183`): the
MULTITHREADED mode serializes device batches on a writer thread pool into
host shuffle storage (files), readers fetch and coalesce back onto the
device (`GpuShuffleCoalesceExec`). The UCX device-to-device transport's
analog is the ICI collective path (parallel/collective.py +
parallel/plan_compiler.py).

Failure domain (PR 2 hardening): every block carries a per-block CRC
(shuffle/serde.py, conf spark.rapids.shuffle.checksum.enabled) and
every fetch/decode of an on-disk block runs under the shared
exponential-backoff policy (runtime/backoff.py) — torn files, bit
rot, and injected shuffle.fetch / shuffle.deserialize faults
(runtime/faults.py) are retried `io.retry.attempts` times before a
clean ShuffleFetchError names the exact block. Retries are counted
(`fetch_retries`) so the bench tracks robustness overhead.

Modes here (conf spark.rapids.shuffle.mode):
- CACHE_ONLY: blocks live as in-process host Arrow tables under a host
  byte ledger; when in-memory block bytes exceed the spill threshold the
  coldest blocks degrade to compressed disk files (the
  ShuffleBufferCatalog spill-integration role — blocks are never lost,
  they move tiers).
- MULTITHREADED: blocks are serialized through the native wire format
  (shuffle/serde.py, the JCudfSerialization analog), optionally
  compressed (TableCompressionCodec role), and written to shuffle files
  by a writer thread pool; readers block on the in-flight writes for
  their partition then deserialize.
"""

from __future__ import annotations

import os
import tempfile
import threading
from collections import defaultdict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa


class _MemBlock:
    __slots__ = ("table", "path", "nbytes", "seq")

    def __init__(self, table: Optional[pa.Table], nbytes: int, seq: int):
        self.table = table          # None once spilled
        self.path: Optional[str] = None
        self.nbytes = nbytes
        self.seq = seq


class ShuffleManager:
    """Maps (shuffle_id, reduce_pid) -> shuffle blocks."""

    def __init__(self, mode: str = "CACHE_ONLY", shuffle_dir: str = None,
                 num_threads: int = 8, codec: str = "none",
                 spill_threshold: int = 2 << 30, checksum: bool = True):
        self.mode = mode
        self.codec = codec
        self.checksum = checksum
        self.spill_threshold = spill_threshold
        self.fetch_retries = 0
        self.checksum_failures = 0
        self._blocks: Dict[Tuple[int, int], List[_MemBlock]] = defaultdict(
            list)
        self._files: Dict[Tuple[int, int], List[Future]] = defaultdict(
            list)
        self._lock = threading.Lock()
        self._next_id = 0
        self.bytes_written = 0
        self.bytes_in_memory = 0
        self.blocks_spilled = 0
        self._dir = shuffle_dir
        self._pool = None
        self._seq = 0
        if mode == "MULTITHREADED":
            self._dir = shuffle_dir or tempfile.mkdtemp(
                prefix="srtpu-shuffle-")
            self._pool = ThreadPoolExecutor(
                max_workers=max(1, num_threads),
                thread_name_prefix="shuffle-writer")

    def new_shuffle_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def _spill_dir(self) -> str:
        if self._dir is None:
            self._dir = tempfile.mkdtemp(prefix="srtpu-shuffle-")
        return self._dir

    def _spill_block(self, b: _MemBlock):
        """Under lock: move one in-memory block to a compressed disk
        file (does not touch ledgers — callers own the accounting)."""
        from spark_rapids_tpu.shuffle import serde

        path = os.path.join(self._spill_dir(),
                            f"shuffle-spill-{b.seq}.stpu")
        serde.serialize_table(b.table, codec=self.codec,
                              checksum=self.checksum).tofile(path)
        # path BEFORE table: fetch() snapshots (table, path) and
        # must never observe both unset
        b.path = path
        b.table = None
        self.blocks_spilled += 1

    def _spill_mem_blocks(self):
        """Under lock: move coldest (oldest) in-memory blocks to
        compressed disk files until under the threshold."""
        from spark_rapids_tpu.runtime import host_alloc

        pageable = host_alloc.get().pageable
        victims: List[_MemBlock] = []
        for blocks in self._blocks.values():
            victims.extend(b for b in blocks if b.table is not None)
        victims.sort(key=lambda b: b.seq)
        for b in victims:
            if self.bytes_in_memory <= self.spill_threshold:
                break
            self._spill_block(b)
            self.bytes_in_memory -= b.nbytes
            pageable.release(b.nbytes)

    def put(self, shuffle_id: int, reduce_pid: int, table: pa.Table):
        if self.mode != "MULTITHREADED":
            from spark_rapids_tpu.runtime import host_alloc

            # in-memory shuffle blocks draw from the GLOBAL pageable
            # host budget (runtime/host_alloc.py, HostAlloc role); when
            # the budget is gone this block goes straight to disk
            in_mem = host_alloc.get().pageable.try_reserve(table.nbytes)
            with self._lock:
                self._seq += 1
                blk = _MemBlock(table, table.nbytes, self._seq)
                self._blocks[(shuffle_id, reduce_pid)].append(blk)
                self.bytes_written += table.nbytes
                if in_mem:
                    self.bytes_in_memory += table.nbytes
                    if self.bytes_in_memory > self.spill_threshold:
                        self._spill_mem_blocks()
                else:
                    try:
                        self._spill_block(blk)
                    except BaseException:
                        # drop the half-registered block: it holds no
                        # reservation, and remove_shuffle's
                        # table-means-reserved accounting must never
                        # see it
                        self._blocks[(shuffle_id, reduce_pid)].remove(
                            blk)
                        raise
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
        path = os.path.join(
            self._dir, f"shuffle-{shuffle_id}-{reduce_pid}-{seq}.stpu")

        def write():
            from spark_rapids_tpu.shuffle import serde

            buf = serde.serialize_table(table, codec=self.codec,
                                        checksum=self.checksum)
            with open(path, "wb") as f:
                buf.tofile(f)
            with self._lock:
                self.bytes_written += buf.nbytes
            return path

        fut = self._pool.submit(write)
        with self._lock:
            self._files[(shuffle_id, reduce_pid)].append(fut)

    def partition_sizes(self, shuffle_id: int, nparts: int) -> List[int]:
        """Per-reduce-partition byte sizes of a materialized shuffle —
        the MapOutputStatistics role AQE re-planning consumes."""
        out = [0] * nparts
        with self._lock:
            for (sid, rp), blks in self._blocks.items():
                if sid == shuffle_id and rp < nparts:
                    out[rp] += sum(b.nbytes for b in blks)
            futs = [((sid, rp), list(fs))
                    for (sid, rp), fs in self._files.items()
                    if sid == shuffle_id and rp < nparts]
        import os as _os

        for (sid, rp), fs in futs:
            for f in fs:
                try:
                    out[rp] += _os.path.getsize(f.result())
                except OSError:
                    pass
        return out

    def _fetch_block(self, path: str, shuffle_id: int,
                     reduce_pid: int) -> pa.Table:
        """Read + decode one on-disk block under the backoff policy:
        OSError / checksum mismatch / injected shuffle.fetch or
        shuffle.deserialize faults each consume an attempt (re-reading
        the file is the repair for all of them); the exhausted budget
        surfaces as a ShuffleFetchError naming the block."""
        from spark_rapids_tpu.runtime import backoff
        from spark_rapids_tpu.runtime.errors import (
            RetryExhausted,
            ShuffleChecksumError,
            ShuffleFetchError,
        )
        from spark_rapids_tpu.shuffle import serde

        def read_decode():
            data = np.fromfile(path, dtype=np.uint8)
            try:
                return serde.deserialize_table(data)
            except ShuffleChecksumError:
                self.checksum_failures += 1
                raise

        def count_retry(_exc):
            with self._lock:
                self.fetch_retries += 1

        try:
            return backoff.retry_io(
                read_decode,
                what=f"shuffle block ({shuffle_id}, {reduce_pid}) "
                     f"{os.path.basename(path)}",
                site="shuffle.fetch",
                retry_on=(OSError, ShuffleChecksumError),
                absorb_sites=("shuffle.deserialize",),
                counter="shuffle.fetch",
                on_retry=count_retry)
        except RetryExhausted as e:
            raise ShuffleFetchError(
                f"shuffle block (shuffle_id={shuffle_id}, "
                f"reduce_pid={reduce_pid}) unrecoverable after retry "
                f"budget: {path}") from e

    def fetch(self, shuffle_id: int, reduce_pid: int) -> List[pa.Table]:
        if self.mode != "MULTITHREADED":
            with self._lock:
                snap = [(b.table, b.path) for b in
                        self._blocks.get((shuffle_id, reduce_pid), [])]
            out = []
            for table, path in snap:
                if table is not None:
                    out.append(table)
                else:
                    out.append(self._fetch_block(path, shuffle_id,
                                                 reduce_pid))
            return out
        with self._lock:
            futs = list(self._files.get((shuffle_id, reduce_pid), []))
        tables = []
        for fut in futs:
            path = fut.result()  # blocks on in-flight writes
            tables.append(self._fetch_block(path, shuffle_id,
                                            reduce_pid))
        return tables

    def remove_shuffle(self, shuffle_id: int):
        from spark_rapids_tpu.runtime import host_alloc

        pageable = host_alloc.get().pageable
        with self._lock:
            spilled_paths = []
            for k in [k for k in self._blocks if k[0] == shuffle_id]:
                for b in self._blocks.pop(k):
                    if b.table is not None:
                        self.bytes_in_memory -= b.nbytes
                        pageable.release(b.nbytes)
                    elif b.path:
                        spilled_paths.append(b.path)
            futs = []
            for k in [k for k in self._files if k[0] == shuffle_id]:
                futs.extend(self._files.pop(k))
        # wait + unlink OUTSIDE the lock so unrelated shuffles proceed
        for p in spilled_paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        for fut in futs:
            try:
                os.unlink(fut.result())
            except Exception:
                pass

    def shutdown(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)


_manager = ShuffleManager()
_mgr_lock = threading.Lock()


def configure_shuffle(mode: str, shuffle_dir: str = None,
                      num_threads: int = 8, codec: str = "none",
                      spill_threshold: int = 2 << 30,
                      checksum: bool = True):
    """Install a manager for the session's shuffle settings (reference
    GpuShuffleEnv.initShuffleManager, Plugin.scala:531)."""
    global _manager
    with _mgr_lock:
        settings = (mode, shuffle_dir, num_threads, codec,
                    spill_threshold, checksum)
        if getattr(_manager, "_settings", None) != settings:
            _manager.shutdown()
            _manager = ShuffleManager(mode, shuffle_dir, num_threads,
                                      codec, spill_threshold, checksum)
            _manager._settings = settings
    return _manager


def get_shuffle_manager() -> ShuffleManager:
    return _manager
