"""Columnar shuffle wire format — the JCudfSerialization analog
(reference GpuColumnarBatchSerializer.scala:82,170: cuDF serialized
tables, header + raw buffers, written to shuffle streams).

A table serializes to ONE contiguous framed buffer: [schema IPC bytes,
meta JSON, column buffers...] packed by the native runtime
(native/sparktpu_runtime.cpp stpu_pack) with 64-byte alignment so
deserialization is zero-copy buffer slicing. Flat types only (primitives,
strings, dates/timestamps/decimals) — the engine's device surface.
"""

from __future__ import annotations

import json
from typing import List

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import native


def serialize_table(table: pa.Table) -> np.ndarray:
    """Arrow table -> one contiguous uint8 buffer."""
    schema_buf = np.frombuffer(table.schema.serialize(), dtype=np.uint8)
    bufs: List[np.ndarray] = []
    col_specs = []
    for col in table.columns:
        arr = col.combine_chunks()
        if arr.offset != 0:
            arr = arr.take(pa.array(np.arange(len(arr))))
        spec = {"nbufs": 0, "present": []}
        for b in arr.buffers():
            if b is None:
                spec["present"].append(False)
                continue
            spec["present"].append(True)
            bufs.append(np.frombuffer(b, dtype=np.uint8))
            spec["nbufs"] += 1
        col_specs.append(spec)
    meta = json.dumps({"nrows": table.num_rows,
                       "cols": col_specs}).encode()
    meta_buf = np.frombuffer(meta, dtype=np.uint8)
    return native.pack_buffers([schema_buf, meta_buf] + bufs)


def deserialize_table(data: np.ndarray) -> pa.Table:
    parts = native.unpack_buffers(data)
    schema = pa.ipc.read_schema(pa.py_buffer(parts[0].tobytes()))
    meta = json.loads(bytes(parts[1]))
    arrays = []
    bi = 2
    for field, spec in zip(schema, meta["cols"]):
        buffers = []
        for present in spec["present"]:
            if present:
                buffers.append(pa.py_buffer(parts[bi].tobytes()))
                bi += 1
            else:
                buffers.append(None)
        arrays.append(pa.Array.from_buffers(field.type, meta["nrows"],
                                            buffers))
    return pa.Table.from_arrays(arrays, schema=schema)
