"""Columnar shuffle wire format — the JCudfSerialization analog
(reference GpuColumnarBatchSerializer.scala:82,170: cuDF serialized
tables, header + raw buffers, written to shuffle streams).

A table serializes to ONE contiguous framed buffer: [schema IPC bytes,
meta JSON, column buffers...] packed by the native runtime
(native/sparktpu_runtime.cpp stpu_pack) with 64-byte alignment so
deserialization is zero-copy buffer slicing for flat columns
(primitives, strings, dates/timestamps/decimals). Nested columns
(list/struct/map) ride as per-column arrow-IPC record batches inside
the same frame — their child buffers interleave in Array.buffers(),
so raw slicing cannot reassemble them.

Optional block compression (`codec=`) wraps the packed frame with a
10-byte header [magic u8, codec u8, raw_len i64] — the
TableCompressionCodec / NvcompLZ4CompressionCodec role (reference
compresses shuffle payloads with nvcomp LZ4/ZSTD; here zstd level 1 or
zlib on the host).

Per-block checksums (`checksum=`, default on) add an outermost
14-byte envelope [magic u8, algo u8, crc u32, payload_len i64] over
the whole frame, verified on deserialize: a torn shuffle file or a
bit flip surfaces as ShuffleChecksumError (which the shuffle manager
retries with backoff) instead of a corrupt query result. crc32c is
used when the wheel is installed, else zlib's crc32 — the algorithm id
rides in the header so readers never guess. Checksum-less frames from
older writers still deserialize.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import List

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import native
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime.errors import ShuffleChecksumError

_CODEC_MAGIC = 0xC7
_CODECS = {"none": 0, "zstd": 1, "zlib": 2}
_CODEC_NAMES = {v: k for k, v in _CODECS.items()}

_CRC_MAGIC = 0xCC
_ALGO_CRC32C = 1
_ALGO_CRC32 = 2
_CRC_HEADER = struct.Struct("<BBIq")

try:
    import crc32c as _crc32c_mod
except ImportError:
    _crc32c_mod = None


def _checksum(data) -> tuple:
    """-> (algo_id, crc) of a bytes-like; crc32c preferred (hardware-
    accelerated where available, and what the reference storage stack
    uses), stdlib crc32 otherwise."""
    if _crc32c_mod is not None:
        return _ALGO_CRC32C, _crc32c_mod.crc32c(data) & 0xFFFFFFFF
    return _ALGO_CRC32, zlib.crc32(data) & 0xFFFFFFFF


def _checksum_with(algo: int, data) -> int:
    if algo == _ALGO_CRC32C:
        if _crc32c_mod is None:
            raise ShuffleChecksumError(
                "block checksummed with crc32c but no crc32c module is "
                "available to verify it")
        return _crc32c_mod.crc32c(data) & 0xFFFFFFFF
    return zlib.crc32(data) & 0xFFFFFFFF


def zstd_available() -> bool:
    """The zstandard wheel is optional at runtime; environments without
    it degrade the DEFAULT codec to stdlib zlib rather than failing
    every shuffle (the stream header records whatever was actually
    used, so readers never guess)."""
    try:
        import zstandard  # noqa: F401

        return True
    except ImportError:
        return False


def resolve_codec(codec: str) -> str:
    if codec == "zstd" and not zstd_available():
        return "zlib"
    return codec


def _compress(raw: bytes, codec: str) -> bytes:
    if codec == "zstd":
        import zstandard

        return zstandard.ZstdCompressor(level=1).compress(raw)
    if codec == "zlib":
        import zlib

        return zlib.compress(raw, level=1)
    return raw


def _decompress(payload: bytes, codec: str, raw_len: int) -> bytes:
    if codec == "zstd":
        import zstandard

        return zstandard.ZstdDecompressor().decompress(
            payload, max_output_size=raw_len)
    if codec == "zlib":
        import zlib

        return zlib.decompress(payload)
    return payload


def serialize_table(table: pa.Table, codec: str = "none",
                    checksum: bool = True) -> np.ndarray:
    """Arrow table -> one contiguous uint8 buffer (optionally
    codec-compressed, CRC-framed unless checksum=False)."""
    schema_buf = np.frombuffer(table.schema.serialize(), dtype=np.uint8)
    bufs: List[np.ndarray] = []
    col_specs = []
    for ci, col in enumerate(table.columns):
        arr = col.combine_chunks()
        if pa.types.is_nested(arr.type) or \
                pa.types.is_dictionary(arr.type):
            # nested columns (list/struct/map) carry CHILD arrays whose
            # buffers interleave in Array.buffers(); frame them as one
            # arrow-IPC record batch instead of raw buffer slices. The
            # IPC writer handles sliced arrays natively, so no offset
            # normalization (shuffle map slices make offset != 0 the
            # common case here). DICTIONARY columns take the same IPC
            # frame: the block then carries CODES plus one dictionary
            # reference instead of decoded values (compressed
            # execution's shuffle representation), and the reduce-side
            # re-upload re-interns the dictionary by content so the
            # device copy dedupes across blocks.
            sink = pa.BufferOutputStream()
            rb = pa.record_batch([arr],
                                 schema=pa.schema(
                                     [table.schema.field(ci)]))
            with pa.ipc.new_stream(sink, rb.schema) as w:
                w.write_batch(rb)
            bufs.append(np.frombuffer(sink.getvalue(), dtype=np.uint8))
            col_specs.append({"ipc": True})
            continue
        if arr.offset != 0:
            # flat columns serialize as raw buffer slices, which cannot
            # express a nonzero offset
            arr = arr.take(pa.array(np.arange(len(arr))))
        spec = {"nbufs": 0, "present": []}
        for b in arr.buffers():
            if b is None:
                spec["present"].append(False)
                continue
            spec["present"].append(True)
            bufs.append(np.frombuffer(b, dtype=np.uint8))
            spec["nbufs"] += 1
        col_specs.append(spec)
    meta = json.dumps({"nrows": table.num_rows,
                       "cols": col_specs}).encode()
    meta_buf = np.frombuffer(meta, dtype=np.uint8)
    packed = native.pack_buffers([schema_buf, meta_buf] + bufs)
    codec = resolve_codec(codec)
    if codec != "none":
        raw = packed.tobytes()
        payload = _compress(raw, codec)
        header = struct.pack("<BBq", _CODEC_MAGIC, _CODECS[codec],
                             len(raw))
        packed = np.frombuffer(header + payload, dtype=np.uint8)
    if not checksum:
        return packed
    body = packed.tobytes()
    algo, crc = _checksum(body)
    env = _CRC_HEADER.pack(_CRC_MAGIC, algo, crc, len(body))
    return np.frombuffer(env + body, dtype=np.uint8)


def _unwrap_checksum(data: np.ndarray) -> np.ndarray:
    """Strip + verify the CRC envelope when present. The magic byte
    alone could collide with a raw packed frame, so the header only
    counts when the recorded payload length matches exactly."""
    if data.size < _CRC_HEADER.size or int(data[0]) != _CRC_MAGIC or \
            int(data[1]) not in (_ALGO_CRC32C, _ALGO_CRC32):
        return data
    magic, algo, want, plen = _CRC_HEADER.unpack(
        data[:_CRC_HEADER.size].tobytes())
    if plen != data.size - _CRC_HEADER.size:
        return data
    payload = data[_CRC_HEADER.size:]
    got = _checksum_with(algo, payload.tobytes())
    if got != want:
        raise ShuffleChecksumError(
            f"shuffle block checksum mismatch "
            f"(algo={'crc32c' if algo == _ALGO_CRC32C else 'crc32'}, "
            f"expected {want:#010x}, got {got:#010x}, "
            f"{plen} payload bytes)")
    return payload


def deserialize_table(data: np.ndarray) -> pa.Table:
    faults.maybe_inject("shuffle.deserialize")
    data = _unwrap_checksum(data)
    if data.size >= 10 and int(data[0]) == _CODEC_MAGIC and \
            int(data[1]) in (1, 2):
        magic, codec_id, raw_len = struct.unpack("<BBq",
                                                 data[:10].tobytes())
        raw = _decompress(data[10:].tobytes(), _CODEC_NAMES[codec_id],
                          raw_len)
        data = np.frombuffer(raw, dtype=np.uint8)
    parts = native.unpack_buffers(data)
    schema = pa.ipc.read_schema(pa.py_buffer(parts[0].tobytes()))
    meta = json.loads(bytes(parts[1]))
    arrays = []
    bi = 2
    for field, spec in zip(schema, meta["cols"]):
        if spec.get("ipc"):
            with pa.ipc.open_stream(
                    pa.py_buffer(parts[bi].tobytes())) as r:
                rb = r.read_all()
            bi += 1
            arrays.append(rb.column(0).combine_chunks())
            continue
        buffers = []
        for present in spec["present"]:
            if present:
                buffers.append(pa.py_buffer(parts[bi].tobytes()))
                bi += 1
            else:
                buffers.append(None)
        arrays.append(pa.Array.from_buffers(field.type, meta["nrows"],
                                            buffers))
    return pa.Table.from_arrays(arrays, schema=schema)
