"""Columnar shuffle wire format — the JCudfSerialization analog
(reference GpuColumnarBatchSerializer.scala:82,170: cuDF serialized
tables, header + raw buffers, written to shuffle streams).

A table serializes to ONE contiguous framed buffer: [schema IPC bytes,
meta JSON, column buffers...] packed by the native runtime
(native/sparktpu_runtime.cpp stpu_pack) with 64-byte alignment so
deserialization is zero-copy buffer slicing for flat columns
(primitives, strings, dates/timestamps/decimals). Nested columns
(list/struct/map) ride as per-column arrow-IPC record batches inside
the same frame — their child buffers interleave in Array.buffers(),
so raw slicing cannot reassemble them.

Optional block compression (`codec=`) wraps the packed frame with a
10-byte header [magic u8, codec u8, raw_len i64] — the
TableCompressionCodec / NvcompLZ4CompressionCodec role (reference
compresses shuffle payloads with nvcomp LZ4/ZSTD; here zstd level 1 or
zlib on the host).
"""

from __future__ import annotations

import json
import struct
from typing import List

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import native

_CODEC_MAGIC = 0xC7
_CODECS = {"none": 0, "zstd": 1, "zlib": 2}
_CODEC_NAMES = {v: k for k, v in _CODECS.items()}


def zstd_available() -> bool:
    """The zstandard wheel is optional at runtime; environments without
    it degrade the DEFAULT codec to stdlib zlib rather than failing
    every shuffle (the stream header records whatever was actually
    used, so readers never guess)."""
    try:
        import zstandard  # noqa: F401

        return True
    except ImportError:
        return False


def resolve_codec(codec: str) -> str:
    if codec == "zstd" and not zstd_available():
        return "zlib"
    return codec


def _compress(raw: bytes, codec: str) -> bytes:
    if codec == "zstd":
        import zstandard

        return zstandard.ZstdCompressor(level=1).compress(raw)
    if codec == "zlib":
        import zlib

        return zlib.compress(raw, level=1)
    return raw


def _decompress(payload: bytes, codec: str, raw_len: int) -> bytes:
    if codec == "zstd":
        import zstandard

        return zstandard.ZstdDecompressor().decompress(
            payload, max_output_size=raw_len)
    if codec == "zlib":
        import zlib

        return zlib.decompress(payload)
    return payload


def serialize_table(table: pa.Table, codec: str = "none") -> np.ndarray:
    """Arrow table -> one contiguous uint8 buffer (optionally
    codec-compressed)."""
    schema_buf = np.frombuffer(table.schema.serialize(), dtype=np.uint8)
    bufs: List[np.ndarray] = []
    col_specs = []
    for ci, col in enumerate(table.columns):
        arr = col.combine_chunks()
        if pa.types.is_nested(arr.type):
            # nested columns (list/struct/map) carry CHILD arrays whose
            # buffers interleave in Array.buffers(); frame them as one
            # arrow-IPC record batch instead of raw buffer slices. The
            # IPC writer handles sliced arrays natively, so no offset
            # normalization (shuffle map slices make offset != 0 the
            # common case here)
            sink = pa.BufferOutputStream()
            rb = pa.record_batch([arr],
                                 schema=pa.schema(
                                     [table.schema.field(ci)]))
            with pa.ipc.new_stream(sink, rb.schema) as w:
                w.write_batch(rb)
            bufs.append(np.frombuffer(sink.getvalue(), dtype=np.uint8))
            col_specs.append({"ipc": True})
            continue
        if arr.offset != 0:
            # flat columns serialize as raw buffer slices, which cannot
            # express a nonzero offset
            arr = arr.take(pa.array(np.arange(len(arr))))
        spec = {"nbufs": 0, "present": []}
        for b in arr.buffers():
            if b is None:
                spec["present"].append(False)
                continue
            spec["present"].append(True)
            bufs.append(np.frombuffer(b, dtype=np.uint8))
            spec["nbufs"] += 1
        col_specs.append(spec)
    meta = json.dumps({"nrows": table.num_rows,
                       "cols": col_specs}).encode()
    meta_buf = np.frombuffer(meta, dtype=np.uint8)
    packed = native.pack_buffers([schema_buf, meta_buf] + bufs)
    codec = resolve_codec(codec)
    if codec == "none":
        return packed
    raw = packed.tobytes()
    payload = _compress(raw, codec)
    header = struct.pack("<BBq", _CODEC_MAGIC, _CODECS[codec], len(raw))
    return np.frombuffer(header + payload, dtype=np.uint8)


def deserialize_table(data: np.ndarray) -> pa.Table:
    if data.size >= 10 and int(data[0]) == _CODEC_MAGIC and \
            int(data[1]) in (1, 2):
        magic, codec_id, raw_len = struct.unpack("<BBq",
                                                 data[:10].tobytes())
        raw = _decompress(data[10:].tobytes(), _CODEC_NAMES[codec_id],
                          raw_len)
        data = np.frombuffer(raw, dtype=np.uint8)
    parts = native.unpack_buffers(data)
    schema = pa.ipc.read_schema(pa.py_buffer(parts[0].tobytes()))
    meta = json.loads(bytes(parts[1]))
    arrays = []
    bi = 2
    for field, spec in zip(schema, meta["cols"]):
        if spec.get("ipc"):
            with pa.ipc.open_stream(
                    pa.py_buffer(parts[bi].tobytes())) as r:
                rb = r.read_all()
            bi += 1
            arrays.append(rb.column(0).combine_chunks())
            continue
        buffers = []
        for present in spec["present"]:
            if present:
                buffers.append(pa.py_buffer(parts[bi].tobytes()))
                bi += 1
            else:
                buffers.append(None)
        arrays.append(pa.Array.from_buffers(field.type, meta["nrows"],
                                            buffers))
    return pa.Table.from_arrays(arrays, schema=schema)
