"""Shim loader — the ShimLoader / SparkShimServiceProvider analog.

The reference supports 24 Spark versions by compiling per-version
"parallel worlds" source trees and mounting the right one at runtime
(sql-plugin-api/.../ShimLoader.scala:182, SparkShimServiceProvider SPI,
build/shimplify.py). The moving target here is JAX, whose public API
shifted across releases (shard_map moved from jax.experimental to the
jax namespace and renamed check_rep -> check_vma, among others). Each
shim module is a provider declaring which jax versions it serves; the
loader probes providers at first use and every caller goes through the
selected world.

Adding support for a new jax release = adding one provider module, the
same mechanics as adding a spark3xx world in the reference.
"""

from __future__ import annotations

import threading
from typing import List, Optional

_PROVIDERS = (
    "spark_rapids_tpu.shims.jax_current",
    "spark_rapids_tpu.shims.jax_legacy",
)

# Every provider must export exactly this surface (api_validation
# checks it; see tools/api_validation.py and tests/test_shims.py)
SHIM_API = (
    "VERSIONS",
    "matches",
    "shard_map",
    "make_mesh",
    "description",
)

_lock = threading.Lock()
_selected = None


class ShimError(RuntimeError):
    pass


def _jax_version() -> str:
    import jax

    return jax.__version__


def detect_shim_provider(version: Optional[str] = None):
    """Probe providers in order; first match wins (ShimLoader.
    detectShimProvider analog)."""
    import importlib

    v = version or _jax_version()
    tried: List[str] = []
    for name in _PROVIDERS:
        mod = importlib.import_module(name)
        if mod.matches(v):
            return mod
        tried.append(f"{name} (serves {mod.VERSIONS})")
    raise ShimError(
        f"no shim provider serves jax {v}; probed: {tried}")


def get_shim():
    """The active shim world (cached after first detection)."""
    global _selected
    with _lock:
        if _selected is None:
            _selected = detect_shim_provider()
        return _selected
