"""Shim world for jax 0.4.x / 0.5.x: `jax.experimental.shard_map`
with the pre-rename `check_rep` flag."""

from __future__ import annotations

VERSIONS = ("0.4", "0.5")


def matches(version: str) -> bool:
    return version.startswith(VERSIONS)


def description() -> str:
    return "jax.experimental.shard_map world (jax 0.4-0.5)"


def shard_map(fn, mesh, in_specs, out_specs, check: bool = False):
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check)


def make_mesh(devices, axis_name: str):
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(devices), (axis_name,))
