"""Shim world for jax >= 0.6: `jax.shard_map` with `check_vma`."""

from __future__ import annotations

VERSIONS = ("0.6", "0.7", "0.8", "0.9", "1.")


def matches(version: str) -> bool:
    return version.startswith(VERSIONS)


def description() -> str:
    return "jax.shard_map world (jax >= 0.6)"


def shard_map(fn, mesh, in_specs, out_specs, check: bool = False):
    """Bind the SPMD program over the mesh (replication checking off by
    default: batch row counts legitimately differ per shard)."""
    import jax

    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check)


def make_mesh(devices, axis_name: str):
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(devices), (axis_name,))
