from spark_rapids_tpu.columnar.batch import (  # noqa: F401
    DeviceColumn,
    ColumnBatch,
    concat_batches,
    make_column,
    next_capacity,
    row_mask,
)
from spark_rapids_tpu.columnar.arrow_bridge import (  # noqa: F401
    arrow_to_device,
    device_to_arrow,
    arrow_to_pandas,
)
