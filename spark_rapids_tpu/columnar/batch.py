"""Device columnar batch — the `GpuColumnVector`/`ColumnarBatch` analog.

The reference wraps cuDF device columns as Spark `ColumnarBatch` columns
(`sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java:555`).
Here the device format is designed for XLA on TPU instead of for cuDF:

- Every batch has a **static row capacity** (power-of-two bucket) plus a
  traced `num_rows` scalar. XLA compiles one program per (schema, capacity)
  bucket; refills of the same bucket hit the jit cache. This is the answer
  to "dynamic shapes on XLA" (SURVEY.md section 7 hard part #1): operators
  whose output size is data-dependent (filter, join, aggregate) write into
  full-capacity buffers and carry the logical row count as data.
- Columns are validity-masked flat arrays; strings are a padded byte matrix
  plus a length vector (see sqltypes.datatypes.StringType).
- `ColumnBatch`/`DeviceColumn` are registered JAX pytrees so jitted kernels
  take and return them natively, and `jax.device_put`/`device_get` move
  whole batches for the spill tiers.

Rows at index >= num_rows are garbage; every kernel masks with
``row_mask(capacity, num_rows)``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.sqltypes import (
    DataType,
    StringType,
    StructField,
    StructType,
)

MIN_CAPACITY = 1024

# device-epoch stamp source (runtime/device_monitor.py). Lazy module
# ref: importing device_monitor at module level would cycle through
# the runtime package __init__ back into this module.
_dm = None


def _current_epoch() -> int:
    global _dm
    if _dm is None:
        from spark_rapids_tpu.runtime import device_monitor

        _dm = device_monitor
    return _dm._EPOCH


def next_capacity(rows: int, minimum: int = MIN_CAPACITY) -> int:
    """Smallest power-of-two capacity bucket holding `rows`."""
    cap = max(int(minimum), 1)
    rows = max(int(rows), 1)
    while cap < rows:
        cap <<= 1
    return cap


def row_mask(capacity: int, num_rows) -> jnp.ndarray:
    """Boolean [capacity] mask of logically-live rows."""
    return jnp.arange(capacity, dtype=jnp.int32) < jnp.asarray(
        num_rows, dtype=jnp.int32)


class DeviceColumn:
    """One device column: data (+ lengths for strings/arrays) + validity.

    data:     [cap] of dtype.np_dtype; [cap, max_bytes] uint8 for strings;
              [cap, max_elems] of element np_dtype for arrays;
              [cap, max_elems, max_bytes] uint8 for array<string>
    lengths:  [cap] int32 (strings: byte count; arrays: element count)
    validity: [cap] bool, True = valid (non-null row)
    elem_validity: [cap, max_elems] bool (arrays only): per-element nulls
    elem_lengths:  [cap, max_elems] int32 (array<string> only): per-
              element byte counts
    encoding: DeviceDictionary (columnar/encoding.py) for DICTIONARY-
              ENCODED string columns: `data` is then a [cap] vector of
              integer codes into the shared device dictionary and
              `lengths` is None; decode is deferred to the last
              operator that needs materialized values.
    """

    __slots__ = ("dtype", "data", "validity", "lengths",
                 "elem_validity", "map_values", "vrange", "children",
                 "elem_lengths", "encoding", "epoch")

    def __init__(self, dtype: DataType, data, validity, lengths=None,
                 elem_validity=None, map_values=None, vrange=None,
                 children=None, elem_lengths=None, encoding=None,
                 epoch=None):
        self.dtype = dtype
        self.data = data          # maps: the KEY matrix
        self.validity = validity
        self.lengths = lengths
        self.elem_validity = elem_validity  # maps: VALUE validity
        self.map_values = map_values        # maps only: value matrix
        self.elem_lengths = elem_lengths    # array<string> only
        # STATIC (lo, hi) bound on the column's integer values, stamped
        # at upload time (quantized so refills retrace rarely). Enables
        # the sort-free direct-binned group-by; ops that change values
        # drop it (None).
        self.vrange = vrange
        # STRUCT columns: per-field child DeviceColumns (struct-of-
        # arrays; the cuDF nested-column role). `data` is a [cap] int8
        # placeholder carrying the capacity; row-level ops recurse.
        self.children = children
        # DICTIONARY-ENCODED strings: the shared DeviceDictionary
        # (columnar/encoding.py); data is then [cap] integer codes
        self.encoding = encoding
        # DEVICE EPOCH stamp (runtime/device_monitor.py): which
        # generation of the PJRT backend this column's device buffers
        # belong to. Checked at dispatch/unspill use sites — a column
        # stamped before a device-loss recovery raises DeviceLostError
        # instead of touching recycled device memory. Deliberately NOT
        # part of the pytree aux: treedefs (and thus traced programs)
        # are epoch-independent; unflattened columns re-stamp at the
        # current epoch because their leaves were just produced by the
        # live backend.
        self.epoch = _current_epoch() if epoch is None else epoch

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, StringType)

    @property
    def is_encoded(self) -> bool:
        return self.encoding is not None

    @property
    def is_array(self) -> bool:
        from spark_rapids_tpu.sqltypes import ArrayType

        return isinstance(self.dtype, ArrayType)

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def max_bytes(self) -> Optional[int]:
        return int(self.data.shape[1]) \
            if self.is_string and self.data.ndim == 2 else None

    @property
    def max_elems(self) -> Optional[int]:
        return int(self.data.shape[1]) if self.is_array else None

    @property
    def is_struct(self) -> bool:
        return self.children is not None

    def truncate(self, cap: int) -> "DeviceColumn":
        """Row-prefix view [:cap] of every per-row leaf (trace-safe;
        static slice); the shared dictionary of an encoded column is
        NOT row-shaped and rides unchanged. Callers guarantee live
        rows fit in cap."""
        return DeviceColumn(
            self.dtype, self.data[:cap], self.validity[:cap],
            None if self.lengths is None else self.lengths[:cap],
            None if self.elem_validity is None
            else self.elem_validity[:cap],
            None if self.map_values is None else self.map_values[:cap],
            self.vrange,
            None if self.children is None
            else [c.truncate(cap) for c in self.children],
            None if self.elem_lengths is None
            else self.elem_lengths[:cap],
            encoding=self.encoding, epoch=self.epoch)

    def device_size_bytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize
        n += self.validity.size  # bool = 1 byte
        if self.lengths is not None:
            n += self.lengths.size * 4
        if self.elem_validity is not None:
            n += self.elem_validity.size
        if self.map_values is not None:
            n += self.map_values.size * self.map_values.dtype.itemsize
        if self.elem_lengths is not None:
            n += self.elem_lengths.size * 4
        if self.children is not None:
            n += sum(c.device_size_bytes() for c in self.children)
        # the dictionary of an encoded column is deliberately EXCLUDED:
        # it is shared across every referencing batch and owned/charged
        # by the encoding cache's own SpillCatalog reservation
        # (columnar/encoding.py device_dictionary)
        return n

    def with_validity(self, validity) -> "DeviceColumn":
        return self.replace(validity=validity)

    def replace(self, **kw) -> "DeviceColumn":
        """Copy with selected leaves replaced. The ONLY sanctioned way
        to rebuild a column from an existing one — hand-rolled
        DeviceColumn(c.dtype, c.data, ...) constructions silently drop
        leaves added later (struct children taught this the hard way)."""
        return DeviceColumn(
            kw.get("dtype", self.dtype),
            kw.get("data", self.data),
            kw.get("validity", self.validity),
            kw.get("lengths", self.lengths),
            kw.get("elem_validity", self.elem_validity),
            kw.get("map_values", self.map_values),
            kw.get("vrange", self.vrange),
            kw.get("children", self.children),
            kw.get("elem_lengths", self.elem_lengths),
            encoding=kw.get("encoding", self.encoding),
            epoch=kw.get("epoch", self.epoch),
        )

    def gather(self, indices) -> "DeviceColumn":
        """Row gather; indices must be in [0, capacity). Gathered values
        are a subset, so the static vrange bound survives — and for an
        encoded column only the [cap] CODES move (the dictionary is
        shared, which is exactly why join payload gathers over encoded
        strings are cheap)."""
        return DeviceColumn(
            self.dtype,
            jnp.take(self.data, indices, axis=0),
            jnp.take(self.validity, indices, axis=0),
            None if self.lengths is None else jnp.take(self.lengths, indices,
                                                       axis=0),
            None if self.elem_validity is None else jnp.take(
                self.elem_validity, indices, axis=0),
            None if self.map_values is None else jnp.take(
                self.map_values, indices, axis=0),
            vrange=self.vrange,
            children=None if self.children is None
            else [c.gather(indices) for c in self.children],
            elem_lengths=None if self.elem_lengths is None
            else jnp.take(self.elem_lengths, indices, axis=0),
            encoding=self.encoding,
            epoch=self.epoch,
        )

    def _tree_flatten(self):
        leaves = [self.data, self.validity]
        if self.lengths is not None:
            leaves.append(self.lengths)
        if self.elem_validity is not None:
            leaves.append(self.elem_validity)
        if self.map_values is not None:
            leaves.append(self.map_values)
        if self.elem_lengths is not None:
            leaves.append(self.elem_lengths)
        if self.encoding is not None:
            # DeviceDictionary is a registered pytree node; its aux
            # carries the dict_id, so a different dictionary means a
            # different treedef (and a retrace) by construction
            leaves.append(self.encoding)
        if self.children is not None:
            # child DeviceColumns are registered pytree nodes; jax
            # recurses into them
            leaves.extend(self.children)
        return tuple(leaves), (self.dtype, self.lengths is not None,
                               self.elem_validity is not None,
                               self.map_values is not None, self.vrange,
                               len(self.children)
                               if self.children is not None else -1,
                               self.elem_lengths is not None,
                               self.encoding is not None)

    @classmethod
    def _tree_unflatten(cls, aux, children):
        (dtype, has_len, has_ev, has_mv, vrange, n_struct, has_el,
         has_enc) = aux
        it = iter(children)
        data = next(it)
        validity = next(it)
        lengths = next(it) if has_len else None
        ev = next(it) if has_ev else None
        mv = next(it) if has_mv else None
        el = next(it) if has_el else None
        enc = next(it) if has_enc else None
        kids = ([next(it) for _ in range(n_struct)]
                if n_struct >= 0 else None)
        return cls(dtype, data, validity, lengths, ev, mv, vrange, kids,
                   el, encoding=enc)


jax.tree_util.register_pytree_node(
    DeviceColumn,
    lambda c: c._tree_flatten(),
    DeviceColumn._tree_unflatten,
)


class ColumnBatch:
    """A batch of device columns with shared capacity and row count.

    `num_rows` may be a Python int or a traced/device int32 scalar; inside
    jitted kernels it is always traced. `row_count()` forces a host value
    (device sync) and caches it.
    """

    __slots__ = ("schema", "columns", "num_rows", "_host_rows")

    def __init__(self, schema: StructType, columns: List[DeviceColumn],
                 num_rows):
        assert len(schema.fields) == len(columns)
        self.schema = schema
        self.columns = columns
        self.num_rows = num_rows
        self._host_rows = num_rows if isinstance(num_rows, int) else None

    @property
    def capacity(self) -> int:
        if not self.columns:
            return MIN_CAPACITY
        return self.columns[0].capacity

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def row_count(self) -> int:
        if self._host_rows is None:
            from spark_rapids_tpu.obs import telemetry

            self._host_rows = int(telemetry.ledgered_get(
                self.num_rows, "batch.rowCount"))
        return self._host_rows

    def live_mask(self) -> jnp.ndarray:
        return row_mask(self.capacity, self.num_rows)

    def device_size_bytes(self) -> int:
        return sum(c.device_size_bytes() for c in self.columns)

    def column(self, name: str) -> DeviceColumn:
        return self.columns[self.schema.field_index(name)]

    def select(self, indices: Sequence[int]) -> "ColumnBatch":
        return ColumnBatch(
            StructType([self.schema.fields[i] for i in indices]),
            [self.columns[i] for i in indices],
            self.num_rows,
        )

    def gather(self, indices, new_num_rows) -> "ColumnBatch":
        return ColumnBatch(
            self.schema, [c.gather(indices) for c in self.columns],
            new_num_rows)

    def _tree_flatten(self):
        nr = self.num_rows
        if isinstance(nr, (int, np.integer)):
            nr = jnp.asarray(nr, jnp.int32)
        return (tuple(self.columns), nr), self.schema

    @classmethod
    def _tree_unflatten(cls, schema, children):
        columns, num_rows = children
        return cls(schema, list(columns), num_rows)

    def __repr__(self):
        return (f"ColumnBatch(rows={self._host_rows or '?'}, "
                f"cap={self.capacity}, cols={self.schema.names})")


jax.tree_util.register_pytree_node(
    ColumnBatch,
    lambda b: b._tree_flatten(),
    ColumnBatch._tree_unflatten,
)


def make_column(dtype: DataType, values: np.ndarray,
                validity: Optional[np.ndarray], capacity: int,
                lengths: Optional[np.ndarray] = None,
                elem_validity: Optional[np.ndarray] = None) -> DeviceColumn:
    """Build a column from host numpy data, padding to capacity. The
    returned column holds NUMPY leaves — the caller uploads the whole
    batch with ONE jax.device_put (per-array jnp.asarray costs ~6x in
    transfer setup, and far more over tunneled devices).

    For strings, `values` is a [n, max_bytes] uint8 matrix and `lengths`
    the per-row byte counts. For arrays, `values` is [n, max_elems] of
    the element dtype, `lengths` the element counts, and `elem_validity`
    the per-element null mask.
    """
    from spark_rapids_tpu.sqltypes import ArrayType

    # maps pass (key_matrix, value_matrix)
    n = len(values[0]) if isinstance(values, tuple) else len(values)
    if validity is None:
        validity = np.ones(n, dtype=np.bool_)
    vpad = np.zeros(capacity, dtype=np.bool_)
    vpad[:n] = validity
    if isinstance(dtype, StringType):
        assert values.ndim == 2 and values.dtype == np.uint8
        data = np.zeros((capacity, values.shape[1]), dtype=np.uint8)
        data[:n, :] = values
        lpad = np.zeros(capacity, dtype=np.int32)
        if lengths is not None:
            lpad[:n] = lengths
        return DeviceColumn(dtype, data, vpad, lpad)
    if isinstance(dtype, ArrayType) and isinstance(dtype.elementType,
                                                   StringType):
        # array<string>: (values cube [n, E, B] uint8, per-element byte
        # lengths [n, E]) arrive as a tuple
        cube, elens = values
        assert cube.ndim == 3 and cube.dtype == np.uint8
        data = np.zeros((capacity,) + cube.shape[1:], dtype=np.uint8)
        data[:n] = cube
        lpad = np.zeros(capacity, dtype=np.int32)
        if lengths is not None:
            lpad[:n] = lengths
        ev = np.zeros((capacity, cube.shape[1]), dtype=np.bool_)
        if elem_validity is not None:
            ev[:n] = elem_validity
        el = np.zeros((capacity, cube.shape[1]), dtype=np.int32)
        el[:n] = elens
        return DeviceColumn(dtype, data, vpad, lpad, ev,
                            elem_lengths=el)
    if isinstance(dtype, ArrayType):
        assert values.ndim == 2
        data = np.zeros((capacity, values.shape[1]),
                        dtype=dtype.elementType.np_dtype)
        data[:n, :] = values
        lpad = np.zeros(capacity, dtype=np.int32)
        if lengths is not None:
            lpad[:n] = lengths
        ev = np.zeros((capacity, values.shape[1]), dtype=np.bool_)
        if elem_validity is not None:
            ev[:n, :] = elem_validity
        return DeviceColumn(dtype, data, vpad, lpad, ev)
    from spark_rapids_tpu.sqltypes import MapType

    if isinstance(dtype, MapType):
        # values is (key_matrix, value_matrix); elem_validity covers
        # VALUES (map keys are never null)
        kmat, vmat = values
        me = kmat.shape[1]
        kd = np.zeros((capacity, me), dtype=dtype.keyType.np_dtype)
        kd[:n, :] = kmat
        vd = np.zeros((capacity, me), dtype=dtype.valueType.np_dtype)
        vd[:n, :] = vmat
        lpad = np.zeros(capacity, dtype=np.int32)
        if lengths is not None:
            lpad[:n] = lengths
        ev = np.zeros((capacity, me), dtype=np.bool_)
        if elem_validity is not None:
            ev[:n, :] = elem_validity
        return DeviceColumn(dtype, kd, vpad, lpad, ev, vd)
    if values.ndim == 2:  # DECIMAL128 limb matrix [n, 2]
        data = np.zeros((capacity, 2), dtype=np.int64)
        data[:n, :] = values
        return DeviceColumn(dtype, data, vpad)
    data = np.zeros(capacity, dtype=dtype.np_dtype)
    data[:n] = values
    return DeviceColumn(dtype, data, vpad)


def row_select(pred, x, y):
    """Row-wise where: broadcast a [cap] predicate across every
    trailing axis of x/y (strings, arrays, array<string> cubes)."""
    return jnp.where(pred.reshape((-1,) + (1,) * (x.ndim - 1)), x, y)


def pad_trailing(x, trailing):
    """Zero-pad x's trailing axes up to `trailing` (no-op when equal) —
    the one alignment primitive for variable-width leaves (string
    bytes, array elems, array<string> elems x bytes)."""
    if x is None or tuple(x.shape[1:]) == tuple(trailing):
        return x
    return jnp.pad(x, ((0, 0),) + tuple(
        (0, t - s) for s, t in zip(x.shape[1:], trailing)))


def align_trailing(leaves):
    """Pad every leaf's trailing axes to the per-axis max across
    leaves (all leaves must share ndim)."""
    nd = leaves[0].ndim
    if nd == 1:
        return list(leaves)
    target = tuple(max(int(x.shape[ax]) for x in leaves)
                   for ax in range(1, nd))
    return [pad_trailing(x, target) for x in leaves]


def _empty_column(dataType: DataType, capacity: int,
                  string_bytes: int) -> DeviceColumn:
    from spark_rapids_tpu.sqltypes import ArrayType

    if isinstance(dataType, StringType):
        return DeviceColumn(
            dataType,
            jnp.zeros((capacity, string_bytes), jnp.uint8),
            jnp.zeros(capacity, jnp.bool_),
            jnp.zeros(capacity, jnp.int32))
    if isinstance(dataType, ArrayType):
        et = dataType.elementType
        if isinstance(et, StringType):  # array<string> cube layout
            return DeviceColumn(
                dataType,
                jnp.zeros((capacity, 1, string_bytes), jnp.uint8),
                jnp.zeros(capacity, jnp.bool_),
                jnp.zeros(capacity, jnp.int32),
                jnp.zeros((capacity, 1), jnp.bool_),
                elem_lengths=jnp.zeros((capacity, 1), jnp.int32))
        return DeviceColumn(
            dataType,
            jnp.zeros((capacity, 1), et.np_dtype),
            jnp.zeros(capacity, jnp.bool_),
            jnp.zeros(capacity, jnp.int32),
            jnp.zeros((capacity, 1), jnp.bool_))
    if isinstance(dataType, StructType):
        return DeviceColumn(
            dataType, jnp.zeros(capacity, jnp.int8),
            jnp.zeros(capacity, jnp.bool_),
            children=[_empty_column(f.dataType, capacity, string_bytes)
                      for f in dataType.fields])
    from spark_rapids_tpu.ops import decimal128 as _d128

    shape = ((capacity, 2) if _d128.is_wide(dataType)
             else (capacity,))
    return DeviceColumn(
        dataType,
        jnp.zeros(shape, dataType.np_dtype),
        jnp.zeros(capacity, jnp.bool_))


def empty_like_schema(schema: StructType, capacity: int,
                      string_bytes: int = 8) -> ColumnBatch:
    cols = [_empty_column(f.dataType, capacity, string_bytes)
            for f in schema.fields]
    return ColumnBatch(schema, cols, 0)


def _concat_columns(pieces: List[Tuple[DeviceColumn, int]], cap: int,
                    total: int, dtype: DataType) -> DeviceColumn:
    """Concatenate per-batch column prefixes into one [cap] column
    (recursing into struct children). Encoded pieces stay encoded only
    when every piece shares ONE dictionary; any identity mismatch
    decodes first (code spaces are not comparable across
    dictionaries)."""
    if any(c.encoding is not None for c, _ in pieces):
        from spark_rapids_tpu.columnar import encoding as _enc

        aligned = _enc.align_encodings([c for c, _ in pieces])
        pieces = list(zip(aligned, (n for _, n in pieces)))
    first = pieces[0][0]
    if first.children is not None:
        kids = [
            _concat_columns([(c.children[i], n) for c, n in pieces],
                            cap, total, first.children[i].dtype)
            for i in range(len(first.children))
        ]
        pad = cap - total
        val = jnp.pad(jnp.concatenate(
            [c.validity[:n] for c, n in pieces]), (0, pad))
        data = jnp.zeros((cap,), jnp.int8)
        return DeviceColumn(dtype, data, val, children=kids)
    def align_cat(parts):
        """Concatenate row prefixes, padding every TRAILING axis to
        its max across pieces (string bytes, array elems, and both
        axes of an array<string> cube)."""
        parts = align_trailing(parts)
        out = jnp.concatenate(parts, axis=0)
        if pad:
            out = jnp.pad(out,
                          ((0, pad),) + ((0, 0),) * (out.ndim - 1))
        return out

    pad = cap - total
    data = align_cat([c.data[:n] for c, n in pieces])
    val = align_cat([c.validity[:n] for c, n in pieces])
    lens = ev = mv = el = None
    if first.lengths is not None:
        lens = align_cat([c.lengths[:n] for c, n in pieces])
    if first.elem_validity is not None:
        ev = align_cat([c.elem_validity[:n] for c, n in pieces])
    if first.map_values is not None:
        mv = align_cat([c.map_values[:n] for c, n in pieces])
    if first.elem_lengths is not None:
        el = align_cat([c.elem_lengths[:n] for c, n in pieces])
    # encoded columns keep their [0, K) code bound through concat (the
    # binned group-by depends on it); plain columns keep the historical
    # drop-vrange-at-concat behavior
    vr = first.vrange if (
        first.encoding is not None
        and all(c.vrange == first.vrange for c, _ in pieces)) else None
    return DeviceColumn(dtype, data, val, lens, ev, mv, vrange=vr,
                        elem_lengths=el, encoding=first.encoding)


def concat_batches(batches: List[ColumnBatch]) -> ColumnBatch:
    """Concatenate batches (cuDF `Table.concatenate` analog) — the engine of
    coalescing (reference GpuCoalesceBatches.scala:250)."""
    assert batches
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    total = sum(b.row_count() for b in batches)
    cap = next_capacity(total)
    cols: List[DeviceColumn] = []
    for ci, field in enumerate(schema.fields):
        pieces = [(b.columns[ci], b.row_count()) for b in batches]
        cols.append(_concat_columns(pieces, cap, total, field.dataType))
    return ColumnBatch(schema, cols, total)
