"""Host (Arrow) <-> device (ColumnBatch) transitions.

The reference's row/columnar transitions are `GpuRowToColumnarExec` and
`GpuColumnarToRowExec` plus the cuDF host<->device copies
(`GpuRowToColumnarExec.scala:861`, `GpuColumnarToRowExec.scala:335`). Here
the host-side columnar currency is pyarrow (which also backs the CPU oracle
backend and the file readers), so the transitions are Arrow<->ColumnBatch:

- arrow_to_device: pads each column into its capacity bucket, builds the
  string byte-matrix layout vectorized in numpy (no per-row Python), and
  `jax.device_put`s the result.
- device_to_arrow: slices to the logical row count and rebuilds Arrow
  arrays, reconstructing string offsets from the padded matrix.
"""

from __future__ import annotations

import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar.batch import (
    ColumnBatch,
    DeviceColumn,
    make_column,
    next_capacity,
)
from spark_rapids_tpu.sqltypes import (
    ArrayType,
    DataType,
    DecimalType,
    MapType,
    StringType,
    StructField,
    StructType,
)
from spark_rapids_tpu.sqltypes.datatypes import from_arrow_type, to_arrow_type


def _round_up_pow2(n: int, minimum: int = 8) -> int:
    c = minimum
    while c < n:
        c <<= 1
    return c


def schema_from_arrow(schema: pa.Schema) -> StructType:
    return StructType([
        StructField(f.name, from_arrow_type(f.type), f.nullable)
        for f in schema
    ])


def _check_string_ceiling(max_len: int) -> None:
    """Enforce spark.rapids.tpu.string.maxBytes: the padded-matrix
    width adapts per column, but a pathological value (a megabyte blob)
    would multiply the whole column's footprint — fail loudly with the
    conf escape hatch instead."""
    from spark_rapids_tpu.config import rapids_conf as rc

    ceiling = rc.STRING_MAX_BYTES.default
    try:
        from spark_rapids_tpu.api.session import TpuSparkSession

        s = TpuSparkSession.active()
        if s is not None:
            ceiling = s.rapids_conf.get(rc.STRING_MAX_BYTES)
    except Exception:
        pass
    if max_len > ceiling:
        from spark_rapids_tpu.runtime.errors import StringWidthExceeded

        raise StringWidthExceeded(
            f"string of {max_len} bytes exceeds the device padded-width "
            f"ceiling {ceiling} (spark.rapids.tpu.string.maxBytes); "
            "query falls back to the CPU engine")


def _string_to_matrix(arr: pa.Array, pad_to: Optional[int] = None):
    """Arrow utf8 array -> ([n, max_bytes] uint8, lengths int32) vectorized."""
    arr = arr.cast(pa.large_string()) if pa.types.is_string(arr.type) else arr
    if pa.types.is_large_string(arr.type):
        offsets = np.frombuffer(arr.buffers()[1], dtype=np.int64,
                                count=len(arr) + arr.offset + 1)
    else:
        offsets = np.frombuffer(arr.buffers()[1], dtype=np.int32,
                                count=len(arr) + arr.offset + 1)
    offsets = offsets[arr.offset:arr.offset + len(arr) + 1].astype(np.int64)
    data_buf = arr.buffers()[2]
    flat = (np.frombuffer(data_buf, dtype=np.uint8)
            if data_buf is not None and len(data_buf) else
            np.zeros(1, dtype=np.uint8))
    lengths = (offsets[1:] - offsets[:-1]).astype(np.int32)
    max_len = int(lengths.max()) if len(lengths) else 0
    _check_string_ceiling(max_len)
    mb = _round_up_pow2(max(max_len, 1), minimum=pad_to or 8)
    n = len(arr)
    idx = offsets[:-1, None] + np.arange(mb, dtype=np.int64)[None, :]
    mask = np.arange(mb, dtype=np.int32)[None, :] < lengths[:, None]
    out = np.where(mask, flat[np.clip(idx, 0, len(flat) - 1)], 0).astype(
        np.uint8)
    return out, lengths


def _matrix_to_string(data: np.ndarray, lengths: np.ndarray,
                      validity: np.ndarray) -> pa.Array:
    """([n, mb] uint8, lengths, validity) -> Arrow utf8 array."""
    n = len(lengths)
    if n == 0:
        return pa.array([], type=pa.string())
    mb = data.shape[1]
    lengths = np.minimum(lengths.astype(np.int64), mb)
    mask = np.arange(mb)[None, :] < lengths[:, None]
    flat = data[mask]
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    arr = pa.StringArray.from_buffers(
        n, pa.py_buffer(offsets.tobytes()), pa.py_buffer(flat.tobytes()))
    if not validity.all():
        arr = pa.compute.if_else(pa.array(validity), arr,
                                 pa.nulls(n, pa.string()))
    return arr


def _list_to_matrix(arr: pa.Array, elem_dtype: DataType):
    """Arrow list<primitive> -> ([n, max_elems] element matrix,
    lengths int32, elem_validity [n, max_elems]) vectorized."""
    arr = arr.cast(pa.large_list(arr.type.value_type)) \
        if pa.types.is_list(arr.type) else arr
    offsets = np.asarray(arr.offsets).astype(np.int64)
    values = arr.values  # flat child array
    lengths = (offsets[1:] - offsets[:-1]).astype(np.int32)
    n = len(arr)
    max_len = int(lengths.max()) if len(lengths) else 0
    me = _round_up_pow2(max(max_len, 1), minimum=4)
    flat_vals, flat_valid = _primitive_np(values, elem_dtype)
    if len(flat_vals) == 0:
        flat_vals = np.zeros(1, dtype=elem_dtype.np_dtype)
        flat_valid = np.zeros(1, dtype=np.bool_)
    idx = offsets[:-1, None] + np.arange(me, dtype=np.int64)[None, :]
    in_row = np.arange(me, dtype=np.int32)[None, :] < lengths[:, None]
    safe = np.clip(idx, 0, len(flat_vals) - 1)
    mat = np.where(in_row, flat_vals[safe], 0).astype(elem_dtype.np_dtype)
    ev = np.where(in_row, flat_valid[safe], False)
    return mat, lengths, ev


def _strlist_to_cube(arr: pa.Array):
    """Arrow list<string> -> ([n, max_elems, max_bytes] uint8 cube,
    row lengths int32, elem_validity [n, E], elem byte lengths [n, E])
    — the string padded-matrix layout one level up."""
    arr = arr.cast(pa.large_list(pa.large_string())) \
        if not pa.types.is_large_list(arr.type) else arr
    offsets = np.asarray(arr.offsets).astype(np.int64)
    lengths = (offsets[1:] - offsets[:-1]).astype(np.int32)
    n = len(arr)
    max_e = int(lengths.max()) if len(lengths) else 0
    me = _round_up_pow2(max(max_e, 1), minimum=2)
    smat, slens = _string_to_matrix(arr.values)  # flat child strings
    svalid = np.asarray(arr.values.is_valid()) if len(arr.values) \
        else np.zeros(0, bool)
    if len(smat) == 0:
        smat = np.zeros((1, 1), np.uint8)
        slens = np.zeros(1, np.int32)
        svalid = np.zeros(1, bool)
    idx = offsets[:-1, None] + np.arange(me, dtype=np.int64)[None, :]
    in_row = np.arange(me, dtype=np.int32)[None, :] < lengths[:, None]
    safe = np.clip(idx, 0, len(smat) - 1)
    cube = np.where(in_row[:, :, None], smat[safe], 0)
    ev = np.where(in_row, svalid[safe], False)
    el = np.where(in_row, slens[safe], 0).astype(np.int32)
    return cube, lengths, ev, el


def _cube_to_strlist(data: np.ndarray, lengths: np.ndarray,
                     validity: np.ndarray, ev: np.ndarray,
                     el: np.ndarray) -> pa.Array:
    """Device array<string> cube -> Arrow list<string>, vectorized:
    flatten the in-row elements to one string matrix, reuse the
    offsets-reconstruction of _matrix_to_string, and wrap with list
    offsets — no per-element Python."""
    n = len(lengths)
    if n == 0:
        return pa.array([], type=pa.list_(pa.string()))
    E = data.shape[1]
    lengths = np.minimum(lengths, E)  # clamp like _matrix_to_list
    in_row = (np.arange(E, dtype=np.int32)[None, :] < lengths[:, None]
              ) & validity[:, None]  # null rows contribute no elements
    ri, ei = np.nonzero(in_row)           # kept elements, row-major
    flat = data[ri, ei]                   # [m, B] uint8
    flens = el[ri, ei].astype(np.int32)
    fvalid = ev[ri, ei]
    values = _matrix_to_string(flat, flens, fvalid)
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(np.where(validity, lengths, 0), out=offsets[1:])
    return pa.ListArray.from_arrays(
        pa.array(offsets), values,
        mask=None if validity.all() else pa.array(~validity))


def _matrix_to_list(data: np.ndarray, lengths: np.ndarray,
                    validity: np.ndarray, ev: np.ndarray,
                    elem_dtype: DataType) -> pa.Array:
    """Device array layout -> Arrow list<primitive>."""
    n = len(lengths)
    at = to_arrow_type(elem_dtype)
    if n == 0:
        return pa.array([], type=pa.list_(at))
    me = data.shape[1]
    lengths = np.minimum(lengths.astype(np.int64), me)
    in_row = np.arange(me)[None, :] < lengths[:, None]
    flat = data[in_row]
    flat_valid = ev[in_row]
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    if isinstance(elem_dtype, DecimalType):
        import decimal as _dec

        s = elem_dtype.scale
        with _dec.localcontext() as _ctx:
            _ctx.prec = 50  # scaleb rounds at context precision
            child = pa.array(
                [_dec.Decimal(int(v)).scaleb(-s) if ok else None
                 for v, ok in zip(flat, flat_valid)], type=at)
    else:
        child = pa.array(flat, type=at,
                         mask=None if flat_valid.all() else ~flat_valid)
    mask = None if validity.all() else pa.array(~validity)
    return pa.ListArray.from_arrays(pa.array(offsets, type=pa.int32()),
                                    child, mask=mask)


def _map_to_matrices(arr: pa.Array, dt):
    """Arrow map<k, v> -> (key matrix, value matrix, lengths,
    value validity) in the device padded-matrix layout."""
    offsets = np.asarray(arr.offsets).astype(np.int64)
    offsets = offsets[:len(arr) + 1]
    lengths = (offsets[1:] - offsets[:-1]).astype(np.int32)
    n = len(arr)
    max_len = int(lengths.max()) if len(lengths) else 0
    me = _round_up_pow2(max(max_len, 1), minimum=4)
    kvals, _ = _primitive_np(arr.keys, dt.keyType)
    vvals, vvalid = _primitive_np(arr.items, dt.valueType)
    if len(kvals) == 0:
        kvals = np.zeros(1, dtype=dt.keyType.np_dtype)
        vvals = np.zeros(1, dtype=dt.valueType.np_dtype)
        vvalid = np.zeros(1, dtype=np.bool_)
    idx = offsets[:-1, None] + np.arange(me, dtype=np.int64)[None, :]
    in_row = np.arange(me, dtype=np.int32)[None, :] < lengths[:, None]
    safe = np.clip(idx, 0, len(kvals) - 1)
    kmat = np.where(in_row, kvals[safe], 0).astype(dt.keyType.np_dtype)
    vmat = np.where(in_row, vvals[safe], 0).astype(
        dt.valueType.np_dtype)
    ev = np.where(in_row, vvalid[safe], False)
    return kmat, vmat, lengths, ev


def _matrices_to_map(kmat: np.ndarray, vmat: np.ndarray,
                     lengths: np.ndarray, validity: np.ndarray,
                     vvalid: np.ndarray, dt) -> pa.Array:
    """Device map layout -> Arrow map array."""
    at = to_arrow_type(dt)
    n = len(lengths)
    if n == 0:
        return pa.array([], type=at)
    me = kmat.shape[1]
    lengths = np.minimum(lengths.astype(np.int64), me)
    in_row = np.arange(me)[None, :] < lengths[:, None]
    flat_k = kmat[in_row]
    flat_v = vmat[in_row]
    flat_vv = vvalid[in_row]
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])

    def child_array(flat, t, target_type, mask):
        if isinstance(t, DecimalType):
            import decimal as _dec

            with _dec.localcontext() as _ctx:
                _ctx.prec = 50
                return pa.array(
                    [_dec.Decimal(int(v)).scaleb(-t.scale)
                     if ok else None
                     for v, ok in zip(flat, (np.ones(len(flat), bool)
                                             if mask is None else mask))],
                    type=target_type)
        return pa.array(flat, type=target_type,
                        mask=None if mask is None or mask.all()
                        else ~mask)

    keys = child_array(flat_k, dt.keyType, at.key_type, None)
    items = child_array(flat_v, dt.valueType, at.item_type, flat_vv)
    mask = None if validity.all() else pa.array(~validity)
    if mask is not None:
        # MapArray.from_arrays has no mask param in older pyarrow;
        # compose via null substitution
        m = pa.MapArray.from_arrays(pa.array(offsets, type=pa.int32()),
                                    keys, items)
        return pa.compute.if_else(pa.array(validity), m,
                                  pa.nulls(n, at))
    return pa.MapArray.from_arrays(pa.array(offsets, type=pa.int32()),
                                   keys, items)


def _primitive_np(arr: pa.Array, dtype: DataType):
    """Arrow primitive array -> (np values with nulls zero-filled, validity)."""
    validity = np.asarray(arr.is_valid())
    at = arr.type
    if pa.types.is_decimal(at):
        # 16-byte little-endian two's complement words from the
        # decimal128 buffer directly (vectorized). precision<=18: the
        # low word IS the value (DECIMAL64); wider: [n, 2] (hi, lo)
        # limb matrix (the device DECIMAL128 layout, ops/decimal128.py).
        arr128 = arr.cast(pa.decimal128(38, at.scale))
        buf = arr128.buffers()[1]
        words = np.frombuffer(buf, dtype=np.int64,
                              count=(arr128.offset + len(arr128)) * 2)
        words = words[arr128.offset * 2:(arr128.offset + len(arr128)) * 2]
        lo = words[0::2].copy()
        if isinstance(dtype, DecimalType) and \
                dtype.precision > DecimalType.MAX_LONG_DIGITS:
            hi = words[1::2].copy()
            lo[~validity] = 0
            hi[~validity] = 0
            return np.stack([hi, lo], axis=1), validity
        lo[~validity] = 0
        return lo, validity
    if pa.types.is_timestamp(at):
        arr = arr.cast(pa.timestamp("us", tz=getattr(at, "tz", None) or "UTC"))
        vals = np.asarray(arr.cast(pa.int64()).fill_null(0))
        return vals.astype(np.int64), validity
    if pa.types.is_date32(at):
        vals = np.asarray(arr.cast(pa.int32()).fill_null(0))
        return vals.astype(np.int32), validity
    if pa.types.is_boolean(at):
        vals = np.asarray(arr.fill_null(False))
        return vals.astype(np.bool_), validity
    fill = arr.type
    zero = 0
    vals = np.asarray(arr.fill_null(zero))
    return vals.astype(dtype.np_dtype), validity


def column_from_arrow(arr, field, cap: int,
                      string_pad_min: int = 8) -> DeviceColumn:
    """One pyarrow array -> one capacity-padded host-numpy DeviceColumn
    (shared by arrow_to_device and the fused executor's narrowed
    upload). THE encoding-aware entry point for dictionary columns:
    low-cardinality strings upload as codes + a deduplicated device
    dictionary (columnar/encoding.py); everything else decodes through
    the ONE shared `encoding.dictionary_decode` so the two upload paths
    can never disagree on null handling again."""
    if pa.types.is_dictionary(arr.type):
        from spark_rapids_tpu.columnar import encoding as _enc

        enc_col = _enc.encoded_column_from_arrow(arr, field, cap)
        if enc_col is not None:
            return enc_col
        arr = _enc.dictionary_decode(arr)
    if isinstance(field.dataType, StringType):
        mat, lengths = _string_to_matrix(arr, pad_to=string_pad_min)
        validity = np.asarray(arr.is_valid())
        return make_column(field.dataType, mat, validity, cap,
                           lengths=lengths)
    if isinstance(field.dataType, ArrayType):
        if isinstance(field.dataType.elementType, StringType):
            cube, lengths, ev, el = _strlist_to_cube(arr)
            validity = np.asarray(arr.is_valid())
            return make_column(field.dataType, (cube, el), validity,
                               cap, lengths=lengths, elem_validity=ev)
        mat, lengths, ev = _list_to_matrix(
            arr, field.dataType.elementType)
        validity = np.asarray(arr.is_valid())
        return make_column(field.dataType, mat, validity, cap,
                           lengths=lengths, elem_validity=ev)
    if isinstance(field.dataType, MapType):
        kmat, vmat, lengths, vvalid = _map_to_matrices(
            arr, field.dataType)
        validity = np.asarray(arr.is_valid())
        return make_column(field.dataType, (kmat, vmat),
                           validity, cap, lengths=lengths,
                           elem_validity=vvalid)
    if isinstance(field.dataType, StructType):
        # struct-of-arrays: one child DeviceColumn per field, parent
        # validity for row nullity
        n = len(arr)
        validity = np.asarray(arr.is_valid()) if n else np.zeros(0, bool)
        vpad = np.zeros(cap, dtype=np.bool_)
        vpad[:n] = validity
        kids = [
            column_from_arrow(
                arr.field(i) if n else pa.array(
                    [], type=to_arrow_type(f.dataType)),
                f, cap, string_pad_min)
            for i, f in enumerate(field.dataType.fields)]
        return DeviceColumn(field.dataType, np.zeros(cap, np.int8),
                            vpad, children=kids)
    vals, validity = _primitive_np(arr, field.dataType)
    return make_column(field.dataType, vals, validity, cap)


def arrow_to_device(table, capacity: Optional[int] = None,
                    string_pad_min: int = 8) -> ColumnBatch:
    """pyarrow Table/RecordBatch -> device ColumnBatch."""
    if isinstance(table, pa.RecordBatch):
        table = pa.Table.from_batches([table])
    table = table.combine_chunks()
    n = table.num_rows
    cap = capacity or next_capacity(n)
    schema = schema_from_arrow(table.schema)
    cols: List[DeviceColumn] = []
    for i, field in enumerate(schema.fields):
        col = table.column(i)
        arr = (col.chunk(0) if col.num_chunks else
               pa.array([], type=table.schema.field(i).type))
        cols.append(column_from_arrow(arr, field, cap, string_pad_min))
    # ONE transfer for the whole batch: batched device_put is ~6x
    # faster than per-array jnp.asarray, and hugely so on tunneled
    # devices (make_column returns numpy-backed columns). The staging
    # bytes ride the pinned transfer budget (runtime/host_alloc.py,
    # PinnedMemoryPool role). device_put dispatches asynchronously, so
    # the scope bounds concurrent DISPATCHES, not completion — syncing
    # here would serialize the upload pipeline the engine works hard
    # to keep full on tunneled devices.
    from spark_rapids_tpu.obs import telemetry
    from spark_rapids_tpu.runtime import host_alloc

    nbytes = sum(c.device_size_bytes() for c in cols)
    with host_alloc.get().reserved(nbytes, pinned=True):
        t0 = time.monotonic_ns()
        out = jax.device_put(ColumnBatch(schema, cols, n))
        # ns covers the DISPATCH only (device_put is async by design
        # here) — bytes are exact, per-site GB/s is an upper bound
        telemetry.record("h2d", "upload.arrow", nbytes,
                         ns=time.monotonic_ns() - t0)
    out._host_rows = n  # pytree flatten devicified num_rows; keep the
    # known count so the first row_count() is not a device roundtrip
    return out


def _attached_dict_bytes(batch: ColumnBatch) -> int:
    """Bytes of the DISTINCT dictionaries riding a batch's encoded
    columns — they cross the link with the batch pytree, so D2H
    accounting must include them (once per distinct dictionary)."""
    seen = {}
    for c in batch.columns:
        dd = getattr(c, "encoding", None)
        if dd is not None:
            seen[dd.dict_id] = dd.size_bytes()
    return sum(seen.values())


def device_to_arrow(batch: ColumnBatch,
                    encoded: bool = False) -> pa.Table:
    """Device ColumnBatch -> pyarrow Table (device->host boundary).

    Slices to the smallest capacity bucket ON DEVICE before the D2H
    copy: operators hand back full-capacity buffers (an aggregate over
    a 4M-row batch returns a 4M-capacity result holding 2K groups), and
    fetching dead capacity dominates wall time on PCIe — and utterly
    dominates on tunneled devices.

    Encoded columns fetch as CODES + their (small) dictionary and
    decode host-side — the link never carries decoded strings. With
    `encoded=True` (the shuffle write path) the arrow output keeps them
    as DictionaryArrays, so shuffle blocks carry codes + a per-block
    dictionary reference instead of decoded values."""
    n = batch.row_count()
    small = next_capacity(n)
    if small < batch.capacity:
        batch = ColumnBatch(
            batch.schema,
            [c.truncate(small) for c in batch.columns],
            n)
    from spark_rapids_tpu.obs import telemetry
    from spark_rapids_tpu.runtime import host_alloc

    nbytes = batch.device_size_bytes() + _attached_dict_bytes(batch)
    with host_alloc.get().reserved(nbytes, pinned=True):
        t0 = time.monotonic_ns()
        host = jax.device_get(batch)
        telemetry.record("d2h", "collect", nbytes,
                         ns=time.monotonic_ns() - t0)
    return _host_batch_to_arrow(batch.schema, host.columns, n,
                                encoded=encoded)


def device_to_arrow_fused(batch: ColumnBatch, extra):
    """Single-sync D2H variant: fetches (batch, extra) in ONE
    device_get — no row_count pre-sync, no on-device slice; the row
    count rides along and slicing happens host-side. On high-latency
    links (tunneled devices: ~100-180 ms per roundtrip measured) the
    dead-capacity bytes of a small result are far cheaper than the two
    extra roundtrips the standard path pays. Callers should keep the
    standard `device_to_arrow` for large-capacity results.

    Returns (table, host_extra)."""
    from spark_rapids_tpu.obs import telemetry
    from spark_rapids_tpu.runtime import host_alloc

    nbytes = batch.device_size_bytes() + _attached_dict_bytes(batch)
    with host_alloc.get().reserved(nbytes, pinned=True):
        t0 = time.monotonic_ns()
        host, host_extra = jax.device_get((batch, extra))
        telemetry.record("d2h", "collect.fused", nbytes,
                         ns=time.monotonic_ns() - t0)
    n = int(np.asarray(host.num_rows))
    return _host_batch_to_arrow(host.schema, host.columns, n), host_extra


def _host_batch_to_arrow(schema, host_columns, n: int,
                         encoded: bool = False) -> pa.Table:
    arrays = []
    names = []
    for field, col in zip(schema.fields, host_columns):
        names.append(field.name)
        arrays.append(_host_column_to_array(field, col, n,
                                            encoded=encoded))
    return pa.Table.from_arrays(arrays, names=names)


def _host_column_to_array(field, col, n: int,
                          encoded: bool = False) -> pa.Array:
    validity = np.asarray(col.validity[:n])
    if getattr(col, "encoding", None) is not None:
        # encoded column: the fetched leaves are [n] codes plus the
        # shared dictionary — decode host-side (a numpy gather), or
        # keep the DictionaryArray for the shuffle wire
        dd = col.encoding
        ddata = np.asarray(dd.data)
        dlens = np.asarray(dd.lengths)
        k = max(ddata.shape[0], 1)
        codes = np.clip(np.asarray(col.data[:n]).astype(np.int64),
                        0, k - 1)
        if encoded:
            from spark_rapids_tpu.columnar import encoding as _enc

            values = _enc.dictionary_values(dd.dict_id)
            if values is None:
                values = _matrix_to_string(ddata, dlens,
                                           np.ones(len(dlens), bool))
            idx = pa.array(codes.astype(np.int32),
                           mask=None if validity.all() else ~validity)
            return pa.DictionaryArray.from_arrays(idx, values)
        return _matrix_to_string(
            ddata[codes], np.where(validity, dlens[codes], 0),
            validity)
    if isinstance(field.dataType, StructType):
        if not field.dataType.fields:  # struct() with no fields
            return pa.array(
                [{} if ok else None for ok in validity],
                type=pa.struct([]))
        kids = [_host_column_to_array(f, kid, n)
                for f, kid in zip(field.dataType.fields, col.children)]
        return pa.StructArray.from_arrays(
            kids,
            fields=[pa.field(f.name, to_arrow_type(f.dataType),
                             f.nullable)
                    for f in field.dataType.fields],
            mask=None if validity.all() else pa.array(~validity))
    if isinstance(field.dataType, StringType):
        return _matrix_to_string(
            np.asarray(col.data[:n]), np.asarray(col.lengths[:n]),
            validity)
    if isinstance(field.dataType, MapType):
        return _matrices_to_map(
            np.asarray(col.data[:n]),
            np.asarray(col.map_values[:n]),
            np.asarray(col.lengths[:n]), validity,
            np.asarray(col.elem_validity[:n]), field.dataType)
    if isinstance(field.dataType, ArrayType):
        if isinstance(field.dataType.elementType, StringType):
            return _cube_to_strlist(
                np.asarray(col.data[:n]), np.asarray(col.lengths[:n]),
                validity, np.asarray(col.elem_validity[:n]),
                np.asarray(col.elem_lengths[:n]))
        return _matrix_to_list(
            np.asarray(col.data[:n]), np.asarray(col.lengths[:n]),
            validity, np.asarray(col.elem_validity[:n]),
            field.dataType.elementType)
    vals = np.asarray(col.data[:n])
    at = to_arrow_type(field.dataType)
    if isinstance(field.dataType, DecimalType):
        import decimal as _dec
        s = field.dataType.scale
        # scaleb rounds at context precision (default 28 digits —
        # it would corrupt 29+ digit DECIMAL128 values)
        with _dec.localcontext() as _ctx:
            _ctx.prec = 50
            if vals.ndim == 2:  # DECIMAL128 limb matrix (hi, lo)
                py = []
                for (h, lo_), ok in zip(vals, validity):
                    if not ok:
                        py.append(None)
                        continue
                    v = (int(h) << 64) | (int(lo_) & ((1 << 64) - 1))
                    v &= (1 << 128) - 1
                    if v >= 1 << 127:
                        v -= 1 << 128
                    py.append(_dec.Decimal(v).scaleb(-s))
            else:
                py = [
                    _dec.Decimal(int(v)).scaleb(-s) if ok else None
                    for v, ok in zip(vals, validity)
                ]
        return pa.array(py, type=at)
    mask = None if validity.all() else ~validity
    if pa.types.is_timestamp(at):
        arr = pa.array(vals.astype(np.int64), type=pa.int64(), mask=mask)
        return arr.cast(at)
    if pa.types.is_date32(at):
        arr = pa.array(vals.astype(np.int32), type=pa.int32(), mask=mask)
        return arr.cast(at)
    return pa.array(vals, type=at, mask=mask)


def arrow_to_pandas(table: pa.Table):
    return table.to_pandas(types_mapper=None)
